"""Phase timing probes — the TIMETAG analog (serial_tree_learner.cpp:15-43).

The boosting iteration is one fused jit program, so per-phase time cannot be
read from inside it; instead each phase's op is re-run standalone on the
booster's real shapes and timed. The taxonomy mirrors the reference's
(init/hist/find-split/split) plus the TPU-specific partition/gather phase.
``jax.profiler`` traces can be layered on via trace_dir for a full timeline.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------- compiles
# Process-wide compile accounting, shared by serving.metrics and the
# training-side zero-recompile invariant (bench.py, compile_cache_smoke):
#
# - ``backend_compiles`` rides jax.monitoring's backend-compile duration
#   event, so it counts REAL XLA compilations — including accidental
#   retraces a cache key cannot see (shape leaks, weak-type flips);
# - ``persistent_cache_hits``/``misses`` ride the compilation-cache events,
#   so a warm ``compile_cache_dir`` shows up as hits. (The backend-compile
#   duration event fires on cache hits too in this jax, so hits/misses —
#   not the backend count — are what distinguish a warm start.)
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"
_counts_lock = threading.Lock()
_hooks_installed = False

# the counters themselves live on the process-wide obs registry
# (lightgbm_tpu/obs/registry.py) so one Prometheus scrape sees them next
# to serving/training series; this module keeps its historical API as a
# thin shim over those series
from .obs.registry import get_registry  # noqa: E402

_c_backend = get_registry().counter(
    "lgbm_jax_backend_compiles_total",
    "XLA backend compilations observed via jax.monitoring.")
_c_cache_hit = get_registry().counter(
    "lgbm_jax_compile_cache_hits_total",
    "Persistent compilation-cache hits.")
_c_cache_miss = get_registry().counter(
    "lgbm_jax_compile_cache_misses_total",
    "Persistent compilation-cache misses.")


def _on_event_duration(event: str, duration: float, **kwargs) -> None:
    if event == _BACKEND_COMPILE_EVENT:
        _c_backend.inc()


def _on_event(event: str, **kwargs) -> None:
    if event == _CACHE_HIT_EVENT:
        _c_cache_hit.inc()
    elif event == _CACHE_MISS_EVENT:
        _c_cache_miss.inc()


def install_compile_hook() -> None:
    """Register the compile/cache listeners (idempotent, process-wide)."""
    global _hooks_installed
    with _counts_lock:
        if _hooks_installed:
            return
        _hooks_installed = True
    import jax.monitoring
    jax.monitoring.register_event_duration_secs_listener(_on_event_duration)
    jax.monitoring.register_event_listener(_on_event)


def backend_compile_count() -> int:
    """XLA backend compilations observed since the hook was installed."""
    return int(_c_backend.value)


def compile_cache_stats() -> Dict[str, int]:
    """Snapshot of the compile counters (installs the hooks first, so the
    first caller anchors counting at zero)."""
    install_compile_hook()
    return {"backend_compiles": int(_c_backend.value),
            "persistent_cache_hits": int(_c_cache_hit.value),
            "persistent_cache_misses": int(_c_cache_miss.value)}


def enable_compile_cache(cache_dir: str) -> bool:
    """Point jax's persistent compilation cache at ``cache_dir`` (the
    ``compile_cache_dir`` config param) and install the counters. Every
    compile is made cacheable (no min-time/min-size floor) so a warm
    directory means zero backend compiles on restart. Idempotent;
    returns False when ``cache_dir`` is empty."""
    if not cache_dir:
        return False
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", os.fspath(cache_dir))
    for name, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(name, val)
        except Exception:  # noqa: BLE001 - knob absent in this jax version
            pass
    install_compile_hook()
    return True


def _timed(fn, *args, reps=3, **kw) -> float:
    out = fn(*args, **kw)
    jax.block_until_ready(out)  # lgbm-lint: disable=LGL103 bench warmup
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args, **kw)
    jax.block_until_ready(out)  # lgbm-lint: disable=LGL103 bench barrier
    return (time.perf_counter() - t0) / reps


def latency_summary(samples_ms) -> Dict[str, float]:
    """Quantile summary of a latency sample window (milliseconds) — the
    serving-side SLO view (p50/p90/p99) shared by serving.metrics and any
    offline analysis of its JSON-lines output."""
    a = np.asarray(list(samples_ms), np.float64)
    if a.size == 0:
        return {"count": 0, "mean_ms": 0.0, "p50_ms": 0.0, "p90_ms": 0.0,
                "p99_ms": 0.0, "max_ms": 0.0}
    p50, p90, p99 = np.percentile(a, [50.0, 90.0, 99.0])
    return {"count": int(a.size), "mean_ms": round(float(a.mean()), 4),
            "p50_ms": round(float(p50), 4), "p90_ms": round(float(p90), 4),
            "p99_ms": round(float(p99), 4),
            "max_ms": round(float(a.max()), 4)}


def phase_probe(booster, trace_dir: Optional[str] = None) -> Dict[str, float]:
    """Per-phase seconds for one boosting iteration's building blocks, using
    the booster's actual data/shapes. Keys: grad, hist_full,
    partition_hist_fused, hist_leaf_half, find_split,
    compile_cache_hits/misses, plus frontier_hist / frontier_hist_w<k> /
    frontier_waves / frontier_sweeps_per_tree / frontier_wave_occupancy /
    frontier_slot_sweeps_per_tree when the booster grows in frontier mode
    (docs/Performance.md describes each)."""
    from .core.histogram import build_histogram
    from .core.partition import (frontier_slots_from_partition, hist_for_leaf,
                                 init_partition, make_row_gather,
                                 partition_and_hist,
                                 sort_placement_profitable, stack_vals)
    from .core.split import find_best_split

    from .obs.trace import perfetto_trace

    xb = booster.xb
    n = booster.num_data
    params = booster.grow_params
    meta = booster.feature_meta
    out: Dict[str, float] = {}

    # trace_dir rides the shared Perfetto helper (obs/trace.py), which
    # degrades to a warning when the profiler backend is unavailable or a
    # capture is already active instead of crashing the probe
    with perfetto_trace(trace_dir):
        scores = booster.scores
        if booster.objective is not None:
            obj = booster.objective
            if booster.num_tree_per_iteration == 1:
                grad_fn = jax.jit(lambda s: obj.get_gradients(s[:, 0]))
            else:
                grad_fn = jax.jit(lambda s: obj.get_gradients(s))
            out["grad"] = _timed(grad_fn, scores)
            g, h = grad_fn(scores)
            if g.ndim == 2:           # multiclass: probe class 0's tree
                g, h = g[:, 0], h[:, 0]
        else:
            g = jnp.zeros((n,), jnp.float32)
            h = jnp.ones((n,), jnp.float32)
        mask = jnp.ones((n,), jnp.float32)

        packed = int(getattr(params, "word_packed_cols", 0) or 0)
        out["hist_full"] = _timed(
            build_histogram, xb, g, h, mask, num_bins=params.num_bins,
            row_chunk=params.row_chunk, impl=params.hist_impl,
            packed_cols=packed)
        hist = build_histogram(xb, g, h, mask, num_bins=params.num_bins,
                               row_chunk=params.row_chunk,
                               impl=params.hist_impl, packed_cols=packed)

        part = init_partition(n, params.num_leaves, params.row_chunk)
        # sized to the partition TILE, not n: the decision closure below
        # is sliced per row tile, which is row_chunk wide even when the
        # dataset is smaller
        half = jnp.asarray(
            np.arange(max(n, params.row_chunk), dtype=np.int64) % 2 == 0)
        # probe in f32 regardless of ambient x64: the gather closure owns
        # the packed bins/values boundary, so dtypes must be consistent
        # the partition machinery gathers plain uint8 columns — probe it
        # on a transient unpacked view when the device matrix is
        # word-packed (the frontier grower routes from words directly;
        # these two probes price the EXACT grower's phases)
        if packed:
            from .core.binpack import unpack_words
            xb_cols = unpack_words(xb, packed)
        else:
            xb_cols = xb
        gr = make_row_gather(
            xb_cols, stack_vals(g.astype(jnp.float32),
                                h.astype(jnp.float32),
                                mask.astype(jnp.float32)))
        ncols = xb_cols.shape[1]
        # the real growth path: one fused pass that partitions the root and
        # prices both children — same placement selection as grow_tree
        # (sort path on device / pallas_interpret, scatter loop on CPU)
        use_sort = sort_placement_profitable(params.hist_impl,
                                             params.vmapped_classes)
        fused = jax.jit(lambda p: partition_and_hist(
            p, jnp.zeros((n,), jnp.int32), jnp.int32(0), jnp.int32(1),
            lambda rows: half[:rows.shape[0]],
            jnp.asarray(True), params.row_chunk, gr, ncols,
            params.num_bins, params.hist_impl, use_sort=use_sort))
        out["partition_hist_fused"] = _timed(lambda p: fused(p)[0], part)
        part2 = fused(part)[0]
        out["hist_leaf_half"] = _timed(
            jax.jit(lambda p: hist_for_leaf(
                p, jnp.int32(0), gr, n, ncols, params.num_bins,
                params.row_chunk, impl=params.hist_impl)), part2)

        if getattr(params, "frontier_mode", False):
            from . import bucketing
            from .core.histogram import build_histogram_frontier
            # the frontier wave cost: the partition hands the builder the
            # wave's LEAF IDS and one leaf-indexed sweep prices them all.
            # kb is the clamped maximum wave width; with bucketing on,
            # early waves run at the smaller pow-2 ladder widths, so the
            # per-width probes below show the per-sweep cost the grower
            # actually pays per wave
            bucketed = getattr(params, "frontier_bucketing", False)
            kb = bucketing.frontier_max_width(params.num_leaves,
                                              params.max_depth)
            ladder = (bucketing.wave_width_ladder(params.num_leaves,
                                                  params.max_depth)
                      if bucketed else [kb])
            for w in sorted({ladder[0], ladder[len(ladder) // 2],
                             ladder[-1]}):
                slots_w = frontier_slots_from_partition(
                    part2, jnp.arange(w, dtype=jnp.int32), n)
                t_w = _timed(
                    build_histogram_frontier, xb, slots_w, g, h, mask,
                    num_bins=params.num_bins, num_slots=w,
                    row_chunk=params.row_chunk, impl=params.hist_impl,
                    packed_cols=packed)
                out["frontier_hist_w%d" % w] = t_w
                if w == ladder[-1]:      # full width: the pre-bucketing key
                    out["frontier_hist"] = t_w
            # dataset sweeps per tree scale with DEPTH, not leaf count:
            # wave w splits the leaves created in wave w-1, so waves = max
            # leaf depth of the grown tree, sweeps = waves + 1 (the root).
            # An internal node's depth IS the wave that committed it (every
            # positive-gain leaf splits at the first wave after it
            # appears), so per-depth internal-node counts reconstruct each
            # wave's live width exactly.
            if booster.models:
                for k, v in frontier_tree_stats(booster.models[0],
                                                params).items():
                    out["frontier_" + k] = v

        sum_g = jnp.sum(g)
        sum_h = jnp.sum(h)
        cnt = jnp.asarray(float(n), jnp.float32)
        fmask = jnp.ones((meta.num_bin.shape[0],), bool)
        split_fn = jax.jit(lambda hh: find_best_split(
            hh, meta, params.split, sum_g, sum_h, cnt, fmask,
            with_categorical=params.with_categorical))
        # find_split works on per-feature views; without EFB hist == view
        if not params.with_efb:
            out["find_split"] = _timed(split_fn, hist)

        # persistent-compile-cache accounting (compile_cache_dir): both
        # stay 0 unless the cache is enabled; a warm cache shows as hits
        stats = compile_cache_stats()
        out["compile_cache_hits"] = float(stats["persistent_cache_hits"])
        out["compile_cache_misses"] = float(stats["persistent_cache_misses"])

        # checkpoint overhead (lightgbm_tpu.checkpoint): one full-state
        # snapshot save + restore on the booster's real model/shapes, so
        # the per-period cost shows up next to the phases it competes with
        out.update(_checkpoint_probe(booster))

        # roofline attribution (obs/costmodel.py): join extracted XLA
        # per-call costs with this probe's standalone wall times + any
        # span totals the run accumulated. Best-effort — a probe must
        # never fail because cost extraction cannot run here.
        try:
            from .obs.costmodel import (detect_peaks, roofline_table,
                                        span_wall_times)
            booster.extract_cost_model(force=True)
            wall = span_wall_times()
            for k, v in out.items():
                if k.startswith("frontier_hist_w"):
                    wall[k] = (float(v), 1.0)
            out["roofline"] = roofline_table(wall, peaks=detect_peaks())
        except Exception:  # noqa: BLE001
            pass
    return {k: (round(v, 5) if isinstance(v, float) else v)
            for k, v in out.items()}


def frontier_tree_stats(tree, params) -> Dict[str, float]:
    """Deterministic per-tree wave accounting from a grown HostTree:
    waves, dataset sweeps, occupancy and slot-sweeps under the
    bucketing ladder. An internal node's depth IS the wave that
    committed it (every positive-gain leaf splits at the first wave
    after it appears), so per-depth internal-node counts reconstruct
    each wave's live width exactly. Shared by phase_probe and the perf
    gate (obs/perfgate.py) — semantic counters, no timing."""
    from . import bucketing
    bucketed = getattr(params, "frontier_bucketing", False)
    kb = bucketing.frontier_max_width(params.num_leaves, params.max_depth)
    live_at: Dict[int, int] = {}
    stack = [(0, 0)] if tree.num_leaves > 1 else []
    while stack:
        nd, d = stack.pop()
        live_at[d] = live_at.get(d, 0) + 1
        for ch in (int(tree.left_child[nd]), int(tree.right_child[nd])):
            if ch >= 0:              # ~leaf encoding: negative = leaf
                stack.append((ch, d + 1))
    waves = (max(live_at) + 1) if live_at else 0
    live = [live_at.get(w, 0) for w in range(waves)]
    paid = [(bucketing.wave_width_bucket(lv, params.num_leaves,
                                         params.max_depth)
             if bucketed else kb) for lv in live]
    # occupancy: live slots / paid bucket width, occupancy-weighted over
    # the tree's waves; slot_sweeps is what the hist builder actually
    # swept (fixed width pays waves*kb)
    return {"waves": float(waves),
            "sweeps_per_tree": float(waves + 1),
            "wave_occupancy": (float(sum(live))
                               / max(float(sum(paid)), 1.0)),
            "slot_sweeps_per_tree": float(sum(paid)),
            "slot_sweeps_fixed_width": float(waves * kb)}


def _checkpoint_probe(booster) -> Dict[str, float]:
    """checkpoint_save_s / checkpoint_restore_s: wall time of one snapshot
    write (state npz + manifest + model text) and one verified load back
    into the same driver. Restoring the state it just saved is a no-op for
    the booster. Empty dict when the booster has no trained trees yet."""
    import shutil
    import tempfile
    try:
        if not booster.models:
            return {}
        from .checkpoint.manager import CheckpointManager
        tmp = tempfile.mkdtemp(prefix="lgbm_tpu_ckpt_probe_")
        try:
            mgr = CheckpointManager(tmp, keep_last_n=1)
            t0 = time.perf_counter()
            mgr.save(booster)
            save_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            handle = mgr.load_latest()
            booster.load_training_state(handle.meta, handle.arrays)
            restore_s = time.perf_counter() - t0
            return {"checkpoint_save_s": save_s,
                    "checkpoint_restore_s": restore_s}
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    except Exception:  # noqa: BLE001 - a probe must not kill the caller
        return {}
