"""Logging for lightgbm_tpu.

TPU-native re-design of the reference logger (include/LightGBM/utils/log.h:22-99):
leveled logging with a redirectable callback (the reference redirects into Python
logging via ``Log::ResetCallBack``), and ``Fatal`` raising instead of aborting.
"""
from __future__ import annotations

import sys
from typing import Callable, Optional


class LightGBMError(Exception):
    """Error raised by lightgbm_tpu (mirrors LightGBMError in the reference C API)."""


class OverloadedError(LightGBMError):
    """The serving queue shed this request (bounded admission or open
    circuit breaker); ``retry_after_s`` hints when to come back."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


# Levels mirror LogLevel in the reference (log.h:14-20).
LEVEL_FATAL = -1
LEVEL_WARNING = 0
LEVEL_INFO = 1
LEVEL_DEBUG = 2

_NAMES = {LEVEL_WARNING: "Warning", LEVEL_INFO: "Info", LEVEL_DEBUG: "Debug"}


class Log:
    """Static logger with a thread-shared level and optional callback redirect."""

    _level: int = LEVEL_INFO
    _callback: Optional[Callable[[str], None]] = None

    @classmethod
    def reset_level(cls, level: int) -> None:
        cls._level = level

    @classmethod
    def reset_callback(cls, callback: Optional[Callable[[str], None]]) -> None:
        cls._callback = callback

    @classmethod
    def _write(cls, level: int, msg: str) -> None:
        if level > cls._level:
            return
        line = "[LightGBM-TPU] [%s] %s" % (_NAMES.get(level, "Info"), msg)
        if cls._callback is not None:
            cls._callback(line + "\n")
        else:
            print(line, file=sys.stderr, flush=True)

    @classmethod
    def debug(cls, msg: str, *args) -> None:
        cls._write(LEVEL_DEBUG, msg % args if args else msg)

    @classmethod
    def info(cls, msg: str, *args) -> None:
        cls._write(LEVEL_INFO, msg % args if args else msg)

    @classmethod
    def warning(cls, msg: str, *args) -> None:
        cls._write(LEVEL_WARNING, msg % args if args else msg)

    @classmethod
    def fatal(cls, msg: str, *args) -> None:
        raise LightGBMError(msg % args if args else msg)


def check(condition: bool, msg: str = "check failed") -> None:
    """CHECK macro analog (log.h:22-28)."""
    if not condition:
        raise LightGBMError(msg)
