"""ctypes bridge to the native (C++) host runtime.

The reference keeps its data plane in C++ behind a C ABI consumed by the
bindings (src/c_api.cpp, python-package _load_lib basic.py:25); this module
is that seam for lightgbm_tpu. The shared library is built on demand from
``native/`` with the baked-in toolchain; every entry point has a pure-Python
fallback, so a missing compiler only costs speed, never functionality.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

from .log import Log

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_LIB_NAME = "liblgbm_tpu_native.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


class _ParseResult(ctypes.Structure):
    _fields_ = [("data", ctypes.POINTER(ctypes.c_double)),
                ("label", ctypes.POINTER(ctypes.c_double)),
                ("rows", ctypes.c_long),
                ("cols", ctypes.c_long),
                ("header", ctypes.c_char_p),
                ("format", ctypes.c_int)]


def _build() -> Optional[str]:
    so = os.path.join(_NATIVE_DIR, _LIB_NAME)
    src = os.path.join(_NATIVE_DIR, "src", "text_parser.cpp")
    if not os.path.exists(src):
        return None
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(src):
        return so
    try:
        r = subprocess.run(["make", "-C", _NATIVE_DIR],
                           capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            Log.warning("native build failed, using Python fallbacks:\n%s",
                        r.stderr[-500:])
            return None
    except Exception as e:  # no make/g++ — pure-Python mode
        Log.warning("native build unavailable (%s); using Python fallbacks", e)
        return None
    return so if os.path.exists(so) else None


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        so = _build()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
            lib.LGBMT_ParseFile.restype = ctypes.c_int
            lib.LGBMT_ParseFile.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(_ParseResult), ctypes.c_char_p, ctypes.c_int]
            lib.LGBMT_FreeParseResult.argtypes = [ctypes.POINTER(_ParseResult)]
            _lib = lib
        except OSError as e:
            Log.warning("cannot load native library: %s", e)
            _lib = None
        return _lib


def parse_file_native(path: str, has_header: bool, label_idx: int
                      ) -> Optional[Tuple[np.ndarray, np.ndarray,
                                          Optional[List[str]], int]]:
    """Parse a data file with the C++ parser.

    Returns (X [N, F] float64, label [N], header tokens or None, format) or
    None when the native library is unavailable (caller falls back).
    Raises on parse errors reported by the library.
    """
    lib = get_lib()
    if lib is None:
        return None
    res = _ParseResult()
    err = ctypes.create_string_buffer(512)
    rc = lib.LGBMT_ParseFile(path.encode(), int(has_header), int(label_idx),
                             ctypes.byref(res), err, len(err))
    if rc != 0:
        from .log import LightGBMError
        raise LightGBMError(err.value.decode())
    try:
        n, f = int(res.rows), int(res.cols)
        X = np.ctypeslib.as_array(res.data, shape=(n, f)).copy()
        y = np.ctypeslib.as_array(res.label, shape=(n,)).copy()
        header = res.header.decode() if res.header else None
        fmt = int(res.format)
    finally:
        lib.LGBMT_FreeParseResult(ctypes.byref(res))
    tokens = None
    if header is not None:
        delim = "\t" if "\t" in header else ("," if "," in header else " ")
        tokens = header.strip().split(delim)
    return X, y, tokens, fmt
