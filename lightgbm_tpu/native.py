"""ctypes bridge to the native (C++) host runtime.

The reference keeps its data plane in C++ behind a C ABI consumed by the
bindings (src/c_api.cpp, python-package _load_lib basic.py:25); this module
is that seam for lightgbm_tpu. The shared library is built on demand from
``native/`` with the baked-in toolchain; every entry point has a pure-Python
fallback, so a missing compiler only costs speed, never functionality.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

from .log import Log

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_LIB_NAME = "liblgbm_tpu_native.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


class _ParseResult(ctypes.Structure):
    _fields_ = [("data", ctypes.POINTER(ctypes.c_double)),
                ("label", ctypes.POINTER(ctypes.c_double)),
                ("rows", ctypes.c_long),
                ("cols", ctypes.c_long),
                ("header", ctypes.c_char_p),
                ("format", ctypes.c_int)]


def _build() -> Optional[str]:
    so = os.path.join(_NATIVE_DIR, _LIB_NAME)
    srcs = [os.path.join(_NATIVE_DIR, "src", f)
            for f in ("text_parser.cpp", "binning.cpp")]
    srcs = [f for f in srcs if os.path.exists(f)]
    if not srcs:
        return None
    if os.path.exists(so) and \
            os.path.getmtime(so) >= max(os.path.getmtime(f) for f in srcs):
        return so
    try:
        r = subprocess.run(["make", "-C", _NATIVE_DIR],
                           capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            Log.warning("native build failed, using Python fallbacks:\n%s",
                        r.stderr[-500:])
            return None
    except Exception as e:  # no make/g++ — pure-Python mode
        Log.warning("native build unavailable (%s); using Python fallbacks", e)
        return None
    return so if os.path.exists(so) else None


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        so = _build()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
            lib.LGBMT_ParseFile.restype = ctypes.c_int
            lib.LGBMT_ParseFile.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(_ParseResult), ctypes.c_char_p, ctypes.c_int]
            lib.LGBMT_FreeParseResult.argtypes = [ctypes.POINTER(_ParseResult)]
            lib.LGBMT_BinNumeric.restype = None
            lib.LGBMT_BinNumeric.argtypes = [
                ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_double), ctypes.c_int32,
                ctypes.c_int32, ctypes.POINTER(ctypes.c_int32)]
            _lib = lib
        except (OSError, AttributeError) as e:
            # AttributeError: a stale prebuilt .so from before a symbol was
            # added — fall back to Python rather than crash dataset loading
            Log.warning("cannot load native library: %s", e)
            _lib = None
        return _lib


def parse_file_native(path: str, has_header: bool, label_idx: int
                      ) -> Optional[Tuple[np.ndarray, np.ndarray,
                                          Optional[List[str]], int]]:
    """Parse a data file with the C++ parser.

    Returns (X [N, F] float64, label [N], header tokens or None, format) or
    None when the native library is unavailable (caller falls back).
    Raises on parse errors reported by the library.
    """
    lib = get_lib()
    if lib is None:
        return None
    res = _ParseResult()
    err = ctypes.create_string_buffer(512)
    rc = lib.LGBMT_ParseFile(path.encode(), int(has_header), int(label_idx),
                             ctypes.byref(res), err, len(err))
    if rc != 0:
        from .log import LightGBMError
        raise LightGBMError(err.value.decode())
    try:
        n, f = int(res.rows), int(res.cols)
        X = np.ctypeslib.as_array(res.data, shape=(n, f)).copy()
        y = np.ctypeslib.as_array(res.label, shape=(n,)).copy()
        header = res.header.decode() if res.header else None
        fmt = int(res.format)
    finally:
        lib.LGBMT_FreeParseResult(ctypes.byref(res))
    tokens = None
    if header is not None:
        delim = "\t" if "\t" in header else ("," if "," in header else " ")
        tokens = header.strip().split(delim)
    return X, y, tokens, fmt


def bin_numeric_native(values: np.ndarray, bounds: np.ndarray,
                       nan_bin: int) -> Optional[np.ndarray]:
    """Assign bins for a numeric column with the OpenMP binner
    (native/src/binning.cpp); None when the library is unavailable.

    ``bounds`` are the numeric upper bounds excluding the +inf sentinel;
    ``nan_bin`` >= 0 routes NaN there, < 0 treats NaN as 0.0. Matches
    BinMapper.values_to_bins (searchsorted "left") exactly.
    """
    lib = get_lib()
    if lib is None:
        return None
    values = np.ascontiguousarray(values, dtype=np.float64)
    bounds = np.ascontiguousarray(bounds, dtype=np.float64)
    out = np.empty(len(values), dtype=np.int32)
    lib.LGBMT_BinNumeric(
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.c_int64(len(values)),
        bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.c_int32(len(bounds)), ctypes.c_int32(nan_bin),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return out
