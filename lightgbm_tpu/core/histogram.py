"""Histogram construction — the hottest op in GBDT training.

TPU-native re-design of the reference histogram kernels (dense_bin.hpp:66-130
ConstructHistogram, the OpenCL kernels ocl/histogram{16,64,256}.cl, and
Dataset::ConstructHistograms, src/io/dataset.cpp). Instead of per-thread /
per-workgroup scatter with atomics, bins are accumulated as a one-hot matmul
so the contraction runs on the MXU:

    hist[f, b, k] = sum_n onehot(X[n, f] == b) * vals[n, k]

chunked over rows with ``lax.scan`` so the transient one-hot tile stays small.
A scatter-add (segment-sum) variant is kept for CPU meshes where XLA scatter
is fast. Accumulation follows the value dtype: float32 by default, like the
GPU learner's single-precision histograms (gpu_tree_learner.h:74-78), or
float64 when gpu_use_dp / tpu_hist_dtype=float64 casts the stacked values
(the reference's double-precision histograms, config.h:784).

The entry ``build_histogram`` returns ``[F, B, 3]`` with channels
(sum_grad, sum_hess, count), the HistogramBinEntry layout (bin.h:29-57) as a
structure-of-arrays stack.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .binpack import unpack_words


def _hist_chunk_matmul(xb_chunk: jnp.ndarray, vals_chunk: jnp.ndarray,
                       num_bins: int) -> jnp.ndarray:
    """One row-chunk via one-hot contraction on the MXU.

    xb_chunk: [C, F] uint8/int32; vals_chunk: [C, 3] f32 -> [F, B, 3] f32.
    """
    c, f = xb_chunk.shape
    onehot = (xb_chunk[:, :, None] == jnp.arange(num_bins, dtype=xb_chunk.dtype)
              ).astype(vals_chunk.dtype)  # [C, F, B]
    # contract over rows: [F*B, C] @ [C, 3]. HIGHEST keeps full-precision
    # accumulation in the value dtype on the MXU (TPU matmuls default to
    # bf16 inputs, which breaks the 1e-4 AUC parity budget).
    return lax.dot_general(onehot, vals_chunk,
                           (((0,), (0,)), ((), ())),
                           precision=lax.Precision.HIGHEST)  # [F, B, 3]


def _hist_scatter(xb: jnp.ndarray, vals: jnp.ndarray, num_bins: int) -> jnp.ndarray:
    """Scatter-add variant: good on CPU, used for small row counts."""
    n, f = xb.shape
    flat = xb.astype(jnp.int32) + jnp.arange(f, dtype=jnp.int32)[None, :] * num_bins
    hist = jnp.zeros((f * num_bins, vals.shape[-1]), dtype=vals.dtype)
    hist = hist.at[flat.reshape(-1)].add(
        jnp.broadcast_to(vals[:, None, :], (n, f, vals.shape[-1])
                         ).reshape(n * f, vals.shape[-1]))
    return hist.reshape(f, num_bins, vals.shape[-1])


def hist_tile_vals(xb_rows: jnp.ndarray, vals: jnp.ndarray, num_bins: int,
                   impl: str) -> jnp.ndarray:
    """One fixed-size row tile with pre-stacked [rows, 3] values
    (grad*mask, hess*mask, mask) -> [F, B, 3]. Used by the row-partition
    path (core/partition.py), which gathers the stacked values in a single
    indexed read per tile."""
    if impl.startswith("pallas"):
        from .histogram_pallas import build_histogram_pallas_vals
        return build_histogram_pallas_vals(
            xb_rows, vals.T, num_bins, interpret=impl.endswith("interpret"),
            highest="highest" in impl)
    if impl == "scatter":
        return _hist_scatter(xb_rows, vals, num_bins)
    return _hist_chunk_matmul(xb_rows, vals, num_bins)


@functools.partial(jax.jit, static_argnames=("num_bins", "row_chunk", "impl",
                                             "packed_cols"))
def build_histogram(xb: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
                    mask: jnp.ndarray, num_bins: int,
                    row_chunk: int = 16384, impl: str = "matmul",
                    packed_cols: int = 0) -> jnp.ndarray:
    """Build (grad, hess, count) histograms for every feature.

    Args:
      xb: [N, F] binned features (uint8), or — when ``packed_cols`` > 0 —
        [N, ceil(F/4)] int32 words holding 4 eight-bit codes each
        (core/binpack.py; unpack happens inside the chosen impl, never as
        a second device-resident copy of the matrix).
      grad, hess: [N] f32 gradients/hessians (already weighted by objective).
      mask: [N] f32 row inclusion (leaf membership x bagging); 0 excludes.
      num_bins: static total bin count B (max over features).
      row_chunk: rows per scan step (bounds transient one-hot memory).
      impl: "matmul" (MXU one-hot) or "scatter" (XLA scatter-add).
      packed_cols: the real column count F when xb is word-packed; 0 =
        xb is the plain uint8 matrix.

    Returns: [F, B, 3] f32.
    """
    n = xb.shape[0]
    f = packed_cols or xb.shape[1]
    if impl.startswith("pallas"):
        # pallas | pallas_highest | pallas_interpret | pallas_highest_interpret
        from .histogram_pallas import build_histogram_pallas
        return build_histogram_pallas(xb, grad, hess, mask, num_bins,
                                      interpret=impl.endswith("interpret"),
                                      highest="highest" in impl,
                                      packed_cols=packed_cols)
    vals = jnp.stack([grad * mask, hess * mask, mask], axis=-1)  # [N, 3]
    if impl == "scatter" or n <= row_chunk:
        if packed_cols:
            xb = unpack_words(xb, packed_cols)
        if impl == "scatter":
            return _hist_scatter(xb, vals, num_bins)
        return _hist_chunk_matmul(xb, vals, num_bins)

    num_chunks = (n + row_chunk - 1) // row_chunk
    pad = num_chunks * row_chunk - n
    if pad:
        xb = jnp.pad(xb, ((0, pad), (0, 0)))
        vals = jnp.pad(vals, ((0, pad), (0, 0)))  # padded rows have mask 0
    xb_c = xb.reshape(num_chunks, row_chunk, xb.shape[1])
    vals_c = vals.reshape(num_chunks, row_chunk, 3)

    def step(acc, chunk):
        xbc, vc = chunk
        if packed_cols:
            # per-chunk unpack keeps the transient uint8 tile row_chunk-
            # sized — the full matrix only ever exists as words
            xbc = unpack_words(xbc, packed_cols)
        return acc + _hist_chunk_matmul(xbc, vc, num_bins), None

    init = jnp.zeros((f, num_bins, 3), dtype=vals.dtype)
    hist, _ = lax.scan(step, init, (xb_c, vals_c))
    return hist


def _frontier_scatter(xb: jnp.ndarray, slot: jnp.ndarray, vals: jnp.ndarray,
                      num_bins: int, num_slots: int) -> jnp.ndarray:
    """Leaf-indexed segment scatter: one combined (slot, feature, bin)
    index per row-feature, one scatter-add over the whole dataset.
    Rows with slot -1 are deactivated by zeroing their value channels (the
    clamped slot-0 writes then add zeros)."""
    n, f = xb.shape
    k = vals.shape[-1]
    active = slot >= 0
    vals = vals * active[:, None].astype(vals.dtype)
    s_c = jnp.where(active, slot, 0).astype(jnp.int32)
    flat = (s_c[:, None] * f + jnp.arange(f, dtype=jnp.int32)[None, :]) \
        * num_bins + xb.astype(jnp.int32)
    hist = jnp.zeros((num_slots * f * num_bins, k), dtype=vals.dtype)
    hist = hist.at[flat.reshape(-1)].add(
        jnp.broadcast_to(vals[:, None, :], (n, f, k)).reshape(n * f, k))
    return hist.reshape(num_slots, f, num_bins, k)


def _frontier_chunk_matmul(xb_chunk: jnp.ndarray, slot_chunk: jnp.ndarray,
                           vals_chunk: jnp.ndarray, num_bins: int,
                           num_slots: int) -> jnp.ndarray:
    """One row chunk of the (leaf, bin) one-hot MXU path: the slot one-hot
    spreads each row's value channels into its slot's lane group, then one
    bin-one-hot contraction prices every (slot, feature, bin) cell:

        hist[s, f, b, k] = sum_c onehot(bin)[c, f, b] * onehot(slot x val)[c, s, k]

    Each row lands in exactly one slot, so this pays num_slots x the MXU
    work of a plain histogram — the price of batching a whole frontier
    wave into one pass (the Pallas slot kernel removes the redundancy on
    real devices). slot -1 matches no one-hot column, deactivating the row.
    """
    c, f = xb_chunk.shape
    k = vals_chunk.shape[-1]
    onehot_s = (slot_chunk[:, None] == jnp.arange(num_slots, dtype=jnp.int32)
                ).astype(vals_chunk.dtype)                     # [C, S]
    svals = (onehot_s[:, :, None] * vals_chunk[:, None, :]
             ).reshape(c, num_slots * k)                       # [C, S*K]
    onehot_b = (xb_chunk[:, :, None]
                == jnp.arange(num_bins, dtype=xb_chunk.dtype)
                ).astype(vals_chunk.dtype)                     # [C, F, B]
    out = lax.dot_general(onehot_b, svals, (((0,), (0,)), ((), ())),
                          precision=lax.Precision.HIGHEST)     # [F, B, S*K]
    return jnp.moveaxis(out.reshape(f, num_bins, num_slots, k), 2, 0)


@functools.partial(jax.jit, static_argnames=("num_bins", "num_slots",
                                             "row_chunk", "impl",
                                             "packed_cols"))
def build_histogram_frontier(xb: jnp.ndarray, slot: jnp.ndarray,
                             grad: jnp.ndarray, hess: jnp.ndarray,
                             mask: jnp.ndarray, num_bins: int, num_slots: int,
                             row_chunk: int = 16384,
                             impl: str = "matmul",
                             packed_cols: int = 0) -> jnp.ndarray:
    """Histograms for EVERY live frontier leaf in ONE pass over the rows.

    The multi-leaf generalization of build_histogram (the level-indexed
    pass of the GPU GBDT literature — arXiv:1706.08359 §4, arXiv:1806.11248
    §3.2): instead of sweeping the dataset once per leaf, every row carries
    its leaf's frontier slot and one fused pass produces the whole wave's
    [num_slots, F, B, 3] tensor. A tree then costs O(depth) dataset sweeps
    instead of O(num_leaves).

    Args:
      xb: [N, F] binned features (uint8), or int32 packed words when
        ``packed_cols`` > 0 (same contract as build_histogram).
      slot: [N] int32 frontier slot in [0, num_slots), or -1 for rows in no
        frontier leaf (excluded from every slot).
      grad, hess, mask: [N] f32, same contract as build_histogram.
      num_bins, num_slots: static sizes.
      impl: "matmul" ((leaf, bin) one-hot MXU contraction) | "scatter"
        (combined-index scatter-add) | pallas spellings (the slot kernel,
        histogram_pallas.build_histogram_frontier_pallas).
      packed_cols: real column count F when xb is word-packed; 0 = plain.

    Returns: [num_slots, F, B, 3] f32 (sum_grad, sum_hess, count).
    """
    n = xb.shape[0]
    f = packed_cols or xb.shape[1]
    if impl.startswith("pallas"):
        from .histogram_pallas import build_histogram_frontier_pallas
        vals = jnp.stack([grad * mask, hess * mask, mask], axis=0)  # [3, N]
        return build_histogram_frontier_pallas(
            xb, slot, vals, num_bins=num_bins, n_slots=num_slots,
            interpret=impl.endswith("interpret"),
            highest="highest" in impl, packed_cols=packed_cols)
    vals = jnp.stack([grad * mask, hess * mask, mask], axis=-1)     # [N, 3]
    if impl == "scatter":
        if packed_cols:
            xb = unpack_words(xb, packed_cols)
        return _frontier_scatter(xb, slot, vals, num_bins, num_slots)
    slot = slot.astype(jnp.int32)
    if n <= row_chunk:
        if packed_cols:
            xb = unpack_words(xb, packed_cols)
        return _frontier_chunk_matmul(xb, slot, vals, num_bins, num_slots)
    num_chunks = (n + row_chunk - 1) // row_chunk
    pad = num_chunks * row_chunk - n
    if pad:
        xb = jnp.pad(xb, ((0, pad), (0, 0)))
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
        slot = jnp.pad(slot, (0, pad), constant_values=-1)

    def step(acc, chunk):
        xbc, sc, vc = chunk
        if packed_cols:
            xbc = unpack_words(xbc, packed_cols)
        return acc + _frontier_chunk_matmul(xbc, sc, vc, num_bins,
                                            num_slots), None

    init = jnp.zeros((num_slots, f, num_bins, 3), dtype=vals.dtype)
    hist, _ = lax.scan(step, init,
                       (xb.reshape(num_chunks, row_chunk, xb.shape[1]),
                        slot.reshape(num_chunks, row_chunk),
                        vals.reshape(num_chunks, row_chunk, 3)))
    return hist


def subtract_histogram(parent: jnp.ndarray, child: jnp.ndarray) -> jnp.ndarray:
    """Histogram subtraction trick: sibling = parent - child
    (FeatureHistogram::Subtract, feature_histogram.hpp:67-75)."""
    return parent - child


def fix_histogram(hist: jnp.ndarray, default_bins: jnp.ndarray,
                  sum_grad: jnp.ndarray, sum_hess: jnp.ndarray,
                  count: jnp.ndarray) -> jnp.ndarray:
    """Reconstruct a skipped default bin from leaf totals
    (Dataset::FixHistogram, dataset.h:411-412).

    Our kernels always accumulate every bin, so this is only used to repair
    float32 drift on the default bin after repeated subtraction: the default
    bin is recomputed so per-feature totals equal the (exact) leaf totals.

    hist: [F, B, 3]; default_bins: [F] int32; sums: scalars.
    """
    f, b, _ = hist.shape
    arange_b = jnp.arange(b, dtype=jnp.int32)[None, :]
    is_default = arange_b == default_bins[:, None]  # [F, B]
    totals = jnp.stack([sum_grad, sum_hess, count])  # [3]
    sum_wo_default = jnp.sum(jnp.where(is_default[..., None], 0.0, hist), axis=1)
    fixed = totals[None, :] - sum_wo_default  # [F, 3]
    return jnp.where(is_default[..., None], fixed[:, None, :], hist)
