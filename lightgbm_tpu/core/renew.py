"""Device-side RenewTreeOutput: per-leaf weighted-percentile leaf refit.

The reference refits L1/Quantile/MAPE leaf outputs after growth by walking
each leaf's rows on the host (SerialTreeLearner::RenewTreeOutput,
src/treelearner/serial_tree_learner.cpp:850-928, calling the objective's
percentile functions, src/objective/regression_objective.hpp:20-75, with a
distributed GlobalSumReducer in the parallel learners). Host loops don't
exist on a TPU step, so the same math runs in-graph as ONE segmented
weighted-percentile over all leaves at once:

- rows are sorted once by (leaf, residual) — a [N] `lax.sort` instead of
  per-leaf gathers;
- each leaf's weighted CDF is a slice of one global `cumsum`;
- the percentile index is a vectorized `searchsorted` of every leaf's
  target into the global CDF, clipped to the leaf's segment.

Semantics match the host `_weighted_percentile` (objectives.py): the
returned value is the first sorted residual whose cumulative weight
reaches ``alpha * total`` — the documented lower-percentile simplification
of the reference's interpolating PercentileFun (the golden endpoint tests
in test_parity_tasks.py pin that this stays within reference tolerance).

Under a data-parallel mesh this code runs at the jit level (outside the
explicit shard_map learners), so XLA partitions the sort/cumsum globally —
the GlobalSum moment of the reference's distributed renew.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def renew_leaf_values(resid: jnp.ndarray, weight: jnp.ndarray,
                      leaf_id: jnp.ndarray, mask: jnp.ndarray,
                      num_leaves: int,
                      alpha: float,
                      orig_leaf_value: jnp.ndarray) -> jnp.ndarray:
    """[L] renewed leaf values: weighted alpha-percentile of ``resid`` over
    each leaf's masked rows; leaves with no rows keep ``orig_leaf_value``.

    resid/weight [N] float; leaf_id [N] int32; mask [N] (bool or float —
    nonzero = row participates, the bagging_mapper analog).
    """
    n = resid.shape[0]
    active = mask > 0 if mask.dtype != jnp.bool_ else mask
    # masked-out rows sort past every real leaf segment
    lid = jnp.where(active, leaf_id, num_leaves).astype(jnp.int32)
    w_eff = jnp.where(active, weight, 0.0).astype(resid.dtype)
    srt_lid, srt_resid, srt_w = lax.sort(
        (lid, resid, w_eff), num_keys=2)
    cw = jnp.cumsum(srt_w)
    counts = jnp.zeros((num_leaves + 1,), jnp.int32).at[lid].add(
        1, mode="promise_in_bounds")
    cnt = counts[:num_leaves]
    begin = (jnp.cumsum(counts, dtype=jnp.int32) - counts)[:num_leaves]
    end = begin + cnt                                   # exclusive
    zero = jnp.zeros((), cw.dtype)
    seg_lo = jnp.where(begin > 0, cw[jnp.maximum(begin - 1, 0)], zero)
    seg_hi = jnp.where(end > 0, cw[jnp.maximum(end - 1, 0)], zero)
    # host analog: idx = searchsorted(cum_seg, alpha * total, 'left');
    # the global CDF is the segment CDF shifted by seg_lo, so one
    # vectorized searchsorted serves every leaf
    target = seg_lo + alpha * (seg_hi - seg_lo)
    pos = jnp.searchsorted(cw, target, side="left").astype(jnp.int32)
    pos = jnp.clip(pos, begin, jnp.maximum(end - 1, begin))
    val = srt_resid[jnp.clip(pos, 0, n - 1)]
    return jnp.where(cnt > 0, val, orig_leaf_value)
