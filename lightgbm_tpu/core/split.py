"""Best-split search over histograms.

TPU-native re-design of FeatureHistogram::FindBestThreshold*
(src/treelearner/feature_histogram.hpp:83-271, 443-643). The reference scans
bins sequentially per feature on one CPU thread; here every (feature, bin)
candidate is evaluated simultaneously as a prefix-scan over the bin axis —
bins are <=256 so the whole candidate tensor is tiny and the two missing-value
directions become two masked cumulative sums instead of two loops.

Semantics preserved exactly:
- gain math with L1 soft-threshold, L2, max_delta_step
  (ThresholdL1 / CalculateSplittedLeafOutput / GetLeafSplitGainGivenOutput,
  feature_histogram.hpp:443-499);
- two-direction scan for missing defaults: missing-left (dir=-1) first, the
  missing-right (dir=+1) candidate replaces it only on strictly greater gain;
- MissingType::Zero skips the default (zero) bin in both accumulations;
  MissingType::NaN keeps the NaN bin (last) with the defaulted side;
- tie-breaks: dir=-1 keeps the highest threshold, dir=+1 the lowest;
- validity: min_data_in_leaf / min_sum_hessian_in_leaf on both sides,
  gain strictly > parent gain + min_gain_to_split;
- monotone constraints reject splits with wrong output ordering and clamp
  leaf outputs to [min_constraint, max_constraint].
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

K_EPSILON = 1e-15
K_MIN_SCORE = -jnp.inf

# MissingType codes (bin.h:22-26)
MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2


class FeatureMeta(NamedTuple):
    """Per-feature metadata as device arrays (FeatureMetainfo analog)."""
    num_bin: jnp.ndarray        # [F] int32 (includes NaN bin when present)
    missing_type: jnp.ndarray   # [F] int32
    default_bin: jnp.ndarray    # [F] int32
    is_categorical: jnp.ndarray  # [F] bool
    penalty: jnp.ndarray        # [F] f32 feature_contri multiplier
    monotone: jnp.ndarray       # [F] int32 (-1/0/+1, config.h monotone_constraints)
    # EFB storage layout (feature_group.h:35-50): which stored column the
    # feature lives in and at which bin offset; None = identity (no bundles)
    col: Optional[jnp.ndarray] = None       # [F] int32
    offset: Optional[jnp.ndarray] = None    # [F] int32
    bundled: Optional[jnp.ndarray] = None   # [F] bool
    # joint-coded pair packing (io/dataset.py _pack_small_pairs): feature
    # bin = (stored // pack_div) % pack_mod; pack_partner = the pair-mate's
    # bin count (marginalization width). div=1/mod=0 = unpacked.
    pack_div: Optional[jnp.ndarray] = None      # [F] int32
    pack_mod: Optional[jnp.ndarray] = None      # [F] int32
    pack_partner: Optional[jnp.ndarray] = None  # [F] int32


class SplitParams(NamedTuple):
    """Static split hyper-parameters (subset of Config used by gain math)."""
    lambda_l1: float
    lambda_l2: float
    max_delta_step: float
    min_data_in_leaf: int
    min_sum_hessian_in_leaf: float
    min_gain_to_split: float
    # categorical
    max_cat_threshold: int
    cat_smooth: float
    cat_l2: float
    max_cat_to_onehot: int
    min_data_per_group: int


class BestSplit(NamedTuple):
    """SplitInfo analog (split_info.hpp:48-130) as arrays over leading dims."""
    gain: jnp.ndarray          # f32; -inf when unsplittable
    feature: jnp.ndarray       # int32, inner feature index
    threshold: jnp.ndarray     # int32 bin threshold (left: bin <= thr)
    default_left: jnp.ndarray  # bool
    left_sum_grad: jnp.ndarray
    left_sum_hess: jnp.ndarray
    left_count: jnp.ndarray    # f32 (histogram count channel)
    right_sum_grad: jnp.ndarray
    right_sum_hess: jnp.ndarray
    right_count: jnp.ndarray
    left_output: jnp.ndarray
    right_output: jnp.ndarray
    # categorical: bitset over bins going LEFT (one uint32 x 8 = 256 bins)
    is_categorical: jnp.ndarray  # bool
    cat_bitset: jnp.ndarray      # [..., 8] uint32


def threshold_l1(s, l1):
    """ThresholdL1 (feature_histogram.hpp:449-452)."""
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def calculate_leaf_output(sum_grad, sum_hess, l1, l2, max_delta_step):
    """CalculateSplittedLeafOutput (feature_histogram.hpp:454-462)."""
    ret = -threshold_l1(sum_grad, l1) / (sum_hess + l2)
    if max_delta_step > 0.0:
        ret = jnp.clip(ret, -max_delta_step, max_delta_step)
    return ret


def leaf_split_gain_given_output(sum_grad, sum_hess, l1, l2, output):
    """GetLeafSplitGainGivenOutput (feature_histogram.hpp:494-497)."""
    sg_l1 = threshold_l1(sum_grad, l1)
    return -(2.0 * sg_l1 * output + (sum_hess + l2) * output * output)


def leaf_split_gain(sum_grad, sum_hess, l1, l2, max_delta_step):
    """GetLeafSplitGain (feature_histogram.hpp:487-491)."""
    out = calculate_leaf_output(sum_grad, sum_hess, l1, l2, max_delta_step)
    return leaf_split_gain_given_output(sum_grad, sum_hess, l1, l2, out)


def _split_gains(lg, lh, rg, rh, p: SplitParams, min_c, max_c, monotone):
    """GetSplitGains incl. monotone rejection (feature_histogram.hpp:465-478).

    Returns (gain, left_output, right_output); any broadcastable shapes.
    """
    lo = calculate_leaf_output(lg, lh, p.lambda_l1, p.lambda_l2, p.max_delta_step)
    ro = calculate_leaf_output(rg, rh, p.lambda_l1, p.lambda_l2, p.max_delta_step)
    lo = jnp.clip(lo, min_c, max_c)
    ro = jnp.clip(ro, min_c, max_c)
    bad = ((monotone > 0) & (lo > ro)) | ((monotone < 0) & (lo < ro))
    gain = (leaf_split_gain_given_output(lg, lh, p.lambda_l1, p.lambda_l2, lo)
            + leaf_split_gain_given_output(rg, rh, p.lambda_l1, p.lambda_l2, ro))
    return jnp.where(bad, 0.0, gain), lo, ro


class PerFeatureSplit(NamedTuple):
    """Best numerical split of every feature (pre-argmax), fields [F]."""
    gain: jnp.ndarray          # shifted, penalty-scaled gain; -inf unusable
    threshold: jnp.ndarray     # int32
    default_left: jnp.ndarray  # bool
    left_sum_grad: jnp.ndarray
    left_sum_hess: jnp.ndarray
    left_count: jnp.ndarray
    left_output: jnp.ndarray
    right_output: jnp.ndarray


def per_feature_split_numerical(
        hist: jnp.ndarray,          # [F, B, 3] (grad, hess, count)
        meta: FeatureMeta,
        params: SplitParams,
        sum_grad: jnp.ndarray,      # scalar leaf totals
        sum_hess: jnp.ndarray,
        num_data: jnp.ndarray,      # scalar f32 count
        feature_mask: jnp.ndarray,  # [F] bool (feature_fraction sampling)
        monotone: Optional[jnp.ndarray] = None,   # [F] int8
        min_constraint: float | jnp.ndarray = -jnp.inf,
        max_constraint: float | jnp.ndarray = jnp.inf,
) -> PerFeatureSplit:
    """Vectorized FindBestThresholdNumerical over all features at once.

    Candidate layout: threshold t means left = bins <= t. The missing-left
    scan (reference dir=-1) accumulates the right side from the top numeric
    bin; missing-right (dir=+1) accumulates the left side from bin 0. With a
    full dense histogram (no ``bias`` offset — we always store bin 0) both
    reduce to masked prefix sums.

    Also the voting-parallel learner's local scorer: PV-Tree votes on each
    rank's per-feature best gains (voting_parallel_tree_learner.cpp:322-342),
    which is exactly this function applied to a local histogram.
    """
    f, b, _ = hist.shape
    sum_hess = sum_hess + 2 * K_EPSILON
    if monotone is None:
        monotone = meta.monotone

    bins = jnp.arange(b, dtype=jnp.int32)[None, :]            # [1, B]
    num_bin = meta.num_bin[:, None]                            # [F, 1]
    has_nan_bin = (meta.missing_type[:, None] == MISSING_NAN)
    nb_numeric = num_bin - has_nan_bin.astype(jnp.int32)       # numeric bins
    in_numeric = bins < nb_numeric                             # [F, B]
    skip_default = (meta.missing_type[:, None] == MISSING_ZERO) & \
        (bins == meta.default_bin[:, None])

    g = jnp.where(in_numeric & ~skip_default, hist[..., 0], 0.0)
    h = jnp.where(in_numeric & ~skip_default, hist[..., 1], 0.0)
    c = jnp.where(in_numeric & ~skip_default, hist[..., 2], 0.0)

    pg = jnp.cumsum(g, axis=1)   # prefix over bins: left side of threshold t
    ph = jnp.cumsum(h, axis=1)
    pc = jnp.cumsum(c, axis=1)
    # totals over accumulated (numeric, non-default) bins
    tg, th, tc = pg[:, -1:], ph[:, -1:], pc[:, -1:]

    gain_shift = leaf_split_gain(sum_grad, sum_hess, params.lambda_l1,
                                 params.lambda_l2, params.max_delta_step)
    min_gain_shift = gain_shift + params.min_gain_to_split

    def eval_candidates(lg, lh, lc):
        rg_ = sum_grad - lg
        rh_ = sum_hess - lh
        rc_ = num_data - lc
        ok = ((lc >= params.min_data_in_leaf)
              & (rc_ >= params.min_data_in_leaf)
              & (lh >= params.min_sum_hessian_in_leaf)
              & (rh_ >= params.min_sum_hessian_in_leaf))
        gain, lo, ro = _split_gains(lg, lh, rg_, rh_, params,
                                    min_constraint, max_constraint,
                                    monotone[:, None])
        ok = ok & (gain > min_gain_shift)
        return jnp.where(ok, gain, K_MIN_SCORE), lo, ro

    # ---- missing-left scan (reference dir=-1, runs first) -----------------
    # right side accumulated from top numeric bins; threshold = t means
    # right = accumulated bins > t; left = parent - right (keeps default/NaN).
    # valid thresholds: 0 .. nb_numeric-2
    rgL = tg - pg
    rhL = (th - ph) + K_EPSILON
    rcL = tc - pc
    lgL = sum_grad - rgL
    lhL = sum_hess - rhL
    lcL = num_data - rcL
    gainL, loL, roL = eval_candidates(lgL, lhL, lcL)
    validL = (bins <= nb_numeric - 2) & (bins >= 0)
    # reference dir=-1 skips evaluating at scanned bin == default_bin,
    # i.e. threshold == default_bin - 1
    validL = validL & ~((meta.missing_type[:, None] == MISSING_ZERO)
                        & (bins == meta.default_bin[:, None] - 1))
    gainL = jnp.where(validL, gainL, K_MIN_SCORE)
    # tie-break: highest threshold wins -> argmax over reversed bins
    idxL = (b - 1) - jnp.argmax(gainL[:, ::-1], axis=1)       # [F]
    bestL = jnp.take_along_axis(gainL, idxL[:, None], 1)[:, 0]

    # ---- missing-right scan (reference dir=+1) ----------------------------
    # left side accumulated from bin 0; threshold t: left = bins <= t.
    # valid thresholds: 0 .. nb_numeric-2, plus nb_numeric-1 when NaN bin
    # exists (split purely on missingness).
    lgR = pg + 0.0
    lhR = ph + K_EPSILON
    lcR = pc
    gainR, loR, roR = eval_candidates(lgR, lhR, lcR)
    validR = (bins <= nb_numeric - 2 + has_nan_bin.astype(jnp.int32))
    validR = validR & ~((meta.missing_type[:, None] == MISSING_ZERO)
                        & (bins == meta.default_bin[:, None]))
    # only two-direction features run this scan (missing type != None and
    # num_bin > 2, feature_histogram.hpp:88-99)
    two_dir = (meta.missing_type[:, None] != MISSING_NONE) & (num_bin > 2)
    gainR = jnp.where(validR & two_dir, gainR, K_MIN_SCORE)
    idxR = jnp.argmax(gainR, axis=1)
    bestR = jnp.take_along_axis(gainR, idxR[:, None], 1)[:, 0]

    # dir=+1 replaces dir=-1 only on strictly greater gain
    use_right = bestR > bestL
    per_feat_gain = jnp.where(use_right, bestR, bestL)
    per_feat_thr = jnp.where(use_right, idxR, idxL).astype(jnp.int32)
    # default_left = (winning dir == -1); "fix direction error" for 2-bin NaN
    # features (feature_histogram.hpp:101-104)
    default_left = ~use_right
    fix2bin = (meta.missing_type == MISSING_NAN) & (meta.num_bin <= 2)
    default_left = jnp.where(fix2bin, False, default_left)

    take = lambda a, i: jnp.take_along_axis(a, i[:, None], 1)[:, 0]
    lg_best = jnp.where(use_right, take(lgR, idxR), take(lgL, idxL))
    lh_best = jnp.where(use_right, take(lhR, idxR), take(lhL, idxL))
    lc_best = jnp.where(use_right, take(lcR, idxR), take(lcL, idxL))
    lo_best = jnp.where(use_right, take(loR, idxR), take(loL, idxL))
    ro_best = jnp.where(use_right, take(roR, idxR), take(roL, idxL))

    # feature-level masks: sampled out, trivial, categorical handled elsewhere
    usable = feature_mask & ~meta.is_categorical & (meta.num_bin > 1)
    per_feat_gain = jnp.where(usable, per_feat_gain, K_MIN_SCORE)
    # feature penalty multiplies the (shifted) gain (FindBestThreshold :81)
    out_gain = (per_feat_gain - min_gain_shift) * meta.penalty

    return PerFeatureSplit(
        gain=out_gain,
        threshold=per_feat_thr,
        default_left=default_left,
        left_sum_grad=lg_best,
        left_sum_hess=lh_best - K_EPSILON,   # strip the numeric-safety pad
        left_count=lc_best,
        left_output=lo_best,
        right_output=ro_best,
    )


def find_best_split_numerical(
        hist: jnp.ndarray, meta: FeatureMeta, params: SplitParams,
        sum_grad: jnp.ndarray, sum_hess: jnp.ndarray, num_data: jnp.ndarray,
        feature_mask: jnp.ndarray,
        monotone: Optional[jnp.ndarray] = None,
        min_constraint: float | jnp.ndarray = -jnp.inf,
        max_constraint: float | jnp.ndarray = jnp.inf,
) -> BestSplit:
    """ArgMax over per-feature best splits (SplitInfo selection,
    serial_tree_learner.cpp:506-591)."""
    pf = per_feature_split_numerical(
        hist, meta, params, sum_grad, sum_hess, num_data, feature_mask,
        monotone, min_constraint, max_constraint)
    best_f = jnp.argmax(pf.gain).astype(jnp.int32)
    sel = lambda a: a[best_f]
    gain = pf.gain[best_f]
    splittable = jnp.isfinite(gain)
    zeros8 = jnp.zeros((8,), dtype=jnp.uint32)
    return BestSplit(
        gain=jnp.where(splittable, gain, K_MIN_SCORE),
        feature=best_f,
        threshold=sel(pf.threshold),
        default_left=sel(pf.default_left),
        left_sum_grad=sel(pf.left_sum_grad),
        left_sum_hess=sel(pf.left_sum_hess),
        left_count=sel(pf.left_count),
        right_sum_grad=sum_grad - sel(pf.left_sum_grad),
        right_sum_hess=sum_hess - sel(pf.left_sum_hess),
        right_count=num_data - sel(pf.left_count),
        left_output=sel(pf.left_output),
        right_output=sel(pf.right_output),
        is_categorical=jnp.asarray(False),
        cat_bitset=zeros8,
    )


def _split_gains_l2(lg, lh, rg, rh, p: SplitParams, l2, min_c, max_c):
    """GetSplitGains with an explicit l2 (categorical adds cat_l2,
    feature_histogram.hpp:171)."""
    lo = calculate_leaf_output(lg, lh, p.lambda_l1, l2, p.max_delta_step)
    ro = calculate_leaf_output(rg, rh, p.lambda_l1, l2, p.max_delta_step)
    lo = jnp.clip(lo, min_c, max_c)
    ro = jnp.clip(ro, min_c, max_c)
    gain = (leaf_split_gain_given_output(lg, lh, p.lambda_l1, l2, lo)
            + leaf_split_gain_given_output(rg, rh, p.lambda_l1, l2, ro))
    return gain, lo, ro


def _bin_membership_bitset(member: jnp.ndarray) -> jnp.ndarray:
    """[B] bool -> [8] uint32 bitset over bin indices (SplitInfo
    cat_threshold as a fixed 256-bit set)."""
    b = member.shape[0]
    idx = jnp.arange(b, dtype=jnp.uint32)
    bits = member.astype(jnp.uint32) << (idx & 31)
    return jax.ops.segment_sum(bits, (idx >> 5).astype(jnp.int32),
                               num_segments=8).astype(jnp.uint32)


def per_feature_split_categorical(
        hist: jnp.ndarray,          # [F, B, 3]
        meta: FeatureMeta,
        params: SplitParams,
        sum_grad: jnp.ndarray,
        sum_hess: jnp.ndarray,
        num_data: jnp.ndarray,
        feature_mask: jnp.ndarray,
        min_constraint: float | jnp.ndarray = -jnp.inf,
        max_constraint: float | jnp.ndarray = jnp.inf,
) -> Tuple[PerFeatureSplit, jnp.ndarray]:
    """Vectorized FindBestThresholdCategorical
    (feature_histogram.hpp:110-271).

    Two candidate generators, selected per feature by
    ``num_bin <= max_cat_to_onehot``:

    - one-vs-rest: every real category bin t as left = {t};
    - sorted-subset: bins with count >= cat_smooth sorted by
      sum_grad/(sum_hess + cat_smooth); prefix scans from both ends, at most
      min(max_cat_threshold, (used+1)/2) categories, evaluating only when the
      accumulated group reaches min_data_per_group, with l2 += cat_l2.

    Bin 0 is this framework's catch-all (unseen categories / NaN,
    binning.py:_find_bin_categorical) and always stays on the right — the
    raw-value bitset could not express "unknown goes left" at predict time.

    Returns per-feature best splits plus [F, 8] uint32 bin-space bitsets of
    the categories going left.
    """
    f, b, _ = hist.shape
    sp = params
    sum_hess = sum_hess + 2 * K_EPSILON
    bins = jnp.arange(b, dtype=jnp.int32)

    gain_shift = leaf_split_gain(sum_grad, sum_hess, sp.lambda_l1,
                                 sp.lambda_l2, sp.max_delta_step)
    min_gain_shift = gain_shift + sp.min_gain_to_split
    l2_cat = sp.lambda_l2 + sp.cat_l2

    def one_feature(hist_f, num_bin):
        is_real = (bins >= 1) & (bins < num_bin)
        g = jnp.where(is_real, hist_f[:, 0], 0.0)
        h = jnp.where(is_real, hist_f[:, 1], 0.0)
        c = jnp.where(is_real, hist_f[:, 2], 0.0)

        # ---- one-vs-rest (use_onehot branch, :130-161) -------------------
        oh_g = sum_grad - g
        oh_h = sum_hess - h - K_EPSILON
        oh_c = num_data - c
        ok1 = (is_real & (c >= sp.min_data_in_leaf)
               & (h >= sp.min_sum_hessian_in_leaf)
               & (oh_c >= sp.min_data_in_leaf)
               & (oh_h >= sp.min_sum_hessian_in_leaf))
        gain1, lo1, ro1 = _split_gains_l2(
            g, h + K_EPSILON, oh_g, oh_h, sp, sp.lambda_l2,
            min_constraint, max_constraint)
        gain1 = jnp.where(ok1 & (gain1 > min_gain_shift), gain1, K_MIN_SCORE)
        t1 = jnp.argmax(gain1)
        onehot = dict(
            gain=gain1[t1], lg=g[t1], lh=h[t1], lc=c[t1],
            lo=lo1[t1], ro=ro1[t1], member=bins == t1)

        # ---- sorted-subset scan (:162-235) -------------------------------
        elig = is_real & (c >= sp.cat_smooth)
        n_elig = jnp.sum(elig.astype(jnp.int32))
        ctr = g / (h + sp.cat_smooth)
        max_num_cat = jnp.minimum(sp.max_cat_threshold, (n_elig + 1) // 2)

        def one_direction(key):
            order = jnp.argsort(key)
            gs, hs, cs = g[order], h[order], c[order]
            pg = jnp.cumsum(gs)
            ph = jnp.cumsum(hs) + K_EPSILON
            pc = jnp.cumsum(cs)
            i = jnp.arange(b, dtype=jnp.int32)
            in_range = (i < max_num_cat) & (i < n_elig)
            left_ok = (pc >= sp.min_data_in_leaf) \
                & (ph >= sp.min_sum_hessian_in_leaf)
            rc = num_data - pc
            rh = sum_hess - ph
            stop = (rc < sp.min_data_in_leaf) | (rc < sp.min_data_per_group) \
                | (rh < sp.min_sum_hessian_in_leaf)
            # `break` fires only when reached (left_ok passed), killing the
            # current position and everything after (:204-210)
            alive = jnp.cumsum((left_ok & stop).astype(jnp.int32)) == 0
            can = in_range & alive & left_ok

            def gstep(cnt_group, inp):
                cs_i, can_i = inp
                cnt_group = cnt_group + cs_i
                do_eval = can_i & (cnt_group >= sp.min_data_per_group)
                return jnp.where(do_eval, 0.0, cnt_group), do_eval

            _, do_eval = jax.lax.scan(gstep, jnp.asarray(0.0), (cs, can))
            gain2, lo2, ro2 = _split_gains_l2(
                pg, ph, sum_grad - pg, sum_hess - ph, sp, l2_cat,
                min_constraint, max_constraint)
            gain2 = jnp.where(do_eval & (gain2 > min_gain_shift), gain2,
                              K_MIN_SCORE)
            ib = jnp.argmax(gain2)
            inv_rank = jnp.argsort(order)
            member = (inv_rank <= ib) & elig
            return dict(gain=gain2[ib], lg=pg[ib], lh=ph[ib] - K_EPSILON,
                        lc=pc[ib], lo=lo2[ib], ro=ro2[ib], member=member)

        asc = one_direction(jnp.where(elig, ctr, jnp.inf))
        desc = one_direction(jnp.where(elig, -ctr, jnp.inf))
        sorted_best = jax.tree.map(
            lambda a_, d_: jnp.where(asc["gain"] >= desc["gain"], a_, d_),
            asc, desc)

        use_onehot = num_bin <= sp.max_cat_to_onehot
        return jax.tree.map(
            lambda o, s_: jnp.where(use_onehot, o, s_), onehot, sorted_best)

    res = jax.vmap(one_feature)(hist, meta.num_bin)
    usable = feature_mask & meta.is_categorical & (meta.num_bin > 1)
    out_gain = jnp.where(usable & jnp.isfinite(res["gain"]),
                         (res["gain"] - min_gain_shift) * meta.penalty,
                         K_MIN_SCORE)
    bitsets = jax.vmap(_bin_membership_bitset)(res["member"])
    pf = PerFeatureSplit(
        gain=out_gain,
        threshold=jnp.zeros((f,), jnp.int32),
        default_left=jnp.zeros((f,), bool),
        left_sum_grad=res["lg"],
        left_sum_hess=res["lh"],
        left_count=res["lc"],
        left_output=res["lo"],
        right_output=res["ro"],
    )
    return pf, bitsets


def find_best_split(
        hist: jnp.ndarray, meta: FeatureMeta, params: SplitParams,
        sum_grad: jnp.ndarray, sum_hess: jnp.ndarray, num_data: jnp.ndarray,
        feature_mask: jnp.ndarray,
        min_constraint: float | jnp.ndarray = -jnp.inf,
        max_constraint: float | jnp.ndarray = jnp.inf,
        with_categorical: bool = False,
        gain_penalty: jnp.ndarray | None = None,
) -> BestSplit:
    """Best split over all features, numerical and (when the dataset has any)
    categorical — the per-leaf SplitInfo argmax
    (serial_tree_learner.cpp:506-591).

    ``gain_penalty`` [F] is subtracted from each feature's best gain before
    the argmax — the CEGB cost model (serial_tree_learner.cpp:533-539):
    penalized gains both rank candidates and become the recorded split gain,
    exactly as the reference mutates SplitInfo::gain in place.
    """
    pf, bitsets = per_feature_split_merged(
        hist, meta, params, sum_grad, sum_hess, num_data, feature_mask,
        min_constraint, max_constraint, with_categorical)
    if gain_penalty is not None:
        pf = pf._replace(gain=jnp.where(jnp.isfinite(pf.gain),
                                        pf.gain - gain_penalty, pf.gain))
    best_f = jnp.argmax(pf.gain).astype(jnp.int32)
    sel = lambda a: a[best_f]
    gain = pf.gain[best_f]
    splittable = jnp.isfinite(gain)
    return BestSplit(
        gain=jnp.where(splittable, gain, K_MIN_SCORE),
        feature=best_f,
        threshold=sel(pf.threshold),
        default_left=sel(pf.default_left),
        left_sum_grad=sel(pf.left_sum_grad),
        left_sum_hess=sel(pf.left_sum_hess),
        left_count=sel(pf.left_count),
        right_sum_grad=sum_grad - sel(pf.left_sum_grad),
        right_sum_hess=sum_hess - sel(pf.left_sum_hess),
        right_count=num_data - sel(pf.left_count),
        left_output=sel(pf.left_output),
        right_output=sel(pf.right_output),
        is_categorical=meta.is_categorical[best_f],
        cat_bitset=bitsets[best_f],
    )


def per_feature_split_merged(
        hist: jnp.ndarray, meta: FeatureMeta, params: SplitParams,
        sum_grad: jnp.ndarray, sum_hess: jnp.ndarray, num_data: jnp.ndarray,
        feature_mask: jnp.ndarray,
        min_constraint: float | jnp.ndarray = -jnp.inf,
        max_constraint: float | jnp.ndarray = jnp.inf,
        with_categorical: bool = False,
) -> Tuple[PerFeatureSplit, jnp.ndarray]:
    """Per-feature best splits, each feature using its own finder
    (FindBestThreshold dispatch, feature_histogram.hpp:68-108)."""
    f = hist.shape[0]
    pf = per_feature_split_numerical(
        hist, meta, params, sum_grad, sum_hess, num_data, feature_mask,
        None, min_constraint, max_constraint)
    if not with_categorical:
        return pf, jnp.zeros((f, 8), jnp.uint32)
    pfc, bitsets = per_feature_split_categorical(
        hist, meta, params, sum_grad, sum_hess, num_data, feature_mask,
        min_constraint, max_constraint)
    is_cat = meta.is_categorical
    merged = PerFeatureSplit(*[
        jnp.where(is_cat, cv, nv) for nv, cv in zip(pf, pfc)])
    bitsets = jnp.where(is_cat[:, None], bitsets, 0).astype(jnp.uint32)
    return merged, bitsets
