"""Batched-frontier tree growth: split many leaves per sequential step.

Why this exists (docs/Performance.md "Known limits"): on TPU, per-split
latency inside a sequential growth loop has a ~1-1.5 ms floor set by the
dependency chain partition -> child split scans -> next leaf choice —
nearly independent of how fast the histogram kernel is. Exact leaf-wise
(best-first) growth (serial_tree_learner.cpp:169-233) therefore costs
~(num_leaves - 1) x floor per tree no matter what. This module amortizes
the floor: each sequential step takes the TOP-K leaves of the frontier by
best gain and splits them all at once — one fused routing pass, one
multi-leaf histogram build, one vmapped split search, one set of scatters
per STEP instead of per SPLIT. A 255-leaf tree takes ~20 steps at K=16
instead of 254.

Semantics: this is *approximate* best-first. Exact leaf-wise would re-rank
after every single split (a child can out-gain the current second-best
leaf); top-K batching commits to K splits per re-rank. K=1 reproduces the
exact algorithm (and is tested to). The accuracy contract follows the
reference's own precedent for its GPU learner — small, documented
deviations from the CPU algorithm in exchange for device throughput
(GPU-Performance.rst:132-139) — opt-in via ``tree_growth=batched``.
Forced splits and CEGB keep the exact path (their per-split accounting is
order-dependent).

Design notes (same profiling facts as core/partition.py):
- rows are routed by ONE dense table-gather pass per step: each row reads
  its leaf's split-rank (-1 = leaf not splitting), gathers its split's
  feature column byte via one take_along_axis, and computes go-left for
  all K splits simultaneously;
- child histograms for all 2K children come from ONE histogram build over
  a combined index (child_slot * B + bin) — the multi-leaf analog of the
  fused partition+histogram pass;
- tree/leaf bookkeeping writes use scatter-with-drop (invalid lanes route
  to an out-of-bounds index) so masked lanes cannot race resident writes.

Node numbering: step-local rank i (gain-descending) gets node
(num_leaves - 1 + i) and right-child leaf (num_leaves + i) — identical to
the reference's numbering (tree.cpp:49-67) when K=1, and still
deterministic (gain-ranked) for K>1.
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..compat import pcast
from .histogram import build_histogram, build_histogram_frontier
from .grow import (GrowParams, TreeArrays, _bin_go_left, _empty_best,
                   decode_bundle_value, empty_tree, expand_hist,
                   propagate_monotone_bounds)
from .split import (BestSplit, FeatureMeta, K_MIN_SCORE,
                    calculate_leaf_output, find_best_split)


def _drop_set(arr: jnp.ndarray, idx: jnp.ndarray, val: jnp.ndarray,
              cond: jnp.ndarray) -> jnp.ndarray:
    """Scatter val into arr[idx] where cond; lanes with cond False write
    nowhere (out-of-bounds index + mode='drop'). Unlike a write-back of
    arr[idx], this cannot race another lane targeting the same index."""
    n = arr.shape[0]
    safe = jnp.where(cond, idx, n)
    return arr.at[safe].set(val, mode="drop")


def interleave_lr(a: jnp.ndarray, c: jnp.ndarray) -> jnp.ndarray:
    """[K] left + [K] right per-split values -> [2K] interleaved
    L,R,L,R,... — the child lane order of the wave-wide vmapped split
    search (left child of rank i at lane 2i, right at 2i+1)."""
    return jnp.stack([a, c], axis=1).reshape(-1)


def apply_split_wave(tree: TreeArrays, leaf_min: jnp.ndarray,
                     leaf_max: jnp.ndarray, cur, gleaf: jnp.ndarray,
                     node: jnp.ndarray, right_leaf: jnp.ndarray,
                     valid: jnp.ndarray, nvalid: jnp.ndarray,
                     meta: FeatureMeta, sp, max_depth: int):
    """Commit one wave of up to K frontier splits to the tree arrays
    (Tree::Split x K, tree.cpp:49-67) plus monotone-bound propagation.

    Every write is a scatter-with-drop, so invalid lanes touch nothing.
    Shared by the plain batched, partitioned-batched and frontier-wave
    growers so the wave-commit semantics cannot drift between them.
    Returns (tree, leaf_min, leaf_max, safe_leaf, ch_min, ch_max, ch_ok)
    with the ch_* arrays in the interleaved [2K] child lane order."""
    l = tree.leaf_value.shape[0]
    nl = tree.num_leaves
    safe_leaf = jnp.where(valid, gleaf, l - 1)
    parent_node = tree.leaf_parent[safe_leaf]                 # [K]
    p_exists = valid & (parent_node >= 0)
    safe_p = jnp.maximum(parent_node, 0)
    was_left = tree.left_child[safe_p] == ~safe_leaf
    left_child = _drop_set(tree.left_child, safe_p, node,
                           p_exists & was_left)
    right_child = _drop_set(tree.right_child, safe_p, node,
                            p_exists & ~was_left)
    left_child = _drop_set(left_child, node, ~safe_leaf, valid)
    right_child = _drop_set(right_child, node, ~right_leaf, valid)

    depth = tree.leaf_depth[safe_leaf] + 1                    # [K]
    parent_value = calculate_leaf_output(
        cur.left_sum_grad + cur.right_sum_grad,
        cur.left_sum_hess + cur.right_sum_hess,
        sp.lambda_l1, sp.lambda_l2, sp.max_delta_step)

    def set_node(arr, val):
        return _drop_set(arr, node, val, valid)

    def set_leaves(arr, lval, rval):
        return _drop_set(_drop_set(arr, safe_leaf, lval, valid),
                         right_leaf, rval, valid)

    tree = tree._replace(
        split_feature=set_node(tree.split_feature, cur.feature),
        threshold_bin=set_node(tree.threshold_bin, cur.threshold),
        default_left=set_node(tree.default_left, cur.default_left),
        missing_type=set_node(tree.missing_type,
                              meta.missing_type[cur.feature]),
        is_categorical=set_node(tree.is_categorical, cur.is_categorical),
        cat_bitset=_drop_set(tree.cat_bitset, node, cur.cat_bitset,
                             valid),
        left_child=left_child, right_child=right_child,
        split_gain=set_node(tree.split_gain, cur.gain),
        internal_value=set_node(tree.internal_value, parent_value),
        internal_weight=set_node(tree.internal_weight,
                                 cur.left_sum_hess + cur.right_sum_hess),
        internal_count=set_node(tree.internal_count,
                                cur.left_count + cur.right_count),
        split_leaf=set_node(tree.split_leaf, safe_leaf),
        leaf_value=set_leaves(tree.leaf_value, cur.left_output,
                              cur.right_output),
        leaf_weight=set_leaves(tree.leaf_weight, cur.left_sum_hess,
                               cur.right_sum_hess),
        leaf_count=set_leaves(tree.leaf_count, cur.left_count,
                              cur.right_count),
        leaf_parent=set_leaves(tree.leaf_parent, node, node),
        leaf_depth=set_leaves(tree.leaf_depth, depth, depth),
        num_leaves=nl + nvalid)

    mono = meta.monotone[cur.feature]
    p_min, p_max = leaf_min[safe_leaf], leaf_max[safe_leaf]
    l_min, l_max, r_min, r_max = propagate_monotone_bounds(
        mono, cur.left_output, cur.right_output, p_min, p_max)
    leaf_min = set_leaves(leaf_min, l_min, r_min)
    leaf_max = set_leaves(leaf_max, l_max, r_max)

    depth_ok = (max_depth <= 0) | (depth < max_depth)
    return (tree, leaf_min, leaf_max, safe_leaf,
            interleave_lr(l_min, r_min), interleave_lr(l_max, r_max),
            interleave_lr(depth_ok, depth_ok))


def scatter_child_best(best, b2k, safe_leaf: jnp.ndarray,
                       right_leaf: jnp.ndarray, valid: jnp.ndarray):
    """De-interleave the [2K]-lane child split search back onto the
    per-leaf best table (left child keeps the parent's leaf index, right
    child takes its new leaf) — drop-scattered so invalid lanes write
    nothing. Shared by every wave-batched grower."""
    bl = jax.tree.map(lambda a: a[0::2], b2k)
    br = jax.tree.map(lambda a: a[1::2], b2k)
    return jax.tree.map(
        lambda arr, vl, vr: _drop_set(_drop_set(arr, safe_leaf, vl, valid),
                                      right_leaf, vr, valid),
        best, bl, br)


def route_split_rows(xb_fm, rank, rs, onek, cur, meta, with_efb,
                     with_categorical):
    """Per-row go-left decisions for the K frontier splits, built
    ENTIRELY from dense one-hot selects over the K split descriptors.

    Per-row gathers (take_along_axis on the bins, [rs]-indexed parameter
    lookups) are latency-bound on TPU (~0.3-0.5 ms EACH; the round-3
    routing cost ~18 ms/step at 1M rows, round-4 kernel lab) — one
    [kb, N] one-hot serves every lookup instead. Shared by the plain and
    partitioned batched growers so the routing semantics cannot drift.

    xb_fm: [C, N] feature-major bins; rank: [kb] iota; rs: [N] clamped
    per-row split rank; onek: [kb, N] (rank == rs) one-hot.
    Returns go_left [N] bool.
    """
    def sel_k(table_k):
        """[kb] per-split values -> [N] per-row via the one-hot."""
        t = table_k[:, None]
        if t.dtype == jnp.bool_:
            return jnp.any(onek & t, axis=0)
        return jnp.sum(jnp.where(onek, t, jnp.zeros_like(t)), axis=0)

    stored_col = (meta.col[cur.feature] if with_efb
                  else cur.feature).astype(jnp.int32)        # [kb]
    cols = xb_fm[stored_col, :].astype(jnp.int32)            # [kb, N]
    colv = jnp.sum(jnp.where(onek, cols, 0), axis=0)         # [N]
    num_bin_r = sel_k(meta.num_bin[cur.feature])
    default_bin_r = sel_k(meta.default_bin[cur.feature])
    if with_efb:
        fbin = decode_bundle_value(
            colv, sel_k(meta.offset[cur.feature]),
            num_bin_r, default_bin_r,
            pack_div=(sel_k(meta.pack_div[cur.feature])
                      if meta.pack_div is not None else None),
            pack_mod=(sel_k(meta.pack_mod[cur.feature])
                      if meta.pack_mod is not None else None))
    else:
        fbin = colv
    return _bin_go_left(
        fbin, sel_k(cur.threshold), sel_k(cur.default_left),
        sel_k(meta.missing_type[cur.feature]),
        num_bin_r, default_bin_r,
        (cur.is_categorical[rs] if with_categorical else None),
        (cur.cat_bitset[rs] if with_categorical else None))


class _BatchState(NamedTuple):
    leaf_id: jnp.ndarray      # [N] int32
    best: BestSplit           # per-leaf best split, fields [L]
    tree: TreeArrays
    leaf_min: jnp.ndarray     # [L] f32 monotone lower bound
    leaf_max: jnp.ndarray     # [L] f32 monotone upper bound


def _combined_hist(xb, slot, active, grad, hess, hmask, b, kb, impl,
                   row_chunk, pack):
    """All 2K children's [C, B, 3] histograms in one pass over the rows.

    Pallas spellings use the slot-extended digit kernel (the combined
    slot*B+bin index as a third one-hot factor on the MXU); matmul/scatter
    delegate to histogram.build_histogram_frontier, the leaf-indexed
    frontier builder (slot one-hot x bin one-hot), with inactive rows
    marked slot -1.

    ``pack`` (tpu_batched_pack): gather the ACTIVE rows (those inside a
    splitting leaf) to the front with a stable cumsum partition before
    the kernel, and mark everything behind them slot -1 — all-inactive
    row tiles then skip their compute body (pl.when), so per-step kernel
    cost tracks the split leaves' rows instead of N. Costs one [N, C]
    gather + one scatter per step; opt-in until measured on chip.
    """
    if impl.startswith("pallas"):
        from .histogram_pallas import build_histogram_slots
        if pack:
            n = slot.shape[0]
            act32 = active.astype(jnp.int32)
            na = jnp.cumsum(act32)
            total = na[-1]
            pos = jnp.where(active, na - 1,
                            total + jnp.cumsum(1 - act32) - 1)
            perm = jnp.zeros((n,), jnp.int32).at[pos].set(
                jnp.arange(n, dtype=jnp.int32))
            xb = jnp.take(xb, perm, axis=0)
            slot = jnp.where(active, slot, -1)[perm]
            grad, hess, hmask = grad[perm], hess[perm], hmask[perm]
        vals = jnp.stack([grad * hmask, hess * hmask, hmask], axis=0)
        out = build_histogram_slots(
            xb, slot, vals, num_bins=b, n_slots=2 * kb,
            interpret=impl.endswith("interpret"),
            highest="highest" in impl)                  # [2K, C, B, 3]
        return out
    return build_histogram_frontier(
        xb, jnp.where(active, slot, -1), grad, hess, hmask,
        num_bins=b, num_slots=2 * kb, row_chunk=row_chunk, impl=impl)


def grow_tree_batched(xb: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
                      sample_mask: jnp.ndarray, meta: FeatureMeta,
                      feature_mask: jnp.ndarray, params: GrowParams,
                      axis_name: Optional[str] = None,
                      ) -> Tuple[TreeArrays, jnp.ndarray, None]:
    """Grow one tree, splitting up to ``params.batch_splits`` frontier
    leaves per sequential step. Same contract as grow.grow_tree (minus
    forced/CEGB, which require exact ordering); returns
    (tree, final per-row leaf_id, None)."""
    n, ncols = xb.shape
    f = meta.num_bin.shape[0]
    l = params.num_leaves
    b = params.num_bins
    sp = params.split
    kb = max(1, min(params.batch_splits, l - 1))
    with_efb = params.with_efb

    def psum(x):
        return lax.psum(x, axis_name) if axis_name is not None else x

    def child_best(hist_col, sum_g, sum_h, cnt, min_c, max_c):
        return find_best_split(
            expand_hist(hist_col, sum_g, sum_h, cnt, meta, params, ncols),
            meta, sp, sum_g, sum_h, cnt, feature_mask,
            min_constraint=min_c, max_constraint=max_c,
            with_categorical=params.with_categorical)

    # ---- root (identical to exact mode) ---------------------------------
    sample_mask = sample_mask.astype(jnp.float32)
    root_g = psum(jnp.sum(grad * sample_mask))
    root_h = psum(jnp.sum(hess * sample_mask))
    root_c = psum(jnp.sum(sample_mask))
    hist_root = psum(build_histogram(xb, grad, hess, sample_mask, num_bins=b,
                                     row_chunk=params.row_chunk,
                                     impl=params.hist_impl))
    tree = empty_tree(l)
    tree = tree._replace(
        leaf_value=tree.leaf_value.at[0].set(
            calculate_leaf_output(root_g, root_h, sp.lambda_l1, sp.lambda_l2,
                                  sp.max_delta_step)),
        leaf_weight=tree.leaf_weight.at[0].set(root_h),
        leaf_count=tree.leaf_count.at[0].set(root_c))
    best0 = child_best(hist_root, root_g, root_h, root_c, -jnp.inf, jnp.inf)
    best = jax.tree.map(lambda a, v: a.at[0].set(v), _empty_best(l), best0)

    # feature-major view for split-column routing: loop-invariant, so the
    # transpose happens once per tree, not per step (measured ~4 ms per
    # occurrence on a v5e chip at 1M rows — the routing gather it
    # replaces measured ~18 ms per step)
    xb_fm = xb.T

    leaf_id0 = jnp.zeros((n,), jnp.int32)
    if axis_name is not None:
        leaf_id0 = pcast(leaf_id0, (axis_name,), to="varying")
    state = _BatchState(
        leaf_id=leaf_id0, best=best, tree=tree,
        leaf_min=jnp.full((l,), -jnp.inf, jnp.float32),
        leaf_max=jnp.full((l,), jnp.inf, jnp.float32))

    def cond_fn(s: _BatchState) -> jnp.ndarray:
        return (s.tree.num_leaves < l) & jnp.any(s.best.gain > 0.0)

    def step(s: _BatchState) -> _BatchState:
        tree = s.tree
        nl = tree.num_leaves                      # dynamic scalar
        rank = jnp.arange(kb, dtype=jnp.int32)
        gval, gleaf = lax.top_k(s.best.gain, kb)  # distinct leaves, desc
        # both conditions are prefix masks of the gain-sorted ranks
        valid = (gval > 0.0) & (rank < (l - nl))
        nvalid = jnp.sum(valid.astype(jnp.int32))
        node = (nl - 1) + rank                    # [kb]
        right_leaf = nl + rank                    # [kb]
        cur = jax.tree.map(lambda a: a[gleaf], s.best)   # fields [kb]

        # ---- route every row through its leaf's split (one dense pass) --
        rank_of_leaf = jnp.full((l,), -1, jnp.int32)
        rank_of_leaf = _drop_set(rank_of_leaf, gleaf, rank, valid)
        r_r = rank_of_leaf[s.leaf_id]             # [N], -1 = not splitting
        active = r_r >= 0
        rs = jnp.maximum(r_r, 0)
        onek = rank[:, None] == rs[None, :]                  # [kb, N]
        go_left = route_split_rows(xb_fm, rank, rs, onek, cur, meta,
                                   with_efb, params.with_categorical)
        leaf_id = jnp.where(active & ~go_left, right_leaf[rs], s.leaf_id)

        # ---- all 2K children's histograms in one combined build ---------
        hmask = sample_mask * active.astype(jnp.float32)
        if params.hist_impl.startswith("pallas") and not params.batched_pack:
            # parent-slot x 6-channel joint kernel: half the slot one-hot
            # width, double the MXU row utilization (round-4 on-chip fix)
            from .histogram_pallas import build_histogram_slots6
            vals3 = jnp.stack([grad * hmask, hess * hmask, hmask], axis=0)
            h6 = psum(build_histogram_slots6(
                xb, jnp.where(active, rs, -1), go_left.astype(jnp.float32),
                vals3, num_bins=b, n_slots=kb,
                interpret=params.hist_impl.endswith("interpret"),
                highest="highest" in params.hist_impl))   # [K, C, B, 6]
            ch_hist = jnp.stack([h6[..., :3], h6[..., 3:]],
                                axis=1).reshape(2 * kb, ncols, b, 3)
        else:
            # child slot = 2*rank + side; combined bin index = slot*B + bin
            slot = jnp.where(active,
                             rs * 2 + (~go_left).astype(jnp.int32), 0)
            ch_hist = psum(_combined_hist(
                xb, slot, active, grad, hess, hmask, b, kb,
                params.hist_impl, params.row_chunk,
                params.batched_pack))                     # [2K, C, B, 3]

        # ---- tree bookkeeping for up to K splits (Tree::Split, x K) -----
        (tree, leaf_min, leaf_max, safe_leaf,
         ch_min, ch_max, ch_ok) = apply_split_wave(
            tree, s.leaf_min, s.leaf_max, cur, gleaf, node, right_leaf,
            valid, nvalid, meta, sp, params.max_depth)

        # ---- best splits for all 2K children, one vmapped search --------
        ch_sg = interleave_lr(cur.left_sum_grad, cur.right_sum_grad)
        ch_sh = interleave_lr(cur.left_sum_hess, cur.right_sum_hess)
        ch_cnt = interleave_lr(cur.left_count, cur.right_count)
        b2k = jax.vmap(child_best)(ch_hist, ch_sg, ch_sh, ch_cnt,
                                   ch_min, ch_max)
        b2k = b2k._replace(gain=jnp.where(ch_ok, b2k.gain, K_MIN_SCORE))
        best = scatter_child_best(s.best, b2k, safe_leaf, right_leaf, valid)

        return _BatchState(leaf_id=leaf_id, best=best, tree=tree,
                           leaf_min=leaf_min, leaf_max=leaf_max)

    state = lax.while_loop(cond_fn, step, state)
    return state.tree, state.leaf_id, None
