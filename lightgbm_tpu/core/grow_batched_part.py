"""Partitioned batched-frontier growth: K splits per step over rows kept
physically grouped by leaf.

Why this exists (round-4 on-chip measurements, docs/Performance.md): the
original batched mode (core/grow_batched.py) pays a FULL pass over all N
rows per sequential step, and its joint slot kernel contracts every row
against an S = 2K-wide slot one-hot — S x redundant MXU work, since each
row lands in exactly one slot. Measured on a v5e chip it LOSES to exact
growth (0.74 vs 1.79 iters/s at 1M x 28), inverting the CPU datapoint
that motivated it. Exact growth wins because its row partition
(core/partition.py) makes per-split cost track rows-in-leaf — but it
still pays the ~ms-scale sequential-step floor per SPLIT.

Measured outcome (v5e, 1M x 28, K = 16): the per-step ROW PERMUTATION —
one XLA gather over the [C, Np] bins + [3, Np] values, ~2.3 GB/s
effective, ~30 ms — and the per-tile output DMA latency of the
scalar-prefetch kernel cost more than the slot-redundancy they remove,
so this mode currently LOSES to both exact growth and the joint slot
kernel (0.25 vs 1.79 / 0.74 iters/s) and stays opt-in
(tpu_batched_part=true). It is kept because the design is the only one
whose per-step cost is asymptotically right (tracks splitting leaves'
rows, no S-factor); if the permutation moves into a device kernel or
XLA's gather improves, revisit docs/Performance.md's round-4 table.

This module combines two structural advantages:

- rows live physically grouped by leaf (the DataPartition invariant,
  data_partition.hpp:20-37) in row_tile-ALIGNED segments of a
  feature-major [C, Np] buffer, so each kernel row-tile belongs to at
  most one frontier leaf;
- each sequential step takes the top-K frontier leaves and routes,
  histograms, and splits them all at once — per-step cost tracks the
  SPLITTING leaves' rows (tiles outside them skip their compute body via
  a scalar-prefetched tile->slot map, histogram_pallas.py
  build_histogram_part_tiles), with zero slot-one-hot redundancy;
- both children of every splitting leaf are priced in ONE pass over the
  parent's rows: the per-row go-left bit routes (g, h, m) into left/right
  channel triples, which also doubles MXU row utilization (M = 96 vs 48);
- the layout is maintained by ONE dense permutation per step (a
  tile-aligned segmented cumsum computes every row's new position; XLA
  gathers move the [C, Np] bins, [3, Np] values and row metadata), the
  functional analog of DataPartition::Split.

Semantics are identical to grow_batched (approximate best-first, K = 1 ==
exact; node numbering tree.cpp:49-67); only row visit ORDER inside
histogram sums differs (f32 summation-order noise). Forced splits and
CEGB keep the exact path, same as grow_batched.
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..compat import pcast
from .histogram import build_histogram
from .grow import (GrowParams, TreeArrays, _empty_best, empty_tree,
                   expand_hist)
from .grow_batched import (_combined_hist, _drop_set, apply_split_wave,
                           interleave_lr, route_split_rows,
                           scatter_child_best)
from .split import (BestSplit, FeatureMeta, K_MIN_SCORE,
                    calculate_leaf_output, find_best_split)

PART_TILE = 2048   # kernel row tile AND segment alignment quantum


def _local_slot_mask(slot_vals: jnp.ndarray, n_slots: int) -> jnp.ndarray:
    """[n_slots] bool: which slots appear in ``slot_vals`` (-1 = none).

    The pallas part-tiles kernel only WRITES the output block of a slot
    that owns at least one local row tile — a slot with no local tiles
    leaves its block uninitialized (histogram_pallas.py documents this).
    Under a data-parallel shard_map a globally-valid leaf can easily have
    zero rows on one shard, so masking by global validity alone would
    feed that shard's garbage block into the psum. Negative entries are
    routed to index ``n_slots`` and dropped (never wrapped to the last
    slot)."""
    idx = jnp.where(slot_vals >= 0, slot_vals, n_slots)
    return jnp.zeros((n_slots,), bool).at[idx].set(True, mode="drop")


def _part_capacity(n: int, num_leaves: int, tile: int) -> int:
    """Static padded row capacity: every leaf segment rounded up to a
    tile boundary fits, and the last row is guaranteed padding (the
    drop-target of the permutation scatter)."""
    return -(-n // tile) * tile + (num_leaves + 1) * tile


class _PartState(NamedTuple):
    xb_fm: jnp.ndarray        # [C, Np] uint8, feature-major, leaf-grouped
    vals3: jnp.ndarray        # [3, Np] f32 (g*m, h*m, m), same layout
    row_leaf: jnp.ndarray     # [Np] int32 leaf id (-1 = padding)
    orig: jnp.ndarray         # [Np] int32 original row id (-1 = padding)
    leaf_begin: jnp.ndarray   # [L] int32 (tile-aligned)
    leaf_count: jnp.ndarray   # [L] int32
    best: BestSplit           # per-leaf best split, fields [L]
    tree: TreeArrays
    leaf_min: jnp.ndarray     # [L] f32 monotone lower bound
    leaf_max: jnp.ndarray     # [L] f32 monotone upper bound


def grow_tree_batched_part(xb: jnp.ndarray, grad: jnp.ndarray,
                           hess: jnp.ndarray, sample_mask: jnp.ndarray,
                           meta: FeatureMeta, feature_mask: jnp.ndarray,
                           params: GrowParams,
                           axis_name: Optional[str] = None,
                           ) -> Tuple[TreeArrays, jnp.ndarray, None]:
    """Same contract as grow_batched.grow_tree_batched (returns
    (tree, per-row leaf_id in ORIGINAL row order, None))."""
    n, ncols = xb.shape
    l = params.num_leaves
    b = params.num_bins
    sp = params.split
    kb = max(1, min(params.batch_splits, l - 1))
    with_efb = params.with_efb
    tile = PART_TILE
    np_cap = _part_capacity(n, l, tile)
    n_tiles = np_cap // tile
    impl = params.hist_impl
    use_kernel = impl.startswith("pallas")

    def psum(x):
        return lax.psum(x, axis_name) if axis_name is not None else x

    def child_best(hist_col, sum_g, sum_h, cnt, min_c, max_c):
        return find_best_split(
            expand_hist(hist_col, sum_g, sum_h, cnt, meta, params, ncols),
            meta, sp, sum_g, sum_h, cnt, feature_mask,
            min_constraint=min_c, max_constraint=max_c,
            with_categorical=params.with_categorical)

    # ---- root (identical to grow_batched) -------------------------------
    sample_mask = sample_mask.astype(jnp.float32)
    root_g = psum(jnp.sum(grad * sample_mask))
    root_h = psum(jnp.sum(hess * sample_mask))
    root_c = psum(jnp.sum(sample_mask))
    hist_root = psum(build_histogram(xb, grad, hess, sample_mask, num_bins=b,
                                     row_chunk=params.row_chunk,
                                     impl=params.hist_impl))
    tree = empty_tree(l)
    tree = tree._replace(
        leaf_value=tree.leaf_value.at[0].set(
            calculate_leaf_output(root_g, root_h, sp.lambda_l1, sp.lambda_l2,
                                  sp.max_delta_step)),
        leaf_weight=tree.leaf_weight.at[0].set(root_h),
        leaf_count=tree.leaf_count.at[0].set(root_c))
    best0 = child_best(hist_root, root_g, root_h, root_c, -jnp.inf, jnp.inf)
    best = jax.tree.map(lambda a, v: a.at[0].set(v), _empty_best(l), best0)

    # ---- initial partitioned layout: leaf 0 owns [0, n) -----------------
    pad = np_cap - n
    ar = jnp.arange(np_cap, dtype=jnp.int32)
    xb_fm = jnp.pad(xb.T, ((0, 0), (0, pad))).astype(jnp.uint8)
    m = sample_mask
    vals3 = jnp.pad(jnp.stack([grad * m, hess * m, m], axis=0),
                    ((0, 0), (0, pad)))
    row_leaf = jnp.where(ar < n, 0, -1).astype(jnp.int32)
    orig = jnp.where(ar < n, ar, -1)
    if axis_name is not None:
        row_leaf = pcast(row_leaf, (axis_name,), to="varying")
        orig = pcast(orig, (axis_name,), to="varying")
    leaf_begin = jnp.zeros((l,), jnp.int32)
    leaf_count = jnp.zeros((l,), jnp.int32).at[0].set(jnp.int32(n))

    state = _PartState(
        xb_fm=xb_fm, vals3=vals3, row_leaf=row_leaf, orig=orig,
        leaf_begin=leaf_begin, leaf_count=leaf_count, best=best, tree=tree,
        leaf_min=jnp.full((l,), -jnp.inf, jnp.float32),
        leaf_max=jnp.full((l,), jnp.inf, jnp.float32))

    def cond_fn(s: _PartState) -> jnp.ndarray:
        return (s.tree.num_leaves < l) & jnp.any(s.best.gain > 0.0)

    def step(s: _PartState) -> _PartState:
        tree = s.tree
        nl = tree.num_leaves
        rank = jnp.arange(kb, dtype=jnp.int32)
        gval, gleaf = lax.top_k(s.best.gain, kb)
        valid = (gval > 0.0) & (rank < (l - nl))
        nvalid = jnp.sum(valid.astype(jnp.int32))
        node = (nl - 1) + rank
        right_leaf = nl + rank
        cur = jax.tree.map(lambda a: a[gleaf], s.best)     # fields [kb]

        # ---- per-row slot + go-left over the K split columns ------------
        rank_of_leaf = jnp.full((l,), -1, jnp.int32)
        rank_of_leaf = _drop_set(rank_of_leaf, gleaf, rank, valid)
        safe_rl = jnp.clip(s.row_leaf, 0, l - 1)
        slot_r = jnp.where(s.row_leaf >= 0, rank_of_leaf[safe_rl], -1)
        active = slot_r >= 0
        rs = jnp.maximum(slot_r, 0)

        onek = rank[:, None] == rs[None, :]                 # [kb, Np]
        go_left = route_split_rows(s.xb_fm, rank, rs, onek, cur, meta,
                                   with_efb, params.with_categorical)

        # ---- segmented left-counts via one cumsum -----------------------
        actL = active & go_left
        gl_cum = jnp.cumsum(actL.astype(jnp.int32))         # inclusive
        beg = s.leaf_begin[gleaf]                           # [kb]
        cnt = jnp.where(valid, s.leaf_count[gleaf], 0)
        base_l = jnp.where(beg > 0, gl_cum[jnp.maximum(beg - 1, 0)], 0)
        end_i = jnp.clip(beg + cnt - 1, 0, np_cap - 1)
        n_left = jnp.where(cnt > 0, gl_cum[end_i] - base_l, 0)
        n_right = cnt - n_left

        # ---- new tile-aligned layout ------------------------------------
        counts_new = _drop_set(s.leaf_count, gleaf, n_left, valid)
        counts_new = _drop_set(counts_new, right_leaf, n_right, valid)
        seg_tiles = -(-counts_new // tile)                  # ceil [L]
        begin_new = (jnp.cumsum(seg_tiles) - seg_tiles) * tile

        base_l_r = base_l[rs]
        lrank = gl_cum - 1 - base_l_r
        rrank = (ar - beg[rs]) - (gl_cum - base_l_r)
        pos_split = jnp.where(go_left,
                              begin_new[safe_rl] + lrank,
                              begin_new[jnp.minimum(right_leaf[rs], l - 1)]
                              + rrank)
        pos_unsplit = begin_new[safe_rl] + (ar - s.leaf_begin[safe_rl])
        pos = jnp.where(active, pos_split, pos_unsplit)
        pos = jnp.where(s.row_leaf >= 0, pos, np_cap)       # pads drop

        row_leaf_new = jnp.where(active & ~go_left,
                                 right_leaf[rs], s.row_leaf)

        # ---- all 2K children's histograms over the OLD layout -----------
        if use_kernel:
            from .histogram_pallas import build_histogram_part_tiles
            tstart = jnp.arange(n_tiles, dtype=jnp.int32) * tile
            slot_at = slot_r[tstart]                        # [T]
            prev = jnp.concatenate([jnp.full((1,), -2, jnp.int32),
                                    slot_at[:-1]])
            first = ((slot_at >= 0) & (slot_at != prev)).astype(jnp.int32)
            hist6 = build_histogram_part_tiles(
                s.xb_fm, go_left.astype(jnp.float32), s.vals3,
                slot_at, first, num_bins=b, n_slots=kb, row_tile=tile,
                interpret=impl.endswith("interpret"),
                highest="highest" in impl)                  # [kb, C, B, 6]
            # the kernel leaves blocks of slots with NO local tiles
            # uninitialized; those slots can still be globally valid under
            # shard_map, so they must be zeroed here, per shard, before
            # the psum — validity alone is not enough
            has_tile = _local_slot_mask(slot_at, kb)        # [kb]
            ch_hist = jnp.stack([hist6[..., :3], hist6[..., 3:]],
                                axis=1).reshape(2 * kb, ncols, b, 3)
        else:
            # reference fallback (tests, CPU): combined-index build over
            # per-row child slots on the row-major view
            child_slot = jnp.where(active,
                                   rs * 2 + (~go_left).astype(jnp.int32), 0)
            ch_hist = _combined_hist(
                s.xb_fm.T, child_slot, active, s.vals3[0], s.vals3[1],
                s.vals3[2] * active.astype(jnp.float32), b, kb, impl,
                params.row_chunk, False)                    # [2K, C, B, 3]
            # scatter-built histograms are zero-initialized, so this mask
            # is a semantic no-op here — applying it anyway keeps the CPU
            # shard_map tests exercising the same masking the kernel needs
            has_tile = _local_slot_mask(jnp.where(active, slot_r, -1), kb)
        keep2 = jnp.repeat(valid & has_tile, 2)
        ch_hist = jnp.where(keep2[:, None, None, None], ch_hist, 0.0)
        ch_hist = psum(ch_hist)

        # ---- apply the permutation (DataPartition::Split analog) --------
        perm = jnp.full((np_cap,), np_cap - 1, jnp.int32)
        perm = perm.at[pos].set(ar, mode="drop")
        xb_fm2 = jnp.take(s.xb_fm, perm, axis=1)
        vals3_2 = jnp.take(s.vals3, perm, axis=1)
        row_leaf2 = row_leaf_new[perm]
        orig2 = s.orig[perm]

        # ---- tree bookkeeping for up to K splits (same as grow_batched) -
        (tree, leaf_min, leaf_max, safe_leaf,
         ch_min, ch_max, ch_ok) = apply_split_wave(
            tree, s.leaf_min, s.leaf_max, cur, gleaf, node, right_leaf,
            valid, nvalid, meta, sp, params.max_depth)

        # ---- best splits for all 2K children, one vmapped search --------
        ch_sg = interleave_lr(cur.left_sum_grad, cur.right_sum_grad)
        ch_sh = interleave_lr(cur.left_sum_hess, cur.right_sum_hess)
        ch_cnt = interleave_lr(cur.left_count, cur.right_count)
        b2k = jax.vmap(child_best)(ch_hist, ch_sg, ch_sh, ch_cnt,
                                   ch_min, ch_max)
        b2k = b2k._replace(gain=jnp.where(ch_ok, b2k.gain, K_MIN_SCORE))
        best = scatter_child_best(s.best, b2k, safe_leaf, right_leaf, valid)

        return _PartState(
            xb_fm=xb_fm2, vals3=vals3_2, row_leaf=row_leaf2, orig=orig2,
            leaf_begin=begin_new, leaf_count=counts_new, best=best,
            tree=tree, leaf_min=leaf_min, leaf_max=leaf_max)

    state = lax.while_loop(cond_fn, step, state)

    # ---- final per-row leaf ids in ORIGINAL row order -------------------
    safe_orig = jnp.where(state.orig >= 0, state.orig, n)
    leaf_id = jnp.zeros((n,), jnp.int32).at[safe_orig].set(
        jnp.maximum(state.row_leaf, 0), mode="drop")
    return state.tree, leaf_id, None
