"""Bit-packed device bin matrix (tpu_bin_packing; docs/Performance.md).

The reference keeps 4-bit bins two-per-byte in ``Dense4bitsBin``
(dense_nbits_bin.hpp); the TPU-native analog is split across two layers:

- **pair coding** (io/dataset.py ``_pack_small_pairs``): two <=16-bin
  features share one stored 8-bit column, ``code = bin_a * nb_b + bin_b``
  — the real "two bins per byte". ``tpu_bin_packing=nibble`` raises the
  joint-code cap from ``max_bin`` to 256 so pairing engages dataset-wide,
  halving stored columns and every byte of downstream histogram traffic.
- **word packing** (this module): whatever 8-bit columns the dataset
  produced are stored on device 4-codes-per-int32 word. Mosaic has no
  uint8 casts, so the int32-word layout is the Pallas-kernel-native one;
  matmul/scatter impls unpack lanes inside the jitted region (a shift/
  mask, never a second device copy of the unpacked matrix).

Codes are always 8 bits — a 4-bit word field buys nothing (XLA's cost
model floors scatter traffic at the f32 updates + i32 indices, and pair
codes need the full byte), so there is exactly ONE word format for both
``byte`` and ``nibble`` modes; the modes differ only at the dataset
level. All helpers here are layout-pure: pack -> unpack round-trips
bit-exactly for any column count (tail lanes zero-padded).
"""
from typing import Sequence

import numpy as np

import jax.numpy as jnp

# int32 words hold 4 eight-bit bin codes, little-endian lanes:
# word = c0 | c1 << 8 | c2 << 16 | c3 << 24
CODES_PER_WORD = 4
_LANE_BITS = 8
_LANE_MASK = 0xFF


def words_per_row(num_cols: int) -> int:
    """Packed word-matrix columns for ``num_cols`` 8-bit code columns."""
    return (int(num_cols) + CODES_PER_WORD - 1) // CODES_PER_WORD


def pack_words_np(xb: np.ndarray) -> np.ndarray:
    """Host-side pack: uint8 [N, C] -> int32 [N, ceil(C/4)] words.

    Tail lanes of the last word are zero (bin 0 is always a valid code,
    and no consumer addresses columns >= C, so the padding is inert).
    """
    xb = np.ascontiguousarray(xb, dtype=np.uint8)
    if xb.ndim != 2:
        raise ValueError("pack_words_np expects [N, C], got %s" % (xb.shape,))
    n, c = xb.shape
    w = words_per_row(c)
    padded = np.zeros((n, w * CODES_PER_WORD), dtype=np.uint8)
    padded[:, :c] = xb
    # little-endian uint8 lanes ARE the int32 word layout; a view avoids
    # per-lane shift loops on the host
    return padded.reshape(n, w, CODES_PER_WORD).view(np.uint32)[
        :, :, 0].astype(np.int32)


def unpack_words(xw: jnp.ndarray, num_cols: int,
                 dtype=jnp.uint8) -> jnp.ndarray:
    """Traceable inverse: int32 [N, W] words -> [N, num_cols] codes.

    Arithmetic right shift is fine — the & 0xFF strips any sign fill.
    ``dtype=jnp.int32`` skips the narrowing cast for consumers that want
    the lanes kernel-native (Mosaic has no uint8 casts).
    """
    cols = jnp.arange(num_cols, dtype=jnp.int32)
    w = xw[:, cols // CODES_PER_WORD]
    out = (w >> ((cols % CODES_PER_WORD) * _LANE_BITS)) & _LANE_MASK
    return out if dtype == jnp.int32 else out.astype(dtype)


def unpack_words_np(xw: np.ndarray, num_cols: int) -> np.ndarray:
    """Host-side inverse of :func:`pack_words_np` (tests, debugging)."""
    xw = np.ascontiguousarray(xw, dtype=np.int32)
    lanes = xw.view(np.uint8).reshape(xw.shape[0], -1)
    return lanes[:, :num_cols].copy()


def gather_code_columns(xw: jnp.ndarray, cols: jnp.ndarray) -> jnp.ndarray:
    """Gather selected code columns straight out of the packed words.

    ``cols`` is int32 [K] (or [N] for a per-row column choice, in which
    case xw rows and cols align); returns the 8-bit codes as int32
    without materializing the full unpacked matrix — the routing path's
    replacement for ``jnp.take_along_axis`` on an unpacked ``xb``.
    """
    word = jnp.take_along_axis(
        xw, (cols // CODES_PER_WORD).reshape(xw.shape[0], -1), axis=1)
    shift = ((cols % CODES_PER_WORD) * _LANE_BITS).reshape(xw.shape[0], -1)
    out = (word >> shift) & _LANE_MASK
    return out.reshape(cols.shape)


def resolve_bin_packing(mode: str, *, streamed: bool, tpu_shaped: bool,
                        col_num_bin: Sequence[int]) -> str:
    """Resolve tpu_bin_packing=auto to a concrete mode.

    auto policy: plain uint8 columns for the in-memory CPU path (word
    unpack adds shift/mask work the cost model charges for with no
    bandwidth to win back), ``byte`` for streamed ingest (words halve
    nothing by themselves but keep host chunks in the kernel-native
    layout), ``nibble`` on TPU-shaped backends — falling back to ``byte``
    when some candidate feature needs more than 16 bins (pair coding
    only engages for <=16-bin features).
    """
    mode = str(mode).strip().lower()
    if mode != "auto":
        return mode
    all_small = all(int(b) <= 16 for b in col_num_bin) if col_num_bin else False
    if tpu_shaped:
        return "nibble" if all_small else "byte"
    if streamed:
        return "byte"
    return "none"
