"""Device-side row partition: per-leaf contiguous index ranges.

TPU-native re-design of DataPartition (src/treelearner/data_partition.hpp:
20-37, 100+) — the component that makes histogram construction cost
O(rows_in_leaf) instead of O(num_data) per split. The reference keeps
``indices_`` grouped by leaf with ``leaf_begin_``/``leaf_count_`` and
partitions a leaf's range with per-thread counts + prefix sums; here the
same invariant is maintained functionally:

- ``order``   [N + chunk] int32 — row ids grouped by leaf (padded tail
  entries point past N and are dropped by masked scatters).
- ``leaf_begin`` / ``leaf_count`` [L] int32 — each leaf's contiguous range.

Both maintenance and consumption are chunked ``lax.while_loop``s whose trip
count is data-dependent (ceil(count / chunk)), so the device work per split
is proportional to the rows actually touched — the O(N x depth) total the
reference achieves — while every tensor op inside the loop body has static
shapes for XLA. The partition scatter fills the left child forward from the
range start and the right child backward from the range end, so a single
pass suffices (no count-then-scatter double pass; within-leaf row order is
irrelevant to histogram sums).

Histogram builds gather the leaf's rows through ``order`` (the analog of the
reference's ordered-gradient gather, dataset.cpp ConstructHistograms) and
feed fixed-size [chunk, F] tiles to the same one-hot-matmul / Pallas kernels
as the full-data path.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .histogram import hist_tile


class RowPartition(NamedTuple):
    order: jnp.ndarray       # [N + chunk] int32
    leaf_begin: jnp.ndarray  # [L] int32
    leaf_count: jnp.ndarray  # [L] int32


def init_partition(num_data: int, num_leaves: int, chunk: int) -> RowPartition:
    order = jnp.concatenate([
        jnp.arange(num_data, dtype=jnp.int32),
        jnp.full((chunk,), num_data, jnp.int32)])  # padded tail -> dropped
    leaf_begin = jnp.zeros((num_leaves,), jnp.int32)
    leaf_count = jnp.zeros((num_leaves,), jnp.int32) \
        .at[0].set(jnp.int32(num_data))
    return RowPartition(order, leaf_begin, leaf_count)


def split_leaf(part: RowPartition, leaf_id: jnp.ndarray, leaf, right_leaf,
               go_left_fn, valid, chunk: int
               ) -> Tuple[RowPartition, jnp.ndarray]:
    """Partition ``leaf``'s range into (left: keeps ``leaf``) and (right:
    becomes ``right_leaf``), updating per-row ``leaf_id`` along the way.

    ``go_left_fn(row_idx) -> bool[chunk]`` evaluates the split decision for a
    chunk of row ids (the Tree::Split + DataPartition::Split pair). With
    ``valid`` false the loop body never runs and nothing changes.
    """
    n_rows = leaf_id.shape[0]
    order_len = part.order.shape[0]
    beg = part.leaf_begin[leaf]
    cnt = jnp.where(valid, part.leaf_count[leaf], 0)

    def cond(c):
        i, nl, nr, _, _ = c
        return i * chunk < cnt

    def body(c):
        i, nl, nr, order_new, lid = c
        start = beg + i * chunk
        idx = lax.dynamic_slice(part.order, (start,), (chunk,))
        j = jnp.arange(chunk, dtype=jnp.int32)
        in_range = (i * chunk + j) < cnt
        go_left = go_left_fn(idx)
        is_l = go_left & in_range
        is_r = (~go_left) & in_range
        lpos = beg + nl + (jnp.cumsum(is_l.astype(jnp.int32)) - is_l)
        rpos = beg + cnt - 1 - nr - (jnp.cumsum(is_r.astype(jnp.int32)) - is_r)
        pos = jnp.where(go_left, lpos, rpos)
        pos = jnp.where(in_range, pos, order_len)        # OOB -> dropped
        order_new = order_new.at[pos].set(idx, mode="drop")
        idx_safe = jnp.where(in_range, idx, n_rows)      # OOB -> dropped
        lid = lid.at[idx_safe].set(
            jnp.where(go_left, leaf, right_leaf).astype(lid.dtype),
            mode="drop")
        return (i + 1, nl + jnp.sum(is_l.astype(jnp.int32)),
                nr + jnp.sum(is_r.astype(jnp.int32)), order_new, lid)

    _, n_left, n_right, order_new, leaf_id = lax.while_loop(
        cond, body, (jnp.int32(0), jnp.int32(0), jnp.int32(0),
                     part.order, leaf_id))

    leaf_begin = part.leaf_begin.at[right_leaf].set(
        jnp.where(valid, beg + n_left, part.leaf_begin[right_leaf]))
    leaf_count = part.leaf_count.at[leaf].set(
        jnp.where(valid, n_left, part.leaf_count[leaf]))
    leaf_count = leaf_count.at[right_leaf].set(
        jnp.where(valid, n_right, leaf_count[right_leaf]))
    return RowPartition(order_new, leaf_begin, leaf_count), leaf_id


def hist_for_leaf(part: RowPartition, leaf, xb: jnp.ndarray,
                  grad: jnp.ndarray, hess: jnp.ndarray, mask: jnp.ndarray,
                  num_bins: int, chunk: int, valid=True,
                  impl: str = "matmul") -> jnp.ndarray:
    """Build [F, B, 3] (grad, hess, count) histograms over one leaf's rows.

    Touches ceil(leaf_count / chunk) fixed-size tiles: row ids come from a
    contiguous slice of ``order``; feature bytes and gradients are gathered
    per tile. ``mask`` carries bagging/GOSS inclusion.
    """
    f = xb.shape[1]
    beg = part.leaf_begin[leaf]
    cnt = jnp.where(valid, part.leaf_count[leaf], 0)

    def cond(c):
        i, _ = c
        return i * chunk < cnt

    def body(c):
        i, acc = c
        start = beg + i * chunk
        idx = lax.dynamic_slice(part.order, (start,), (chunk,))
        j = jnp.arange(chunk, dtype=jnp.int32)
        in_range = (i * chunk + j) < cnt
        idx_safe = jnp.where(in_range, idx, 0)
        rows = jnp.take(xb, idx_safe, axis=0)            # [chunk, F]
        m = jnp.take(mask, idx_safe) * in_range.astype(jnp.float32)
        g = jnp.take(grad, idx_safe)
        h = jnp.take(hess, idx_safe)
        return i + 1, acc + hist_tile(rows, g, h, m, num_bins, impl)

    _, hist = lax.while_loop(
        cond, body, (jnp.int32(0), jnp.zeros((f, num_bins, 3), jnp.float32)))
    return hist
