"""Device-side row partition: per-leaf contiguous index ranges.

TPU-native re-design of DataPartition (src/treelearner/data_partition.hpp:
20-37, 100+) — the component that makes histogram construction cost
O(rows_in_leaf) instead of O(num_data) per split. The reference keeps
``indices_`` grouped by leaf with ``leaf_begin_``/``leaf_count_`` and
partitions a leaf's range with per-thread counts + prefix sums; here the
same invariant is maintained functionally:

- ``order``   [N + chunk] int32 — row ids grouped by leaf (the padded tail
  holds one trash slot that no leaf range ever covers).
- ``leaf_begin`` / ``leaf_count`` [L] int32 — each leaf's contiguous range.

Design notes from profiling on a v5e chip: inside a sequential growth loop,
dynamic-indexed ops (gather/scatter) cost ~0.4-0.8 ms *each* in latency
regardless of size up to ~64k elements, while dense full-array ops run at
memory bandwidth. The layout below therefore minimizes the NUMBER of
indexed ops per split rather than the elements they touch:

- per-row bins AND values ride behind one make_row_gather closure —
  bit-packed side by side on the normal path, so a histogram trip does
  ONE row gather total (two only under vmapped class batching, where
  packing would copy the shared bin matrix per class);
- every gather/scatter is annotated promise-in-bounds (indices are clamped
  or routed to the trash slot first);
- ``leaf_id`` is NOT maintained per split — it is reconstructed once per
  tree from the final ranges (leaf_id_from_partition), replacing
  O(N x depth) scattered writes with one dense searchsorted + one scatter.

Both maintenance and consumption are chunked ``lax.while_loop``s whose trip
count is data-dependent (ceil(count / chunk)); with the default chunk most
leaves take a single trip. The partition scatter fills the left child
forward from the range start and the right child backward from the range
end, so a single pass suffices (within-leaf row order is irrelevant to
histogram sums).

Histogram builds gather the leaf's rows through ``order`` (the analog of the
reference's ordered-gradient gather, dataset.cpp ConstructHistograms) and
feed fixed-size [chunk, F] tiles to the same one-hot-matmul / Pallas kernels
as the full-data path.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .histogram import hist_tile_vals


class RowPartition(NamedTuple):
    order: jnp.ndarray       # [N + chunk] int32
    leaf_begin: jnp.ndarray  # [L] int32
    leaf_count: jnp.ndarray  # [L] int32


def init_partition(num_data: int, num_leaves: int, chunk: int) -> RowPartition:
    order = jnp.concatenate([
        jnp.arange(num_data, dtype=jnp.int32),
        jnp.full((chunk,), num_data, jnp.int32)])  # padded tail -> dropped
    leaf_begin = jnp.zeros((num_leaves,), jnp.int32)
    leaf_count = jnp.zeros((num_leaves,), jnp.int32) \
        .at[0].set(jnp.int32(num_data))
    return RowPartition(order, leaf_begin, leaf_count)


def stack_vals(grad: jnp.ndarray, hess: jnp.ndarray,
               mask: jnp.ndarray) -> jnp.ndarray:
    """[N, 3] (grad*mask, hess*mask, mask) — one gather per histogram trip
    instead of three (the ordered-gradients copy of the reference,
    dataset.cpp ConstructHistograms)."""
    m = mask.astype(grad.dtype)
    return jnp.stack([grad * m, hess * m, m], axis=1)


def make_row_gather(xb: jnp.ndarray, vals: jnp.ndarray,
                    packed: bool = True):
    """Build the per-tile ``gather_rows(idx_safe) -> (rows, v)`` closure
    the partition loops use, owning the bins/values layout in ONE place.

    packed=True bit-packs [N, C] uint8 bins and [N, 3] float values side
    by side into one [N, C + 3*itemsize] uint8 array, so a histogram
    trip does ONE row gather instead of two (round-4 measurement: trip
    cost is bound by the NUMBER of indexed ops, not the bytes they
    move); the per-tile unpack is a free bitcast. packed=False keeps
    two gathers — required under vmapped class-batched growth, where
    the concat would materialize a PER-CLASS copy of the shared bin
    matrix."""
    if not packed:
        def gather_rows(idx_safe):
            rows = xb.at[idx_safe].get(mode="promise_in_bounds")
            v = vals.at[idx_safe].get(mode="promise_in_bounds")
            return rows, v
        return gather_rows
    n, c = xb.shape
    nbytes = jnp.dtype(vals.dtype).itemsize
    vb = lax.bitcast_convert_type(vals, jnp.uint8).reshape(n, -1)
    xv = jnp.concatenate([xb, vb], axis=1)
    val_dtype = vals.dtype

    def gather_rows(idx_safe):
        p = xv.at[idx_safe].get(mode="promise_in_bounds")
        rows = p[:, :c]
        v = lax.bitcast_convert_type(
            p[:, c:].reshape(p.shape[0], 3, nbytes), val_dtype)
        return rows, v
    return gather_rows


def tpu_shaped_backend() -> bool:
    """Allow-list backend sniff (tpu / the axon PJRT plugin), shared by
    the sort-placement policy below and the GBDT multiclass
    class-batching decision — an unknown plugin backend counts as NOT
    TPU-shaped so untested backends keep the conservative paths."""
    import jax
    backend = jax.default_backend().lower()
    return "tpu" in backend or "axon" in backend


def sort_placement_profitable(hist_impl: str, vmapped: bool) -> bool:
    """Single policy for partition_and_hist's use_sort flag.

    Round-4 on-chip re-measurement INVERTED the round-2 decision: at the
    new auto row_chunk (4096; also at 8192/16384) the scatter loop beats
    the single-trip sort placement on a v5e chip — 2.31 vs 1.97 iters/s
    at the 1M x 28 bench shape (a 4096-key lax.sort per split costs more
    than the scatter it replaced). Default is therefore OFF everywhere;
    ``LIGHTGBM_TPU_SORT_PLACEMENT=1`` re-enables it for experiments, the
    interpret spellings opt in so CPU tests keep covering the sort
    branch, and vmapped class-batched growth can never use it
    (lax.switch under vmap runs every branch per split)."""
    if vmapped:
        return False
    import os
    ov = os.environ.get("LIGHTGBM_TPU_SORT_PLACEMENT", "").strip().lower()
    if ov in ("1", "true", "yes", "on"):
        return True
    if ov in ("0", "false", "no", "off"):
        return False
    if ov:
        from ..log import Log
        Log.warning("ignoring unrecognized LIGHTGBM_TPU_SORT_PLACEMENT=%r "
                    "(use 0 or 1)" % ov)
    return hist_impl.startswith("pallas") and hist_impl.endswith("interpret")


def partition_and_hist(part: RowPartition, leaf_id, leaf, right_leaf,
                       go_left_from_rows, valid, chunk: int,
                       gather_rows, num_cols: int, num_bins: int,
                       impl: str, maintain_leaf_id: bool = False,
                       use_sort: bool = False, val_dtype=jnp.float32):
    """One pass over ``leaf``'s rows that BOTH partitions the range and
    builds both children's [F, B, 3] histograms.

    This fuses DataPartition::Split with ConstructHistograms and replaces
    the histogram-subtraction dance (serial_tree_learner.cpp:383-397): with
    the parent's rows already gathered for the partition decision, weighting
    them into six value channels (3 per child) prices both children at one
    row visit — fewer total rows touched than smaller-child + subtraction
    (P vs 1.5P per split), and two fewer indexed ops per split, which is
    what actually dominates on TPU (see module docstring).

    ``go_left_from_rows(rows[chunk, F]) -> bool[chunk]`` evaluates the split
    decision directly on the gathered feature bytes. ``gather_rows`` is a
    make_row_gather() closure owning the bins+values layout (packed:
    ONE row gather per tile serves both the routing bytes and the value
    channels). ``use_sort`` selects the single-trip sort placement (keep
    it off under vmap — the batching rule for lax.switch lowers to a
    select that runs every branch per split, semantically fine but a
    performance cliff).

    Returns (new_part, new_leaf_id, hist_left, hist_right).
    """
    n_rows = leaf_id.shape[0]
    f = num_cols
    order_len = part.order.shape[0]
    trash = order_len - 1                  # never inside any leaf range
    beg = part.leaf_begin[leaf]
    cnt = jnp.where(valid, part.leaf_count[leaf], 0)

    def load_tile(start, in_range):
        """Shared tile load: gather the tile's bins+values rows, decide
        the split, weight the six child channels, add the histogram
        tile."""
        idx = lax.dynamic_slice(part.order, (start,), (chunk,))
        idx_safe = jnp.minimum(idx, n_rows - 1)
        rows, v = gather_rows(idx_safe)                        # [chunk, F/3]
        v = v * in_range[:, None].astype(v.dtype)
        go_left = go_left_from_rows(rows)
        is_l = go_left & in_range
        is_r = (~go_left) & in_range
        v6 = jnp.concatenate([v * is_l[:, None].astype(v.dtype),
                              v * is_r[:, None].astype(v.dtype)],
                             axis=1)                           # [chunk, 6]
        hist = hist_tile_vals(rows, v6, num_bins, impl)
        return idx, idx_safe, go_left, is_l, is_r, hist

    def maybe_lid(lid, idx_safe, is_r):
        if not maintain_leaf_id:
            return lid
        # max-scatter: right_leaf exceeds every id assigned so far; left
        # rows keep their id; padded/OOB duplicates contribute 0
        val = jnp.where(is_r, right_leaf, 0).astype(lid.dtype)
        return lid.at[idx_safe].max(val, mode="promise_in_bounds")

    def cond(c):
        i = c[0]
        return i * chunk < cnt

    def body(c):
        i, nl, nr, order_new, lid, acc = c
        j = jnp.arange(chunk, dtype=jnp.int32)
        in_range = (i * chunk + j) < cnt
        idx, idx_safe, go_left, is_l, is_r, hist = load_tile(
            beg + i * chunk, in_range)
        acc = acc + hist
        # in_range is a prefix mask, so within range the right-side running
        # count is (position + 1) - left count: one cumsum covers both
        cl = jnp.cumsum(is_l.astype(jnp.int32), dtype=jnp.int32)
        cr = (j + 1) - cl
        kl = cl[-1]
        kr = jnp.sum(in_range.astype(jnp.int32), dtype=jnp.int32) - kl
        lpos = beg + nl + (cl - is_l)
        rpos = beg + cnt - 1 - nr - (cr - is_r)
        pos = jnp.where(go_left, lpos, rpos)
        pos = jnp.where(in_range, pos, trash)
        order_new = order_new.at[pos].set(idx, mode="promise_in_bounds")
        lid = maybe_lid(lid, idx_safe, is_r)
        return (i + 1, nl + kl, nr + kr, order_new, lid, acc)

    def multi_trip(_):
        init = (jnp.int32(0), jnp.int32(0), jnp.int32(0), part.order,
                leaf_id, jnp.zeros((f, num_bins, 6), val_dtype))
        _, nl, nr, order_new, lid, acc = lax.while_loop(cond, body, init)
        return order_new, lid, nl, nr, acc

    if not use_sort:
        # two reasons to stay on the bare while_loop (which already handles
        # cnt == 0 and single trips): on CPU XLA's scatter is cheap and the
        # sort is not, and under vmap (multiclass class-batched growth)
        # lax.switch would execute ALL branches per split
        order_new, leaf_id, n_left, n_right, acc6 = multi_trip(None)
    else:
        def single_trip(_):
            # cnt <= chunk: the whole leaf fits in one tile, and the stable
            # partition becomes a SORT + one contiguous
            # dynamic-update-slice — no scatter, no cumsum (both are
            # latency-bound on TPU). The tail of the slice reads whatever
            # follows the leaf's range (the next leaf's rows / the
            # padding); keyed 2 it sorts stably to the back and is written
            # back unchanged, so the rest of ``order`` is untouched.
            in_range = jnp.arange(chunk, dtype=jnp.int32) < cnt
            idx, idx_safe, _, is_l, is_r, acc = load_tile(beg, in_range)
            key = jnp.where(is_l, 0, jnp.where(is_r, 1, 2)).astype(jnp.uint8)
            _, sidx = lax.sort((key, idx), num_keys=1, is_stable=True)
            order_new = lax.dynamic_update_slice(part.order, sidx, (beg,))
            lid = maybe_lid(leaf_id, idx_safe, is_r)
            return (order_new, lid,
                    jnp.sum(is_l.astype(jnp.int32), dtype=jnp.int32),
                    jnp.sum(is_r.astype(jnp.int32), dtype=jnp.int32), acc)

        def dead(_):
            return (part.order, leaf_id, jnp.int32(0), jnp.int32(0),
                    jnp.zeros((f, num_bins, 6), val_dtype))

        which = jnp.where(cnt == 0, 0, jnp.where(cnt <= chunk, 1, 2))
        order_new, leaf_id, n_left, n_right, acc6 = lax.switch(
            which, [dead, single_trip, multi_trip], None)

    leaf_begin = part.leaf_begin.at[right_leaf].set(
        jnp.where(valid, beg + n_left, part.leaf_begin[right_leaf]))
    leaf_count = part.leaf_count.at[leaf].set(
        jnp.where(valid, n_left, part.leaf_count[leaf]))
    leaf_count = leaf_count.at[right_leaf].set(
        jnp.where(valid, n_right, leaf_count[right_leaf]))
    return (RowPartition(order_new, leaf_begin, leaf_count), leaf_id,
            acc6[:, :, :3], acc6[:, :, 3:])


def hist_for_leaf(part: RowPartition, leaf, gather_rows, num_rows: int,
                  num_cols: int, num_bins: int, chunk: int, valid=True,
                  impl: str = "matmul",
                  val_dtype=jnp.float32) -> jnp.ndarray:
    """Build [F, B, 3] (grad, hess, count) histograms over one leaf's rows.

    Touches ceil(leaf_count / chunk) fixed-size tiles: row ids come from a
    contiguous slice of ``order``; ``gather_rows`` (make_row_gather) loads
    each tile's bins+values — one gather when packed.
    """
    f = num_cols
    beg = part.leaf_begin[leaf]
    cnt = jnp.where(valid, part.leaf_count[leaf], 0)

    def cond(c):
        i, _ = c
        return i * chunk < cnt

    def body(c):
        i, acc = c
        start = beg + i * chunk
        idx = lax.dynamic_slice(part.order, (start,), (chunk,))
        j = jnp.arange(chunk, dtype=jnp.int32)
        in_range = (i * chunk + j) < cnt
        idx_safe = jnp.minimum(jnp.where(in_range, idx, 0), num_rows - 1)
        rows, v = gather_rows(idx_safe)                        # [chunk, F/3]
        v = v * in_range[:, None].astype(v.dtype)
        return i + 1, acc + hist_tile_vals(rows, v, num_bins, impl)

    _, hist = lax.while_loop(
        cond, body, (jnp.int32(0), jnp.zeros((f, num_bins, 3), val_dtype)))
    return hist


def leaf_id_from_partition(part: RowPartition, num_data: int,
                           num_leaves: int) -> jnp.ndarray:
    """Reconstruct the per-row leaf assignment from the final ranges.

    The leaf ranges tile [0, num_data) exactly (DataPartition invariant), so
    position -> leaf is a searchsorted over the count-filtered sorted begins,
    and row -> leaf is one scatter through ``order`` — O(N log L) dense work
    once per tree instead of O(N x depth) scattered writes during growth.
    """
    # empty leaves sort past every real range
    begins = jnp.where(part.leaf_count > 0, part.leaf_begin,
                       jnp.int32(num_data + 1))
    sort_begins, sort_leaf = lax.sort(
        (begins, jnp.arange(num_leaves, dtype=jnp.int32)), num_keys=1)
    pos = jnp.arange(num_data, dtype=jnp.int32)
    block = jnp.searchsorted(sort_begins, pos, side="right") - 1
    pos_leaf = sort_leaf[jnp.clip(block, 0, num_leaves - 1)]
    rows = jnp.minimum(part.order[:num_data], num_data - 1)
    return jnp.zeros((num_data,), jnp.int32).at[rows].set(
        pos_leaf, mode="promise_in_bounds")


def frontier_slots_from_partition(part: RowPartition, leaves: jnp.ndarray,
                                  num_data: int) -> jnp.ndarray:
    """Per-row frontier slot from the row partition: rows inside
    ``leaves[i]``'s range get slot i, every other row -1.

    This is the hand-off from the partition to
    histogram.build_histogram_frontier — the partition gives the builder
    the wave's LEAF IDS and the builder sweeps the dataset once for all
    of them, instead of extracting one leaf's row list per histogram.
    Same searchsorted-over-sorted-begins shape as leaf_id_from_partition,
    except the selected leaves cover only PART of [0, num_data), so a
    positional hit also range-checks against the owning leaf's count.
    """
    k = leaves.shape[0]
    leaf_begin = part.leaf_begin[leaves]
    leaf_count = part.leaf_count[leaves]
    # empty/unselected ranges sort past every real one
    begins = jnp.where(leaf_count > 0, leaf_begin, jnp.int32(num_data + 1))
    sort_begins, sort_slot = lax.sort(
        (begins, jnp.arange(k, dtype=jnp.int32)), num_keys=1)
    pos = jnp.arange(num_data, dtype=jnp.int32)
    block = jnp.searchsorted(sort_begins, pos, side="right") - 1
    cand = sort_slot[jnp.clip(block, 0, k - 1)]
    inside = ((block >= 0) & (pos >= leaf_begin[cand])
              & (pos < leaf_begin[cand] + leaf_count[cand]))
    pos_slot = jnp.where(inside, cand, -1)
    rows = jnp.minimum(part.order[:num_data], num_data - 1)
    return jnp.full((num_data,), -1, jnp.int32).at[rows].set(
        pos_slot, mode="promise_in_bounds")
