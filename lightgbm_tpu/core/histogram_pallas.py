"""Pallas TPU histogram kernel — the device analog of the reference's OpenCL
histogram kernels (ocl/histogram256.cl workgroup local-memory design,
gpu_tree_learner.cpp:951-1045).

Digit-factorized design (measured 4.3x faster than a direct one-hot kernel
on a v5e chip at 1M x 28 x 256): split each bin index into high/low base-16
digits, b = 16*hi + lo. The [B]-wide one-hot comparison then factorizes into
two 16-wide one-hots whose *outer product* is the full one-hot — and the
outer-product contraction over rows is exactly a matmul:

    hist[k, hi, lo] = sum_c (vals[k, c] * eqhi[hi, c]) * eqlo[c, lo]

so the bin axis is materialized by the MXU as a [3*Hi, C] @ [C, 16] product
instead of by N*F*B vector comparisons; the VPU only builds N*F*(Hi+16)
comparisons. All intermediates live in VMEM: per-pass HBM traffic is just
xb (N*F bytes) + vals (12N bytes) + the [3, F, B] output.

Precision: the values operand is split into two bfloat16 terms
(a = hi16(a) + lo16(a)) and contracted with the exactly-representable
one-hot in two default-precision MXU passes, at half Precision.HIGHEST's
cost. Per-ELEMENT error is ~|v|*2^-17; summed over a bin this lands within
~3e-6 of float64 relative to the bin's sum of |values| (measured), though a
bin whose gradients nearly cancel can see a larger error relative to its
small net sum — same caveat as any fixed-precision accumulation, and the
same stance as the GPU learner's single-precision histograms
(gpu_tree_learner.h:74-78).

Grid = (feature_tiles, row_tiles); rows are the innermost sequential
reduction so each feature tile's accumulator stays resident in VMEM across
all row tiles (the "workgroup local histogram" without atomics — one grid
cell owns its bin slice).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .binpack import unpack_words


def _digit_contract(a, eq, highest: bool):
    """Shared MXU contraction of every digit kernel in this file:
    [M, C] values-by-digit LHS against a [Nw, C] one-hot RHS, contracted
    over rows. ``highest`` keeps full f32 (the gpu_use_dp analog, ~2x
    MXU cost); the default splits the values operand into two bfloat16
    terms — the one-hot side is exactly representable, so two
    default-precision passes land within ~3e-6 of f32."""
    if highest:
        return jax.lax.dot_general(
            a, eq, (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)
    a_top = a.astype(jnp.bfloat16)
    a_rem = (a - a_top.astype(jnp.float32)).astype(jnp.bfloat16)
    eqb = eq.astype(jnp.bfloat16)
    part = jax.lax.dot_general(
        a_top, eqb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    return part + jax.lax.dot_general(
        a_rem, eqb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)


def _hist_kernel(xb_ref, vals_ref, out_ref, *, hi_n: int, highest: bool):
    """One (feature_tile, row_tile) grid cell.

    xb_ref: [Ft, C] uint8 binned values; vals_ref: [K, C] f32 value
    channels (K = 3: grad*mask, hess*mask, mask; K = 6: the same for both
    children of a fused partition+histogram pass);
    out_ref: [K, Ft, Hi, 16] f32 accumulator.

    ``highest``: contract in full f32 (Precision.HIGHEST) instead of the
    default two-term bf16 split — ~2x the MXU cost, for users who need the
    tightest reference parity (the gpu_use_dp analog, config.h:784).
    """
    r = pl.program_id(1)
    xb = xb_ref[...].astype(jnp.int32)                       # [Ft, C]
    vals = vals_ref[...]                                     # [K, C]
    ft, c = xb.shape
    k = vals.shape[0]

    @pl.when(r == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    iota_lo = jax.lax.broadcasted_iota(jnp.int32, (16, c), 0)
    iota_hi = jax.lax.broadcasted_iota(jnp.int32, (hi_n, c), 0)
    for j in range(ft):
        x = xb[j:j + 1, :]                                   # [1, C]
        hi_eq = iota_hi == (x >> 4)                          # [Hi, C]
        lo_eq = iota_lo == (x & 15)                          # [16, C]
        a = jnp.where(hi_eq[None, :, :], vals[:, None, :],
                      0.0).reshape(k * hi_n, c)              # [K*Hi, C]
        # NB: build the one-hot in f32 and let _digit_contract downcast —
        # a direct bf16 select on the i1 mask trips a Mosaic relayout bug
        # on this toolchain
        eqlo = jnp.where(lo_eq, 1.0, 0.0)
        part = _digit_contract(a, eqlo, highest)             # [K*Hi, 16]
        out_ref[:, j, :, :] += part.reshape(k, hi_n, 16)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "row_tile", "feature_tile",
                                    "interpret", "highest", "packed_cols"))
def build_histogram_pallas(xb: jnp.ndarray, grad: jnp.ndarray,
                           hess: jnp.ndarray, mask: jnp.ndarray,
                           num_bins: int, row_tile: int = 2048,
                           feature_tile: int = 8,
                           interpret: bool = False,
                           highest: bool = False,
                           packed_cols: int = 0) -> jnp.ndarray:
    """[N, F] uint8 bins + per-row values -> [F, B, 3] f32 histograms.

    Same contract as histogram.build_histogram (incl. int32-word-packed
    xb via ``packed_cols``). The feature-major transpose of ``xb`` is
    loop-invariant across the splits of one tree, so XLA hoists it out of
    the growth loop.
    """
    vals = jnp.stack([grad * mask, hess * mask, mask], axis=0)   # [3, N]
    return build_histogram_pallas_vals(xb, vals, num_bins, row_tile,
                                       feature_tile, interpret, highest,
                                       packed_cols)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "row_tile", "feature_tile",
                                    "interpret", "highest", "packed_cols"))
def build_histogram_pallas_vals(xb: jnp.ndarray, vals: jnp.ndarray,
                                num_bins: int, row_tile: int = 2048,
                                feature_tile: int = 8,
                                interpret: bool = False,
                                highest: bool = False,
                                packed_cols: int = 0) -> jnp.ndarray:
    """Same kernel with pre-stacked value channels: vals [K, N] -> output
    [F, B, K] (K = 3 for one histogram, 6 for a fused two-child pass)."""
    if packed_cols:
        # unpack int32 words straight to int32 lanes (the kernels cast to
        # int32 anyway and Mosaic has no uint8 casts, so the word layout
        # is kernel-native: shift/mask, no narrowing)
        xb = unpack_words(xb, packed_cols, dtype=jnp.int32)
    n, f = xb.shape
    k = vals.shape[0]
    hi_n = max(1, (num_bins + 15) // 16)   # bins above num_bins stay zero

    f_pad = (-f) % feature_tile
    n_pad = (-n) % row_tile
    # NB: uint8, not int8 — bins >= 128 must not wrap negative (packed
    # lanes stay int32, already masked non-negative)
    xb_t = jnp.pad(xb.T, ((0, f_pad), (0, n_pad)))
    if not packed_cols:
        xb_t = xb_t.astype(jnp.uint8)
    vals = jnp.pad(vals, ((0, 0), (0, n_pad)))   # padded rows carry mask 0
    fp = f + f_pad

    kernel = functools.partial(_hist_kernel, hi_n=hi_n, highest=highest)
    out = pl.pallas_call(
        kernel,
        grid=(fp // feature_tile, (n + n_pad) // row_tile),
        in_specs=[
            pl.BlockSpec((feature_tile, row_tile), lambda i, r: (i, r)),
            pl.BlockSpec((k, row_tile), lambda i, r: (0, r)),
        ],
        out_specs=pl.BlockSpec((k, feature_tile, hi_n, 16),
                               lambda i, r: (0, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((k, fp, hi_n, 16), jnp.float32),
        interpret=interpret,
    )(xb_t, vals)
    out = out.reshape(k, fp, hi_n * 16)
    return jnp.moveaxis(out, 0, -1)[:f, :num_bins]           # [F, B, 3]


def _hist_slot6_kernel(xb_ref, slot_ref, sel_ref, vals_ref, out_ref, *,
                       hi_n: int, n_slots: int, highest: bool):
    """Joint slot kernel, PARENT-slot x 6-channel variant (round-4 MXU
    fix): rows carry their splitting PARENT's rank (n_slots = K) and a
    go-left selector; the kernel routes (g, h, m) into left/right channel
    triples, so both children come out of half the slot one-hot width of
    the child-slot variant above — 2x fewer MXU column passes AND 2x the
    systolic-row utilization (M = 6*Hi = 96 vs 48).
    """
    r = pl.program_id(1)
    slot = slot_ref[...].astype(jnp.int32)                   # [1, C]
    sel = sel_ref[...]                                       # [1, C]
    v3 = vals_ref[...]                                       # [3, C]
    xb = xb_ref[...].astype(jnp.int32)                       # [Ft, C]
    ft, c = xb.shape

    @pl.when(r == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(jnp.any(slot >= 0))
    def _body():
        v6 = jnp.concatenate([v3 * sel, v3 * (1.0 - sel)],
                             axis=0)                         # [6, C]
        iota_lo = jax.lax.broadcasted_iota(jnp.int32, (16, c), 0)
        iota_hi = jax.lax.broadcasted_iota(jnp.int32, (hi_n, c), 0)
        iota_s = jax.lax.broadcasted_iota(jnp.int32, (n_slots, c), 0)
        s_eq = iota_s == slot                                # [S, C]
        for j in range(ft):
            x = xb[j:j + 1, :]
            hi_eq = iota_hi == (x >> 4)
            lo_eq = iota_lo == (x & 15)
            a = jnp.where(hi_eq[None, :, :], v6[:, None, :],
                          0.0).reshape(6 * hi_n, c)          # [6*Hi, C]
            eqj = jnp.where(s_eq[:, None, :] & lo_eq[None, :, :], 1.0,
                            0.0).reshape(n_slots * 16, c)    # [S*16, C]
            part = _digit_contract(a, eqj, highest)
            out_ref[:, j, :, :] += part.reshape(6, hi_n, n_slots * 16)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "n_slots", "row_tile",
                                    "feature_tile", "interpret", "highest"))
def build_histogram_slots6(xb: jnp.ndarray, slot: jnp.ndarray,
                           sel: jnp.ndarray, vals: jnp.ndarray,
                           num_bins: int, n_slots: int,
                           row_tile: int = 2048, feature_tile: int = 8,
                           interpret: bool = False,
                           highest: bool = False) -> jnp.ndarray:
    """[N, F] uint8 bins + per-row PARENT-slot ids (-1 = inactive) +
    per-row go-left selector + [3, N] value channels ->
    [n_slots, F, B, 6] f32: channels [g,h,m]*sel then [g,h,m]*(1-sel) —
    both children of every splitting parent in one pass, at half the
    one-hot width of build_histogram_slots."""
    n, f = xb.shape
    hi_n = max(1, (num_bins + 15) // 16)
    f_pad = (-f) % feature_tile
    n_pad = (-n) % row_tile
    xb_t = jnp.pad(xb.T, ((0, f_pad), (0, n_pad))).astype(jnp.uint8)
    slot2 = jnp.minimum(slot.astype(jnp.int32), n_slots - 1)
    slot2 = jnp.pad(slot2, (0, n_pad), constant_values=-1)[None, :]
    sel2 = jnp.pad(sel.astype(jnp.float32), (0, n_pad))[None, :]
    vals = jnp.pad(vals, ((0, 0), (0, n_pad)))
    fp = f + f_pad

    kernel = functools.partial(_hist_slot6_kernel, hi_n=hi_n,
                               n_slots=n_slots, highest=highest)
    out = pl.pallas_call(
        kernel,
        grid=(fp // feature_tile, (n + n_pad) // row_tile),
        in_specs=[
            pl.BlockSpec((feature_tile, row_tile), lambda i, r: (i, r)),
            pl.BlockSpec((1, row_tile), lambda i, r: (0, r)),
            pl.BlockSpec((1, row_tile), lambda i, r: (0, r)),
            pl.BlockSpec((3, row_tile), lambda i, r: (0, r)),
        ],
        out_specs=pl.BlockSpec((6, feature_tile, hi_n, n_slots * 16),
                               lambda i, r: (0, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((6, fp, hi_n, n_slots * 16),
                                       jnp.float32),
        interpret=interpret,
    )(xb_t, slot2, sel2, vals)
    # [6, F, Hi, S, 16] -> [S, F, B, 6]
    out = out.reshape(6, fp, hi_n, n_slots, 16)
    out = jnp.transpose(out, (3, 1, 2, 4, 0)).reshape(
        n_slots, fp, hi_n * 16, 6)
    return out[:, :f, :num_bins]


def _hist_part_kernel(tile_slot_ref, tile_first_ref, xb_ref, sel_ref,
                      vals_ref, out_ref, *, hi_n: int, highest: bool):
    """One (feature_tile, row_tile) grid cell of the PARTITIONED batched
    kernel (core/grow_batched_part.py): rows arrive physically grouped by
    leaf into row_tile-ALIGNED segments, so every row tile belongs to at
    most ONE frontier slot — the tile->slot map rides in scalar-prefetch
    SMEM and drives the OUTPUT BlockSpec index directly. Unlike the joint
    slot kernel above, no S-wide one-hot ever materializes: per-row work
    is the base digit kernel's (the joint kernel pays S x redundant MXU
    work because each row matches exactly one of its S x 16 columns).

    Six value channels per slot: ``sel`` in {1.0, 0.0} routes each row's
    (g, h, m) into the first or second channel triple — both children of
    a splitting leaf (sel = go_left) in ONE pass over the parent's rows,
    at BETTER MXU utilization than 3 channels (M = 6*Hi = 96 rows of the
    systolic array instead of 48).

    tile_slot[t] == -1 marks a tile with no frontier rows: its compute
    body is skipped entirely, so per-step cost tracks the splitting
    leaves' rows, not N. tile_first[t] == 1 marks the first tile of a
    slot's run and zero-initializes the accumulator (blocks of slots that
    never appear keep garbage — callers mask invalid slots after).

    Pallas TPU's pipelined output machinery requires every output block
    to be visited in ONE contiguous grid run — revisiting a block after
    visiting others corrupts it via the stale double-buffer (measured on
    a v5e chip: mapping inactive tiles to slot 0 silently mixed partial
    sums into slot 0's result). Inactive tiles therefore index a
    DEDICATED dummy block (slot n_slots) whose garbage content is
    dropped by the caller; real slots are each one contiguous segment of
    the layout, so they are never revisited.
    """
    r = pl.program_id(1)
    slot = tile_slot_ref[r]

    @pl.when(tile_first_ref[r] == 1)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(slot >= 0)
    def _body():
        xb = xb_ref[...].astype(jnp.int32)                   # [Ft, C]
        sel = sel_ref[...]                                   # [1, C]
        v3 = vals_ref[...]                                   # [3, C]
        ft, c = xb.shape
        v6 = jnp.concatenate([v3 * sel, v3 * (1.0 - sel)],
                             axis=0)                         # [6, C]
        iota_lo = jax.lax.broadcasted_iota(jnp.int32, (16, c), 0)
        iota_hi = jax.lax.broadcasted_iota(jnp.int32, (hi_n, c), 0)
        for j in range(ft):
            x = xb[j:j + 1, :]                               # [1, C]
            hi_eq = iota_hi == (x >> 4)                      # [Hi, C]
            lo_eq = iota_lo == (x & 15)                      # [16, C]
            a = jnp.where(hi_eq[None, :, :], v6[:, None, :],
                          0.0).reshape(6 * hi_n, c)          # [6*Hi, C]
            eqlo = jnp.where(lo_eq, 1.0, 0.0)
            part = _digit_contract(a, eqlo, highest)         # [6*Hi, 16]
            out_ref[0, :, j, :, :] += part.reshape(6, hi_n, 16)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "n_slots", "row_tile",
                                    "feature_tile", "interpret", "highest"))
def build_histogram_part_tiles(xb_fm: jnp.ndarray, sel: jnp.ndarray,
                               vals: jnp.ndarray, tile_slot: jnp.ndarray,
                               tile_first: jnp.ndarray, num_bins: int,
                               n_slots: int, row_tile: int = 2048,
                               feature_tile: int = 8,
                               interpret: bool = False,
                               highest: bool = False) -> jnp.ndarray:
    """Partitioned-layout histograms: [F, Np] FEATURE-MAJOR uint8 bins
    (Np a multiple of row_tile, rows grouped into tile-aligned leaf
    segments) + per-row channel selector + [3, Np] value channels +
    per-tile slot/first maps -> [n_slots, F, B, 6] f32.

    Channel order per slot: [g*sel, h*sel, m*sel, g*(1-sel), h*(1-sel),
    m*(1-sel)] — left child then right child when sel = go_left. Rows in
    tiles with tile_slot == -1 and rows whose value channels are zero
    (segment padding) contribute nothing. Slots with no tiles keep
    UNINITIALIZED memory — mask invalid slots downstream.
    """
    f, np_ = xb_fm.shape
    assert np_ % row_tile == 0, "partitioned layout must be tile-aligned"
    hi_n = max(1, (num_bins + 15) // 16)
    f_pad = (-f) % feature_tile
    xb_p = jnp.pad(xb_fm, ((0, f_pad), (0, 0))).astype(jnp.uint8)
    fp = f + f_pad
    t = np_ // row_tile

    from jax.experimental.pallas import tpu as pltpu
    kernel = functools.partial(_hist_part_kernel, hi_n=hi_n,
                               highest=highest)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(fp // feature_tile, t),
        in_specs=[
            pl.BlockSpec((feature_tile, row_tile),
                         lambda i, r, *_: (i, r)),
            pl.BlockSpec((1, row_tile), lambda i, r, *_: (0, r)),
            pl.BlockSpec((3, row_tile), lambda i, r, *_: (0, r)),
        ],
        out_specs=pl.BlockSpec(
            (1, 6, feature_tile, hi_n, 16),
            lambda i, r, slot_ref, first_ref: (
                jnp.where(slot_ref[r] < 0, n_slots, slot_ref[r]),
                0, i, 0, 0)),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_slots + 1, 6, fp, hi_n, 16),
                                       jnp.float32),
        interpret=interpret,
    )(tile_slot.astype(jnp.int32), tile_first.astype(jnp.int32),
      xb_p, sel[None, :], vals)
    # [S+1, 6, Fp, Hi, 16] -> [S, F, B, 6] (dummy slot dropped)
    out = out[:n_slots].reshape(n_slots, 6, fp, hi_n * 16)
    return jnp.transpose(out, (0, 2, 3, 1))[:, :f, :num_bins]


def _hist_slot_kernel(xb_ref, slot_ref, vals_ref, out_ref, *, hi_n: int,
                      n_slots: int, highest: bool):
    """One (feature_tile, row_tile) grid cell of the SLOT-EXTENDED digit
    kernel (batched-frontier growth, core/grow_batched.py): every row
    carries a slot id in [0, n_slots) — which frontier-leaf child it
    belongs to this step — and the kernel accumulates a separate [B]
    histogram per (slot, feature).

    The combined index slot*B + 16*hi + lo factorizes into THREE one-hots;
    grouping (vals x hi) on the left and (slot x lo) on the right keeps
    one MXU contraction per feature: [K*Hi, C] @ [C, S*16]. Rows whose
    value channels are zero (masked / not in any split leaf) contribute
    nothing regardless of slot id.

    xb_ref: [Ft, C] uint8; slot_ref: [1, C] int32 (-1 = row inactive this
    step); vals_ref: [K, C] f32; out_ref: [K, Ft, Hi, S*16] f32 (lo is
    minor so the RHS one-hot needs no in-kernel transpose; the caller
    reorders to [S, F, B, K]).

    A row tile whose slots are ALL -1 skips its entire compute body —
    with actives packed to the front (grow_batched's tpu_batched_pack),
    per-step cost becomes proportional to the split leaves' rows instead
    of N.
    """
    r = pl.program_id(1)
    slot = slot_ref[...].astype(jnp.int32)                   # [1, C]
    vals = vals_ref[...]                                     # [K, C]
    k = vals.shape[0]
    ft = xb_ref.shape[0]
    c = slot.shape[1]

    @pl.when(r == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(jnp.any(slot >= 0))
    def _body():
        _hist_slot_tile(xb_ref, slot, vals, out_ref, hi_n=hi_n,
                        n_slots=n_slots, highest=highest, k=k, ft=ft, c=c)


def _hist_slot_tile(xb_ref, slot, vals, out_ref, *, hi_n, n_slots, highest,
                    k, ft, c):
    xb = xb_ref[...].astype(jnp.int32)                       # [Ft, C]
    iota_lo = jax.lax.broadcasted_iota(jnp.int32, (16, c), 0)
    iota_hi = jax.lax.broadcasted_iota(jnp.int32, (hi_n, c), 0)
    iota_s = jax.lax.broadcasted_iota(jnp.int32, (n_slots, c), 0)
    s_eq = iota_s == slot                                    # [S, C]
    for j in range(ft):
        x = xb[j:j + 1, :]                                   # [1, C]
        hi_eq = iota_hi == (x >> 4)                          # [Hi, C]
        lo_eq = iota_lo == (x & 15)                          # [16, C]
        a = jnp.where(hi_eq[None, :, :], vals[:, None, :],
                      0.0).reshape(k * hi_n, c)              # [K*Hi, C]
        # RHS one-hot of (slot, lo) jointly: column index s*16 + lo
        eqj = jnp.where(s_eq[:, None, :] & lo_eq[None, :, :], 1.0,
                        0.0).reshape(n_slots * 16, c)        # [S*16, C]
        part = _digit_contract(a, eqj, highest)              # [K*Hi, S*16]
        out_ref[:, j, :, :] += part.reshape(k, hi_n, n_slots * 16)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "n_slots", "row_tile",
                                    "feature_tile", "interpret", "highest",
                                    "packed_cols"))
def build_histogram_slots(xb: jnp.ndarray, slot: jnp.ndarray,
                          vals: jnp.ndarray, num_bins: int, n_slots: int,
                          row_tile: int = 2048, feature_tile: int = 8,
                          interpret: bool = False,
                          highest: bool = False,
                          packed_cols: int = 0) -> jnp.ndarray:
    """[N, F] uint8 bins + per-row slot ids + [K, N] value channels ->
    [n_slots, F, B, K] f32 histograms — every slot's histogram in ONE pass
    over the rows (the multi-leaf step of batched-frontier growth).

    Rows outside every slot should carry slot -1 (matches no one-hot AND
    lets an all-inactive row tile skip its compute body entirely); zero
    value channels keep them harmless either way. Padding rows are
    slot -1. ``packed_cols`` > 0: xb is int32 words (core/binpack.py),
    unpacked here to kernel-native int32 lanes."""
    if packed_cols:
        xb = unpack_words(xb, packed_cols, dtype=jnp.int32)
    n, f = xb.shape
    k = vals.shape[0]
    hi_n = max(1, (num_bins + 15) // 16)

    f_pad = (-f) % feature_tile
    n_pad = (-n) % row_tile
    xb_t = jnp.pad(xb.T, ((0, f_pad), (0, n_pad)))
    if not packed_cols:
        xb_t = xb_t.astype(jnp.uint8)
    slot2 = jnp.minimum(slot.astype(jnp.int32), n_slots - 1)
    slot2 = jnp.pad(slot2, (0, n_pad),
                    constant_values=-1)[None, :]             # [1, N+pad]
    vals = jnp.pad(vals, ((0, 0), (0, n_pad)))
    fp = f + f_pad

    kernel = functools.partial(_hist_slot_kernel, hi_n=hi_n,
                               n_slots=n_slots, highest=highest)
    out = pl.pallas_call(
        kernel,
        grid=(fp // feature_tile, (n + n_pad) // row_tile),
        in_specs=[
            pl.BlockSpec((feature_tile, row_tile), lambda i, r: (i, r)),
            pl.BlockSpec((1, row_tile), lambda i, r: (0, r)),
            pl.BlockSpec((k, row_tile), lambda i, r: (0, r)),
        ],
        out_specs=pl.BlockSpec((k, feature_tile, hi_n, n_slots * 16),
                               lambda i, r: (0, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((k, fp, hi_n, n_slots * 16),
                                       jnp.float32),
        interpret=interpret,
    )(xb_t, slot2, vals)
    # [K, F, Hi, S, 16] -> [S, F, B, K]
    out = out.reshape(k, fp, hi_n, n_slots, 16)
    out = jnp.transpose(out, (3, 1, 2, 4, 0)).reshape(
        n_slots, fp, hi_n * 16, k)
    return out[:, :f, :num_bins]


def build_histogram_frontier_pallas(xb: jnp.ndarray, slot: jnp.ndarray,
                                    vals: jnp.ndarray, num_bins: int,
                                    n_slots: int, row_tile: int = 2048,
                                    feature_tile: int = 8,
                                    interpret: bool = False,
                                    highest: bool = False,
                                    packed_cols: int = 0) -> jnp.ndarray:
    """Frontier-wave entry of the slot kernel: the device path of
    histogram.build_histogram_frontier.

    One frontier wave's histograms — [n_slots, F, B, K] with slot = the
    row's frontier rank (-1 = row in no splitting leaf) — ARE the slot
    kernel's contract, so this is a named alias of build_histogram_slots:
    the digit-factorized MXU contraction with a per-tile slot one-hot as
    the third factor, all-inactive row tiles skipping their compute body.
    Kept as its own entry so the frontier grower's kernel dependency is
    explicit and its tiling defaults can diverge from the batched grower's
    without touching that path."""
    return build_histogram_slots(
        xb, slot, vals, num_bins=num_bins, n_slots=n_slots,
        row_tile=row_tile, feature_tile=feature_tile,
        interpret=interpret, highest=highest, packed_cols=packed_cols)
