"""Pallas TPU histogram kernel — the device analog of the reference's OpenCL
histogram kernels (ocl/histogram256.cl workgroup local-memory design,
gpu_tree_learner.cpp:951-1045).

Why a kernel at all: the XLA one-hot-matmul path (histogram.py) materializes a
[rows, F, B] one-hot tensor per row-chunk in HBM — for HIGGS-scale data that
is hundreds of MB of pure bandwidth per histogram build. Here the one-hot
tile is created and consumed inside VMEM, so HBM traffic is just
xb (N*F bytes) + vals (12N bytes) + the [3, F, B] output.

Design (mirrors the OpenCL kernel's structure, re-mapped to TPU):
- grid = (feature_tiles, row_tiles); the row dimension is the innermost,
  sequential reduction — each feature tile's accumulator block stays resident
  in VMEM across all row tiles (the "workgroup local histogram", without
  atomics because one grid cell owns its bin slice).
- xb arrives feature-major [F, N] so rows ride the 128-wide lane dimension;
  vals arrive [3, N] for the same reason.
- per step: eq[ft, b, c] = (xb[ft, c] == b) built in VMEM, then contracted
  with vals on the MXU: [3, C] x [Ft*B, C]^T -> [3, Ft, B].
- accumulation is f32 (like the GPU learner's single-precision histograms,
  gpu_tree_learner.h:74-78).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from jax.experimental import pallas as pl
try:  # TPU-specific memory spaces; absent on some builds
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None


def _hist_kernel(xb_ref, vals_ref, out_ref, *, num_bins: int):
    """One (feature_tile, row_tile) grid cell.

    xb_ref: [Ft, C] int8 binned values; vals_ref: [3, C] f32
    (grad*mask, hess*mask, mask); out_ref: [3, Ft, B] f32 accumulator.
    """
    r = pl.program_id(1)

    xb = xb_ref[...].astype(jnp.int32)                       # [Ft, C]
    vals = vals_ref[...]                                     # [3, C]
    ft, c = xb.shape
    bins = jax.lax.broadcasted_iota(jnp.int32, (c, num_bins), 1)

    @pl.when(r == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    # one 2-D MXU matmul per feature row keeps every operand in a clean
    # (sublane, lane) layout — no in-kernel reshape across tiled dims
    for j in range(ft):
        eq = (xb[j:j + 1, :].T == bins).astype(jnp.float32)  # [C, B]
        part = jax.lax.dot_general(
            vals, eq, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)             # [3, B]
        out_ref[:, j, :] += part


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "row_tile", "feature_tile",
                                    "interpret"))
def build_histogram_pallas(xb: jnp.ndarray, grad: jnp.ndarray,
                           hess: jnp.ndarray, mask: jnp.ndarray,
                           num_bins: int, row_tile: int = 512,
                           feature_tile: int = 8,
                           interpret: bool = False) -> jnp.ndarray:
    """[N, F] uint8 bins + per-row values -> [F, B, 3] f32 histograms.

    Same contract as histogram.build_histogram. The feature-major transpose
    of ``xb`` is loop-invariant across the splits of one tree, so XLA hoists
    it out of the growth loop.
    """
    n, f = xb.shape
    vals = jnp.stack([grad * mask, hess * mask, mask], axis=0)   # [3, N]

    f_pad = (-f) % feature_tile
    n_pad = (-n) % row_tile
    # NB: uint8, not int8 — bins >= 128 must not wrap negative
    xb_t = jnp.pad(xb.T, ((0, f_pad), (0, n_pad))).astype(jnp.uint8)
    vals = jnp.pad(vals, ((0, 0), (0, n_pad)))   # padded rows carry mask 0
    fp = f + f_pad
    num_f_tiles = fp // feature_tile
    num_r_tiles = (n + n_pad) // row_tile

    kernel = functools.partial(_hist_kernel, num_bins=num_bins)
    out = pl.pallas_call(
        kernel,
        grid=(num_f_tiles, num_r_tiles),
        in_specs=[
            pl.BlockSpec((feature_tile, row_tile),
                         lambda i, r: (i, r)),
            pl.BlockSpec((3, row_tile), lambda i, r: (0, r)),
        ],
        out_specs=pl.BlockSpec((3, feature_tile, num_bins),
                               lambda i, r: (0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((3, fp, num_bins), jnp.float32),
        interpret=interpret,
    )(xb_t, vals)
    return jnp.moveaxis(out, 0, -1)[:f]          # [F, B, 3]
