"""TreeSHAP feature contributions.

Re-implementation of the path-dependent TreeSHAP algorithm (Lundberg &
Lee 2017) matching the reference's ``PredictContrib`` semantics
(src/io/tree.cpp:628-698 TreeSHAP/Extend/Unwind, src/boosting/gbdt.cpp
PredictContrib): output has ``num_features + 1`` columns per class, the last
being the expected value (bias); columns sum to the raw score.

Host-side NumPy recursion for now — contribution queries are an offline
explainability path, not the training hot loop. A vectorized device port is
planned once categorical kernels land.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from .split import MISSING_NAN, MISSING_NONE, MISSING_ZERO

K_ZERO_THRESHOLD = 1e-35


def _children(ht, node: int):
    """Resolve (left, right) child node ids; negative = ~leaf."""
    return int(ht.left_child[node]), int(ht.right_child[node])


def _node_cover(ht, node_or_leaf: int) -> float:
    if node_or_leaf < 0:
        return float(ht.leaf_count[~node_or_leaf])
    return float(ht.internal_count[node_or_leaf])


def _decision_go_left(ht, node: int, x: np.ndarray) -> bool:
    """Raw-value decision (tree.h:212-243 NumericalDecision /
    CategoricalDecision), mirrored from core.tree._raw_go_left."""
    fval = x[ht.split_feature[node]]
    missing_type = int(ht.missing_type[node])
    if ht.is_categorical[node]:
        if np.isnan(fval) or fval < 0 or fval >= ht.cat_bitset.shape[1] * 32:
            return False
        ci = int(fval)
        return bool((int(ht.cat_bitset[node][ci >> 5]) >> (ci & 31)) & 1)
    is_nan = bool(np.isnan(fval))
    if missing_type != MISSING_NAN and is_nan:
        fval = 0.0
        is_nan = False
    if missing_type == MISSING_NAN and is_nan:
        return bool(ht.default_left[node])
    if missing_type == MISSING_ZERO and abs(fval) <= K_ZERO_THRESHOLD:
        return bool(ht.default_left[node])
    return fval <= ht.threshold[node]


class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, feature_index=-1, zero_fraction=0.0, one_fraction=0.0,
                 pweight=0.0):
        self.feature_index = feature_index
        self.zero_fraction = zero_fraction
        self.one_fraction = one_fraction
        self.pweight = pweight

    def copy(self):
        return _PathElement(self.feature_index, self.zero_fraction,
                            self.one_fraction, self.pweight)


def _extend(path: List[_PathElement], unique_depth: int, zero_fraction: float,
            one_fraction: float, feature_index: int) -> None:
    path[unique_depth].feature_index = feature_index
    path[unique_depth].zero_fraction = zero_fraction
    path[unique_depth].one_fraction = one_fraction
    path[unique_depth].pweight = 1.0 if unique_depth == 0 else 0.0
    for i in range(unique_depth - 1, -1, -1):
        path[i + 1].pweight += (one_fraction * path[i].pweight * (i + 1)
                                / (unique_depth + 1))
        path[i].pweight = (zero_fraction * path[i].pweight
                           * (unique_depth - i) / (unique_depth + 1))


def _unwind(path: List[_PathElement], unique_depth: int, path_index: int) -> None:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = (next_one_portion * (unique_depth + 1)
                               / ((i + 1) * one_fraction))
            next_one_portion = tmp - path[i].pweight * zero_fraction * \
                (unique_depth - i) / (unique_depth + 1)
        else:
            path[i].pweight = (path[i].pweight * (unique_depth + 1)
                               / (zero_fraction * (unique_depth - i)))
    for i in range(path_index, unique_depth):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction


def _unwound_path_sum(path: List[_PathElement], unique_depth: int,
                      path_index: int) -> float:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    total = 0.0
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = (next_one_portion * (unique_depth + 1)
                   / ((i + 1) * one_fraction))
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction * \
                ((unique_depth - i) / (unique_depth + 1))
        else:
            total += (path[i].pweight / zero_fraction
                      / ((unique_depth - i) / (unique_depth + 1)))
    return total


def _tree_shap(ht, x: np.ndarray, phi: np.ndarray, node: int,
               unique_depth: int, parent_path: List[_PathElement],
               parent_zero_fraction: float, parent_one_fraction: float,
               parent_feature_index: int) -> None:
    path = [p.copy() for p in parent_path[:unique_depth]] + \
        [_PathElement() for _ in range(64)]
    _extend(path, unique_depth, parent_zero_fraction, parent_one_fraction,
            parent_feature_index)

    if node < 0:  # leaf
        leaf = ~node
        for i in range(1, unique_depth + 1):
            w = _unwound_path_sum(path, unique_depth, i)
            el = path[i]
            phi[el.feature_index] += w * (el.one_fraction - el.zero_fraction) \
                * float(ht.leaf_value[leaf])
        return

    left, right = _children(ht, node)
    hot, cold = (left, right) if _decision_go_left(ht, node, x) else (right, left)
    node_count = _node_cover(ht, node)
    hot_zero_fraction = _node_cover(ht, hot) / node_count
    cold_zero_fraction = _node_cover(ht, cold) / node_count
    incoming_zero_fraction = 1.0
    incoming_one_fraction = 1.0

    # if we have already split on this feature, undo and combine fractions
    split_feat = int(ht.split_feature[node])
    path_index = next((i for i in range(1, unique_depth + 1)
                       if path[i].feature_index == split_feat), 0)
    if path_index > 0:
        incoming_zero_fraction = path[path_index].zero_fraction
        incoming_one_fraction = path[path_index].one_fraction
        _unwind(path, unique_depth, path_index)
        unique_depth -= 1

    _tree_shap(ht, x, phi, hot, unique_depth + 1, path,
               hot_zero_fraction * incoming_zero_fraction,
               incoming_one_fraction, split_feat)
    _tree_shap(ht, x, phi, cold, unique_depth + 1, path,
               cold_zero_fraction * incoming_zero_fraction, 0.0, split_feat)


def tree_expected_value(ht) -> float:
    """Count-weighted mean leaf output (Tree expected value for the bias
    column, gbdt.cpp PredictContrib era)."""
    nl = ht.num_leaves_actual
    counts = np.asarray(ht.leaf_count[:nl], np.float64)
    total = counts.sum()
    if total <= 0:
        return float(ht.leaf_value[0])
    return float(np.dot(counts, np.asarray(ht.leaf_value[:nl], np.float64))
                 / total)


def predict_contrib(impl, X: np.ndarray,
                    num_iteration: Optional[int] = None) -> np.ndarray:
    """SHAP contributions for a boosting model.

    Returns [N, (F+1) * K]: per class, per-feature contributions plus the
    expected-value column; rows sum (per class) to the raw score.
    """
    X = np.asarray(X, np.float64)
    if X.ndim == 1:
        X = X.reshape(1, -1)
    n = X.shape[0]
    k = impl.num_tree_per_iteration
    total_iters = len(impl.models) // max(k, 1)
    use_iters = total_iters if num_iteration is None or num_iteration <= 0 \
        else min(num_iteration, total_iters)
    num_feat = max(
        (int(np.max(t.split_feature[:max(t.num_leaves_actual - 1, 0)]))
         for t in impl.models if t.num_leaves_actual > 1), default=-1) + 1
    if impl.train_data is not None:
        num_feat = impl.train_data.num_total_features
    num_feat = max(num_feat, X.shape[1])

    out = np.zeros((n, k, num_feat + 1), np.float64)
    root_path = [_PathElement() for _ in range(64)]
    for it in range(use_iters):
        for c in range(k):
            ht = impl.models[it * k + c]
            ev = tree_expected_value(ht)
            for r in range(n):
                out[r, c, num_feat] += ev
                if ht.num_leaves_actual > 1:
                    _tree_shap(ht, X[r], out[r, c, :], 0, 0, root_path,
                               1.0, 1.0, -1)
    if impl.average_output and use_iters > 0:
        out /= use_iters
    return out.reshape(n, -1) if k > 1 else out[:, 0, :]
