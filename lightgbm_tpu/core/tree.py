"""Tree prediction on device.

TPU-native re-design of Tree::Predict / GetLeaf (include/LightGBM/tree.h:203-260,
src/boosting/gbdt_prediction.cpp:9-83). Instead of per-row pointer-chasing
node traversal, prediction replays splits in creation order: node ``t`` split
leaf ``split_leaf[t]``, so processing nodes 0..L-2 sequentially moves each row
through exactly the decisions it would make in a traversal — every step is one
vectorized compare over all rows. This mirrors how training's DataPartition
evolves, and maps to the TPU as L-1 fused elementwise passes.

Raw-value prediction uses real thresholds (converted from bin thresholds at
model-extraction time, like Tree::Split storing ``threshold_`` alongside
``threshold_in_bin_``).
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .split import MISSING_NAN, MISSING_NONE, MISSING_ZERO

K_ZERO_THRESHOLD = 1e-35


class PredictTree(NamedTuple):
    """Per-tree arrays needed for replay prediction; stack along axis 0 for a
    whole model ([T, L-1] / [T, L])."""
    split_leaf: jnp.ndarray      # [L-1] int32; -1 = unused node
    split_feature: jnp.ndarray   # [L-1] int32 (real feature index for raw)
    threshold: jnp.ndarray       # [L-1] f32 real threshold (raw predict)
    threshold_bin: jnp.ndarray   # [L-1] int32 (binned predict)
    default_left: jnp.ndarray    # [L-1] bool
    missing_type: jnp.ndarray    # [L-1] int32
    is_categorical: jnp.ndarray  # [L-1] bool
    cat_bitset: jnp.ndarray      # [L-1, 8] uint32
    leaf_value: jnp.ndarray      # [L] f32


def pack_predict_table(ht, max_nodes: int, max_leaves: int,
                       cat_words: Optional[int] = None) -> "PredictTree":
    """Pad a host tree's SoA arrays to model-wide fixed shapes for stacked
    device prediction. ``ht`` is any object with the HostTree field layout
    (boosting.gbdt.HostTree or io.model_text.LoadedTree). ``cat_words``
    widens the categorical bitset so trees with different raw-category
    ranges stack (Tree cat_threshold_ is variable-width, tree.h:276-291)."""
    import numpy as np

    def pad(a, n, fill=0):
        out = np.full((n,) + a.shape[1:], fill, a.dtype)
        out[:len(a)] = a
        return out

    bitset = ht.cat_bitset
    if cat_words is not None and bitset.shape[1] < cat_words:
        bitset = np.pad(bitset, ((0, 0), (0, cat_words - bitset.shape[1])))

    return PredictTree(
        split_leaf=pad(ht.split_leaf, max_nodes, -1),
        split_feature=pad(ht.split_feature, max_nodes),
        threshold=pad(ht.threshold.astype(np.float32), max_nodes),
        threshold_bin=pad(ht.threshold_bin, max_nodes),
        default_left=pad(ht.default_left, max_nodes),
        missing_type=pad(ht.missing_type, max_nodes),
        is_categorical=pad(ht.is_categorical, max_nodes),
        cat_bitset=pad(bitset, max_nodes),
        leaf_value=pad(ht.leaf_value.astype(np.float32), max_leaves),
    )


def decision_go_left(fval: jnp.ndarray, threshold: jnp.ndarray,
                     default_left: jnp.ndarray, missing_type: jnp.ndarray,
                     is_cat: jnp.ndarray, gather_cat_word,
                     max_cat: int) -> jnp.ndarray:
    """Tree::NumericalDecision / CategoricalDecision on raw values
    (tree.h:212-243), shared by the replay path below and the serving
    SoA traversal (serving/traversal.py) so both make bit-identical
    routing decisions. ``gather_cat_word(word_index)`` abstracts the
    bitset lookup — the two callers gather along different axes."""
    is_nan = jnp.isnan(fval)
    # NaN with non-NaN missing handling is treated as 0 (tree.h NumericalDecision)
    fval_safe = jnp.where(is_nan, 0.0, fval)
    is_zero = jnp.abs(fval_safe) <= K_ZERO_THRESHOLD
    use_default = jnp.where(
        missing_type == MISSING_NAN, is_nan,
        jnp.where(missing_type == MISSING_ZERO, is_zero | is_nan, False))
    numerical = jnp.where(use_default, default_left, fval_safe <= threshold)
    cat_i = jnp.clip(fval_safe, 0, max_cat - 1).astype(jnp.int32)
    word = gather_cat_word(cat_i >> 5)
    cat_ok = (~is_nan) & (fval >= 0) & (fval < max_cat)
    categorical = cat_ok & (((word >> (cat_i & 31).astype(jnp.uint32)) & 1) == 1)
    return jnp.where(is_cat, categorical, numerical)


def _raw_go_left(fval: jnp.ndarray, threshold: jnp.ndarray,
                 default_left: jnp.ndarray, missing_type: jnp.ndarray,
                 is_cat: jnp.ndarray, cat_bitset: jnp.ndarray) -> jnp.ndarray:
    """Replay-path decision: one node's ``[W]`` bitset, rows vectorized."""
    max_cat = cat_bitset.shape[0] * 32     # variable-width bitset
    return decision_go_left(fval, threshold, default_left, missing_type,
                            is_cat, lambda wi: cat_bitset[wi], max_cat)


def predict_tree_leaves_raw(tree: PredictTree, x: jnp.ndarray) -> jnp.ndarray:
    """Leaf index per row for raw [N, F] float input (Tree::GetLeaf analog)."""
    n = x.shape[0]
    num_nodes = tree.split_leaf.shape[0]

    def step(t, leaf_id):
        active = tree.split_leaf[t] >= 0
        fval = jnp.take(x, tree.split_feature[t], axis=1)
        go_left = _raw_go_left(fval, tree.threshold[t], tree.default_left[t],
                               tree.missing_type[t], tree.is_categorical[t],
                               tree.cat_bitset[t])
        in_node = leaf_id == tree.split_leaf[t]
        return jnp.where(active & in_node & ~go_left, t + 1, leaf_id)

    return lax.fori_loop(0, num_nodes, step, jnp.zeros((n,), jnp.int32))


def predict_tree_raw(tree: PredictTree, x: jnp.ndarray) -> jnp.ndarray:
    """Per-row tree output for raw input."""
    return tree.leaf_value[predict_tree_leaves_raw(tree, x)]


@functools.partial(jax.jit, static_argnames=())
def predict_forest_raw(trees: PredictTree, x: jnp.ndarray) -> jnp.ndarray:
    """Sum of all tree outputs; ``trees`` fields stacked [T, ...].

    Returns [N] raw scores (single output model). Multiclass callers vmap or
    reshape the tree axis.
    """
    def body(acc, tree):
        return acc + predict_tree_raw(tree, x), None

    init = jnp.zeros((x.shape[0],), jnp.float32)
    out, _ = lax.scan(body, init, trees)
    return out


def predict_forest_leaves_raw(trees: PredictTree, x: jnp.ndarray) -> jnp.ndarray:
    """[N, T] leaf indices (PredictLeafIndex analog, gbdt.cpp:564-583)."""
    def body(_, tree):
        return 0, predict_tree_leaves_raw(tree, x)

    _, leaves = lax.scan(body, 0, trees)
    return leaves.T


def predict_forest_scores(trees: PredictTree, x: jnp.ndarray) -> jnp.ndarray:
    """[N, K] raw scores from trees stacked [iters, K, ...] — the serving
    forward pass (lightgbm_tpu.serving): all K class trees of an iteration
    are applied in one vmapped step, so a whole multiclass model is ONE
    compiled program per batch shape instead of K per-class programs.

    Per-class summation order is iteration order — identical to the
    per-class path GBDT.predict takes, so f32 accumulation matches it
    bit-for-bit.
    """
    n = x.shape[0]
    k = trees.leaf_value.shape[1]

    def body(acc, tree_k):
        delta = jax.vmap(lambda t: predict_tree_raw(t, x))(tree_k)  # [K, N]
        return acc + delta.T, None

    init = jnp.zeros((n, k), jnp.float32)
    out, _ = lax.scan(body, init, trees)
    return out


def predict_forest_early_stop(trees: PredictTree, x: jnp.ndarray,
                              freq: int, margin: float,
                              is_multiclass: bool) -> jnp.ndarray:
    """Forest prediction with margin-based per-row early stop
    (src/boosting/prediction_early_stop.cpp): every ``freq`` iterations rows
    whose margin (binary: 2*|score|; multiclass: top1-top2) exceeds
    ``margin`` stop accumulating further trees.

    ``trees`` fields are stacked [iters, K, ...]; returns [N, K] raw scores.
    The reference stops the per-row tree loop on CPU; here the whole batch
    keeps running but stopped rows freeze — same results, SPMD-friendly.
    """
    n = x.shape[0]
    k = trees.leaf_value.shape[1]

    def margin_of(acc):  # acc [N, K]
        if is_multiclass and k > 1:
            top2 = lax.top_k(acc, 2)[0]
            return top2[:, 0] - top2[:, 1]
        return 2.0 * jnp.abs(acc[:, 0])

    def body(carry, tree_k):
        acc, stopped, it = carry
        delta = jax.vmap(lambda t: predict_tree_raw(t, x))(tree_k)  # [K, N]
        acc = acc + jnp.where(stopped[:, None], 0.0, delta.T)
        it = it + 1
        check_now = (it % freq) == 0
        stopped = stopped | (check_now & (margin_of(acc) >= margin))
        return (acc, stopped, it), None

    init = (jnp.zeros((n, k), jnp.float32), jnp.zeros((n,), bool),
            jnp.asarray(0, jnp.int32))
    (acc, _, _), _ = lax.scan(body, init, trees)
    return acc
