"""In-tile row partition as a permutation one-hot matmul (Pallas TPU).

Phase one of the partition-step mega-kernel plan (docs/Performance.md,
"The path to the north star"): every row tile is stably partitioned —
go-left rows compacted to the front, go-right rows to the back — by
building the [tile, tile] permutation one-hot in-register and letting
the MXU apply it. For byte-packed payloads this is EXACT: each output
element is a single {0,1} x integer<=255 product, so no accumulation
error exists; the per-tile left-counts come back in a side output.

Proven on a v5e chip this round (tools/kernel_lab.py history): ~8.8 ms
per 1M x 128-byte pass, correctness exact. Mosaic constraints honored
here (and worth knowing): no uint8<->bf16 casts (route via int32), no
cumsum (prefix sums are a lower-triangular f32 matvec), no f32 iota
(int iota + cast), no scalar extraction from vectors (keep everything
2D; keepdims reductions), block last-two dims divisible by (8, 128).

The XLA prototype consuming this dataflow is core/grow_batched_part.py;
replacing its ~2.3 GB/s gather-based permutation with this kernel (plus
a cross-tile shift stage of the same matmul form) is the round-5 build.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _partition_tile_kernel(xb_ref, gl_ref, out_ref, cnt_ref):
    xb = xb_ref[...].astype(jnp.int32).astype(jnp.bfloat16)   # [t, C]
    gl2 = gl_ref[...]                                         # [1, t] f32
    t = xb.shape[0]
    iota0 = jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
    iota1 = jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
    # inclusive prefix count of lefts, as a triangular matvec
    ut = jnp.where(iota1 <= iota0, 1.0, 0.0)
    cl2 = jax.lax.dot_general(gl2, ut, (((1,), (1,)), ((), ())),
                              precision=jax.lax.Precision.HIGHEST,
                              preferred_element_type=jnp.float32)  # [1, t]
    nl2 = jnp.sum(gl2, axis=1, keepdims=True)                 # [1, 1]
    ii = jax.lax.broadcasted_iota(jnp.int32, (1, t), 1).astype(jnp.float32)
    pos2 = jnp.where(gl2 > 0, cl2 - 1.0, nl2 + (ii + 1.0) - cl2 - 1.0)
    perm = jnp.where(iota0 == pos2.astype(jnp.int32), 1.0, 0.0) \
        .astype(jnp.bfloat16)                                 # [t_out, t_in]
    out = jax.lax.dot_general(perm, xb, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    out_ref[...] = out.astype(jnp.int32).astype(jnp.uint8)
    cnt_ref[...] = jnp.broadcast_to(nl2, cnt_ref.shape).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("row_tile", "interpret"))
def partition_tiles(rows: jnp.ndarray, go_left: jnp.ndarray,
                    row_tile: int = 512, interpret: bool = False):
    """Stably partition every ``row_tile`` tile of byte-packed rows.

    rows: [N, C] uint8 (N divisible by row_tile, C by 128 — the caller
    pads; pack_rows-style payloads carry bins+values side by side);
    go_left: [N] bool/float. Returns (out_rows [N, C] uint8 with each
    tile's left rows first, left_counts [N // row_tile] int32).
    """
    n, c = rows.shape
    assert n % row_tile == 0, "row count must be tile-aligned"
    assert c % 128 == 0, "payload width must be lane-aligned (pad to 128)"
    t = n // row_tile
    gl = go_left.astype(jnp.float32)[None, :]
    # the count side-output is one scalar per tile, but Mosaic's minimum
    # block is (8, 128) — each tile broadcasts its count over one such
    # block and the [::8, 0] stride reads the scalars back out
    out, cnt = pl.pallas_call(
        _partition_tile_kernel,
        grid=(t,),
        in_specs=[pl.BlockSpec((row_tile, c), lambda r: (r, 0)),
                  pl.BlockSpec((1, row_tile), lambda r: (0, r))],
        out_specs=[pl.BlockSpec((row_tile, c), lambda r: (r, 0)),
                   pl.BlockSpec((8, 128), lambda r: (r, 0))],
        out_shape=[jax.ShapeDtypeStruct((n, c), jnp.uint8),
                   jax.ShapeDtypeStruct((t * 8, 128), jnp.int32)],
        interpret=interpret,
    )(rows, gl)
    return out, cnt[::8, 0]
