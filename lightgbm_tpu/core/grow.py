"""Leaf-wise (best-first) tree growth as a single jit-compiled loop.

TPU-native re-design of SerialTreeLearner::Train
(src/treelearner/serial_tree_learner.cpp:169-233) and Tree::Split
(include/LightGBM/tree.h:393, src/io/tree.cpp:49-67). Differences by design:

- The reference breaks out of the split loop when the best gain <= 0
  (serial_tree_learner.cpp:217-219); under jit the loop runs a fixed
  ``num_leaves - 1`` iterations with *masked no-op* splits instead.
- Single-device growth keeps rows grouped by leaf (core/partition.py) and
  fuses DataPartition::Split with ConstructHistograms: one pass over the
  split leaf's rows partitions the range AND prices both children through
  six value channels — no histogram pool, nothing to subtract. The final
  ``leaf_id`` (reconstructed from the ranges) doubles as the score-update
  fast path (score_updater.hpp:53-117).
- Mesh paths use masked full-data passes with a per-row ``leaf_id`` vector
  and keep the histogram-subtraction trick: only the smaller child's
  histogram is built (serial_tree_learner.cpp:383-397, 547-548); the
  sibling is parent - child. Dead iterations skip work via lax.cond.
- Node numbering matches the reference exactly: splitting leaf ``l`` at step
  ``t`` creates internal node ``t``; the left child keeps leaf index ``l``,
  the right child becomes leaf ``t + 1`` (tree.cpp:49-67). Child pointers use
  the ``~leaf`` encoding (negative = leaf).
- Data-parallel training (data_parallel_tree_learner.cpp:146-245) falls out
  of the same code: when ``axis_name`` is set, histograms and root sums are
  psum-reduced over the mesh axis — the ReduceScatter+best-split-sync dance
  collapses into XLA collectives.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..compat import pcast
from .histogram import build_histogram
from .partition import (RowPartition, hist_for_leaf, init_partition,
                        leaf_id_from_partition, make_row_gather,
                        partition_and_hist, sort_placement_profitable,
                        stack_vals)
from .split import (BestSplit, FeatureMeta, SplitParams, K_EPSILON,
                    K_MIN_SCORE, MISSING_NAN, MISSING_NONE, MISSING_ZERO,
                    calculate_leaf_output, find_best_split, leaf_split_gain,
                    per_feature_split_merged)


class GrowParams(NamedTuple):
    """Static growth hyper-parameters (hashable; part of the jit key)."""
    num_leaves: int
    num_bins: int           # padded bin axis size B
    max_depth: int
    split: SplitParams
    row_chunk: int = 16384
    hist_impl: str = "matmul"
    # histogram accumulation dtype: "f32" (default) or "f64" (gpu_use_dp,
    # config.h:784 — the reference's double-precision histograms; needs
    # jax_enable_x64, enforced by the GBDT driver)
    hist_dtype: str = "f32"
    # PV-Tree voting-parallel (voting_parallel_tree_learner.cpp): each device
    # votes its local top_k features; only the elected <=2*top_k candidates'
    # histograms are globally reduced. 0 = disabled (full reduction).
    voting_top_k: int = 0
    # dataset has categorical features -> run the categorical split finder
    # alongside the numerical one (FindBestThreshold dispatch)
    with_categorical: bool = False
    # row-partition mode (DataPartition analog, core/partition.py): keep rows
    # grouped by leaf and build each histogram only over the leaf's rows —
    # O(N x depth) row visits per tree instead of O(N x num_leaves).
    use_partition: bool = False
    # allow the partition path under an explicit shard_map data-parallel
    # learner: every device partitions its LOCAL row shard (trip counts
    # diverge freely — no collective sits inside the chunk loops) and only
    # the fused [F, B, 6] child histograms are psum-combined, the
    # ReduceScatter moment of data_parallel_tree_learner.cpp:146-161.
    # GSPMD paths must keep this off (a gather through a sharded order
    # array would shuffle rows across devices).
    partition_on_mesh: bool = False
    # EFB (io/bundle.py): histograms are built over stored bundle columns
    # ([C, num_bins]) and expanded to per-feature views ([F, num_feat_bins])
    # before split search; split decisions decode column values through
    # meta.col/offset. num_feat_bins = 0 means "same as num_bins".
    with_efb: bool = False
    num_feat_bins: int = 0
    # joint-coded pair packing: max marginalization width (the largest
    # pack_partner; 1 = no packed columns, expand() stays a pure gather)
    # and the static tuple of packed inner-feature indices
    pack_j: int = 1
    packed_features: tuple = ()
    # word-packed device bin matrix (tpu_bin_packing, core/binpack.py):
    # the REAL stored-column count C when xb arrives as int32 words
    # holding 4 eight-bit codes each ([N, ceil(C/4)]); 0 = xb is the
    # plain [N, C] uint8 matrix. Unpack happens inside each histogram
    # impl and routing gathers codes straight from the words — the
    # unpacked matrix never exists on device. Frontier growth only.
    word_packed_cols: int = 0
    # forced splits (serial_tree_learner.cpp ForceSplits :593-751): the
    # first `num_forced` loop steps split a BFS-predetermined (leaf,
    # feature, threshold) instead of the best-gain candidate
    num_forced: int = 0
    # CEGB (serial_tree_learner.cpp :533-539): per-candidate gain penalties.
    # cegb_split_penalty is tradeoff * cegb_penalty_split (scaled by leaf
    # count at evaluation time); coupled/lazy switches enable the
    # feature-acquisition terms carried in CegbState.
    cegb_split_penalty: float = 0.0
    with_cegb_coupled: bool = False
    with_cegb_lazy: bool = False
    # grow_tree is class-batched under jax.vmap (multiclass, uncapped
    # pool): lax.switch would then run every branch per split, so the
    # sort-placement fast path must stay off
    vmapped_classes: bool = False
    # histogram pool cap (HistogramPool, feature_histogram.hpp:646-820):
    # 0 = one slot per leaf (unlimited); otherwise S < num_leaves slots with
    # LRU eviction, rebuilding an evicted parent histogram from its rows
    # when that leaf is finally chosen for splitting (the Move/Get dance)
    pool_slots: int = 0
    # batched-frontier growth (core/grow_batched.py): split up to this many
    # of the highest-gain frontier leaves per sequential step instead of
    # exactly one. 0 = exact leaf-wise (the reference's semantics)
    batch_splits: int = 0
    # pack active rows to the front each batched step so all-inactive row
    # tiles skip the slot kernel's compute body (tpu_batched_pack; opt-in
    # until measured on chip)
    batched_pack: bool = False
    # partitioned batched growth (core/grow_batched_part.py): rows kept
    # physically grouped by leaf in tile-aligned segments; per-step
    # KERNEL cost tracks the splitting leaves' rows with no slot-one-hot
    # redundancy — but the per-step row permutation (XLA gather) measured
    # slower than the kernel savings on a v5e chip, so this stays opt-in
    # (docs/Performance.md round-4 table)
    batched_part: bool = False
    # frontier-wave growth (core/grow_frontier.py): split EVERY
    # positive-gain frontier leaf per sequential step, with histogram
    # construction batched into one leaf-indexed dataset pass per wave
    # (histogram.build_histogram_frontier) — O(depth) sweeps per tree
    # instead of O(num_leaves). Split selection stays leaf-wise/best-first
    # within each wave (gain-ranked node numbering, like batched growth)
    frontier_mode: bool = False
    # wave-width bucketing (tpu_frontier_bucketing): the frontier
    # while_loop body lax.switches into a wave step specialized at the
    # smallest pow-2 ladder width covering the live positive-gain
    # frontier, so early waves pay 2^w slot-sweeps instead of
    # num_leaves - 1 (lightgbm_tpu.bucketing.wave_width_ladder). Committed
    # splits and numbering are identical to the fixed-width wave. Must
    # stay off under vmapped_classes — vmap lowers switch to
    # execute-all-branches, which would cost MORE than fixed width.
    frontier_bucketing: bool = False
    # frontier data-parallel reduce-scatter schedule (parallel/learners.py
    # DataRSLearner, data_parallel_tree_learner.cpp:146-161): the per-wave
    # histogram psum becomes a tiled psum_scatter over the feature axis,
    # each device scans only its contiguous feature block, and one small
    # all_gather of packed best-split records elects the global winners.
    # Requires stored columns divisible by the mesh axis size (the GBDT
    # driver pads) and no EFB. False = the PR 2 full-psum schedule.
    frontier_rs: bool = False
    # observability health piggy-back (lightgbm_tpu.obs): the frontier
    # wave loop threads a 2-scalar (waves executed, nonfinite committed
    # gain) accumulator through its carry and returns it in the aux slot.
    # The accumulator derives from the gains the wave already computed
    # from its psum'd histograms, so the per-wave collective count is
    # unchanged (pinned by tests/test_obs.py). Off: aux slot stays None
    # and the compiled program is identical to an uninstrumented build.
    obs_health: bool = False
    # model-statistics piggy-back (lightgbm_tpu.obs.modelstats): the
    # frontier wave loop additionally threads an f32[F, 3] per-feature
    # (split count, gain sum, gain max) accumulator through its carry and
    # returns it alongside health in the aux slot. Like obs_health it is
    # scatter-updated from the committed lanes the wave already ranked
    # (zero new sweeps or collectives; psums/wave pinned by
    # tests/test_modelstats.py). Off: the carry leaf stays None and the
    # compiled program is byte-identical to an uninstrumented build.
    obs_modelstats: bool = False


class TreeArrays(NamedTuple):
    """Fixed-capacity SoA tree, mirroring Tree's layout (tree.h:404-517).

    Internal-node arrays have length ``num_leaves - 1``; leaf arrays
    ``num_leaves``. ``split_leaf[t]`` records which leaf node ``t`` split —
    that is what makes sequential partition replay (and thus vectorized
    prediction) possible without pointer chasing.
    """
    split_feature: jnp.ndarray    # [L-1] int32 (inner feature index)
    threshold_bin: jnp.ndarray    # [L-1] int32
    default_left: jnp.ndarray     # [L-1] bool
    missing_type: jnp.ndarray     # [L-1] int32
    is_categorical: jnp.ndarray   # [L-1] bool
    cat_bitset: jnp.ndarray       # [L-1, 8] uint32 (bins going left)
    left_child: jnp.ndarray       # [L-1] int32 (~leaf encoding for leaves)
    right_child: jnp.ndarray      # [L-1] int32
    split_gain: jnp.ndarray       # [L-1] f32
    internal_value: jnp.ndarray   # [L-1] f32 (node output)
    internal_weight: jnp.ndarray  # [L-1] f32 (sum_hess)
    internal_count: jnp.ndarray   # [L-1] f32
    split_leaf: jnp.ndarray       # [L-1] int32
    leaf_value: jnp.ndarray       # [L] f32
    leaf_weight: jnp.ndarray      # [L] f32 (sum_hess)
    leaf_count: jnp.ndarray       # [L] f32
    leaf_parent: jnp.ndarray      # [L] int32 (node index, -1 = root)
    leaf_depth: jnp.ndarray       # [L] int32
    num_leaves: jnp.ndarray       # scalar int32

    @property
    def max_leaves(self) -> int:
        return self.leaf_value.shape[0]


def empty_tree(num_leaves: int, dtype=jnp.float32) -> TreeArrays:
    l = num_leaves
    return TreeArrays(
        split_feature=jnp.zeros((l - 1,), jnp.int32),
        threshold_bin=jnp.zeros((l - 1,), jnp.int32),
        default_left=jnp.zeros((l - 1,), bool),
        missing_type=jnp.zeros((l - 1,), jnp.int32),
        is_categorical=jnp.zeros((l - 1,), bool),
        cat_bitset=jnp.zeros((l - 1, 8), jnp.uint32),
        left_child=jnp.full((l - 1,), -1, jnp.int32),
        right_child=jnp.full((l - 1,), -1, jnp.int32),
        split_gain=jnp.zeros((l - 1,), dtype),
        internal_value=jnp.zeros((l - 1,), dtype),
        internal_weight=jnp.zeros((l - 1,), dtype),
        internal_count=jnp.zeros((l - 1,), dtype),
        split_leaf=jnp.full((l - 1,), -1, jnp.int32),
        leaf_value=jnp.zeros((l,), dtype),
        leaf_weight=jnp.zeros((l,), dtype),
        leaf_count=jnp.zeros((l,), dtype),
        leaf_parent=jnp.full((l,), -1, jnp.int32),
        leaf_depth=jnp.zeros((l,), jnp.int32),
        num_leaves=jnp.asarray(1, jnp.int32),
    )


class ForcedSplits(NamedTuple):
    """BFS-linearized forcedsplits_filename JSON (ForceSplits,
    serial_tree_learner.cpp:593-751). Step ``t < num_forced`` splits
    ``leaf[t]`` on ``feature[t]`` at feature-space bin ``threshold[t]``
    (rows with bin <= threshold go left). The leaf indices are computable
    at setup time because the node numbering is deterministic: step t's
    right child is always leaf t + 1."""
    leaf: jnp.ndarray       # [Q] int32
    feature: jnp.ndarray    # [Q] int32 (inner feature index)
    threshold: jnp.ndarray  # [Q] int32 (feature-space bin)


class CegbState(NamedTuple):
    """Cost-Effective Gradient Boosting acquisition state. Persists across
    trees (a SerialTreeLearner member in the reference, reset only with the
    training data): once a feature is bought, later splits on it are free."""
    coupled_penalty: jnp.ndarray  # [F] f32, tradeoff * penalty_feature_coupled
    lazy_penalty: jnp.ndarray     # [F] f32, tradeoff * penalty_feature_lazy
    feature_used: jnp.ndarray     # [F] bool — any split on f so far
    row_used: jnp.ndarray         # [F, N] uint8 — row paid for f (lazy);
    #                               [F, 0] when lazy penalties are off


class PoolMap(NamedTuple):
    """Slot bookkeeping for the capped histogram pool."""
    slot_of_leaf: jnp.ndarray  # [L] int32, -1 = evicted / never built
    leaf_of_slot: jnp.ndarray  # [S] int32, -1 = free
    last_used: jnp.ndarray     # [S] int32 LRU stamp, -1 = free


class _GrowState(NamedTuple):
    leaf_id: jnp.ndarray      # [N] int32
    hist_pool: jnp.ndarray    # [S, F, B, 3] f32 histogram slots (S = L
    #                           uncapped, or pool_slots under the LRU cap)
    best: BestSplit           # per-leaf best split, fields [L]
    tree: TreeArrays
    leaf_min: jnp.ndarray     # [L] f32 monotone lower output bound
    leaf_max: jnp.ndarray     # [L] f32 monotone upper output bound
    part: Optional[RowPartition]  # row partition (use_partition mode only)
    cegb: Optional[CegbState]     # CEGB acquisition state (None = off)
    force_aborted: jnp.ndarray    # scalar bool — a forced split failed;
    #                               remaining forced steps fall back to
    #                               best-first (aborted_last_force_split)
    pool_map: Optional[PoolMap]   # LRU slot map (None = uncapped)


def _empty_best(num_leaves: int, dtype=jnp.float32) -> BestSplit:
    l = num_leaves
    f32 = lambda: jnp.zeros((l,), dtype)
    return BestSplit(
        gain=jnp.full((l,), K_MIN_SCORE, dtype),
        feature=jnp.zeros((l,), jnp.int32),
        threshold=jnp.zeros((l,), jnp.int32),
        default_left=jnp.zeros((l,), bool),
        left_sum_grad=f32(), left_sum_hess=f32(), left_count=f32(),
        right_sum_grad=f32(), right_sum_hess=f32(), right_count=f32(),
        left_output=f32(), right_output=f32(),
        is_categorical=jnp.zeros((l,), bool),
        cat_bitset=jnp.zeros((l, 8), jnp.uint32),
    )


def _masked_set(arr: jnp.ndarray, idx: jnp.ndarray, val, valid) -> jnp.ndarray:
    return arr.at[idx].set(jnp.where(valid, val, arr[idx]))


def expand_hist(hist, sum_g, sum_h, cnt, meta: FeatureMeta,
                params: "GrowParams", ncols: int) -> jnp.ndarray:
    """[C, B, 3] column histograms -> [F, Bf, 3] per-feature views.

    EFB: each feature's bins are a contiguous slice of its column
    (feature_group.h bin_offsets_). A bundled feature's default bin is
    shared with its bundle-mates, so its entry is rebuilt from leaf
    totals — the Dataset::FixHistogram idea (dataset.h:411-412).
    Joint-coded pair columns: a feature's bin-b entry is the MARGINAL
    over the pair-mate's digit — sum of `pack_partner` joint bins at
    stride pack_div (for the high digit) or pack_mod (low digit).
    """
    b = params.num_bins
    bf = params.num_feat_bins or b
    if not params.with_efb:
        return hist
    flat = hist.reshape(ncols * b, 3)
    bidx = jnp.arange(bf, dtype=jnp.int32)[None, :]          # [1, Bf]
    in_feat = bidx < meta.num_bin[:, None]                   # [F, Bf]
    idx = meta.col[:, None] * b + meta.offset[:, None] + bidx
    out = jnp.take(flat, jnp.clip(idx, 0, ncols * b - 1), axis=0) \
        * in_feat[..., None]
    if params.packed_features:
        # joint-coded pairs: overwrite just the packed features' rows
        # with marginals of their column's joint histogram — a [P, Bf,
        # J] gather-sum over the (static) packed subset, so unpacked
        # features never pay for the marginalization width
        pf = jnp.asarray(params.packed_features, jnp.int32)  # [P]
        jstride = jnp.where(meta.pack_div[pf] > 1, 1,
                            jnp.maximum(meta.pack_mod[pf], 1))
        jj = jnp.arange(params.pack_j, dtype=jnp.int32)[None, None, :]
        bidx_p = jnp.arange(bf, dtype=jnp.int32)[None, :, None]
        idx_p = (meta.col[pf][:, None, None] * b
                 + bidx_p * meta.pack_div[pf][:, None, None]
                 + jj * jstride[:, None, None])              # [P, Bf, J]
        ok = (jj < meta.pack_partner[pf][:, None, None]) \
            & (bidx_p < meta.num_bin[pf][:, None, None])
        out_p = jnp.sum(
            jnp.take(flat, jnp.clip(idx_p, 0, ncols * b - 1), axis=0)
            * ok[..., None], axis=2)                         # [P, Bf, 3]
        out = out.at[pf].set(out_p)
    totals = jnp.stack([sum_g, sum_h, cnt])                  # [3]
    is_def = bidx == meta.default_bin[:, None]               # [F, Bf]
    sum_wo_def = jnp.sum(jnp.where(is_def[..., None], 0.0, out), axis=1)
    rebuilt = totals[None, :] - sum_wo_def                   # [F, 3]
    return jnp.where((is_def & meta.bundled[:, None])[..., None],
                     rebuilt[:, None, :], out)


def decode_bundle_value(v: jnp.ndarray, offset: jnp.ndarray,
                        num_bin: jnp.ndarray,
                        default_bin: jnp.ndarray,
                        pack_div=None, pack_mod=None) -> jnp.ndarray:
    """Stored column value -> the feature's own bin index.

    EFB bundles: a value inside [offset, offset + num_bin) belongs to this
    feature; anything else means some bundle-mate (or the shared zero slot)
    is active, i.e. this feature sits at its default bin (io/bundle.py
    encoding). Joint-coded pair columns (io/dataset.py _pack_small_pairs):
    the feature's bin is a base-`pack_div` digit of the stored value.
    Identity for singleton columns (offset 0, values always in range).
    """
    vv = v.astype(jnp.int32)
    if pack_div is not None:
        packed = pack_mod > 0
        vv = jnp.where(packed,
                       (vv // jnp.maximum(pack_div, 1))
                       % jnp.maximum(pack_mod, 1), vv)
    vv = vv - offset
    return jnp.where((vv >= 0) & (vv < num_bin), vv, default_bin)


def _bin_go_left(col: jnp.ndarray, threshold: jnp.ndarray,
                 default_left: jnp.ndarray, missing_type: jnp.ndarray,
                 num_bin: jnp.ndarray, default_bin: jnp.ndarray,
                 is_cat: jnp.ndarray, cat_bitset: jnp.ndarray) -> jnp.ndarray:
    """Decision in bin space (Tree::NumericalDecisionInner /
    CategoricalDecisionInner, tree.h:212-260).

    One split (cat_bitset [8], scalar split params) or per-row splits
    (cat_bitset [N, 8], every param [N] — batched-frontier routing); the
    missing-value and categorical semantics must stay in exactly one
    place so exact growth, batched growth, and predict cannot diverge.
    ``is_cat=None`` skips the categorical branch entirely (datasets with
    no categorical features — avoids materializing [N, 8] bitset gathers
    in the batched routing pass).
    """
    coli = col.astype(jnp.int32)
    is_missing = jnp.where(
        missing_type == MISSING_NAN, coli == num_bin - 1,
        jnp.where(missing_type == MISSING_ZERO, coli == default_bin, False))
    numerical = jnp.where(is_missing, default_left, coli <= threshold)
    if is_cat is None:
        return numerical
    if cat_bitset.ndim == 1:
        word = cat_bitset[coli >> 5]
    else:
        word = jnp.take_along_axis(cat_bitset, (coli >> 5)[:, None],
                                   axis=1)[:, 0]
    categorical = ((word >> (coli & 31).astype(jnp.uint32)) & 1) == 1
    return jnp.where(is_cat, categorical, numerical)


class FeatureParallelCtx(NamedTuple):
    """Device-varying context for the EXPLICIT feature-parallel learner
    (feature_parallel_tree_learner.cpp:30-60): every device holds the full
    rows, histogram/search work is divided by a bin-balanced column
    assignment, and only best-split STRUCTS cross the mesh.

    xb_local: [N, Cd] this device's stored-column slice (hist build input);
    meta_local: FeatureMeta over the device's features, with ``col``
    pointing into xb_local; global_of_local: [Fd] int32 map back to global
    feature indices (-1 padding carries feature_mask False).
    """
    xb_local: jnp.ndarray
    meta_local: FeatureMeta
    global_of_local: jnp.ndarray


def sync_best_split(bs: BestSplit, axis_name: str) -> BestSplit:
    """SyncUpGlobalBestSplit (parallel_tree_learner.h:186-230) as one
    argmax-allreduce: every rank contributes its local best-split struct,
    the max-gain rank's struct is broadcast to all. Comm volume is
    O(struct fields), never O(F*B)."""
    gains = lax.all_gather(bs.gain, axis_name)          # [D]
    winner = jnp.argmax(gains).astype(jnp.int32)
    mine = lax.axis_index(axis_name) == winner

    def bcast(v):
        if v.dtype == jnp.bool_:
            z = jnp.where(mine, v.astype(jnp.int32), 0)
            return lax.psum(z, axis_name) > 0
        if v.dtype == jnp.uint32:
            # lossless: bitcast to i32 (sum of winner's word + zeros is
            # exact), never a value-cast that truncates the high bit
            z = jnp.where(mine, lax.bitcast_convert_type(v, jnp.int32), 0)
            return lax.bitcast_convert_type(lax.psum(z, axis_name),
                                            jnp.uint32)
        return lax.psum(jnp.where(mine, v, jnp.zeros_like(v)), axis_name)

    return jax.tree.map(bcast, bs)


def propagate_monotone_bounds(mono, left_output, right_output, p_min, p_max):
    """Monotone constraint propagation (serial_tree_learner.cpp:790-847):
    children inherit the parent's output bounds; a monotone split feature
    additionally pins the shared boundary at the midpoint of the two child
    outputs. Returns (l_min, l_max, r_min, r_max). Shared by exact and
    batched growth — the K=1 bit-for-bit parity contract depends on it."""
    mid = (left_output + right_output) * 0.5
    l_min = jnp.where(mono < 0, jnp.maximum(p_min, mid), p_min)
    l_max = jnp.where(mono > 0, jnp.minimum(p_max, mid), p_max)
    r_min = jnp.where(mono > 0, jnp.maximum(p_min, mid), p_min)
    r_max = jnp.where(mono < 0, jnp.minimum(p_max, mid), p_max)
    return l_min, l_max, r_min, r_max


def grow_tree(xb: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
              sample_mask: jnp.ndarray, meta: FeatureMeta,
              feature_mask: jnp.ndarray, params: GrowParams,
              axis_name: Optional[str] = None,
              forced: Optional[ForcedSplits] = None,
              cegb: Optional[CegbState] = None,
              fp: Optional[FeatureParallelCtx] = None,
              ) -> Tuple[TreeArrays, jnp.ndarray, Optional[CegbState]]:
    """Grow one leaf-wise tree; returns (tree, final per-row leaf_id,
    updated CEGB state or None).

    xb [N, F] uint8 binned features; grad/hess [N] f32 (objective-weighted);
    sample_mask [N] f32 bagging inclusion. With ``axis_name`` set, rows are
    assumed sharded over that mesh axis and histograms/root sums are
    psum-reduced (the data-parallel learner's ReduceScatter analog).

    With ``fp`` set (explicit feature-parallel,
    feature_parallel_tree_learner.cpp:30-60): rows are REPLICATED, each
    device builds histograms and searches splits only over its assigned
    columns (fp.xb_local / fp.meta_local), and the per-leaf best split is
    argmax-allreduced as a struct (sync_best_split) — row partitioning is
    then computed locally and identically on every device from the
    replicated xb.
    """
    n, ncols = xb.shape                 # stored columns (== F without EFB)
    f = meta.num_bin.shape[0]           # logical features
    l = params.num_leaves
    b = params.num_bins                 # column-histogram bin axis
    bf = params.num_feat_bins or b      # per-feature bin axis (split search)
    sp = params.split
    # histogram accumulation dtype (f64 = reference gpu_use_dp semantics)
    # lgbm-lint: disable=LGL105 gated gpu_use_dp fallback, f32 default
    hdt = jnp.float64 if params.hist_dtype == "f64" else jnp.float32

    fp_mode = fp is not None and axis_name is not None
    # self-enforcing invariant (not just the GBDT gate): fp mode has no
    # expand/global-histogram machinery for forced splits, CEGB penalties,
    # or voting — silently dropping them would build wrong trees
    assert not fp_mode or params.hist_dtype == "f32", \
        "f64 histograms are not supported on the explicit feature-parallel " \
        "learner (sync_best_split bitcasts f32; use the GSPMD fallback)"
    assert not fp_mode or (forced is None and cegb is None
                           and params.num_forced == 0
                           and params.voting_top_k == 0), \
        "feature-parallel fp mode is incompatible with forced splits / " \
        "CEGB / voting (route through the GSPMD fallback instead)"
    voting = params.voting_top_k > 0 and axis_name is not None and not fp_mode
    use_partition = params.use_partition and not fp_mode and (
        axis_name is None or (params.partition_on_mesh and not voting))
    # histogram source: the device's column slice in fp mode
    xb_hist = fp.xb_local if fp_mode else xb
    ncols_h = xb_hist.shape[1]
    if fp_mode:
        gofl = fp.global_of_local
        fmask_local = jnp.where(
            gofl >= 0, feature_mask[jnp.maximum(gofl, 0)], False)

    def psum(x):
        # fp mode: histograms are per-device partial WORK, not partial
        # sums — nothing to reduce (rows are replicated)
        if fp_mode or axis_name is None:
            return x
        return lax.psum(x, axis_name)

    # CEGB's lazy acquisition accounting reads leaf_id during growth; only
    # then is the per-split leaf_id scatter worth its cost — otherwise the
    # assignment is reconstructed from the final ranges in one dense pass
    maintain_lid = (cegb is not None and params.with_cegb_lazy)

    def hist_for_mask(mask_f32):
        h = build_histogram(xb_hist, grad, hess, mask_f32, num_bins=b,
                            row_chunk=params.row_chunk, impl=params.hist_impl)
        # voting mode keeps histograms LOCAL (the pool then supports local
        # subtraction); only elected candidates are reduced, in voting_best
        return h if voting else psum(h)

    def expand(hist, sum_g, sum_h, cnt):
        return expand_hist(hist, sum_g, sum_h, cnt, meta, params, ncols)

    def cegb_gain_penalty(cegb_state, cnt, leaf_mask):
        """[F] CEGB penalty for one candidate leaf
        (serial_tree_learner.cpp:533-539): split cost scales with leaf
        size; coupled cost applies to never-bought features; lazy cost
        counts the leaf's rows that haven't paid for the feature yet
        (CalculateOndemandCosts, :484-504)."""
        if cegb_state is None:
            return None
        pen = jnp.full((f,), params.cegb_split_penalty * cnt, jnp.float32)
        if params.with_cegb_coupled:
            pen = pen + jnp.where(cegb_state.feature_used, 0.0,
                                  cegb_state.coupled_penalty)
        if params.with_cegb_lazy:
            unpaid = psum(jnp.sum(
                leaf_mask[None, :] * (1.0 - cegb_state.row_used
                                      .astype(jnp.float32)), axis=1))  # [F]
            pen = pen + cegb_state.lazy_penalty * unpaid
        return pen

    def full_best(hist, sum_g, sum_h, cnt, depth_ok, min_c=-jnp.inf,
                  max_c=jnp.inf, gain_penalty=None):
        if fp_mode:
            # local search over this device's columns, then one struct
            # allreduce (SyncUpGlobalBestSplit) — comm O(fields), not O(F*B)
            assert gain_penalty is None, \
                "CEGB gain penalties cannot ride the fp-mode local search"
            bs = find_best_split(hist, fp.meta_local, sp, sum_g, sum_h, cnt,
                                 fmask_local, min_constraint=min_c,
                                 max_constraint=max_c,
                                 with_categorical=params.with_categorical)
            bs = bs._replace(
                feature=jnp.maximum(gofl[bs.feature], 0),
                gain=jnp.where(depth_ok, bs.gain, K_MIN_SCORE))
            return sync_best_split(bs, axis_name)
        bs = find_best_split(expand(hist, sum_g, sum_h, cnt), meta, sp,
                             sum_g, sum_h, cnt,
                             feature_mask, min_constraint=min_c,
                             max_constraint=max_c,
                             with_categorical=params.with_categorical,
                             gain_penalty=gain_penalty)
        return bs._replace(gain=jnp.where(depth_ok, bs.gain, K_MIN_SCORE))

    def voting_best(hist_local, sum_g, sum_h, cnt, depth_ok, min_c=-jnp.inf,
                    max_c=jnp.inf, gain_penalty=None):
        """PV-Tree candidate election (voting_parallel_tree_learner.cpp:
        166-360): rank-local top-k proposals from local-histogram gains, a
        global vote elects <=2*top_k features, and only those features'
        histograms are summed across the mesh (comm O(2k*B) vs O(F*B))."""
        assert gain_penalty is None, \
            "CEGB is not supported with the voting-parallel learner"
        k = min(params.voting_top_k, f)
        k2 = min(2 * params.voting_top_k, f)
        # local leaf totals from the local histogram itself: every local row
        # lands in exactly one bin of feature 0
        lsg = jnp.sum(hist_local[0, :, 0])
        lsh = jnp.sum(hist_local[0, :, 1])
        lsc = jnp.sum(hist_local[0, :, 2])
        pf, _ = per_feature_split_merged(
            hist_local, meta, sp, lsg, lsh, lsc, feature_mask,
            with_categorical=params.with_categorical)
        top_gain, top_idx = lax.top_k(pf.gain, k)
        w = jnp.isfinite(top_gain).astype(jnp.int32)   # only real proposals
        all_idx = lax.all_gather(top_idx, axis_name).reshape(-1)
        all_w = lax.all_gather(w, axis_name).reshape(-1)
        votes = jnp.zeros((f,), jnp.int32).at[all_idx].add(all_w)
        elected = lax.top_k(votes, k2)[1]
        cand = lax.psum(jnp.take(hist_local, elected, axis=0), axis_name)
        gh = jnp.zeros_like(hist_local).at[elected].set(cand)
        cand_mask = jnp.zeros((f,), bool).at[elected].set(True)
        bs = find_best_split(gh, meta, sp, sum_g, sum_h, cnt,
                             feature_mask & cand_mask,
                             min_constraint=min_c, max_constraint=max_c,
                             with_categorical=params.with_categorical)
        return bs._replace(gain=jnp.where(depth_ok, bs.gain, K_MIN_SCORE))

    best_for = voting_best if voting else full_best

    # ---- root ------------------------------------------------------------
    sample_mask = sample_mask.astype(hdt)
    grad = grad.astype(hdt)
    hess = hess.astype(hdt)
    # bins + value channels behind one gather closure: packed single-gather
    # rows on the normal path; two gathers under vmapped class batching,
    # where packing would copy the shared bin matrix per class
    # (make_row_gather docstring)
    gather_rows = (make_row_gather(xb, stack_vals(grad, hess, sample_mask),
                                   packed=not params.vmapped_classes)
                   if use_partition else None)
    root_g = psum(jnp.sum(grad * sample_mask))
    root_h = psum(jnp.sum(hess * sample_mask))
    root_c = psum(jnp.sum(sample_mask))
    hist_root = hist_for_mask(sample_mask)

    tree = empty_tree(l, hdt)
    tree = tree._replace(
        leaf_value=tree.leaf_value.at[0].set(
            calculate_leaf_output(root_g, root_h, sp.lambda_l1, sp.lambda_l2,
                                  sp.max_delta_step)),
        leaf_weight=tree.leaf_weight.at[0].set(root_h),
        leaf_count=tree.leaf_count.at[0].set(root_c))

    root_pen = cegb_gain_penalty(cegb, root_c, sample_mask)
    best0 = best_for(hist_root, root_g, root_h, root_c, True,
                     gain_penalty=root_pen)  # root: depth 0
    best = jax.tree.map(lambda a, v: a.at[0].set(v), _empty_best(l, hdt),
                        best0)

    capped = (0 < params.pool_slots < l) and not use_partition
    assert not (capped and axis_name is not None), \
        "histogram_pool_size cap is not supported on sharded learners " \
        "(rebuild-on-miss cannot psum under lax.cond)"
    assert not capped or params.pool_slots >= 2, \
        "a capped histogram pool needs at least 2 slots (both children " \
        "of a split are resident)"
    # the partition path needs no pool at all: the fused pass prices both
    # children directly, so there is no parent to subtract from, and forced
    # splits rebuild any leaf's histogram from its rows
    num_slots = 1 if use_partition else (params.pool_slots if capped else l)
    hist_pool = jnp.zeros((num_slots, ncols_h, b, 3), hdt)
    if voting:
        # the pool holds LOCAL histograms in voting mode -> device-varying
        hist_pool = pcast(hist_pool, (axis_name,), to="varying")
    if not use_partition:
        hist_pool = hist_pool.at[0].set(hist_root)
    pool_map0 = None
    if capped:
        pool_map0 = PoolMap(
            slot_of_leaf=jnp.full((l,), -1, jnp.int32).at[0].set(0),
            leaf_of_slot=jnp.full((num_slots,), -1, jnp.int32).at[0].set(0),
            last_used=jnp.full((num_slots,), -1, jnp.int32).at[0].set(0))

    def leaf_hist(s: _GrowState, leaf_idx, live=True):
        """A leaf's [C, B, 3] histogram: the pool slot when resident, else
        rebuilt from the leaf's rows (HistogramPool::Get miss path). Must
        run BEFORE the step's partition update — the rebuild walks the
        pre-split row partition / leaf_id."""
        if use_partition:
            # no pool in partition mode (only forced splits land here)
            if axis_name is not None:
                # collectives cannot sit under lax.cond in SPMD code: the
                # rebuild runs straight-line (valid=live zeroes the trip
                # count on dead iterations, so they rebuild 0 rows and
                # psum zeros) — this is what lets forced splits ride the
                # fused sharded partition path at all
                return psum(hist_for_leaf(s.part, leaf_idx, gather_rows,
                                          n, ncols, b,
                                          params.row_chunk, valid=live,
                                          impl=params.hist_impl,
                                          val_dtype=hdt))
            # single device: dead iterations never pay for a rebuild
            return lax.cond(
                live,
                lambda _: hist_for_leaf(s.part, leaf_idx, gather_rows,
                                        n, ncols, b,
                                        params.row_chunk, valid=True,
                                        impl=params.hist_impl,
                                        val_dtype=hdt),
                lambda _: jnp.zeros((ncols_h, b, 3), hdt),
                operand=None)
        if not capped:
            return s.hist_pool[leaf_idx]
        sl = s.pool_map.slot_of_leaf[leaf_idx]

        def read(_):
            return s.hist_pool[jnp.maximum(sl, 0)]

        def rebuild(_):
            m = (s.leaf_id == leaf_idx).astype(hdt) * sample_mask
            return hist_for_mask(m)

        # dead iterations (live=False) never pay for a rebuild
        return lax.cond((sl < 0) & live, rebuild, read, operand=None)

    leaf_id0 = jnp.zeros((n,), jnp.int32)
    if axis_name is not None:
        # under shard_map the carry must be marked device-varying up front:
        # it starts as a constant but becomes a function of the sharded rows
        leaf_id0 = pcast(leaf_id0, (axis_name,), to="varying")
    part0 = init_partition(n, l, params.row_chunk) if use_partition else None
    if part0 is not None and axis_name is not None:
        # same pcast story as leaf_id0: starts constant, becomes a function
        # of the device-local rows
        part0 = jax.tree.map(
            lambda a: pcast(a, (axis_name,), to="varying"), part0)
    state = _GrowState(leaf_id=leaf_id0, hist_pool=hist_pool,
                       best=best, tree=tree,
                       leaf_min=jnp.full((l,), -jnp.inf, hdt),
                       leaf_max=jnp.full((l,), jnp.inf, hdt),
                       part=part0, cegb=cegb,
                       force_aborted=jnp.asarray(False),
                       pool_map=pool_map0)

    def forced_split_info(s: _GrowState, t: jnp.ndarray, in_phase):
        """Evaluate the step-t forced (leaf, feature, threshold) from the
        leaf's pooled histogram — GatherInfoForThresholdNumerical
        (feature_histogram.hpp:284-357). Returns (leaf, BestSplit, ok)."""
        tq = jnp.minimum(t, params.num_forced - 1)
        fleaf = forced.leaf[tq]
        ff = forced.feature[tq]
        fthr = forced.threshold[tq]
        # steps past the forced phase discard this whole evaluation;
        # live=False keeps them from paying a pool-miss rebuild
        ph_col = leaf_hist(s, fleaf, live=in_phase)       # [C, B, 3]
        # exact-enough leaf totals: every row lands in one bin of column 0
        sum_g = jnp.sum(ph_col[0, :, 0])
        sum_h = jnp.sum(ph_col[0, :, 1])
        cnt = jnp.sum(ph_col[0, :, 2])
        row = expand(ph_col, sum_g, sum_h, cnt)[ff]       # [Bf, 3]
        nb = meta.num_bin[ff]
        db = meta.default_bin[ff]
        mt = meta.missing_type[ff]
        bidx = jnp.arange(row.shape[0], dtype=jnp.int32)
        # right side accumulates bins > threshold; the default bin (Zero
        # missing) and the NaN bin fall left by subtraction, exactly like
        # the reference's skip_default_bin / use_na_as_missing loop
        in_right = (bidx > fthr) & (bidx < nb) \
            & ~((mt == MISSING_ZERO) & (bidx == db)) \
            & ~((mt == MISSING_NAN) & (bidx == nb - 1))
        r = jnp.sum(row * in_right[:, None].astype(row.dtype), axis=0)
        rg, rh, rc = r[0], r[1] + K_EPSILON, r[2]
        lg, lh, lc = sum_g - rg, sum_h - rh, cnt - rc
        shift = leaf_split_gain(sum_g, sum_h, sp.lambda_l1, sp.lambda_l2,
                                sp.max_delta_step) + sp.min_gain_to_split
        gain = leaf_split_gain(lg, lh, sp.lambda_l1, sp.lambda_l2,
                               sp.max_delta_step) \
            + leaf_split_gain(rg, rh, sp.lambda_l1, sp.lambda_l2,
                              sp.max_delta_step) - shift
        ok = (gain > 0.0) & (lc > 0) & (rc > 0)
        bs = BestSplit(
            gain=jnp.maximum(gain, 1e-30), feature=ff, threshold=fthr,
            default_left=jnp.asarray(True),
            left_sum_grad=lg, left_sum_hess=lh, left_count=lc,
            right_sum_grad=rg, right_sum_hess=rh, right_count=rc,
            left_output=calculate_leaf_output(
                lg, lh, sp.lambda_l1, sp.lambda_l2, sp.max_delta_step),
            right_output=calculate_leaf_output(
                rg, rh, sp.lambda_l1, sp.lambda_l2, sp.max_delta_step),
            is_categorical=jnp.asarray(False),
            cat_bitset=jnp.zeros((8,), jnp.uint32))
        return fleaf, bs, ok

    def step(t: jnp.ndarray, s: _GrowState,
             with_forced: bool = False) -> _GrowState:
        tree = s.tree
        leaf = jnp.argmax(s.best.gain).astype(jnp.int32)
        cur = jax.tree.map(lambda a: a[leaf], s.best)
        force_aborted = s.force_aborted
        if with_forced:
            # only traced into the first num_forced loop steps (the loop is
            # split at the static phase boundary below), so steps past the
            # forced phase never pay the evaluation or its sharded-rebuild
            # psum; the dynamic mask only covers mid-phase aborts
            in_phase = ~s.force_aborted
            fleaf, fcur, fok = forced_split_info(s, t, in_phase)
            use_forced = in_phase & fok
            force_aborted = s.force_aborted | (in_phase & ~fok)
            leaf = jnp.where(use_forced, fleaf, leaf)
            cur = jax.tree.map(
                lambda fv, bv: jnp.where(use_forced, fv, bv), fcur,
                jax.tree.map(lambda a: a[leaf], s.best))
        valid = cur.gain > 0.0  # reference breaks on gain <= 0 (:217-219)

        # ---- partition rows of `leaf` (DataPartition::Split analog) ------
        right_leaf = t + 1
        if params.with_efb:
            stored_col = meta.col[cur.feature]

            def to_feat_bin(v):
                return decode_bundle_value(
                    v, meta.offset[cur.feature],
                    meta.num_bin[cur.feature],
                    meta.default_bin[cur.feature],
                    pack_div=(meta.pack_div[cur.feature]
                              if meta.pack_div is not None else None),
                    pack_mod=(meta.pack_mod[cur.feature]
                              if meta.pack_mod is not None else None))
        else:
            stored_col = cur.feature

            def to_feat_bin(v):
                return v

        if use_partition:
            def go_left_rows(rows):
                # dynamic-column extract as a one-hot matvec — bin bytes
                # are exact in f32, and a dense [chunk, C] @ [C] product
                # avoids another indexed gather
                onehot_col = (jnp.arange(ncols, dtype=jnp.int32)
                              == stored_col).astype(jnp.float32)
                colv = jnp.einsum("rc,c->r", rows.astype(jnp.float32),
                                  onehot_col).astype(jnp.int32)
                return _bin_go_left(
                    to_feat_bin(colv), cur.threshold, cur.default_left,
                    meta.missing_type[cur.feature],
                    meta.num_bin[cur.feature],
                    meta.default_bin[cur.feature],
                    cur.is_categorical, cur.cat_bitset)

            use_sort = sort_placement_profitable(params.hist_impl,
                                                 params.vmapped_classes)
            part, leaf_id, hist_left_d, hist_right_d = partition_and_hist(
                s.part, s.leaf_id, leaf, right_leaf, go_left_rows, valid,
                params.row_chunk, gather_rows, ncols, b, params.hist_impl,
                maintain_leaf_id=maintain_lid, use_sort=use_sort,
                val_dtype=hdt)
            if axis_name is not None:
                # one collective per split: psum the fused 6-channel
                # accumulator, not the two child views separately
                both = psum(jnp.concatenate([hist_left_d, hist_right_d],
                                            axis=2))
                hist_left_d = both[:, :, :3]
                hist_right_d = both[:, :, 3:]
        else:
            part = s.part
            col = jnp.take(xb, stored_col, axis=1)
            go_left = _bin_go_left(
                to_feat_bin(col), cur.threshold, cur.default_left,
                meta.missing_type[cur.feature], meta.num_bin[cur.feature],
                meta.default_bin[cur.feature], cur.is_categorical,
                cur.cat_bitset)
            in_leaf = s.leaf_id == leaf
            leaf_id = jnp.where(valid & in_leaf & ~go_left, right_leaf,
                                s.leaf_id)

        # ---- tree bookkeeping (Tree::Split, tree.cpp:49-67) --------------
        node = t
        parent_node = tree.leaf_parent[leaf]
        safe_p = jnp.maximum(parent_node, 0)
        p_exists = valid & (parent_node >= 0)
        was_left = tree.left_child[safe_p] == ~leaf
        left_child = _masked_set(tree.left_child, safe_p, node,
                                 p_exists & was_left)
        right_child = _masked_set(tree.right_child, safe_p, node,
                                  p_exists & ~was_left)
        left_child = _masked_set(left_child, node, ~leaf, valid)
        right_child = _masked_set(right_child, node, ~right_leaf, valid)

        depth = tree.leaf_depth[leaf] + 1
        parent_value = calculate_leaf_output(
            cur.left_sum_grad + cur.right_sum_grad,
            cur.left_sum_hess + cur.right_sum_hess,
            sp.lambda_l1, sp.lambda_l2, sp.max_delta_step)

        tree = tree._replace(
            split_feature=_masked_set(tree.split_feature, node, cur.feature, valid),
            threshold_bin=_masked_set(tree.threshold_bin, node, cur.threshold, valid),
            default_left=_masked_set(tree.default_left, node, cur.default_left, valid),
            missing_type=_masked_set(tree.missing_type, node,
                                     meta.missing_type[cur.feature], valid),
            is_categorical=_masked_set(tree.is_categorical, node,
                                       cur.is_categorical, valid),
            cat_bitset=tree.cat_bitset.at[node].set(
                jnp.where(valid, cur.cat_bitset, tree.cat_bitset[node])),
            left_child=left_child, right_child=right_child,
            split_gain=_masked_set(tree.split_gain, node, cur.gain, valid),
            internal_value=_masked_set(tree.internal_value, node, parent_value, valid),
            internal_weight=_masked_set(tree.internal_weight, node,
                                        cur.left_sum_hess + cur.right_sum_hess, valid),
            internal_count=_masked_set(tree.internal_count, node,
                                       cur.left_count + cur.right_count, valid),
            split_leaf=_masked_set(tree.split_leaf, node, leaf, valid),
            leaf_value=_masked_set(
                _masked_set(tree.leaf_value, leaf, cur.left_output, valid),
                right_leaf, cur.right_output, valid),
            leaf_weight=_masked_set(
                _masked_set(tree.leaf_weight, leaf, cur.left_sum_hess, valid),
                right_leaf, cur.right_sum_hess, valid),
            leaf_count=_masked_set(
                _masked_set(tree.leaf_count, leaf, cur.left_count, valid),
                right_leaf, cur.right_count, valid),
            leaf_parent=_masked_set(
                _masked_set(tree.leaf_parent, leaf, node, valid),
                right_leaf, node, valid),
            leaf_depth=_masked_set(
                _masked_set(tree.leaf_depth, leaf, depth, valid),
                right_leaf, depth, valid),
            num_leaves=tree.num_leaves + valid.astype(jnp.int32))

        # ---- histograms: build smaller child, subtract for sibling -------
        left_smaller = cur.left_count <= cur.right_count
        small_leaf = jnp.where(left_smaller, leaf, right_leaf)
        large_leaf = jnp.where(left_smaller, right_leaf, leaf)

        if use_partition:
            # both children came out of the fused partition pass
            hist_small = jnp.where(left_smaller, hist_left_d, hist_right_d)
        elif axis_name is None:
            def live_hist(_):
                m = (leaf_id == small_leaf).astype(hdt) * sample_mask
                return hist_for_mask(m)

            # skip dead iterations entirely (tree stopped growing early)
            hist_small = lax.cond(valid, live_hist,
                                  lambda _: jnp.zeros((ncols_h, b, 3),
                                                      hdt),
                                  operand=None)
        else:
            # collectives can't sit under a cond branch in SPMD code; a dead
            # iteration just psums zeros
            hist_small = hist_for_mask(
                (leaf_id == small_leaf).astype(hdt) * sample_mask
                * valid.astype(hdt))
        if use_partition:
            # no subtraction, no pool: the sibling was priced in the same
            # fused pass
            hist_large = jnp.where(left_smaller, hist_right_d, hist_left_d)
            pool_map = s.pool_map
            hist_pool = s.hist_pool
        elif not capped:
            hist_parent = leaf_hist(s, leaf, live=valid)
            hist_large = hist_parent - hist_small
            pool_map = s.pool_map
            hist_pool = s.hist_pool.at[small_leaf].set(
                jnp.where(valid, hist_small, s.hist_pool[small_leaf]))
            hist_pool = hist_pool.at[large_leaf].set(
                jnp.where(valid, hist_large, hist_pool[large_leaf]))
        else:
            hist_parent = leaf_hist(s, leaf, live=valid)
            hist_large = hist_parent - hist_small
            # LRU slot allocation (HistogramPool::Move/Get): the larger
            # child reuses the parent's slot when resident; the smaller
            # child takes the least-recently-used other slot. Evicted
            # occupants rebuild from rows if ever chosen for splitting.
            pm = s.pool_map
            big = jnp.int32(2 ** 30)
            sl_parent = pm.slot_of_leaf[leaf]
            lru1 = jnp.argmin(pm.last_used).astype(jnp.int32)
            target_large = jnp.where(sl_parent >= 0, sl_parent, lru1)
            target_small = jnp.argmin(
                pm.last_used.at[target_large].set(big)).astype(jnp.int32)
            sol = pm.slot_of_leaf
            for prev in (pm.leaf_of_slot[target_large],
                         pm.leaf_of_slot[target_small]):
                sol = sol.at[jnp.maximum(prev, 0)].set(
                    jnp.where(valid & (prev >= 0), -1,
                              sol[jnp.maximum(prev, 0)]))
            sol = _masked_set(sol, large_leaf, target_large, valid)
            sol = _masked_set(sol, small_leaf, target_small, valid)
            los = _masked_set(pm.leaf_of_slot, target_large, large_leaf,
                              valid)
            los = _masked_set(los, target_small, small_leaf, valid)
            stamp = (t + 1).astype(jnp.int32)
            lu = _masked_set(pm.last_used, target_large, stamp, valid)
            lu = _masked_set(lu, target_small, stamp, valid)
            pool_map = PoolMap(slot_of_leaf=sol, leaf_of_slot=los,
                               last_used=lu)
            hist_pool = s.hist_pool.at[target_large].set(
                jnp.where(valid, hist_large, s.hist_pool[target_large]))
            hist_pool = hist_pool.at[target_small].set(
                jnp.where(valid, hist_small, hist_pool[target_small]))

        # ---- best splits for the two children ----------------------------
        depth_ok = (params.max_depth <= 0) | (depth < params.max_depth)
        hist_left = jnp.where(left_smaller, hist_small, hist_large)
        hist_right = jnp.where(left_smaller, hist_large, hist_small)

        mono = meta.monotone[cur.feature]
        p_min, p_max = s.leaf_min[leaf], s.leaf_max[leaf]
        l_min, l_max, r_min, r_max = propagate_monotone_bounds(
            mono, cur.left_output, cur.right_output, p_min, p_max)
        leaf_min = _masked_set(_masked_set(s.leaf_min, leaf, l_min, valid),
                               right_leaf, r_min, valid)
        leaf_max = _masked_set(_masked_set(s.leaf_max, leaf, l_max, valid),
                               right_leaf, r_max, valid)

        # ---- CEGB acquisition-state update (Split, :757, :766-774) -------
        cegb_state = s.cegb
        if cegb_state is not None:
            fu = jnp.where(valid,
                           cegb_state.feature_used.at[cur.feature].set(True),
                           cegb_state.feature_used)
            ru = cegb_state.row_used
            if params.with_cegb_lazy:
                # only bagged rows pay (the reference marks the rows in the
                # data partition, which holds the bagging subset, :766-774)
                in_split = ((leaf_id == leaf) | (leaf_id == right_leaf)) \
                    & valid & (sample_mask > 0)
                ru = ru.at[cur.feature].max(in_split.astype(ru.dtype))
            cegb_state = cegb_state._replace(feature_used=fu, row_used=ru)

        def child_bests(_):
            lp = rp = None
            if cegb_state is not None:
                lp = cegb_gain_penalty(cegb_state, cur.left_count,
                                       (leaf_id == leaf)
                                       .astype(jnp.float32) * sample_mask)
                rp = cegb_gain_penalty(cegb_state, cur.right_count,
                                       (leaf_id == right_leaf)
                                       .astype(jnp.float32) * sample_mask)
            if voting:
                bl = best_for(hist_left, cur.left_sum_grad,
                              cur.left_sum_hess, cur.left_count, depth_ok,
                              l_min, l_max, gain_penalty=lp)
                br = best_for(hist_right, cur.right_sum_grad,
                              cur.right_sum_hess, cur.right_count, depth_ok,
                              r_min, r_max, gain_penalty=rp)
                return bl, br
            # both children's split searches are independent — one vmapped
            # call instead of two sequential ones halves the small-op chain
            # (the scalar-heavy bin scans dominate per-split latency once
            # histogram building is fused into the partition pass)
            hist2 = jnp.stack([hist_left, hist_right])
            sg2 = jnp.stack([cur.left_sum_grad, cur.right_sum_grad])
            sh2 = jnp.stack([cur.left_sum_hess, cur.right_sum_hess])
            cc2 = jnp.stack([cur.left_count, cur.right_count])
            mn2 = jnp.stack([l_min, r_min])
            mx2 = jnp.stack([l_max, r_max])
            if lp is None:
                b2 = jax.vmap(
                    lambda hh, sg, sh, cc, mn, mx: full_best(
                        hh, sg, sh, cc, depth_ok, mn, mx))(
                    hist2, sg2, sh2, cc2, mn2, mx2)
            else:
                pen2 = jnp.stack([lp, rp])
                b2 = jax.vmap(
                    lambda hh, sg, sh, cc, mn, mx, pen: full_best(
                        hh, sg, sh, cc, depth_ok, mn, mx,
                        gain_penalty=pen))(
                    hist2, sg2, sh2, cc2, mn2, mx2, pen2)
            bl = jax.tree.map(lambda a: a[0], b2)
            br = jax.tree.map(lambda a: a[1], b2)
            return bl, br

        def dead_bests(_):
            dead = jax.tree.map(lambda a: a[0], _empty_best(1, hdt))
            return dead, dead

        if voting or fp_mode or (axis_name is not None
                                 and cegb_state is not None
                                 and params.with_cegb_lazy):
            # voting_best / sync_best_split / the lazy-CEGB unpaid-rows
            # psum hold collectives — they cannot sit under a cond branch;
            # dead iterations just reduce over zeros and are discarded by
            # the masked best-update below
            bl, br = child_bests(None)
        else:
            bl, br = lax.cond(valid, child_bests, dead_bests, operand=None)
        best = jax.tree.map(
            lambda arr, vl, vr: _masked_set(_masked_set(arr, leaf, vl, valid),
                                            right_leaf, vr, valid),
            s.best, bl, br)

        return _GrowState(leaf_id=leaf_id, hist_pool=hist_pool,
                          best=best, tree=tree,
                          leaf_min=leaf_min, leaf_max=leaf_max, part=part,
                          cegb=cegb_state, force_aborted=force_aborted,
                          pool_map=pool_map)

    if params.num_forced > 0 and forced is not None:
        nf = min(params.num_forced, l - 1)
        state = lax.fori_loop(
            0, nf, functools.partial(step, with_forced=True), state)
        state = lax.fori_loop(nf, l - 1, step, state)
    else:
        state = lax.fori_loop(0, l - 1, step, state)
    leaf_id_out = state.leaf_id
    if use_partition and not maintain_lid:
        leaf_id_out = leaf_id_from_partition(state.part, n, l)
    # the model contract is f32 tree arrays regardless of the histogram
    # accumulation dtype (the reference also stores float leaf values)
    tree_out = jax.tree.map(
        # lgbm-lint: disable=LGL105 downcast guard: removes f64, never adds
        lambda a: a.astype(jnp.float32) if a.dtype == jnp.float64 else a,
        state.tree)
    return tree_out, leaf_id_out, state.cegb
