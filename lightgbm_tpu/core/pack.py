"""Device->host tree transport: one int32 buffer per trained tree.

The boosting driver (boosting/gbdt.py) trains asynchronously: each
iteration's TreeArrays stay on device, and host materialization happens in
batched flushes. A naive per-field fetch costs ~20 device->host round trips
per iteration (one per TreeArrays field) — ruinous when the accelerator
sits behind a high-latency transport, and with no analog in the reference,
whose learner and booster share one address space (GBDT::TrainOneIter,
src/boosting/gbdt.cpp:333-412, hands over a Tree* pointer). Packing every
field into a single flat int32 buffer makes a flush of P pending iterations
exactly ONE transfer of a [P, K, T] array.

Encoding: f32 and u32 fields are bitcast (lossless), bools widen to int32.
The spec is ordered and static given ``num_leaves``, so host unpacking is
pure numpy view/reshape — no per-element work.
"""
from __future__ import annotations

from types import SimpleNamespace
from typing import List, Tuple

import numpy as np

import jax.numpy as jnp
from jax import lax

# (field name, kind, shape builder) — kinds: i32 | f32 | u32 | bool.
# Order must match TreeArrays (core/grow.py) field-for-field semantics;
# shapes are functions of num_leaves ``l``.
_FIELDS: List[Tuple[str, str]] = [
    ("split_feature", "i32"),
    ("threshold_bin", "i32"),
    ("default_left", "bool"),
    ("missing_type", "i32"),
    ("is_categorical", "bool"),
    ("cat_bitset", "u32"),
    ("left_child", "i32"),
    ("right_child", "i32"),
    ("split_gain", "f32"),
    ("internal_value", "f32"),
    ("internal_weight", "f32"),
    ("internal_count", "f32"),
    ("split_leaf", "i32"),
    ("leaf_value", "f32"),
    ("leaf_weight", "f32"),
    ("leaf_count", "f32"),
    ("leaf_parent", "i32"),
    ("leaf_depth", "i32"),
    ("num_leaves", "i32"),
]


def _shapes(l: int) -> List[Tuple[int, ...]]:
    per_node = (l - 1,)
    per_leaf = (l,)
    by_name = {
        "cat_bitset": (l - 1, 8),
        "leaf_value": per_leaf, "leaf_weight": per_leaf,
        "leaf_count": per_leaf, "leaf_parent": per_leaf,
        "leaf_depth": per_leaf, "num_leaves": (),
    }
    return [by_name.get(name, per_node) for name, _ in _FIELDS]


def packed_size(l: int) -> int:
    return sum(int(np.prod(s)) if s else 1 for s in _shapes(l))


def pack_trees(trees) -> jnp.ndarray:
    """TreeArrays with a leading class axis [K, ...] -> [K, T] int32.

    Runs inside jit; all ops are bitcasts/casts + one concatenate.
    """
    k = trees.leaf_value.shape[0]
    parts = []
    for name, kind in _FIELDS:
        a = getattr(trees, name).reshape(k, -1)
        if kind in ("f32", "u32"):
            a = lax.bitcast_convert_type(a, jnp.int32)
        else:
            a = a.astype(jnp.int32)
        parts.append(a)
    return jnp.concatenate(parts, axis=1)


def unpack_tree(row: np.ndarray, l: int) -> SimpleNamespace:
    """One packed [T] int32 host row -> namespace of typed numpy arrays.

    The result quacks like a single-tree TreeArrays (same field names and
    shapes), so GBDT._extract_host_tree consumes it unchanged.
    """
    row = np.ascontiguousarray(row, dtype=np.int32)
    out = {}
    off = 0
    for (name, kind), shape in zip(_FIELDS, _shapes(l)):
        size = int(np.prod(shape)) if shape else 1
        seg = row[off:off + size]
        off += size
        if kind == "f32":
            a = seg.view(np.float32)
        elif kind == "u32":
            a = seg.view(np.uint32)
        elif kind == "bool":
            a = seg.astype(bool)
        else:
            a = seg
        out[name] = a.reshape(shape) if shape else a[0]
    return SimpleNamespace(**out)
