"""Frontier-wave tree growth: O(depth) dataset sweeps per tree.

grow_tree (exact) rebuilds ONE leaf's histogram per loop iteration, so a
255-leaf tree pays ~254 serial sweeps over (half of) the dataset — the
dominant cost in the round-5 bench (partition_hist_fused ~86 ms +
hist_leaf_half ~17 ms per split step on CPU). Both GPU GBDT papers in
PAPERS.md (arXiv:1706.08359, arXiv:1806.11248) fix this the same way:
build the histograms of EVERY active node of a level in a single
node-indexed pass over the data. This module is that schedule:

- split selection stays leaf-wise / best-first WITHIN each wave: every
  frontier leaf whose best split has positive gain is committed, ranked
  by gain (rank i -> node nl-1+i, right leaf nl+i — the same numbering
  as grow_batched, and tree.cpp:49-67 when one leaf splits);
- histogram construction is batched per wave: ONE leaf-indexed pass
  (histogram.build_histogram_frontier) produces the [K, F, B, 3] tensor
  for every split's SMALLER child at once, and the larger sibling is
  derived by the subtraction trick from a per-leaf histogram pool that
  survives across waves — so a tree costs O(max leaf depth) ~ 8-12
  dataset sweeps instead of O(num_leaves) ~ 254;
- the sharded path psums the batched [K, F, B, 3] tensor ONCE per wave
  instead of once per leaf.

Routing differs from grow_batched.route_split_rows on purpose: that
helper materializes a [K, N] one-hot so per-STEP routing costs no
per-row gathers — the right trade at K<=32 where the one-hot is cheap
and steps are many. Here K can be num_leaves - 1 (every leaf can
split), so a [K, N] one-hot would be O(L*N) per wave; instead each row
gathers its own split's parameters (~6 per-row gathers per WAVE), which
runs O(depth) times per tree, not O(num_leaves) times.

Wave-width bucketing (GrowParams.frontier_bucketing): wave ``w`` has at
most ``min(2^w, leaf budget)`` positive-gain leaves, but a fixed-width
wave builds the full ``[kb, C, B, 3]`` histogram tensor regardless —
~``depth * kb`` slot-sweeps per tree where ~``num_leaves`` are live.
Both GPU GBDT papers size the node dimension to the actual frontier;
here that is done with compile-time specialization, reusing serving's
pow-2 bucket ladder (lightgbm_tpu.bucketing): the while_loop body counts
the live frontier and ``lax.switch``es into a wave step specialized at
the smallest ladder width covering it, so hist FLOPs and the per-wave
psum payload track ``2^w`` on early waves. Occupancy-weighted
slot-sweeps become ``sum_w bucket(live_w) <= 2 * (num_leaves - 1)``.
Every branch runs the same gain-ranked top_k prefix (stable ties, and
the live set always fits the chosen width), so committed splits, node
numbering, and the hist pool are bit-identical to the fixed-width path.
The branch index derives from psum-replicated gains, so all devices of
a shard_map mesh take the same branch and the per-branch psum is a
uniform collective. The ladder is also clamped by max_depth — a
depth-``d`` tree's frontier never exceeds ``2^(d-1)`` leaves (depth-
capped children are never granted positive gain).

Semantics: splitting every positive-gain frontier leaf is exactly the
set of splits exact best-first performs when the num_leaves cap never
binds (each leaf's best split depends only on its own rows and its
ancestors' monotone bounds), so the grown PARTITION is identical there —
tested in tests/test_grow_frontier.py. Near the cap the wave commits
gain-ranked until the cap, which can differ from fully-serial re-ranking
(same documented approximation as grow_batched at K>1). Forced splits
and CEGB keep the exact path (order-dependent accounting), same as
grow_batched.
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..bucketing import frontier_max_width, wave_width_ladder
from ..compat import pcast
from ..obs.modelstats import init_mstats, update_mstats
from ..parallel.learners import make_frontier_learner
from .binpack import CODES_PER_WORD, words_per_row
from .histogram import build_histogram, build_histogram_frontier
from .grow import (GrowParams, TreeArrays, _bin_go_left, _empty_best,
                   decode_bundle_value, empty_tree, expand_hist)
from .grow_batched import (_drop_set, apply_split_wave, interleave_lr,
                           scatter_child_best)
from .split import (FeatureMeta, K_MIN_SCORE, calculate_leaf_output,
                    find_best_split)


def _xb_sds(n: int, xb_cols: int, xb_dtype, params: GrowParams):
    """ShapeDtypeStruct mirror of the grower's bin-matrix operand:
    int32 packed words when the params say the device matrix is
    word-packed (core/binpack.py), the plain [N, C] matrix otherwise."""
    if params.word_packed_cols:
        return jax.ShapeDtypeStruct(
            (n, words_per_row(params.word_packed_cols)), jnp.int32)
    return jax.ShapeDtypeStruct((n, xb_cols), jnp.dtype(xb_dtype))


def wave_hist_entry(n: int, xb_cols: int, xb_dtype, params: GrowParams,
                    kw: int):
    """The wave's one-dataset-sweep kernel — ``wave_step(kw)``'s
    ``build_histogram_frontier`` call — as a standalone AOT-lowerable
    entry point: returns ``(fn, args, kwargs)`` such that
    ``fn.lower(*args, **kwargs)`` lowers exactly the program a width-
    ``kw`` wave dispatches for its dataset sweep.  Args are
    ``jax.ShapeDtypeStruct`` mirrors (no real arrays are built), so the
    obs cost model and the perf gate price wave buckets through this one
    definition and can never drift from the grower's actual kernel."""
    sds = jax.ShapeDtypeStruct
    args = (_xb_sds(n, xb_cols, xb_dtype, params),
            sds((n,), jnp.int32),          # slot: wave rank or -1
            sds((n,), jnp.float32),        # grad
            sds((n,), jnp.float32),        # hess
            sds((n,), jnp.float32))        # sample mask
    kwargs = dict(num_bins=params.num_bins, num_slots=int(kw),
                  row_chunk=params.row_chunk, impl=params.hist_impl,
                  packed_cols=params.word_packed_cols)
    return build_histogram_frontier, args, kwargs


def derive_child_hists(parent_hist, hist_small, left_small, kw: int):
    """Sibling-subtraction step shared by the wave commit and the fused
    pricing entry: [kw, C, B, 3] smaller-child sweep + pooled parents ->
    the interleaved [2*kw, C, B, 3] (left, right) child tensor."""
    hist_large = parent_hist - hist_small
    ls = left_small[:, None, None, None]
    hist_left = jnp.where(ls, hist_small, hist_large)
    hist_right = jnp.where(ls, hist_large, hist_small)
    ch_hist = jnp.stack([hist_left, hist_right],
                        axis=1).reshape((2 * kw,) + hist_left.shape[1:])
    return hist_left, hist_right, ch_hist


def wave_fused_entry(n: int, xb_cols: int, xb_dtype, meta: FeatureMeta,
                     feature_mask, params: GrowParams, kw: int):
    """The ENTIRE fused wave region — histogram sweep -> sibling
    subtraction -> expand/fix -> 2K-child bin-scan best split — as one
    AOT-lowerable entry: ``(fn, args, kwargs)`` with ShapeDtypeStruct
    args, same contract as :func:`wave_hist_entry`.

    This is the pricing seam of the fused pipeline (serial schedule): it
    composes the same building blocks the wave step runs
    (``build_histogram_frontier``, :func:`derive_child_hists`,
    ``expand_hist`` + ``find_best_split``), so the [kw, C, B, 3] wave
    histogram is an internal value of ONE compiled region — never a
    separate dispatch output — and the per-bucket cost entries
    (``frontier_wave_w*``) price work that genuinely scales with the
    wave width (the bin scan and subtraction are O(kw * C * B), unlike
    the scatter sweep whose update traffic is width-invariant)."""
    ncols = params.word_packed_cols or xb_cols
    sds = jax.ShapeDtypeStruct
    f32 = jnp.float32
    hshape = (kw, ncols, params.num_bins, 3)
    args = (_xb_sds(n, xb_cols, xb_dtype, params),
            sds((n,), jnp.int32),           # slot
            sds((n,), f32), sds((n,), f32), sds((n,), f32),
            sds(hshape, f32),               # pooled parent histograms
            sds((kw,), jnp.bool_),          # left_small
            sds((2 * kw,), f32), sds((2 * kw,), f32),   # child g/h sums
            sds((2 * kw,), f32),            # child counts
            sds((2 * kw,), f32), sds((2 * kw,), f32))   # monotone bounds

    def fused(xb, slot, grad, hess, mask, parent_hist, left_small,
              ch_sg, ch_sh, ch_cnt, ch_min, ch_max):
        hist_small = build_histogram_frontier(
            xb, slot, grad, hess, mask, num_bins=params.num_bins,
            num_slots=kw, row_chunk=params.row_chunk,
            impl=params.hist_impl, packed_cols=params.word_packed_cols)
        _, _, ch_hist = derive_child_hists(parent_hist, hist_small,
                                           left_small, kw)

        def one(hc, sg, sh, cnt, mn, mx):
            return find_best_split(
                expand_hist(hc, sg, sh, cnt, meta, params, ncols),
                meta, params.split, sg, sh, cnt, feature_mask,
                min_constraint=mn, max_constraint=mx,
                with_categorical=params.with_categorical)

        return jax.vmap(one)(ch_hist, ch_sg, ch_sh, ch_cnt, ch_min,
                             ch_max)

    return jax.jit(fused), args, {}


class _FrontierState(NamedTuple):
    leaf_id: jnp.ndarray      # [N] int32
    hist_pool: jnp.ndarray    # [L, C, B, 3] per-leaf histograms
    best: jnp.ndarray         # per-leaf best split, fields [L] (BestSplit)
    tree: TreeArrays
    leaf_min: jnp.ndarray     # [L] f32 monotone lower bound
    leaf_max: jnp.ndarray     # [L] f32 monotone upper bound
    # [2] f32 (waves executed, nonfinite committed gain) when
    # params.obs_health, else None (empty pytree leaf — the carry and the
    # compiled program are unchanged when monitoring is off)
    health: Optional[jnp.ndarray] = None
    # [F, MS_WIDTH] f32 per-feature (split count, gain sum, gain max)
    # when params.obs_modelstats, else None (same empty-leaf contract)
    mstats: Optional[jnp.ndarray] = None


def _gain_anomaly(gain: jnp.ndarray) -> jnp.ndarray:
    """Elementwise "this gain is corrupt": NaN or +inf. -inf is the
    K_MIN_SCORE no-valid-split sentinel and therefore healthy."""
    return jnp.isnan(gain) | (gain == jnp.inf)


def _route_rows_gather(xb, rs, cur, meta, with_efb, with_categorical,
                       packed_cols: int = 0):
    """Per-row go-left decisions for the wave's splits via per-row
    gathers of each row's split descriptor (see module docstring for why
    this is gather-based where route_split_rows is one-hot-based).

    xb: [N, C] row-major bins (int32 packed words when ``packed_cols``);
    rs: [N] clamped per-row split rank; cur: BestSplit fields [K].
    Returns go_left [N] bool (garbage on rows whose leaf is not
    splitting — callers mask with ``active``)."""
    fk = cur.feature[rs]                                     # [N]
    stored_col = (meta.col[fk] if with_efb else fk).astype(jnp.int32)
    if packed_cols:
        # gather the routed column's code straight from the packed words
        # (one per-row word gather + shift/mask — the full unpacked
        # matrix never materializes on the routing path either)
        word = jnp.take_along_axis(
            xb, (stored_col // CODES_PER_WORD)[:, None], axis=1)[:, 0]
        colv = (word >> ((stored_col % CODES_PER_WORD) * 8)) & 0xFF
    else:
        colv = jnp.take_along_axis(
            xb, stored_col[:, None], axis=1)[:, 0].astype(jnp.int32)
    num_bin_r = meta.num_bin[fk]
    default_bin_r = meta.default_bin[fk]
    if with_efb:
        fbin = decode_bundle_value(
            colv, meta.offset[fk], num_bin_r, default_bin_r,
            pack_div=(meta.pack_div[fk]
                      if meta.pack_div is not None else None),
            pack_mod=(meta.pack_mod[fk]
                      if meta.pack_mod is not None else None))
    else:
        fbin = colv
    return _bin_go_left(
        fbin, cur.threshold[rs], cur.default_left[rs],
        meta.missing_type[fk], num_bin_r, default_bin_r,
        (cur.is_categorical[rs] if with_categorical else None),
        (cur.cat_bitset[rs] if with_categorical else None))


def wave_plan(best, nl, kw: int, l: int):
    """Wave bookkeeping that depends only on per-leaf state (no dataset
    access): the gain-ranked top-k frontier, its commit mask, node/leaf
    numbering, the gathered split records, and the leaf->rank map.
    Shared verbatim by the in-memory wave (``wave_step``) and the
    streamed grower (stream/grow_stream.py), which runs it once per wave
    BEFORE touching any chunk."""
    rank = jnp.arange(kw, dtype=jnp.int32)
    gval, gleaf = lax.top_k(best.gain, kw)    # distinct leaves, desc
    # the whole positive-gain frontier splits, gain-ranked; both
    # conditions are prefix masks of the sorted ranks
    valid = (gval > 0.0) & (rank < (l - nl))
    nvalid = jnp.sum(valid.astype(jnp.int32))
    node = (nl - 1) + rank                    # [kw]
    right_leaf = nl + rank                    # [kw]
    cur = jax.tree.map(lambda a: a[gleaf], best)     # fields [kw]
    rank_of_leaf = jnp.full((l,), -1, jnp.int32)
    rank_of_leaf = _drop_set(rank_of_leaf, gleaf, rank, valid)
    return gval, gleaf, valid, nvalid, node, right_leaf, cur, rank_of_leaf


def wave_route(xb, leaf_id, cur, rank_of_leaf, right_leaf, meta,
               with_efb: bool, with_categorical: bool,
               packed_cols: int = 0):
    """Route a batch of rows through their leaf's committed split.
    Works on any row slice whose ``leaf_id`` it is given — the full
    dataset in-memory, one resident chunk when streaming."""
    r_r = rank_of_leaf[leaf_id]               # [N], -1 = not splitting
    active = r_r >= 0
    rs = jnp.maximum(r_r, 0)
    go_left = _route_rows_gather(xb, rs, cur, meta, with_efb,
                                 with_categorical, packed_cols)
    new_leaf_id = jnp.where(active & ~go_left, right_leaf[rs], leaf_id)
    return new_leaf_id, active, rs, go_left


def wave_slots(cur, active, go_left, rs):
    """Histogram slot of every row: its split's rank iff it lands in
    the SMALLER child, else -1 (the larger sibling comes from the pool
    by subtraction, so the sweep touches each splitting row at most
    once)."""
    left_small = cur.left_count <= cur.right_count       # [kw]
    in_small = active & (go_left == left_small[rs])
    slot = jnp.where(in_small, rs, -1)
    return left_small, slot


def wave_commit(s: "_FrontierState", kw: int, l: int, gval, gleaf, valid,
                nvalid, node, right_leaf, cur, left_small, hist_small,
                meta: FeatureMeta, sp, max_depth: int, lrn):
    """Everything after the wave's dataset sweep: sibling derivation from
    the pool, pool update, tree bookkeeping, the 2K-children best-split
    search, and the health/mstats accumulators. ``hist_small`` is the
    learner-reduced [kw, C, B, 3] smaller-child tensor — one sweep
    in-memory, a sum of per-chunk sweeps when streaming (histograms are
    additive, so the commit is identical either way)."""
    parent_hist = s.hist_pool[jnp.where(valid, gleaf, 0)]
    hist_left, hist_right, ch_hist = derive_child_hists(
        parent_hist, hist_small, left_small, kw)

    # pool update: left child reuses the parent's leaf index, right
    # child takes its new leaf; invalid lanes drop
    pool = s.hist_pool
    pool = pool.at[jnp.where(valid, gleaf, l)].set(
        hist_left, mode="drop")
    pool = pool.at[jnp.where(valid, right_leaf, l)].set(
        hist_right, mode="drop")

    # ---- tree bookkeeping for the wave (shared with grow_batched) ---
    (tree, leaf_min, leaf_max, safe_leaf,
     ch_min, ch_max, ch_ok) = apply_split_wave(
        s.tree, s.leaf_min, s.leaf_max, cur, gleaf, node, right_leaf,
        valid, nvalid, meta, sp, max_depth)

    # ---- best splits for all 2K children, one vmapped search --------
    ch_sg = interleave_lr(cur.left_sum_grad, cur.right_sum_grad)
    ch_sh = interleave_lr(cur.left_sum_hess, cur.right_sum_hess)
    ch_cnt = interleave_lr(cur.left_count, cur.right_count)
    b2k = lrn.best_children(ch_hist, ch_sg, ch_sh, ch_cnt,
                            ch_min, ch_max)
    b2k = b2k._replace(gain=jnp.where(ch_ok, b2k.gain, K_MIN_SCORE))
    best = scatter_child_best(s.best, b2k, safe_leaf, right_leaf, valid)

    health = s.health
    if health is not None:
        # committed lanes must be finite (NaN/-inf never pass
        # gval > 0, +inf does); child searches may only return real
        # gains or the -inf sentinel
        bad_gain = jnp.any(~jnp.isfinite(gval) & valid) | \
            jnp.any(_gain_anomaly(b2k.gain))
        health = jnp.stack([health[0] + 1.0,
                            jnp.maximum(health[1],
                                        bad_gain.astype(jnp.float32))])

    mstats = s.mstats
    if mstats is not None:
        # committed lanes' inner feature + ranked gain, values the
        # wave computed anyway — two scatter-adds + a scatter-max,
        # zero new collectives
        mstats = update_mstats(mstats, cur.feature, gval, valid)

    return pool, tree, leaf_min, leaf_max, best, health, mstats


def root_state(hist_root, root_g, root_h, root_c, n: int, l: int, sp,
               lrn, params: GrowParams, feature_mask,
               axis_name: Optional[str]) -> "_FrontierState":
    """Seed the frontier state from the root's (already learner-reduced)
    histogram and psum'd gradient sums — tree arrays, per-leaf best
    records, the histogram pool, and the obs accumulators. Shared by the
    in-memory grower and the streamed one (which sums the root histogram
    over chunks first)."""
    tree = empty_tree(l)
    tree = tree._replace(
        leaf_value=tree.leaf_value.at[0].set(
            calculate_leaf_output(root_g, root_h, sp.lambda_l1, sp.lambda_l2,
                                  sp.max_delta_step)),
        leaf_weight=tree.leaf_weight.at[0].set(root_h),
        leaf_count=tree.leaf_count.at[0].set(root_c))
    best0 = lrn.best_root(hist_root, root_g, root_h, root_c)
    best = jax.tree.map(lambda a, v: a.at[0].set(v), _empty_best(l), best0)

    # per-leaf histogram pool: a frontier leaf's histogram survives from
    # the wave that created it, so the subtraction trick works wave-wide
    # (parent - smaller child = larger child; histogram.cpp:xx Subtract).
    # Shape follows the learner's reduced histogram: full [C, B, 3] on the
    # serial/voting schedules, the device's feature shard under data_rs
    hist_pool = jnp.zeros((l,) + hist_root.shape, jnp.float32)
    if lrn.varying_pool:
        # the pool holds device-varying content (local histograms under
        # voting, per-device feature shards under data_rs)
        hist_pool = pcast(hist_pool, (axis_name,), to="varying")
    hist_pool = hist_pool.at[0].set(hist_root)

    leaf_id0 = jnp.zeros((n,), jnp.int32)
    if axis_name is not None:
        leaf_id0 = pcast(leaf_id0, (axis_name,), to="varying")
    # health accumulator (obs): waves executed + anomalous gain, seeded
    # with the root search's gain — everything below reads values the
    # wave already computed, so no new sweeps or collectives. Anomalous
    # means NaN or +inf: K_MIN_SCORE (-inf) is the legitimate "no valid
    # split" sentinel and must not flag.
    health0 = None
    if params.obs_health:
        health0 = jnp.stack([
            jnp.float32(0.0),
            jnp.any(_gain_anomaly(best0.gain)).astype(jnp.float32)])
    # model-statistics accumulator (obs.modelstats): zeros are correct —
    # EVERY committed split, the root's included, flows through a
    # wave_step commit and scatters there
    mstats0 = (init_mstats(feature_mask.shape[0])
               if params.obs_modelstats else None)
    return _FrontierState(
        leaf_id=leaf_id0, hist_pool=hist_pool, best=best, tree=tree,
        leaf_min=jnp.full((l,), -jnp.inf, jnp.float32),
        leaf_max=jnp.full((l,), jnp.inf, jnp.float32),
        health=health0, mstats=mstats0)


def _frontier_driver(xb: jnp.ndarray, sample_mask: jnp.ndarray,
                     meta: FeatureMeta, feature_mask: jnp.ndarray,
                     params: GrowParams, axis_name: Optional[str]):
    """Shared machinery of the single-class and class-batched frontier
    growers: returns ``(seed, wave_step, ladder, kb)`` where
    ``seed(grad, hess)`` builds the root _FrontierState and
    ``wave_step(s, grad, hess, kw)`` runs one width-``kw`` wave. Both
    take gradients explicitly (not by closure) so the class-batched
    driver can jax.vmap them over the class axis while the ladder
    selection stays OUTSIDE the vmap."""
    n = xb.shape[0]
    ncols = params.word_packed_cols or xb.shape[1]
    l = params.num_leaves
    b = params.num_bins
    sp = params.split
    # max wave width: any frontier leaf can split, but max_depth bounds
    # the frontier at 2^(d-1) leaves — without the clamp a shallow-tree
    # config pays full num_leaves-1 slot-sweeps per wave
    kb = frontier_max_width(l, params.max_depth)
    with_efb = params.with_efb
    packed = params.word_packed_cols
    sample_mask = sample_mask.astype(jnp.float32)

    def psum(x):
        return lax.psum(x, axis_name) if axis_name is not None else x

    def child_best(hist_col, sum_g, sum_h, cnt, min_c, max_c):
        return find_best_split(
            expand_hist(hist_col, sum_g, sum_h, cnt, meta, params, ncols),
            meta, sp, sum_g, sum_h, cnt, feature_mask,
            min_constraint=min_c, max_constraint=max_c,
            with_categorical=params.with_categorical)

    # wave-collective schedule (parallel/learners.py): serial emits the
    # psum/child_best closures verbatim; data_rs reduce-scatters histograms
    # over the feature axis and elects packed best records; voting keeps
    # histograms local and exchanges only vote-elected columns
    lrn = make_frontier_learner(params, axis_name, meta, feature_mask,
                                psum, child_best)

    def seed(grad: jnp.ndarray, hess: jnp.ndarray) -> _FrontierState:
        # ---- root (identical to exact mode) -----------------------------
        root_g = psum(jnp.sum(grad * sample_mask))
        root_h = psum(jnp.sum(hess * sample_mask))
        root_c = psum(jnp.sum(sample_mask))
        hist_root = lrn.reduce(build_histogram(
            xb, grad, hess, sample_mask, num_bins=b,
            row_chunk=params.row_chunk, impl=params.hist_impl,
            packed_cols=packed))
        return root_state(hist_root, root_g, root_h, root_c, n, l, sp,
                          lrn, params, feature_mask, axis_name)

    def wave_step(s: _FrontierState, grad, hess, kw: int) -> _FrontierState:
        """One frontier wave at static width ``kw`` (1 <= kw <= kb). The
        caller guarantees the live positive-gain frontier fits in ``kw``
        lanes, so the top_k prefix it commits — and therefore the grown
        structure and numbering — is identical for every width. A wave
        with NO positive-gain leaf is a perfect no-op (every commit
        scatter drops), which is what lets the class-batched driver run
        finished classes through further waves harmlessly."""
        nl = s.tree.num_leaves                    # dynamic scalar
        (gval, gleaf, valid, nvalid, node, right_leaf, cur,
         rank_of_leaf) = wave_plan(s.best, nl, kw, l)

        # ---- route every row through its leaf's split -------------------
        leaf_id, active, rs, go_left = wave_route(
            xb, s.leaf_id, cur, rank_of_leaf, right_leaf, meta, with_efb,
            params.with_categorical, packed)

        # ---- ONE dataset sweep: smaller child of every split ------------
        # slot = split rank iff the row lands in the SMALLER child of its
        # leaf's split, else -1 (inactive); the larger sibling is derived
        # from the pool by subtraction, so the sweep touches each
        # splitting row at most once and the wave costs one pass total.
        # The sweep, subtraction, expand/fix, and the bin-scan best-split
        # below compile into ONE wave region (wave_fused_entry is the
        # AOT pricing mirror) — the [kw, C, B, 3] tensor is an internal
        # value, never a separate dispatch output.
        left_small, slot = wave_slots(cur, active, go_left, rs)
        hist_small = lrn.reduce(build_histogram_frontier(
            xb, slot, grad, hess, sample_mask, num_bins=b, num_slots=kw,
            row_chunk=params.row_chunk,
            impl=params.hist_impl,
            packed_cols=packed))                   # [kw, C, B, 3]

        (pool, tree, leaf_min, leaf_max, best, health,
         mstats) = wave_commit(
            s, kw, l, gval, gleaf, valid, nvalid, node, right_leaf, cur,
            left_small, hist_small, meta, sp, params.max_depth, lrn)

        return _FrontierState(leaf_id=leaf_id, hist_pool=pool, best=best,
                              tree=tree, leaf_min=leaf_min,
                              leaf_max=leaf_max, health=health,
                              mstats=mstats)

    ladder = wave_width_ladder(l, params.max_depth)  # pow-2 widths, <= kb
    return seed, wave_step, ladder, kb


def grow_tree_frontier(xb: jnp.ndarray, grad: jnp.ndarray,
                       hess: jnp.ndarray, sample_mask: jnp.ndarray,
                       meta: FeatureMeta, feature_mask: jnp.ndarray,
                       params: GrowParams,
                       axis_name: Optional[str] = None,
                       ) -> Tuple[TreeArrays, jnp.ndarray,
                                  Optional[jnp.ndarray]]:
    """Grow one tree in frontier waves: every positive-gain frontier
    leaf splits per sequential step, with ONE batched histogram pass per
    wave. Same contract as grow.grow_tree (minus forced/CEGB); returns
    (tree, final per-row leaf_id, aux). The aux slot is the [2] f32
    health accumulator (waves executed, nonfinite committed gain) when
    ``params.obs_health`` and None otherwise — unless
    ``params.obs_modelstats``, in which case aux is the 2-tuple
    ``(health_or_None, mstats)`` with ``mstats`` the f32[F, MS_WIDTH]
    per-feature (split count, gain sum, gain max) accumulator."""
    l = params.num_leaves
    seed, wave_step, ladder, kb = _frontier_driver(
        xb, sample_mask, meta, feature_mask, params, axis_name)
    state = seed(grad, hess)

    def cond_fn(s: _FrontierState) -> jnp.ndarray:
        return (s.tree.num_leaves < l) & jnp.any(s.best.gain > 0.0)

    if params.frontier_bucketing and len(ladder) > 1:
        # adaptive width: count the live frontier and dispatch the wave
        # step specialized at the smallest covering ladder width. ``live``
        # is replicated across a shard_map mesh (gains derive from psum'd
        # histograms), so every device takes the same branch and the
        # branch-local psum stays a uniform collective. cond_fn guarantees
        # live >= 1; live <= kb always (the frontier is one depth level,
        # bounded by 2^(max_depth-1) and by the nl < l leaf budget), so
        # the chosen width never truncates the live set.
        widths = jnp.asarray(ladder, jnp.int32)
        branches = [lambda s, w=w: wave_step(s, grad, hess, w)
                    for w in ladder]

        def step(s: _FrontierState) -> _FrontierState:
            live = jnp.sum(s.best.gain > 0.0)
            return lax.switch(jnp.sum(live > widths), branches, s)
    else:
        # fixed width (frontier_bucketing=false, or a degenerate ladder):
        # every wave runs at the clamped maximum
        def step(s: _FrontierState) -> _FrontierState:
            return wave_step(s, grad, hess, kb)

    state = lax.while_loop(cond_fn, step, state)
    if params.obs_modelstats:
        return state.tree, state.leaf_id, (state.health, state.mstats)
    return state.tree, state.leaf_id, state.health


def grow_tree_frontier_classes(xb: jnp.ndarray, grad: jnp.ndarray,
                               hess: jnp.ndarray,
                               sample_mask: jnp.ndarray,
                               meta: FeatureMeta,
                               feature_mask: jnp.ndarray,
                               params: GrowParams,
                               ) -> Tuple[TreeArrays, jnp.ndarray,
                                          Optional[jnp.ndarray]]:
    """Class-batched frontier growth with the wave ladder OUTSIDE the
    vmap: grad/hess are [K, N] (one row per class) and all K trees grow
    together, one class-vmapped wave per step.

    The naive ``jax.vmap(grow_tree_frontier)`` forces bucketing off
    because vmapping a ``lax.switch`` on a batched index lowers to
    execute-ALL-branches — every wave would pay the whole ladder. Here
    the while_loop and the switch live at the top level: the branch
    index is the MAX live frontier over classes (an unbatched scalar, so
    the switch stays a real single-branch dispatch) and the chosen
    branch vmaps ``wave_step`` over classes. A class whose frontier is
    exhausted (or whose leaf budget is spent) runs through later waves
    as a structural no-op — wave_plan grants it zero valid lanes and
    every commit write is a drop-mode scatter — so the grown structure
    of every class is identical to its solo unbucketed run; only the
    health wave COUNTER sees the shared schedule (it counts global
    waves, max over classes instead of per-class).

    Serial learner only (the vmapped-multiclass gate never arises on
    sharded schedules — the GBDT driver keeps mesh multiclass on the
    pooled path)."""
    l = params.num_leaves
    seed, wave_step, ladder, kb = _frontier_driver(
        xb, sample_mask, meta, feature_mask, params, axis_name=None)
    states = jax.vmap(seed)(grad, hess)

    def cond_fn(ss: _FrontierState) -> jnp.ndarray:
        return jnp.any((ss.tree.num_leaves < l)
                       & jnp.any(ss.best.gain > 0.0, axis=-1))

    if params.frontier_bucketing and len(ladder) > 1:
        widths = jnp.asarray(ladder, jnp.int32)
        branches = [
            lambda ss, w=w: jax.vmap(
                lambda s, g, h: wave_step(s, g, h, w))(ss, grad, hess)
            for w in ladder]

        def step(ss: _FrontierState) -> _FrontierState:
            # widest live frontier over classes still in budget — an
            # UNBATCHED scalar, so lax.switch dispatches one real branch
            live_c = jnp.sum(ss.best.gain > 0.0, axis=-1)       # [K]
            can = ss.tree.num_leaves < l                        # [K]
            live = jnp.max(jnp.where(can, live_c, 0))
            return lax.switch(jnp.sum(live > widths), branches, ss)
    else:
        def step(ss: _FrontierState) -> _FrontierState:
            return jax.vmap(
                lambda s, g, h: wave_step(s, g, h, kb))(ss, grad, hess)

    states = lax.while_loop(cond_fn, step, states)
    if params.obs_modelstats:
        return states.tree, states.leaf_id, (states.health, states.mstats)
    return states.tree, states.leaf_id, states.health
