"""Jaxpr-level audit primitives (layer 2a of the analyzer).

One implementation of the jaxpr walk the repo used to hand-roll per PR
(the obs psum-count test of PR 5, the costmodel jaxpr-identity test of
PR 6): recursive equation iteration, a stable STRUCTURAL FINGERPRINT of
a traced program (primitive sequence + avals, hashed), the collective
schedule (every psum / all-gather with operand shapes), f64-primitive
and host-callback counts.

Everything here consumes a ``ClosedJaxpr`` from ``jax.make_jaxpr`` —
pure tracing, no compilation — so auditing an entry point can never
recompile or perturb its executing program.  ``jax.ShapeDtypeStruct``
mirrors are accepted anywhere real arrays are, which is how the audit
prices entry points without touching training state (the
obs/costmodel.py extraction discipline).

The sharded-grower entry (``sharded_frontier_fn``) is the 8-virtual-
device construction previously duplicated between obs/perfgate.py and
tests/test_obs.py; both now import it from here.
"""
from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, Iterator, List, Optional, Tuple

# primitive names that are cross-device collectives (operand shapes =
# the per-wave payload the multi-chip roadmap items care about)
COLLECTIVE_PRIMITIVES = {
    "psum", "pmax", "pmin", "pmean", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "reduce_scatter", "psum2", "allreduce",
    "all_reduce",
}
# primitives that call back into the host from compiled code
HOST_CALLBACK_PRIMITIVES = {
    "pure_callback", "io_callback", "debug_callback", "host_callback",
    "outside_call", "infeed", "outfeed", "python_callback",
}


def _sub_jaxprs(eqn) -> Iterator[Any]:
    """Inner jaxprs of a call/control-flow equation (pjit, scan, cond,
    while, shard_map, custom_* ...), wherever they hide in params."""
    for val in eqn.params.values():
        for item in (val if isinstance(val, (list, tuple)) else [val]):
            jaxpr = getattr(item, "jaxpr", None)
            if jaxpr is not None and hasattr(jaxpr, "eqns"):
                yield jaxpr                     # ClosedJaxpr -> Jaxpr
            elif hasattr(item, "eqns"):
                yield item                      # bare Jaxpr


def iter_eqns(jaxpr) -> Iterator[Any]:
    """Depth-first iteration over every equation, recursing into
    sub-jaxprs (scan bodies, cond branches, shard_map shards...)."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)      # accept ClosedJaxpr
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub)


def _aval_sig(var) -> str:
    aval = getattr(var, "aval", None)
    if aval is None:
        return "?"
    shape = getattr(aval, "shape", ())
    dtype = getattr(aval, "dtype", "?")
    return "%s[%s]" % (dtype, ",".join(map(str, shape)))


def primitive_sequence(jaxpr) -> List[str]:
    """The flattened primitive-name sequence of a traced program — the
    raw material of the structural fingerprint."""
    return [eqn.primitive.name for eqn in iter_eqns(jaxpr)]


def structural_fingerprint(jaxpr) -> str:
    """Stable hash of a program's STRUCTURE: the depth-first primitive
    sequence plus each equation's output avals and the program's
    input/output avals.  Two programs with the same fingerprint execute
    the same primitive schedule on the same shapes — "byte-identical
    grower" as one comparison.  Parameters (branch indices, donated
    buffers, compiler options) are deliberately NOT hashed: they either
    show up as structure or are execution details."""
    h = hashlib.sha256()
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    h.update(",".join(_aval_sig(v) for v in inner.invars).encode())
    h.update(b"|")
    h.update(",".join(_aval_sig(v) for v in inner.outvars).encode())
    for eqn in iter_eqns(jaxpr):
        h.update(eqn.primitive.name.encode())
        h.update(b"(")
        h.update(",".join(_aval_sig(v) for v in eqn.outvars).encode())
        h.update(b");")
    return h.hexdigest()


def collective_schedule(jaxpr) -> List[Dict[str, Any]]:
    """Every collective equation in program order with operand shapes —
    the audit's "exactly one psum per wave, of exactly this payload"
    invariant.  Returns ``[{"primitive", "operands"}, ...]``."""
    out: List[Dict[str, Any]] = []
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name in COLLECTIVE_PRIMITIVES:
            out.append({
                "primitive": eqn.primitive.name,
                "operands": [_aval_sig(v) for v in eqn.invars],
            })
    return out


def count_collectives(jaxpr) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for entry in collective_schedule(jaxpr):
        counts[entry["primitive"]] = counts.get(entry["primitive"], 0) + 1
    return counts


def count_f64_eqns(jaxpr) -> int:
    """Equations producing a float64 output — must be zero everywhere on
    the f32-only frontier path."""
    n = 0
    for eqn in iter_eqns(jaxpr):
        for v in eqn.outvars:
            dtype = getattr(getattr(v, "aval", None), "dtype", None)
            if dtype is not None and str(dtype) == "float64":
                n += 1
                break
    return n


def host_callback_primitives(jaxpr) -> List[str]:
    """Host-callback equations in the program (must be empty in hot
    paths — a callback serializes the dispatch pipeline)."""
    return [eqn.primitive.name for eqn in iter_eqns(jaxpr)
            if eqn.primitive.name in HOST_CALLBACK_PRIMITIVES
            or "callback" in eqn.primitive.name]


def audit_jaxpr(jaxpr) -> Dict[str, Any]:
    """The full invariant record of one traced entry point, as stored in
    ANALYSIS_BASELINE.json."""
    sched = collective_schedule(jaxpr)
    counts = count_collectives(jaxpr)
    return {
        "fingerprint": structural_fingerprint(jaxpr),
        "num_eqns": len(primitive_sequence(jaxpr)),
        "psums": counts.get("psum", 0),
        "all_gathers": counts.get("all_gather", 0),
        "collectives": sum(counts.values()),
        "collective_schedule": sched,
        "f64_eqns": count_f64_eqns(jaxpr),
        "host_callbacks": host_callback_primitives(jaxpr),
    }


# ------------------------------------------------------------ shared entry
def sharded_frontier_fn(num_devices: int = 8,
                        param_overrides: Optional[Dict[str, Any]] = None,
                        num_features: int = 4):
    """The canonical sharded frontier-grower entry: ``(fn, args,
    params)`` such that ``jax.make_jaxpr(fn)(*args)`` is the
    8-virtual-device shard_map program whose per-wave psum count
    obs/perfgate.py gates, the audit baseline records, and
    tests/test_obs.py pins.  One construction, three consumers.
    ``param_overrides`` lets invariance tests toggle GrowParams fields
    (``obs_health``) on the otherwise-identical program.
    ``num_features`` widens the feature axis (default 4, the historical
    shape — baselines keyed on it must not drift); the reduce-scatter
    learner needs it divisible by ``num_devices``.

    Returns None when fewer than ``num_devices`` devices exist (the
    analyze/perf-gate CLIs re-exec with a virtual-device flag to
    guarantee them)."""
    import jax
    if len(jax.devices()) < num_devices:
        return None
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from ..compat import shard_map
    from ..core.grow import GrowParams
    from ..core.grow_frontier import grow_tree_frontier
    from ..core.split import FeatureMeta, SplitParams

    r = np.random.RandomState(0)
    n, f, b = 256, int(num_features), 16
    xb = r.randint(0, b, (n, f)).astype(np.uint8)
    g = r.randn(n).astype(np.float32)
    ones = np.ones(n, np.float32)
    meta = FeatureMeta(
        num_bin=jnp.full((f,), b, jnp.int32),
        missing_type=jnp.zeros((f,), jnp.int32),
        default_bin=jnp.zeros((f,), jnp.int32),
        is_categorical=jnp.zeros((f,), bool),
        penalty=jnp.ones((f,), jnp.float32),
        monotone=jnp.zeros((f,), jnp.int32))
    sp = SplitParams(lambda_l1=0.0, lambda_l2=0.0, max_delta_step=0.0,
                     min_data_in_leaf=5, min_sum_hessian_in_leaf=1e-3,
                     min_gain_to_split=0.0, max_cat_threshold=32,
                     cat_smooth=10.0, cat_l2=10.0, max_cat_to_onehot=4,
                     min_data_per_group=100)
    params = GrowParams(num_leaves=7, num_bins=b, max_depth=3, split=sp,
                        row_chunk=16384, hist_impl="scatter",
                        **(param_overrides or {}))
    fmask = jnp.ones((f,), bool)
    mesh = Mesh(np.asarray(jax.devices()[:num_devices]), ("data",))

    def inner(xbj, gj, hj, mj):
        return grow_tree_frontier(xbj, gj, hj, mj, meta, fmask, params,
                                  axis_name="data")

    shapes = jax.eval_shape(
        lambda: grow_tree_frontier(jnp.asarray(xb), jnp.asarray(g),
                                   jnp.asarray(ones), jnp.asarray(ones),
                                   meta, fmask, params))
    out_specs = jax.tree.map(lambda _: P(), shapes)
    # only the per-row leaf ids stay sharded
    out_specs = (out_specs[0], P("data"), out_specs[2])
    fn = shard_map(inner, mesh=mesh, in_specs=(P("data"),) * 4,
                   out_specs=out_specs)
    return fn, (xb, g, ones, ones), params


def streamed_sharded_fn(num_devices: int = 8,
                        param_overrides: Optional[Dict[str, Any]] = None,
                        num_features: int = 16):
    """The chunks-x-chips entry: ``(fn, args, params)`` such that
    ``jax.make_jaxpr(fn)(*args)`` traces ONE full growth wave of the
    mesh-mode StreamFrontierGrower — the host-dispatched sequence
    ``wave_begin`` (psum'd continue flag) -> ``chunk_wave`` (no
    collectives) -> ``chunk_wave_commit`` (the learner schedule fused
    into the last chunk).  Its collective count/payload is the per-wave
    comm contract of distributed out-of-core training that
    obs/perfgate.py gates and the audit baseline records: one int32
    psum (the flag) plus exactly the in-memory learner's schedule, so
    the f32 payload must EQUAL the ``wave_payload_f32_*`` pins.

    ``param_overrides`` picks the learner (``frontier_rs`` /
    ``voting_top_k``), as with ``sharded_frontier_fn``.  Args are
    ``ShapeDtypeStruct`` mirrors — tracing only, nothing executes.
    Returns None when fewer than ``num_devices`` devices exist."""
    import jax
    if len(jax.devices()) < num_devices:
        return None
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from ..core.grow import GrowParams
    from ..core.split import FeatureMeta, SplitParams
    from ..parallel.mesh import DATA_AXIS
    from ..stream.grow_stream import StreamFrontierGrower
    from ..stream.pipeline import ShardedChunkPipeline

    r = np.random.RandomState(0)
    world, chunk_rows, f, b = int(num_devices), 32, int(num_features), 16
    rows = 2 * chunk_rows                   # 2 uniform chunks per shard
    shard_chunks = [[r.randint(0, b, (rows, f)).astype(np.uint8)]
                    for _ in range(world)]
    mesh = Mesh(np.asarray(jax.devices()[:world]), (DATA_AXIS,))
    pipe = ShardedChunkPipeline(shard_chunks, [rows] * world, chunk_rows,
                                mesh)
    meta = FeatureMeta(
        num_bin=jnp.full((f,), b, jnp.int32),
        missing_type=jnp.zeros((f,), jnp.int32),
        default_bin=jnp.zeros((f,), jnp.int32),
        is_categorical=jnp.zeros((f,), bool),
        penalty=jnp.ones((f,), jnp.float32),
        monotone=jnp.zeros((f,), jnp.int32))
    sp = SplitParams(lambda_l1=0.0, lambda_l2=0.0, max_delta_step=0.0,
                     min_data_in_leaf=5, min_sum_hessian_in_leaf=1e-3,
                     min_gain_to_split=0.0, max_cat_threshold=32,
                     cat_smooth=10.0, cat_l2=10.0, max_cat_to_onehot=4,
                     min_data_per_group=100)
    params = GrowParams(num_leaves=7, num_bins=b, max_depth=3, split=sp,
                        row_chunk=16384, hist_impl="scatter",
                        **(param_overrides or {}))
    grower = StreamFrontierGrower(pipe, meta, params, mesh=mesh)
    fns = grower._audit_fns

    n = pipe.num_padded
    sds = jax.ShapeDtypeStruct
    scal = sds((), jnp.float32)
    fmask = sds((f,), jnp.bool_)
    acc0 = sds((world,) + grower._hist_shape, jnp.float32)
    state = jax.eval_shape(fns["root_commit"], acc0, scal, scal, scal,
                           fmask)
    xb_c = sds((world * chunk_rows, pipe.num_cols), jnp.uint8)
    row = sds((n,), jnp.float32)
    hist_acc = sds((world, grower.wave_width) + grower._hist_shape,
                   jnp.float32)

    def one_wave(state, xb_c, grad, hess, mask, hist_acc, fmask):
        do, plan = fns["wave_begin"](state.best, state.tree.num_leaves)
        leaf_id, hist_acc = fns["chunk_wave"](
            xb_c, np.int32(0), state.leaf_id, grad, hess, mask, plan,
            hist_acc)
        state = fns["chunk_wave_commit"](
            xb_c, np.int32(chunk_rows), state, leaf_id, grad, hess, mask,
            plan, hist_acc, fmask)
        return do, state

    return one_wave, (state, xb_c, row, row, row, hist_acc, fmask), params


def schedule_signature(schedule: List[Dict[str, Any]]) -> str:
    """Canonical string form of a collective schedule (baseline diffs)."""
    return json.dumps(schedule, sort_keys=True)
