"""Static analysis: JAX-aware source lint + compiled-program audit.

Two layers, one exit-code contract (tools/analyze.py):

- ``astlint``: an AST pass over the package source with JAX-specific
  rules — tracer-unsafe Python inside jit-traced functions, host syncs,
  weak-dtype array construction (the recompile class PR 4 fixed by
  hand), f64-producing constructs on the device path, module-global
  mutation under trace, and config-parameter reads the config table does
  not declare.  Findings carry a rule ID, severity and a
  ``# lgbm-lint: disable=RULE`` suppression channel.

- ``jaxpr_audit`` / ``hlo_audit``: programmatic auditors that lower the
  REAL entry points (fused train block, every ``wave_step(kw)`` ladder
  bucket, serving predict buckets, materialize, the sharded grower under
  the 8-virtual-device mesh) and verify invariants against the committed
  ``ANALYSIS_BASELINE.json``: collective schedule (exact psum /
  all-gather count and operand shapes per entry), zero f64 primitives,
  no host callbacks in hot paths, donation effectiveness (declared
  donated args really input-output aliased in the compiled executable),
  and jaxpr structural fingerprints — "byte-identical grower" as a
  one-line gate instead of a bespoke test per PR.

Auditing is PULL-only: tracing/AOT lowering shares nothing with the
executing programs (the discipline established by obs/costmodel.py), so
an audit run never recompiles or perturbs training/serving executables.
"""
from .astlint import (Finding, LINT_RULES, lint_package, lint_paths,
                      lint_source)
from .jaxpr_audit import (collective_schedule, count_f64_eqns,
                          host_callback_primitives, iter_eqns,
                          primitive_sequence, structural_fingerprint)

__all__ = [
    "Finding", "LINT_RULES", "lint_source", "lint_paths", "lint_package",
    "iter_eqns", "primitive_sequence", "structural_fingerprint",
    "collective_schedule", "count_f64_eqns", "host_callback_primitives",
]
