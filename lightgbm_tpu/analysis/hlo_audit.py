"""HLO-level audit (layer 2b of the analyzer).

What tracing cannot see, the compiled executable can: whether declared
buffer donations were actually honored by XLA (the
``input_output_alias`` table in the HLO module header — a donation XLA
silently drops turns the scores/bag-mask rebinding into a full copy per
block), f64 types that appear only after lowering, and host custom-calls
hiding in compiled code.

Everything here consumes an AOT artifact from
``fn.lower(*ShapeDtypeStruct_mirrors).compile()`` — the obs/costmodel.py
extraction discipline: AOT lowering shares no cache with the executing
programs, so an audit run never recompiles or perturbs training or
serving executables.
"""
from __future__ import annotations

import re
import warnings
from typing import Any, Dict, List, Sequence, Tuple

# one entry of the HLO header's input_output_alias table:
#   { {0}: (3, {}, may-alias), {1}: (8, {}, must-alias) }
# reads "output tuple index {0} aliases parameter 3".
_ALIAS_ENTRY_RE = re.compile(
    r"\{([\d,\s]*)\}:\s*\((\d+),\s*\{[^}]*\}(?:,\s*([a-z-]+))?\)")


def hlo_text(compiled: Any) -> str:
    """The HLO text of a compiled executable (AOT ``.compile()`` result
    or anything exposing ``as_text()``)."""
    if hasattr(compiled, "as_text"):
        return compiled.as_text()
    return str(compiled)


def input_output_aliases(text: str) -> List[Dict[str, Any]]:
    """Parse the ``input_output_alias={...}`` table from an HLO module
    header.  Returns ``[{"output_index", "param_number", "kind"}, ...]``
    — empty when the module declares no aliasing (i.e. every donation
    was dropped)."""
    start = text.find("input_output_alias={")
    if start < 0:
        return []
    i = text.index("{", start)
    depth, j = 0, i
    while j < len(text):
        if text[j] == "{":
            depth += 1
        elif text[j] == "}":
            depth -= 1
            if depth == 0:
                break
        j += 1
    seg = text[i:j + 1]
    out: List[Dict[str, Any]] = []
    for m in _ALIAS_ENTRY_RE.finditer(seg):
        idx = [int(x) for x in m.group(1).replace(",", " ").split()]
        out.append({"output_index": idx,
                    "param_number": int(m.group(2)),
                    "kind": m.group(3) or "may-alias"})
    return out


def flat_param_ranges(args: Sequence[Any]) -> List[Tuple[int, int]]:
    """Per-python-argument ``[start, end)`` ranges into the flattened
    HLO parameter list — how ``donate_argnums`` positions map onto the
    ``param_number`` column of the alias table."""
    import jax
    ranges: List[Tuple[int, int]] = []
    off = 0
    for a in args:
        n = len(jax.tree_util.tree_leaves(a))
        ranges.append((off, off + n))
        off += n
    return ranges


def audit_donation(fn: Any, args: Sequence[Any],
                   donate_argnums: Sequence[int]) -> Dict[str, Any]:
    """Lower ``fn`` AOT with ``donate_argnums`` and verify every donated
    leaf is input-output aliased in the compiled executable.

    ``args`` are ShapeDtypeStruct mirrors of the real call (use
    ``Booster.train_block_sds``), so the audited program has the exact
    signature of the dispatched one.  Donation is forced here even on
    backends where the executing jit gates it off (CPU) — XLA records
    the alias table regardless, which is what makes the check portable
    to the TPU-less CI host.

    Lowering uses ``keep_unused=True``: without it jit drops dead
    argument leaves (a disabled bagging path's keys, for instance) and
    the HLO parameter numbering no longer matches the flattened python
    signature the donation indices are defined against.
    """
    import jax
    with warnings.catch_warnings():
        # CPU backends warn that donation is unimplemented; the alias
        # TABLE is still recorded, which is all the audit reads
        warnings.simplefilter("ignore")
        jitted = jax.jit(fn, donate_argnums=tuple(donate_argnums),
                         keep_unused=True)
        compiled = jitted.lower(*args).compile()
    text = hlo_text(compiled)
    aliases = input_output_aliases(text)
    aliased_params = {a["param_number"] for a in aliases}
    ranges = flat_param_ranges(args)
    donated_params: List[int] = []
    for argnum in donate_argnums:
        lo, hi = ranges[argnum]
        donated_params.extend(range(lo, hi))
    missing = sorted(set(donated_params) - aliased_params)
    return {
        "donate_argnums": list(donate_argnums),
        "donated_params": donated_params,
        "aliased_params": sorted(aliased_params),
        "missing": missing,
        "aliases": aliases,
        "ok": not missing,
    }


def count_f64(text: str) -> int:
    """``f64`` tensor types in HLO text — catches f64 that appears only
    after lowering (constant folding, upcasts the jaxpr does not show)."""
    return len(re.findall(r"\bf64\[", text))


def host_custom_calls(text: str) -> List[str]:
    """Custom-call targets in the HLO — host callbacks lower to these;
    any hit in a hot-path entry is a dispatch-pipeline stall."""
    return re.findall(r'custom_call_target="([^"]+)"', text)
