"""The audit runner: lower every hot entry point, verify invariants
against the committed ``ANALYSIS_BASELINE.json``.

One deterministic audit workload (small enough to trace in seconds,
shaped to exercise the full [1, 2, 4, 8] wave-width ladder) is trained
in-process; every entry point the repo dispatches is then mirrored as
``ShapeDtypeStruct`` and traced with ``jax.make_jaxpr`` — pure tracing,
zero compiles — except the donation check, which AOT-compiles ONE
program under the costmodel discipline (AOT shares no cache with
executing programs).

Entries audited:

- ``train_block``        the fused boosting block (unjitted core, the
                         exact signature the executing jit compiled)
- ``frontier_hist_w<k>`` every wave-width ladder bucket, via
                         ``core.grow_frontier.wave_hist_entry``
- ``materialize``        the tree-flush concatenation
- ``grower``             the unsharded frontier grower (the structural
                         fingerprint PR 6 pinned as a string compare)
- ``grower_sharded``     the 8-virtual-device shard_map grower (the
                         psum schedule PR 5 pinned by hand)
- ``grower_streamed_*``  one full wave of the mesh-mode streamed grower
                         (chunks x chips: the psum'd continue flag +
                         the learner schedule, zero extra f32 payload)
- ``predict_b<bucket>``  every serving bucket's forward pass (the SoA
                         traversal — serving/traversal.py)
- ``predict_cascade_b<min_bucket>``  the early-exit cascade variant
                         (stage-1 prefix + conditional stage 2)

Hard invariants hold regardless of baseline content: zero f64 equations
and zero host callbacks in every entry, and every declared train-block
donation actually aliased.  Everything else (fingerprints, collective
schedules, equation counts) is compared exactly against the baseline —
re-baselining is an explicit, reviewed act (``tools/analyze.py
--write-baseline``).
"""
from __future__ import annotations

import functools
import json
import os
from typing import Any, Dict, List, Optional, Tuple

from . import hlo_audit, jaxpr_audit

BASELINE_NAME = "ANALYSIS_BASELINE.json"
SCHEMA = 1

# deterministic audit workload: frontier growth with the full
# [1, 2, 4, 8] wave ladder, bucketed serving at two buckets
AUDIT_WORKLOAD: Dict[str, Any] = {
    "rows": 256, "features": 4, "num_leaves": 15, "max_depth": 4,
    "iters": 3, "seed": 0, "min_bucket": 32, "max_batch": 64,
}


def _train_audit_booster(wl: Dict[str, Any]):
    import numpy as np
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(wl["seed"])
    X = rng.randn(wl["rows"], wl["features"]).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)
    params = {"objective": "binary", "verbosity": -1,
              "num_leaves": wl["num_leaves"], "max_depth": wl["max_depth"],
              "tree_growth": "frontier", "seed": wl["seed"]}
    bst = lgb.train(params, lgb.Dataset(X, label=y),
                    num_boost_round=wl["iters"])
    bst._impl.models          # flush: sets block/flush shapes
    return bst


def collect_audit(workload: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Any]:
    """Measure every entry's invariant record on the current source.
    Returns ``{"entries": {...}, "donation": {...}, "workload": ...}``."""
    import jax
    import jax.numpy as jnp

    wl = dict(AUDIT_WORKLOAD)
    if workload:
        wl.update(workload)
    bst = _train_audit_booster(wl)
    b = bst._impl
    sds = jax.ShapeDtypeStruct
    entries: Dict[str, Dict[str, Any]] = {}

    # ---- fused train block (exact executing signature)
    block = int(getattr(b, "_last_block_len", 0) or 0)
    if block > 0 and getattr(b, "_iter_capture", None) is not None:
        run_block = b._build_run_block()
        args = b.train_block_sds(block)
        entries["train_block"] = jaxpr_audit.audit_jaxpr(
            jax.make_jaxpr(run_block)(*args))

    # ---- every wave-width ladder bucket
    from .. import bucketing
    from ..core.grow_frontier import wave_hist_entry
    params = b.grow_params
    n = b.xb.shape[0]
    # stored-column count, not the word-matrix width (core/binpack.py)
    ncols = params.word_packed_cols or b.xb.shape[1]
    for w in bucketing.wave_width_ladder(params.num_leaves,
                                         params.max_depth):
        fn, hargs, hkw = wave_hist_entry(n, ncols, b.xb.dtype, params, w)
        entries["frontier_hist_w%d" % w] = jaxpr_audit.audit_jaxpr(
            jax.make_jaxpr(functools.partial(fn, **hkw))(*hargs))

    # ---- materialize flush
    flush = list(getattr(b, "_last_flush_shapes", ()))
    if flush:
        entries["materialize"] = jaxpr_audit.audit_jaxpr(
            jax.make_jaxpr(lambda *bufs: jnp.concatenate(bufs, axis=0))(
                *flush))

    # ---- unsharded grower (the PR 6 "byte-identical grower" compare)
    from ..core.grow_frontier import grow_tree_frontier
    f = params.word_packed_cols or b.xb.shape[1]
    fmask = jnp.ones((f,), bool)
    entries["grower"] = jaxpr_audit.audit_jaxpr(jax.make_jaxpr(
        lambda xb, g, h, m: grow_tree_frontier(
            xb, g, h, m, b.feature_meta, fmask, b.grow_params))(
        sds(b.xb.shape, b.xb.dtype), sds((n,), jnp.float32),
        sds((n,), jnp.float32), sds((n,), jnp.float32)))

    # ---- sharded grower under the 8-virtual-device mesh (PR 5 psums)
    sharded = jaxpr_audit.sharded_frontier_fn()
    if sharded is not None:
        sfn, sargs, _ = sharded
        entries["grower_sharded"] = jaxpr_audit.audit_jaxpr(
            jax.make_jaxpr(sfn)(*sargs))

    # ---- parallel-learner wave schedules (parallel/learners.py): the
    # reduce-scatter data learner and the PV-Tree voting learner on the
    # same 8-device mesh, feature axis widened to 16 so the psum_scatter
    # tiles evenly (8 | F). These PIN the comm-volume win statically:
    # data_rs exchanges F*B*3/P + P*RECORD_LANES floats per wave where the
    # serial schedule psums F*B*3; voting exchanges only the 2*top_k
    # elected columns (+ two int32 vote gathers).
    for nm, overrides in (("grower_sharded_data", {"frontier_rs": True}),
                          ("grower_sharded_voting", {"voting_top_k": 2})):
        sharded = jaxpr_audit.sharded_frontier_fn(
            param_overrides=overrides, num_features=16)
        if sharded is not None:
            sfn, sargs, _ = sharded
            entries[nm] = jaxpr_audit.audit_jaxpr(
                jax.make_jaxpr(sfn)(*sargs))

    # ---- streamed mesh grower, one full wave (chunks x chips,
    # stream/grow_stream.py): the host-dispatched wave_begin ->
    # chunk_wave -> fused chunk_wave_commit sequence under the same
    # 8-device mesh. Pins that distributed out-of-core training adds
    # exactly ONE collective over the in-memory learner schedule — the
    # int32 psum'd continue flag — and zero f32 payload.
    for nm, overrides in (("grower_streamed_data", {"frontier_rs": True}),
                          ("grower_streamed_voting",
                           {"voting_top_k": 2})):
        streamed = jaxpr_audit.streamed_sharded_fn(
            param_overrides=overrides, num_features=16)
        if streamed is not None:
            sfn, sargs, _ = streamed
            entries[nm] = jaxpr_audit.audit_jaxpr(
                jax.make_jaxpr(sfn)(*sargs))

    # ---- serving predict buckets (traced, never compiled)
    from ..serving.predictor import ServingEngine, bucket_sizes
    from ..serving.registry import ModelRegistry
    reg = ModelRegistry()
    reg.register_booster("audit", bst)
    eng = ServingEngine(registry=reg, max_batch=wl["max_batch"],
                        min_bucket=wl["min_bucket"])
    bundle = reg.get("audit")
    nf = max(bundle.num_features, 1)
    for bucket in bucket_sizes(eng.min_bucket, eng.max_batch):
        entry = eng._predictor(bundle, bucket, False,
                               bundle.effective_iterations(None))
        trees_sds = jax.tree_util.tree_map(
            lambda a: sds(a.shape, a.dtype), entry._trees)
        entries["predict_b%d" % bucket] = jaxpr_audit.audit_jaxpr(
            jax.make_jaxpr(entry._fn)(
                trees_sds, sds((bucket, nf), jnp.float32)))

    # ---- early-exit cascade variant (stage-1 prefix + lax.cond stage 2)
    ceng = ServingEngine(registry=reg, max_batch=wl["max_batch"],
                         min_bucket=wl["min_bucket"],
                         cascade_trees=1, cascade_margin=2.0)
    centry = ceng._predictor(bundle, wl["min_bucket"], False,
                             bundle.effective_iterations(None))
    ctrees_sds = jax.tree_util.tree_map(
        lambda a: sds(a.shape, a.dtype), centry._trees)
    entries["predict_cascade_b%d" % wl["min_bucket"]] = \
        jaxpr_audit.audit_jaxpr(jax.make_jaxpr(centry._fn)(
            ctrees_sds, sds((wl["min_bucket"], nf), jnp.float32)))

    # ---- fleet refit core (fleet/refit.py): the scan-over-iterations
    # leaf re-estimation program, traced at the audit workload's row
    # count. Pins the continuous-training loop's structural fingerprint
    # the same way the predict entries pin serving: zero collectives,
    # zero host callbacks, stable equation count.
    from ..fleet.refit import refit_audit_entry
    rfn, rargs = refit_audit_entry(bst, rows=wl["rows"])
    entries["fleet_refit"] = jaxpr_audit.audit_jaxpr(
        jax.make_jaxpr(rfn)(*rargs))

    # ---- donation effectiveness (the one AOT compile of the audit)
    donation: Dict[str, Any] = {}
    if block > 0 and getattr(b, "_iter_capture", None) is not None:
        donation["train_block"] = hlo_audit.audit_donation(
            b._build_run_block(), b.train_block_sds(block),
            type(b).TRAIN_BLOCK_DONATE)
        # the alias table is the contract; HLO text is not baselined
        donation["train_block"].pop("aliases", None)

    import jax as _jax
    return {"schema": SCHEMA, "jax": _jax.__version__,
            "backend": _jax.default_backend(), "workload": wl,
            "entries": entries, "donation": donation}


# ------------------------------------------------------------ comparison
# per-entry fields compared exactly against the baseline
_EXACT_FIELDS = ("fingerprint", "num_eqns", "psums", "all_gathers",
                 "collectives", "collective_schedule")


def compare_audit(baseline: Dict[str, Any], measured: Dict[str, Any]
                  ) -> Tuple[List[Dict[str, Any]], str]:
    """Violations + human-readable report.  Empty violations == gate
    passes.  Every violation names the entry point and the invariant."""
    violations: List[Dict[str, Any]] = []
    lines: List[str] = []

    def viol(entry: str, invariant: str, base: Any, meas: Any,
             reason: str) -> None:
        violations.append({"entry": entry, "invariant": invariant,
                           "baseline": base, "measured": meas,
                           "reason": reason})

    base_entries = baseline.get("entries", {})
    meas_entries = measured.get("entries", {})
    for name in sorted(set(base_entries) | set(meas_entries)):
        be, me = base_entries.get(name), meas_entries.get(name)
        if me is None:
            viol(name, "present", "present", "missing",
                 "baselined entry no longer audited")
            lines.append("%-18s MISSING from measurement" % name)
            continue
        # hard invariants first: they hold even without a baseline
        if me.get("f64_eqns", 0) != 0:
            viol(name, "zero_f64", 0, me["f64_eqns"],
                 "f64 primitives on an f32-only entry")
        if me.get("host_callbacks"):
            viol(name, "no_host_callbacks", [], me["host_callbacks"],
                 "host callbacks in a hot-path entry")
        if be is None:
            lines.append("%-18s NEW (not in baseline): psums=%d fp=%s"
                         % (name, me.get("psums", 0),
                            me.get("fingerprint", "")[:12]))
            continue
        ok = True
        for field in _EXACT_FIELDS:
            if be.get(field) != me.get(field):
                invariant = ("collective_schedule"
                             if field == "collective_schedule" else field)
                viol(name, invariant, be.get(field), me.get(field),
                     "%s drift" % field)
                ok = False
        lines.append("%-18s %s psums=%d collectives=%d fp=%s"
                     % (name, "ok  " if ok else "FAIL",
                        me.get("psums", 0), me.get("collectives", 0),
                        me.get("fingerprint", "")[:12]))

    base_don = baseline.get("donation", {})
    meas_don = measured.get("donation", {})
    for name in sorted(set(base_don) | set(meas_don)):
        md = meas_don.get(name)
        if md is None:
            viol(name, "donation_present", "present", "missing",
                 "baselined donation record no longer audited")
            continue
        if not md.get("ok", False):
            viol(name, "donation_aliased",
                 base_don.get(name, {}).get("donated_params"),
                 md.get("missing"),
                 "declared donated buffers not input-output aliased")
        bd = base_don.get(name)
        if bd is not None and bd.get("donated_params") \
                != md.get("donated_params"):
            viol(name, "donation_declaration", bd.get("donated_params"),
                 md.get("donated_params"), "donate_argnums drift")
        lines.append("%-18s donation %s params=%s"
                     % (name, "ok  " if md.get("ok") else "FAIL",
                        md.get("donated_params")))

    return violations, "\n".join(lines)


def publish(measured: Dict[str, Any],
            violations: List[Dict[str, Any]], registry=None) -> None:
    """Land the audit outcome as ``lgbm_analysis_*`` registry gauges so
    the stats server / prometheus scrape sees the last audit state."""
    from ..obs.registry import get_registry
    reg = registry if registry is not None else get_registry()
    entries = measured.get("entries", {})
    reg.gauge("lgbm_analysis_entries",
              "entry points audited").set(float(len(entries)))
    reg.gauge("lgbm_analysis_violations",
              "invariant violations in the last audit").set(
        float(len(violations)))
    reg.gauge("lgbm_analysis_collectives_total",
              "collective equations across audited entries").set(
        float(sum(e.get("collectives", 0) for e in entries.values())))
    reg.gauge("lgbm_analysis_f64_eqns_total",
              "f64-producing equations across audited entries").set(
        float(sum(e.get("f64_eqns", 0) for e in entries.values())))


# ------------------------------------------------------------ baseline IO
def default_baseline_path() -> str:
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, BASELINE_NAME)


def load_baseline(path: Optional[str] = None) -> Dict[str, Any]:
    with open(path or default_baseline_path(), encoding="utf-8") as fh:
        return json.load(fh)


def write_baseline(measured: Dict[str, Any],
                   path: Optional[str] = None) -> str:
    """Refuse to baseline a state that breaks the HARD invariants —
    a baseline must never grandfather f64 or a dropped donation in."""
    for name, e in measured.get("entries", {}).items():
        if e.get("f64_eqns", 0) != 0:
            raise ValueError("refusing to baseline %s: f64 equations "
                             "present" % name)
        if e.get("host_callbacks"):
            raise ValueError("refusing to baseline %s: host callbacks "
                             "present" % name)
    for name, d in measured.get("donation", {}).items():
        if not d.get("ok", False):
            raise ValueError("refusing to baseline %s: donation not "
                             "aliased" % name)
    path = path or default_baseline_path()
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(measured, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return path
