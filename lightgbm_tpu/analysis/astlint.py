"""JAX-aware AST lint over the package source (layer 1 of the analyzer).

Every rule here encodes a failure class this repo has already paid for
once by hand:

- ``LGL101`` tracer-unsafe branch: a Python ``if``/``while`` whose test
  consumes a traced value inside a jit-traced function raises
  ``TracerBoolConversionError`` at trace time — or worse, silently
  specializes when the value is concrete on the first call.
- ``LGL102`` tracer concretization: ``float()`` / ``int()`` / ``bool()``
  / ``.item()`` / ``.tolist()`` on traced values force a host sync (or a
  trace error) from inside compiled code.
- ``LGL103`` host sync: ``jax.block_until_ready`` / ``jax.device_get``
  stall the dispatch pipeline; the only approved sites are span closes
  (obs/trace.py), warmup, and explicit probes — each carries an inline
  suppression with its reason.
- ``LGL104`` weak-dtype construction: dtype-less ``jnp.arange`` /
  ``zeros`` / ``ones`` / ``full`` / ``linspace`` in jit-traced code is
  the recompile class PR 4 fixed by hand in ``train_many`` (a nonzero-
  start ``jnp.arange`` compiled a stray ``convert_element_type`` on the
  second block).
- ``LGL105`` f64 construct: ``jnp.float64`` / ``dtype="float64"`` /
  x64-mode flips produce f64 device programs; the frontier path is
  f32-only by contract (the explicitly gated ``gpu_use_dp`` fallback is
  the one suppressed exception).  Host-side ``np.float64`` is fine and
  never flagged.
- ``LGL106`` global mutation under trace: assigning module globals (or
  mutating module-level containers) inside a jit-traced function runs at
  TRACE time, not call time — a classic silent-staleness bug.
- ``LGL107`` unvalidated config read: ``cfg.<name>`` / ``config.<name>``
  / ``self.config.<name>`` where ``<name>`` is not a canonical parameter
  or declared Config attribute — the typo class config.py's table
  validation exists to catch.

Suppression: ``# lgbm-lint: disable=LGL104`` on the finding's line (or
the line directly above, for long expressions), comma-separated for
multiple rules, free text after the rule list as the reason.  A file-
level ``# lgbm-lint: disable-file=LGL103`` in the first ten lines
suppresses a rule for the whole file.

The linter is pure AST — it never imports the linted modules.  Only
``LGL107`` imports ``lightgbm_tpu.config`` (for the parameter table),
and skips itself if that import fails.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# rule id -> (severity, summary)
LINT_RULES: Dict[str, Tuple[str, str]] = {
    "LGL101": ("error",
               "tracer-unsafe Python branch on a traced value inside a "
               "jit-traced function"),
    "LGL102": ("error",
               "tracer concretization (float()/int()/bool()/.item()/"
               ".tolist()) inside a jit-traced function"),
    "LGL103": ("warning",
               "host sync (block_until_ready / device_get) outside an "
               "approved, suppressed site"),
    "LGL104": ("error",
               "dtype-less jnp array construction in jit-traced code "
               "(weak-dtype recompile hazard)"),
    "LGL105": ("error",
               "f64-producing construct on the device path"),
    "LGL106": ("error",
               "module-global mutation inside a jit-traced function"),
    "LGL107": ("warning",
               "config parameter read that config.py does not declare"),
}

_SUPPRESS_TOKEN = "lgbm-lint:"

# decorator / call names that make a function's body run under trace
_TRACING_DECORATORS = {
    "jit", "vmap", "pmap", "shard_map", "checkpoint", "remat", "grad",
    "value_and_grad", "custom_jvp", "custom_vjp",
}
# call targets whose function-valued arguments are traced
_TRACING_CALLS = {
    "jit", "vmap", "pmap", "shard_map", "scan", "while_loop", "cond",
    "switch", "fori_loop", "map", "associative_scan", "checkpoint",
    "remat", "grad", "value_and_grad", "eval_shape", "make_jaxpr",
}
# the subset that CALLS its function argument with tracer positionals
# (a scan body's carry/xs ARE tracers, no array evidence required) —
# unlike jit-likes, whose params may be static config (strings, ints)
_CONTROL_FLOW_CALLS = {
    "scan", "while_loop", "cond", "switch", "fori_loop", "map",
    "associative_scan",
}
# jnp constructors with their minimum positional-arg count that already
# includes an explicit dtype (so fewer positionals + no dtype= kwarg
# means the default/weak dtype is taken)
_DTYPE_CONSTRUCTORS = {
    "arange": 4, "zeros": 2, "ones": 2, "empty": 2, "full": 3,
    "linspace": 7,
}
_CONCRETIZERS = {"float", "int", "bool"}
_CONCRETIZER_METHODS = {"item", "tolist"}
_HOST_SYNCS = {"block_until_ready", "device_get"}
_JNP_ALIASES = {"jnp", "jdn", "jax_numpy"}   # import jax.numpy as jnp


@dataclass
class Finding:
    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return "%s:%d:%d: %s [%s] %s" % (
            self.path, self.line, self.col, self.severity, self.rule,
            self.message)


# ------------------------------------------------------------ suppression
def _suppressions(src: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """Per-line and file-level suppressed rule sets from lint comments."""
    per_line: Dict[int, Set[str]] = {}
    per_file: Set[str] = set()
    for i, line in enumerate(src.splitlines(), start=1):
        if _SUPPRESS_TOKEN not in line:
            continue
        tail = line.split(_SUPPRESS_TOKEN, 1)[1].strip()
        file_level = tail.startswith("disable-file=")
        if not (file_level or tail.startswith("disable=")):
            continue
        spec = tail.split("=", 1)[1]
        # the rule list ends at the first whitespace; everything after
        # is the human reason and ignored by the parser
        rules = {r.strip() for r in spec.split()[0].split(",") if r.strip()}
        if file_level and i <= 10:
            per_file |= rules
        elif not file_level:
            per_line.setdefault(i, set()).update(rules)
    return per_line, per_file


def _suppressed(rule: str, line: int, per_line: Dict[int, Set[str]],
                per_file: Set[str]) -> bool:
    for rules in (per_file, per_line.get(line, ()),
                  per_line.get(line - 1, ())):
        if rule in rules or "all" in rules:
            return True
    return False


# ------------------------------------------------------------ AST helpers
def _root_name(node: ast.AST) -> Optional[str]:
    """a.b.c -> 'a'; foo -> 'foo'."""
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_jnp_attr(node: ast.AST, attr: Optional[str] = None) -> bool:
    """True for ``jnp.<attr>`` / ``jax.numpy.<attr>`` attribute nodes."""
    if not isinstance(node, ast.Attribute):
        return False
    if attr is not None and node.attr != attr:
        return False
    v = node.value
    if isinstance(v, ast.Name) and v.id in _JNP_ALIASES:
        return True
    return (isinstance(v, ast.Attribute) and v.attr == "numpy"
            and isinstance(v.value, ast.Name) and v.value.id == "jax")


def _func_args(call: ast.Call) -> List[ast.AST]:
    """Function-valued argument candidates of a tracing call: bare args
    plus elements of list/tuple args (lax.switch branch lists)."""
    out: List[ast.AST] = []
    for a in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(a, (ast.List, ast.Tuple)):
            out.extend(a.elts)
        else:
            out.append(a)
    return out


class _Parents(ast.NodeVisitor):
    def __init__(self):
        self.parent: Dict[ast.AST, ast.AST] = {}

    def generic_visit(self, node):
        for child in ast.iter_child_nodes(node):
            self.parent[child] = node
        super().generic_visit(node)


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _collect_traced_functions(tree: ast.Module) -> Dict[ast.AST, bool]:
    """Function/lambda nodes whose bodies run under jax tracing: those
    with tracing decorators, those passed (by name or inline) to tracing
    calls anywhere in the module, and everything nested inside one —
    nested defs execute at trace time.

    Maps each node to a STRICT flag: True when the function is a
    control-flow body (scan/cond/while_loop...), whose positional
    parameters are tracers by construction; False for jit-likes, where
    parameters may be static config and the array-evidence pass decides."""
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    traced: Dict[ast.AST, bool] = {}

    def mark(fn: ast.AST, strict: bool) -> None:
        traced[fn] = traced.get(fn, False) or strict

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                name = (target.attr if isinstance(target, ast.Attribute)
                        else getattr(target, "id", None))
                if name in _TRACING_DECORATORS:
                    mark(node, False)
                # @partial(jax.jit, ...) — the tracer is the first arg
                if isinstance(dec, ast.Call) and name == "partial":
                    for a in dec.args[:1]:
                        an = (a.attr if isinstance(a, ast.Attribute)
                              else getattr(a, "id", None))
                        if an in _TRACING_DECORATORS:
                            mark(node, False)
        elif isinstance(node, ast.Call):
            target = node.func
            name = (target.attr if isinstance(target, ast.Attribute)
                    else getattr(target, "id", None))
            if name not in _TRACING_CALLS:
                continue
            strict = name in _CONTROL_FLOW_CALLS
            for a in _func_args(node):
                if isinstance(a, ast.Lambda):
                    mark(a, strict)
                elif isinstance(a, ast.Name) and a.id in defs_by_name:
                    for d in defs_by_name[a.id]:
                        mark(d, strict)

    # transitive closure over nesting: inner defs run at trace time but
    # their own params are evidence-based unless separately marked
    changed = True
    while changed:
        changed = False
        for t in list(traced):
            for inner in ast.walk(t):
                if inner is not t and isinstance(inner, _FUNC_NODES) \
                        and inner not in traced:
                    traced[inner] = False
                    changed = True
    return traced


def _param_names(fn: ast.AST) -> Set[str]:
    args = fn.args
    names = {a.arg for a in args.args + args.posonlyargs + args.kwonlyargs}
    for extra in (args.vararg, args.kwarg):
        if extra is not None:
            names.add(extra.arg)
    names.discard("self")
    names.discard("cls")
    return names


_ARRAY_METHODS = {
    "astype", "reshape", "sum", "mean", "max", "min", "argmax", "argmin",
    "cumsum", "take", "dot", "at", "set", "add", "transpose", "squeeze",
    "ravel", "flatten", "clip",
}


def _array_evidence(fn: ast.AST) -> Set[str]:
    """Names the function body uses AS ARRAYS: subscripted, passed as
    the leading argument of a jnp/lax/jax call, or the receiver of an
    array method.  Parameters without such evidence are treated as
    static Python values (``impl`` strings, ``row_chunk`` ints) — the
    distinction a purely syntactic tracer analysis cannot otherwise
    make, and the one that keeps LGL101/102 precise."""
    ev: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Subscript) and \
                isinstance(node.value, ast.Name):
            ev.add(node.value.id)
        elif isinstance(node, ast.Call):
            func = node.func
            root = _root_name(func)
            jaxish = root in _JNP_ALIASES | {"jax", "lax"} or \
                (isinstance(func, ast.Attribute) and _is_jnp_attr(func))
            if jaxish and node.args:
                for sub in ast.walk(node.args[0]):
                    if isinstance(sub, ast.Name):
                        ev.add(sub.id)
            if isinstance(func, ast.Attribute) and \
                    func.attr in _ARRAY_METHODS and \
                    isinstance(func.value, ast.Name):
                ev.add(func.value.id)
    return ev


def _strict_param_names(fn: ast.AST) -> Set[str]:
    """Positional parameters WITHOUT defaults — the ones a control-flow
    combinator fills with tracers.  Defaulted params (``with_forced:
    bool = False``) stay evidence-based: the combinator never passes
    them, so they keep their static default."""
    args = fn.args
    pos = list(args.posonlyargs) + list(args.args)
    if args.defaults:
        pos = pos[:len(pos) - len(args.defaults)]
    names = {a.arg for a in pos}
    names.discard("self")
    names.discard("cls")
    return names


def _traced_names(fn: ast.AST, inherited: Set[str],
                  strict: bool = False) -> Set[str]:
    """Array-evidenced parameter names (plus ALL no-default positionals
    for control-flow bodies) plus locals assigned from traced
    expressions — a bounded forward propagation, not full dataflow."""
    traced = (_param_names(fn) & _array_evidence(fn)) | set(inherited)
    if strict:
        traced |= _strict_param_names(fn)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for _ in range(4):
        added = False
        for node in body:
            for sub in ast.walk(node):
                if isinstance(sub, _FUNC_NODES):
                    continue
                if isinstance(sub, ast.Assign) and \
                        _uses_traced(sub.value, traced):
                    for tgt in sub.targets:
                        for t in ast.walk(tgt):
                            if isinstance(t, ast.Name) and \
                                    t.id not in traced:
                                traced.add(t.id)
                                added = True
        if not added:
            break
    return traced


def _uses_traced(expr: ast.AST, traced: Set[str]) -> bool:
    """Whether ``expr`` consumes a traced value *as data*: a bare Name
    or a Subscript of one.  Attribute chains (``params.foo`` — static
    config objects; ``x.shape`` — static on tracers), ``is``/``is not``
    comparisons and ``isinstance``/``len``/``getattr`` calls never
    count: they are legal on tracers / static carriers."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute):
            continue
        if isinstance(node, ast.Call):
            fname = (node.func.id if isinstance(node.func, ast.Name)
                     else None)
            if fname in ("isinstance", "len", "getattr", "hasattr",
                         "type"):
                return False  # static-inspection call dominates the test
        if isinstance(node, ast.Compare):
            if any(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return False
            # comparison against a string constant is static dispatch
            # (`impl == "scatter"`) — a tracer never compares to a str
            if any(isinstance(c, ast.Constant) and isinstance(c.value, str)
                   for c in [node.left] + list(node.comparators)):
                return False
    # second pass: find a data use that is not behind an Attribute
    return _has_bare_use(expr, traced)


def _has_bare_use(expr: ast.AST, traced: Set[str]) -> bool:
    if isinstance(expr, ast.Attribute):
        return False   # x.anything is a static read
    if isinstance(expr, ast.Name):
        return expr.id in traced
    if isinstance(expr, ast.Subscript):
        return _has_bare_use(expr.value, traced) or \
            _has_bare_use(expr.slice, traced)
    if isinstance(expr, ast.Call):
        # an array-method result is traced iff its receiver is
        # (`xb.reshape(...)`, `g.astype(...)`); a plain call is traced
        # iff an argument is — the callee name itself is not a data use
        func = expr.func
        if isinstance(func, ast.Attribute) and \
                func.attr in _ARRAY_METHODS and \
                isinstance(func.value, ast.Name) and \
                func.value.id in traced:
            return True
        return any(_has_bare_use(a, traced)
                   for a in list(expr.args)
                   + [kw.value for kw in expr.keywords])
    if isinstance(expr, _FUNC_NODES):
        return False
    return any(_has_bare_use(c, traced) for c in ast.iter_child_nodes(expr))


# ------------------------------------------------------------ the linter
class _Linter:
    def __init__(self, src: str, path: str,
                 known_params: Optional[Set[str]]):
        self.src = src
        self.path = path
        self.known_params = known_params
        self.findings: List[Finding] = []
        self.per_line, self.per_file = _suppressions(src)

    def emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        if _suppressed(rule, line, self.per_line, self.per_file):
            return
        sev = LINT_RULES[rule][0]
        self.findings.append(Finding(rule, sev, self.path, line,
                                     getattr(node, "col_offset", 0),
                                     message))

    # -------------------------------------------------------- module-wide
    def run(self) -> List[Finding]:
        try:
            tree = ast.parse(self.src)
        except SyntaxError as exc:
            self.findings.append(Finding(
                "LGL000", "error", self.path, exc.lineno or 1, 0,
                "syntax error: %s" % exc.msg))
            return self.findings
        module_globals = {
            t.id for node in tree.body
            for stmt in ([node] if isinstance(node, (ast.Assign,
                                                     ast.AnnAssign)) else [])
            for t in ast.walk(stmt)
            if isinstance(t, ast.Name) and isinstance(t.ctx, ast.Store)}
        traced_fns = _collect_traced_functions(tree)
        # attributes used as call targets (`cfg.update(...)`) are method
        # accesses, not parameter reads — LGL107 skips them
        call_funcs = {node.func for node in ast.walk(tree)
                      if isinstance(node, ast.Call)}

        for node in ast.walk(tree):
            self._check_host_sync(node)
            self._check_f64(node)
            self._check_config_read(node, call_funcs)

        # scoped rules: walk each traced function once, skipping nested
        # function bodies (they are themselves in traced_fns)
        for fn, strict in traced_fns.items():
            inherited: Set[str] = set()
            self._lint_traced_fn(fn, inherited, module_globals, strict)
        seen: Set[Tuple[str, int, int]] = set()
        uniq: List[Finding] = []
        for f in self.findings:
            key = (f.rule, f.line, f.col)
            if key not in seen:
                seen.add(key)
                uniq.append(f)
        self.findings = uniq
        return self.findings

    # -------------------------------------------------------- LGL103/105/107
    def _check_host_sync(self, node: ast.AST) -> None:
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _HOST_SYNCS:
            self.emit("LGL103", node,
                      "host sync `%s` — approved sites (span close, "
                      "warmup, probes) must suppress with a reason"
                      % node.func.attr)

    def _check_f64(self, node: ast.AST) -> None:
        if _is_jnp_attr(node, "float64") or _is_jnp_attr(node, "double"):
            self.emit("LGL105", node,
                      "jnp.float64 on the device path (f32-only contract)")
            return
        if isinstance(node, ast.Call):
            fname = (node.func.attr if isinstance(node.func, ast.Attribute)
                     else getattr(node.func, "id", None))
            if fname == "update":
                args = node.args
                if args and isinstance(args[0], ast.Constant) and \
                        args[0].value == "jax_enable_x64":
                    self.emit("LGL105", node,
                              "flipping jax_enable_x64 switches the whole "
                              "process to f64 semantics")
            # dtype="float64" passed into a jnp/jax call
            if isinstance(node.func, ast.Attribute) and \
                    (_is_jnp_attr(node.func.value) or
                     _root_name(node.func) in _JNP_ALIASES | {"jax", "lax"}):
                for kw in node.keywords:
                    if kw.arg == "dtype" and \
                            isinstance(kw.value, ast.Constant) and \
                            kw.value.value in ("float64", "f64", "double"):
                        self.emit("LGL105", node,
                                  'dtype="float64" in a jax call')

    def _check_config_read(self, node: ast.AST,
                           call_funcs: Set[ast.AST]) -> None:
        if self.known_params is None or not isinstance(node, ast.Attribute):
            return
        if not isinstance(node.ctx, ast.Load) or node in call_funcs:
            return
        if _root_name(node) == "jax":
            return  # jax.config.* is the jax runtime config, not ours
        v = node.value
        is_cfg = (isinstance(v, ast.Name) and v.id in ("cfg", "config")) \
            or (isinstance(v, ast.Attribute) and v.attr == "config")
        if is_cfg and not node.attr.startswith("_") and \
                node.attr not in self.known_params:
            self.emit("LGL107", node,
                      "config attribute `%s` is not declared in "
                      "config.py's parameter table" % node.attr)

    # -------------------------------------------------------- traced scope
    def _lint_traced_fn(self, fn: ast.AST, inherited: Set[str],
                        module_globals: Set[str],
                        strict: bool = False) -> None:
        traced = _traced_names(fn, inherited, strict)
        globals_declared: Set[str] = set()
        body = fn.body if isinstance(fn.body, list) else [fn.body]

        def walk_scope(node: ast.AST):
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FUNC_NODES):
                    continue   # nested fn: separate traced scope
                yield from walk_scope(child)

        for stmt in body:
            for node in walk_scope(stmt):
                if isinstance(node, (ast.If, ast.While)):
                    if _uses_traced(node.test, traced):
                        self.emit(
                            "LGL101", node,
                            "`%s` on a traced value — use lax.cond / "
                            "jnp.where / lax.while_loop"
                            % ("while" if isinstance(node, ast.While)
                               else "if"))
                elif isinstance(node, ast.Call):
                    self._check_concretize(node, traced)
                    self._check_weak_dtype(node)
                elif isinstance(node, ast.Global):
                    globals_declared.update(node.names)
                    self.emit("LGL106", node,
                              "`global %s` inside a jit-traced function "
                              "runs at trace time, not call time"
                              % ", ".join(node.names))
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    self._check_global_mutation(node, traced,
                                                module_globals,
                                                globals_declared)

    def _check_concretize(self, node: ast.Call, traced: Set[str]) -> None:
        fname = getattr(node.func, "id", None)
        if fname in _CONCRETIZERS and node.args and \
                _uses_traced(node.args[0], traced):
            self.emit("LGL102", node,
                      "`%s()` of a traced value forces concretization"
                      % fname)
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _CONCRETIZER_METHODS and \
                _has_bare_use(node.func.value, traced):
            self.emit("LGL102", node,
                      "`.%s()` of a traced value forces a host sync"
                      % node.func.attr)

    def _check_weak_dtype(self, node: ast.Call) -> None:
        if not isinstance(node.func, ast.Attribute):
            return
        ctor = node.func.attr
        if ctor not in _DTYPE_CONSTRUCTORS or not _is_jnp_attr(node.func):
            return
        if any(kw.arg == "dtype" for kw in node.keywords):
            return
        if len(node.args) >= _DTYPE_CONSTRUCTORS[ctor]:
            return
        self.emit("LGL104", node,
                  "dtype-less `jnp.%s` in jit-traced code — weak/default "
                  "dtypes recompile when the surrounding types shift "
                  "(the train_many arange regression)" % ctor)

    def _check_global_mutation(self, node: ast.AST, traced: Set[str],
                               module_globals: Set[str],
                               globals_declared: Set[str]) -> None:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for tgt in targets:
            if isinstance(tgt, ast.Name) and tgt.id in globals_declared:
                self.emit("LGL106", node,
                          "assignment to global `%s` inside a jit-traced "
                          "function" % tgt.id)
            elif isinstance(tgt, (ast.Subscript, ast.Attribute)):
                base = tgt
                while isinstance(base, (ast.Attribute, ast.Subscript)):
                    base = base.value
                base = base.id if isinstance(base, ast.Name) else None
                if base is not None and base in module_globals and \
                        base not in traced and not base.startswith("__"):
                    self.emit(
                        "LGL106", node,
                        "mutation of module-level `%s` inside a "
                        "jit-traced function happens at trace time"
                        % base)


# ------------------------------------------------------------ entry points
def _known_config_params() -> Optional[Set[str]]:
    """Canonical names + aliases + declared Config attributes, or None
    when the package is not importable (pure-AST contexts)."""
    try:
        from .. import config as config_mod
        cfg = config_mod.Config({})
        names = set(config_mod._CANON) | set(config_mod._ALIASES)
        names |= set(vars(cfg))
        names |= {a for a in dir(config_mod.Config)
                  if not a.startswith("_")}
        return names
    except Exception:  # noqa: BLE001 - lint must run without the package
        return None


def lint_source(src: str, path: str = "<string>",
                known_params: Optional[Set[str]] = None,
                resolve_params: bool = True) -> List[Finding]:
    if known_params is None and resolve_params:
        known_params = _known_config_params()
    return _Linter(src, path, known_params).run()


def lint_paths(paths: Iterable[str]) -> List[Finding]:
    known = _known_config_params()
    findings: List[Finding] = []
    for p in sorted(paths):
        with open(p, encoding="utf-8") as fh:
            findings.extend(lint_source(fh.read(), p, known_params=known,
                                        resolve_params=False))
    return findings


def package_sources(root: Optional[str] = None) -> List[str]:
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out: List[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".jax_cache")]
        out.extend(os.path.join(dirpath, f) for f in filenames
                   if f.endswith(".py"))
    return sorted(out)


def lint_package(root: Optional[str] = None) -> List[Finding]:
    """Lint every .py file of the installed package (or ``root``)."""
    return lint_paths(package_sources(root))
