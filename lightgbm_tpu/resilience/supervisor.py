"""Supervised training: watchdog + restart loop + peer-death detection.

Three layers, smallest blast radius first:

- :class:`Watchdog` — a deadline on the per-iteration heartbeat the
  training loop emits (the synced ``block_until_ready`` window the obs
  layer already times). The FIRST deadline is warmup-aware: the initial
  compile legitimately takes far longer than any later iteration, so the
  grace window is added until the first beat lands. On expiry it sets the
  fault-injection abort event, which wakes cooperative waits (injected
  hangs) into a :class:`~..resilience.faults.WatchdogAbort`.

- :class:`Supervisor` — the in-process restart loop behind
  ``train(supervise=True)``: on a crash or watchdog abort it records the
  flight-dump path the engine attached to the exception, sleeps a bounded
  exponential backoff, and re-runs the attempt with
  ``resume_from=checkpoint_dir`` (byte-exact resume, PR 3 contract).
  After ``max_restarts`` failed restarts it raises with the LAST
  flight-dump path in the message — the operator's entry point.

- :class:`ProcessSupervisor` — the same loop one level up: the trainer is
  a child process, so SIGKILL and genuinely-stuck dispatches (which no
  in-process watchdog can interrupt) are survivable. Hang detection rides
  a heartbeat FILE the trainer touches each iteration
  (``supervise_heartbeat_file`` / :func:`heartbeat_file_callback`);
  a stale heartbeat gets the child SIGKILLed and restarted. The chaos
  smoke drives kill-and-resume byte-identity through this class.

- :class:`KvHeartbeat` — per-rank liveness leases in the jax.distributed
  coordination-service KV store, so a multi-process rank can fail fast
  with "rank 1 is dead" instead of blocking a full KV timeout
  (``KvHostComm(peer_guard=hb.dead_peers)``).
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional

from ..log import LightGBMError, Log
from . import faults

ATTEMPT_ENV = "LGBM_SUPERVISOR_ATTEMPT"


def _registry_counter(name: str, doc: str):
    from ..obs.registry import get_registry
    return get_registry().counter(name, doc)


class Watchdog:
    """Heartbeat deadline with a warmup-aware first window.

    ``beat()`` is called by the training loop each iteration; until the
    first beat the deadline is ``timeout_s + warmup_grace_s`` (the first
    compile is slow-but-alive), after it plain ``timeout_s``. On expiry
    ``on_fire(elapsed_s)`` runs once and the fault-injection abort event
    is set so cooperative waits unwind as WatchdogAbort.
    """

    def __init__(self, timeout_s: float, warmup_grace_s: float = 0.0,
                 on_fire: Optional[Callable[[float], None]] = None,
                 name: str = "train"):
        self.timeout_s = float(timeout_s)
        self.warmup_grace_s = max(float(warmup_grace_s), 0.0)
        self.on_fire = on_fire
        self.name = name
        self.fired = False
        self.beats = 0
        self._deadline = 0.0
        self._last = 0.0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Watchdog":
        now = time.monotonic()
        with self._lock:
            self._last = now
            self._deadline = now + self.timeout_s + self.warmup_grace_s
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="lgbm-watchdog-%s" % self.name,
            daemon=True)
        self._thread.start()
        return self

    def beat(self) -> None:
        now = time.monotonic()
        with self._lock:
            self.beats += 1
            self._last = now
            self._deadline = now + self.timeout_s

    def _loop(self) -> None:
        poll = max(min(self.timeout_s / 4.0, 0.5), 0.01)
        while not self._stop.wait(poll):
            with self._lock:
                expired = time.monotonic() > self._deadline
                elapsed = time.monotonic() - self._last
            if expired and not self.fired:
                self.fired = True
                Log.warning("watchdog %r fired: no heartbeat for %.1fs "
                            "(timeout %.1fs%s)", self.name, elapsed,
                            self.timeout_s,
                            ", warmup grace spent" if not self.beats else "")
                faults.request_abort(
                    "watchdog %r: no heartbeat for %.1fs"
                    % (self.name, elapsed))
                if self.on_fire is not None:
                    try:
                        self.on_fire(elapsed)
                    except Exception:
                        pass
                return

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def callback(self):
        """A before_iteration training callback that beats this watchdog."""
        wd = self

        class _Beat:
            before_iteration = True
            order = -100          # first: the beat must precede any work

            def __call__(self, env):
                wd.beat()

        return _Beat()


def heartbeat_file_callback(path: str):
    """A before_iteration callback touching ``path`` every iteration —
    the cross-process heartbeat a :class:`ProcessSupervisor` watches."""

    class _Touch:
        before_iteration = True
        order = -99
        heartbeat_path = path

        def __call__(self, env):
            with open(path, "w") as fh:
                fh.write("%d %.6f\n" % (env.iteration, time.time()))

    return _Touch()


class Supervisor:
    """In-process restart loop: crash / watchdog-abort -> flight dump ->
    bounded exponential backoff -> resume from the newest valid
    checkpoint -> retry, up to ``max_restarts`` restarts."""

    def __init__(self, checkpoint_dir: str, max_restarts: int = 3,
                 backoff_s: float = 1.0, backoff_max_s: float = 60.0,
                 hang_timeout_s: float = 0.0, warmup_grace_s: float = 120.0):
        if not checkpoint_dir:
            raise LightGBMError(
                "supervised training needs checkpoint_dir: auto-resume "
                "has nowhere to resume from")
        self.checkpoint_dir = checkpoint_dir
        self.max_restarts = max(int(max_restarts), 0)
        self.backoff_s = max(float(backoff_s), 0.0)
        self.backoff_max_s = max(float(backoff_max_s), self.backoff_s)
        self.hang_timeout_s = max(float(hang_timeout_s), 0.0)
        self.warmup_grace_s = max(float(warmup_grace_s), 0.0)
        self.restarts = 0
        self.last_flight_dump: Optional[str] = None
        self._c_restarts = _registry_counter(
            "lgbm_supervisor_restarts_total",
            "Supervised-training restarts (crash, watchdog, or SIGTERM).")
        self._c_fires = _registry_counter(
            "lgbm_supervisor_watchdog_fires_total",
            "Watchdog deadline expiries during supervised training.")

    def run(self, attempt: Callable):
        """``attempt(resume_from, watchdog)`` until it returns; the first
        try resumes from ``initial_resume`` (usually None), every retry
        from the supervisor's checkpoint dir."""
        delay = self.backoff_s
        resume: Optional[str] = None
        while True:
            wd: Optional[Watchdog] = None
            if self.hang_timeout_s > 0:
                wd = Watchdog(self.hang_timeout_s, self.warmup_grace_s,
                              on_fire=lambda _s: self._c_fires.inc())
                wd.start()
            try:
                result = attempt(resume, wd)
                return result
            except Exception as e:  # noqa: BLE001 - the restart seam
                dump = getattr(e, "flight_dump_path", None)
                if dump:
                    self.last_flight_dump = dump
                self.restarts += 1
                self._c_restarts.inc()
                if self.restarts > self.max_restarts:
                    suffix = (" (last flight dump: %s)" % self.last_flight_dump
                              if self.last_flight_dump else "")
                    raise LightGBMError(
                        "supervised training failed after %d restart%s: "
                        "%s: %s%s" % (self.max_restarts,
                                      "" if self.max_restarts == 1 else "s",
                                      type(e).__name__, e, suffix)) from e
                Log.warning(
                    "supervisor: attempt %d failed (%s: %s); resuming from "
                    "%s in %.1fs%s", self.restarts, type(e).__name__, e,
                    self.checkpoint_dir, delay,
                    " [flight dump %s]" % dump if dump else "")
                time.sleep(delay)
                delay = min(delay * 2.0, self.backoff_max_s)
                resume = self.checkpoint_dir
            finally:
                if wd is not None:
                    wd.stop()
                faults.clear_abort()


class ProcessSupervisor:
    """Restart loop around a trainer CHILD process — survives SIGKILL and
    non-cooperative hangs. The child is expected to resume itself (pass a
    ``resume``/``checkpoint_dir`` that makes a rerun continue); the
    supervisor's job is only death/hang detection, backoff, and the
    restart budget. Each attempt's index rides the LGBM_SUPERVISOR_ATTEMPT
    env var so chaos workers can arm faults on attempt 0 only."""

    def __init__(self, argv: List[str], max_restarts: int = 3,
                 backoff_s: float = 0.5, backoff_max_s: float = 30.0,
                 hang_timeout_s: float = 0.0, warmup_grace_s: float = 60.0,
                 heartbeat_file: Optional[str] = None,
                 env: Optional[dict] = None, cwd: Optional[str] = None,
                 poll_s: float = 0.25):
        self.argv = list(argv)
        self.max_restarts = max(int(max_restarts), 0)
        self.backoff_s = max(float(backoff_s), 0.0)
        self.backoff_max_s = max(float(backoff_max_s), self.backoff_s)
        self.hang_timeout_s = max(float(hang_timeout_s), 0.0)
        self.warmup_grace_s = max(float(warmup_grace_s), 0.0)
        self.heartbeat_file = heartbeat_file
        self.env = env
        self.cwd = cwd
        self.poll_s = max(float(poll_s), 0.05)
        self.restarts = 0
        self.hang_kills = 0
        self.attempts: List[int] = []     # exit codes, one per attempt

    def _heartbeat_age(self, started: float) -> float:
        """Seconds since the last heartbeat (file mtime), measuring from
        child start while no heartbeat exists yet."""
        if self.heartbeat_file and os.path.exists(self.heartbeat_file):
            return time.time() - os.path.getmtime(self.heartbeat_file)
        return time.time() - started

    def _run_once(self, attempt: int) -> int:
        env = dict(self.env if self.env is not None else os.environ)
        env[ATTEMPT_ENV] = str(attempt)
        started = time.time()
        warmed = False
        proc = subprocess.Popen(self.argv, env=env, cwd=self.cwd)
        try:
            while True:
                rc = proc.poll()
                if rc is not None:
                    return rc
                if self.hang_timeout_s > 0:
                    age = self._heartbeat_age(started)
                    budget = self.hang_timeout_s + (
                        0.0 if warmed else self.warmup_grace_s)
                    if self.heartbeat_file and \
                            os.path.exists(self.heartbeat_file) and \
                            os.path.getmtime(self.heartbeat_file) >= started:
                        warmed = True
                        budget = self.hang_timeout_s
                    if age > budget:
                        self.hang_kills += 1
                        Log.warning(
                            "process supervisor: heartbeat stale %.1fs "
                            "(> %.1fs); killing pid %d", age, budget,
                            proc.pid)
                        proc.kill()
                        proc.wait(timeout=30)
                        return -9
                time.sleep(self.poll_s)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)

    def run(self) -> int:
        delay = self.backoff_s
        attempt = 0
        while True:
            rc = self._run_once(attempt)
            self.attempts.append(rc)
            if rc == 0:
                return 0
            self.restarts += 1
            if self.restarts > self.max_restarts:
                raise LightGBMError(
                    "process supervisor: command failed after %d restarts "
                    "(exit codes %s): %s"
                    % (self.max_restarts, self.attempts,
                       " ".join(self.argv[:6])))
            Log.warning("process supervisor: attempt %d exited %s; "
                        "restarting in %.1fs", attempt, rc, delay)
            time.sleep(delay)
            delay = min(delay * 2.0, self.backoff_max_s)
            attempt += 1


class KvHeartbeat:
    """Per-rank liveness leases in the coordination-service KV store.

    Each rank's daemon thread rewrites ``<ns>/p<rank>`` every
    ``period_s`` with a wall-clock stamp; ``dead_peers()`` returns the
    ranks whose lease is older than ``lease_s`` (or missing after the
    initial grace). ``client`` defaults to the live jax.distributed
    client; tests inject a dict-backed stub."""

    def __init__(self, namespace: str = "lgbm_hb", period_s: float = 2.0,
                 lease_s: float = 10.0, client=None, rank: Optional[int] = None,
                 num_processes: Optional[int] = None):
        self._ns = str(namespace)
        self.period_s = max(float(period_s), 0.1)
        self.lease_s = max(float(lease_s), self.period_s)
        self._client = client
        self._rank = rank
        self._n = num_processes
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_at = 0.0

    def _resolve(self):
        if self._client is None:
            from jax._src import distributed as _jdist
            self._client = getattr(_jdist.global_state, "client", None)
            if self._client is None:
                raise LightGBMError(
                    "KvHeartbeat needs jax.distributed to be initialized")
        if self._rank is None or self._n is None:
            import jax
            self._rank = int(jax.process_index())
            self._n = int(jax.process_count())
        return self._client

    def _key(self, rank: int) -> str:
        return "%s/p%d" % (self._ns, rank)

    def beat_once(self) -> None:
        client = self._resolve()
        key = self._key(self._rank)
        stamp = "%.6f" % time.time()
        try:
            client.key_value_delete(key)
        except Exception:
            pass
        client.key_value_set(key, stamp)

    def start(self) -> "KvHeartbeat":
        self._resolve()
        self._started_at = time.time()
        self.beat_once()
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.period_s):
                try:
                    self.beat_once()
                except Exception as e:  # noqa: BLE001 - liveness best-effort
                    Log.debug("KvHeartbeat beat failed: %s", e)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="lgbm-kv-heartbeat")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        try:
            self._resolve().key_value_delete(self._key(self._rank))
        except Exception:
            pass

    def last_seen(self, rank: int) -> Optional[float]:
        client = self._resolve()
        try:
            raw = client.blocking_key_value_get(self._key(rank), 200)
            return float(raw)
        except Exception:
            return None

    def dead_peers(self) -> List[int]:
        """Ranks whose lease expired. A never-seen peer only counts as
        dead once our own uptime exceeds the lease (startup grace)."""
        self._resolve()
        now = time.time()
        dead = []
        for p in range(self._n):
            if p == self._rank:
                continue
            seen = self.last_seen(p)
            if seen is None:
                if self._started_at and now - self._started_at > self.lease_s:
                    dead.append(p)
            elif now - seen > self.lease_s:
                dead.append(p)
        return dead
