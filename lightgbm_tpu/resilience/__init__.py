"""Fault tolerance: deterministic fault injection, supervised training,
and serving overload protection (docs/Resilience.md).

Three pillars, all strictly host-side (compiled programs are pinned
byte-identical by ANALYSIS_BASELINE.json / PERF_COUNTERS.json):

- ``faults``     — a seeded, config-driven fault plan
  (``fault_inject="kv_timeout@round:2,kill@iter:7"``) with named
  injection points threaded through the host seams; inert by default.
- ``supervisor`` — watchdog + restart loop around ``engine.train``
  (``supervise=True``), plus a process-level supervisor that survives
  SIGKILL and true hangs, and KV heartbeat leases for peer-death
  detection.
- ``breaker``    — consecutive-failure circuit breaker for the serving
  front-ends (503 + Retry-After, half-open probe).
"""
from .breaker import CircuitBreaker
from .faults import (FaultPlan, WatchdogAbort, active_plan, clear_plan,
                     inject, install_plan, parse_plan)
from .supervisor import (ProcessSupervisor, Supervisor, Watchdog,
                         heartbeat_file_callback)

__all__ = [
    "CircuitBreaker", "FaultPlan", "WatchdogAbort", "active_plan",
    "clear_plan", "inject", "install_plan", "parse_plan",
    "ProcessSupervisor", "Supervisor", "Watchdog",
    "heartbeat_file_callback",
]
