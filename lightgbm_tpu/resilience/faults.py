"""Deterministic fault injection: a config-driven plan of named faults.

A fault plan is parsed from the ``fault_inject`` parameter — a comma list
of ``kind@unit:match[:arg]`` tokens, e.g.::

    fault_inject="kv_timeout@round:2,kill@iter:7,serve_error@req:50"

Each token arms ONE fault ``kind`` at a named injection point, firing when
the trigger counter named ``unit`` reaches ``match`` (an integer, or ``*``
for every occurrence). The seams call :func:`inject` with whatever
counters they know (``iteration=7``, ``round=2``, ``path=...``); counters
a seam does not pass are counted per-point by the plan itself (1-based
call index), which is how ``serve_error@req:50`` means "the 50th predict".

The catalog (kind -> injection point -> effect):

====================  ==============  =====================================
``kv_timeout``        ``kv_get``      raise a coordination-service-shaped
                                      DEADLINE_EXCEEDED RuntimeError
``kv_error``          ``kv_get``      raise a transient UNAVAILABLE error
``kv_set_error``      ``kv_set``      raise a transient UNAVAILABLE error
``kv_delay``          ``kv_get``      sleep ``arg`` ms (default 100)
``ckpt_torn``         ``ckpt_write``  truncate the just-written state file
                                      (torn write; manifest sha catches it)
``kill``              ``train_dispatch``  SIGKILL self (``arg=term`` sends
                                      SIGTERM instead)
``hang``              ``train_dispatch``  block for ``arg`` seconds
                                      (default 3600) on the abort event —
                                      a watchdog abort raises WatchdogAbort
``crash``             ``train_dispatch``  raise LightGBMError
``serve_error``       ``serve_predict``   raise LightGBMError
``serve_delay``       ``serve_predict``   sleep ``arg`` ms (default 250)
====================  ==============  =====================================

Determinism: triggers are exact counter matches and the plan's state
(fire counts, call counters) lives in-process, so the same plan against
the same run fires at the same places every time. ``seed`` is carried for
faults that ever need randomized arguments. Everything here is host-side
Python — with no plan installed, :func:`inject` is a two-attribute check,
and no compiled program changes either way.
"""
from __future__ import annotations

import os
import signal
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..log import LightGBMError, Log

# kind -> injection-point name (a seam fires every kind mapped to it)
FAULT_KINDS: Dict[str, str] = {
    "kv_timeout": "kv_get",
    "kv_error": "kv_get",
    "kv_set_error": "kv_set",
    "kv_delay": "kv_get",
    "ckpt_torn": "ckpt_write",
    "kill": "train_dispatch",
    "hang": "train_dispatch",
    "crash": "train_dispatch",
    "serve_error": "serve_predict",
    "serve_delay": "serve_predict",
}

# accepted spellings of the trigger-counter names the seams report
_UNIT_ALIASES = {
    "iter": "iteration", "iterations": "iteration",
    "block": "round", "rounds": "round",
    "req": "request", "requests": "request",
    "snap": "snapshot", "snapshots": "snapshot",
    "call": "calls",
}


class WatchdogAbort(LightGBMError):
    """An injected hang (or other cooperative wait) was aborted by the
    supervisor's watchdog."""


class FaultSpec:
    """One armed fault: ``kind@unit:match[:arg]``."""

    __slots__ = ("kind", "point", "unit", "match", "arg", "fires")

    def __init__(self, kind: str, unit: str, match: Optional[int],
                 arg: Optional[str]):
        self.kind = kind
        self.point = FAULT_KINDS[kind]
        self.unit = unit
        self.match = match          # None == '*' == every occurrence
        self.arg = arg
        self.fires = 0

    def __repr__(self) -> str:
        m = "*" if self.match is None else str(self.match)
        a = ":" + self.arg if self.arg else ""
        return "%s@%s:%s%s" % (self.kind, self.unit, m, a)

    def arg_float(self, default: float) -> float:
        try:
            return float(self.arg) if self.arg else default
        except ValueError:
            return default


class FaultPlan:
    """Parsed ``fault_inject`` plan; owns the per-point call counters."""

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = int(seed)
        self.faults: List[FaultSpec] = []
        self._calls: Dict[str, int] = {}
        self._lock = threading.Lock()
        for token in str(spec).split(","):
            token = token.strip()
            if token:
                self.faults.append(self._parse_token(token))

    @staticmethod
    def _parse_token(token: str) -> FaultSpec:
        if "@" not in token:
            raise LightGBMError(
                "fault_inject token %r: expected kind@unit:match[:arg]"
                % token)
        kind, _, trigger = token.partition("@")
        kind = kind.strip().lower()
        if kind not in FAULT_KINDS:
            raise LightGBMError(
                "fault_inject kind %r unknown (known: %s)"
                % (kind, "/".join(sorted(FAULT_KINDS))))
        parts = trigger.split(":")
        if len(parts) < 2 or not parts[0]:
            raise LightGBMError(
                "fault_inject token %r: trigger must be unit:match[:arg]"
                % token)
        unit = parts[0].strip().lower()
        unit = _UNIT_ALIASES.get(unit, unit)
        raw = parts[1].strip()
        if raw == "*":
            match: Optional[int] = None
        else:
            try:
                match = int(raw)
            except ValueError:
                raise LightGBMError(
                    "fault_inject token %r: match must be an integer or *"
                    % token)
        arg = ":".join(parts[2:]).strip() or None
        return FaultSpec(kind, unit, match, arg)

    # ---------------------------------------------------------------- fire
    def check(self, point: str, counters: Dict) -> List[FaultSpec]:
        """Faults armed at ``point`` whose trigger matches this call.
        Single-shot faults (integer match) fire at most once; ``*`` faults
        fire every time. The per-point call counter (1-based) backs any
        unit the seam did not pass explicitly."""
        with self._lock:
            self._calls[point] = self._calls.get(point, 0) + 1
            ncall = self._calls[point]
            hits = []
            for f in self.faults:
                if f.point != point:
                    continue
                if f.match is not None and f.fires:
                    continue       # single-shot already spent
                value = counters.get(f.unit, ncall)
                if f.match is None or int(value) == f.match:
                    f.fires += 1
                    hits.append(f)
            return hits


_PLAN: Optional[FaultPlan] = None
_ABORT = threading.Event()
_ABORT_REASON: List[str] = []


def parse_plan(spec: str, seed: int = 0) -> FaultPlan:
    """Parse (and validate) a ``fault_inject`` string; raises
    LightGBMError on malformed tokens — config validation calls this."""
    return FaultPlan(spec, seed)


def install_plan(spec: str, seed: int = 0) -> Optional[FaultPlan]:
    """Install the process-global plan. Re-installing an IDENTICAL
    (spec, seed) keeps the existing plan — its fire counts survive an
    in-process supervised restart, so a single-shot ``crash@iter:3``
    fires once, not once per attempt. Empty spec is a no-op (never
    clears a plan someone else installed; use :func:`clear_plan`)."""
    global _PLAN
    if not str(spec).strip():
        return _PLAN
    if _PLAN is not None and _PLAN.spec == spec and _PLAN.seed == int(seed):
        return _PLAN
    _PLAN = FaultPlan(spec, seed)
    Log.warning("fault injection ARMED: %s (seed=%d)",
                ",".join(repr(f) for f in _PLAN.faults), _PLAN.seed)
    return _PLAN


def clear_plan() -> None:
    global _PLAN
    _PLAN = None
    clear_abort()


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


# ------------------------------------------------------------------ abort
def request_abort(reason: str) -> None:
    """Watchdog seam: wake any cooperative wait (injected hangs) and make
    the next inject() raise WatchdogAbort."""
    _ABORT_REASON.append(str(reason))
    _ABORT.set()


def clear_abort() -> None:
    _ABORT.clear()
    del _ABORT_REASON[:]


def abort_event() -> threading.Event:
    return _ABORT


# ------------------------------------------------------------------ inject
def inject(point: str, **counters) -> None:
    """Fire any armed faults at a named injection point. The production
    fast path (no plan, no abort pending) is two attribute checks."""
    if _ABORT.is_set():
        reason = _ABORT_REASON[-1] if _ABORT_REASON else "watchdog"
        raise WatchdogAbort("aborted at fault point %r: %s" % (point, reason))
    plan = _PLAN
    if plan is None:
        return
    for f in plan.check(point, counters):
        _execute(f, point, counters)


def _execute(f: FaultSpec, point: str, counters: Dict) -> None:
    where = ", ".join("%s=%s" % kv for kv in sorted(counters.items())
                      if kv[0] != "path")
    Log.warning("fault %r firing at %s (%s)", repr(f), point, where)
    if f.kind in ("kv_timeout",):
        raise RuntimeError(
            "DEADLINE_EXCEEDED: injected kv timeout (%r at %s)" % (f, where))
    if f.kind in ("kv_error", "kv_set_error"):
        raise RuntimeError(
            "UNAVAILABLE: injected transient kv error (%r at %s)" % (f, where))
    if f.kind == "kv_delay":
        time.sleep(f.arg_float(100.0) / 1000.0)
        return
    if f.kind == "ckpt_torn":
        path = counters.get("path")
        if path and os.path.exists(path):
            size = os.path.getsize(path)
            with open(path, "r+b") as fh:
                fh.truncate(max(size // 2, 1))
            Log.warning("fault ckpt_torn: truncated %s to %d bytes",
                        path, max(size // 2, 1))
        return
    if f.kind == "kill":
        sig = (signal.SIGTERM if (f.arg or "").lower() == "term"
               else signal.SIGKILL)
        Log.warning("fault kill: sending %s to self", sig.name)
        os.kill(os.getpid(), sig)
        # SIGTERM may be latched (checkpoint callback); SIGKILL never
        # returns. Give a latched handler the iteration boundary.
        return
    if f.kind == "hang":
        seconds = f.arg_float(3600.0)
        Log.warning("fault hang: blocking up to %.0fs (abort event wakes "
                    "it)", seconds)
        if _ABORT.wait(timeout=seconds):
            reason = _ABORT_REASON[-1] if _ABORT_REASON else "watchdog"
            raise WatchdogAbort(
                "injected hang at %s aborted: %s" % (point, reason))
        return
    if f.kind == "crash":
        raise LightGBMError("injected crash at %s (%s)" % (point, where))
    if f.kind == "serve_error":
        raise LightGBMError("injected serving fault at %s (%s)"
                            % (point, where))
    if f.kind == "serve_delay":
        time.sleep(f.arg_float(250.0) / 1000.0)
        return
