"""Consecutive-failure circuit breaker for the serving front-ends.

Classic three-state breaker sized for a model server: CLOSED counts
consecutive dispatch failures (client errors don't count — the caller
classifies); at ``failure_threshold`` it OPENS and every request is
rejected fast with a Retry-After hint for ``cooldown_s``; the first
request after the cooldown is admitted as a HALF-OPEN probe — success
closes the breaker, failure re-opens it for another full cooldown.
Shedding load this way keeps a wedged engine (bad model roll, device
loss) from stacking up threads behind futures that will never resolve.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """Thread-safe; ``failure_threshold=0`` disables (always allows)."""

    def __init__(self, failure_threshold: int = 5, cooldown_s: float = 5.0):
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = max(float(cooldown_s), 0.0)
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_out = False
        self._trips = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def trips(self) -> int:
        with self._lock:
            return self._trips

    def allow(self) -> bool:
        """May this request proceed? While OPEN, the first call after the
        cooldown transitions to HALF_OPEN and is admitted as the single
        probe; further calls are rejected until the probe reports."""
        if self.failure_threshold <= 0:
            return True
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if time.monotonic() - self._opened_at >= self.cooldown_s:
                    self._state = HALF_OPEN
                    self._probe_out = True
                    return True
                return False
            # HALF_OPEN: exactly one probe in flight
            if not self._probe_out:
                self._probe_out = True
                return True
            return False

    def retry_after_s(self) -> float:
        """Seconds until the next probe would be admitted (the 503
        Retry-After value); 0 when not rejecting."""
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(self.cooldown_s - (time.monotonic() - self._opened_at),
                       0.0)

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._probe_out = False
            self._state = CLOSED

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            if self._state == HALF_OPEN or (
                    self.failure_threshold > 0
                    and self._consecutive >= self.failure_threshold):
                if self._state != OPEN:
                    self._trips += 1
                self._state = OPEN
                self._opened_at = time.monotonic()
                self._probe_out = False

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self._state,
                    "consecutive_failures": self._consecutive,
                    "trips": self._trips,
                    "retry_after_s": round(
                        max(self.cooldown_s
                            - (time.monotonic() - self._opened_at), 0.0), 3)
                    if self._state == OPEN else 0.0}
