"""Training and cross-validation entry points.

Reference: python-package/lightgbm/engine.py — ``train`` (:19, boost loop
:211-236) and ``cv`` (:336, stratified folds :270, aggregation :325). Same
semantics: callbacks run before/after each iteration, ``EarlyStopException``
unwinds and truncates to best_iteration, ``evals_result`` records history.
"""
from __future__ import annotations

import collections
import copy
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from . import callback
from .basic import Booster, Dataset, _InnerPredictor
from .config import Config
from .log import Log, LightGBMError


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          fobj: Optional[Callable] = None,
          feval: Optional[Callable] = None,
          init_model: Optional[Union[str, Booster]] = None,
          feature_name: Union[str, List[str]] = "auto",
          categorical_feature: Union[str, List] = "auto",
          early_stopping_rounds: Optional[int] = None,
          evals_result: Optional[Dict] = None,
          verbose_eval: Union[bool, int] = True,
          learning_rates: Optional[Union[List[float], Callable]] = None,
          keep_training_booster: bool = False,
          callbacks: Optional[List[Callable]] = None,
          resume_from: Optional[str] = None,
          supervise: Optional[bool] = None) -> Booster:
    """engine.py:19 — train with the reference's full signature, plus
    ``resume_from``: a lightgbm_tpu.checkpoint directory to continue from
    (``num_boost_round`` stays the TOTAL target — a run checkpointed at
    iteration k trains the remaining ``num_boost_round - k`` rounds and
    produces a model byte-identical to the uninterrupted run;
    docs/Checkpointing.md), and ``supervise`` (or ``supervise=true`` in
    params): run under the resilience supervisor — a watchdog over the
    per-iteration heartbeat (``supervise_hang_timeout_s``; warmup-aware
    so a slow first compile never false-fires) plus a restart loop that
    flight-dumps on crash and auto-resumes from the newest valid
    checkpoint under bounded exponential backoff, byte-identical to the
    uninterrupted run (docs/Resilience.md)."""
    if supervise is None:
        raw = (params or {}).get("supervise",
                                 (params or {}).get("supervised", False))
        supervise = str(raw).strip().lower() in ("true", "1", "yes", "+")
    if supervise:
        return _train_supervised(
            params, train_set, num_boost_round, valid_sets, valid_names,
            fobj, feval, init_model, feature_name, categorical_feature,
            early_stopping_rounds, evals_result, verbose_eval,
            learning_rates, keep_training_booster, callbacks, resume_from)
    return _train_once(
        params, train_set, num_boost_round, valid_sets, valid_names, fobj,
        feval, init_model, feature_name, categorical_feature,
        early_stopping_rounds, evals_result, verbose_eval, learning_rates,
        keep_training_booster, callbacks, resume_from)


def _train_supervised(params, train_set, num_boost_round, valid_sets,
                      valid_names, fobj, feval, init_model, feature_name,
                      categorical_feature, early_stopping_rounds,
                      evals_result, verbose_eval, learning_rates,
                      keep_training_booster, callbacks,
                      resume_from) -> Booster:
    from .resilience.supervisor import Supervisor, heartbeat_file_callback
    cfg = Config(copy.deepcopy(params) if params else {})
    sup = Supervisor(cfg.checkpoint_dir,
                     max_restarts=cfg.supervise_max_restarts,
                     backoff_s=cfg.supervise_backoff_s,
                     backoff_max_s=cfg.supervise_backoff_max_s,
                     hang_timeout_s=cfg.supervise_hang_timeout_s,
                     warmup_grace_s=cfg.supervise_warmup_grace_s)

    def attempt(resume, watchdog):
        cbs = list(callbacks or [])
        if watchdog is not None:
            cbs.append(watchdog.callback())
        if cfg.supervise_heartbeat_file:
            cbs.append(heartbeat_file_callback(cfg.supervise_heartbeat_file))
        return _train_once(
            params, train_set, num_boost_round, valid_sets, valid_names,
            fobj, feval, init_model, feature_name, categorical_feature,
            early_stopping_rounds, evals_result, verbose_eval,
            learning_rates, keep_training_booster, cbs,
            resume if resume is not None else resume_from)

    return sup.run(attempt)


def _train_once(params: Dict[str, Any], train_set: Dataset,
                num_boost_round: int = 100,
                valid_sets: Optional[List[Dataset]] = None,
                valid_names: Optional[List[str]] = None,
                fobj: Optional[Callable] = None,
                feval: Optional[Callable] = None,
                init_model: Optional[Union[str, Booster]] = None,
                feature_name: Union[str, List[str]] = "auto",
                categorical_feature: Union[str, List] = "auto",
                early_stopping_rounds: Optional[int] = None,
                evals_result: Optional[Dict] = None,
                verbose_eval: Union[bool, int] = True,
                learning_rates: Optional[Union[List[float], Callable]] = None,
                keep_training_booster: bool = False,
                callbacks: Optional[List[Callable]] = None,
                resume_from: Optional[str] = None) -> Booster:
    params = copy.deepcopy(params) if params else {}
    # resolve num_boost_round aliases out of params (engine.py:96-107)
    for alias in ("num_boost_round", "num_iterations", "num_iteration",
                  "num_tree", "num_trees", "num_round", "num_rounds",
                  "n_estimators"):
        if alias in params:
            num_boost_round = int(params.pop(alias))
    for alias in ("early_stopping_round", "early_stopping_rounds",
                  "early_stopping"):
        if alias in params and params[alias] is not None:
            early_stopping_rounds = int(params.pop(alias))
    if fobj is not None:
        params["objective"] = "none"
    if feature_name != "auto":
        train_set.feature_name = feature_name
    if categorical_feature != "auto":
        train_set.categorical_feature = categorical_feature

    predictor = None
    if isinstance(init_model, str):
        predictor = _InnerPredictor(Booster(model_file=init_model))
    elif isinstance(init_model, Booster):
        predictor = _InnerPredictor(init_model)
    if predictor is not None:
        train_set._set_predictor(predictor)

    if not train_set.params:
        train_set.params = params
    booster = Booster(params=params, train_set=train_set)
    is_valid_contain_train = False
    train_data_name = "training"
    if valid_sets is not None:
        if isinstance(valid_sets, Dataset):
            valid_sets = [valid_sets]
        name_valid_sets = valid_names or \
            ["valid_%d" % i for i in range(len(valid_sets))]
        for i, vs in enumerate(valid_sets):
            if vs is train_set:
                is_valid_contain_train = True
                train_data_name = name_valid_sets[i]
                continue
            if vs.reference is None:
                vs.reference = train_set
            booster.add_valid(vs, name_valid_sets[i])
    booster.train_set_name = train_data_name

    # a list, not a set: equal-`order` callbacks must run in a deterministic
    # (registration) order — Python's stable sort preserves list order
    cbs = list(callbacks or [])
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.append(callback.early_stopping(
            early_stopping_rounds,
            first_metric_only=bool(params.get("first_metric_only", False))))
    if verbose_eval is True:
        cbs.append(callback.print_evaluation())
    elif isinstance(verbose_eval, int) and verbose_eval:
        cbs.append(callback.print_evaluation(verbose_eval))
    if evals_result is not None:
        cbs.append(callback.record_evaluation(evals_result))
    # every evaluated iteration also lands in the process metrics registry
    # (lgbm_eval_metric gauges) for the stats endpoint / cluster federation;
    # only_consumes_evals, so eval-free runs still fuse on device
    if not any(isinstance(c, callback._ExportEvalMetrics) for c in cbs):
        cbs.append(callback.export_eval_metrics())
    if learning_rates is not None:
        cbs.append(callback.reset_parameter(learning_rate=learning_rates))
    # checkpoint_dir in params auto-attaches the checkpoint callback (the
    # CLI's config-driven path; Python users can pass callback.checkpoint
    # explicitly instead)
    if booster.config.checkpoint_dir and \
            not any(getattr(c, "is_checkpoint", False) for c in cbs):
        cbs.append(callback.checkpoint(
            booster.config.checkpoint_dir,
            period=booster.config.checkpoint_period,
            keep_last_n=booster.config.checkpoint_keep))
    cbs_before = [c for c in cbs if getattr(c, "before_iteration", False)]
    cbs_after = [c for c in cbs if not getattr(c, "before_iteration", False)]
    cbs_before.sort(key=lambda c: getattr(c, "order", 0))
    cbs_after.sort(key=lambda c: getattr(c, "order", 0))
    # the checkpoint callback reads loop-level state (early stopping) off
    # the booster when it snapshots
    booster._callbacks = cbs_before + cbs_after

    # resume (lightgbm_tpu.checkpoint): restore driver + callback state,
    # shrink the remaining-round budget to the original total
    resumed = False
    if resume_from is None and booster.config.resume:
        resume_from = booster.config.resume
    if resume_from:
        from . import checkpoint as ckpt_mod
        handle = ckpt_mod.load_latest(resume_from)
        if handle is None:
            Log.info("resume_from=%s: no checkpoint found; starting fresh",
                     resume_from)
        else:
            completed = ckpt_mod.restore(booster, handle,
                                         cbs_before + cbs_after)
            num_boost_round = max(num_boost_round - completed, 0)
            resumed = True

    # boosting loop (engine.py:211-246); a crash anywhere in it triggers
    # a flight-recorder dump (when armed) and the dump path rides the
    # exception for the supervisor / operator
    init_iteration = booster.current_iteration
    finished_early = False
    evaluation_result_list = []
    try:
        if valid_sets is None and fobj is None and not cbs_before and \
                not resumed and \
                all(getattr(c, "only_consumes_evals", False)
                    for c in cbs_after):
            # nothing needs the host between iterations (eval-display
            # callbacks are no-ops with no valid sets): fuse the whole
            # loop into on-device blocks (GBDT.train_many)
            booster._impl.train_many(num_boost_round)
            num_boost_round = 0
        for i in range(init_iteration, init_iteration + num_boost_round):
            for cb in cbs_before:
                cb(callback.CallbackEnv(
                    model=booster, params=params, iteration=i,
                    begin_iteration=init_iteration,
                    end_iteration=init_iteration + num_boost_round,
                    evaluation_result_list=None))
            stopped = booster.update(fobj=fobj)

            evaluation_result_list = []
            if valid_sets is not None or cbs_after:
                if is_valid_contain_train:
                    evaluation_result_list.extend(booster.eval_train(feval))
                if valid_sets is not None and booster._valid_sets:
                    evaluation_result_list.extend(booster.eval_valid(feval))
            try:
                for cb in cbs_after:
                    cb(callback.CallbackEnv(
                        model=booster, params=params, iteration=i,
                        begin_iteration=init_iteration,
                        end_iteration=init_iteration + num_boost_round,
                        evaluation_result_list=evaluation_result_list))
            except callback.EarlyStopException as earlyStopException:
                booster.best_iteration = earlyStopException.best_iteration + 1
                evaluation_result_list = earlyStopException.best_score
                finished_early = True
                break
            if stopped:
                break
    except callback.EarlyStopException:
        raise
    except Exception as train_err:
        obs = getattr(booster._impl, "obs", None)
        if obs is not None and not getattr(train_err,
                                           "flight_dump_path", None):
            try:
                dump = obs.crash_flush("train-exception: %s: %s"
                                       % (type(train_err).__name__,
                                          train_err))
                if dump:
                    train_err.flight_dump_path = dump
            except Exception:   # the dump must never mask the crash
                pass
        raise

    booster.best_score = collections.defaultdict(dict)
    for dataset_name, eval_name, score, _ in (evaluation_result_list or []):
        booster.best_score[dataset_name][eval_name] = score
    if booster.best_iteration <= 0:
        booster.best_iteration = booster.current_iteration
    obs = getattr(booster._impl, "obs", None)
    if obs is not None and obs.enabled:
        # flush the event stream / close any open Perfetto window; the
        # stats endpoint stays up for post-train scrapes
        obs.finish()
    return booster


class CVBooster:
    """Ensemble of per-fold boosters returned by cv(return_cvbooster=True)."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, booster: Booster) -> None:
        self.boosters.append(booster)


def _make_n_folds(full_data: Dataset, folds, nfold: int, params, seed: int,
                  stratified: bool, shuffle: bool):
    """engine.py:270-325: fold construction (sklearn-style if available)."""
    full_data = full_data.construct()
    num_data = full_data.num_data()
    if folds is not None:
        if not hasattr(folds, "__iter__") and hasattr(folds, "split"):
            group = full_data.get_group()
            group_info = None if group is None else np.asarray(group, np.int64)
            flattened = (np.repeat(range(len(group_info)), repeats=group_info)
                         if group_info is not None else None)
            folds = folds.split(X=np.zeros(num_data),
                                y=full_data.get_label(), groups=flattened)
        fold_list = list(folds)
    else:
        rng = np.random.RandomState(seed)
        group = full_data.get_group()
        if group is not None:
            # group-aware folds: whole queries assigned to folds
            num_group = len(group)
            gidx = np.arange(num_group)
            if shuffle:
                rng.shuffle(gidx)
            boundaries = np.concatenate([[0], np.cumsum(np.asarray(group))])
            fold_list = []
            for k in range(nfold):
                test_g = gidx[k::nfold]
                test_idx = np.concatenate(
                    [np.arange(boundaries[g], boundaries[g + 1])
                     for g in test_g]) if len(test_g) else np.array([], np.int64)
                mask = np.ones(num_data, bool)
                mask[test_idx] = False
                fold_list.append((np.where(mask)[0], test_idx))
        elif stratified:
            label = np.asarray(full_data.get_label())
            classes = np.unique(label)
            test_folds = [[] for _ in range(nfold)]
            for c in classes:
                cls_idx = np.where(label == c)[0]
                if shuffle:
                    rng.shuffle(cls_idx)
                for k in range(nfold):
                    test_folds[k].append(cls_idx[k::nfold])
            fold_list = []
            for k in range(nfold):
                test_idx = np.sort(np.concatenate(test_folds[k]))
                mask = np.ones(num_data, bool)
                mask[test_idx] = False
                fold_list.append((np.where(mask)[0], test_idx))
        else:
            idx = np.arange(num_data)
            if shuffle:
                rng.shuffle(idx)
            fold_list = []
            for k in range(nfold):
                test_idx = np.sort(idx[k::nfold])
                mask = np.ones(num_data, bool)
                mask[test_idx] = False
                fold_list.append((np.where(mask)[0], test_idx))
    return fold_list


def _agg_cv_result(raw_results):
    """engine.py:325-334: aggregate across folds -> mean/std per metric."""
    cvmap = collections.OrderedDict()
    metric_type = {}
    for one_result in raw_results:
        for one_line in one_result:
            key = one_line[0] + " " + one_line[1]
            metric_type[key] = one_line[3]
            cvmap.setdefault(key, [])
            cvmap[key].append(one_line[2])
    return [("cv_agg", k, float(np.mean(v)), metric_type[k], float(np.std(v)))
            for k, v in cvmap.items()]


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True,
       shuffle: bool = True, metrics=None, fobj=None, feval=None,
       init_model=None, feature_name="auto", categorical_feature="auto",
       early_stopping_rounds: Optional[int] = None, fpreproc=None,
       verbose_eval=None, show_stdv: bool = True, seed: int = 0,
       callbacks=None, eval_train_metric: bool = False,
       return_cvbooster: bool = False):
    """engine.py:336 — k-fold cross-validation."""
    params = copy.deepcopy(params) if params else {}
    for alias in ("num_boost_round", "num_iterations", "num_iteration",
                  "num_tree", "num_trees", "num_round", "num_rounds",
                  "n_estimators"):
        if alias in params:
            num_boost_round = int(params.pop(alias))
    for alias in ("early_stopping_round", "early_stopping_rounds",
                  "early_stopping"):
        if alias in params and params[alias] is not None:
            early_stopping_rounds = int(params.pop(alias))
    if fobj is not None:
        params["objective"] = "none"
    if metrics is not None:
        params["metric"] = metrics
    if isinstance(params.get("metric"), str):
        params["metric"] = [params["metric"]]

    train_set = train_set.construct() if train_set._binned is None else train_set
    if params.get("objective") not in ("binary", "multiclass",
                                       "multiclassova") and folds is None:
        stratified = False
    folds_list = _make_n_folds(train_set, folds, nfold, params, seed,
                               stratified, shuffle)

    # build per-fold boosters
    cvbooster = CVBooster()
    raw_X = _raw_matrix(train_set)
    label = np.asarray(train_set.get_label())
    weight = train_set.get_weight()
    for train_idx, test_idx in folds_list:
        dtrain = Dataset(raw_X[train_idx], label=label[train_idx],
                         weight=None if weight is None else
                         np.asarray(weight)[train_idx],
                         params=dict(params),
                         categorical_feature=train_set.categorical_feature)
        dtest = dtrain.create_valid(
            raw_X[test_idx], label=label[test_idx],
            weight=None if weight is None else np.asarray(weight)[test_idx])
        if fpreproc is not None:
            dtrain, dtest, fold_params = fpreproc(dtrain, dtest, dict(params))
        else:
            fold_params = params
        bst = Booster(params=dict(fold_params), train_set=dtrain)
        bst.add_valid(dtest, "valid")
        cvbooster.append(bst)

    results = collections.defaultdict(list)
    # list, not set: deterministic order among equal-`order` callbacks
    cbs = list(callbacks or [])
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.append(callback.early_stopping(early_stopping_rounds,
                                           verbose=False))
    if verbose_eval is True:
        cbs.append(callback.print_evaluation(show_stdv=show_stdv))
    elif isinstance(verbose_eval, int) and verbose_eval:
        cbs.append(callback.print_evaluation(verbose_eval, show_stdv))
    cbs_before = sorted((c for c in cbs if getattr(c, "before_iteration", False)),
                        key=lambda c: getattr(c, "order", 0))
    cbs_after = sorted((c for c in cbs if not getattr(c, "before_iteration", False)),
                       key=lambda c: getattr(c, "order", 0))

    for i in range(num_boost_round):
        fold_results = []
        for bst in cvbooster.boosters:
            for cb in cbs_before:
                cb(callback.CallbackEnv(
                    model=bst, params=params, iteration=i, begin_iteration=0,
                    end_iteration=num_boost_round,
                    evaluation_result_list=None))
            bst.update(fobj=fobj)
            one = []
            if eval_train_metric:
                one.extend(bst.eval_train(feval))
            one.extend(bst.eval_valid(feval))
            fold_results.append(one)
        agg = _agg_cv_result(fold_results)
        for _, key, mean, _, std in agg:
            results[key + "-mean"].append(mean)
            results[key + "-stdv"].append(std)
        try:
            for cb in cbs_after:
                cb(callback.CallbackEnv(
                    model=cvbooster, params=params, iteration=i,
                    begin_iteration=0, end_iteration=num_boost_round,
                    evaluation_result_list=agg))
        except callback.EarlyStopException as e:
            cvbooster.best_iteration = e.best_iteration + 1
            for k in list(results):
                results[k] = results[k][:cvbooster.best_iteration]
            break

    if return_cvbooster:
        results["cvbooster"] = cvbooster
    return dict(results)


def _raw_matrix(ds: Dataset) -> np.ndarray:
    """Raw feature matrix for fold slicing; requires raw data retained."""
    if isinstance(ds.data, str):
        from .io.parser import parse_file
        X, _, _ = parse_file(ds.data, has_header=Config(ds.params).header,
                             label_column=Config(ds.params).label_column)
        return X
    from .basic import _to_2d_float
    return _to_2d_float(ds.data)
