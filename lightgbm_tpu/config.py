"""Configuration system: LightGBM-compatible parameter names, aliases, defaults.

TPU-native re-design of the reference config (include/LightGBM/config.h:27-855,
src/io/config.cpp:15-279, src/io/config_auto.cpp). The reference generates its
setters from docs/Parameters.rst; here a single table of (name, type, default,
aliases) drives parsing, alias resolution and validation. LightGBM parameter
names are a de-facto standard, so the Python API accepts any alias the
reference accepts (config.h:857-865 ParameterAlias::KeyAliasTransform).
"""
from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Tuple

from .log import Log, LightGBMError

# (canonical_name, python_type, default, [aliases])
# Mirrors config.h params; list type uses comma-separated parsing like the
# reference's Common::StringToArray.
_PARAMS: List[Tuple[str, type, Any, List[str]]] = [
    # ---- core (config.h:100-240) ----
    ("config", str, "", ["config_file"]),
    ("task", str, "train", ["task_type"]),
    ("objective", str, "regression",
     ["objective_type", "app", "application", "loss"]),
    ("boosting", str, "gbdt", ["boosting_type", "boost"]),
    ("data", str, "", ["train", "train_data", "train_data_file", "data_filename"]),
    ("valid", list, [], ["test", "valid_data", "valid_data_file", "test_data",
                         "test_data_file", "valid_filenames"]),
    ("num_iterations", int, 100,
     ["num_iteration", "n_iter", "num_tree", "num_trees", "num_round",
      "num_rounds", "num_boost_round", "n_estimators", "max_iter"]),
    ("learning_rate", float, 0.1, ["shrinkage_rate", "eta"]),
    ("num_leaves", int, 31, ["num_leaf", "max_leaves", "max_leaf", "max_leaf_nodes"]),
    ("tree_learner", str, "serial", ["tree", "tree_type", "tree_learner_type"]),
    ("num_threads", int, 0,
     ["num_thread", "nthread", "nthreads", "n_jobs"]),
    ("device_type", str, "tpu", ["device"]),
    ("seed", int, 0, ["random_seed", "random_state"]),
    # ---- learning control (config.h:241-470) ----
    ("max_depth", int, -1, []),
    ("min_data_in_leaf", int, 20, ["min_data_per_leaf", "min_data", "min_child_samples", "min_samples_leaf"]),
    ("min_sum_hessian_in_leaf", float, 1e-3,
     ["min_sum_hessian_per_leaf", "min_sum_hessian", "min_hessian", "min_child_weight"]),
    ("bagging_fraction", float, 1.0, ["sub_row", "subsample", "bagging"]),
    ("bagging_freq", int, 0, ["subsample_freq"]),
    ("bagging_seed", int, 3, ["bagging_fraction_seed"]),
    ("feature_fraction", float, 1.0, ["sub_feature", "colsample_bytree"]),
    ("feature_fraction_seed", int, 2, []),
    ("early_stopping_round", int, 0,
     ["early_stopping_rounds", "early_stopping", "n_iter_no_change"]),
    ("first_metric_only", bool, False, []),
    ("max_delta_step", float, 0.0, ["max_tree_output", "max_leaf_output"]),
    ("lambda_l1", float, 0.0, ["reg_alpha", "l1_regularization"]),
    ("lambda_l2", float, 0.0, ["reg_lambda", "lambda", "l2_regularization"]),
    ("min_gain_to_split", float, 0.0, ["min_split_gain"]),
    # DART (config.h:300-340)
    ("drop_rate", float, 0.1, ["rate_drop"]),
    ("max_drop", int, 50, []),
    ("skip_drop", float, 0.5, []),
    ("xgboost_dart_mode", bool, False, []),
    ("uniform_drop", bool, False, []),
    ("drop_seed", int, 4, []),
    # GOSS
    ("top_rate", float, 0.2, []),
    ("other_rate", float, 0.1, []),
    # categorical
    ("min_data_per_group", int, 100, []),
    ("max_cat_threshold", int, 32, []),
    ("cat_l2", float, 10.0, []),
    ("cat_smooth", float, 10.0, []),
    ("max_cat_to_onehot", int, 4, []),
    # voting-parallel candidate count (config.h:349 top_k; PV-Tree,
    # voting_parallel_tree_learner.cpp): with tree_learner=voting each
    # device nominates its local top_k features per frontier slot and
    # only the <= 2*top_k vote-elected features' histogram columns are
    # exchanged per wave — comm O(2*top_k*B) instead of O(F*B). Larger is
    # more accurate (top_k >= num_features degenerates to the exact
    # data-parallel search), smaller is cheaper. Must be >= 1.
    ("top_k", int, 20, ["topk"]),
    ("monotone_constraints", list, [], ["mc", "monotone_constraint"]),
    ("feature_contri", list, [], ["feature_contrib", "fc", "fp", "feature_penalty"]),
    ("forcedsplits_filename", str, "", ["fs", "forced_splits_filename",
                                        "forced_splits_file", "forced_splits"]),
    ("refit_decay_rate", float, 0.9, []),
    ("cegb_tradeoff", float, 1.0, []),
    ("cegb_penalty_split", float, 0.0, []),
    ("cegb_penalty_feature_lazy", list, [], []),
    ("cegb_penalty_feature_coupled", list, [], []),
    # ---- IO (config.h:400-600) ----
    ("verbosity", int, 1, ["verbose"]),
    ("max_bin", int, 255, []),
    ("min_data_in_bin", int, 3, []),
    ("bin_construct_sample_cnt", int, 200000, ["subsample_for_bin"]),
    ("histogram_pool_size", float, -1.0, ["hist_pool_size"]),
    ("data_random_seed", int, 1, ["data_seed"]),
    ("output_model", str, "LightGBM_model.txt", ["model_output", "model_out"]),
    ("snapshot_freq", int, -1, ["save_period"]),
    # preemption-safe checkpoints (lightgbm_tpu.checkpoint,
    # docs/Checkpointing.md): full-training-state snapshots + exact resume
    ("checkpoint_dir", str, "", ["checkpoint_directory", "checkpoint_path"]),
    ("checkpoint_period", int, 1, ["checkpoint_freq"]),
    ("checkpoint_keep", int, 3, ["checkpoint_keep_last_n"]),
    ("resume", str, "", ["resume_from", "resume_dir"]),
    ("input_model", str, "", ["model_input", "model_in"]),
    ("output_result", str, "LightGBM_predict_result.txt",
     ["predict_result", "prediction_result", "predict_name", "prediction_name",
      "pred_name", "name_pred"]),
    ("initscore_filename", str, "", ["init_score_filename", "init_score_file",
                                     "init_score", "input_init_score"]),
    ("valid_data_initscores", list, [], ["valid_data_init_scores",
                                         "valid_init_score_file", "valid_init_score"]),
    ("pre_partition", bool, False, ["is_pre_partition"]),
    ("enable_bundle", bool, True, ["is_enable_bundle", "bundle"]),
    # pack pairs of <=16-bin features into one stored column via joint
    # encoding (the Dense4bitsBin analog, dense_nbits_bin.hpp) — halves
    # both storage bytes and histogram columns for small-bin features
    ("enable_nbit_packing", bool, True, ["nbit_packing"]),
    ("max_conflict_rate", float, 0.0, []),
    ("is_enable_sparse", bool, True, ["is_sparse", "enable_sparse", "sparse"]),
    ("sparse_threshold", float, 0.8, []),
    ("use_missing", bool, True, []),
    ("zero_as_missing", bool, False, []),
    ("two_round", bool, False, ["two_round_loading", "use_two_round_loading"]),
    ("save_binary", bool, False, ["is_save_binary", "is_save_binary_file"]),
    ("header", bool, False, ["has_header"]),
    ("label_column", str, "", ["label"]),
    ("weight_column", str, "", ["weight"]),
    ("group_column", str, "", ["group", "group_id", "query_column", "query", "query_id"]),
    ("ignore_column", str, "", ["ignore_feature", "blacklist"]),
    ("categorical_feature", str, "", ["cat_feature", "categorical_column", "cat_column"]),
    ("predict_raw_score", bool, False, ["is_predict_raw_score", "predict_rawscore", "raw_score"]),
    ("predict_leaf_index", bool, False, ["is_predict_leaf_index", "leaf_index"]),
    ("predict_contrib", bool, False, ["is_predict_contrib", "contrib"]),
    ("num_iteration_predict", int, -1, []),
    ("pred_early_stop", bool, False, []),
    ("pred_early_stop_freq", int, 10, []),
    ("pred_early_stop_margin", float, 10.0, []),
    ("convert_model_language", str, "", []),
    ("convert_model", str, "gbdt_prediction.cpp", ["convert_model_file"]),
    # ---- objective (config.h:600-740) ----
    ("num_class", int, 1, ["num_classes"]),
    ("is_unbalance", bool, False, ["unbalance", "unbalanced_sets"]),
    ("scale_pos_weight", float, 1.0, []),
    ("sigmoid", float, 1.0, []),
    ("boost_from_average", bool, True, []),
    ("reg_sqrt", bool, False, []),
    ("alpha", float, 0.9, []),
    ("fair_c", float, 1.0, []),
    ("poisson_max_delta_step", float, 0.7, []),
    ("tweedie_variance_power", float, 1.5, []),
    ("max_position", int, 20, []),
    ("label_gain", list, [], []),
    # ---- metric (config.h:700-760) ----
    ("metric", list, [], ["metrics", "metric_types"]),
    ("metric_freq", int, 1, ["output_freq"]),
    ("is_provide_training_metric", bool, False,
     ["training_metric", "is_training_metric", "train_metric"]),
    ("eval_at", list, [1, 2, 3, 4, 5],
     ["ndcg_eval_at", "ndcg_at", "map_eval_at", "map_at"]),
    # ---- network (config.h:740-770) ----
    ("num_machines", int, 1, ["num_machine"]),
    ("local_listen_port", int, 12400, ["local_port", "port"]),
    ("time_out", int, 120, []),
    ("machine_list_filename", str, "", ["machine_list_file", "machine_list", "mlist"]),
    ("machines", str, "", ["workers", "nodes"]),
    # ---- device (config.h:770-790); gpu_* accepted for compat, unused on TPU ----
    ("gpu_platform_id", int, -1, []),
    ("gpu_device_id", int, -1, []),
    ("gpu_use_dp", bool, False, []),          # true -> f64 histogram accum
    #   (reference double-precision histograms, config.h:784; enables jax
    #   x64 mode — ~2x memory, slower on TPU, tightest reference parity)
    # ---- TPU-specific extensions (no reference counterpart) ----
    ("tpu_hist_dtype", str, "float32", []),   # histogram accumulation dtype
    # histogram kernel: auto (pallas on TPU, scatter on CPU) | pallas |
    # pallas_highest (full-f32 MXU contraction, ~2x cost) | matmul |
    # scatter | pallas_interpret; f64 mode routes off the f32-only pallas
    # — the GPUTreeLearner device-path dispatch analog (tree_learner.cpp:9-31)
    ("tpu_hist_impl", str, "auto", []),
    # device bin-matrix packing (core/binpack.py; docs/Performance.md
    # "Packed bins & fused wave"): none = uint8 [N,C] columns on device;
    # byte = the same 8-bit codes packed 4-per-int32 word (lane-friendly
    # unpack inside each histogram impl; bitwise-identical trees);
    # nibble = byte packing PLUS pair-coding every two <=16-bin features
    # into one joint 8-bit column (extends enable_nbit_packing's cap from
    # max_bin to 256) — halves stored columns, host->device transfer, and
    # histogram scatter traffic (>=1.5x costmodel bytes), trees
    # structure-identical to unpacked. auto = none in-memory on CPU,
    # byte for streamed ingest, nibble on TPU-shaped backends when every
    # candidate feature fits 16 bins (byte otherwise).
    ("tpu_bin_packing", str, "auto", ["bin_packing"]),
    ("tpu_donate_buffers", bool, True, []),   # donate score/state buffers under jit
    ("mesh_shape", list, [], []),             # e.g. [8] / [4,2]; empty = all devices on one axis
    # growth strategy: exact = reference leaf-wise best-first; batched =
    # split the top-tree_batch_splits frontier leaves per sequential step
    # (approximate best-first; amortizes TPU per-split latency — the same
    # accuracy stance as the reference GPU learner's documented deviations,
    # GPU-Performance.rst:132-139; core/grow_batched.py); frontier =
    # split EVERY positive-gain frontier leaf per step with ONE batched
    # histogram sweep per wave — O(depth) dataset sweeps per tree instead
    # of O(num_leaves) (core/grow_frontier.py).
    ("tree_growth", str, "exact", ["growth_mode", "tree_grow_mode"]),
    ("tree_batch_splits", int, 16, []),
    # frontier wave-width bucketing (core/grow_frontier.py): specialize
    # each wave at the smallest pow-2 slot count covering the live
    # frontier instead of always num_leaves - 1 — hist FLOPs and psum
    # payload track 2^depth on early waves, structure unchanged. false
    # pins every wave at the fixed maximum width (debug / A-B runs).
    ("tpu_frontier_bucketing", bool, True, ["frontier_bucketing"]),
    # frontier data-parallel reduce-scatter schedule (parallel/learners.py
    # DataRSLearner): replace the per-wave full-histogram psum with a
    # tiled psum_scatter over the feature axis + a small all_gather/argmax
    # election of packed best-split records — per-device wave comm and
    # hist-pool memory drop to ~1/P. Committed trees are identical to the
    # psum schedule (contiguous rank-ordered feature blocks preserve the
    # first-max tie-break). false restores the full-psum wave (debug /
    # A-B runs). Only applies to tree_learner=data + tree_growth=frontier.
    ("tpu_frontier_rs", bool, True, ["frontier_rs"]),
    # persistent XLA compilation cache (jax_compilation_cache_dir):
    # compiled executables are written here and reloaded by later
    # processes, so warm starts skip backend compilation entirely —
    # profiling.enable_compile_cache wires it before the first compile
    # and counts hits/misses. Empty = off (jax default).
    ("compile_cache_dir", str, "", ["compilation_cache_dir",
                                    "jax_compilation_cache_dir"]),
    # batched growth: pack active rows so dead row tiles skip the slot
    # kernel's compute (cost ~ split-leaf rows, not N); opt-in until
    # measured on chip
    ("tpu_batched_pack", bool, False, []),
    # partitioned batched growth (core/grow_batched_part.py): rows kept
    # physically grouped by leaf so per-step kernel cost tracks the
    # splitting leaves' rows. auto currently = off — the per-step row
    # permutation measured slower than the kernel savings on chip
    # (docs/Performance.md); true forces it on for experiments.
    ("tpu_batched_part", str, "auto", []),
    # out-of-core streamed training (lightgbm_tpu.stream;
    # docs/OutOfCore.md): > 0 caps the rows of each host-resident binned
    # chunk — the dataset is ingested two-round (sample-based bin
    # boundaries, per-chunk quantize) and trained with per-chunk wave
    # histograms summed before split finding (additive, so the grown
    # structure matches single-shot at the same boundaries). 0 = off
    # (whole dataset in one device allocation). Requires
    # tree_growth=frontier and boosting gbdt/goss; single device only.
    ("data_stream_chunk_rows", int, 0, ["stream_chunk_rows"]),
    # chunks kept in flight ahead of the sweep cursor: each is
    # jax.device_put BEFORE the previous chunk's histogram kernel needs
    # it, so host->device transfer overlaps device compute
    ("data_stream_prefetch", int, 2, ["stream_prefetch"]),
    # rows per chunk of the partitioned growth loops (core/partition.py).
    # 0 = auto: 4096 on TPU-shaped backends (measured round-4 winner:
    # most leaves are far smaller than the old 16384 default, whose
    # single-trip padded work dominated the per-split floor), 16384
    # elsewhere. Larger chunks measured strictly worse on chip (65536 ->
    # 0.59x, 262144 -> 0.22x the 16384 throughput).
    ("tpu_row_chunk", int, 0, []),
    # ---- serving (lightgbm_tpu.serving; task=serve) ----
    ("serve_host", str, "127.0.0.1", []),
    ("serve_port", int, 8080, []),            # 0 = OS-assigned (tests)
    ("serve_max_batch", int, 4096, []),       # padded-batch cap / chunk size
    ("serve_min_bucket", int, 16, []),        # smallest padded batch
    ("serve_deadline_ms", float, 2.0, []),    # micro-batch coalesce window
    ("serve_num_devices", int, 1, []),        # 0 = all local devices
    ("serve_stdin", bool, False, []),         # JSON-lines on stdin/stdout
    ("serve_warmup", bool, True, []),         # compile all buckets at boot
    ("serve_metrics_file", str, "", []),      # JSON-lines metrics sink
    ("serve_metrics_freq", float, 10.0, []),  # seconds between snapshots
    # serving hot path (serving/traversal.py): SoA traversal vs replay,
    # early-exit cascade, and int16 leaf-table quantization
    ("serving_backend", str, "traversal", ["serve_backend"]),
    ("serving_cascade_trees", int, 0, ["serve_cascade_trees"]),
    ("serving_cascade_margin", float, 10.0, ["serve_cascade_margin"]),
    ("serving_quantize_leaves", bool, False, ["serve_quantize_leaves"]),
    # ---- observability (lightgbm_tpu.obs; docs/Observability.md) ----
    # none: zero instrumentation (default). basic: fused blocks kept,
    # per-block spans/events/health (<3% overhead, bench-verified).
    # full: per-iteration dispatch with true spans, health within one
    # iteration, Perfetto window capture, per-iteration HBM accounting.
    ("observability", str, "none", ["obs", "observability_level"]),
    # JSON-lines event stream (spans, iterations, health); "" = off
    ("obs_event_file", str, "", ["obs_events", "observability_event_file"]),
    # training stats HTTP endpoint: -1 = off, 0 = OS-assigned port
    ("obs_stats_port", int, -1, ["obs_metrics_port"]),
    # jax.profiler Perfetto capture (observability=full): directory,
    # first iteration and iteration count of the capture window
    ("obs_perfetto_dir", str, "", ["obs_trace_dir"]),
    ("obs_perfetto_start", int, 0, []),
    ("obs_perfetto_iters", int, 0, []),       # 0 = no capture
    # device-side anomaly response: auto = warn when observability is on,
    # else off; abort = checkpoint (checkpoint_dir) then raise
    ("health_monitor", str, "auto",
     ["health_monitor_action", "obs_health"]),
    # ---- distributed obs (obs/distributed.py) ----
    # cross-process metric federation + straggler detection: auto = armed
    # whenever observability is on AND jax.process_count() > 1; on forces
    # it even single-process (degenerate local view); off disables
    ("obs_distributed", str, "auto", []),
    # warn when max/median per-process block wall time crosses this
    # ratio (routed through HealthMonitor, warn-only); 0 disables
    ("obs_straggler_warn_skew", float, 2.0, ["straggler_warn_skew"]),
    # flight-recorder ring size: recent events kept in memory per process
    # and dumped to <obs_event_file>.<process>.crash.jsonl on HealthMonitor
    # abort, SIGTERM, or unhandled exception; 0 = off
    ("obs_flight_recorder", int, 512, ["obs_flight_recorder_size"]),
    # ---- model statistics & drift (obs/modelstats.py, obs/drift.py) ----
    # per-feature split-count/gain accumulators + leaf distributions,
    # streamed as lgbm_model_* metrics and model_iter events. On the
    # frontier grower this piggy-backs an accumulator on the wave loop
    # (zero extra collectives); off keeps the compiled training program
    # byte-identical to an uninstrumented build.
    ("obs_modelstats", bool, False, ["model_stats", "modelstats"]),
    # train/serve drift detection (serving side; needs a model with a
    # training data profile): warn-only HealthMonitor routing + on_drift
    # refit hooks fire when any feature's PSI crosses this threshold
    ("obs_drift_warn_psi", float, 0.25, ["drift_warn_psi"]),
    # decay factor of the served score-distribution sketch (per row)
    ("obs_drift_decay", float, 0.999, ["drift_decay"]),
    # rows observed before PSI warns are armed (early traffic is noise)
    ("obs_drift_min_rows", int, 256, ["drift_min_rows"]),
    # drift monitoring on the serving predict path; off = zero overhead
    ("serve_drift", bool, True, []),
    # ---- request-scoped tracing (obs/reqtrace.py) ----
    # span tree per admitted request / streamed training iteration,
    # emitted on the event stream with tail-based sampling; off (default)
    # is the shared no-op span — zero allocation on the hot path and the
    # compiled programs are byte-identical either way (host-side only)
    ("obs_trace", bool, False, ["request_trace", "reqtrace"]),
    # always keep traces at least this slow (ms); shed/error always kept
    ("obs_trace_slow_ms", float, 250.0, ["trace_slow_ms"]),
    # fraction of the remaining (fast, ok) traces kept, decided by a
    # deterministic hash of (seed, trace_id) in [0, 1]
    ("obs_trace_sample", float, 0.01, ["trace_sample"]),
    # ---- SLO burn-rate engine (obs/slo.py; /slo on both StatsServers) ----
    # serving latency objective: p-fraction of requests under this many
    # ms (objective = serve_slo_target); 0 = no latency SLO
    ("serve_slo_p99_ms", float, 0.0, ["slo_p99_ms"]),
    # good-fraction the latency SLO targets (0.99 => 1% error budget)
    ("serve_slo_target", float, 0.99, []),
    # availability objective: fraction of requests NOT errored/shed/timed
    # out (e.g. 0.999); 0 = no availability SLO
    ("serve_slo_availability", float, 0.0, ["slo_availability"]),
    # streamed-training throughput floor (rows/sec); 0 = no training SLO
    ("train_slo_rows_per_sec", float, 0.0, ["slo_rows_per_sec"]),
    # Google-SRE multi-window burn rates: fast window for responsiveness,
    # slow window to ride out blips; burning when BOTH exceed the warn
    # threshold (burn 1.0 = consuming exactly the error budget)
    ("slo_fast_window_s", float, 300.0, []),
    ("slo_slow_window_s", float, 3600.0, []),
    ("slo_burn_warn", float, 2.0, ["slo_burn_threshold"]),
    # seconds between background SLO evaluations (serving ticker)
    ("slo_tick_s", float, 5.0, []),
    # ---- resilience (lightgbm_tpu.resilience; docs/Resilience.md) ----
    # deterministic fault plan: comma list of kind@unit:match[:arg], e.g.
    # "kv_timeout@round:2,kill@iter:7,serve_error@req:50". Strictly
    # host-side; "" (default) = injection fully inert.
    ("fault_inject", str, "", ["fault_plan"]),
    ("fault_seed", int, 0, []),
    # supervised training: watchdog + auto-resume restart loop around the
    # boosting loop (needs checkpoint_dir for somewhere to resume from)
    ("supervise", bool, False, ["supervised"]),
    ("supervise_max_restarts", int, 3, ["max_restarts"]),
    ("supervise_backoff_s", float, 1.0, []),
    ("supervise_backoff_max_s", float, 60.0, []),
    # hung-dispatch watchdog deadline (seconds); 0 = no watchdog. The
    # FIRST deadline adds supervise_warmup_grace_s: the initial compile
    # is slow-but-alive and must not false-fire.
    ("supervise_hang_timeout_s", float, 0.0, ["hang_timeout_s"]),
    ("supervise_warmup_grace_s", float, 120.0, []),
    # heartbeat file touched every iteration for an external process-level
    # supervisor (tools/chaos_smoke.py); "" = off
    ("supervise_heartbeat_file", str, "", ["heartbeat_file"]),
    # KvHostComm robustness: bounded retry-with-backoff on transient
    # coordination-service set/get failures before surfacing
    ("kv_retries", int, 3, []),
    ("kv_retry_backoff_s", float, 0.25, []),
    # KV heartbeat leases for peer-death detection (multi-process): each
    # rank re-leases every period_s; a peer silent past lease_s is dead
    ("kv_heartbeat_period_s", float, 2.0, []),
    ("kv_heartbeat_lease_s", float, 10.0, []),
    # serving overload protection: bounded admission in ROWS (0 = no
    # bound), per-request deadline in ms (0 = none)
    ("serve_max_queue_rows", int, 0, []),
    ("serve_request_timeout_ms", float, 0.0, []),
    # consecutive dispatch failures that trip the serving circuit breaker
    # to 503+Retry-After (0 disables); cooldown before a half-open probe
    ("serve_breaker_failures", int, 5, ["serve_breaker_threshold"]),
    ("serve_breaker_cooldown_s", float, 5.0, []),
    # guarded hot-roll: score canary rows on a staged bundle (finite
    # outputs, traversal-vs-replay parity, optional latency cap) and
    # refuse the swap on failure, keeping the prior generation live
    ("serve_guard_hot_roll", bool, True, ["serve_guarded_roll"]),
    ("serve_canary_rows", int, 16, []),
    ("serve_roll_max_latency_ms", float, 0.0, []),   # 0 = no latency gate
    # structure-preserving refit (fleet/refit.py): device path for dense
    # inputs (host numpy fallback for sparse / when disabled)
    ("refit_device", bool, True, []),
    # multi-model QoS (fleet/qos.py): default per-model queued-row quota
    # (0 = engine-wide bound only) and "model=weight,..." weighted-fair
    # scheduling weights (empty = every model weight 1; QoS engages when
    # either is set)
    ("serve_qos_quota_rows", int, 0, []),
    ("serve_qos_weights", str, "", []),
    # cascade-margin autotuning: hold observed per-bucket p99 under this
    # budget by walking serving_cascade_margin down a geometric ladder
    # (0 = autotune off; needs serving_cascade_trees > 0)
    ("serve_latency_budget_ms", float, 0.0, []),
    ("serve_qos_tune_interval_s", float, 2.0, []),
    # serving fleet (fleet/replica.py): shared file-KV directory replicas
    # announce generations/state through, this process' replica name, and
    # the announce period (fleet engages when fleet_kv_dir is set)
    ("fleet_kv_dir", str, "", []),
    ("fleet_replica", str, "", []),
    ("fleet_announce_period_s", float, 1.0, []),
]

# known spellings, validated in _post_process (a typo'd kernel or growth
# mode must fail loudly at config time, not fall through to some default
# deep in the dispatch)
TREE_GROW_MODES = ("exact", "batched", "frontier")
SERVING_BACKENDS = ("traversal", "replay")
OBSERVABILITY_LEVELS = ("none", "basic", "full")
HEALTH_MONITOR_ACTIONS = ("auto", "none", "warn", "abort", "raise")
OBS_DISTRIBUTED_MODES = ("auto", "on", "off")
HIST_IMPLS = ("auto", "matmul", "scatter", "pallas", "pallas_highest",
              "pallas_interpret", "pallas_highest_interpret")
BIN_PACKING_MODES = ("auto", "none", "nibble", "byte")

_CANON: Dict[str, Tuple[type, Any]] = {n: (t, d) for n, t, d, _ in _PARAMS}
_ALIASES: Dict[str, str] = {}
for _n, _t, _d, _al in _PARAMS:
    _ALIASES[_n] = _n
    for _a in _al:
        _ALIASES[_a] = _n

# Objective aliases (objective_function.cpp:14-42 & config_auto resolution).
_OBJECTIVE_ALIASES = {
    "regression": "regression", "regression_l2": "regression", "l2": "regression",
    "mean_squared_error": "regression", "mse": "regression", "l2_root": "regression",
    "root_mean_squared_error": "regression", "rmse": "regression",
    "regression_l1": "regression_l1", "l1": "regression_l1",
    "mean_absolute_error": "regression_l1", "mae": "regression_l1",
    "mean_absolute_percentage_error": "mape", "mape": "mape",
    "huber": "huber", "fair": "fair", "poisson": "poisson",
    "quantile": "quantile", "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary",
    "multiclass": "multiclass", "softmax": "multiclass",
    "multiclassova": "multiclassova", "multiclass_ova": "multiclassova",
    "ova": "multiclassova", "ovr": "multiclassova",
    "xentropy": "xentropy", "cross_entropy": "xentropy",
    "xentlambda": "xentlambda", "cross_entropy_lambda": "xentlambda",
    "lambdarank": "lambdarank", "rank_xendcg": "lambdarank",
    "none": "none", "null": "none", "custom": "none", "na": "none",
}

_BOOSTING_ALIASES = {
    "gbdt": "gbdt", "gbrt": "gbdt",
    "dart": "dart",
    "goss": "goss",
    "rf": "rf", "random_forest": "rf",
}

_TREE_LEARNER_ALIASES = {
    "serial": "serial",
    "feature": "feature", "feature_parallel": "feature",
    "data": "data", "data_parallel": "data",
    "voting": "voting", "voting_parallel": "voting",
}


def _coerce(name: str, typ: type, value: Any) -> Any:
    try:
        if typ is bool:
            if isinstance(value, str):
                return value.strip().lower() in ("true", "+", "1", "yes")
            return bool(value)
        if typ is int:
            return int(float(value)) if isinstance(value, str) else int(value)
        if typ is float:
            return float(value)
        if typ is list:
            if isinstance(value, str):
                value = [v for v in value.replace(" ", ",").split(",") if v != ""]
            if isinstance(value, (int, float)):
                value = [value]
            out = []
            for v in value:
                if isinstance(v, str):
                    try:
                        v = int(v)
                    except ValueError:
                        try:
                            v = float(v)
                        except ValueError:
                            pass
                out.append(v)
            return out
        if typ is str:
            return str(value)
    except (TypeError, ValueError) as err:
        raise LightGBMError("Parameter %s should be of type %s, got %r (%s)"
                            % (name, typ.__name__, value, err))
    return value


def param_dict_to_str(params: Optional[Dict[str, Any]]) -> str:
    """Serialize params to the ``k=v`` space-joined string the C API uses."""
    if not params:
        return ""
    pairs = []
    for k, v in params.items():
        if isinstance(v, (list, tuple)):
            pairs.append("%s=%s" % (k, ",".join(map(str, v))))
        elif v is not None:
            pairs.append("%s=%s" % (k, v))
    return " ".join(pairs)


def kv2map(args: List[str]) -> Dict[str, str]:
    """CLI ``key=value`` token parser (config.cpp:15 KV2Map)."""
    out: Dict[str, str] = {}
    for token in args:
        token = token.split("#", 1)[0].strip()
        if not token:
            continue
        if "=" not in token:
            Log.warning("Unknown parameter %s", token)
            continue
        k, v = token.split("=", 1)
        out[k.strip()] = v.strip()
    return out


class Config:
    """Typed parameter container (config.h:27 Config struct analog)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None):
        for name, (_typ, default) in _CANON.items():
            setattr(self, name, copy.copy(default))
        self.extra_params: Dict[str, Any] = {}
        if params:
            self.set(params)

    @staticmethod
    def resolve_key(key: str) -> str:
        """ParameterAlias::KeyAliasTransform (config.h:857-865)."""
        return _ALIASES.get(key, key)

    def set(self, params: Dict[str, Any]) -> "Config":
        """Config::Set (config.cpp:153): alias resolve, coerce, validate."""
        resolved: Dict[str, Any] = {}
        for key, value in params.items():
            if value is None:
                continue
            canon = self.resolve_key(key)
            if canon in resolved and canon != key:
                Log.warning("%s is set with both %s and an alias; using %r",
                            canon, key, resolved[canon])
                continue
            resolved[canon] = value
        for key, value in resolved.items():
            if key in _CANON:
                typ, _ = _CANON[key]
                setattr(self, key, _coerce(key, typ, value))
            else:
                self.extra_params[key] = value
        self._post_process()
        return self

    def _post_process(self) -> None:
        obj = str(self.objective).strip().lower()
        if obj.startswith("quantile_l2"):
            obj = "quantile"
        if obj in ("l2_root", "root_mean_squared_error", "rmse"):
            self.reg_sqrt = True
        self.objective = _OBJECTIVE_ALIASES.get(obj, obj)
        self.boosting = _BOOSTING_ALIASES.get(str(self.boosting).strip().lower(),
                                              self.boosting)
        self.tree_learner = _TREE_LEARNER_ALIASES.get(
            str(self.tree_learner).strip().lower(), self.tree_learner)
        if self.tree_learner not in ("serial", "feature", "data", "voting"):
            raise LightGBMError("Unknown tree learner type %s" % self.tree_learner)
        if self.boosting not in ("gbdt", "dart", "goss", "rf"):
            raise LightGBMError("Unknown boosting type %s" % self.boosting)
        # derived: is_parallel (config.h:790)
        self.is_parallel = (self.tree_learner != "serial") or self.num_machines > 1
        if self.boosting == "rf":
            if not (self.bagging_freq > 0 and 0.0 < self.bagging_fraction < 1.0):
                raise LightGBMError(
                    "Random forest needs bagging_freq > 0 and bagging_fraction in (0, 1)")
        if self.boosting == "goss":
            if self.top_rate + self.other_rate > 1.0:
                raise LightGBMError("GOSS needs top_rate + other_rate <= 1.0")
        if not (0.0 < self.feature_fraction <= 1.0):
            raise LightGBMError("feature_fraction should be in (0, 1.0]")
        if not (0.0 < self.bagging_fraction <= 1.0):
            raise LightGBMError("bagging_fraction should be in (0, 1.0]")
        if not (1 < self.max_bin <= 256):
            raise LightGBMError("max_bin should be in (1, 256]")
        if self.num_leaves < 2:
            raise LightGBMError("num_leaves should be >= 2")
        self.tree_growth = str(self.tree_growth).strip().lower()
        if self.tree_growth not in TREE_GROW_MODES:
            raise LightGBMError("tree_growth should be one of %s, got %s"
                                % ("/".join(TREE_GROW_MODES),
                                   self.tree_growth))
        self.tpu_hist_impl = str(self.tpu_hist_impl).strip().lower()
        if self.tpu_hist_impl not in HIST_IMPLS:
            raise LightGBMError("tpu_hist_impl should be one of %s, got %s"
                                % ("/".join(HIST_IMPLS),
                                   self.tpu_hist_impl))
        self.tpu_bin_packing = str(self.tpu_bin_packing).strip().lower()
        if self.tpu_bin_packing not in BIN_PACKING_MODES:
            raise LightGBMError("tpu_bin_packing should be one of %s, got %s"
                                % ("/".join(BIN_PACKING_MODES),
                                   self.tpu_bin_packing))
        if self.tree_batch_splits < 1:
            raise LightGBMError("tree_batch_splits should be >= 1")
        self.tpu_batched_part = str(self.tpu_batched_part).strip().lower()
        if self.tpu_batched_part not in ("auto", "true", "false", "1", "0"):
            raise LightGBMError("tpu_batched_part should be auto, true or "
                                "false, got %s" % self.tpu_batched_part)
        if self.tpu_row_chunk < 0:
            raise LightGBMError("tpu_row_chunk should be >= 0 (0 = auto), "
                                "got %s" % self.tpu_row_chunk)
        if self.data_stream_chunk_rows < 0:
            raise LightGBMError("data_stream_chunk_rows should be >= 0 "
                                "(0 = off), got %s"
                                % self.data_stream_chunk_rows)
        if self.data_stream_prefetch < 1:
            raise LightGBMError("data_stream_prefetch should be >= 1, got %s"
                                % self.data_stream_prefetch)
        if self.data_stream_chunk_rows > 0:
            # the streamed trainer is the frontier grower driven from the
            # host; every incompatible combination fails HERE, at config
            # time, not deep inside the training dispatch
            if self.tree_growth != "frontier":
                raise LightGBMError(
                    "data_stream_chunk_rows requires tree_growth=frontier "
                    "(cross-chunk histogram accumulation rides the wave "
                    "sweep); got tree_growth=%s" % self.tree_growth)
            if self.boosting not in ("gbdt", "goss"):
                raise LightGBMError(
                    "data_stream_chunk_rows supports boosting gbdt/goss "
                    "only (dart/rf replay full binned data per iteration); "
                    "got boosting=%s" % self.boosting)
            # chunks x chips: a data-parallel mesh composes with the
            # chunk stream (each process sweeps its row shard and the
            # learner collectives fire once per wave); the remaining
            # unsupported combinations each fail here BY NAME
            if self.mesh_shape and self.tree_learner == "feature":
                raise LightGBMError(
                    "gate streamed+feature-learner: the chunk stream is "
                    "row-partitioned, so tree_learner=feature (column-"
                    "partitioned search) cannot ride it; use "
                    "tree_learner=data or voting with "
                    "data_stream_chunk_rows")
            if self.mesh_shape and self.gpu_use_dp:
                raise LightGBMError(
                    "gate streamed-mesh+f64: streamed mesh training "
                    "accumulates f32 wave histograms and the reduce-"
                    "scatter/voting schedules bitcast f32 records; unset "
                    "gpu_use_dp or data_stream_chunk_rows/mesh_shape")
            if self.gpu_use_dp:
                raise LightGBMError(
                    "data_stream_chunk_rows accumulates f32 wave "
                    "histograms; gpu_use_dp (f64) is not supported")
        if self.top_k < 1:
            raise LightGBMError("top_k should be >= 1 (voting-parallel "
                                "candidate count), got %s" % self.top_k)
        # a file where the cache DIRECTORY should be will corrupt silently
        # deep inside jax; fail at config time like the other path params
        if self.compile_cache_dir:
            import os
            if os.path.exists(self.compile_cache_dir) and \
                    not os.path.isdir(self.compile_cache_dir):
                raise LightGBMError(
                    "compile_cache_dir %s exists and is not a directory"
                    % self.compile_cache_dir)
        if self.checkpoint_period < 1:
            raise LightGBMError("checkpoint_period should be >= 1, got %s"
                                % self.checkpoint_period)
        if self.checkpoint_keep < 1:
            raise LightGBMError("checkpoint_keep should be >= 1, got %s"
                                % self.checkpoint_keep)
        self.observability = str(self.observability).strip().lower()
        if self.observability not in OBSERVABILITY_LEVELS:
            raise LightGBMError("observability should be one of %s, got %s"
                                % ("/".join(OBSERVABILITY_LEVELS),
                                   self.observability))
        self.health_monitor = str(self.health_monitor).strip().lower()
        if self.health_monitor not in HEALTH_MONITOR_ACTIONS:
            raise LightGBMError("health_monitor should be one of %s, got %s"
                                % ("/".join(HEALTH_MONITOR_ACTIONS),
                                   self.health_monitor))
        if not -1 <= self.obs_stats_port <= 65535:
            raise LightGBMError("obs_stats_port should be in [-1, 65535] "
                                "(-1 = off, 0 = OS-assigned), got %s"
                                % self.obs_stats_port)
        if self.obs_perfetto_start < 0 or self.obs_perfetto_iters < 0:
            raise LightGBMError("obs_perfetto_start/obs_perfetto_iters "
                                "should be >= 0")
        self.obs_distributed = str(self.obs_distributed).strip().lower()
        if self.obs_distributed not in OBS_DISTRIBUTED_MODES:
            raise LightGBMError("obs_distributed should be one of %s, "
                                "got %s"
                                % ("/".join(OBS_DISTRIBUTED_MODES),
                                   self.obs_distributed))
        if self.obs_straggler_warn_skew < 0:
            raise LightGBMError("obs_straggler_warn_skew should be >= 0 "
                                "(0 disables), got %s"
                                % self.obs_straggler_warn_skew)
        if self.obs_flight_recorder < 0:
            raise LightGBMError("obs_flight_recorder should be >= 0 "
                                "(0 = off), got %s"
                                % self.obs_flight_recorder)
        if self.obs_drift_warn_psi <= 0:
            raise LightGBMError("obs_drift_warn_psi should be > 0, got %s"
                                % self.obs_drift_warn_psi)
        if not 0.0 < self.obs_drift_decay <= 1.0:
            raise LightGBMError("obs_drift_decay should be in (0, 1], "
                                "got %s" % self.obs_drift_decay)
        if self.obs_drift_min_rows < 0:
            raise LightGBMError("obs_drift_min_rows should be >= 0, got %s"
                                % self.obs_drift_min_rows)
        if self.obs_trace_slow_ms < 0:
            raise LightGBMError("obs_trace_slow_ms should be >= 0, got %s"
                                % self.obs_trace_slow_ms)
        if not 0.0 <= self.obs_trace_sample <= 1.0:
            raise LightGBMError("obs_trace_sample should be in [0, 1], "
                                "got %s" % self.obs_trace_sample)
        if self.serve_slo_p99_ms < 0:
            raise LightGBMError("serve_slo_p99_ms should be >= 0 "
                                "(0 = no latency SLO), got %s"
                                % self.serve_slo_p99_ms)
        if not 0.0 < self.serve_slo_target < 1.0:
            raise LightGBMError("serve_slo_target should be in (0, 1), "
                                "got %s" % self.serve_slo_target)
        if not 0.0 <= self.serve_slo_availability < 1.0:
            raise LightGBMError("serve_slo_availability should be in "
                                "[0, 1) (0 = no availability SLO), got %s"
                                % self.serve_slo_availability)
        if self.train_slo_rows_per_sec < 0:
            raise LightGBMError("train_slo_rows_per_sec should be >= 0 "
                                "(0 = no training SLO), got %s"
                                % self.train_slo_rows_per_sec)
        if self.slo_fast_window_s <= 0 or self.slo_slow_window_s <= 0:
            raise LightGBMError(
                "slo_fast_window_s/slo_slow_window_s should be > 0")
        if self.slo_fast_window_s > self.slo_slow_window_s:
            raise LightGBMError("slo_fast_window_s (%s) should not exceed "
                                "slo_slow_window_s (%s)"
                                % (self.slo_fast_window_s,
                                   self.slo_slow_window_s))
        if self.slo_burn_warn <= 0:
            raise LightGBMError("slo_burn_warn should be > 0, got %s"
                                % self.slo_burn_warn)
        if self.slo_tick_s <= 0:
            raise LightGBMError("slo_tick_s should be > 0, got %s"
                                % self.slo_tick_s)
        self.serving_backend = str(self.serving_backend).strip().lower()
        if self.serving_backend not in SERVING_BACKENDS:
            raise LightGBMError("serving_backend should be one of %s, got %s"
                                % ("/".join(SERVING_BACKENDS),
                                   self.serving_backend))
        if self.serving_cascade_trees < 0:
            raise LightGBMError("serving_cascade_trees should be >= 0 "
                                "(0 = no cascade), got %s"
                                % self.serving_cascade_trees)
        if self.serving_cascade_margin < 0:
            raise LightGBMError("serving_cascade_margin should be >= 0, "
                                "got %s" % self.serving_cascade_margin)
        # fault plans parse at config time — a typo'd kind must fail here,
        # not silently never fire mid-chaos-run
        if self.fault_inject:
            from .resilience import faults as _faults
            _faults.parse_plan(self.fault_inject, self.fault_seed)
        if self.supervise_max_restarts < 0:
            raise LightGBMError("supervise_max_restarts should be >= 0, "
                                "got %s" % self.supervise_max_restarts)
        if self.supervise_backoff_s < 0 or self.supervise_backoff_max_s < 0:
            raise LightGBMError(
                "supervise_backoff_s/supervise_backoff_max_s should be >= 0")
        if self.supervise_hang_timeout_s < 0 or \
                self.supervise_warmup_grace_s < 0:
            raise LightGBMError(
                "supervise_hang_timeout_s/supervise_warmup_grace_s should "
                "be >= 0 (0 = no watchdog)")
        if self.kv_retries < 0:
            raise LightGBMError("kv_retries should be >= 0, got %s"
                                % self.kv_retries)
        if self.kv_retry_backoff_s < 0:
            raise LightGBMError("kv_retry_backoff_s should be >= 0, got %s"
                                % self.kv_retry_backoff_s)
        if self.kv_heartbeat_period_s <= 0 or self.kv_heartbeat_lease_s <= 0:
            raise LightGBMError(
                "kv_heartbeat_period_s/kv_heartbeat_lease_s should be > 0")
        if self.serve_max_queue_rows < 0:
            raise LightGBMError("serve_max_queue_rows should be >= 0 "
                                "(0 = unbounded), got %s"
                                % self.serve_max_queue_rows)
        if self.serve_request_timeout_ms < 0:
            raise LightGBMError("serve_request_timeout_ms should be >= 0 "
                                "(0 = none), got %s"
                                % self.serve_request_timeout_ms)
        if self.serve_breaker_failures < 0:
            raise LightGBMError("serve_breaker_failures should be >= 0 "
                                "(0 disables), got %s"
                                % self.serve_breaker_failures)
        if self.serve_breaker_cooldown_s < 0:
            raise LightGBMError("serve_breaker_cooldown_s should be >= 0, "
                                "got %s" % self.serve_breaker_cooldown_s)
        if self.serve_canary_rows < 1:
            raise LightGBMError("serve_canary_rows should be >= 1, got %s"
                                % self.serve_canary_rows)
        if self.serve_roll_max_latency_ms < 0:
            raise LightGBMError("serve_roll_max_latency_ms should be >= 0 "
                                "(0 = no latency gate), got %s"
                                % self.serve_roll_max_latency_ms)
        if self.serve_qos_quota_rows < 0:
            raise LightGBMError("serve_qos_quota_rows should be >= 0 "
                                "(0 = engine-wide bound only), got %s"
                                % self.serve_qos_quota_rows)
        if self.serve_latency_budget_ms < 0:
            raise LightGBMError("serve_latency_budget_ms should be >= 0 "
                                "(0 = autotune off), got %s"
                                % self.serve_latency_budget_ms)
        if self.serve_latency_budget_ms > 0 and \
                self.serving_cascade_trees <= 0:
            raise LightGBMError(
                "serve_latency_budget_ms needs serving_cascade_trees > 0 "
                "(there is no early-exit cascade to autotune)")
        if self.serve_qos_tune_interval_s <= 0:
            raise LightGBMError("serve_qos_tune_interval_s should be > 0, "
                                "got %s" % self.serve_qos_tune_interval_s)
        if self.fleet_announce_period_s <= 0:
            raise LightGBMError("fleet_announce_period_s should be > 0, "
                                "got %s" % self.fleet_announce_period_s)
        # verbosity drives the process logger unconditionally so
        # verbosity=-1 (fatal-only) also silences obs warnings; previously
        # negative values were dropped and warnings leaked through
        Log.reset_level(self.verbosity)

    def copy(self) -> "Config":
        return copy.deepcopy(self)

    def to_dict(self) -> Dict[str, Any]:
        d = {name: getattr(self, name) for name in _CANON}
        d.update(self.extra_params)
        return d

    def __repr__(self) -> str:  # pragma: no cover
        return "Config(%r)" % (self.to_dict(),)


def load_config_file(path: str) -> Dict[str, str]:
    """Parse a ``key=value`` config file with # comments (application.cpp:48-81)."""
    with open(path, "r") as fh:
        return kv2map(fh.read().splitlines())
