"""jax API compatibility shims.

The explicit-collective learners target the modern spellings
(``jax.shard_map``, ``lax.pcast``); older jax releases (<= 0.4.x) ship
them as ``jax.experimental.shard_map.shard_map(check_rep=...)`` and have
no pcast at all (their shard_map has no varying-axes type system to
satisfy, so pcast degrades to identity). Everything below dispatches once
at import time.
"""
from __future__ import annotations

import jax
from jax import lax

if hasattr(jax, "shard_map"):
    def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
else:
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)

if hasattr(lax, "pcast"):
    def pcast(x, axes, to: str = "varying"):
        return lax.pcast(x, axes, to=to)
else:
    def pcast(x, axes, to: str = "varying"):
        return x
