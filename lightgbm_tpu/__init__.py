"""lightgbm_tpu: a TPU-native gradient-boosting framework.

Re-designed from scratch for JAX/XLA/Pallas with the capabilities of
LightGBM v2.2.4 (reference: mark5434/LightGBM): histogram-based GBDT with
leaf-wise growth, EFB-style binning, GOSS/DART/RF boosting modes, the full
objective/metric suite, distributed training over jax.sharding meshes, and a
LightGBM-compatible Python API and model format.
"""

from .config import Config
from .log import Log, LightGBMError

__version__ = "0.1.0"

__all__ = [
    "Config", "Log", "LightGBMError",
    "Dataset", "Booster", "train", "cv",
    "LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker",
]


def __getattr__(name):
    # lazy imports keep `import lightgbm_tpu` light and avoid jax init at
    # import time for tooling that only wants Config/version
    if name in ("Dataset", "Booster"):
        from . import basic
        return getattr(basic, name)
    if name in ("train", "cv"):
        from . import engine
        return getattr(engine, name)
    if name in ("LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"):
        from . import sklearn as _sk
        return getattr(_sk, name)
    if name in ("plot_importance", "plot_metric", "plot_tree", "create_tree_digraph"):
        from . import plotting
        return getattr(plotting, name)
    if name in ("early_stopping", "print_evaluation", "record_evaluation",
                "reset_parameter"):
        from . import callback
        return getattr(callback, name)
    # NOTE: the checkpoint *callback factory* lives at callback.checkpoint;
    # `lightgbm_tpu.checkpoint` is the subsystem package itself
    if name == "CheckpointManager":
        from .checkpoint import CheckpointManager
        return CheckpointManager
    raise AttributeError("module 'lightgbm_tpu' has no attribute %r" % name)
