"""Random Forest mode.

TPU-native re-design of src/boosting/rf.hpp: ``average_output`` on, bagging
mandatory, no shrinkage (rate 1.0), and every tree is fit to gradients
computed ONCE at the objective's init score (rf.hpp Boosting :76-95) — so
trees are independent given the bagging masks. Each tree gets the init score
folded in via AddBias (rf.hpp :118-121) and the model output is the average
over iterations (GBDT::average_output handling).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..log import LightGBMError
from .gbdt import GBDT, HostTree


class RF(GBDT):
    boosting_type = "rf"
    average_output = True

    def __init__(self, config: Config, train_data, objective, metrics=None):
        if not (config.bagging_freq > 0 and 0.0 < config.bagging_fraction < 1.0):
            raise LightGBMError(
                "Random forest needs bagging_freq > 0 and "
                "bagging_fraction in (0, 1)")
        super().__init__(config, train_data, objective, metrics)
        self.shrinkage_rate = 1.0
        self._use_input_grads = True
        self._grad_cache: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None
        self._init_scores_rf = np.zeros(self.num_tree_per_iteration, np.float32)
        # scores hold the running SUM of tree outputs; eval views divide by
        # the iteration count (score_updater MultiplyScore dance, rf.hpp)
        self._score_sum = self.scores
        self._valid_score_sum = {}
        # RF rewrites each iteration's trees (AddBias) and re-averages scores
        # immediately after training them; flush synchronously.
        self._flush_every = 1

    def _fixed_gradients(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Gradients at the constant init score (rf.hpp Boosting :76-95)."""
        if self._grad_cache is None:
            k = self.num_tree_per_iteration
            n = self.num_data
            if self.config.boost_from_average and self.objective is not None:
                self._init_scores_rf = np.array(
                    [self.objective.boost_from_score(c) for c in range(k)],
                    np.float32)
            base = jnp.broadcast_to(jnp.asarray(self._init_scores_rf)[None, :],
                                    (n, k))
            if k == 1:
                g, h = self.objective.get_gradients(base[:, 0])
                g, h = g[:, None], h[:, None]
            else:
                g, h = self.objective.get_gradients(base)
            self._grad_cache = (g, h)
        return self._grad_cache

    def _boost_from_average(self) -> None:
        # RF does not seed the running scores; init score lives in each tree
        # via AddBias instead (rf.hpp :118-121).
        self.boost_from_average_done = True
        self.init_score_offsets = np.zeros(self.num_tree_per_iteration,
                                           np.float32)

    def train_one_iter(self, grad=None, hess=None) -> bool:
        prev_sum = self._score_sum
        n_before = len(self.models)
        # make super() accumulate onto the raw sums (cache["scores"] holds the
        # averaged view between iterations; the raw sums live in
        # _valid_score_sum / _score_sum)
        self.scores = prev_sum
        for vi, cache in self._valid_pred_cache.items():
            cache["scores"] = self._valid_score_sum.get(vi, cache["scores"])
        ret = super().train_one_iter(grad, hess)
        if ret:
            it = float(max(self.current_iteration, 1))
            self.scores = self._score_sum / it
            for vi, cache in self._valid_pred_cache.items():
                self._valid_score_sum[vi] = cache["scores"]
                cache["scores"] = cache["scores"] / it
            return ret
        k = self.num_tree_per_iteration
        # AddBias: fold the init score into the new trees + their score deltas
        new_trees = self.models[n_before:]
        for c, ht in enumerate(new_trees):
            bias = float(self._init_scores_rf[c])
            if abs(bias) > 1e-15:
                ht.leaf_value += bias
                ht.internal_value += bias
                self.scores = self.scores.at[:, c].add(bias)
                for cache in self._valid_pred_cache.values():
                    cache["scores"] = cache["scores"].at[:, c].add(bias)
        self._score_sum = self.scores
        it = float(self.current_iteration)
        self.scores = self._score_sum / it
        for vi, cache in self._valid_pred_cache.items():
            self._valid_score_sum[vi] = cache["scores"]
            cache["scores"] = cache["scores"] / it
        return False
