"""GBDT training driver.

TPU-native re-design of src/boosting/gbdt.cpp (Init :45-115, TrainOneIter
:333-412, Bagging :159-241, UpdateScore :451-470, early stopping :476-533).
The whole boosting iteration — gradients, bagging mask, K class trees, score
update — is one jit-compiled function; the host loop only sequences
iterations, snapshots tiny tree arrays, and runs metrics every
``metric_freq`` rounds.

Key mappings:
- ScoreUpdater (score_updater.hpp) -> a device score array updated via the
  final per-row ``leaf_id`` from growth (the "by learner partition" fast path,
  serial_tree_learner.h:58-70) — out-of-bag rows get their leaf the same way,
  so no separate OOB pass is needed.
- Multiclass K trees/iteration (gbdt.cpp:348-398) -> ``jax.vmap`` of tree
  growth over the class axis.
- Tree::Shrinkage (tree.h:139) -> leaf values scaled by learning_rate when a
  tree is extracted into the host-side model list.
- RenewTreeOutput for percentile objectives (serial_tree_learner.cpp:850-928)
  -> in-graph segmented weighted percentile (core/renew.py): one sort +
  cumsum + searchsorted renews every leaf at once, no host round-trip.
"""
from __future__ import annotations

import functools
import math
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from ..compat import shard_map
from ..config import Config
from ..log import Log, LightGBMError, check
from ..io.dataset import BinnedDataset
from ..io.binning import BinType, MissingType as BinMissingType
from ..core.split import FeatureMeta, SplitParams
from ..core.grow import GrowParams, TreeArrays, empty_tree, grow_tree
from ..core import partition as partition_mod
from ..core.pack import pack_trees, unpack_tree
from ..core import tree as tree_mod
from ..objectives import ObjectiveFunction
from ..metrics import Metric
from ..resilience import faults as _faults


class HostTree:
    """One trained tree pulled to host: numpy SoA + real-value thresholds.

    The analog of the serialized Tree model (tree.h:404-517) — what gets
    saved, loaded, and used for raw-input prediction.
    """

    def __init__(self, num_leaves: int):
        n = max(num_leaves - 1, 1)
        self.num_leaves = num_leaves
        self.split_feature = np.zeros(n, np.int32)       # real feature index
        self.split_gain = np.zeros(n, np.float32)
        self.threshold = np.zeros(n, np.float64)         # real-value threshold
        self.threshold_bin = np.zeros(n, np.int32)
        self.default_left = np.zeros(n, bool)
        self.missing_type = np.zeros(n, np.int32)
        self.is_categorical = np.zeros(n, bool)
        self.cat_bitset = np.zeros((n, 8), np.uint32)      # raw category values
        self.cat_bitset_bin = np.zeros((n, 8), np.uint32)  # bin indices (train replay)
        self.left_child = np.full(n, -1, np.int32)
        self.right_child = np.full(n, -1, np.int32)
        self.split_leaf = np.full(n, -1, np.int32)
        self.internal_value = np.zeros(n, np.float64)
        self.internal_weight = np.zeros(n, np.float64)
        self.internal_count = np.zeros(n, np.int64)
        self.leaf_value = np.zeros(num_leaves, np.float64)
        self.leaf_weight = np.zeros(num_leaves, np.float64)
        self.leaf_count = np.zeros(num_leaves, np.int64)
        self.shrinkage = 1.0

    @property
    def num_nodes(self) -> int:
        return self.num_leaves - 1

    def shrink(self, rate: float) -> None:
        """Tree::Shrinkage (tree.h:139-147)."""
        self.leaf_value *= rate
        self.internal_value *= rate
        self.shrinkage *= rate

    def predict_table(self, max_nodes: int, max_leaves: int,
                      cat_words: Optional[int] = None) -> tree_mod.PredictTree:
        """Pad to model-wide fixed shapes for stacked device prediction."""
        return tree_mod.pack_predict_table(self, max_nodes, max_leaves,
                                           cat_words)


def _pad_feature_meta(meta: FeatureMeta, fpad: int) -> FeatureMeta:
    """Append `fpad` unusable (num_bin=1) features for even column sharding."""
    if fpad <= 0:
        return meta
    return FeatureMeta(
        num_bin=jnp.concatenate([meta.num_bin,
                                 jnp.ones((fpad,), jnp.int32)]),
        missing_type=jnp.concatenate([meta.missing_type,
                                      jnp.zeros((fpad,), jnp.int32)]),
        default_bin=jnp.concatenate([meta.default_bin,
                                     jnp.zeros((fpad,), jnp.int32)]),
        is_categorical=jnp.concatenate([meta.is_categorical,
                                        jnp.zeros((fpad,), bool)]),
        penalty=jnp.concatenate([meta.penalty,
                                 jnp.ones((fpad,), jnp.float32)]),
        monotone=jnp.concatenate([meta.monotone,
                                  jnp.zeros((fpad,), jnp.int32)]),
        # padding only happens on meshes, where EFB is off -> identity layout
        col=jnp.concatenate([meta.col,
                             jnp.arange(meta.col.shape[0],
                                        meta.col.shape[0] + fpad,
                                        dtype=jnp.int32)]),
        offset=jnp.concatenate([meta.offset, jnp.zeros((fpad,), jnp.int32)]),
        bundled=jnp.concatenate([meta.bundled, jnp.zeros((fpad,), bool)]),
        pack_div=jnp.concatenate([meta.pack_div,
                                  jnp.ones((fpad,), jnp.int32)]),
        pack_mod=jnp.concatenate([meta.pack_mod,
                                  jnp.zeros((fpad,), jnp.int32)]),
        pack_partner=jnp.concatenate([meta.pack_partner,
                                      jnp.ones((fpad,), jnp.int32)]))


def _feature_meta_from_dataset(ds: BinnedDataset, config: Config) -> FeatureMeta:
    f = ds.num_features
    num_bin = np.array([ds.feature_num_bin(j) for j in range(f)], np.int32)
    missing = np.array(
        [ds.bin_mappers[ds.used_features[j]].missing_type for j in range(f)],
        np.int32)
    default_bin = np.array(
        [ds.bin_mappers[ds.used_features[j]].default_bin for j in range(f)],
        np.int32)
    is_cat = np.array(
        [ds.bin_mappers[ds.used_features[j]].bin_type == BinType.CATEGORICAL
         for j in range(f)], bool)
    penalty = np.ones(f, np.float32)
    if config.feature_contri:
        fc = np.asarray(config.feature_contri, np.float32)
        for j in range(f):
            rj = ds.used_features[j]
            if rj < len(fc):
                penalty[j] = fc[rj]
    monotone = np.zeros(f, np.int32)
    if config.monotone_constraints:
        mc = np.asarray(config.monotone_constraints, np.int32)
        # reference CHECKs the constraint list covers every feature
        # (dataset.cpp:295); silently zero-filling would violate the
        # constraints the user asked for
        check(len(mc) == ds.num_total_features,
              "monotone_constraints has %d entries but the dataset has %d "
              "features" % (len(mc), ds.num_total_features))
        for j in range(f):
            monotone[j] = mc[ds.used_features[j]]
    (feat_col, feat_offset, feat_bundled, pack_div, pack_mod,
     pack_partner) = ds.feature_layout()
    return FeatureMeta(
        num_bin=jnp.asarray(num_bin), missing_type=jnp.asarray(missing),
        default_bin=jnp.asarray(default_bin), is_categorical=jnp.asarray(is_cat),
        penalty=jnp.asarray(penalty), monotone=jnp.asarray(monotone),
        col=jnp.asarray(feat_col), offset=jnp.asarray(feat_offset),
        bundled=jnp.asarray(feat_bundled),
        pack_div=jnp.asarray(pack_div), pack_mod=jnp.asarray(pack_mod),
        pack_partner=jnp.asarray(pack_partner))


def _hist_dtype(cfg: Config) -> str:
    """Histogram accumulation dtype: tpu_hist_dtype is the explicit knob,
    gpu_use_dp (config.h:784) the reference-compatible alias for f64."""
    spelled = str(cfg.tpu_hist_dtype).strip().lower()
    if spelled in ("float64", "f64", "double"):
        return "f64"
    if spelled not in ("float32", "f32", "single", ""):
        raise LightGBMError("unknown tpu_hist_dtype %r "
                            "(use float32 or float64)" % cfg.tpu_hist_dtype)
    return "f64" if cfg.gpu_use_dp else "f32"


def _resolve_hist_impl(cfg: Config) -> str:
    """Histogram-kernel dispatch (the GPUTreeLearner device-path analog,
    tree_learner.cpp:9-31): CPU -> XLA scatter-add; device -> the Pallas
    VMEM-accumulator kernel, with one-hot matmul as the explicit fallback.
    gpu_use_dp (config.h:784) means what it means in the reference:
    DOUBLE-precision histogram accumulation. The Pallas kernels are
    f32-only, so dp routes to the XLA paths (scatter / one-hot matmul),
    which accumulate in the value dtype — f64 once the GBDT driver casts
    the stacked values (GrowParams.hist_dtype). Users who want the f32
    Precision.HIGHEST kernel without f64 cost ask for
    tpu_hist_impl=pallas_highest explicitly."""
    impl = cfg.tpu_hist_impl
    if _hist_dtype(cfg) == "f64":
        if impl == "auto" or impl.startswith("pallas"):
            if impl.startswith("pallas"):
                Log.warning("f64 histograms: the f32-only Pallas kernel "
                            "%s is replaced by the f64 XLA path" % impl)
            return ("scatter" if jax.default_backend() == "cpu"
                    else "matmul")
        return impl
    if impl == "auto":
        impl = ("scatter" if jax.default_backend() == "cpu" else "pallas")
    return impl


class GBDT:
    """Boosting driver (include/LightGBM/boosting.h:22-294, gbdt.{h,cpp})."""

    boosting_type = "gbdt"
    average_output = False

    def __init__(self, config: Config, train_data: Optional[BinnedDataset],
                 objective: Optional[ObjectiveFunction],
                 metrics: Optional[List[Metric]] = None):
        self.config = config
        if getattr(config, "fault_inject", ""):
            # arm the deterministic fault plan (docs/Resilience.md) before
            # any seam can fire; identical (spec, seed) re-installs keep
            # fire counts across in-process supervised restarts
            from ..resilience import faults
            faults.install_plan(config.fault_inject, config.fault_seed)
        if getattr(config, "compile_cache_dir", ""):
            # persistent XLA compile cache: wired before the first jit so
            # every executable this booster builds is cacheable — warm
            # starts (same shapes, same jax) then compile nothing
            from ..profiling import enable_compile_cache
            enable_compile_cache(config.compile_cache_dir)
        if _hist_dtype(config) == "f64" and not jax.config.jax_enable_x64:
            # reference gpu_use_dp = double-precision histograms
            # (config.h:784); jax needs x64 enabled for f64 to exist at
            # trace time. Process-wide, explicit user opt-in.
            Log.info("gpu_use_dp=true: enabling jax x64 mode for "
                     "double-precision histogram accumulation")
            # lgbm-lint: disable=LGL105 explicit gpu_use_dp user opt-in
            jax.config.update("jax_enable_x64", True)
        self.train_data = train_data
        self.objective = objective
        self.train_metrics = metrics or []
        self.valid_data: List[BinnedDataset] = []
        self.valid_metrics: List[List[Metric]] = []
        # Async driver state: trained trees stay on device ([K, T] packed
        # int32 buffers, core/pack.py) and are materialized to HostTrees in
        # batched flushes — one device->host transfer per flush instead of
        # ~20 per iteration. `_models` is the materialized list; `models` is
        # a flushing property.
        self._models: List[HostTree] = []
        self._pending: List[Dict[str, Any]] = []
        self._stopped = False
        self._stopped_dev = jnp.asarray(False)  # device-side stop latch
        self._flush_every = 64
        self.iter_ = 0
        self.num_init_iteration = 0
        self.best_score: Dict[Any, Dict[str, float]] = {}
        self.num_class = config.num_class
        self.num_tree_per_iteration = (
            objective.num_model_per_iteration if objective is not None
            else max(1, config.num_class))
        self.shrinkage_rate = config.learning_rate
        # subclasses (RF) force the grad_in/hess_in path even with an objective
        self._use_input_grads = False
        self.mesh = None
        self._row_valid = None
        self._frontier_rs = False
        # out-of-core streamed training (lightgbm_tpu.stream): the chunk
        # pipeline, the host-driven grower, and its pre/post jits — set by
        # _setup_train when the dataset is a StreamedDataset
        self._stream = None
        self._stream_grower = None
        self._stream_pre = None
        self._stream_post = None
        self._stream_capture = ()
        self._stream_layout = None
        self._stream_perm = None
        self._stream_col_pad = 0
        # observability facade (lightgbm_tpu.obs): replaced by the
        # config-driven one in _setup_train; loaded/predict-only boosters
        # keep the disabled no-op
        from ..obs.runtime import TrainingObs
        self.obs = TrainingObs.disabled()

        if train_data is not None:
            self._setup_train(train_data)

    # ------------------------------------------------------------ setup
    def _setup_stream_mesh(self, ds) -> np.ndarray:
        """Chunks x chips: validate the topology, build the sharded chunk
        pipeline, and fix the SHARD-MAJOR padded row layout (see
        stream/pipeline.py). Returns ``row_valid`` in that layout.

        Two topologies land here: a multi-process run whose dataset was
        ingested through a ``ShardedSource`` (each process holds exactly
        its rank's row block — ``shard_world`` must equal the data-axis
        size), and a single-process multi-device run whose resident chunk
        list is split into contiguous rank-ordered blocks on the spot
        with the same shard-assignment contract.
        """
        cfg = self.config
        from ..parallel import mesh as mesh_mod
        from ..stream.pipeline import (ShardedChunkPipeline,
                                       shard_rows_host, shard_rows_perm,
                                       split_chunks_rows)
        from ..stream.source import shard_offsets
        mesh = self.mesh
        axis = mesh_mod.DATA_AXIS
        if axis not in mesh.axis_names:
            raise LightGBMError(
                "streamed mesh training is data-parallel only: mesh_shape "
                "must map the %r axis (got axes %s)"
                % (axis, list(mesh.axis_names)))
        fsize = (mesh.shape[mesh_mod.FEATURE_AXIS]
                 if mesh_mod.FEATURE_AXIS in mesh.axis_names else 1)
        if fsize > 1:
            raise LightGBMError(
                "streamed training cannot shard the feature axis (the "
                "chunk stream is row-partitioned); use a pure data mesh "
                "(tree_learner=data|voting) or set "
                "data_stream_chunk_rows=0")
        if int(cfg.data_stream_chunk_rows) <= 0:
            raise LightGBMError(
                "streamed mesh training needs an explicit "
                "data_stream_chunk_rows: the per-wave kernel shapes must "
                "agree on every process")
        dsize = int(mesh.shape[axis])
        # reduce-scatter wave histograms need the stored columns to tile
        # over the data axis (DataRSLearner); pad columns here and the
        # feature metadata below with unusable num_bin=1 entries
        self._frontier_rs = (
            cfg.tree_learner == "data"
            and bool(cfg.tpu_frontier_rs)
            and _hist_dtype(cfg) != "f64")
        ncols = int(ds.chunks[0].shape[1]) if ds.chunks \
            else int(ds.num_columns)
        col_pad = (-ncols) % dsize if self._frontier_rs else 0
        self._stream_col_pad = col_pad
        world = int(getattr(ds, "shard_world", 1) or 1)
        if world > 1:
            if world != dsize:
                raise LightGBMError(
                    "dataset is sharded %d ways but the mesh data axis "
                    "has %d positions; ShardedSource world must equal "
                    "the data-axis size" % (world, dsize))
            counts = [int(c) for c in ds.shard_row_counts]
            shard_chunks = [ds.chunks]
        else:
            if jax.process_count() > 1:
                raise LightGBMError(
                    "multi-process streamed training needs a sharded "
                    "ingest: wrap the source in stream.source."
                    "ShardedSource(rank, world) so each process streams "
                    "only its row block")
            offs = shard_offsets(ds.num_data, dsize)
            counts = [offs[p + 1] - offs[p] for p in range(dsize)]
            shard_chunks = split_chunks_rows(ds.chunks, offs)
        self._stream = ShardedChunkPipeline(
            shard_chunks, counts, int(cfg.data_stream_chunk_rows), mesh,
            prefetch=int(cfg.data_stream_prefetch), col_pad=col_pad)
        if world > 1 and \
                self._stream.local_shards != [int(ds.shard_rank)]:
            raise LightGBMError(
                "shard/mesh misalignment: this process ingested shard %d "
                "but addresses mesh position(s) %s — keep process rank "
                "order equal to shard rank order"
                % (int(ds.shard_rank), self._stream.local_shards))
        offs = self._stream.shard_offsets()
        local_padded = self._stream.local_padded
        self._stream_layout = (
            lambda a, _o=offs, _n=local_padded: shard_rows_host(a, _o, _n))
        self._stream_perm = shard_rows_perm(offs, local_padded)
        return shard_rows_host(np.ones(ds.num_data, np.float32), offs,
                               local_padded)

    def _setup_train(self, ds: BinnedDataset) -> None:
        cfg = self.config
        from ..parallel import mesh as mesh_mod
        self.mesh = mesh_mod.build_mesh(cfg)
        self.num_data_orig = ds.num_data
        xb_np = ds.X_binned
        row_valid = None
        streamed = bool(getattr(ds, "is_streamed", False))
        self._stream_layout = None   # host [n0,...] -> padded-layout rows
        self._stream_perm = None     # padded index of each original row
        self._stream_col_pad = 0
        if streamed:
            # out-of-core path: the bin matrix exists only as host chunks;
            # everything per-row stays device-resident at padded length
            if cfg.tree_growth != "frontier":
                raise LightGBMError(
                    "streamed training requires tree_growth=frontier")
            if _hist_dtype(cfg) == "f64":
                # the satellite gate for streamed mesh + f64 is this same
                # branch: every streamed run accumulates f32 wave
                # histograms (config.py pre-validates the mesh spelling)
                raise LightGBMError(
                    "streamed training accumulates f32 wave histograms; "
                    "set gpu_use_dp=false" + (
                        " (streamed + mesh_shape requires f32)"
                        if self.mesh is not None else ""))
            if self.mesh is not None:
                row_valid = self._setup_stream_mesh(ds)
            else:
                if int(getattr(ds, "shard_world", 1) or 1) > 1:
                    raise LightGBMError(
                        "dataset was ingested as shard %d/%d but no mesh "
                        "is configured; set mesh_shape=[%d] (or ingest "
                        "without a ShardedSource)"
                        % (ds.shard_rank, ds.shard_world, ds.shard_world))
                from ..core.binpack import resolve_bin_packing
                from ..stream.pipeline import ChunkPipeline
                chunk_cap = int(cfg.data_stream_chunk_rows) or \
                    max(1, max(ds.chunk_row_counts))
                # packed host chunks (core/binpack.py): word-pack at repack
                # time so every host->device transfer ships the
                # kernel-native int32-word layout; under
                # tpu_bin_packing=nibble the DATASET pair coding already
                # halved the stored columns, so the per-row transfer bytes
                # halve with it
                stream_packed = resolve_bin_packing(
                    cfg.tpu_bin_packing, streamed=True,
                    tpu_shaped=partition_mod.tpu_shaped_backend(),
                    col_num_bin=list(ds.col_num_bin)) != "none"
                self._stream = ChunkPipeline(
                    ds.chunks, chunk_cap,
                    prefetch=int(cfg.data_stream_prefetch),
                    packed=stream_packed)
                pad = self._stream.num_padded - ds.num_data
                if pad:
                    row_valid = np.concatenate(
                        [np.ones(ds.num_data, np.float32),
                         np.zeros(pad, np.float32)])
        if self.mesh is not None and not streamed:
            # pad rows to a multiple of the data-axis size so every shard is
            # even; padded rows carry mask 0 everywhere (the distributed
            # loader's row partition, dataset_loader.cpp:469-495, without the
            # loss of remainder rows)
            axis = mesh_mod.DATA_AXIS
            dsize = (self.mesh.shape[axis]
                     if axis in self.mesh.axis_names else 1)
            pad = (-ds.num_data) % dsize
            if pad:
                xb_np = np.concatenate(
                    [xb_np, np.zeros((pad, xb_np.shape[1]), xb_np.dtype)])
            if pad:
                row_valid = np.concatenate(
                    [np.ones(ds.num_data, np.float32),
                     np.zeros(pad, np.float32)])
            # feature-parallel: pad columns to a multiple of the feature axis
            # so the [N, F] bin matrix shards evenly; padded columns get
            # num_bin=1 metadata which the split search treats as unusable
            fsize = (self.mesh.shape[mesh_mod.FEATURE_AXIS]
                     if mesh_mod.FEATURE_AXIS in self.mesh.axis_names else 1)
            # frontier data-parallel reduce-scatter (parallel/learners.py
            # DataRSLearner): the per-wave psum_scatter tiles the feature
            # axis over the DATA axis, so columns must also divide dsize
            self._frontier_rs = (
                cfg.tree_growth == "frontier"
                and cfg.tree_learner == "data"
                and mesh_mod.DATA_AXIS in self.mesh.axis_names
                and bool(cfg.tpu_frontier_rs)
                and _hist_dtype(cfg) != "f64")
            if self._frontier_rs:
                fsize = fsize * dsize // math.gcd(fsize, dsize)
            fpad = (-xb_np.shape[1]) % fsize
            if fpad:
                xb_np = np.concatenate(
                    [xb_np, np.zeros((xb_np.shape[0], fpad), xb_np.dtype)],
                    axis=1)
        if self.mesh is not None and (ds.has_bundles or ds.has_packed):
            raise LightGBMError(
                "EFB bundles / nbit-packed columns are not yet supported "
                "with a device mesh; set enable_bundle=false and "
                "enable_nbit_packing=false for distributed training")
        self.num_data = (self._stream.num_padded if streamed
                         else xb_np.shape[0])
        self._feature_pad = (self._stream_col_pad if streamed
                             else xb_np.shape[1] - ds.num_columns)
        self._row_valid = (jnp.asarray(row_valid) if row_valid is not None
                           else None)
        self.feature_meta = _pad_feature_meta(
            _feature_meta_from_dataset(ds, cfg), self._feature_pad)
        self.num_bins = max(ds.max_col_bins(), 2)
        self.num_feat_bins = max(ds.max_num_bin(), 2)
        # explicit feature-parallel (feature_parallel_tree_learner.cpp:
        # 30-60): rows REPLICATED, search work divided by a bin-balanced
        # column assignment, best splits argmax-allreduced as structs.
        # Order-dependent extras (forced splits, CEGB) keep the GSPMD
        # fallback, whose comm the partitioner infers.
        self._explicit_fp = (
            self.mesh is not None
            and cfg.tree_learner == "feature"
            and _hist_dtype(cfg) == "f32"  # sync_best_split bitcasts f32
            and mesh_mod.FEATURE_AXIS in self.mesh.axis_names
            and not cfg.forcedsplits_filename
            and not cfg.cegb_penalty_feature_coupled
            and not cfg.cegb_penalty_feature_lazy
            and cfg.cegb_penalty_split <= 0)
        self.xb = None if streamed else jnp.asarray(xb_np)
        self._fp_capture = None
        if self._explicit_fp:
            # xb stays replicated (every FP worker holds the full data,
            # like the reference's feature-parallel machines); each device
            # additionally gets its own column slice for histogram work
            self._fp_capture = self._setup_feature_parallel(xb_np)
        elif self.mesh is not None and self.xb is not None:
            self.xb = jax.device_put(
                self.xb, mesh_mod.feature_sharding(self.mesh))
        if self.objective is not None:
            self.objective.init(ds.metadata, ds.num_data)
            if self.mesh is not None:
                # streamed mesh: per-row arrays go to the shard-major
                # padded layout instead of trailing-padding
                self.objective.pad_to(self.num_data, self.mesh,
                                      layout=self._stream_layout)
            elif streamed and self.num_data > ds.num_data:
                # chunk-uniform padding: per-row objective arrays stretch
                # to the padded length; padded rows are masked everywhere
                self.objective.pad_to(self.num_data)
        for m in self.train_metrics:
            m.init(ds.metadata, ds.num_data)

        self._forced_splits, num_forced = self._setup_forced_splits()
        self._cegb_state = self._setup_cegb()
        # histogram pool cap (histogram_pool_size MB, config.h; the
        # HistogramPool LRU of feature_histogram.hpp:646-820). -1 = one
        # slot per leaf.
        pool_slots = 0
        # mesh modes keep the full pool: the rebuild-on-miss cond cannot
        # hold the psum a sharded rebuild needs (same SPMD constraint the
        # growth loop documents for its dead-iteration histograms)
        if cfg.histogram_pool_size > 0 and cfg.tree_learner != "voting" \
                and self.mesh is None and not streamed:
            bytes_per_hist = xb_np.shape[1] * self.num_bins * 3 * 4
            pool_slots = int(cfg.histogram_pool_size * 1024 * 1024
                             // max(bytes_per_hist, 1))
            pool_slots = max(2, min(cfg.num_leaves, pool_slots))
            if pool_slots >= cfg.num_leaves:
                pool_slots = 0  # cap larger than the full pool: uncapped
        if cfg.tree_learner == "voting" and self.mesh is not None and \
                (num_forced > 0 or self._cegb_state is not None):
            raise LightGBMError("forced splits / CEGB are not supported "
                                "with the voting-parallel tree learner")

        # batched-frontier growth (core/grow_batched.py) and frontier-wave
        # growth (core/grow_frontier.py): both incompatible with anything
        # whose bookkeeping depends on exact one-split-at-a-time ordering
        batch_splits = 0
        frontier_mode = False
        if cfg.tree_growth in ("batched", "frontier"):
            mode = "tree_growth=%s" % cfg.tree_growth
            if num_forced > 0 or self._cegb_state is not None:
                raise LightGBMError(
                    mode + " requires exact split ordering; disable forced "
                    "splits / CEGB or use tree_growth=exact")
            # the frontier wave grower carries the voting-parallel election
            # (parallel/learners.py VotingLearner); batched growth and the
            # explicit feature-parallel learner still need exact ordering /
            # the grow_tree fp context
            if cfg.tree_learner == "feature" or (
                    cfg.tree_learner == "voting"
                    and cfg.tree_growth != "frontier"):
                raise LightGBMError(
                    mode + " does not support tree_learner=%s (serial and "
                    "data always work; voting needs tree_growth=frontier)"
                    % cfg.tree_learner)
            if _hist_dtype(cfg) == "f64":
                # both wave growers accumulate f32 (slot kernel layout);
                # silently downgrading would betray the dp promise
                Log.warning(mode + " does not support f64 histograms yet; "
                            "falling back to exact growth")
            elif cfg.tree_growth == "frontier":
                frontier_mode = True
            else:
                batch_splits = min(cfg.tree_batch_splits,
                                   cfg.num_leaves - 1)
        # multiclass class batching: vmapped growth measured 1.9x SLOWER
        # than sequential per-class growth on a v5e chip (1.65 vs 0.88
        # s/iter at 500k x 28 x 5 classes, tools/onchip_r4_results.json
        # "multiclass") — vmap serializes the growth while_loop in
        # lockstep AND forces the sort-placement fast path off. TPU-shaped
        # backends (the same allow-list predicate the sort-placement
        # policy uses — NOT a hist-impl proxy, so f64/matmul TPU runs are
        # covered too) therefore grow classes sequentially even with an
        # uncapped pool; vmap remains the CPU default, where it wins.
        vmapped = (self.num_tree_per_iteration > 1 and pool_slots == 0
                   and not partition_mod.tpu_shaped_backend())
        # partitioned batched growth (core/grow_batched_part.py): a GSPMD
        # mesh path must keep it off (the per-step permutation would
        # shuffle rows across devices) — the explicit shard_map
        # data-parallel learner partitions each LOCAL shard and may use it.
        part_ok = (batch_splits > 0 and not vmapped
                   and (self.mesh is None
                        or (cfg.tree_learner == "data"
                            and mesh_mod.DATA_AXIS in self.mesh.axis_names)))
        if cfg.tpu_batched_part in ("true", "1"):
            if not part_ok and batch_splits > 0:
                Log.warning("tpu_batched_part=true is unsupported here "
                            "(vmapped multiclass or GSPMD mesh path); "
                            "using the unpartitioned batched step")
            batched_part = part_ok
        elif cfg.tpu_batched_part in ("false", "0"):
            batched_part = False
        else:
            # auto = OFF: measured on a v5e chip the per-step permutation
            # (XLA gather ~2.3 GB/s) and per-tile DMA latency make the
            # partitioned step LOSE to both exact growth and the joint
            # slot kernel at 1M x 28 (docs/Performance.md round-4 table);
            # revisit if those two costs change
            batched_part = False

        # explicit shard_map data-parallel learner: every device partitions
        # its local row shard and only child histograms cross the mesh
        # (data_parallel_tree_learner.cpp:146-161). Forced splits rebuild
        # leaf histograms straight-line + psum (grow.py leaf_hist), and
        # CEGB state threads through the shard_map with row_used sharded —
        # neither drops this learner to the masked fallback anymore.
        self._partition_on_mesh = (
            self.mesh is not None
            and cfg.tree_learner == "data"
            and mesh_mod.DATA_AXIS in self.mesh.axis_names)

        # observability: built before grow_params so the device-side
        # health piggy-back (GrowParams.obs_health) keys off the resolved
        # health action
        from ..obs.runtime import TrainingObs
        self.obs = TrainingObs.from_config(cfg)

        # resolved once: _resolve_hist_impl logs a user-facing warning on
        # the f64-routes-off-pallas path, which must not repeat per call
        hist_impl = _resolve_hist_impl(cfg)
        # packed-bin device matrix (core/binpack.py): the int32-word
        # layout rides the frontier grower on single-device in-memory
        # runs — mesh learners shard the feature axis of the plain
        # matrix, and streamed chunks pack per-chunk in the pipeline.
        # nibble vs byte only matters at the DATASET level (pair
        # coding); on device both store 8-bit codes 4-per-word, so the
        # decision here is solely mode != "none".
        word_packed_cols = 0
        if streamed:
            if self._stream.packed:
                word_packed_cols = int(self._stream.num_cols)
        elif frontier_mode and self.mesh is None:
            from ..core.binpack import resolve_bin_packing
            pack_mode = resolve_bin_packing(
                cfg.tpu_bin_packing, streamed=False,
                tpu_shaped=partition_mod.tpu_shaped_backend(),
                col_num_bin=list(ds.col_num_bin))
            if pack_mode != "none":
                word_packed_cols = int(xb_np.shape[1])
        self.grow_params = GrowParams(
            num_leaves=cfg.num_leaves,
            num_bins=self.num_bins,
            max_depth=cfg.max_depth,
            num_forced=num_forced,
            pool_slots=pool_slots,
            cegb_split_penalty=float(cfg.cegb_tradeoff
                                     * cfg.cegb_penalty_split),
            with_cegb_coupled=bool(len(cfg.cegb_penalty_feature_coupled)),
            with_cegb_lazy=bool(len(cfg.cegb_penalty_feature_lazy)),
            split=SplitParams(
                lambda_l1=cfg.lambda_l1, lambda_l2=cfg.lambda_l2,
                max_delta_step=cfg.max_delta_step,
                min_data_in_leaf=cfg.min_data_in_leaf,
                min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf,
                min_gain_to_split=cfg.min_gain_to_split,
                max_cat_threshold=cfg.max_cat_threshold,
                cat_smooth=cfg.cat_smooth, cat_l2=cfg.cat_l2,
                max_cat_to_onehot=cfg.max_cat_to_onehot,
                min_data_per_group=cfg.min_data_per_group),
            # 0 = auto: 4096 on TPU (round-4 on-chip sweep: 1.97 vs 1.80
            # iters/s at 16384; 65536+ strictly worse), 16384 on CPU
            # (fewer while-loop trips win when indexed ops are cheap)
            row_chunk=(int(cfg.tpu_row_chunk) or
                       (4096 if hist_impl.startswith("pallas")
                        else 16384)),
            # CPU: XLA scatter-add wins; TPU: the Pallas VMEM-accumulator
            # kernel is the default device path (the GPUTreeLearner analog,
            # gpu_tree_learner.cpp:951-1045) — one-hot matmul is the fallback
            hist_impl=hist_impl,
            hist_dtype=_hist_dtype(cfg),
            voting_top_k=(cfg.top_k if cfg.tree_learner == "voting"
                          and self.mesh is not None else 0),
            with_categorical=bool(np.asarray(self.feature_meta.is_categorical)
                                  .any()),
            use_partition=(self.mesh is None or self._partition_on_mesh),
            partition_on_mesh=self._partition_on_mesh,
            vmapped_classes=vmapped,
            batch_splits=batch_splits,
            batched_pack=(batch_splits > 0 and cfg.tpu_batched_pack),
            batched_part=batched_part,
            frontier_mode=frontier_mode,
            # reduce-scatter wave histograms (DataRSLearner): resolved at
            # padding time — needs frontier + data learner + a data axis +
            # tpu_frontier_rs + f32 histograms (and columns padded to the
            # axis size, which _frontier_rs guaranteed above)
            frontier_rs=(frontier_mode and self._frontier_rs),
            # wave-width bucketing: single-device vmapped multiclass now
            # routes to grow_tree_frontier_classes, which hoists the
            # width switch OUTSIDE the vmap (an unbatched branch index),
            # so bucketing stays on there; it remains off for vmapped
            # growth over a mesh, where vmapping the shard_map'd grower
            # would lower the switch to execute-ALL-branches. Also off
            # when streaming: a ladder would multiply the per-chunk
            # kernel set by its length and make the compiled-program
            # count depend on which widths a run visits (the perf gate
            # pins that count invariant in chunk count)
            frontier_bucketing=(frontier_mode
                                and not (vmapped and self.mesh is not None)
                                and not streamed
                                and bool(cfg.tpu_frontier_bucketing)),
            word_packed_cols=word_packed_cols,
            with_efb=ds.has_bundles or ds.has_packed,
            num_feat_bins=self.num_feat_bins,
            # single source of truth: the marginalization width IS the
            # largest pack_partner the layout recorded, and the packed
            # subset is wherever a mod was recorded
            pack_j=int(np.asarray(self.feature_meta.pack_partner).max()
                       if self.feature_meta.pack_partner is not None
                       and self.feature_meta.pack_partner.size else 1),
            packed_features=tuple(
                int(i) for i in np.nonzero(
                    np.asarray(self.feature_meta.pack_mod))[0])
            if self.feature_meta.pack_mod is not None else (),
            # frontier health piggy-back rides the single-device /
            # GSPMD growth call; the explicit shard_map learner slices
            # the aux slot off, so it stays off there (iteration-level
            # grad/hess health still applies on every path)
            obs_health=(frontier_mode and not self._partition_on_mesh
                        and not (streamed and self.mesh is not None)
                        and self.obs.health_enabled),
            # model statistics ride the same aux slot under the same
            # guard; the shard_map learners slice aux off, so they fall
            # back to host-side recomputation at materialize (the
            # streamed mesh grower carries no aux slot at all)
            obs_modelstats=(frontier_mode and not self._partition_on_mesh
                            and not (streamed and self.mesh is not None)
                            and bool(cfg.obs_modelstats)))

        self._word_packed_cols = word_packed_cols
        if word_packed_cols and not streamed:
            # replace the device matrix with its packed words NOW — the
            # uint8 copy was never materialized on device (self.xb above
            # is only committed lazily by jnp.asarray at first use on
            # CPU backends; repacking from the host array keeps this a
            # single transfer of the halved/word layout)
            from ..core.binpack import pack_words_np
            self.xb = jnp.asarray(pack_words_np(xb_np))
            Log.info("bin packing: %d uint8 columns stored as %d int32 "
                     "words/row on device (tpu_bin_packing=%s)",
                     word_packed_cols, self.xb.shape[1],
                     cfg.tpu_bin_packing)

        if streamed:
            if not frontier_mode:
                raise LightGBMError(
                    "streamed training requires the frontier wave grower "
                    "(tree_growth=frontier with f32 histograms)")
            from ..stream.grow_stream import StreamFrontierGrower
            self._stream_grower = StreamFrontierGrower(
                self._stream, self.feature_meta, self.grow_params,
                mesh=self.mesh)

        k = self.num_tree_per_iteration
        n = self.num_data
        n0 = self.num_data_orig
        init_scores = np.zeros((n, k), np.float32)
        # init score from file/metadata (ScoreUpdater ctor :32-51)
        if ds.metadata.init_score is not None:
            isc = np.asarray(ds.metadata.init_score, np.float32).reshape(-1)
            if len(isc) == n0 * k:
                vals = isc.reshape(k, n0).T
            else:
                vals = np.tile(isc.reshape(-1, 1), (1, k))
            if self._stream_layout is not None:
                init_scores = self._stream_layout(
                    np.asarray(vals, np.float32))
            else:
                init_scores[:n0] = vals
        self._init_scores_provided = ds.metadata.init_score is not None
        self.scores = jnp.asarray(init_scores)
        if self.mesh is not None:
            from ..parallel import mesh as mesh_mod
            self.scores = jax.device_put(
                self.scores, mesh_mod.row_sharding(self.mesh, extra_dims=1))
        self.boost_from_average_done = False
        self._rng = np.random.RandomState(cfg.feature_fraction_seed)
        self._bag_key = jax.random.PRNGKey(cfg.bagging_seed)
        self._bag_mask = jnp.ones((n,), jnp.float32)
        # group-aware bagging: under a ranking objective, bagging samples
        # whole QUERY GROUPS — one uniform per query broadcast to its rows
        # — never fractions of a query (a partial query corrupts every
        # pairwise lambda and NDCG normalizer within it). row_group maps
        # row -> query index; mesh-padding rows get a synthetic trailing
        # group (they are masked out by _row_valid regardless).
        self._row_group = None
        qb_meta = ds.metadata.query_boundaries
        if qb_meta is not None and \
                getattr(self.objective, "name", "") == "lambdarank":
            qb_arr = np.asarray(qb_meta, np.int64)
            groups = np.repeat(np.arange(len(qb_arr) - 1, dtype=np.int32),
                               np.diff(qb_arr))
            if len(groups) < n:
                groups = np.concatenate([
                    groups, np.full(n - len(groups), len(qb_arr) - 1,
                                    np.int32)])
            self._row_group = jnp.asarray(groups[:n])
            self._num_groups = int(len(qb_arr))  # num_queries + pad group
        self._compiled_iter = None
        self._iter_core = None
        self._compiled_block = None
        self._ladder_warmup: Optional[Dict[str, Any]] = None
        # shape bookkeeping for PULL-based cost-model extraction
        # (extract_cost_model): what the last fused block / flush looked
        # like, so extraction can mirror the exact programs that ran
        self._last_block_len = 0
        self._last_flush_shapes: List[Any] = []
        self._valid_pred_cache: Dict[int, jnp.ndarray] = {}
        # model statistics (obs.modelstats): host-side cumulative state,
        # fed from the frontier piggy-back when grow_params carries it
        # and recomputed from materialized trees otherwise
        self._modelstats = None
        if cfg.obs_modelstats:
            from ..obs.modelstats import ModelStats
            self._modelstats = ModelStats(
                ds.num_total_features, feature_names=ds.feature_names,
                inner_to_real=[ds.real_feature_index(i)
                               for i in range(ds.num_features)],
                registry=self.obs.registry, events=self.obs.events)

    def add_valid_data(self, ds: BinnedDataset, metrics: List[Metric]) -> None:
        for m in metrics:
            m.init(ds.metadata, ds.num_data)
        self.valid_data.append(ds)
        self.valid_metrics.append(metrics)
        # device copy of binned valid features + running scores
        k = self.num_tree_per_iteration
        init = np.zeros((ds.num_data, k), np.float32)
        if ds.metadata.init_score is not None:
            isc = np.asarray(ds.metadata.init_score, np.float32).reshape(-1)
            if len(isc) == ds.num_data * k:
                init = isc.reshape(k, ds.num_data).T.copy()
            else:
                init = np.tile(isc.reshape(-1, 1), (1, k))
        cache = {
            "xb": jnp.asarray(ds.X_binned),
            "scores": jnp.asarray(init),
        }
        self._valid_pred_cache[len(self.valid_data) - 1] = cache
        self._materialize()
        if self._models and ds.metadata.init_score is None:
            # continued training: valid scores must include the merged init
            # model's trees (score_updater.hpp:32-51). Binned replay works
            # for matrix- and file-backed valid sets alike.
            for i, ht in enumerate(self._models):
                c = i % k
                leaf = self._replay_leaves_binned(ht, cache["xb"])
                cache["scores"] = cache["scores"].at[:, c].add(
                    jnp.asarray(ht.leaf_value.astype(np.float32))[leaf])

    # ------------------------------------------------------------ training
    def _boost_from_average(self) -> None:
        """gbdt.cpp:298-331: seed scores with the objective's init score."""
        if (self.boost_from_average_done or self.objective is None
                or not self.config.boost_from_average
                or self._init_scores_provided):
            self.boost_from_average_done = True
            return
        k = self.num_tree_per_iteration
        inits = np.array([self.objective.boost_from_score(c) for c in range(k)],
                         np.float32)
        if np.any(inits != 0):
            self.scores = self.scores + jnp.asarray(inits)[None, :]
            for vd in self._valid_pred_cache.values():
                vd["scores"] = vd["scores"] + jnp.asarray(inits)[None, :]
            self.init_score_offsets = inits
        else:
            self.init_score_offsets = np.zeros(k, np.float32)
        self.boost_from_average_done = True

    def _setup_forced_splits(self):
        """Parse forcedsplits_filename into BFS step arrays (the ForceSplits
        queue walk, serial_tree_learner.cpp:593-751, linearized at setup
        because the leaf numbering is deterministic: step t's right child
        is leaf t + 1). Returns (ForcedSplits | None, count)."""
        fname = self.config.forcedsplits_filename
        if not fname:
            return None, 0
        import json as _json
        from collections import deque
        with open(fname) as fh:
            root = _json.load(fh)
        if not root:
            return None, 0
        ds = self.train_data
        inner_of = {real: i for i, real in enumerate(ds.used_features)}
        leaf_arr: List[int] = []
        feat_arr: List[int] = []
        thr_arr: List[int] = []
        q = deque([(root, 0)])
        t = 0
        while q and t < self.config.num_leaves - 1:
            node, leaf = q.popleft()
            real_f = int(node["feature"])
            check(real_f in inner_of,
                  "forced split feature %d is trivial/unused" % real_f)
            mapper = ds.bin_mappers[real_f]
            check(mapper.bin_type != BinType.CATEGORICAL,
                  "forced splits on categorical features are not supported")
            # rows with bin < ValueToBin(threshold) go left (BinThreshold,
            # dataset.h:507); our convention is `<= bin`, so -1 legitimately
            # means "empty left" — the forced split then aborts on
            # left_count == 0, like the reference's negative-gain gather
            tb = mapper.value_to_bin(float(node["threshold"])) - 1
            leaf_arr.append(leaf)
            feat_arr.append(inner_of[real_f])
            thr_arr.append(tb)
            right_leaf = t + 1
            if isinstance(node.get("left"), dict):
                q.append((node["left"], leaf))
            if isinstance(node.get("right"), dict):
                q.append((node["right"], right_leaf))
            t += 1
        from ..core.grow import ForcedSplits
        return ForcedSplits(leaf=jnp.asarray(leaf_arr, jnp.int32),
                            feature=jnp.asarray(feat_arr, jnp.int32),
                            threshold=jnp.asarray(thr_arr, jnp.int32)), t

    def _setup_feature_parallel(self, xb_np: np.ndarray):
        """Bin-balanced per-device column assignment for the explicit
        feature-parallel learner (the reference balances workers by bin
        count, feature_parallel_tree_learner.cpp:30-60). Returns
        (xb_cols [D, N, Cd], meta_local FeatureMeta of [D, Fd] arrays,
        global_of_local [D, Fd]) device_put so device d holds row d.

        Requires no EFB/packing (columns == features), which _setup_train
        already enforces for meshes."""
        from ..parallel import mesh as mesh_mod
        from jax.sharding import NamedSharding, PartitionSpec as P
        d = self.mesh.shape[mesh_mod.FEATURE_AXIS]
        n, f = xb_np.shape
        meta = self.feature_meta
        num_bin = np.asarray(meta.num_bin)
        # greedy: biggest feature to the least-loaded device
        order = np.argsort(-num_bin, kind="stable")
        loads = np.zeros(d, np.int64)
        assign: List[List[int]] = [[] for _ in range(d)]
        for j in order:
            dev = int(np.argmin(loads))
            assign[dev].append(int(j))
            loads[dev] += max(int(num_bin[j]), 1)
        fd = max(max(len(a) for a in assign), 1)
        xb_cols = np.zeros((d, n, fd), xb_np.dtype)
        gofl = np.full((d, fd), -1, np.int32)
        local = {"num_bin": np.ones((d, fd), np.int32),
                 "missing_type": np.zeros((d, fd), np.int32),
                 "default_bin": np.zeros((d, fd), np.int32),
                 "is_categorical": np.zeros((d, fd), bool),
                 "penalty": np.ones((d, fd), np.float32),
                 "monotone": np.zeros((d, fd), np.int32)}
        for dev, cols in enumerate(assign):
            if not cols:
                continue
            cc = np.asarray(cols, np.int64)
            xb_cols[dev, :, :len(cols)] = xb_np[:, cc]
            gofl[dev, :len(cols)] = cc
            for name in local:
                local[name][dev, :len(cols)] = np.asarray(
                    getattr(meta, name))[cc]
        meta_local = FeatureMeta(
            num_bin=jnp.asarray(local["num_bin"]),
            missing_type=jnp.asarray(local["missing_type"]),
            default_bin=jnp.asarray(local["default_bin"]),
            is_categorical=jnp.asarray(local["is_categorical"]),
            penalty=jnp.asarray(local["penalty"]),
            monotone=jnp.asarray(local["monotone"]),
            col=jnp.tile(jnp.arange(fd, dtype=jnp.int32)[None], (d, 1)),
            offset=jnp.zeros((d, fd), jnp.int32),
            bundled=jnp.zeros((d, fd), bool))
        ax = mesh_mod.FEATURE_AXIS
        sh1 = NamedSharding(self.mesh, P(ax))
        # device_put straight from numpy: one sharded transfer, never a
        # full [D, N, Fd] copy committed to a single device first
        put = lambda a: jax.device_put(np.asarray(a), sh1)
        return (put(xb_cols),
                jax.tree.map(lambda a: put(a), meta_local),
                put(gofl))

    def _setup_cegb(self):
        """CEGB acquisition state (device-resident, persists across trees —
        SerialTreeLearner feature_used / feature_used_in_data,
        serial_tree_learner.cpp:103-112). None when CEGB is off."""
        cfg = self.config
        coupled = list(cfg.cegb_penalty_feature_coupled)
        lazy = list(cfg.cegb_penalty_feature_lazy)
        if not coupled and not lazy and cfg.cegb_penalty_split <= 0:
            return None
        from ..core.grow import CegbState
        f = int(self.feature_meta.num_bin.shape[0])
        ds = self.train_data
        coupled_arr = np.zeros(f, np.float32)
        lazy_arr = np.zeros(f, np.float32)
        for i, real in enumerate(ds.used_features):
            if coupled:
                check(real < len(coupled), "cegb_penalty_feature_coupled "
                      "must cover every feature")
                coupled_arr[i] = cfg.cegb_tradeoff * float(coupled[real])
            if lazy:
                check(real < len(lazy), "cegb_penalty_feature_lazy "
                      "must cover every feature")
                lazy_arr[i] = cfg.cegb_tradeoff * float(lazy[real])
        n_lazy = self.num_data if lazy else 0
        return CegbState(
            coupled_penalty=jnp.asarray(coupled_arr),
            lazy_penalty=jnp.asarray(lazy_arr),
            feature_used=jnp.zeros((f,), bool),
            row_used=jnp.zeros((f, n_lazy), jnp.uint8))

    def _sample_feature_mask(self) -> jnp.ndarray:
        """Per-tree column sampling (serial_tree_learner.cpp:271-292)."""
        f = self.train_data.num_features
        fpad = getattr(self, "_feature_pad", 0)
        frac = self.config.feature_fraction
        if frac >= 1.0 or f == 0:
            return jnp.ones((f + fpad,), bool)
        used = max(1, int(f * frac))
        idx = self._rng.choice(f, used, replace=False)
        mask = np.zeros(f + fpad, bool)
        mask[idx] = True
        return jnp.asarray(mask)

    def _sample_bagging_mask(self, iter_idx: int) -> jnp.ndarray:
        """Row bagging (gbdt.cpp:180-241); resampled every bagging_freq.
        Ranking models bag whole query groups (one uniform per query,
        broadcast through ``_row_group``)."""
        cfg = self.config
        if cfg.bagging_freq <= 0 or cfg.bagging_fraction >= 1.0:
            return self._apply_row_valid(self._bag_mask)
        if iter_idx % cfg.bagging_freq == 0:
            self._bag_key, sub = jax.random.split(self._bag_key)
            if self._row_group is not None:
                u = jax.random.uniform(sub, (self._num_groups,))
                u = u[self._row_group]
            else:
                u = jax.random.uniform(sub, (self.num_data,))
            self._bag_mask = (u < cfg.bagging_fraction).astype(jnp.float32)
        return self._apply_row_valid(self._bag_mask)

    def _apply_row_valid(self, mask: jnp.ndarray) -> jnp.ndarray:
        """Exclude padded rows (even-sharding padding) from training."""
        if self._row_valid is not None:
            return mask * self._row_valid
        return mask

    def _make_train_iter_fn(self) -> Callable:
        """Build the jitted per-iteration function.

        Mesh-sharded constants (the binned matrix, the objective's per-row
        arrays) are ARGUMENTS, not closure captures: a multi-controller jit
        may not close over arrays that span non-addressable devices, and
        the single-process path costs nothing by sharing the convention.
        ``self._iter_capture`` holds the tuple to pass each call.
        """
        meta = self.feature_meta
        params = self.grow_params
        mesh = self.mesh
        obj = self.objective
        k = self.num_tree_per_iteration
        n = self.num_data
        use_input = self._use_input_grads or obj is None
        # per-row device arrays living on the objective (label, weights,
        # trans_label, onehot, ...) — anything get_gradients might read
        obj_row_names = tuple(sorted(
            nm for nm, v in (obj.__dict__.items() if obj is not None else ())
            if isinstance(v, jnp.ndarray) and v.ndim >= 1
            and v.shape[0] in (n, self.num_data_orig)))
        self._iter_capture = (
            self.xb, tuple(getattr(obj, nm) for nm in obj_row_names),
            self._fp_capture)
        import copy as _copy
        # device-side health flags (lightgbm_tpu.obs): computed from
        # values the step already holds — two reductions over grad/hess
        # plus the grower's aux accumulator. Off: the step returns a
        # constant zero vector and no health compute enters the program.
        health_on = self.obs.health_enabled
        is_goss = self.boosting_type == "goss"
        if is_goss:
            # counts from the REAL row count, not the mesh-padding-inflated
            # one — padded rows carry |g·h| = 0 and sort last, so top-k over
            # the padded array with real counts is exact (goss.hpp:87-135)
            n_real = self.num_data_orig
            top_cnt = max(1, int(n_real * self.config.top_rate))
            other_cnt = max(1, int(n_real * self.config.other_rate))
            goss_multiply = float(n_real - top_cnt) / other_cnt

        forced_splits = self._forced_splits
        # RenewTreeOutput objectives (L1/Quantile/MAPE): leaf refit runs
        # IN-GRAPH (core/renew.py) — no host round-trip, and train_many
        # block fusion stays eligible
        renew_alpha = None
        renew_w_attr = None
        if not use_input and obj is not None \
                and getattr(obj, "renew_percentile", None) is not None:
            renew_alpha = float(obj.renew_percentile())
            renew_w_attr = ("label_weight" if obj.name == "mape"
                            else "weights")

        def run_iter(xb, obj_rows, fp_capture, scores, sample_mask,
                     feature_mask, grad_in, hess_in, lr, goss_active,
                     goss_key, cegb_state, stopped_in):
            # gradients: objective or custom (grad_in) (gbdt.cpp:333-347)
            if not use_input:
                # bind the argument arrays onto a shallow copy — the traced
                # values, not the captured originals, feed get_gradients
                o = _copy.copy(obj)
                for nm, v in zip(obj_row_names, obj_rows):
                    setattr(o, nm, v)
                if k == 1:
                    g, h = o.get_gradients(scores[:, 0])
                    g = g[:, None]
                    h = h[:, None]
                else:
                    g, h = o.get_gradients(scores)
            else:
                g, h = grad_in, hess_in

            if is_goss:
                # GOSS one-side sampling on device (goss.hpp:87-135): keep all
                # of the top |g*h| rows, sample the rest, amplify their
                # grad/hess by (n - top)/other so expectations are unbiased.
                # Warmup iterations (goss_active == 0) skip the sort entirely.
                def goss_mult(_):
                    gh = jnp.sum(jnp.abs(g * h), axis=1)
                    thr = jax.lax.top_k(gh, top_cnt)[0][-1]
                    is_top = gh >= thr
                    u = jax.random.uniform(goss_key, (n,))
                    p_rest = other_cnt / max(n_real - top_cnt, 1)
                    keep_other = (~is_top) & (u < p_rest)
                    return jnp.where(is_top, 1.0,
                                     jnp.where(keep_other, goss_multiply, 0.0))

                mult = jax.lax.cond(goss_active > 0, goss_mult,
                                    lambda _: jnp.ones((n,), jnp.float32),
                                    operand=None)
                g = g * mult[:, None]
                h = h * mult[:, None]
                sample_mask = sample_mask * (mult > 0).astype(jnp.float32)

            # one place decides which wave-batched grower runs (the
            # shard_map and single-device branches below both use it)
            grow_batched_fn = None
            if params.frontier_mode:
                from ..core.grow_frontier import \
                    grow_tree_frontier as grow_batched_fn
            elif params.batch_splits > 0:
                if params.batched_part:
                    from ..core.grow_batched_part import \
                        grow_tree_batched_part as grow_batched_fn
                else:
                    from ..core.grow_batched import \
                        grow_tree_batched as grow_batched_fn

            if fp_capture is not None:
                # explicit feature-parallel: one shard_map over the feature
                # axis; rows replicated, column slices + local metas device-
                # varying, best splits struct-allreduced inside grow_tree
                from jax.sharding import PartitionSpec as P
                from ..parallel.mesh import FEATURE_AXIS
                from ..core.grow import FeatureParallelCtx
                tree_spec = jax.tree.map(lambda _: P(),
                                         empty_tree(params.num_leaves))
                xb_cols, meta_loc, gofl = fp_capture
                ml_specs = jax.tree.map(lambda _: P(FEATURE_AXIS), meta_loc)

                def _fp_core(xbg, xbl, ml, go, gj, hj, mj, fm):
                    ctx = FeatureParallelCtx(
                        xb_local=xbl[0],
                        meta_local=jax.tree.map(lambda a: a[0], ml),
                        global_of_local=go[0])
                    return grow_tree(xbg, gj, hj, mj, meta, fm, params,
                                     axis_name=FEATURE_AXIS, fp=ctx)[:2]

                grow_fp = shard_map(
                    _fp_core, mesh=mesh,
                    in_specs=(P(), P(FEATURE_AXIS), ml_specs,
                              P(FEATURE_AXIS), P(), P(), P(), P()),
                    out_specs=(tree_spec, P()), check_vma=False)

                def grow_one(gk, hk, cs):
                    t, li = grow_fp(xb, xb_cols, meta_loc, gofl, gk, hk,
                                    sample_mask, feature_mask)
                    return t, li, None
            elif params.partition_on_mesh or params.voting_top_k > 0:
                # explicit shard_map learners (mutually exclusive configs):
                # - data-parallel partition: local fused partition+hist per
                #   device, psum only on the [F, B, 6] child histograms;
                # - voting-parallel: manual PV-Tree election collectives
                #   (all_gather of proposals, psum of elected candidates).
                # check_vma=False: the replicated tree output is
                # device-identical by construction (psum'd histograms /
                # identical election), but the varying-axes type system
                # cannot prove it through the growth loop
                from jax.sharding import PartitionSpec as P
                from ..parallel.mesh import DATA_AXIS
                tree_spec = jax.tree.map(lambda _: P(),
                                         empty_tree(params.num_leaves))
                has_cegb = self._cegb_state is not None \
                    and params.voting_top_k == 0
                # grow_one's definedness below depends on this invariant
                # (enforced at config time, gbdt batched gating): keep it
                # local so relaxing that check can't unbind grow_one
                assert not (has_cegb and grow_batched_fn is not None), \
                    "wave-batched growth cannot carry CEGB state"

                if grow_batched_fn is not None:
                    def _grow_core(xbj, gj, hj, mj, fm):
                        return grow_batched_fn(
                            xbj, gj, hj, mj, meta, fm, params,
                            axis_name=DATA_AXIS)[:2]
                elif has_cegb:
                    from ..core.grow import CegbState

                    def _grow_core_cegb(xbj, gj, hj, mj, fm, cs):
                        return grow_tree(xbj, gj, hj, mj, meta, fm, params,
                                         axis_name=DATA_AXIS,
                                         forced=forced_splits, cegb=cs)
                    # acquisition state: per-feature fields replicated,
                    # lazy per-row accounting sharded with the rows
                    cegb_specs = CegbState(
                        coupled_penalty=P(), lazy_penalty=P(),
                        feature_used=P(), row_used=P(None, DATA_AXIS))
                    grow_cegb = shard_map(
                        _grow_core_cegb,
                        mesh=mesh, in_specs=(P(DATA_AXIS), P(DATA_AXIS),
                                             P(DATA_AXIS), P(DATA_AXIS),
                                             P(), cegb_specs),
                        out_specs=(tree_spec, P(DATA_AXIS), cegb_specs),
                        check_vma=False)

                    def grow_one(gk, hk, cs):
                        return grow_cegb(xb, gk, hk, sample_mask,
                                         feature_mask, cs)
                else:
                    def _grow_core(xbj, gj, hj, mj, fm):
                        return grow_tree(xbj, gj, hj, mj, meta, fm, params,
                                         axis_name=DATA_AXIS,
                                         forced=forced_splits)[:2]
                if not has_cegb:
                    grow_sharded = shard_map(
                        _grow_core,
                        mesh=mesh, in_specs=(P(DATA_AXIS), P(DATA_AXIS),
                                             P(DATA_AXIS), P(DATA_AXIS),
                                             P()),
                        out_specs=(tree_spec, P(DATA_AXIS)),
                        check_vma=False)

                    def grow_one(gk, hk, cs):
                        t, li = grow_sharded(xb, gk, hk, sample_mask,
                                             feature_mask)
                        return t, li, None
            elif grow_batched_fn is not None:
                def grow_one(gk, hk, cs):
                    return grow_batched_fn(xb, gk, hk, sample_mask, meta,
                                           feature_mask, params)
            else:
                def grow_one(gk, hk, cs):
                    return grow_tree(xb, gk, hk, sample_mask, meta,
                                     feature_mask, params,
                                     forced=forced_splits, cegb=cs)

            # class batching: k == 1 calls directly; multiclass maps
            # classes sequentially when (a) the pool is capped — vmap
            # would turn the rebuild-on-miss lax.cond into a both-branches
            # select, and sequential keeps one pool's worth of live
            # memory, the point of the cap — or (b) the backend is
            # TPU-shaped, where sequential measured 1.9x faster than vmap
            # even uncapped (round-4, tools/onchip_r4_results.json).
            # params.vmapped_classes is the ONE predicate: grow_tree keys
            # its sort-placement/pool decisions off the same flag this
            # dispatch uses, so the two can never disagree.
            if k == 1:
                t1, li1, cb1 = grow_one(g[:, 0], h[:, 0], cegb_state)
                trees = jax.tree.map(lambda a: a[None], t1)
                leaf_ids = li1[None]
                cegb_out = (jax.tree.map(lambda a: a[None], cb1)
                            if cb1 is not None else None)
            elif params.vmapped_classes:
                if params.frontier_mode and fp_capture is None \
                        and not params.partition_on_mesh \
                        and params.voting_top_k == 0:
                    # class-batched frontier growth with the wave-width
                    # switch OUTSIDE the vmap (grow_frontier.py): the
                    # branch index is an unbatched max-live scalar, so
                    # bucketing dispatches ONE ladder branch per wave
                    # instead of vmap's execute-all-branches lowering
                    from ..core.grow_frontier import \
                        grow_tree_frontier_classes
                    trees, leaf_ids, cegb_out = grow_tree_frontier_classes(
                        xb, g.T, h.T, sample_mask, meta, feature_mask,
                        params)
                else:
                    trees, leaf_ids, cegb_out = jax.vmap(
                        grow_one, in_axes=(1, 1, None))(g, h, cegb_state)
            else:
                trees, leaf_ids, cegb_out = lax.map(
                    lambda gh: grow_one(gh[0], gh[1], cegb_state),
                    (g.T, h.T))
            # the grower's third output is CEGB state on the exact path
            # and, on the frontier path, the obs aux: the [K, 2] health
            # accumulator with obs_health, or the (health_or_None,
            # [K, F, MS_WIDTH] mstats) tuple with obs_modelstats (the
            # frontier and CEGB paths are config-exclusive)
            grower_health = None
            grower_mstats = None
            if params.frontier_mode and params.obs_modelstats:
                aux, cegb_out = cegb_out, None
                grower_health, grower_mstats = aux
            elif params.frontier_mode and params.obs_health:
                grower_health, cegb_out = cegb_out, None
            if cegb_state is not None:
                # classes train from the iteration-start state; acquisitions
                # merge across class trees for the next iteration (the
                # sequential-classes analog of the reference's shared
                # learner state)
                cegb_new = cegb_state._replace(
                    feature_used=jnp.any(cegb_out.feature_used, axis=0),
                    row_used=jnp.max(cegb_out.row_used, axis=0))
            else:
                cegb_new = None
            if renew_alpha is not None:
                # device RenewTreeOutput (serial_tree_learner.cpp:850-928):
                # refit leaf values to the weighted percentile of residuals
                # against the PRE-update scores, exactly like the
                # reference's post-growth renew
                from ..core.renew import renew_leaf_values
                rw = getattr(o, renew_w_attr, None)
                if rw is None:
                    rw = jnp.ones_like(o.label)

                def renew_one(t, li, sc_col):
                    # scores live in the (possibly reg_sqrt-transformed)
                    # label space the gradients were computed in
                    lab = getattr(o, "trans_label", None)
                    lab = o.label if lab is None else lab
                    new_lv = renew_leaf_values(
                        lab - sc_col, rw, li, sample_mask,
                        params.num_leaves, renew_alpha, t.leaf_value)
                    return t._replace(leaf_value=new_lv)

                trees = jax.vmap(renew_one, in_axes=(0, 0, 1))(
                    trees, leaf_ids, scores)
            # score update fast path: leaf_id -> leaf_value (shrinkage applied)
            deltas = jax.vmap(
                lambda t, li: t.leaf_value[li] * lr)(trees, leaf_ids)  # [K, N]
            # A fully-stumped iteration (no class tree split) means training
            # has converged; the reference discards the tree and stops
            # (gbdt.cpp:379-396). The stop flag accumulates ON DEVICE across
            # iterations: once any iteration stumps, every later dispatched
            # iteration freezes the scores too — so the async driver can
            # discard the overshoot trees at the next flush without
            # rewinding anything, even when bagging/feature sampling would
            # have let a later iteration split again.
            any_split = jnp.any(trees.num_leaves > 1)
            stopped_out = stopped_in | ~any_split
            apply = (any_split & ~stopped_in).astype(jnp.float32)
            new_scores = scores + deltas.T * apply
            if health_on:
                from ..obs.health import health_vec
                health = health_vec(g, h, any_split, grower_health)
            else:
                health = jnp.zeros((4,), jnp.float32)
            # grower_mstats is None unless obs_modelstats: a None output
            # is an empty pytree leaf, so the compiled program (and every
            # jaxpr fingerprint) is unchanged when the feature is off
            return pack_trees(trees), leaf_ids, new_scores, cegb_new, \
                stopped_out, health, grower_mstats

        self._iter_core = run_iter   # unjitted: train_many scans over it
        return jax.jit(run_iter)

    def _make_stream_iter_fns(self) -> None:
        """Build the two jitted halves of a streamed iteration.

        The grower itself (StreamFrontierGrower) is host-driven, so the
        per-iteration device work splits around it: ``stream_pre`` turns
        scores into (possibly GOSS-resampled) gradients, ``stream_post``
        applies the grown trees to the scores with the same renew /
        stop-latch / health semantics as ``run_iter``. Both take the
        objective's per-row arrays as arguments (``_stream_capture``),
        matching the non-streamed capture convention.
        """
        obj = self.objective
        k = self.num_tree_per_iteration
        n = self.num_data
        obj_row_names = tuple(sorted(
            nm for nm, v in (obj.__dict__.items() if obj is not None else ())
            if isinstance(v, jnp.ndarray) and v.ndim >= 1
            and v.shape[0] in (n, self.num_data_orig)))
        self._stream_capture = tuple(getattr(obj, nm)
                                     for nm in obj_row_names)
        import copy as _copy

        def bind(obj_rows):
            o = _copy.copy(obj)
            for nm, v in zip(obj_row_names, obj_rows):
                setattr(o, nm, v)
            return o

        health_on = self.obs.health_enabled
        is_goss = self.boosting_type == "goss"
        if is_goss:
            n_real = self.num_data_orig
            top_cnt = max(1, int(n_real * self.config.top_rate))
            other_cnt = max(1, int(n_real * self.config.other_rate))
            goss_multiply = float(n_real - top_cnt) / other_cnt
        row_valid = self._row_valid
        renew_alpha = None
        renew_w_attr = None
        if obj is not None \
                and getattr(obj, "renew_percentile", None) is not None:
            renew_alpha = float(obj.renew_percentile())
            renew_w_attr = ("label_weight" if obj.name == "mape"
                            else "weights")

        def stream_pre(obj_rows, scores, sample_mask, goss_active,
                       goss_key):
            o = bind(obj_rows)
            if k == 1:
                g, h = o.get_gradients(scores[:, 0])
                g = g[:, None]
                h = h[:, None]
            else:
                g, h = o.get_gradients(scores)
            if is_goss:
                def goss_mult(_):
                    gh = jnp.sum(jnp.abs(g * h), axis=1)
                    if row_valid is not None:
                        # padded rows accumulate leaf deltas of whatever
                        # leaf id their slot happens to carry, so unlike
                        # the mesh-padding case their |g*h| is NOT zero —
                        # mask before ranking or they'd occupy top-k slots
                        gh = gh * row_valid
                    thr = jax.lax.top_k(gh, top_cnt)[0][-1]
                    is_top = gh >= thr
                    u = jax.random.uniform(goss_key, (n,))
                    p_rest = other_cnt / max(n_real - top_cnt, 1)
                    keep_other = (~is_top) & (u < p_rest)
                    return jnp.where(is_top, 1.0,
                                     jnp.where(keep_other, goss_multiply,
                                               0.0))

                mult = jax.lax.cond(goss_active > 0, goss_mult,
                                    lambda _: jnp.ones((n,), jnp.float32),
                                    operand=None)
                g = g * mult[:, None]
                h = h * mult[:, None]
                sample_mask = sample_mask * (mult > 0).astype(jnp.float32)
            return g, h, sample_mask

        def stream_post(obj_rows, trees, leaf_ids, scores, sample_mask,
                        g, h, grower_health, lr, stopped_in):
            if renew_alpha is not None:
                from ..core.renew import renew_leaf_values
                o = bind(obj_rows)
                rw = getattr(o, renew_w_attr, None)
                if rw is None:
                    rw = jnp.ones_like(o.label)

                def renew_one(t, li, sc_col):
                    lab = getattr(o, "trans_label", None)
                    lab = o.label if lab is None else lab
                    new_lv = renew_leaf_values(
                        lab - sc_col, rw, li, sample_mask,
                        self.grow_params.num_leaves, renew_alpha,
                        t.leaf_value)
                    return t._replace(leaf_value=new_lv)

                trees = jax.vmap(renew_one, in_axes=(0, 0, 1))(
                    trees, leaf_ids, scores)
            deltas = jax.vmap(
                lambda t, li: t.leaf_value[li] * lr)(trees, leaf_ids)
            any_split = jnp.any(trees.num_leaves > 1)
            stopped_out = stopped_in | ~any_split
            apply = (any_split & ~stopped_in).astype(jnp.float32)
            new_scores = scores + deltas.T * apply
            if health_on:
                from ..obs.health import health_vec
                health = health_vec(g, h, any_split, grower_health)
            else:
                health = jnp.zeros((4,), jnp.float32)
            return pack_trees(trees), new_scores, stopped_out, health

        self._stream_pre = jax.jit(stream_pre)
        self._stream_post = jax.jit(stream_post)

    def _train_one_iter_streamed(self) -> bool:
        """Streamed TrainOneIter: host wave loop over device chunks.

        Same dispatch/flush contract as ``train_one_iter`` — trees stay
        packed on device until `_materialize` — but the grower is the
        host-driven StreamFrontierGrower, so the iteration is three
        stages: jitted gradient pre-pass, per-class chunk-swept growth,
        jitted score/stop post-pass.
        """
        if self._stopped:
            return True
        _faults.inject("train_dispatch", iteration=self.iter_)
        self._boost_from_average()
        if self._stream_pre is None:
            self._make_stream_iter_fns()

        iter_idx = self.iter_
        obs = self.obs
        t0 = time.perf_counter() if obs.enabled else 0.0
        sample_mask = self._sample_bagging_mask(iter_idx)
        feature_mask = self._sample_feature_mask()
        self._bag_key, goss_key = jax.random.split(self._bag_key)
        obs.perfetto_step(iter_idx, iter_idx + 1)
        t_disp = t0
        params = self.grow_params
        k = self.num_tree_per_iteration
        # request-scoped iteration trace (obs/reqtrace.py): a no-op span
        # unless obs_trace is on; mirrors the serving span tree with
        # per-wave children under a per-iteration root
        tspan = obs.trace_iter(iter_idx)
        with obs.span("train_iter", iteration=iter_idx):
            gspan = tspan.child("gradients")
            g, h, sm = self._stream_pre(
                self._stream_capture, self.scores, sample_mask,
                jnp.float32(self._goss_active(iter_idx)), goss_key)
            gspan.end()
            trees_l, lids_l, aux_l = [], [], []
            for c in range(k):
                cspan = tspan.child("tree", cls=c)
                t, li, aux = self._stream_grower.grow(
                    g[:, c], h[:, c], sm, feature_mask,
                    trace_span=cspan if cspan else None)
                cspan.end()
                trees_l.append(t)
                lids_l.append(li)
                aux_l.append(aux)
            trees = jax.tree.map(lambda *a: jnp.stack(a), *trees_l)
            leaf_ids = jnp.stack(lids_l)
            grower_health = None
            mstats = None
            if params.obs_modelstats:
                if aux_l[0][0] is not None:
                    grower_health = jnp.stack([a[0] for a in aux_l])
                mstats = jnp.stack([a[1] for a in aux_l])
            elif params.obs_health:
                grower_health = jnp.stack(aux_l)
            pspan = tspan.child("score_commit")
            packed, new_scores, self._stopped_dev, health = \
                self._stream_post(
                    self._stream_capture, trees, leaf_ids, self.scores,
                    sm, g, h, grower_health,
                    jnp.float32(self.shrinkage_rate), self._stopped_dev)
            pspan.end()
            if obs.enabled:
                t_disp = time.perf_counter()
                wspan = tspan.child("device_wait")
                jax.block_until_ready(new_scores)  # lgbm-lint: disable=LGL103 span close
                wspan.end()
        t_done = time.perf_counter() if obs.enabled else 0.0
        self.scores = new_scores

        pend: Dict[str, Any] = {"packed": packed[None],
                                "shrinkage": self.shrinkage_rate,
                                "count": 1,
                                "mstats": (mstats[None]
                                           if mstats is not None else None)}
        self._pending.append(pend)
        self.iter_ += 1
        if obs.enabled:
            hrow = np.asarray(health)[None]
            obs.dispatch_done(iter_idx, 1, t_done - t0,
                              health_rows=hrow,
                              busy_s=t_disp - t0, wait_s=t_done - t_disp)
            obs.account_rows(self.num_data_orig)
            if obs.per_iteration:
                obs.record_hbm()
            obs.check_health(hrow, iter_idx, booster=self)
        elif obs.health_enabled:
            obs.check_health(np.asarray(health)[None], iter_idx,
                             booster=self)
        tspan.finish("ok")
        if sum(p["count"] for p in self._pending) >= self._flush_every:
            return self._materialize()
        return False

    # the block's threaded train-state buffers by run_block position:
    # scores [N, K] and the bagging mask [N].  One declaration, three
    # consumers: the executing jit below, the donation audit
    # (analysis/hlo_audit.py) and its regression test.
    TRAIN_BLOCK_DONATE = (3, 8)

    def _build_run_block(self) -> Callable:
        """The unjitted fused-block callable — separated from
        ``_make_train_block_fn`` so the donation audit can re-jit it
        with explicit ``donate_argnums`` on any backend without
        touching the executing program.

        Fuses ``block`` boosting iterations into ONE device program
        (lax.scan over the single-iteration core). The whole boosting loop
        — gradients, bagging refresh, GOSS sampling, tree growth, score
        update — runs on device with no host round trips; trees come back
        stacked [block, K, T] for the async flush. This is the TPU-native
        shape of GBDT::Train (gbdt.cpp:243-261): the reference's per-iter
        host loop exists because its learner lives in host memory; ours
        does not.
        """
        core = self._iter_core
        cfg = self.config
        n, k = self.num_data, self.num_tree_per_iteration
        bag_enabled = cfg.bagging_freq > 0 and 0.0 < cfg.bagging_fraction \
            < 1.0
        freq = max(cfg.bagging_freq, 1)
        frac = cfg.bagging_fraction
        row_valid = self._row_valid
        row_group = self._row_group          # group-aware bagging (ranking)
        num_groups = getattr(self, "_num_groups", 0)

        def run_block(xb, obj_rows, fp_capture, scores, feature_masks,
                      goss_actives, iter_idxs, keys, bag_mask0, cegb_state,
                      stopped_in, lr):
            g0 = jnp.zeros((n, k), jnp.float32)
            h0 = jnp.ones((n, k), jnp.float32)

            def step(carry, xs):
                sc, bag_mask, cegb, stopped = carry
                fm, ga, it, key = xs
                bkey, gkey = jax.random.split(key)
                if bag_enabled:
                    # bagging refresh on schedule (gbdt.cpp:180-241);
                    # ranking: one uniform per QUERY, broadcast to rows
                    refresh = (it % freq) == 0
                    if row_group is not None:
                        u = jax.random.uniform(bkey, (num_groups,))
                        u = u[row_group]
                    else:
                        u = jax.random.uniform(bkey, (n,))
                    new_mask = (u < frac).astype(jnp.float32)
                    bag_mask = jnp.where(refresh, new_mask, bag_mask)
                sm = bag_mask if row_valid is None else bag_mask * row_valid
                packed, _leaf_ids, sc2, cegb2, stopped2, health, ms = core(
                    xb, obj_rows, fp_capture, sc, sm, fm, g0, h0, lr, ga,
                    gkey, cegb, stopped)
                return (sc2, bag_mask, cegb2, stopped2), (packed, health, ms)

            carry, (packs, healths, mstats) = lax.scan(
                step, (scores, bag_mask0, cegb_state, stopped_in),
                (feature_masks, goss_actives, iter_idxs, keys))
            new_scores, bag_mask, cegb_out, stopped_out = carry
            # healths: [block, 4] per-iteration health vectors (zeros when
            # monitoring is off) — one tiny transfer per block, not per
            # iter. mstats: [block, K, F, MS_WIDTH] per-iteration model
            # statistics with obs_modelstats, else None (invisible in the
            # compiled program)
            return packs, healths, new_scores, bag_mask, cegb_out, \
                stopped_out, mstats

        return run_block

    def _make_train_block_fn(self) -> Callable:
        """The executing fused-block jit (see ``_build_run_block``)."""
        run_block = self._build_run_block()
        # donate the threaded train-state buffers (TRAIN_BLOCK_DONATE) —
        # both are rebound to the block's outputs by the caller, so XLA
        # may alias the output into the input allocation instead of
        # holding both live. CPU has no donation support and would warn
        # per compile, so gate on backend.
        donate = (self.TRAIN_BLOCK_DONATE
                  if self.config.tpu_donate_buffers
                  and jax.default_backend() != "cpu" else ())
        return jax.jit(run_block, donate_argnums=donate)

    def train_block_sds(self, block: int) -> Tuple[Any, ...]:
        """``jax.ShapeDtypeStruct`` mirrors of one ``run_block`` call at
        ``block`` fused iterations — the exact argument signature the
        executing program was compiled with.  Shared by cost-model
        extraction and the donation audit so the audited program IS the
        dispatched one (never a near-miss signature that would compile a
        second specialization)."""
        sds = jax.ShapeDtypeStruct

        def _mirror_leaf(a):
            if not hasattr(a, "shape") or not hasattr(a, "dtype"):
                return a
            try:
                return sds(a.shape, a.dtype,
                           sharding=getattr(a, "sharding", None))
            except Exception:  # noqa: BLE001 - sharding kwarg is optional
                return sds(a.shape, a.dtype)

        mirror = lambda tree: jax.tree_util.tree_map(_mirror_leaf, tree)  # noqa: E731
        f = self.train_data.num_features
        fpad = getattr(self, "_feature_pad", 0)
        key_arr = jnp.asarray(self._bag_key)
        return tuple(mirror(self._iter_capture)) + (
            mirror(self.scores),
            sds((block, f + fpad), jnp.bool_),      # feature_masks
            sds((block,), jnp.float32),             # goss_actives
            sds((block,), jnp.int32),               # iter_idxs
            sds((block,) + tuple(key_arr.shape), key_arr.dtype),
            mirror(self._bag_mask),
            mirror(self._cegb_state),
            mirror(self._stopped_dev),
            sds((), jnp.float32),                   # lr
        )

    def warmup_wave_ladder(self) -> Dict[str, Any]:
        """Pre-compile ``build_histogram_frontier`` at every wave-width
        bucket the frontier grower can dispatch (the serving ``warmup()``
        analog for training): one all-inactive-slot call per ladder width
        on the real data shapes, so standalone probes and eager frontier
        calls after this never compile — and with ``compile_cache_dir``
        set, later PROCESSES reload every specialization from disk.
        Returns per-bucket compile counts + seconds (reported by
        profiling/bench). No-op unless the booster grows frontier-mode.
        """
        from .. import bucketing
        from ..profiling import backend_compile_count, compile_cache_stats
        params = self.grow_params
        if not getattr(params, "frontier_mode", False) or \
                self.mesh is not None or self.xb is None:
            # mesh growth compiles inside shard_map on shard-local shapes,
            # and streamed growth (self.xb is None) compiles its own
            # fixed-chunk kernels on first dispatch; the standalone
            # global-shape warmup would not match either
            return {"widths": [], "per_bucket_compiles": {},
                    "seconds": 0.0, "cache_hits": 0, "cache_misses": 0}
        from ..core.histogram import build_histogram_frontier
        widths = (bucketing.wave_width_ladder(params.num_leaves,
                                              params.max_depth)
                  if params.frontier_bucketing
                  else [bucketing.frontier_max_width(params.num_leaves,
                                                     params.max_depth)])
        n = self.num_data
        slot = jnp.full((n,), -1, jnp.int32)     # all-inactive: cheap sweep
        g = jnp.zeros((n,), jnp.float32)
        h = jnp.ones((n,), jnp.float32)
        mask = jnp.ones((n,), jnp.float32)
        before = compile_cache_stats()
        t0 = time.perf_counter()
        per_bucket: Dict[int, int] = {}
        for w in widths:
            c0 = backend_compile_count()
            # lgbm-lint: disable=LGL103 warmup probe, sync is the point
            jax.block_until_ready(build_histogram_frontier(
                self.xb, slot, g, h, mask, num_bins=params.num_bins,
                num_slots=w, row_chunk=params.row_chunk,
                impl=params.hist_impl,
                packed_cols=params.word_packed_cols))
            per_bucket[w] = backend_compile_count() - c0
        after = compile_cache_stats()
        return {
            "widths": widths,
            "per_bucket_compiles": per_bucket,
            "seconds": time.perf_counter() - t0,
            "cache_hits": (after["persistent_cache_hits"]
                           - before["persistent_cache_hits"]),
            "cache_misses": (after["persistent_cache_misses"]
                             - before["persistent_cache_misses"]),
        }

    def _maybe_warm_ladder(self) -> None:
        """Run the bucket-ladder warmup once, at train start — only when a
        persistent compile cache is configured. In-process, every switch
        branch compiles INSIDE the first training block's program anyway;
        the eager ladder exists to populate the cross-process cache and to
        produce the per-bucket compile/hit/miss accounting, both of which
        only matter in compile_cache_dir runs (bench, the CI smoke)."""
        if self._ladder_warmup is None and \
                getattr(self.config, "compile_cache_dir", ""):
            self._ladder_warmup = self.warmup_wave_ladder()

    def extract_cost_model(self, force: bool = False
                           ) -> Dict[str, Dict[str, float]]:
        """XLA cost-model extraction for this booster's compiled entry
        points (obs/costmodel.py): the fused train block at its last
        dispatched length, every frontier wave-width bucket's histogram
        sweep, and the materialize flush at its last shape.  Per-entry
        FLOPs / bytes / memory land as ``lgbm_costmodel_*`` gauges and
        feed ``GET /roofline``, bench and the perf gate.

        PULL-based by design: nothing in the training loop calls this,
        so ``observability=none`` runs do zero costmodel work — and with
        obs off it returns ``{}`` unless ``force=True`` (bench, probes
        and the perf tools force it).  Arguments are mirrored as
        ``jax.ShapeDtypeStruct`` (sharding preserved), never sampled:
        extraction must not advance ``self._rng`` / ``self._bag_key`` or
        resumed-run byte-identity would break.  AOT lowering shares no
        cache with the executing programs, so this never recompiles or
        perturbs them (pinned by tests/test_costmodel.py).
        """
        if not (force or self.obs.enabled):
            return {}
        from ..obs.costmodel import get_cost_model
        cm = get_cost_model()

        out: Dict[str, Dict[str, float]] = {}
        block = int(getattr(self, "_last_block_len", 0) or 0)
        if self._compiled_block is not None and block > 0 \
                and getattr(self, "_iter_capture", None) is not None:
            out["train_block"] = cm.analyze(
                "train_block", self._compiled_block,
                *self.train_block_sds(block),
                extra_key="block=%d" % block)
        params = self.grow_params
        if getattr(params, "frontier_mode", False) and self.mesh is None \
                and self.xb is not None:
            # mesh growth lowers inside shard_map on shard-local shapes;
            # the standalone global-shape entry would not price it
            from .. import bucketing
            from ..core.grow_frontier import (wave_fused_entry,
                                              wave_hist_entry)
            widths = (bucketing.wave_width_ladder(params.num_leaves,
                                                  params.max_depth)
                      if params.frontier_bucketing
                      else [bucketing.frontier_max_width(
                          params.num_leaves, params.max_depth)])
            n = self.xb.shape[0]
            # real stored-column count, not the word-matrix width: the
            # packed entry's SDS mirror derives its own word shape
            ncols = params.word_packed_cols or self.xb.shape[1]
            fmask = jnp.ones((ncols,), bool)
            for w in widths:
                hfn, hargs, hkw = wave_hist_entry(
                    n, ncols, self.xb.dtype, params, w)
                name = "frontier_hist_w%d" % w
                out[name] = cm.analyze(name, hfn, *hargs, **hkw)
                # the whole fused wave region (hist -> sibling subtract
                # -> expand/fix -> 2K-child bin scan): unlike the sweep
                # alone — whose scatter update traffic is structurally
                # width-invariant (updates are [n, C, 3] whatever kw) —
                # this entry's flops/bytes genuinely scale with kw, so
                # per-bucket costs are distinguishable in the gate
                ffn, fargs, fkw = wave_fused_entry(
                    n, ncols, self.xb.dtype, self.feature_meta, fmask,
                    params, w)
                name = "frontier_wave_w%d" % w
                out[name] = cm.analyze(name, ffn, *fargs, **fkw)
        if self._stream is not None:
            # streamed growth: one fixed-width per-chunk sweep is the
            # whole kernel story — price it at the pipeline's chunk shape
            from .. import bucketing
            from ..core.grow_frontier import wave_hist_entry
            w = bucketing.frontier_max_width(params.num_leaves,
                                             params.max_depth)
            hfn, hargs, hkw = wave_hist_entry(
                self._stream.chunk_rows, self._stream.num_cols,
                jnp.uint8, params, w)
            name = "stream_chunk_hist_w%d" % w
            out[name] = cm.analyze(name, hfn, *hargs, **hkw)
        flush = list(getattr(self, "_last_flush_shapes", ()))
        if flush:
            concat = jax.jit(lambda *bufs: jnp.concatenate(bufs, axis=0))
            out["materialize"] = cm.analyze(
                "materialize", concat, *flush,
                extra_key="blocks=%d" % len(flush))
        return out

    def train_many(self, num_iters: int) -> bool:
        """Run ``num_iters`` iterations, fusing them into on-device blocks
        when no per-iteration host work is required. Returns True when
        training stopped. Boosting modes with per-iteration host logic
        (DART's drop sets, RF's re-averaging, custom gradients) fall back
        to the per-iteration path; percentile-renew objectives fuse fine —
        their leaf refit runs in-graph (core/renew.py).
        """
        eligible = (self.boosting_type in ("gbdt", "goss")
                    and not self._use_input_grads
                    # streamed growth is host-driven (per-chunk kernels
                    # under a host wave loop) — it cannot fuse into one
                    # scanned device program; per-iteration dispatch is
                    # the streamed fast path
                    and self._stream is None)
        if eligible and self.obs.per_iteration:
            # observability=full wants TRUE per-iteration spans and
            # health-within-one-iteration, so it forgoes block fusion —
            # that cost is the documented basic/full trade
            eligible = False
        if not eligible:
            for _ in range(num_iters):
                if self.train_one_iter():
                    return True
            return False

        self._boost_from_average()
        self._maybe_warm_ladder()
        if self._iter_core is None:
            self._compiled_iter = self._make_train_iter_fn()
        if self._compiled_block is None:
            # one jitted scan; jax caches a compilation per block length
            self._compiled_block = self._make_train_block_fn()

        done = 0
        while done < num_iters and not self._stopped:
            block = min(num_iters - done, 64)
            # train_dispatch seam (docs/Resilience.md): fires before the
            # block is dispatched; iteration = block start, round = the
            # per-point block ordinal. Two attribute checks when inert.
            _faults.inject("train_dispatch", iteration=self.iter_,
                           block_len=block)
            self._last_block_len = block
            obs = self.obs
            # host window opens before feature sampling: mask/bag-key prep
            # is host-side work attributed to busy_s in the distributed
            # per-block comm/compute split
            t0 = time.perf_counter() if obs.enabled else 0.0
            fn = self._compiled_block
            fmasks = jnp.stack([self._sample_feature_mask()
                                for _ in range(block)])
            gactive = jnp.asarray(
                [self._goss_active(self.iter_ + i) for i in range(block)],
                jnp.float32)
            # host-side arange: jnp.arange with a nonzero start compiles a
            # tiny convert_element_type on the SECOND block (start=0 takes
            # the iota path), breaking zero-recompiles-after-warmup
            idxs = jnp.asarray(np.arange(self.iter_, self.iter_ + block,
                                         dtype=np.int32))
            all_keys = jax.random.split(self._bag_key, block + 1)
            self._bag_key = all_keys[0]
            obs.perfetto_step(self.iter_, self.iter_ + block)
            t_disp = t0
            with obs.span("train_block", start_iter=self.iter_,
                          count=block):
                packs, healths, self.scores, self._bag_mask, \
                    self._cegb_state, self._stopped_dev, mstats = fn(
                        *self._iter_capture,
                        self.scores, fmasks, gactive, idxs, all_keys[1:],
                        self._bag_mask, self._cegb_state, self._stopped_dev,
                        jnp.float32(self.shrinkage_rate))
                if obs.enabled:
                    # async dispatch returned: host work ends here, the
                    # remainder of the block wall is device wait
                    t_disp = time.perf_counter()
                    # one sync at span close; basic mode's only added
                    # barrier, and the block boundary already is one for
                    # the flush cadence
                    jax.block_until_ready(self.scores)  # lgbm-lint: disable=LGL103 span close
            t_done = time.perf_counter() if obs.enabled else 0.0
            self._pending.append({"packed": packs,
                                  "shrinkage": self.shrinkage_rate,
                                  "count": block,
                                  "mstats": mstats})
            self.iter_ += block
            done += block
            if obs.enabled:
                hrows = np.asarray(healths)
                obs.dispatch_done(self.iter_ - block, block,
                                  t_done - t0,
                                  health_rows=hrows,
                                  busy_s=t_disp - t0,
                                  wait_s=t_done - t_disp)
                obs.account_rows(self.num_data_orig * block)
                obs.record_hbm()
                obs.check_health(hrows, self.iter_ - block, booster=self)
            elif obs.health_enabled:
                obs.check_health(np.asarray(healths), self.iter_ - block,
                                 booster=self)
            if sum(p.get("count", 1) for p in self._pending) \
                    >= self._flush_every:
                self._materialize()
        return self._stopped

    def _goss_active(self, iter_idx: int) -> float:
        return 0.0

    @property
    def models(self) -> List[HostTree]:
        """Materialized HostTrees; flushes any pending device trees first."""
        self._materialize()
        return self._models

    @models.setter
    def models(self, value: List[HostTree]) -> None:
        # wholesale assignment (model load / refit) discards pending work
        self._pending.clear()
        self._stopped = False
        self._stopped_dev = jnp.asarray(False)
        self._models = list(value)

    # ------------------------------------------------- checkpoint state
    def _capture_rows(self, arr) -> np.ndarray:
        """Host copy of a per-row device array for checkpointing. Under a
        multi-process mesh the array is row-sharded and NOT fully
        addressable; each process captures its OWN rows (sorted shard
        order), and ``_restore_rows`` rebuilds the global array from that
        local block — per-rank snapshots stay rank-local, matching the
        rank-folded dataset fingerprint that guards shard reassignment."""
        arr = jnp.asarray(arr)
        if getattr(arr, "is_fully_addressable", True):
            return np.asarray(arr)
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        return np.concatenate([np.asarray(s.data) for s in shards])

    def _restore_rows(self, host, extra_dims: int = 0):
        """Inverse of ``_capture_rows``: device array in the training
        row layout from a host capture (global when fully addressable,
        this process's rows otherwise)."""
        host = np.asarray(host)
        if self.mesh is None:
            return jnp.asarray(host)
        from ..parallel import mesh as mesh_mod
        sh = mesh_mod.row_sharding(self.mesh, extra_dims=extra_dims)
        if host.shape[0] == self.num_data:
            return jax.device_put(host, sh)
        pid = jax.process_index()
        devices = list(np.asarray(self.mesh.devices).reshape(-1))
        local = [d for d in devices if d.process_index == pid]
        if not local or host.shape[0] % len(local):
            raise LightGBMError(
                "checkpointed row block of %d rows does not tile over %d "
                "local mesh devices — was the snapshot written under a "
                "different mesh?" % (host.shape[0], len(local)))
        blk = host.shape[0] // len(local)
        bufs = [jax.device_put(host[i * blk:(i + 1) * blk], d)
                for i, d in enumerate(local)]
        return jax.make_array_from_single_device_arrays(
            (self.num_data,) + host.shape[1:], sh, bufs)

    def training_state(self):
        """Complete mutable training state as ``(meta, arrays)`` — the
        checkpoint subsystem's capture point (lightgbm_tpu.checkpoint).

        ``meta`` is JSON-safe scalars (iteration cursors, RNG cursors,
        tree shape lists); ``arrays`` is numpy payloads (raw HostTree
        fields, f32 scores, PRNGKey, Mersenne-Twister keys, valid-set
        score caches, CEGB leaves). Restoring these verbatim — instead of
        replaying trees — is what keeps a resumed run bit-identical.
        """
        from ..checkpoint import snapshot as snap_mod
        self._materialize()
        meta: Dict[str, Any] = {
            "boosting_type": self.boosting_type,
            "iteration": int(self.iter_),
            "num_init_iteration": int(self.num_init_iteration),
            "stopped": bool(self._stopped),
            "shrinkage_rate": float(self.shrinkage_rate),
            "boost_from_average_done": bool(self.boost_from_average_done),
        }
        arrays: Dict[str, np.ndarray] = {
            "scores": self._capture_rows(self.scores),
            "bag_key": np.asarray(self._bag_key),
            "bag_mask": self._capture_rows(self._bag_mask),
            "stopped_dev": np.asarray(self._stopped_dev),
        }
        ff_meta, ff_keys = snap_mod.rng_state_split(self._rng)
        meta["ff_rng"] = ff_meta
        arrays["ff_rng_keys"] = ff_keys
        # training data profile (obs.drift): rides the JSON meta into
        # snapshot meta.json so serving can score drift against it.
        # Absence is legal (pre-profile snapshots keep loading; drift
        # surfaces report "no_profile"), so failures only warn.
        if self.train_data is not None:
            try:
                meta["data_profile"] = \
                    self.train_data.data_profile().to_json_dict()
            except Exception as e:  # noqa: BLE001 - profile is best-effort
                Log.warning("data profile capture failed (%s); snapshot "
                            "will carry none", e)
        inits = getattr(self, "init_score_offsets", None)
        if inits is not None:
            arrays["init_score_offsets"] = np.asarray(inits)
        if self._cegb_state is not None:
            for j, leaf in enumerate(
                    jax.tree_util.tree_leaves(self._cegb_state)):
                arrays["cegb_%d" % j] = np.asarray(leaf)
        for vi, cache in self._valid_pred_cache.items():
            arrays["valid%d_scores" % vi] = np.asarray(cache["scores"])
        tree_meta, tree_arrays = snap_mod.trees_to_arrays(self._models)
        meta["trees"] = tree_meta
        arrays.update(tree_arrays)
        return meta, arrays

    def load_training_state(self, meta, arrays) -> None:
        """Inverse of training_state; the driver must have been built with
        the same config/data (checkpoint.snapshot.check_compatibility)."""
        from ..checkpoint import snapshot as snap_mod
        # property setter clears pending work and the stop latches
        self.models = snap_mod.trees_from_arrays(meta["trees"], arrays)
        self.iter_ = int(meta["iteration"])
        self.num_init_iteration = int(meta["num_init_iteration"])
        self.shrinkage_rate = float(meta["shrinkage_rate"])
        self.boost_from_average_done = bool(meta["boost_from_average_done"])
        self._stopped = bool(meta["stopped"])
        self._stopped_dev = (jnp.asarray(bool(arrays["stopped_dev"]))
                             if "stopped_dev" in arrays
                             else jnp.asarray(self._stopped))
        self.scores = self._restore_rows(
            np.asarray(arrays["scores"], np.float32), extra_dims=1)
        self._bag_key = jnp.asarray(arrays["bag_key"], dtype=jnp.uint32)
        self._bag_mask = self._restore_rows(
            np.asarray(arrays["bag_mask"], np.float32))
        self._rng.set_state(snap_mod.rng_state_join(meta["ff_rng"],
                                                    arrays["ff_rng_keys"]))
        if "init_score_offsets" in arrays:
            self.init_score_offsets = np.asarray(
                arrays["init_score_offsets"], np.float32)
        if self._cegb_state is not None and "cegb_0" in arrays:
            leaves, treedef = jax.tree_util.tree_flatten(self._cegb_state)
            self._cegb_state = jax.tree_util.tree_unflatten(
                treedef, [jnp.asarray(arrays["cegb_%d" % j])
                          for j in range(len(leaves))])
        k = self.num_tree_per_iteration
        for vi, cache in self._valid_pred_cache.items():
            key = "valid%d_scores" % vi
            if key in arrays:
                # verbatim restore: bit-identical eval history on resume
                cache["scores"] = jnp.asarray(
                    np.asarray(arrays[key], np.float32))
            else:
                Log.warning(
                    "checkpoint has no score cache for validation set %d "
                    "(added after the snapshot was written?); replaying "
                    "trees — eval values may differ in the last ulp", vi)
                for i, ht in enumerate(self._models):
                    leaf = self._replay_leaves_binned(ht, cache["xb"])
                    cache["scores"] = cache["scores"].at[:, i % k].add(
                        jnp.asarray(ht.leaf_value.astype(np.float32))[leaf])

    def warn_lossy_continuation(self) -> None:
        """Warn loudly when continued training from a bare ``init_model``
        silently restarts sampling state from the seeds (the trees survive
        the model file; the RNG cursors do not). Checkpoint resume
        (engine.train(resume_from=...)) restores them exactly."""
        cfg = self.config
        lost = []
        if cfg.bagging_freq > 0 and 0.0 < cfg.bagging_fraction < 1.0:
            lost.append("bagging PRNGKey")
        if cfg.feature_fraction < 1.0:
            lost.append("feature_fraction RandomState")
        if self.boosting_type == "goss":
            lost.append("GOSS sampling key")
        if lost:
            Log.warning(
                "Continued training from init_model: %s restart(s) from "
                "the configured seed(s), so results WILL diverge from an "
                "uninterrupted run. Use checkpoints "
                "(engine.train(resume_from=<dir>)) for exact continuation.",
                ", ".join(lost))

    def enable_health_monitor(self, action: str = "warn") -> None:
        """Arm device-side health monitoring (``callback.health_monitor``).
        When armed before the first compile — the callback's
        ``before_iteration`` slot at iteration 0 — nothing rebuilds; arming
        mid-train discards the compiled step so the health branch enters
        the program from the next dispatch."""
        if not self.obs.arm_health(action):
            return
        if self._compiled_iter is not None or \
                self._compiled_block is not None:
            Log.warning("health_monitor armed after compilation; "
                        "rebuilding the training step with device-side "
                        "health flags")
        self._compiled_iter = None
        self._iter_core = None
        self._compiled_block = None
        if getattr(self, "grow_params", None) is not None \
                and self.grow_params.frontier_mode \
                and not self._partition_on_mesh:
            self.grow_params = self.grow_params._replace(obs_health=True)

    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> bool:
        """One boosting iteration (gbdt.cpp TrainOneIter:333-412).

        Returns True when training should stop (no splittable tree). The
        iteration is dispatched asynchronously: trees stay on device and
        host materialization is deferred to `_materialize` (so the stop may
        be reported up to `_flush_every` iterations late; the in-graph
        score gating makes the overshoot iterations exact no-ops).
        """
        if self._stopped:
            return True
        if self._stream is not None:
            if grad is not None or self._use_input_grads:
                raise LightGBMError(
                    "streamed training does not support externally "
                    "supplied gradients; use a built-in objective or "
                    "unset data_stream_chunk_rows")
            return self._train_one_iter_streamed()
        _faults.inject("train_dispatch", iteration=self.iter_)
        self._boost_from_average()
        self._maybe_warm_ladder()
        if self._compiled_iter is None:
            self._compiled_iter = self._make_train_iter_fn()

        iter_idx = self.iter_
        obs = self.obs
        # host window opens before mask sampling (matches train_many)
        t0 = time.perf_counter() if obs.enabled else 0.0
        sample_mask = self._sample_bagging_mask(iter_idx)
        feature_mask = self._sample_feature_mask()

        n, k = self.num_data, self.num_tree_per_iteration
        if grad is not None:
            g_in = jnp.asarray(np.asarray(grad, np.float32).reshape(k, n).T
                               if np.asarray(grad).ndim == 1 and k > 1
                               else np.asarray(grad, np.float32).reshape(n, k))
            h_in = jnp.asarray(np.asarray(hess, np.float32).reshape(k, n).T
                               if np.asarray(hess).ndim == 1 and k > 1
                               else np.asarray(hess, np.float32).reshape(n, k))
        elif self._use_input_grads:
            g_in, h_in = self._fixed_gradients()
        else:
            g_in = jnp.zeros((n, k), jnp.float32)
            h_in = jnp.ones((n, k), jnp.float32)

        self._bag_key, goss_key = jax.random.split(self._bag_key)
        obs.perfetto_step(iter_idx, iter_idx + 1)
        t_disp = t0
        with obs.span("train_iter", iteration=iter_idx):
            packed, leaf_ids, new_scores, cegb_new, self._stopped_dev, \
                health, mstats = self._compiled_iter(
                    *self._iter_capture,
                    self.scores, sample_mask, feature_mask, g_in, h_in,
                    jnp.float32(self.shrinkage_rate),
                    jnp.float32(self._goss_active(iter_idx)), goss_key,
                    self._cegb_state, self._stopped_dev)
            if obs.enabled:
                t_disp = time.perf_counter()
                # span-close sync: the per-iteration path is already the
                # slow (full/host-logic) path, so one barrier per
                # iteration is the accepted cost of true spans
                jax.block_until_ready(new_scores)  # lgbm-lint: disable=LGL103 span close
        t_done = time.perf_counter() if obs.enabled else 0.0
        self.scores = new_scores
        self._cegb_state = cegb_new

        pend: Dict[str, Any] = {"packed": packed[None],  # [1, K, T] block
                                "shrinkage": self.shrinkage_rate,
                                "count": 1,
                                "mstats": (mstats[None]
                                           if mstats is not None else None)}
        self._pending.append(pend)
        self.iter_ += 1
        if obs.enabled:
            hrow = np.asarray(health)[None]
            obs.dispatch_done(iter_idx, 1, t_done - t0,
                              health_rows=hrow,
                              busy_s=t_disp - t0, wait_s=t_done - t_disp)
            obs.account_rows(self.num_data_orig)
            if obs.per_iteration:
                obs.record_hbm()
            obs.check_health(hrow, iter_idx, booster=self)
        elif obs.health_enabled:
            obs.check_health(np.asarray(health)[None], iter_idx,
                             booster=self)
        if sum(p["count"] for p in self._pending) >= self._flush_every:
            return self._materialize()
        return False

    def _materialize(self) -> bool:
        """Flush pending device trees to HostTrees (one batched transfer).

        Returns True if training has stopped (a fully-stumped iteration was
        found; later pending iterations are no-ops by construction and are
        discarded).
        """
        if not self._pending:
            return self._stopped
        pend, self._pending = self._pending, []
        k = self.num_tree_per_iteration
        l = self.config.num_leaves
        # every pending entry is a [B_i, K, T] block (B_i == 1 for
        # per-iteration dispatches); ONE transfer for the whole backlog
        self._last_flush_shapes = [
            jax.ShapeDtypeStruct(p["packed"].shape, p["packed"].dtype)
            for p in pend]
        with self.obs.span("materialize", blocks=len(pend)):
            buf = np.asarray(jnp.concatenate([p["packed"] for p in pend],
                                             axis=0))  # [sum(B_i), K, T]
        row = 0
        for p in pend:
            if self._stopped:
                break
            for bi in range(p["count"]):
                host_trees = []
                any_split = False
                for c in range(k):
                    t = unpack_tree(buf[row, c], l)
                    ht = self._extract_host_tree(t)
                    if ht.num_leaves_actual > 1:
                        any_split = True
                    host_trees.append(ht)
                row += 1
                if not any_split:
                    Log.warning("Stopped training because there are no "
                                "more leaves that meet the split "
                                "requirements")
                    if not self._models:
                        # keep a constant tree so the model reproduces the
                        # init score (AsConstantTree, gbdt.cpp:379-396)
                        inits = getattr(self, "init_score_offsets",
                                        np.zeros(k, np.float32))
                        for c in range(k):
                            ht = host_trees[c]
                            ht.num_leaves_actual = 1
                            ht.leaf_value[:] = 0.0
                            ht.leaf_value[0] = float(inits[c])
                            ht.split_leaf[:] = -1
                            self._models.append(ht)
                    self._stopped = True
                    self.iter_ = len(self._models) // max(k, 1)
                    break
                self._store_host_trees(host_trees, p)
                if self._modelstats is not None:
                    # model statistics track the KEPT model list exactly:
                    # stump/overshoot iterations broke out above, so this
                    # runs once per stored iteration. ingest after the
                    # store so leaf values are the final (shrunk,
                    # bias-folded) model values. Device accumulators
                    # transfer once per pending entry, lazily.
                    dev_rows = None
                    if p.get("mstats") is not None:
                        if "mstats_host" not in p:
                            p["mstats_host"] = np.asarray(p["mstats"])
                        dev_rows = p["mstats_host"][bi]
                    self._modelstats.ingest_iteration(
                        host_trees, len(self._models) // max(k, 1) - 1,
                        device_rows=dev_rows)
        return self._stopped

    def _store_host_trees(self, host_trees: List[HostTree],
                          pend: Dict[str, Any]) -> None:

        """Renew/shrink/bias-fold one flushed iteration's trees and append
        them to the model list (the tail of the reference's TrainOneIter)."""
        k = self.num_tree_per_iteration
        first_iter = not self._models
        for ht in host_trees:
            ht.shrink(pend["shrinkage"])
        # valid scores get the shrunk tree output (pre-bias; their init score
        # was added by _boost_from_average already)
        self._update_valid_scores(host_trees)
        if first_iter:
            # fold the init score into the first iteration's trees so the
            # saved model is self-contained (AddBias, gbdt.cpp:374-376)
            inits = getattr(self, "init_score_offsets", np.zeros(k, np.float32))
            for c, ht in enumerate(host_trees):
                if abs(float(inits[c])) > 1e-15:
                    ht.leaf_value += float(inits[c])
                    ht.internal_value += float(inits[c])
        self._models.extend(host_trees)

    def _extract_host_tree(self, t) -> HostTree:
        """TreeArrays (device) -> HostTree with real thresholds."""
        ds = self.train_data
        l = self.config.num_leaves
        ht = HostTree(l)
        nl = int(t.num_leaves)
        ht.num_leaves_actual = nl
        nn = nl - 1
        used = np.arange(nn)
        inner_feat = t.split_feature[:nn].astype(np.int64)
        ht.split_feature[:nn] = np.array(
            [ds.real_feature_index(int(j)) for j in inner_feat], np.int32)
        ht.split_gain[:nn] = t.split_gain[:nn]
        ht.threshold_bin[:nn] = t.threshold_bin[:nn]
        # raw-value bitsets are variable-width (Tree cat_threshold_,
        # tree.h:276-291): wide enough for the largest category value of any
        # categorical feature in this dataset
        max_cat_val = max(
            (max(m.bin_2_categorical) for m in ds.bin_mappers
             if m.bin_type == BinType.CATEGORICAL and m.bin_2_categorical),
            default=0)
        cat_words = max(8, (max_cat_val + 32) // 32)
        ht.cat_bitset = np.zeros((max(nn, 1), cat_words), np.uint32)
        for i in range(nn):
            mapper = ds.bin_mappers[int(ht.split_feature[i])]
            if bool(t.is_categorical[i]):
                ht.threshold[i] = 0.0
                # translate the bin-space bitset into raw category values for
                # raw-input prediction and model serialization (the reference
                # stores cat_threshold in value space, tree.cpp)
                for b in range(1, mapper.num_bin):
                    if (int(t.cat_bitset[i][b >> 5]) >> (b & 31)) & 1:
                        v = mapper.bin_2_categorical[b - 1]
                        ht.cat_bitset[i][v >> 5] |= np.uint32(1 << (v & 31))
            else:
                tb = int(t.threshold_bin[i])
                ht.threshold[i] = mapper.bin_to_value(tb)
        ht.default_left[:nn] = t.default_left[:nn]
        ht.missing_type[:nn] = t.missing_type[:nn]
        ht.is_categorical[:nn] = t.is_categorical[:nn]
        ht.cat_bitset_bin[:nn] = t.cat_bitset[:nn]
        ht.left_child[:nn] = t.left_child[:nn]
        ht.right_child[:nn] = t.right_child[:nn]
        ht.split_leaf[:nn] = t.split_leaf[:nn]
        ht.internal_value[:nn] = t.internal_value[:nn]
        ht.internal_weight[:nn] = t.internal_weight[:nn]
        ht.internal_count[:nn] = np.round(t.internal_count[:nn]).astype(np.int64)
        ht.leaf_value[:] = t.leaf_value[:l]
        ht.leaf_weight[:] = t.leaf_weight[:l]
        ht.leaf_count[:] = np.round(t.leaf_count[:l]).astype(np.int64)
        return ht

    # ------------------------------------------------------------ scoring
    def _update_valid_scores(self, host_trees: List[HostTree]) -> None:
        """Add the new trees' output to each valid set's running scores via
        binned replay (ScoreUpdater::AddScore whole-tree path)."""
        if not self.valid_data:
            return
        k = self.num_tree_per_iteration
        for vi, cache in self._valid_pred_cache.items():
            xb = cache["xb"]
            scores = cache["scores"]
            for c, ht in enumerate(host_trees):
                leaf = self._replay_leaves_binned(ht, xb)
                scores = scores.at[:, c].add(
                    jnp.asarray(ht.leaf_value.astype(np.float32))[leaf])
            cache["scores"] = scores

    @staticmethod
    @functools.partial(jax.jit, static_argnames=("packed",))
    def _replay_leaves_binned_impl(split_leaf, stored_col, bin_offset,
                                   threshold_bin, default_left, missing_type,
                                   is_cat, cat_bitset, num_bin, default_bin,
                                   pack_div, pack_mod, xb, packed=False):
        from ..core.grow import _bin_go_left, decode_bundle_value
        n = xb.shape[0]
        num_nodes = split_leaf.shape[0]

        def step(t, leaf_id):
            active = split_leaf[t] >= 0
            if packed:
                # word-packed device matrix (core/binpack.py): extract
                # the split's single code column with a shift/mask
                from ..core.binpack import CODES_PER_WORD
                word = jnp.take(xb, stored_col[t] // CODES_PER_WORD,
                                axis=1)
                col = (word >> ((stored_col[t] % CODES_PER_WORD) * 8)) \
                    & 0xFF
            else:
                col = jnp.take(xb, stored_col[t], axis=1)
            binv = decode_bundle_value(col, bin_offset[t], num_bin[t],
                                       default_bin[t],
                                       pack_div=pack_div[t],
                                       pack_mod=pack_mod[t])
            go_left = _bin_go_left(binv, threshold_bin[t], default_left[t],
                                   missing_type[t], num_bin[t], default_bin[t],
                                   is_cat[t], cat_bitset[t])
            in_node = leaf_id == split_leaf[t]
            return jnp.where(active & in_node & ~go_left, t + 1, leaf_id)

        return jax.lax.fori_loop(0, num_nodes, step,
                                 jnp.zeros((n,), jnp.int32))

    def _replay_leaves_binned(self, ht: HostTree, xb: jnp.ndarray) -> jnp.ndarray:
        ds = self.train_data
        feat_col, feat_offset, _, pack_div, pack_mod, _ = ds.feature_layout()
        inner = np.array([max(ds.inner_feature_index(int(f)), 0)
                          for f in ht.split_feature], np.int32)
        num_bin = np.array([ds.bin_mappers[int(f)].num_bin
                            for f in ht.split_feature], np.int32)
        default_bin = np.array([ds.bin_mappers[int(f)].default_bin
                                for f in ht.split_feature], np.int32)
        # the train matrix may be word-packed (int32 words); the valid
        # caches always hold plain uint8 columns
        packed = (getattr(self, "_word_packed_cols", 0) > 0
                  and xb.dtype == jnp.int32)
        return self._replay_leaves_binned_impl(
            jnp.asarray(ht.split_leaf), jnp.asarray(feat_col[inner]),
            jnp.asarray(feat_offset[inner]),
            jnp.asarray(ht.threshold_bin), jnp.asarray(ht.default_left),
            jnp.asarray(ht.missing_type), jnp.asarray(ht.is_categorical),
            jnp.asarray(ht.cat_bitset_bin), jnp.asarray(num_bin),
            jnp.asarray(default_bin), jnp.asarray(pack_div[inner]),
            jnp.asarray(pack_mod[inner]), xb, packed=packed)

    # ------------------------------------------------------------ evaluation
    def get_eval_at(self, data_idx: int) -> List[Tuple[str, str, float, bool]]:
        """Eval metrics for data_idx (0=train, 1..=valid); returns
        (data_name, metric_name, value, bigger_better) tuples
        (gbdt.cpp OutputMetric:476-533)."""
        # valid-set score caches advance at materialization time
        self._materialize()
        out = []
        conv = (self.objective.convert_output if self.objective is not None
                else None)
        if data_idx == 0:
            if self._stream_perm is not None:
                # streamed mesh: scores live in the shard-major padded
                # layout; gather original-row order back (train-set eval
                # under a MULTI-process mesh is not supported — the
                # global scores are not host-addressable from one rank)
                if not getattr(self.scores, "is_fully_addressable", True):
                    raise LightGBMError(
                        "train-set metrics are not available under "
                        "multi-process streamed training; evaluate on a "
                        "valid set or predict() from the saved model")
                scores = np.asarray(self.scores)[self._stream_perm]
            else:
                scores = np.asarray(self.scores)[:self.num_data_orig]
            for m in self.train_metrics:
                vals = m.eval(scores if self.num_tree_per_iteration > 1
                              else scores[:, 0], conv)
                for name, v in zip(m.names, vals):
                    out.append(("training", name, v, m.factor_to_bigger_better > 0))
        else:
            vi = data_idx - 1
            scores = np.asarray(self._valid_pred_cache[vi]["scores"])
            for m in self.valid_metrics[vi]:
                vals = m.eval(scores if self.num_tree_per_iteration > 1
                              else scores[:, 0], conv)
                for name, v in zip(m.names, vals):
                    out.append(("valid_%d" % (vi + 1) if vi > 0 else "valid_0",
                                name, v, m.factor_to_bigger_better > 0))
        return out

    # ------------------------------------------------------------ prediction
    def _stacked_predict_trees(self, start: int, end: int) -> tree_mod.PredictTree:
        trees = self.models[start:end]
        max_nodes = max((t.num_nodes for t in trees), default=1)
        max_leaves = max((t.num_leaves for t in trees), default=1)
        cat_words = max((t.cat_bitset.shape[1] for t in trees), default=8)
        tables = [t.predict_table(max_nodes, max_leaves, cat_words)
                  for t in trees]
        return jax.tree.map(lambda *xs: jnp.asarray(np.stack(xs)), *tables)

    def predict(self, data: np.ndarray, num_iteration: Optional[int] = None,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_early_stop: bool = False,
                pred_early_stop_freq: int = 10,
                pred_early_stop_margin: float = 10.0) -> np.ndarray:
        """Batch prediction on raw feature values (GBDT::Predict,
        gbdt_prediction.cpp:49-83; early stop:
        src/boosting/prediction_early_stop.cpp)."""
        data = np.asarray(data, np.float32)
        if data.ndim == 1:
            data = data.reshape(1, -1)
        k = self.num_tree_per_iteration
        total_iters = len(self.models) // k
        use_iters = total_iters if num_iteration is None or num_iteration <= 0 \
            else min(num_iteration, total_iters)
        n = data.shape[0]
        if pred_early_stop and self.objective is not None \
                and self.objective.need_accurate_prediction:
            # reference only early-stops classification margins
            # (predictor.hpp:39, NeedAccuratePrediction)
            pred_early_stop = False
        if use_iters == 0:
            out = np.zeros((n, k), np.float64)
        elif pred_early_stop and not pred_leaf:
            x = jnp.asarray(data)
            flat = self._stacked_predict_trees(0, use_iters * k)
            stacked = jax.tree.map(
                lambda a: a.reshape((use_iters, k) + a.shape[1:]), flat)
            out = np.asarray(tree_mod.predict_forest_early_stop(
                stacked, x, max(pred_early_stop_freq, 1),
                pred_early_stop_margin, is_multiclass=(k > 1)), np.float64)
            if self.average_output:
                out = out / use_iters
            if not raw_score and self.objective is not None:
                out = np.asarray(self.objective.convert_output(jnp.asarray(out)))
            return out[:, 0] if k == 1 else out
        else:
            x = jnp.asarray(data)
            outs = []
            for c in range(k):
                idxs = [it * k + c for it in range(use_iters)]
                trees = [self.models[i] for i in idxs]
                max_nodes = max(t.num_nodes for t in trees)
                max_leaves = max(t.num_leaves for t in trees)
                tables = [t.predict_table(max_nodes, max_leaves) for t in trees]
                stacked = jax.tree.map(lambda *xs: jnp.asarray(np.stack(xs)),
                                       *tables)
                if pred_leaf:
                    outs.append(np.asarray(
                        tree_mod.predict_forest_leaves_raw(stacked, x)))
                else:
                    outs.append(np.asarray(
                        tree_mod.predict_forest_raw(stacked, x), np.float64))
            if pred_leaf:
                return np.stack(outs, axis=1).reshape(n, -1) if k > 1 else outs[0]
            out = np.stack(outs, axis=1)
        if self.average_output and use_iters > 0:
            out = out / use_iters
        if not raw_score and self.objective is not None:
            out = np.asarray(self.objective.convert_output(jnp.asarray(out)))
        return out[:, 0] if k == 1 else out

    # ------------------------------------------------------------ management
    def rollback_one_iter(self) -> None:
        """GBDT::RollbackOneIter (gbdt.cpp:414-430)."""
        if self.iter_ <= 0:
            return
        if self._stream is not None:
            raise LightGBMError(
                "rollback_one_iter needs the full binned matrix to replay "
                "dropped trees; it is not supported with streamed "
                "training (data_stream_chunk_rows > 0)")
        k = self.num_tree_per_iteration
        dropped = self.models[-k:]
        del self.models[-k:]
        # recompute training scores by subtracting the dropped trees
        for c, ht in enumerate(dropped):
            leaf = self._replay_leaves_binned(ht, self.xb)
            self.scores = self.scores.at[:, c].add(
                -jnp.asarray(ht.leaf_value.astype(np.float32))[leaf])
        for vi, cache in self._valid_pred_cache.items():
            for c, ht in enumerate(dropped):
                leaf = self._replay_leaves_binned(ht, cache["xb"])
                cache["scores"] = cache["scores"].at[:, c].add(
                    -jnp.asarray(ht.leaf_value.astype(np.float32))[leaf])
        self.iter_ -= 1

    @property
    def current_iteration(self) -> int:
        # must materialize: dispatched iterations past a device-detected
        # stop get discarded at flush, so the pending count alone would
        # overstate the model length (and poison best_iteration)
        self._materialize()
        return len(self._models) // max(self.num_tree_per_iteration, 1)

    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        """GBDT::FeatureImportance (gbdt.cpp era)."""
        num_feat = self.train_data.num_total_features if self.train_data \
            else (int(max((t.split_feature.max(initial=-1)
                           for t in self.models), default=-1)) + 1)
        imp = np.zeros(num_feat, np.float64)
        k = self.num_tree_per_iteration
        n_models = (len(self.models) if iteration is None or iteration <= 0
                    else min(iteration * k, len(self.models)))
        for t in self.models[:n_models]:
            for i in range(t.num_nodes):
                if t.split_leaf[i] >= 0:
                    if importance_type == "split":
                        imp[t.split_feature[i]] += 1
                    else:
                        imp[t.split_feature[i]] += t.split_gain[i]
        return imp
