"""GOSS boosting (Gradient-based One-Side Sampling).

TPU-native re-design of src/boosting/goss.hpp. The sampling itself runs on
device inside the jitted iteration (see GBDT._make_train_iter_fn's is_goss
branch): top ``top_rate`` rows by sum-over-classes |grad*hess| are always
kept; the rest are Bernoulli-sampled at ``other_rate / (1 - top_rate)`` and
their grad/hess amplified by ``(n - top)/other`` (goss.hpp BaggingHelper
:87-135). Like the reference, sampling is disabled for the first
``1 / learning_rate`` iterations (goss.hpp Bagging :137-140).
"""
from __future__ import annotations

from ..config import Config
from ..log import LightGBMError
from .gbdt import GBDT


class GOSS(GBDT):
    boosting_type = "goss"

    def __init__(self, config: Config, train_data, objective, metrics=None):
        if config.bagging_freq > 0 and config.bagging_fraction != 1.0:
            raise LightGBMError("Cannot use bagging in GOSS")
        if not (config.top_rate > 0.0 and config.other_rate > 0.0):
            raise LightGBMError("GOSS needs top_rate > 0 and other_rate > 0")
        self._goss_activated_logged = False
        super().__init__(config, train_data, objective, metrics)

    def _goss_active(self, iter_idx: int) -> float:
        warmup = int(1.0 / max(self.config.learning_rate, 1e-12))
        active = iter_idx >= warmup
        if active and not self._goss_activated_logged:
            # one obs event at the warmup->sampling transition — bagging
            # semantics change here, worth a mark on the event stream
            self._goss_activated_logged = True
            self.obs.event("goss_sampling_active", iteration=iter_idx,
                           warmup_iters=warmup)
        return 1.0 if active else 0.0
