"""Boosting drivers (include/LightGBM/boosting.h:22-294).

Factory mirrors Boosting::CreateBoosting (src/boosting/boosting.cpp:30-45):
"gbdt" | "dart" | "goss" | "rf".
"""
from typing import List, Optional

from ..config import Config
from ..log import LightGBMError
from .gbdt import GBDT, HostTree


def create_boosting(config: Config, train_data=None, objective=None,
                    metrics: Optional[List] = None):
    name = config.boosting
    if name == "gbdt":
        return GBDT(config, train_data, objective, metrics)
    if name == "dart":
        from .dart import DART
        return DART(config, train_data, objective, metrics)
    if name == "goss":
        from .goss import GOSS
        return GOSS(config, train_data, objective, metrics)
    if name == "rf":
        from .rf import RF
        return RF(config, train_data, objective, metrics)
    raise LightGBMError("Unknown boosting type %s" % name)


__all__ = ["GBDT", "HostTree", "create_boosting"]
