"""DART boosting (Dropouts meet Multiple Additive Regression Trees).

TPU-native re-design of src/boosting/dart.hpp:40-205. Semantics preserved:
per-iteration drop set chosen by ``drop_rate`` (uniform or tree-weighted),
skipped entirely with probability ``skip_drop``, capped at ``max_drop``;
the new tree is trained against scores with the dropped trees removed and
shrunk by ``lr / (1 + k)`` (or ``lr / (lr + k)`` in xgboost mode); dropped
trees are then normalized by ``k / (k + 1)`` (xgboost: ``k / (lr + k)``) and
train/valid scores adjusted to match (dart.hpp Normalize :141-186).
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..config import Config
from .gbdt import GBDT, HostTree


class DART(GBDT):
    boosting_type = "dart"

    def __init__(self, config: Config, train_data, objective, metrics=None):
        super().__init__(config, train_data, objective, metrics)
        self._drop_rng = np.random.RandomState(config.drop_seed)
        self.tree_weight: List[float] = []
        self.sum_weight = 0.0
        # DART reads/normalizes stored trees around every iteration, so the
        # async driver's deferred materialization would break its
        # stop-rollback path; flush each iteration.
        self._flush_every = 1

    # ------------------------------------------------- checkpoint state
    def training_state(self):
        from ..checkpoint import snapshot as snap_mod
        meta, arrays = super().training_state()
        drop_meta, drop_keys = snap_mod.rng_state_split(self._drop_rng)
        # JSON float round-trips are exact (repr/shortest-roundtrip), so
        # tree_weight/sum_weight come back bit-identical
        meta["dart"] = {"rng": drop_meta,
                        "tree_weight": [float(w) for w in self.tree_weight],
                        "sum_weight": float(self.sum_weight)}
        arrays["dart_rng_keys"] = drop_keys
        return meta, arrays

    def load_training_state(self, meta, arrays) -> None:
        from ..checkpoint import snapshot as snap_mod
        super().load_training_state(meta, arrays)
        d = meta.get("dart")
        if d is not None and "dart_rng_keys" in arrays:
            self._drop_rng.set_state(
                snap_mod.rng_state_join(d["rng"], arrays["dart_rng_keys"]))
            self.tree_weight = [float(w) for w in d["tree_weight"]]
            self.sum_weight = float(d["sum_weight"])

    def warn_lossy_continuation(self) -> None:
        from ..log import Log
        Log.warning(
            "Continued DART training from init_model: the drop-set "
            "RandomState and per-tree weights cannot be reconstructed from "
            "a model file, so dropping probabilities restart from scratch "
            "and results WILL diverge from an uninterrupted run. Use "
            "checkpoints (engine.train(resume_from=<dir>)) for exact "
            "continuation.")
        super().warn_lossy_continuation()

    def _dropping_trees(self) -> List[int]:
        """Select iteration indices to drop (dart.hpp DroppingTrees:88-139)."""
        cfg = self.config
        drop_index: List[int] = []
        if self._drop_rng.rand() < cfg.skip_drop:
            return drop_index
        drop_rate = cfg.drop_rate
        n_iter = self.iter_
        if not cfg.uniform_drop and self.sum_weight > 0:
            inv_avg = len(self.tree_weight) / self.sum_weight
            if cfg.max_drop > 0:
                drop_rate = min(drop_rate, cfg.max_drop * inv_avg / self.sum_weight)
            for i in range(n_iter):
                if self._drop_rng.rand() < drop_rate * self.tree_weight[i] * inv_avg:
                    drop_index.append(i)
                    if len(drop_index) >= cfg.max_drop > 0:
                        break
        else:
            if cfg.max_drop > 0 and n_iter > 0:
                drop_rate = min(drop_rate, cfg.max_drop / float(n_iter))
            for i in range(n_iter):
                if self._drop_rng.rand() < drop_rate:
                    drop_index.append(i)
                    if len(drop_index) >= cfg.max_drop > 0:
                        break
        return drop_index

    def _tree_delta(self, ht: HostTree, xb) -> jnp.ndarray:
        """Replay one tree's (shrunk) output on a binned matrix."""
        leaf = self._replay_leaves_binned(ht, xb)
        return jnp.asarray(ht.leaf_value.astype(np.float32))[leaf]

    def train_one_iter(self, grad=None, hess=None) -> bool:
        cfg = self.config
        k_cls = self.num_tree_per_iteration
        drop_index = self._dropping_trees()
        k = float(len(drop_index))
        self.obs.event("dart_drop", iteration=self.iter_,
                       dropped=len(drop_index))

        # remove dropped trees from train/valid scores (DroppingTrees :125-131)
        train_deltas = {}   # (iter i, class c) -> [N] device array
        valid_deltas = {}
        with self.obs.span("dart_drop_adjust", dropped=len(drop_index)):
            for i in drop_index:
                for c in range(k_cls):
                    ht = self.models[i * k_cls + c]
                    d = self._tree_delta(ht, self.xb)
                    train_deltas[(i, c)] = d
                    self.scores = self.scores.at[:, c].add(-d)
                    for vi, cache in self._valid_pred_cache.items():
                        dv = self._tree_delta(ht, cache["xb"])
                        valid_deltas[(vi, i, c)] = dv
                        cache["scores"] = cache["scores"].at[:, c].add(-dv)

        # new-tree shrinkage (dart.hpp :133-139)
        if not cfg.xgboost_dart_mode:
            self.shrinkage_rate = cfg.learning_rate / (1.0 + k)
        else:
            self.shrinkage_rate = (cfg.learning_rate if not drop_index else
                                   cfg.learning_rate / (cfg.learning_rate + k))

        ret = super().train_one_iter(grad, hess)
        if ret:
            # restore the dropped trees' contribution before bailing out
            for (i, c), d in train_deltas.items():
                self.scores = self.scores.at[:, c].add(d)
            for (vi, i, c), dv in valid_deltas.items():
                self._valid_pred_cache[vi]["scores"] = \
                    self._valid_pred_cache[vi]["scores"].at[:, c].add(dv)
            return ret

        # Normalize (dart.hpp :141-186): dropped trees scaled in place and
        # their scaled output restored to the scores.
        if drop_index:
            if not cfg.xgboost_dart_mode:
                factor = k / (k + 1.0)
            else:
                factor = k / (cfg.learning_rate + k)
            for i in drop_index:
                for c in range(k_cls):
                    ht = self.models[i * k_cls + c]
                    ht.shrink(factor)
                    self.scores = self.scores.at[:, c].add(
                        train_deltas[(i, c)] * factor)
                    for vi, cache in self._valid_pred_cache.items():
                        cache["scores"] = cache["scores"].at[:, c].add(
                            valid_deltas[(vi, i, c)] * factor)
                if not cfg.uniform_drop:
                    if not cfg.xgboost_dart_mode:
                        self.sum_weight -= self.tree_weight[i] * (1.0 / (k + 1.0))
                    else:
                        self.sum_weight -= self.tree_weight[i] * \
                            (1.0 / (k + cfg.learning_rate))
                    self.tree_weight[i] *= factor

        if not cfg.uniform_drop:
            self.tree_weight.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
        return False
