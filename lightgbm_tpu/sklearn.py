"""scikit-learn estimator wrappers.

Reference: python-package/lightgbm/sklearn.py — LGBMModel (:133),
LGBMRegressor/LGBMClassifier/LGBMRanker (:669, :695, :823), and the
grad/hess-ordering objective/eval adapters (:18-130). Works without sklearn
installed (duck-typed get_params/set_params), and registers as a real
sklearn estimator when it is.
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .basic import Booster, Dataset
from .engine import train as _train
from .log import LightGBMError

try:
    from sklearn.base import BaseEstimator, ClassifierMixin, RegressorMixin
    from sklearn.preprocessing import LabelEncoder
    _SKLEARN_INSTALLED = True
except ImportError:  # pragma: no cover
    _SKLEARN_INSTALLED = False

    class BaseEstimator:
        pass

    class ClassifierMixin:
        pass

    class RegressorMixin:
        pass

    class LabelEncoder:
        def fit(self, y):
            self.classes_ = np.unique(y)
            return self

        def transform(self, y):
            return np.searchsorted(self.classes_, y)

        def fit_transform(self, y):
            return self.fit(y).transform(y)

        def inverse_transform(self, idx):
            return self.classes_[idx]


class _ObjectiveFunctionWrapper:
    """sklearn-style fobj(y_true, y_pred) -> internal fobj(preds, dataset)
    (sklearn.py:18-80)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            grad, hess = self.func(labels, preds)
        elif argc == 3:
            grad, hess = self.func(labels, preds, dataset.get_group())
        else:
            raise TypeError("Self-defined objective should have 2 or 3 args")
        return grad, hess


class _EvalFunctionWrapper:
    """sklearn-style feval (sklearn.py:81-130)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            return self.func(labels, preds)
        if argc == 3:
            return self.func(labels, preds, dataset.get_weight())
        if argc == 4:
            return self.func(labels, preds, dataset.get_weight(),
                             dataset.get_group())
        raise TypeError("Self-defined eval function should have 2-4 args")


class LGBMModel(BaseEstimator):
    """Base estimator (sklearn.py:133)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[Union[str, Callable]] = None,
                 class_weight=None, min_split_gain: float = 0.0,
                 min_child_weight: float = 1e-3, min_child_samples: int = 20,
                 subsample: float = 1.0, subsample_freq: int = 0,
                 colsample_bytree: float = 1.0, reg_alpha: float = 0.0,
                 reg_lambda: float = 0.0, random_state=None,
                 n_jobs: int = -1, silent: bool = True,
                 importance_type: str = "split", **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.silent = silent
        self.importance_type = importance_type
        self._other_params = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._evals_result = None
        self._best_score = None
        self._best_iteration = None
        self._classes = None
        self._n_classes = None
        self._n_features = None
        self._objective = objective
        self.set_params(**kwargs)

    # -------------------------------------------------- sklearn plumbing
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = {}
        for key in ("boosting_type", "num_leaves", "max_depth",
                    "learning_rate", "n_estimators", "subsample_for_bin",
                    "objective", "class_weight", "min_split_gain",
                    "min_child_weight", "min_child_samples", "subsample",
                    "subsample_freq", "colsample_bytree", "reg_alpha",
                    "reg_lambda", "random_state", "n_jobs", "silent",
                    "importance_type"):
            params[key] = getattr(self, key)
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for key, value in params.items():
            setattr(self, key, value)
            if not hasattr(type(self), key):
                self._other_params[key] = value
        return self

    # -------------------------------------------------- fitting
    def _default_objective(self) -> str:
        return "regression"

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_class_weight=None, eval_init_score=None, eval_group=None,
            eval_metric=None, early_stopping_rounds=None, verbose=True,
            feature_name="auto", categorical_feature="auto",
            callbacks=None) -> "LGBMModel":
        objective = self.objective or self._default_objective()
        fobj = None
        if callable(objective):
            fobj = _ObjectiveFunctionWrapper(objective)
            objective = "none"
        params = self.get_params()
        params.pop("objective", None)
        params.pop("class_weight", None)
        params.pop("importance_type", None)
        params.pop("silent", None)
        params.pop("n_jobs", None)
        params.pop("random_state", None)
        params.pop("n_estimators", None)
        params["objective"] = objective
        params["verbosity"] = -1 if self.silent else 1
        if self.random_state is not None:
            params["seed"] = self.random_state \
                if isinstance(self.random_state, int) else 0
        if eval_metric is not None and not callable(eval_metric):
            params["metric"] = eval_metric
        if getattr(self, "_fit_eval_at", None):
            # drop every alias so the fit-time value cannot lose the
            # Config alias-resolution race against a constructor param
            for alias in ("eval_at", "ndcg_eval_at", "ndcg_at",
                          "map_eval_at", "map_at"):
                params.pop(alias, None)
            params["ndcg_eval_at"] = self._fit_eval_at
        feval = _EvalFunctionWrapper(eval_metric) if callable(eval_metric) \
            else None

        X = np.asarray(X, dtype=np.float64) if not hasattr(X, "dtypes") else X
        y = np.asarray(y).reshape(-1)
        if sample_weight is not None:
            sample_weight = np.asarray(sample_weight, np.float64).reshape(-1)
        if self.class_weight is not None and self._n_classes is None:
            sample_weight = self._apply_class_weight(y, sample_weight)

        train_set = Dataset(X, label=y, weight=sample_weight, group=group,
                            init_score=init_score, params=dict(params),
                            feature_name=feature_name,
                            categorical_feature=categorical_feature,
                            free_raw_data=False)
        valid_sets = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                if vx is X and vy is y:
                    valid_sets.append(train_set)
                    continue
                vw = None
                if eval_sample_weight is not None:
                    vw = eval_sample_weight[i]
                vg = eval_group[i] if eval_group is not None else None
                vi = eval_init_score[i] if eval_init_score is not None else None
                vy_arr = np.asarray(vy).reshape(-1)
                if self._classes is not None:
                    vy_arr = self._le.transform(vy_arr)
                valid_sets.append(train_set.create_valid(
                    vx, label=vy_arr, weight=vw, group=vg, init_score=vi))

        evals_result: Dict = {}
        self._Booster = _train(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None, valid_names=eval_names,
            fobj=fobj, feval=feval,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=evals_result,
            verbose_eval=verbose if not self.silent else False,
            callbacks=callbacks)
        self._evals_result = evals_result
        self._best_iteration = self._Booster.best_iteration
        self._best_score = self._Booster.best_score
        self._n_features = X.shape[1] if hasattr(X, "shape") else len(X[0])
        return self

    def _apply_class_weight(self, y, sample_weight):
        if self.class_weight == "balanced":
            classes, counts = np.unique(y, return_counts=True)
            weights = {c: len(y) / (len(classes) * n)
                       for c, n in zip(classes, counts)}
        else:
            weights = dict(self.class_weight)
        w = np.array([weights.get(v, 1.0) for v in y], np.float64)
        if sample_weight is not None:
            w = w * sample_weight
        return w

    def predict(self, X, raw_score: bool = False, num_iteration=None,
                pred_leaf: bool = False, pred_contrib: bool = False, **kwargs):
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted, call fit first")
        return self._Booster.predict(X, raw_score=raw_score,
                                     num_iteration=num_iteration,
                                     pred_leaf=pred_leaf,
                                     pred_contrib=pred_contrib)

    # -------------------------------------------------- attributes
    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise LightGBMError("No booster found, call fit first")
        return self._Booster

    @property
    def best_iteration_(self) -> int:
        return self._best_iteration

    @property
    def best_score_(self):
        return self._best_score

    @property
    def evals_result_(self):
        return self._evals_result

    @property
    def n_features_(self) -> int:
        return self._n_features

    @property
    def feature_importances_(self) -> np.ndarray:
        return self.booster_.feature_importance(
            importance_type=self.importance_type)


class LGBMRegressor(LGBMModel, RegressorMixin):
    """sklearn.py:669."""

    def _default_objective(self) -> str:
        return "regression"

    def fit(self, X, y, **kwargs):
        return super().fit(X, y, **kwargs)


class LGBMClassifier(LGBMModel, ClassifierMixin):
    """sklearn.py:695."""

    def _default_objective(self) -> str:
        return "binary"

    def fit(self, X, y, **kwargs):
        self._le = LabelEncoder().fit(np.asarray(y).reshape(-1))
        self._classes = self._le.classes_
        self._n_classes = len(self._classes)
        y_enc = self._le.transform(np.asarray(y).reshape(-1))
        if self._n_classes > 2:
            if not self.objective or self.objective in ("binary",):
                self.objective = "multiclass"
            self._other_params["num_class"] = self._n_classes
        if self.class_weight is not None:
            kwargs.setdefault("sample_weight", None)
            kwargs["sample_weight"] = self._apply_class_weight(
                y_enc, kwargs.get("sample_weight"))
        return super().fit(X, y_enc, **kwargs)

    @property
    def classes_(self):
        return self._classes

    @property
    def n_classes_(self) -> int:
        return self._n_classes

    def predict(self, X, raw_score: bool = False, num_iteration=None,
                pred_leaf: bool = False, pred_contrib: bool = False, **kwargs):
        result = self.predict_proba(X, raw_score, num_iteration, pred_leaf,
                                    pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim > 1:
            idx = np.argmax(result, axis=1)
        else:
            idx = (result > 0.5).astype(np.int64)
        return self._le.inverse_transform(idx)

    def predict_proba(self, X, raw_score: bool = False, num_iteration=None,
                      pred_leaf: bool = False, pred_contrib: bool = False,
                      **kwargs):
        result = super().predict(X, raw_score, num_iteration, pred_leaf,
                                 pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim == 1:
            return np.vstack([1.0 - result, result]).T
        return result


class LGBMRanker(LGBMModel):
    """sklearn.py:823."""

    def _default_objective(self) -> str:
        return "lambdarank"

    def fit(self, X, y, group=None, eval_set=None, eval_group=None,
            eval_at=None, **kwargs):
        if group is None:
            raise ValueError("Should set group for ranking task")
        if eval_set is not None and eval_group is None:
            raise ValueError("Eval_group cannot be None when eval_set is not "
                             "None")
        # NDCG/MAP truncation levels (sklearn.py:880): fit-local only — the
        # estimator's constructor params must not change across fit calls,
        # and an explicit constructor ndcg_eval_at wins when eval_at is
        # not passed (config's own default covers the rest)
        self._fit_eval_at = list(eval_at) if eval_at is not None else None
        try:
            return super().fit(X, y, group=group, eval_set=eval_set,
                               eval_group=eval_group, **kwargs)
        finally:
            self._fit_eval_at = None
