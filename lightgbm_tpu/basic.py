"""User-facing Dataset and Booster.

LightGBM-compatible Python API surface (reference:
python-package/lightgbm/basic.py — Dataset :656, Booster :1578), implemented
directly over the TPU-native core instead of ctypes into a C library. The
lazy-construction contract is preserved: a ``Dataset`` holds raw data + params
until ``construct()`` bins it (``_lazy_init`` analog, basic.py:693-800);
validation sets bin with the training set's mappers via ``reference``.
"""
from __future__ import annotations

import copy
import os
from typing import Any, Dict, Iterable, List, Optional, Union

import numpy as np

from .config import Config, param_dict_to_str
from .log import Log, LightGBMError, check
from .io.dataset import BinnedDataset, Metadata
from .io import model_text
from .objectives import create_objective
from .metrics import create_metric, default_metric_for_objective
from .boosting import create_boosting

_label_from_pandas_warned = False


def _pandas_frame_to_array(df, pandas_categorical=None):
    """DataFrame -> (float64 array, cat column names, category lists).

    Category-dtype columns become their integer codes (NaN for missing/
    unseen) and their category orders are recorded at train time /
    re-applied at predict time, so raw category values map to identical
    codes across sessions — the semantics of the reference's
    _data_from_pandas (python-package/lightgbm/basic.py:255) and its
    pandas_categorical model-file sidecar.
    """
    cat_cols = [c for c in df.columns
                if str(df[c].dtype) == "category"]
    if pandas_categorical is not None:
        # prediction against a trained mapping: the frame must present the
        # same categorical columns (e.g. a CSV reload that lost the
        # category dtype would otherwise be misread as raw codes)
        check(len(pandas_categorical) == len(cat_cols),
              "train and predict data have different categorical columns")
    if not cat_cols:
        return df.values.astype(np.float64), [], pandas_categorical
    df = df.copy(deep=False)
    if pandas_categorical is None:     # training: record category order
        pandas_categorical = [list(df[c].cat.categories) for c in cat_cols]
    else:                              # prediction: align to trained order
        for c, cats in zip(cat_cols, pandas_categorical):
            df[c] = df[c].cat.set_categories(cats)
    for c in cat_cols:
        codes = df[c].cat.codes.astype(np.float64)
        df[c] = codes.where(codes >= 0, np.nan)
    return df.values.astype(np.float64), [str(c) for c in cat_cols], \
        pandas_categorical


def _to_2d_float(data) -> np.ndarray:
    """Accept ndarray / list / pandas DataFrame / scipy sparse."""
    if hasattr(data, "values") and hasattr(data, "dtypes"):  # DataFrame
        data = _pandas_frame_to_array(data)[0]
    if hasattr(data, "toarray"):  # scipy sparse
        data = data.toarray()
    arr = np.asarray(data, dtype=np.float64)
    if arr.ndim == 1:
        arr = arr.reshape(1, -1)
    check(arr.ndim == 2, "Data must be 2-D")
    return arr


def _to_1d(x) -> Optional[np.ndarray]:
    if x is None:
        return None
    if hasattr(x, "values"):
        x = x.values
    return np.asarray(x, dtype=np.float64).reshape(-1)


class Dataset:
    """Dataset in LightGBM (basic.py:656): lazily-binned training data."""

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None, silent=False,
                 feature_name: Union[str, List[str]] = "auto",
                 categorical_feature: Union[str, List] = "auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.silent = silent
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = copy.deepcopy(params) if params else {}
        self.free_raw_data = free_raw_data
        self.used_indices: Optional[np.ndarray] = None
        self._binned: Optional[BinnedDataset] = None
        self._predictor = None  # _InnerPredictor for continued training
        self.pandas_categorical = None

    # ------------------------------------------------------------ construct
    def construct(self) -> "Dataset":
        """Lazy init (basic.py _lazy_init:693-800)."""
        if self._binned is not None:
            return self
        ref_binned = None
        if self.reference is not None:
            ref_binned = self.reference.construct()._binned
        params = dict(self.params)
        cfg = Config(params)

        if int(cfg.data_stream_chunk_rows) > 0 and self.reference is None \
                and self.used_indices is None \
                and not (isinstance(self.data, str)
                         and self.data.endswith((".npz", ".bin"))):
            # out-of-core path (docs/OutOfCore.md): the raw matrix is
            # consumed chunk-by-chunk and never materialized whole.
            # Validation sets (reference != None) and subsets stay on the
            # in-memory path — they are bounded by construction.
            return self._construct_streamed(cfg)

        data = self.data
        if isinstance(data, str):
            # file path; supports the "bin once" .npz cache
            if data.endswith(".npz") or data.endswith(".bin"):
                self._binned = BinnedDataset.load_binary(data)
                return self
            from .io import parser as parser_mod
            if cfg.two_round and self.used_indices is None \
                    and not parser_mod.sniff_libsvm(data):
                # two-round streaming load: never materializes the float64
                # matrix (dataset_loader.cpp >memory path). Subsets fall
                # through to the one-shot path — they are in-memory anyway.
                cat = (self.categorical_feature
                       if self.categorical_feature != "auto" else None)
                fn = (self.feature_name
                      if self.feature_name != "auto" else None)
                self._binned = BinnedDataset.from_file_two_round(
                    data, cfg, reference=ref_binned,
                    feature_names=fn, categorical_feature=cat)
                if self.label is not None:
                    self._binned.metadata.set_label(_to_1d(self.label))
                w = (self.weight if self.weight is not None
                     else parser_mod.load_weight_file(data))
                if w is not None:
                    self._binned.metadata.set_weight(_to_1d(w))
                g = (self.group if self.group is not None
                     else parser_mod.load_query_file(data))
                if g is not None:
                    self._binned.metadata.set_query(_to_1d(g))
                isc = (self.init_score if self.init_score is not None
                       else parser_mod.load_init_score_file(data))
                if isc is not None:
                    self._binned.metadata.set_init_score(np.asarray(isc))
                return self
            X, y, names = parser_mod.parse_file(data, has_header=cfg.header,
                                                label_column=cfg.label_column)
            if self.label is None:
                self.label = y
            if self.feature_name == "auto" and names:
                self.feature_name = names
            # sidecar metadata files (<data>.weight/.query/.init), the
            # Metadata file convention (src/io/metadata.cpp LoadFromFile)
            if self.weight is None:
                self.weight = parser_mod.load_weight_file(data)
            if self.group is None:
                self.group = parser_mod.load_query_file(data)
            if self.init_score is None:
                self.init_score = parser_mod.load_init_score_file(data)
            data = X

        pandas_cat_cols: List[str] = []
        if hasattr(data, "dtypes") and hasattr(data, "columns"):
            if self.pandas_categorical is None and self.reference is not None:
                # valid sets encode categories in the TRAINING set's order
                self.pandas_categorical = self.reference.pandas_categorical
            data, pandas_cat_cols, self.pandas_categorical = \
                _pandas_frame_to_array(data, self.pandas_categorical)

        from .io.dataset import _is_sparse
        if _is_sparse(data):
            # scipy sparse flows through un-densified: BinnedDataset bins it
            # column-wise and EFB packs exclusive features (io/bundle.py)
            X = data
        else:
            X = _to_2d_float(data)
        label = _to_1d(self.label)
        feature_names = None
        if isinstance(self.feature_name, (list, tuple)):
            feature_names = list(self.feature_name)
        elif hasattr(self.data, "columns"):
            feature_names = [str(c) for c in self.data.columns]

        cat = self.categorical_feature
        if cat == "auto" or cat is None:
            cat = None
        if pandas_cat_cols:
            # pandas category columns are categorical whether or not the
            # user listed them (auto-detection, _data_from_pandas)
            cat = list(cat) if cat else []
            cat.extend(c for c in pandas_cat_cols if c not in cat)
        if self.used_indices is not None:
            # subset construction (basic.py subset/used_indices path)
            X = X[self.used_indices] if not hasattr(X, "tocsr") \
                else X.tocsr()[self.used_indices]
            if label is not None:
                label = label[self.used_indices]

        weight = _to_1d(self.weight)
        init_score = _to_1d(self.init_score)
        group = self.group
        if self.used_indices is not None and weight is not None:
            weight = weight[self.used_indices]
        if self.used_indices is not None and init_score is not None:
            init_score = init_score[self.used_indices]

        self._binned = BinnedDataset.from_matrix(
            X, cfg, label=label, weight=weight, group=group,
            init_score=init_score, feature_names=feature_names,
            categorical_feature=cat, reference=ref_binned)
        self._raw_X = None if self.free_raw_data else X
        return self

    def _construct_streamed(self, cfg: Config) -> "Dataset":
        """Out-of-core construction through ``lightgbm_tpu.stream``.

        Picks a ChunkSource by input kind (.npy memory-map, delimited
        text, in-memory array) and two-round ingests it into a
        ``StreamedDataset`` whose uint8 chunks stay host-side until the
        trainer's pipeline sweeps them.
        """
        from .stream import ArraySource, CsvSource, NpyMmapSource
        from .stream.sampler import ingest
        R = int(cfg.data_stream_chunk_rows)
        data = self.data
        label = self.label
        weight, group, init_score = self.weight, self.group, self.init_score
        pandas_cat_cols: List[str] = []
        if isinstance(data, str):
            from .io import parser as parser_mod
            if data.endswith(".npy"):
                src = NpyMmapSource(data, label=label, chunk_rows=R)
            else:
                src = CsvSource(data, chunk_rows=R, has_header=cfg.header,
                                label_column=cfg.label_column)
            # sidecar metadata files, same convention as the in-memory
            # file path (src/io/metadata.cpp LoadFromFile)
            if weight is None:
                weight = parser_mod.load_weight_file(data)
            if group is None:
                group = parser_mod.load_query_file(data)
            if init_score is None:
                init_score = parser_mod.load_init_score_file(data)
        else:
            if hasattr(data, "dtypes") and hasattr(data, "columns"):
                data, pandas_cat_cols, self.pandas_categorical = \
                    _pandas_frame_to_array(data, self.pandas_categorical)
            from .io.dataset import _is_sparse
            if _is_sparse(data):
                raise LightGBMError(
                    "data_stream_chunk_rows does not support sparse "
                    "input; pass a dense array or stream from .npy/text")
            src = ArraySource(_to_2d_float(data), label=_to_1d(label),
                              chunk_rows=R)

        feature_names = None
        if isinstance(self.feature_name, (list, tuple)):
            feature_names = list(self.feature_name)
        elif hasattr(self.data, "columns"):
            feature_names = [str(c) for c in self.data.columns]
        cat = self.categorical_feature
        if cat == "auto" or cat is None:
            cat = None
        if pandas_cat_cols:
            cat = list(cat) if cat else []
            cat.extend(c for c in pandas_cat_cols if c not in cat)

        binned = ingest(src, cfg, feature_names=feature_names,
                        categorical_feature=cat)
        if label is not None and binned.metadata.label is None:
            binned.metadata.set_label(_to_1d(label))
        if weight is not None:
            binned.metadata.set_weight(_to_1d(weight))
        if group is not None:
            binned.metadata.set_query(_to_1d(group))
        if init_score is not None:
            binned.metadata.set_init_score(np.asarray(init_score))
        self._binned = binned
        self._raw_X = None
        return self

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, silent=False, params=None) -> "Dataset":
        """basic.py:843: validation set aligned to this Dataset's binning."""
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score, silent=silent,
                       params=params or self.params)

    def subset(self, used_indices, params=None) -> "Dataset":
        """Row subset sharing this dataset's raw data (basic.py:1100s)."""
        ds = Dataset(self.data, label=self.label, reference=self.reference,
                     weight=self.weight, group=self.group,
                     init_score=self.init_score,
                     feature_name=self.feature_name,
                     categorical_feature=self.categorical_feature,
                     params=params or self.params,
                     free_raw_data=self.free_raw_data)
        ds.used_indices = np.asarray(sorted(used_indices), dtype=np.int64)
        if self._binned is not None and self.reference is None:
            ds.reference = self
        return ds

    # ------------------------------------------------------------ fields
    def set_label(self, label) -> "Dataset":
        self.label = label
        if self._binned is not None:
            self._binned.metadata.set_label(_to_1d(label))
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = weight
        if self._binned is not None:
            self._binned.metadata.set_weight(_to_1d(weight))
        return self

    def set_group(self, group) -> "Dataset":
        self.group = group
        if self._binned is not None:
            self._binned.metadata.set_query(group)
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = init_score
        if self._binned is not None:
            self._binned.metadata.set_init_score(_to_1d(init_score))
        return self

    def set_reference(self, reference: "Dataset") -> "Dataset":
        check(self._binned is None,
              "Cannot set reference after dataset was constructed")
        self.reference = reference
        return self

    def set_field(self, field_name: str, data) -> "Dataset":
        if field_name == "label":
            return self.set_label(data)
        if field_name == "weight":
            return self.set_weight(data)
        if field_name == "group" or field_name == "query":
            return self.set_group(data)
        if field_name == "init_score":
            return self.set_init_score(data)
        raise LightGBMError("Unknown field name %s" % field_name)

    def get_field(self, field_name: str):
        m = self.construct()._binned.metadata
        if field_name == "label":
            return m.label
        if field_name == "weight":
            return m.weight
        if field_name in ("group", "query"):
            if m.query_boundaries is None:
                return None
            return np.diff(m.query_boundaries)
        if field_name == "init_score":
            return m.init_score
        raise LightGBMError("Unknown field name %s" % field_name)

    def get_label(self):
        return self.get_field("label")

    def get_weight(self):
        return self.get_field("weight")

    def get_group(self):
        return self.get_field("group")

    def get_init_score(self):
        return self.get_field("init_score")

    def num_data(self) -> int:
        return self.construct()._binned.num_data

    def num_feature(self) -> int:
        return self.construct()._binned.num_total_features

    def get_feature_name(self) -> List[str]:
        return list(self.construct()._binned.feature_names)

    def save_binary(self, filename: str) -> "Dataset":
        """basic.py:1312 / dataset.h:394 SaveBinaryFile."""
        self.construct()._binned.save_binary(filename)
        return self

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        check(self._binned is None,
              "Cannot set categorical feature after dataset was constructed")
        self.categorical_feature = categorical_feature
        return self

    def _set_predictor(self, predictor) -> "Dataset":
        self._predictor = predictor
        return self


class _InnerPredictor:
    """Continued-training predictor (basic.py:346): supplies init scores for
    a new training run from an existing model."""

    def __init__(self, booster: "Booster", num_iteration: int = -1):
        self.booster = booster
        self.num_iteration = num_iteration

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        return self.booster.predict(
            X, num_iteration=self.num_iteration
            if self.num_iteration > 0 else None, raw_score=True)

    def models(self):
        """The init model's HostTrees — capped exactly like predict_raw
        (explicit num_iteration, else best_iteration, else all), so the
        merged trees always match the init scores training was seeded
        from."""
        all_models = self.booster._impl.models
        eff = self.num_iteration
        if eff <= 0:
            eff = self.booster.best_iteration
        if eff <= 0:
            return all_models
        k = max(self.booster._impl.num_tree_per_iteration, 1)
        return all_models[:eff * k]


class Booster:
    """Booster in LightGBM (basic.py:1578)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None, silent=False):
        self.params = copy.deepcopy(params) if params else {}
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._valid_sets: List[Dataset] = []
        self.name_valid_sets: List[str] = []
        self._loaded = None      # parsed model dict when created from file/str
        self._train_set: Optional[Dataset] = None
        self._impl = None        # boosting driver (GBDT/DART/GOSS/RF)
        self._objective = None
        self.pandas_categorical = None

        if train_set is not None:
            check(isinstance(train_set, Dataset),
                  "Training data should be Dataset instance")
            self._init_from_train_set(train_set)
        elif model_file is not None:
            with open(model_file, "r") as fh:
                self._init_from_string(fh.read())
        elif model_str is not None:
            self._init_from_string(model_str)
        else:
            # params-only booster (used by set_network-style workflows)
            self.config = Config(self.params)

    # ------------------------------------------------------------ init paths
    def _init_from_train_set(self, train_set: Dataset) -> None:
        train_set.params = {**train_set.params, **self.params} \
            if train_set._binned is None else train_set.params
        train_set.construct()
        self._train_set = train_set
        self.pandas_categorical = train_set.pandas_categorical
        self.config = Config(self.params)
        binned = train_set._binned

        self._objective = create_objective(self.config)
        metric_names = list(self.config.metric)
        if not metric_names:
            default = default_metric_for_objective(self.config.objective)
            if default:
                metric_names = [default]
        self._metric_names = [m for m in metric_names if m and m != "None"]
        train_metrics = [m for m in
                         (create_metric(n, self.config)
                          for n in self._metric_names) if m]

        # continued training: seed scores with the init model's predictions
        if train_set._predictor is not None:
            raw = train_set._predictor.predict_raw(
                _to_2d_float(train_set.data)
                if not isinstance(train_set.data, str) else None)
            binned.metadata.set_init_score(
                np.asarray(raw, np.float64).reshape(-1, order="F"))

        self._impl = create_boosting(self.config, binned, self._objective,
                                     train_metrics)
        if train_set._predictor is not None:
            # the returned booster must be self-contained: prepend the init
            # model's trees (LGBM_BoosterMerge -> GBDT::MergeFrom,
            # gbdt.h:53); deep copies so later shrink/rollback cannot
            # mutate the init booster
            init_models = train_set._predictor.models()
            init_k = max(train_set._predictor.booster._impl
                         .num_tree_per_iteration, 1)
            check(init_k == max(self._impl.num_tree_per_iteration, 1),
                  "init model has %d trees per iteration but the new "
                  "parameters produce %d" % (
                      init_k, max(self._impl.num_tree_per_iteration, 1)))
            self._impl._models = copy.deepcopy(init_models)
            self._impl.num_init_iteration = (
                len(init_models) // max(self._impl.num_tree_per_iteration, 1))
            self._impl.iter_ = self._impl.num_init_iteration
            # a bare init_model carries trees only — warn loudly when the
            # boosting mode has sampling/weight state that a model file
            # cannot restore (checkpoints can: docs/Checkpointing.md)
            self._impl.warn_lossy_continuation()
        self.train_set_name = "training"

    def _init_from_string(self, model_str: str) -> None:
        # pandas_categorical sidecar (may be absent in reference-written
        # files that predate it or carried 'null')
        for line in model_str.splitlines()[::-1]:
            if line.startswith("pandas_categorical:"):
                import json as _json
                try:
                    self.pandas_categorical = _json.loads(
                        line[len("pandas_categorical:"):])
                except ValueError:
                    pass
                break
        parsed = model_text.parse_model_string(model_str)
        self._loaded = parsed
        params = dict(self.params)
        obj_tokens = parsed["objective"].split()
        if obj_tokens:
            params.setdefault("objective", obj_tokens[0])
            for tok in obj_tokens[1:]:
                if ":" in tok:
                    k, v = tok.split(":", 1)
                    params.setdefault(k, v)
                elif tok == "sqrt":
                    params.setdefault("reg_sqrt", True)
        if parsed["num_class"] > 1:
            params["num_class"] = parsed["num_class"]
        self.config = Config(params)
        self._objective = (create_objective(self.config)
                           if obj_tokens and obj_tokens[0] != "custom" else None)
        # build a predict-only driver
        from .boosting.gbdt import GBDT
        impl = GBDT(self.config, None, None, [])
        impl.objective = self._objective
        impl.num_class = parsed["num_class"]
        impl.num_tree_per_iteration = parsed["num_tree_per_iteration"]
        impl.models = parsed["trees"]
        impl.average_output = parsed["average_output"]
        self._impl = impl
        self._feature_names_loaded = parsed["feature_names"]
        self._feature_infos_loaded = parsed["feature_infos"]

    # ------------------------------------------------------------ training
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        check(isinstance(data, Dataset), "Validation data should be Dataset")
        data.construct()
        metrics = [m for m in (create_metric(n, self.config)
                               for n in self._metric_names) if m]
        self._impl.add_valid_data(data._binned, metrics)
        self._valid_sets.append(data)
        self.name_valid_sets.append(name)
        return self

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting round (basic.py:1843). Returns True if stopped."""
        if train_set is not None and train_set is not self._train_set:
            self.reset_training_data(train_set)
        if fobj is None:
            return self._impl.train_one_iter()
        # custom objective path (__boost, basic.py:1891)
        grad, hess = fobj(self.__pred_for_fobj(), self._train_set)
        return self.__boost(grad, hess)

    def __getstate__(self):
        """Pickle as the model text (reference basic.py __getstate__
        drops the native handle the same way): the unpickled booster
        predicts and serializes; training state (datasets, device arrays,
        compiled programs) intentionally does not survive."""
        state = {
            "params": self.params,
            "best_iteration": self.best_iteration,
            "best_score": dict(self.best_score),
            "pandas_categorical": self.pandas_categorical,
            "model_str": (self.model_to_string(num_iteration=-1)
                          if self._impl is not None and self._impl.models
                          else None),
        }
        return state

    def __setstate__(self, state):
        self.__init__(params=state.get("params"),
                      model_str=state.get("model_str"))
        self.best_iteration = state.get("best_iteration", -1)
        self.best_score = state.get("best_score", {})
        if state.get("pandas_categorical") is not None:
            self.pandas_categorical = state["pandas_categorical"]

    def __pred_for_fobj(self) -> np.ndarray:
        scores = np.array(self._impl.scores)
        return scores[:, 0] if scores.shape[1] == 1 else scores.reshape(-1, order="F")

    def __boost(self, grad, hess) -> bool:
        grad = np.asarray(grad, np.float32)
        hess = np.asarray(hess, np.float32)
        return self._impl.train_one_iter(grad, hess)

    def rollback_one_iter(self) -> "Booster":
        self._impl.rollback_one_iter()
        return self

    @property
    def current_iteration(self):
        # LightGBM exposes this as a method; keep method semantics
        return self._impl.current_iteration

    def num_trees(self) -> int:
        return len(self._impl.models)

    def num_model_per_iteration(self) -> int:
        return self._impl.num_tree_per_iteration

    def num_feature(self) -> int:
        if self._train_set is not None:
            return self._train_set.num_feature()
        return len(self._feature_names_loaded)

    # ------------------------------------------------------------ evaluation
    def eval_train(self, feval=None):
        return self.__inner_eval(self.train_set_name, 0, feval)

    def eval_valid(self, feval=None):
        out = []
        for i in range(len(self._valid_sets)):
            out.extend(self.__inner_eval(self.name_valid_sets[i], i + 1, feval))
        return out

    def eval(self, data: Dataset, name: str, feval=None):
        if data is self._train_set:
            return self.eval_train(feval)
        for i, vs in enumerate(self._valid_sets):
            if data is vs:
                return self.__inner_eval(name, i + 1, feval)
        raise LightGBMError("Data should be a validation set added via add_valid")

    def __inner_eval(self, name: str, data_idx: int, feval=None):
        out = [(name, m, v, bb)
               for _, m, v, bb in self._impl.get_eval_at(data_idx)]
        if feval is not None:
            if data_idx == 0:
                ds = self._train_set
                scores = np.array(self._impl.scores)
            else:
                ds = self._valid_sets[data_idx - 1]
                scores = np.array(
                    self._impl._valid_pred_cache[data_idx - 1]["scores"])
            preds = scores[:, 0] if scores.shape[1] == 1 \
                else scores.reshape(-1, order="F")
            res = feval(preds, ds)
            if isinstance(res, list):
                for r in res:
                    out.append((name, r[0], r[1], r[2]))
            elif res is not None:
                out.append((name, res[0], res[1], res[2]))
        return out

    # ------------------------------------------------------------ prediction
    def predict(self, data, num_iteration: Optional[int] = None,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs) -> np.ndarray:
        if isinstance(data, Dataset):
            raise LightGBMError("Cannot use Dataset instance for prediction, "
                                "please use raw data instead")
        if hasattr(data, "dtypes") and hasattr(data, "columns") \
                and self.pandas_categorical is not None:
            data = _pandas_frame_to_array(data, self.pandas_categorical)[0]
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration > 0 \
                else None
        if hasattr(data, "toarray"):
            # sparse input: densify in bounded row blocks (~128 MB of f64),
            # never the whole matrix (PredictForCSR streams rows the same
            # way; an Allstate-shaped 13.2M x 4228 CSR would otherwise
            # materialize ~450 GB). Each block is one device call.
            block = max(256, (1 << 24) // max(int(data.shape[1]), 1))
            if data.shape[0] > block:
                mat = data.tocsr()
                outs = [self.predict(
                            mat[lo:lo + block].toarray(),
                            num_iteration=num_iteration,
                            raw_score=raw_score, pred_leaf=pred_leaf,
                            pred_contrib=pred_contrib, **kwargs)
                        for lo in range(0, mat.shape[0], block)]
                return np.concatenate(outs, axis=0)
        X = _to_2d_float(data)
        if pred_contrib:
            return self._impl_predict_contrib(X, num_iteration)
        return self._impl.predict(
            X, num_iteration=num_iteration, raw_score=raw_score,
            pred_leaf=pred_leaf,
            pred_early_stop=kwargs.get("pred_early_stop", False),
            pred_early_stop_freq=kwargs.get("pred_early_stop_freq", 10),
            pred_early_stop_margin=kwargs.get("pred_early_stop_margin", 10.0))

    def _impl_predict_contrib(self, X, num_iteration):
        from .core.shap import predict_contrib
        return predict_contrib(self._impl, X, num_iteration)

    def reset_training_data(self, train_set: Dataset) -> "Booster":
        """Swap the training dataset under the current model
        (LGBM_BoosterResetTrainingData -> GBDT::ResetTrainingData,
        gbdt.cpp:622-660): bin mappers must align with the old data, the
        model is kept, and train scores are recomputed by replaying every
        tree on the new binned features."""
        check(self._impl is not None, "no training state to reset")
        check(isinstance(train_set, Dataset),
              "Training data should be Dataset instance")
        old_binned = self._train_set.construct()._binned \
            if self._train_set is not None else None
        if train_set._binned is None:
            if train_set.reference is None and self._train_set is not None:
                train_set.reference = self._train_set
            train_set.params = {**(train_set.params or {}), **self.params}
        train_set.construct()
        if old_binned is not None:
            # CheckAlign (gbdt.cpp:624-626): identical bin mappers or fatal
            check(train_set._binned.get_feature_infos()
                  == old_binned.get_feature_infos(),
                  "Cannot reset training data: new training data has "
                  "different bin mappers")

        import jax.numpy as jnp
        old = self._impl
        models = copy.deepcopy(old.models)   # materializes pending work
        new_impl = create_boosting(
            self.config, train_set._binned, create_objective(self.config),
            [m for m in (create_metric(n, self.config)
                         for n in getattr(self, "_metric_names", [])) if m])
        new_impl._models = models
        new_impl.iter_ = old.iter_
        new_impl.num_init_iteration = getattr(old, "num_init_iteration", 0)
        new_impl.boost_from_average_done = True
        offs = getattr(old, "init_score_offsets", None)
        if offs is not None and np.any(np.asarray(offs) != 0):
            new_impl.scores = new_impl.scores + jnp.asarray(
                np.asarray(offs, np.float32))[None, :]
            new_impl.init_score_offsets = np.asarray(offs, np.float32)
        k = max(new_impl.num_tree_per_iteration, 1)
        scores = new_impl.scores
        for i, ht in enumerate(models):
            leaf = new_impl._replay_leaves_binned(ht, new_impl.xb)
            scores = scores.at[:, i % k].add(
                jnp.asarray(ht.leaf_value.astype(np.float32))[leaf])
        new_impl.scores = scores
        # validation sets survive the swap (the reference keeps its
        # valid_score_updaters; add_valid_data replays the model on each)
        for vset, vname in zip(self._valid_sets, self.name_valid_sets):
            mets = [m for m in (create_metric(n, self.config)
                                for n in getattr(self, "_metric_names", []))
                    if m]
            new_impl.add_valid_data(vset.construct()._binned, mets)
        self._impl = new_impl
        self._objective = new_impl.objective
        self._train_set = train_set
        return self

    def as_serving_bundle(self, model_id: str = "default"):
        """Package this booster for lightgbm_tpu.serving: trees stacked
        ``[iterations, trees_per_iteration, ...]`` on device, immutable.
        Register on a ServingEngine with
        ``engine.registry.register(booster.as_serving_bundle(id))``."""
        from .serving.registry import ModelBundle
        check(self._impl is not None and self._impl.models,
              "Cannot serve: no trained model")
        return ModelBundle.from_booster(model_id, self)

    def refit(self, data, label, decay_rate: float = 0.9, weight=None,
              group=None, **kwargs) -> "Booster":
        """Refit existing tree structures to new data (RefitTree,
        gbdt.cpp:263-286 + FitByExistingTree, serial_tree_learner.cpp:235-265):
        every split is kept, leaf outputs are re-estimated from the new data's
        gradients and blended with the old outputs by ``decay_rate``.

        Dense inputs take the device path (fleet/refit.py: one flat-forest
        traversal + one scan over iterations, compiled once and reused;
        ``refit_device=false`` forces this host loop). Sparse inputs stay
        on the host's streamed-block path — it never densifies."""
        import jax
        import jax.numpy as jnp
        from .core import tree as tree_mod
        from .io.dataset import Metadata

        check(self._impl is not None and self._impl.models,
              "Cannot refit: no trained model")
        check(self._objective is not None,
              "Cannot refit a model trained with a custom objective")
        sparse_in = hasattr(data, "toarray") and not hasattr(data, "dtypes")
        if not sparse_in and self.config.refit_device:
            from .fleet.refit import refit_booster
            return refit_booster(self, data, label, decay_rate=decay_rate,
                                 weight=weight, group=group)
        if sparse_in:
            data = data.tocsr()
            n = int(data.shape[0])
        else:
            X = _to_2d_float(data)
            n = X.shape[0]
        k = self._impl.num_tree_per_iteration
        models = self._impl.models

        md = Metadata(n)
        md.set_label(_to_1d(label))
        if weight is not None:
            md.set_weight(_to_1d(weight))
        if group is not None:
            md.set_query(np.asarray(group, np.int64))
        obj = copy.deepcopy(self._objective)
        obj.init(md, n)
        cfg = self.config
        l1, l2, mds = cfg.lambda_l1, cfg.lambda_l2, cfg.max_delta_step

        if sparse_in:
            # bounded-block leaf routing: never materialize the full dense
            # matrix (the sparse-predict contract; PredictForCSR streams)
            blk = max(256, (1 << 24) // max(int(data.shape[1]), 1))

            def leaves_of(pt):
                return np.concatenate([
                    np.asarray(tree_mod.predict_tree_leaves_raw(
                        pt, jnp.asarray(data[lo:lo + blk].toarray(),
                                        jnp.float32)))
                    for lo in range(0, n, blk)])
        else:
            xj = jnp.asarray(X, jnp.float32)

            def leaves_of(pt):
                return np.asarray(tree_mod.predict_tree_leaves_raw(pt, xj))
        scores = np.zeros((n, k), np.float32)
        g = h = None
        new_trees = []
        for i, ht in enumerate(models):
            c = i % k
            if c == 0:  # gradients refresh once per boosting iteration
                if k == 1:
                    gj, hj = obj.get_gradients(jnp.asarray(scores[:, 0]))
                    g, h = np.asarray(gj)[:, None], np.asarray(hj)[:, None]
                else:
                    gj, hj = obj.get_gradients(jnp.asarray(scores))
                    g, h = np.asarray(gj), np.asarray(hj)
            nl = ht.num_leaves_actual
            pt = jax.tree.map(jnp.asarray,
                              ht.predict_table(max(len(ht.split_leaf), 1),
                                               max(len(ht.leaf_value), 1)))
            leaves = leaves_of(pt)
            sg = np.bincount(leaves, weights=g[:, c].astype(np.float64),
                             minlength=nl)
            sh = np.bincount(leaves, weights=h[:, c].astype(np.float64),
                             minlength=nl)
            # CalculateSplittedLeafOutput (feature_histogram.hpp:454-462)
            out = -np.sign(sg) * np.maximum(np.abs(sg) - l1, 0.0) \
                / (sh + l2 + 1e-15)
            if mds > 0:
                out = np.clip(out, -mds, mds)
            out *= getattr(ht, "shrinkage", 1.0)
            nh = copy.deepcopy(ht)
            old = ht.leaf_value[:nl].astype(np.float64)
            nh.leaf_value = ht.leaf_value.copy()
            nh.leaf_value[:nl] = decay_rate * old + (1.0 - decay_rate) * out
            scores[:, c] += nh.leaf_value[leaves].astype(np.float32)
            new_trees.append(nh)

        refitted = Booster(model_str=self.model_to_string())
        refitted._impl.models = new_trees
        return refitted

    # ------------------------------------------------------------ model IO
    def _feature_names(self) -> List[str]:
        if self._train_set is not None:
            return self._train_set.get_feature_name()
        return list(self._feature_names_loaded)

    def _feature_infos(self) -> List[str]:
        if self._train_set is not None:
            return self._train_set.construct()._binned.get_feature_infos()
        return list(self._feature_infos_loaded)

    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0) -> str:
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration > 0 \
                else -1
        out = model_text.model_to_string(
            self._impl, self._feature_names(), self._feature_infos(),
            num_iteration=num_iteration, start_iteration=start_iteration,
            parameters=param_dict_to_str(self.params))
        # the reference's python package appends this sidecar line so raw
        # pandas category values survive save/load (basic.py
        # _dump_pandas_categorical); keep the format identical for interop
        import json as _json

        def _cat_value(v):
            # numeric category values must stay numeric through JSON or
            # set_categories() at load time matches nothing
            if isinstance(v, np.integer):
                return int(v)
            if isinstance(v, np.floating):
                return float(v)
            return str(v)

        out += "\npandas_categorical:%s\n" % _json.dumps(
            self.pandas_categorical, default=_cat_value)
        return out

    def save_model(self, filename: str, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> "Booster":
        with open(filename, "w") as fh:
            fh.write(self.model_to_string(num_iteration, start_iteration))
        return self

    def dump_model(self, num_iteration: Optional[int] = None) -> Dict:
        import json
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration > 0 \
                else -1
        return json.loads(model_text.model_to_json(
            self._impl, self._feature_names(), self._feature_infos(),
            num_iteration=num_iteration))

    # ------------------------------------------------------------ insight
    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        imp = self._impl.feature_importance(importance_type, iteration)
        if importance_type == "split":
            return imp.astype(np.int64)
        return imp

    def feature_name(self) -> List[str]:
        return self._feature_names()

    def get_split_value_histogram(self, feature, bins=None,
                                  xgboost_style: bool = False):
        """Histogram of threshold values this feature was split on
        (basic.py get_split_value_histogram; reference test
        test_engine.py:1247)."""
        if isinstance(feature, str):
            names = self._feature_names()
            check(feature in names, "Feature %s not found" % feature)
            feature = names.index(feature)
        values = []
        for ht in self._impl.models:
            nn = ht.num_leaves_actual - 1
            for t in range(max(nn, 0)):
                if (ht.split_feature[t] == feature
                        and not ht.is_categorical[t]):
                    values.append(float(ht.threshold[t]))
        values = np.asarray(values, np.float64)
        if bins is None:
            bins = max(min(len(values), 255), 1)
        hist, edges = np.histogram(values, bins=bins)
        if xgboost_style:
            rows = [(edges[i + 1], int(hist[i])) for i in range(len(hist))
                    if hist[i] > 0]
            return np.asarray(rows, np.float64).reshape(-1, 2)
        return hist, edges

    def get_leaf_output(self, tree_id: int, leaf_id: int) -> float:
        """Value of a single leaf (reference basic.py:2329 /
        LGBM_BoosterGetLeafValue)."""
        models = self._impl.models
        if not 0 <= tree_id < len(models):
            raise LightGBMError("tree_id %d out of range" % tree_id)
        t = models[tree_id]
        if not 0 <= leaf_id < int(t.num_leaves_actual):
            raise LightGBMError("leaf_id %d out of range" % leaf_id)
        return float(t.leaf_value[leaf_id])

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        """basic.py reset_parameter → learning-rate etc. mid-training."""
        self.params.update(params)
        self.config.set(params)
        if self._impl is not None:
            self._impl.shrinkage_rate = self.config.learning_rate
        return self

    def set_network(self, machines, local_listen_port=12400,
                    listen_time_out=120, num_machines=1) -> "Booster":
        """Multi-host topology configuration (basic.py:1734). On TPU the
        actual collectives ride the ICI/DCN mesh via jax.distributed."""
        from .parallel import network
        network.init(machines=machines, local_listen_port=local_listen_port,
                     time_out=listen_time_out, num_machines=num_machines)
        return self

    def free_network(self) -> "Booster":
        from .parallel import network
        network.free()
        return self
