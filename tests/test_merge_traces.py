"""tools/merge_events.py span-tree reconstruction across process hops.

Contracts pinned here:
- a trace that hops processes (``x-lgbm-trace`` header → ``ctx``) merges
  back into ONE tree: the downstream root is a child of the upstream
  span that minted the header, roots/children resolve across streams;
- legacy per-phase Tracer records (``event: "span"`` but no ``trace``
  field) are invisible to the reconstruction — the two span vocabularies
  share an event name but never mix;
- spans whose parent was never merged in land in ``orphans`` — listed,
  tolerated, never an error (a partial post-mortem beats none);
- tail-based sampling replays deterministically: two tracers with the
  same seed keep exactly the same trace ids (the property the reqtrace
  module docstring pins on this file);
- the CLI round-trip: ``--span-trees`` writes the same trees the library
  call returns.
"""
import json
import os
import sys

from lightgbm_tpu.obs.reqtrace import RequestTracer, format_trace_header
from lightgbm_tpu.obs.registry import MetricsRegistry
from lightgbm_tpu.obs.trace import EventStream

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(HERE), "tools"))

import merge_events   # noqa: E402  (tools/ is not a package)


def _tracer(path, process, **kw):
    events = EventStream(str(path), static_fields={"process": process})
    kw.setdefault("sample", 1.0)
    return RequestTracer(events=events, registry=MetricsRegistry(), **kw), \
        events


def _two_hop_streams(tmp_path):
    """Frontend (process 0) hands the trace to a backend (process 1) via
    the header; each writes its own event file.  Returns (paths, ids)."""
    t0, ev0 = _tracer(tmp_path / "events.0.jsonl", 0)
    t1, ev1 = _tracer(tmp_path / "events.1.jsonl", 1)
    front = t0.start_trace("request", model="m")
    hop = front.child("fleet_hop", target="replica-b")
    header = format_trace_header(hop)
    back = t1.start_trace("request", ctx=header)
    back.child("predict").end()
    back.finish("ok")
    hop.end()
    front.finish("ok")
    ev0.close()
    ev1.close()
    return ([str(tmp_path / "events.0.jsonl"),
             str(tmp_path / "events.1.jsonl")],
            {"trace": front.trace_id, "front": front.span_id,
             "hop": hop.span_id, "back": back.span_id})


def test_cross_process_trace_reassembles_into_one_tree(tmp_path):
    paths, ids = _two_hop_streams(tmp_path)
    merged = list(merge_events.merge(paths))
    assert all("stream" in r for r in merged)
    trees = merge_events.build_span_trees(merged)
    assert set(trees) == {ids["trace"]}
    tree = trees[ids["trace"]]
    assert len(tree["spans"]) == 4 and tree["orphans"] == []
    assert [r["span_id"] for r in tree["roots"]] == [ids["front"]]
    by_id = {s["span_id"]: s for s in tree["spans"]}
    # the downstream root is a CHILD of the upstream hop span
    assert ids["back"] in by_id[ids["hop"]]["children"]
    assert by_id[ids["back"]]["parent"] == ids["hop"]
    # streams still attribute each side of the hop
    assert by_id[ids["front"]]["process"] == 0
    assert by_id[ids["back"]]["process"] == 1


def test_unmerged_upstream_becomes_orphan_not_error(tmp_path):
    paths, ids = _two_hop_streams(tmp_path)
    trees = merge_events.build_span_trees(
        merge_events.merge(paths[1:]))      # backend stream only
    tree = trees[ids["trace"]]
    assert [s["span_id"] for s in tree["orphans"]] == [ids["back"]]
    assert tree["roots"] == []              # true root lives upstream
    assert len(tree["spans"]) == 2          # still a usable partial view


def test_legacy_phase_spans_invisible_to_trees(tmp_path):
    path = tmp_path / "events.jsonl"
    ev = EventStream(str(path))
    # legacy Tracer vocabulary: same event name, "span" key, no "trace"
    ev.write("span", span="train", iteration=3, duration_s=0.5)
    ev.write("metrics", value=1)
    t, _ = _tracer(path, 0)
    root = t.start_trace("train_iter")
    root.finish("ok")
    ev.close()
    trees = merge_events.build_span_trees(merge_events.merge([str(path)]))
    assert set(trees) == {root.trace_id}
    assert len(trees[root.trace_id]["spans"]) == 1


def test_sampling_replays_deterministically(tmp_path):
    ids = ["%016x" % (i * 2654435761) for i in range(300)]

    def kept_set(path, seed):
        t, ev = _tracer(path, 0, sample=0.3, seed=seed)
        for tid in ids:
            t.start_trace("request", ctx=(tid, None)).finish("ok")
        ev.close()
        with open(path) as fh:
            return {json.loads(line)["trace"] for line in fh}

    a = kept_set(tmp_path / "a.jsonl", seed=42)
    b = kept_set(tmp_path / "b.jsonl", seed=42)
    assert a == b and 0 < len(a) < len(ids)     # replica processes agree
    c = kept_set(tmp_path / "c.jsonl", seed=43)
    assert a != c                               # policy is seed-keyed


def test_cli_span_trees_roundtrip(tmp_path, monkeypatch, capsys):
    paths, ids = _two_hop_streams(tmp_path)
    out = tmp_path / "timeline.jsonl"
    trees_path = tmp_path / "trees.json"
    monkeypatch.setattr(sys, "argv",
                        ["merge_events.py"] + paths +
                        ["--out", str(out), "--span-trees", str(trees_path)])
    assert merge_events.main() == 0
    with open(trees_path) as fh:
        trees = json.load(fh)
    assert set(trees) == {ids["trace"]}
    assert len(trees[ids["trace"]]["spans"]) == 4
    with open(out) as fh:
        merged = [json.loads(line) for line in fh]
    assert merge_events.build_span_trees(merged).keys() == trees.keys()
