"""lightgbm_tpu.checkpoint: preemption-safe snapshots, deterministic resume.

The contract under test is the headline guarantee from docs/Checkpointing.md:
a run killed at iteration k and resumed from its checkpoint directory
produces a model file BYTE-identical to the uninterrupted run (same
checkpoint callback attached to both — the callback pins the per-iteration
training path, see the determinism note in checkpoint/callback.py), plus the
failure-containment half: corrupt/truncated snapshots are detected by the
manifest checksums and resume falls back to the newest valid one.
"""
import glob
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import callback, engine
from lightgbm_tpu.checkpoint import CheckpointManager, load_latest
from lightgbm_tpu.log import LightGBMError, Log

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _data(n=200, f=6, seed=7):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    y = (X[:, 0] + X[:, 1] * 2 + 0.3 * r.randn(n) > 0).astype(np.float64)
    return X, y


_BASE = dict(objective="binary", num_leaves=5, learning_rate=0.2,
             min_data_in_leaf=5, verbosity=0)


def _train(params, ckpt_dir, num_rounds, resume=False, valid=False,
           early_stop=False, X=None, y=None):
    if X is None:
        X, y = _data()
    ds = lgb.Dataset(X, label=y, params=dict(params))
    valid_sets = None
    if valid:
        Xv, yv = _data(n=100, seed=8)
        valid_sets = [ds.create_valid(Xv, label=yv)]
    cbs = [callback.checkpoint(ckpt_dir, period=1)]
    if early_stop:
        cbs.append(callback.early_stopping(3, verbose=False))
    ev = {}
    bst = engine.train(dict(params), ds, num_boost_round=num_rounds,
                       valid_sets=valid_sets, callbacks=cbs, evals_result=ev,
                       resume_from=(ckpt_dir if resume else None),
                       verbose_eval=False)
    return bst, ev


def _resume_matches_golden(tmp_path, params, valid=False, early_stop=False,
                           total=8, kill_at=3):
    golden, ev_g = _train(params, str(tmp_path / "g"), total, valid=valid,
                          early_stop=early_stop)
    # "killed" run: only kill_at rounds reach the checkpoint directory
    _train(params, str(tmp_path / "i"), kill_at, valid=valid,
           early_stop=early_stop)
    resumed, ev_r = _train(params, str(tmp_path / "i"), total, resume=True,
                           valid=valid, early_stop=early_stop)
    assert golden.model_to_string() == resumed.model_to_string()
    assert ev_g == ev_r
    assert golden.best_iteration == resumed.best_iteration


# --------------------------------------------------------- byte-identity
def test_resume_byte_identical_gbdt(tmp_path):
    # bagging + feature_fraction: both RNG streams must survive the snapshot
    _resume_matches_golden(tmp_path, dict(
        _BASE, bagging_fraction=0.7, bagging_freq=1, feature_fraction=0.8))


def test_resume_byte_identical_dart(tmp_path):
    # DART adds drop-RNG + mutable per-tree weights to the state surface
    _resume_matches_golden(tmp_path, dict(_BASE, boosting="dart",
                                          drop_rate=0.3))


def test_resume_byte_identical_goss(tmp_path):
    _resume_matches_golden(tmp_path, dict(_BASE, boosting="goss"))


def test_resume_restores_eval_history_and_early_stopping(tmp_path):
    _resume_matches_golden(tmp_path, dict(
        _BASE, bagging_fraction=0.7, bagging_freq=1), valid=True,
        early_stop=True)


def test_resume_from_empty_dir_is_fresh_start(tmp_path):
    bst, _ = _train(_BASE, str(tmp_path / "fresh"), 3, resume=True)
    assert bst.current_iteration == 3


def test_resume_past_target_trains_nothing(tmp_path):
    # num_boost_round is the TOTAL target: a checkpoint already at (or past)
    # it must resume to the same model without another boosting step
    _train(_BASE, str(tmp_path / "c"), 4)
    bst, _ = _train(_BASE, str(tmp_path / "c"), 4, resume=True)
    assert bst.current_iteration == 4


# ------------------------------------------------------ kill-and-resume
@pytest.mark.slow
def test_sigterm_kill_and_resume_byte_identical(tmp_path):
    """The full preemption story in real processes: the victim dies with
    the signal's exit status (143 / -SIGTERM) AFTER the callback snapshots
    at the iteration boundary; resume completes the run byte-identically."""
    worker = os.path.join(REPO, "tests", "ckpt_worker.py")
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}

    def run(ckpt_dir, mode):
        return subprocess.run([sys.executable, worker, ckpt_dir, mode],
                              env=env, cwd=REPO, capture_output=True,
                              text=True, timeout=540)

    g_dir, i_dir = str(tmp_path / "g"), str(tmp_path / "i")
    p = run(g_dir, "golden")
    assert p.returncode == 0, p.stderr[-2000:]
    p = run(i_dir, "victim")
    assert p.returncode in (-15, 143), (p.returncode, p.stderr[-2000:])
    assert glob.glob(os.path.join(i_dir, "snap_*.model.txt"))
    p = run(i_dir, "resume")
    assert p.returncode == 0, p.stderr[-2000:]
    with open(os.path.join(g_dir, "final_model.txt")) as f:
        golden = f.read()
    with open(os.path.join(i_dir, "final_model.txt")) as f:
        resumed = f.read()
    assert golden == resumed


# ------------------------------------------------- corruption / fallback
def _corrupt(path, truncate=False):
    if truncate:
        with open(path, "r+b") as f:
            f.truncate(10)
    else:
        with open(path, "r+b") as f:
            f.seek(0)
            f.write(b"\x00" * 64)


def test_corrupt_newest_snapshot_falls_back(tmp_path):
    d = str(tmp_path)
    _train(_BASE, d, 5)
    assert load_latest(d).iteration == 5
    _corrupt(sorted(glob.glob(os.path.join(d, "snap_*.state.npz")))[-1])
    assert load_latest(d).iteration == 4
    # a truncated write (the classic preemption artifact) is also caught
    _corrupt(sorted(glob.glob(os.path.join(d, "snap_*.meta.json")))[-2],
             truncate=True)
    assert load_latest(d).iteration == 3


def test_corrupt_fallback_still_resumes_byte_identical(tmp_path):
    golden, _ = _train(_BASE, str(tmp_path / "g"), 8)
    d = str(tmp_path / "i")
    _train(_BASE, d, 4)
    _corrupt(sorted(glob.glob(os.path.join(d, "snap_*.state.npz")))[-1])
    resumed, _ = _train(_BASE, d, 8, resume=True)   # falls back to snap 3
    assert golden.model_to_string() == resumed.model_to_string()


def test_all_snapshots_corrupt_raises(tmp_path):
    d = str(tmp_path)
    _train(dict(_BASE, checkpoint_keep=2), d, 2)
    for p in glob.glob(os.path.join(d, "snap_*.state.npz")):
        _corrupt(p)
    with pytest.raises(LightGBMError, match="none passed verification"):
        load_latest(d)


def test_manifest_bak_fallback(tmp_path):
    d = str(tmp_path)
    _train(_BASE, d, 3)
    os.remove(os.path.join(d, "MANIFEST.json"))
    assert load_latest(d).iteration >= 2   # .bak holds the previous publish


def test_retention_keeps_last_n(tmp_path):
    d = str(tmp_path)
    _train(dict(_BASE, checkpoint_keep=2), d, 6)
    ids = sorted(int(os.path.basename(p)[5:13]) for p in
                 glob.glob(os.path.join(d, "snap_*.state.npz")))
    assert ids[-2:] == [5, 6]
    assert len(ids) <= 3   # last 2 + at most one best-flagged survivor


def test_dataset_fingerprint_mismatch_raises(tmp_path):
    d = str(tmp_path)
    _train(_BASE, d, 3)
    X, y = _data(seed=99)   # different data, same shapes
    with pytest.raises(LightGBMError, match="fingerprint"):
        _train(_BASE, d, 6, resume=True, X=X, y=y)


# ------------------------------------------------------------- serving
def test_registry_replace_and_hot_roll(tmp_path):
    from lightgbm_tpu.serving import ModelRegistry, ServingEngine
    d = str(tmp_path)
    X, y = _data()
    _train(_BASE, d, 3)
    reg = ModelRegistry()
    eng = ServingEngine(registry=reg)
    w = reg.watch_dir("m", d)
    assert w.poll() is True          # first poll registers snapshot 3
    assert w.poll() is False         # nothing newer
    assert reg.generation("m") == 1
    p1 = eng.predict("m", X[:8])
    assert eng.cache_size() > 0
    # bare re-registration of a live id must be refused...
    with pytest.raises(LightGBMError, match="replace=True"):
        reg.load_file("m", CheckpointManager(d).latest_model()[1])
    # ...while a newer snapshot hot-rolls atomically: generation bump,
    # compiled-predictor purge, and predictions from the new forest
    _train(_BASE, d, 8, resume=True)
    assert w.poll() is True
    assert reg.generation("m") == 2
    assert eng.cache_size() == 0     # replace listener purged the old entries
    p2 = eng.predict("m", X[:8])
    assert not np.allclose(p1, p2)


# ------------------------------------------------- config / API surface
def test_config_validation():
    with pytest.raises(LightGBMError):
        lgb.Config({"objective": "binary", "checkpoint_period": 0})
    with pytest.raises(LightGBMError):
        lgb.Config({"objective": "binary", "checkpoint_keep": 0})
    cfg = lgb.Config({"objective": "binary", "checkpoint_dir": "/tmp/x",
                      "checkpoint_freq": 5})
    assert cfg.checkpoint_period == 5


def test_checkpoint_dir_param_auto_attaches_callback(tmp_path):
    d = str(tmp_path / "auto")
    X, y = _data()
    ds = lgb.Dataset(X, label=y, params=dict(_BASE))
    engine.train(dict(_BASE, checkpoint_dir=d, checkpoint_period=2), ds,
                 num_boost_round=4, verbose_eval=False)
    assert load_latest(d).iteration == 4


def test_lossy_init_model_continuation_warns(tmp_path):
    params = dict(_BASE, bagging_fraction=0.7, bagging_freq=1)
    bst, _ = _train(params, str(tmp_path), 3)
    msgs = []
    Log.reset_callback(lambda m: msgs.append(m))
    try:
        X, y = _data()
        ds = lgb.Dataset(X, label=y, params=dict(params))
        engine.train(dict(params), ds, num_boost_round=2, init_model=bst,
                     verbose_eval=False)
    finally:
        Log.reset_callback(None)
    assert any("resume_from" in m for m in msgs)


@pytest.mark.slow
def test_phase_probe_reports_checkpoint_cost(tmp_path):
    from lightgbm_tpu.profiling import phase_probe
    bst, _ = _train(_BASE, str(tmp_path), 3)
    ph = phase_probe(bst._impl)
    assert ph["checkpoint_save_s"] > 0
    assert ph["checkpoint_restore_s"] > 0
