"""A REAL multi-process run: two OS processes, one CPU device each,
glued by jax.distributed through parallel/network.py — the executable
form of the reference's parallel-learning walkthrough
(docs/Parallel-Learning-Guide.rst:38-110). Asserts the 2-process
data-parallel model matches single-process training.
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_data_parallel_matches_single(tmp_path):
    port = _free_port()
    out = str(tmp_path / "rank0.json")
    env_base = {**os.environ,
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "",            # exactly one device per process
                "MP_TEST_PORT": str(port),
                "MP_TEST_OUT": out,
                "PYTHONPATH": REPO}
    procs = []
    for rank in range(2):
        env = dict(env_base, LIGHTGBM_TPU_RANK=str(rank))
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "mp_worker.py")],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    outs = []
    for p in procs:
        try:
            so, se = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process worker timed out")
        outs.append((p.returncode, so, se))
    for rc, so, se in outs:
        assert rc == 0, (so[-500:], se[-2000:])
    with open(out) as f:
        pred_mp = np.asarray(json.load(f)["pred"])

    # single-process reference on the identical data/config (serial)
    import jax
    r = np.random.RandomState(0)
    X = r.randn(4096, 8).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.boosting import create_boosting
    cfg = Config({"objective": "binary", "num_leaves": 15,
                  "verbosity": -1})
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    b = create_boosting(cfg, ds, create_objective(cfg), [])
    for _ in range(5):
        b.train_one_iter()
    pred_sp = np.asarray(b.predict(X[:256], raw_score=True), np.float64)
    np.testing.assert_allclose(pred_mp, pred_sp, rtol=2e-4, atol=2e-4)
