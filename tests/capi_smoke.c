/* End-to-end C ABI smoke: dataset from a dense matrix, train, evaluate,
 * predict, save/load roundtrip — a C host driving the TPU runtime through
 * lib_lightgbm.so. Compiled and run by tests/test_capi.py. */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "../native/include/lightgbm_tpu_c_api.h"

#define CHECK(call)                                                   \
  do {                                                                \
    if ((call) != 0) {                                                \
      fprintf(stderr, "FAIL %s: %s\n", #call, LGBM_GetLastError());   \
      return 1;                                                       \
    }                                                                 \
  } while (0)

int main(void) {
  const int n = 2000, f = 5;
  double* X = (double*)malloc(sizeof(double) * n * f);
  float* y = (float*)malloc(sizeof(float) * n);
  unsigned s = 42;
  for (int i = 0; i < n; ++i) {
    double acc = 0;
    for (int j = 0; j < f; ++j) {
      s = s * 1664525u + 1013904223u;
      double v = (double)(s >> 8) / (double)(1u << 24) - 0.5;
      X[i * f + j] = v;
      if (j < 2) acc += v;
    }
    y[i] = acc > 0 ? 1.0f : 0.0f;
  }

  DatasetHandle ds = NULL;
  CHECK(LGBM_DatasetCreateFromMat(X, C_API_DTYPE_FLOAT64, n, f, 1,
                                  "max_bin=255", NULL, &ds));
  CHECK(LGBM_DatasetSetField(ds, "label", y, n, C_API_DTYPE_FLOAT32));

  int32_t nd = 0, nf = 0;
  CHECK(LGBM_DatasetGetNumData(ds, &nd));
  CHECK(LGBM_DatasetGetNumFeature(ds, &nf));
  if (nd != n || nf != f) {
    fprintf(stderr, "FAIL dims: %d %d\n", nd, nf);
    return 1;
  }

  BoosterHandle bst = NULL;
  CHECK(LGBM_BoosterCreate(
      ds, "objective=binary metric=auc num_leaves=15 verbosity=-1", &bst));
  int finished = 0;
  for (int it = 0; it < 10 && !finished; ++it) {
    CHECK(LGBM_BoosterUpdateOneIter(bst, &finished));
  }
  int cur = 0;
  CHECK(LGBM_BoosterGetCurrentIteration(bst, &cur));
  if (cur < 5) {
    fprintf(stderr, "FAIL too few iterations: %d\n", cur);
    return 1;
  }

  int eval_len = 0;
  double evals[16];
  CHECK(LGBM_BoosterGetEval(bst, 0, &eval_len, evals));
  if (eval_len < 1 || evals[0] < 0.9) {
    fprintf(stderr, "FAIL auc: len=%d v=%f\n", eval_len,
            eval_len ? evals[0] : -1);
    return 1;
  }

  int64_t pred_len = 0;
  double* preds = (double*)malloc(sizeof(double) * n);
  CHECK(LGBM_BoosterPredictForMat(bst, X, C_API_DTYPE_FLOAT64, n, f, 1,
                                  C_API_PREDICT_NORMAL, -1, "", &pred_len,
                                  preds));
  if (pred_len != n) {
    fprintf(stderr, "FAIL pred_len: %lld\n", (long long)pred_len);
    return 1;
  }
  int correct = 0;
  for (int i = 0; i < n; ++i) {
    correct += (preds[i] > 0.5) == (y[i] > 0.5f);
  }
  if (correct < n * 0.9) {
    fprintf(stderr, "FAIL accuracy: %d/%d\n", correct, n);
    return 1;
  }

  /* save -> load -> identical raw predictions */
  int64_t mlen = 0;
  CHECK(LGBM_BoosterSaveModelToString(bst, 0, -1, 0, &mlen, NULL));
  char* mstr = (char*)malloc((size_t)mlen);
  int64_t mlen2 = 0;
  CHECK(LGBM_BoosterSaveModelToString(bst, 0, -1, mlen, &mlen2, mstr));
  BoosterHandle bst2 = NULL;
  int iters2 = 0;
  CHECK(LGBM_BoosterLoadModelFromString(mstr, &iters2, &bst2));
  double* preds2 = (double*)malloc(sizeof(double) * n);
  int64_t pred_len2 = 0;
  CHECK(LGBM_BoosterPredictForMat(bst2, X, C_API_DTYPE_FLOAT64, n, f, 1,
                                  C_API_PREDICT_RAW_SCORE, -1, "",
                                  &pred_len2, preds2));
  CHECK(LGBM_BoosterPredictForMat(bst, X, C_API_DTYPE_FLOAT64, n, f, 1,
                                  C_API_PREDICT_RAW_SCORE, -1, "",
                                  &pred_len, preds));
  for (int i = 0; i < n; ++i) {
    if (preds[i] != preds2[i]) {
      fprintf(stderr, "FAIL roundtrip mismatch at %d\n", i);
      return 1;
    }
  }

  /* single-row fast path must agree with row 0 of the batch call */
  double one = 0.0;
  int64_t one_len = 0;
  CHECK(LGBM_BoosterPredictForMatSingleRow(bst, X, C_API_DTYPE_FLOAT64, f,
                                           1, C_API_PREDICT_RAW_SCORE, -1,
                                           "", &one_len, &one));
  if (one_len != 1 || one != preds[0]) {
    fprintf(stderr, "FAIL single-row: len=%lld %f vs %f\n",
            (long long)one_len, one, preds[0]);
    return 1;
  }

  /* GetPredict returns the converted training scores */
  int64_t np_len = 0;
  CHECK(LGBM_BoosterGetNumPredict(bst, 0, &np_len));
  if (np_len != n) {
    fprintf(stderr, "FAIL GetNumPredict: %lld\n", (long long)np_len);
    return 1;
  }
  double* train_pred = (double*)malloc(sizeof(double) * n);
  CHECK(LGBM_BoosterGetPredict(bst, 0, &np_len, train_pred));
  for (int i = 0; i < n; ++i) {
    if (train_pred[i] < 0.0 || train_pred[i] > 1.0) {
      fprintf(stderr, "FAIL GetPredict range at %d: %f\n", i,
              train_pred[i]);
      return 1;
    }
  }

  CHECK(LGBM_BoosterFree(bst));
  CHECK(LGBM_BoosterFree(bst2));
  CHECK(LGBM_DatasetFree(ds));
  printf("CAPI_SMOKE_OK iters=%d auc=%.4f acc=%d/%d\n", cur, evals[0],
         correct, n);
  return 0;
}
