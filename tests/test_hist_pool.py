"""Capped histogram pool (histogram_pool_size): LRU slots + rebuild-on-miss
must reproduce the unlimited pool's model (HistogramPool,
feature_histogram.hpp:646-820)."""
import pytest
import numpy as np

import lightgbm_tpu as lgb


def _train(extra, n=3000, rounds=3, leaves=31):
    rng = np.random.RandomState(3)
    X = rng.randn(n, 6).astype(np.float32)
    y = (X[:, 0] * 1.5 + np.sin(X[:, 1] * 2) + 0.4 * X[:, 2] * X[:, 3]
         + 0.1 * rng.randn(n)).astype(np.float32)
    params = {"objective": "regression", "num_leaves": leaves,
              "verbosity": -1, "min_data_in_leaf": 5, **extra}
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=rounds), X


def test_capped_pool_matches_unlimited():
    full, X = _train({})
    # ~2 slots: every parent histogram must be rebuilt from rows
    tiny, _ = _train({"histogram_pool_size": 1e-4})
    assert tiny._impl.grow_params.pool_slots == 2
    np.testing.assert_allclose(tiny.predict(X), full.predict(X),
                               rtol=1e-5, atol=1e-6)
    # identical tree structure, not merely close predictions
    for tf, tt in zip(full._impl.models, tiny._impl.models):
        np.testing.assert_array_equal(tf.split_feature[:tf.num_nodes],
                                      tt.split_feature[:tt.num_nodes])
        np.testing.assert_array_equal(tf.split_leaf[:tf.num_nodes],
                                      tt.split_leaf[:tt.num_nodes])


def test_mid_size_pool_matches():
    full, X = _train({})
    bytes_per_hist = 6 * 256 * 3 * 4
    mid, _ = _train({"histogram_pool_size":
                     10 * bytes_per_hist / (1024.0 * 1024.0)})
    assert 2 < mid._impl.grow_params.pool_slots < 31
    np.testing.assert_allclose(mid.predict(X), full.predict(X),
                               rtol=1e-5, atol=1e-6)


def test_pool_cap_larger_than_needed_is_uncapped():
    big, _ = _train({"histogram_pool_size": 4096})
    assert big._impl.grow_params.pool_slots == 0


@pytest.mark.slow
def test_capped_pool_multiclass():
    """Capped multiclass takes the sequential-classes path (lax.map)."""
    rng = np.random.RandomState(5)
    X = rng.randn(1500, 5).astype(np.float32)
    y = (np.abs(X[:, 0]) + X[:, 1] > 1).astype(int) + (X[:, 2] > 0)
    kw = {"objective": "multiclass", "num_class": 3, "num_leaves": 15,
          "verbosity": -1}
    full = lgb.train(dict(kw), lgb.Dataset(X, label=y), num_boost_round=3)
    tiny = lgb.train(dict(kw, histogram_pool_size=1e-4),
                     lgb.Dataset(X, label=y), num_boost_round=3)
    assert tiny._impl.grow_params.pool_slots == 2
    np.testing.assert_allclose(tiny.predict(X), full.predict(X),
                               rtol=1e-5, atol=1e-6)
