"""Packed-bin histogram pipeline (core/binpack.py, tpu_bin_packing).

The contract under test, per docs/Performance.md "Packed bins & fused
wave":

- word pack -> unpack round-trips bit-exactly for any column count;
- every histogram impl (matmul, scatter, pallas interpret) produces
  BITWISE identical histograms from the packed words and the plain
  uint8 matrix;
- ``tpu_bin_packing=byte`` training is bitwise identical to unpacked
  training (dense, EFB-bundled, categorical — the words are pure
  storage);
- ``tpu_bin_packing=nibble`` training is structure-identical (pair
  coding reorders f32 accumulation within a joint column);
- streamed packed chunks are bitwise identical to unpacked streaming,
  and each wave runs in chunks+1 dispatches (fused last-chunk+commit);
- vmapped multiclass growth keeps the bucketing ladder (the width
  switch hoisted outside the vmap) with bitwise-identical trees;
- the fused-wave cost entries scale with wave width, and the nibble
  bytes reduction holds the >= 1.5x floor the perf gate pins.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.core.binpack import (gather_code_columns, pack_words_np,
                                       resolve_bin_packing, unpack_words,
                                       unpack_words_np, words_per_row)


def _model_body(bst):
    """Model dump minus the echoed-params line (which records the
    tpu_bin_packing / data_stream settings under test)."""
    return [l for l in bst.model_to_string().splitlines()
            if "tpu_bin_packing" not in l and "data_stream" not in l]


def _structure(lines):
    keep = ("split_feature", "num_leaves", "left_child", "right_child",
            "decision_type")
    return [l for l in lines if any(l.startswith(k) for k in keep)]


def _mixed_xy(n=1600, seed=0):
    rng = np.random.RandomState(seed)
    X = np.concatenate([
        rng.randn(n, 3),                                     # wide bins
        rng.randint(0, 8, size=(n, 4)).astype(np.float64),   # <=16 bins
        rng.randint(0, 6, size=(n, 1)).astype(np.float64),   # categorical
    ], axis=1).astype(np.float32)
    y = ((X[:, 0] + (X[:, 3] > 4) + 0.5 * (X[:, 7] == 2)
          + 0.3 * X[:, 1]) > 1).astype(np.float32)
    return X, y


def _train(X, y, extra, rounds=3, categorical=None):
    params = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
              "max_depth": 4, "tree_growth": "frontier", "seed": 0}
    params.update(extra)
    ds = lgb.Dataset(X, label=y, categorical_feature=categorical or [])
    return lgb.train(params, ds, num_boost_round=rounds)


# ------------------------------------------------------------ layout
def test_word_roundtrip_all_tail_shapes():
    rng = np.random.RandomState(0)
    for c in (1, 3, 4, 5, 8, 9, 17):
        xb = rng.randint(0, 256, size=(37, c)).astype(np.uint8)
        xw = pack_words_np(xb)
        assert xw.shape == (37, words_per_row(c)) and xw.dtype == np.int32
        np.testing.assert_array_equal(unpack_words_np(xw, c), xb)
        np.testing.assert_array_equal(np.asarray(unpack_words(xw, c)), xb)
        # routing's per-row column gather straight from the words
        import jax.numpy as jnp
        cols = jnp.asarray(rng.randint(0, c, size=37), jnp.int32)
        got = np.asarray(gather_code_columns(jnp.asarray(xw), cols))
        want = xb[np.arange(37), np.asarray(cols)]
        np.testing.assert_array_equal(got, want.astype(got.dtype))


def test_resolve_bin_packing_policy():
    small = [14, 16, 9]
    wide = [14, 200, 9]
    # explicit modes pass through untouched
    for m in ("none", "nibble", "byte"):
        assert resolve_bin_packing(m, streamed=True, tpu_shaped=True,
                                   col_num_bin=small) == m
    # auto: nibble on TPU-shaped when every column fits 16 bins
    assert resolve_bin_packing("auto", streamed=False, tpu_shaped=True,
                               col_num_bin=small) == "nibble"
    assert resolve_bin_packing("auto", streamed=False, tpu_shaped=True,
                               col_num_bin=wide) == "byte"
    # auto: streamed ingest keeps the kernel-native words even on CPU
    assert resolve_bin_packing("auto", streamed=True, tpu_shaped=False,
                               col_num_bin=small) == "byte"
    # auto: plain in-memory CPU stays unpacked
    assert resolve_bin_packing("auto", streamed=False, tpu_shaped=False,
                               col_num_bin=small) == "none"


def test_invalid_mode_rejected():
    X, y = _mixed_xy(n=200)
    with pytest.raises(lgb.LightGBMError):
        _train(X, y, {"tpu_bin_packing": "nibbles"}, rounds=1)


# ------------------------------------------------------------ kernels
def test_packed_histograms_bitwise_across_impls():
    import jax.numpy as jnp
    from lightgbm_tpu.core.histogram import (build_histogram,
                                             build_histogram_frontier)

    rng = np.random.RandomState(1)
    n, c, b = 2048, 7, 16
    xb = rng.randint(0, b, size=(n, c)).astype(np.uint8)
    xw = jnp.asarray(pack_words_np(xb))
    xb = jnp.asarray(xb)
    g = jnp.asarray(rng.randn(n).astype(np.float32))
    h = jnp.asarray(rng.rand(n).astype(np.float32))
    m = jnp.asarray((rng.rand(n) > 0.1).astype(np.float32))
    slot = jnp.asarray(rng.randint(-1, 4, size=n).astype(np.int32))
    for impl in ("scatter", "matmul", "pallas_interpret"):
        plain = build_histogram(xb, g, h, m, num_bins=b, row_chunk=512,
                                impl=impl)
        packed = build_histogram(xw, g, h, m, num_bins=b, row_chunk=512,
                                 impl=impl, packed_cols=c)
        np.testing.assert_array_equal(np.asarray(plain),
                                      np.asarray(packed), err_msg=impl)
        plain_f = build_histogram_frontier(
            xb, slot, g, h, m, num_bins=b, num_slots=4, row_chunk=512,
            impl=impl)
        packed_f = build_histogram_frontier(
            xw, slot, g, h, m, num_bins=b, num_slots=4, row_chunk=512,
            impl=impl, packed_cols=c)
        np.testing.assert_array_equal(np.asarray(plain_f),
                                      np.asarray(packed_f), err_msg=impl)


# ------------------------------------------------------------ training
@pytest.mark.slow
def test_byte_mode_bitwise_identity():
    """byte mode changes only the storage layout: same dataset, same
    accumulation order, bitwise-identical model dump — across dense,
    EFB-bundled and categorical features."""
    X, y = _mixed_xy()
    plain = _train(X, y, {"tpu_bin_packing": "none"}, categorical=[7])
    packed = _train(X, y, {"tpu_bin_packing": "byte"}, categorical=[7])
    assert packed._impl.grow_params.word_packed_cols > 0
    assert _model_body(plain) == _model_body(packed)


@pytest.mark.slow
def test_nibble_mode_structure_identity():
    """nibble mode raises the joint-coding cap to 256 ("two bins per
    byte" dataset-wide): at max_bin<=16 the default cap (= dataset max
    bins) blocks almost all pairing, nibble halves the stored columns.
    Trees keep identical structure; values drift only by f32
    accumulation order within joint columns — so the fixture spreads
    well-separated gain weights across the features (a near-gain-tie
    would let that drift flip the winner, the same caveat streaming
    documents)."""
    rng = np.random.RandomState(0)
    n = 1600
    X = np.concatenate([
        rng.randn(n, 3),
        rng.randint(0, 8, size=(n, 4)).astype(np.float64),
        rng.randint(0, 6, size=(n, 1)).astype(np.float64),
    ], axis=1).astype(np.float32)
    y = ((1.7 * X[:, 0] + 0.9 * (X[:, 3] > 4) + 0.45 * (X[:, 7] == 2)
          + 0.23 * X[:, 1] + 0.11 * X[:, 4]) > 1).astype(np.float32)
    plain = _train(X, y, {"tpu_bin_packing": "none", "max_bin": 16,
                          "num_leaves": 7})
    nib = _train(X, y, {"tpu_bin_packing": "nibble", "max_bin": 16,
                        "num_leaves": 7})
    ds_p = plain._impl.train_data
    ds_n = nib._impl.train_data
    assert ds_n.has_packed and ds_n.num_columns < ds_p.num_columns
    assert ds_n.num_columns <= (ds_p.num_columns + 1) // 2
    assert _structure(_model_body(plain)) == _structure(_model_body(nib))
    np.testing.assert_allclose(plain.predict(X), nib.predict(X),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_streamed_packed_chunk_parity():
    """Streamed word-packed chunks (the auto default for streaming) are
    bitwise identical to unpacked streaming, and each wave dispatches
    chunks+1 kernels (the final chunk's sweep fused with the commit)."""
    rng = np.random.RandomState(2)
    X = rng.randn(4000, 9).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)
    st_packed = _train(X, y, {"data_stream_chunk_rows": 1000})
    st_plain = _train(X, y, {"data_stream_chunk_rows": 1000,
                             "tpu_bin_packing": "none"})
    mem = _train(X, y, {"tpu_bin_packing": "none"})
    assert st_packed._impl._stream.packed
    assert not st_plain._impl._stream.packed
    assert _model_body(st_packed) == _model_body(st_plain)
    assert _structure(_model_body(st_packed)) == \
        _structure(_model_body(mem))
    g = st_packed._impl._stream_grower
    chunks = st_packed._impl._stream.num_chunks
    assert g.waves > 0
    assert g.wave_dispatches / g.waves == chunks + 1


@pytest.mark.slow
def test_vmapped_multiclass_keeps_bucketing_identity():
    """The class-batched frontier grower hoists the wave-width switch
    outside the vmap: bucketing stays ON under vmapped multiclass and
    the grown trees are bitwise identical to the fixed-width run (every
    class's structure matches its solo growth by the no-op-wave
    argument in grow_tree_frontier_classes)."""
    rng = np.random.RandomState(3)
    X = rng.randn(1500, 8).astype(np.float32)
    y = rng.randint(0, 3, 1500).astype(np.float32)

    def train(extra):
        p = {"objective": "multiclass", "num_class": 3, "verbosity": -1,
             "num_leaves": 15, "max_depth": 4,
             "tree_growth": "frontier", "seed": 0}
        p.update(extra)
        return lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=3)

    bucketed = train({"tpu_frontier_bucketing": True})
    fixed = train({"tpu_frontier_bucketing": False})
    p = bucketed._impl.grow_params
    assert p.vmapped_classes and p.frontier_bucketing
    assert [l for l in bucketed.model_to_string().splitlines()
            if "tpu_" not in l] == \
        [l for l in fixed.model_to_string().splitlines()
         if "tpu_" not in l]


# ------------------------------------------------------------ costs
@pytest.mark.slow
def test_fused_wave_costs_scale_with_width():
    """The frontier_wave_w* entries price the WHOLE fused wave region
    (sweep + subtraction + 2K-child bin scan), so per-bucket flops must
    strictly grow with the wave width — unlike the bare scatter sweep,
    whose flops are width-invariant (its update traffic is [n, C, 3]
    regardless of slot count), which is why the sweep-only entries
    could never distinguish buckets."""
    rng = np.random.RandomState(4)
    X = rng.randn(512, 6).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    bst = _train(X, y, {}, rounds=1)
    out = bst._impl.extract_cost_model(force=True)
    widths = [1, 2, 4, 8]
    prev = 0.0
    for w in widths:
        name = "frontier_wave_w%d" % w
        assert name in out
        assert out[name]["flops"] > prev, name
        prev = out[name]["flops"]
    # and the fused entries dominate their sweep-only counterparts
    for w in widths:
        assert out["frontier_wave_w%d" % w]["flops"] > \
            out["frontier_hist_w%d" % w]["flops"]


@pytest.mark.slow
def test_packing_bytes_ratio_floor():
    """The headline reduction the perf gate pins: nibble pair coding +
    word packing cut the frontier sweep's cost-model bytes by >= 1.5x
    at the 8192-row probe (both the w=1 and w=8 buckets)."""
    from lightgbm_tpu.obs.perfgate import (PACKING_BYTES_FLOOR,
                                           _packing_counters)
    counters = _packing_counters()
    assert counters["packing_bytes_ratio_w1"] >= PACKING_BYTES_FLOOR
    assert counters["packing_bytes_ratio_w8"] >= PACKING_BYTES_FLOOR
