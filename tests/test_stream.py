"""lightgbm_tpu.stream: out-of-core chunked ingest, binning and training.

The contract under test is docs/OutOfCore.md's headline: because
histograms (and bin counts) are additive over row partitions, training
from host-side chunks is STRUCTURE-IDENTICAL to single-shot training at
the same bin boundaries — same splits, same thresholds, same leaf
partition — for any chunk size, including a ragged last chunk and the
chunk_rows >= n degeneracy. Exact-parity cases pin that end-to-end
(``bin_construct_sample_cnt >= n`` makes round-1 reservoir == full data,
so the boundaries match the in-memory loader bit-for-bit); the
additivity property is additionally pinned at the kernel level for every
histogram impl. Around the core: source error paths, pipeline repacking
and overlap accounting, streamed checkpoints (fingerprint + resume
byte-identity), and per-chunk drift-profile parity.
"""
import os

import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.log import LightGBMError

# structural model-text lines: everything but the float-accumulation-
# sensitive value lines (split_gain / leaf_value / internal_value differ
# in the last ulp because chunked f32 sums run in a different order)
_STRUCT_KEYS = ("split_feature=", "threshold=", "left_child=",
                "right_child=", "leaf_count=", "internal_count=",
                "num_leaves=", "decision_type=", "cat_boundaries=",
                "cat_threshold=", "num_cat=")


def _struct(model_str):
    return [l for l in model_str.splitlines() if l.startswith(_STRUCT_KEYS)]


def _data(n=3000, f=8, seed=0, categorical=False):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    if categorical:
        X[:, 3] = r.randint(0, 8, n)
    y = (2 * X[:, 0] + np.sin(X[:, 1]) + 0.7 * X[:, 2]
         + 0.3 * r.randn(n) > 0).astype(np.float64)
    return X, y


# sample_cnt >= n: round-1 reservoir keeps all rows in order, so the bin
# boundaries are IDENTICAL to the in-memory loader's and parity is exact
_BASE = dict(objective="binary", num_leaves=8, verbosity=-1,
             tree_growth="frontier", bin_construct_sample_cnt=200000,
             min_data_in_leaf=5, deterministic=True)


def _train(params, X, y, rounds=5, **dskw):
    return lgb.train(dict(params), lgb.Dataset(X, label=y, **dskw),
                     num_boost_round=rounds)


# ------------------------------------------------- histogram additivity
@pytest.mark.parametrize("impl", ["matmul", "scatter", "pallas_interpret"])
def test_histogram_additive_over_chunks(impl):
    """sum of per-chunk histograms == full-matrix histogram (to fp32
    accumulation tolerance) for every impl — the property the streamed
    grower's correctness rests on."""
    from lightgbm_tpu.core.histogram import build_histogram
    r = np.random.RandomState(1)
    n, f, b = 2000, 6, 32
    xb = r.randint(0, b, (n, f)).astype(np.uint8)
    g = r.randn(n).astype(np.float32)
    h = np.abs(r.randn(n)).astype(np.float32)
    m = (r.rand(n) < 0.8).astype(np.float32)
    full = np.asarray(build_histogram(
        jnp.asarray(xb), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m),
        num_bins=b, impl=impl))
    acc = np.zeros_like(full)
    for lo in range(0, n, 700):               # ragged last chunk (600)
        hi = min(lo + 700, n)
        acc += np.asarray(build_histogram(
            jnp.asarray(xb[lo:hi]), jnp.asarray(g[lo:hi]),
            jnp.asarray(h[lo:hi]), jnp.asarray(m[lo:hi]),
            num_bins=b, impl=impl))
    np.testing.assert_allclose(acc, full, rtol=1e-5, atol=1e-3)


@pytest.mark.parametrize("impl", ["matmul", "scatter", "pallas_interpret"])
def test_frontier_histogram_additive_over_chunks(impl):
    from lightgbm_tpu.core.histogram import build_histogram_frontier
    r = np.random.RandomState(2)
    n, f, b, k = 2000, 6, 32, 4
    xb = r.randint(0, b, (n, f)).astype(np.uint8)
    slot = r.randint(-1, k, n).astype(np.int32)
    g = r.randn(n).astype(np.float32)
    h = np.abs(r.randn(n)).astype(np.float32)
    m = (r.rand(n) < 0.8).astype(np.float32)
    full = np.asarray(build_histogram_frontier(
        jnp.asarray(xb), jnp.asarray(slot), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(m), num_bins=b, num_slots=k, impl=impl))
    acc = np.zeros_like(full)
    for lo in range(0, n, 700):
        hi = min(lo + 700, n)
        acc += np.asarray(build_histogram_frontier(
            jnp.asarray(xb[lo:hi]), jnp.asarray(slot[lo:hi]),
            jnp.asarray(g[lo:hi]), jnp.asarray(h[lo:hi]),
            jnp.asarray(m[lo:hi]), num_bins=b, num_slots=k, impl=impl))
    np.testing.assert_allclose(acc, full, rtol=1e-5, atol=1e-3)


# --------------------------------------------- end-to-end structure parity
def test_streamed_matches_single_shot_dense():
    X, y = _data()
    a = _train(_BASE, X, y)
    b = _train(dict(_BASE, data_stream_chunk_rows=700), X, y)
    assert _struct(a.model_to_string()) == _struct(b.model_to_string())
    # and the predictions agree to fp32 accumulation noise
    np.testing.assert_allclose(a.predict(X[:256]), b.predict(X[:256]),
                               rtol=1e-4, atol=1e-5)


def test_streamed_matches_single_shot_skewed_last_chunk():
    X, y = _data()
    # 3000 % 1999 = 1001: the last chunk is half-empty after repacking
    a = _train(_BASE, X, y)
    b = _train(dict(_BASE, data_stream_chunk_rows=1999), X, y)
    assert _struct(a.model_to_string()) == _struct(b.model_to_string())


@pytest.mark.slow
@pytest.mark.slow
def test_streamed_chunk_rows_ge_n_degenerates_to_single_chunk():
    X, y = _data(n=1500)
    a = _train(_BASE, X, y)
    b = _train(dict(_BASE, data_stream_chunk_rows=10 ** 6), X, y)
    assert _struct(a.model_to_string()) == _struct(b.model_to_string())
    ds = lgb.Dataset(X, label=y,
                     params=dict(_BASE, data_stream_chunk_rows=10 ** 6))
    assert len(ds.construct()._binned.chunks) == 1


@pytest.mark.slow
@pytest.mark.slow
def test_streamed_matches_single_shot_categorical_and_efb():
    X, y = _data(categorical=True, seed=3)
    # two sparse exclusive-ish columns make EFB bundling kick in
    r = np.random.RandomState(4)
    X[:, 4] = (r.rand(len(X)) < 0.05) * r.randint(1, 5, len(X))
    X[:, 5] = (r.rand(len(X)) < 0.05) * r.randint(1, 5, len(X))
    p = dict(_BASE)
    a = _train(p, X, y, categorical_feature=[3])
    b = _train(dict(p, data_stream_chunk_rows=777), X, y,
               categorical_feature=[3])
    assert _struct(a.model_to_string()) == _struct(b.model_to_string())


@pytest.mark.slow
@pytest.mark.slow
def test_streamed_multiclass_parity():
    X, _ = _data(seed=5)
    r = np.random.RandomState(5)
    y3 = np.digitize(2 * X[:, 0] + np.sin(X[:, 1]) + 0.3 * r.randn(len(X)),
                     [-1.0, 1.0]).astype(np.float64)
    p = dict(_BASE, objective="multiclass", num_class=3)
    a = _train(p, X, y3, rounds=3)
    b = _train(dict(p, data_stream_chunk_rows=700), X, y3, rounds=3)
    assert _struct(a.model_to_string()) == _struct(b.model_to_string())


@pytest.mark.slow
@pytest.mark.slow
def test_streamed_bagging_goss_parity_with_per_iteration_baseline():
    """Bagging / GOSS draw their keys from the per-iteration split chain;
    the fused-block path uses a different (batched) chain, so the
    baseline pins the per-iteration path via observability=full."""
    X, y = _data(seed=6)
    for extra in (dict(boosting="goss"),
                  dict(bagging_fraction=0.7, bagging_freq=1),
                  dict(feature_fraction=0.6)):
        p = dict(_BASE, **extra)
        a = _train(dict(p, observability="full"), X, y)
        b = _train(dict(p, data_stream_chunk_rows=750), X, y)
        assert _struct(a.model_to_string()) == _struct(b.model_to_string())


@pytest.mark.slow
@pytest.mark.slow
def test_streamed_npy_and_csv_sources_match_array(tmp_path):
    X, y = _data(n=1200)
    p = dict(_BASE, data_stream_chunk_rows=500)
    ref = _train(p, X, y, rounds=3)

    npy = str(tmp_path / "X.npy")
    np.save(npy, X)
    b1 = lgb.train(dict(p), lgb.Dataset(npy, label=y, params=dict(p)),
                   num_boost_round=3)
    assert _struct(ref.model_to_string()) == _struct(b1.model_to_string())

    csv = str(tmp_path / "d.csv")
    np.savetxt(csv, np.column_stack([y, X]), delimiter=",", fmt="%.10g")
    b2 = lgb.train(dict(p), lgb.Dataset(csv, params=dict(p)),
                   num_boost_round=3)
    # CSV round-trips through decimal text: boundaries can move by one
    # ulp, so parity is on predictions, not split structure
    np.testing.assert_allclose(ref.predict(X[:128]), b2.predict(X[:128]),
                               rtol=1e-3, atol=1e-3)


# ------------------------------------------------------------- ingest unit
def test_reservoir_sample_matches_two_round_loader():
    """Same RNG stream as BinnedDataset.from_file_two_round: boundaries
    from a SUB-sample (sample_cnt < n) must also match the file loader's,
    not just the trivial sample_cnt >= n case."""
    from lightgbm_tpu.stream import ArraySource
    from lightgbm_tpu.stream.sampler import ingest
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    X, y = _data(n=2500, f=4, seed=9)
    cfg = Config(dict(bin_construct_sample_cnt=400, data_random_seed=11))
    sd = ingest(ArraySource(X, label=y, chunk_rows=600), cfg)
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "x.csv")
        np.savetxt(path, np.column_stack([y, X]), delimiter=",",
                   fmt="%.17g")
        ref = BinnedDataset.from_file_two_round(path, cfg)
    for m1, m2 in zip(sd.bin_mappers, ref.bin_mappers):
        assert m1.to_dict() == m2.to_dict()


def test_streamed_dataset_shape_and_refusals():
    from lightgbm_tpu.stream import ArraySource
    from lightgbm_tpu.stream.sampler import ingest
    from lightgbm_tpu.config import Config
    X, y = _data(n=1100, f=4)
    sd = ingest(ArraySource(X, label=y, chunk_rows=300),
                Config(dict(bin_construct_sample_cnt=200000)))
    assert sd.is_streamed and sd.X_binned is None
    assert sd.chunk_row_counts == [300, 300, 300, 200]
    assert sd.num_data == 1100
    with pytest.raises(LightGBMError, match="save_binary"):
        sd.save_binary("/tmp/nope.bin")


def test_libsvm_rejected(tmp_path):
    from lightgbm_tpu.stream import CsvSource
    path = str(tmp_path / "d.libsvm")
    with open(path, "w") as fh:
        fh.write("1 0:2.5 3:1.2\n0 1:0.5\n")
    with pytest.raises(LightGBMError, match="LibSVM"):
        CsvSource(path, chunk_rows=4)


def test_bad_sources_raise():
    from lightgbm_tpu.stream import (ArraySource, ChunkSource, CsvSource,
                                     NpyMmapSource)
    from lightgbm_tpu.stream.sampler import ingest
    from lightgbm_tpu.config import Config
    cfg = Config(dict(bin_construct_sample_cnt=1000))
    scipy_sparse = pytest.importorskip("scipy.sparse")
    with pytest.raises(LightGBMError):
        ArraySource(scipy_sparse.eye(10).tocsr(), chunk_rows=5)
    with pytest.raises(LightGBMError):
        ArraySource(np.zeros((10, 2)), chunk_rows=0)
    with pytest.raises(LightGBMError):
        ArraySource(np.zeros((10, 2)), label=np.zeros(7), chunk_rows=5)
    with pytest.raises((LightGBMError, IOError, ValueError)):
        NpyMmapSource("/nonexistent/path.npy", chunk_rows=5)

    class Ragged(ChunkSource):
        chunk_rows = 4

        def reset(self):
            pass

        def __iter__(self):
            yield np.zeros((4, 3)), None
            yield np.zeros((4, 2)), None      # feature count changes

    with pytest.raises(LightGBMError, match="feature"):
        ingest(Ragged(), cfg)

    class Empty(ChunkSource):
        chunk_rows = 4

        def reset(self):
            pass

        def __iter__(self):
            return iter(())

    with pytest.raises(LightGBMError, match="no rows"):
        ingest(Empty(), cfg)

    class Shrinking(ChunkSource):
        """Non-restartable: round 2 yields fewer rows than round 1."""
        chunk_rows = 4

        def __init__(self):
            self.calls = 0

        def reset(self):
            self.calls += 1

        def __iter__(self):
            for _ in range(3 if self.calls <= 1 else 2):
                yield np.random.RandomState(0).randn(4, 3), None

    with pytest.raises(LightGBMError, match="restartable"):
        ingest(Shrinking(), cfg)


def test_streaming_config_gates():
    X, y = _data(n=400)
    with pytest.raises(LightGBMError):
        lgb.train(dict(_BASE, data_stream_chunk_rows=-1),
                  lgb.Dataset(X, label=y), num_boost_round=1)
    with pytest.raises(LightGBMError):
        lgb.train(dict(_BASE, data_stream_chunk_rows=100,
                       data_stream_prefetch=0),
                  lgb.Dataset(X, label=y), num_boost_round=1)
    with pytest.raises(LightGBMError):
        lgb.train(dict(_BASE, data_stream_chunk_rows=100,
                       tree_growth="exact"),
                  lgb.Dataset(X, label=y), num_boost_round=1)
    with pytest.raises(LightGBMError):
        lgb.train(dict(_BASE, data_stream_chunk_rows=100, boosting="dart"),
                  lgb.Dataset(X, label=y), num_boost_round=1)


def test_streamed_rollback_and_input_grads_refused():
    X, y = _data(n=600)
    p = dict(_BASE, data_stream_chunk_rows=200)
    bst = lgb.train(dict(p), lgb.Dataset(X, label=y), num_boost_round=2)
    with pytest.raises(LightGBMError, match="rollback"):
        bst._impl.rollback_one_iter()
    with pytest.raises(LightGBMError, match="gradients"):
        bst._impl.train_one_iter(grad=np.zeros(len(y), np.float32),
                                 hess=np.ones(len(y), np.float32))


# ------------------------------------------------------------- pipeline
def test_repack_uniform_and_pipeline_accounting():
    from lightgbm_tpu.stream.pipeline import ChunkPipeline, repack_uniform
    chunks = [np.arange(i * 10, i * 10 + r * 3, dtype=np.uint8
                        ).reshape(r, 3) % 250
              for i, r in enumerate([5, 2, 7, 1])]
    uni, total = repack_uniform(chunks, 4)
    assert total == 15
    assert [c.shape for c in uni] == [(4, 3)] * 4
    flat = np.concatenate(uni)[:total]
    np.testing.assert_array_equal(flat, np.concatenate(chunks))
    assert not np.any(np.concatenate(uni)[total:])   # zero padding

    pipe = ChunkPipeline(chunks, 4, prefetch=2)
    assert pipe.num_chunks == 4 and pipe.num_padded == 16
    assert pipe.valid_rows == [4, 4, 4, 3]
    seen = [(i, np.asarray(c)) for i, c in pipe.sweep()]
    assert [i for i, _ in seen] == [0, 1, 2, 3]
    np.testing.assert_array_equal(np.concatenate([c for _, c in seen]),
                                  np.concatenate(uni))
    st = pipe.stats()
    assert st["sweeps"] == 1 and st["rows_transferred"] == 15
    assert 0.0 <= st["overlap_efficiency"] <= 1.0


# ----------------------------------------------------- checkpoint / drift
def test_streamed_fingerprint_semantics():
    from lightgbm_tpu.checkpoint.snapshot import dataset_fingerprint
    X, y = _data(n=900, f=4)
    mk = lambda params: lgb.Dataset(X, label=y, params=params) \
        .construct()._binned
    d1 = mk(dict(_BASE, data_stream_chunk_rows=250))
    d2 = mk(dict(_BASE, data_stream_chunk_rows=400))
    d3 = lgb.Dataset(X + 1e-3, label=y,
                     params=dict(_BASE, data_stream_chunk_rows=250)) \
        .construct()._binned
    # chunking-invariant (same rows, same layout), data-sensitive
    assert dataset_fingerprint(d1) == dataset_fingerprint(d2)
    assert dataset_fingerprint(d1) != dataset_fingerprint(d3)


@pytest.mark.slow
@pytest.mark.slow
def test_streamed_resume_byte_identical(tmp_path):
    from lightgbm_tpu import callback, engine
    X, y = _data(n=1500)
    p = dict(_BASE, data_stream_chunk_rows=400, bagging_fraction=0.8,
             bagging_freq=1)

    def run(ckpt, rounds, resume=False):
        ds = lgb.Dataset(X, label=y, params=dict(p))
        return engine.train(dict(p), ds, num_boost_round=rounds,
                            callbacks=[callback.checkpoint(ckpt, period=1)],
                            resume_from=(ckpt if resume else None),
                            verbose_eval=False)

    golden = run(str(tmp_path / "g"), 6)
    run(str(tmp_path / "i"), 2)
    resumed = run(str(tmp_path / "i"), 6, resume=True)
    assert golden.model_to_string() == resumed.model_to_string()


def test_streamed_drift_profile_matches_single_shot():
    from lightgbm_tpu.obs.drift import DataProfile
    X, y = _data(n=1300, seed=8, categorical=True)
    r = np.random.RandomState(8)
    X[:, 4] = (r.rand(len(X)) < 0.05) * r.randint(1, 5, len(X))
    X[:, 5] = (r.rand(len(X)) < 0.05) * r.randint(1, 5, len(X))
    full = lgb.Dataset(X, label=y, categorical_feature=[3],
                       params=dict(_BASE)).construct()._binned
    streamed = lgb.Dataset(X, label=y, categorical_feature=[3],
                           params=dict(_BASE, data_stream_chunk_rows=300)) \
        .construct()._binned
    a = DataProfile.from_binned_dataset(full)
    b = streamed.data_profile()
    assert a.num_data == b.num_data
    assert a.features == b.features      # bit-identical counts + mappers
