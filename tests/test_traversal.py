"""Serving SoA traversal: parity vs the replay path, cascades, hot-roll.

The traversal backend (serving/traversal.py) must be bit-identical to the
training-side replay path (core/tree.py) for every decision the reference
Tree::Predict makes — numerical splits, categorical bitsets, missing-value
default directions, num_iteration truncation, multiclass — because the
serving golden tests pin Booster.predict parity at 1e-6 and the two paths
share one decision function (core/tree.py decision_go_left).
"""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from conftest import make_binary
from lightgbm_tpu import callback
from lightgbm_tpu.serving import (ModelRegistry, ServingEngine,
                                  forest_scores_flat, pack_flat_forest)

HERE = os.path.dirname(os.path.abspath(__file__))


def _flat_scores(impl, X, k=1, cascade_trees=0, cascade_margin=10.0,
                 quantize=False, ntrees=None):
    import jax
    import jax.numpy as jnp
    models = impl.models if ntrees is None else impl.models[:ntrees]
    flat, depth = pack_flat_forest(models, quantize=quantize)
    dev = jax.tree.map(jnp.asarray, flat)
    return np.asarray(forest_scores_flat(
        dev, jnp.asarray(np.asarray(X, np.float32)), k, depth,
        cascade_trees=cascade_trees, cascade_margin=cascade_margin))


def _replay_scores(impl, X, k=1, ntrees=None):
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.core import tree as tree_mod
    t = len(impl.models) if ntrees is None else ntrees
    stacked = impl._stacked_predict_trees(0, t)
    trees = jax.tree.map(lambda a: a.reshape((t // k, k) + a.shape[1:]),
                         stacked)
    return np.asarray(tree_mod.predict_forest_scores(
        trees, jnp.asarray(np.asarray(X, np.float32))))


# ------------------------------------------------------------ dense parity
def test_traversal_matches_replay_dense():
    X, y = make_binary(n=600, f=10)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=8)
    Xq = np.random.RandomState(1).rand(257, 10).astype(np.float32)
    out = _flat_scores(bst._impl, Xq)
    ref = _replay_scores(bst._impl, Xq)
    assert np.array_equal(out, ref)     # bit-exact, not just close


def test_traversal_matches_replay_missing_values():
    """NaN routing must follow the node's missing_type/default_left —
    the decision function is shared, but the traversal gathers its
    fields through a different layout."""
    rng = np.random.RandomState(3)
    X, y = make_binary(n=800, f=8)
    X = np.asarray(X, np.float32).copy()
    X[rng.rand(*X.shape) < 0.15] = np.nan
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "use_missing": True, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=6)
    Xq = np.asarray(X[:300], np.float32).copy()
    Xq[rng.rand(*Xq.shape) < 0.3] = np.nan
    assert np.array_equal(_flat_scores(bst._impl, Xq),
                          _replay_scores(bst._impl, Xq))


def test_traversal_matches_replay_categorical():
    rng = np.random.RandomState(7)
    n = 900
    X = np.zeros((n, 4), np.float32)
    X[:, 0] = rng.randint(0, 12, n)           # categorical
    X[:, 1] = rng.rand(n)
    X[:, 2] = rng.randint(0, 40, n)           # categorical, wider
    X[:, 3] = rng.randn(n)
    y = ((X[:, 0] % 3 == 0) ^ (X[:, 1] > 0.5)).astype(np.float32)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1},
                    lgb.Dataset(X, label=y, categorical_feature=[0, 2]),
                    num_boost_round=6, categorical_feature=[0, 2])
    # in-range, out-of-range and negative categories all route the same
    Xq = X[:200].copy()
    Xq[:5, 0] = [-1.0, 99.0, 11.0, 0.0, 3.0]
    assert np.array_equal(_flat_scores(bst._impl, Xq),
                          _replay_scores(bst._impl, Xq))


def test_traversal_matches_replay_efb():
    """EFB-bundled training still extracts per-feature host trees; the
    traversal serves them identically."""
    rng = np.random.RandomState(11)
    X = np.zeros((500, 12), np.float32)
    for j in range(12):                       # sparse, bundleable columns
        mask = rng.rand(500) < 0.15
        X[mask, j] = rng.rand(int(mask.sum()))
    y = (X.sum(axis=1) > 0.2).astype(np.float32)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "enable_bundle": True, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    assert np.array_equal(_flat_scores(bst._impl, X[:200]),
                          _replay_scores(bst._impl, X[:200]))


@pytest.mark.slow
def test_traversal_num_iteration_truncation():
    X, y = make_binary(n=400, f=6)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=9)
    Xq = np.asarray(X[:128], np.float32)
    for ntrees in (1, 4, 9):
        assert np.array_equal(
            _flat_scores(bst._impl, Xq, ntrees=ntrees),
            _replay_scores(bst._impl, Xq, ntrees=ntrees)), ntrees


@pytest.mark.slow
def test_traversal_multiclass():
    rng = np.random.RandomState(5)
    X = rng.rand(600, 8).astype(np.float32)
    y = (X[:, 0] * 3).astype(np.int32).clip(0, 2)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 15, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    Xq = X[:200]
    out = _flat_scores(bst._impl, Xq, k=3)
    ref = _replay_scores(bst._impl, Xq, k=3)
    assert out.shape == (200, 3)
    assert np.array_equal(out, ref)


# ------------------------------------------------------------ engine parity
@pytest.mark.parametrize("raw", [False, True])
def test_engine_traversal_vs_replay_backends(raw):
    """The two ServingEngine backends serve byte-identical outputs (and
    both match Booster.predict, which the serving goldens already pin)."""
    X, y = make_binary(n=500, f=9)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=6)
    Xq = np.random.RandomState(2).rand(77, 9).astype(np.float32)
    outs = {}
    for backend in ("traversal", "replay"):
        eng = ServingEngine(max_batch=128, min_bucket=16, backend=backend)
        eng.registry.register_booster("m", bst)
        outs[backend] = eng.predict("m", Xq, raw_score=raw)
        assert eng._cache and all(
            e.backend == backend for e in eng._cache.values())
    assert np.array_equal(outs["traversal"], outs["replay"])
    assert np.allclose(outs["traversal"], bst.predict(Xq, raw_score=raw),
                       atol=1e-6)


# ------------------------------------------------------------ cascade
@pytest.mark.slow
def test_cascade_margin_inf_is_bit_identical():
    X, y = make_binary(n=500, f=8)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=10)
    Xq = np.random.RandomState(4).rand(300, 8).astype(np.float32)
    full = _flat_scores(bst._impl, Xq)
    casc = _flat_scores(bst._impl, Xq, cascade_trees=3,
                        cascade_margin=float("inf"))
    assert np.array_equal(full, casc)


def test_cascade_margin_zero_serves_stage_one_only():
    X, y = make_binary(n=500, f=8)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=10)
    Xq = np.random.RandomState(4).rand(300, 8).astype(np.float32)
    stage1 = _flat_scores(bst._impl, Xq, ntrees=3)
    casc = _flat_scores(bst._impl, Xq, cascade_trees=3, cascade_margin=0.0)
    assert np.array_equal(stage1, casc)


@pytest.mark.slow
def test_cascade_engine_end_to_end():
    """A cascade engine with a generous margin must still match the full
    model on confident rows and stay within the margin bound elsewhere;
    with margin=inf it matches everywhere (transforms included)."""
    X, y = make_binary(n=600, f=8)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=10)
    Xq = np.random.RandomState(6).rand(200, 8).astype(np.float32)
    eng = ServingEngine(max_batch=256, min_bucket=16,
                        cascade_trees=4, cascade_margin=float("inf"))
    eng.registry.register_booster("m", bst)
    assert np.allclose(eng.predict("m", Xq), bst.predict(Xq), atol=1e-6)


# ------------------------------------------------------------ quantized leaves
@pytest.mark.slow
def test_quantized_leaves_close_not_exact():
    X, y = make_binary(n=500, f=8)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=8)
    Xq = np.random.RandomState(8).rand(300, 8).astype(np.float32)
    ref = _replay_scores(bst._impl, Xq)
    outq = _flat_scores(bst._impl, Xq, quantize=True)
    scale = max(float(np.abs(ref).max()), 1e-9)
    assert np.abs(outq - ref).max() / scale < 1e-3
    eng = ServingEngine(max_batch=256, min_bucket=16, quantize_leaves=True)
    eng.registry.register_booster("m", bst)
    assert np.allclose(eng.predict("m", Xq, raw_score=True), ref[:, 0],
                       atol=1e-3)


# ------------------------------------------------------------ hot-roll prewarm
@pytest.mark.slow
def test_prewarm_hot_roll_zero_recompiles(tmp_path):
    """Staged-generation hot-roll: prewarm compiles the next generation
    off the request path, the generation-aware purge keeps those entries
    at commit, and the recompile/miss floors absorb the prewarm — the
    zero-recompile-after-warmup invariant survives the roll."""
    X, y = make_binary(n=400, f=6)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    bst_a = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=3)
    bst_b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    path_a = str(tmp_path / "a.txt")
    path_b = str(tmp_path / "b.txt")
    bst_a.save_model(path_a)
    bst_b.save_model(path_b)

    Xq = np.random.RandomState(9).rand(40, 6).astype(np.float32)
    # reference BEFORE warmup: Booster.predict's own compiles must not
    # pollute the post-warmup recompile count (serve_smoke.py idiom)
    ref_b = bst_b.predict(Xq)

    eng = ServingEngine(max_batch=64, min_bucket=16)
    eng.registry.load_file("m", path_a)
    warmed = eng.warmup()
    assert warmed == eng.cache_size()
    eng.predict("m", Xq)

    staged = eng.stage_and_prewarm("m", path_b)
    assert staged.generation == eng.registry.generation("m") + 1
    eng.registry.register(staged, replace=True)
    # stale generation purged, prewarmed generation kept
    assert eng.cache_size() == warmed
    out = eng.predict("m", Xq)
    assert np.allclose(out, ref_b, atol=1e-6)
    assert eng.metrics.cache_misses_after_warmup() == 0
    assert eng.metrics.recompiles_after_warmup() == 0
    snap = eng.metrics.snapshot()
    assert snap["warmup_credit_compiles"] >= 1
    assert snap["warmup_credit_misses"] == warmed


def test_generation_aware_purge_without_prewarm(tmp_path):
    """A plain (non-prewarmed) replace still drops every stale entry —
    the pre-existing hot-roll contract (test_checkpoint relies on it)."""
    X, y = make_binary(n=300, f=5)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=2)
    p = str(tmp_path / "m.txt")
    bst.save_model(p)
    eng = ServingEngine(max_batch=32, min_bucket=16)
    eng.registry.load_file("m", p)
    eng.warmup()
    assert eng.cache_size() > 0
    eng.registry.load_file("m", p, replace=True)
    assert eng.cache_size() == 0


@pytest.mark.slow
def test_watcher_prewarms_through_engine(tmp_path):
    """watch_dir(engine=...) rolls a newer checkpoint in with zero
    post-warmup recompiles visible to the serving invariant."""
    from lightgbm_tpu.checkpoint.manager import CheckpointManager

    X, y = make_binary(n=400, f=6)
    d = str(tmp_path / "ckpt")
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    cbs = [callback.checkpoint(d, period=1)]
    lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=2,
              callbacks=cbs)
    eng = ServingEngine(max_batch=64, min_bucket=16)
    w = eng.registry.watch_dir("m", d, engine=eng)
    assert w.poll()
    eng.warmup()
    Xq = np.random.RandomState(10).rand(30, 6).astype(np.float32)
    eng.predict("m", Xq)
    gen0 = eng.registry.generation("m")

    lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=4,
              callbacks=cbs, resume_from=d)
    assert CheckpointManager(d).latest_model() is not None
    # the in-process resume training above compiles its own programs;
    # only compiles from the poll/hot-roll/serve below are under test
    rec_floor = eng.metrics.recompiles_after_warmup()
    assert w.poll()
    assert eng.registry.generation("m") == gen0 + 1
    eng.predict("m", Xq)
    assert eng.metrics.cache_misses_after_warmup() == 0
    assert eng.metrics.recompiles_after_warmup() == rec_floor


def test_chain_tree_with_root_left_leaf_gets_full_depth():
    """Sparse-trained trees often come out chain-shaped with the root's
    LEFT child a leaf and the whole spine hanging off the right child;
    depth must count the spine, not early-out as a stump (the traversal
    freezes mid-tree and serves a wrapped leaf index otherwise)."""
    from lightgbm_tpu.serving.traversal import _tree_depth

    # root: left -> leaf 0, right -> node 1 -> ... -> node 3 spine
    left = np.array([-1, -2, -3, -4], np.int32)
    right = np.array([1, 2, 3, -5], np.int32)
    assert _tree_depth(left, right) == 4
    # true stump: one node, both children leaves
    assert _tree_depth(np.array([-1], np.int32),
                       np.array([-2], np.int32)) == 1
