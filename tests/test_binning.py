"""BinMapper unit tests (reference behavior: src/io/bin.cpp FindBin,
bin.h:457-493 ValueToBin)."""
import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.binning import BinMapper, BinType, MissingType
from lightgbm_tpu.io.dataset import BinnedDataset


def _find(values, total=None, max_bin=255, **kw):
    m = BinMapper()
    values = np.asarray(values, np.float64)
    kw.setdefault("min_data_in_bin", 1)
    kw.setdefault("min_split_data", 1)
    m.find_bin(values, total_sample_cnt=total or len(values), max_bin=max_bin,
               **kw)
    return m


def test_simple_numerical_bins_partition_values():
    vals = np.arange(100, dtype=np.float64)
    m = _find(vals, max_bin=10)
    bins = m.values_to_bins(vals)
    assert bins.min() >= 0 and bins.max() < m.num_bin
    # binning must be monotone in the raw value
    assert (np.diff(bins) >= 0).all()


def test_distinct_few_values_get_own_bins():
    vals = np.array([1.0, 2.0, 3.0] * 50)
    m = _find(vals)
    b = m.values_to_bins(np.array([1.0, 2.0, 3.0]))
    assert len(set(b.tolist())) == 3


def test_trivial_feature():
    m = _find(np.full(100, 5.0), use_missing=False)
    assert m.is_trivial or m.num_bin <= 1


def test_nan_goes_to_last_bin():
    vals = np.concatenate([np.arange(50, dtype=np.float64),
                           np.full(10, np.nan)])
    m = _find(vals, use_missing=True)
    assert m.missing_type == MissingType.NAN
    b = m.values_to_bins(np.array([np.nan]))
    assert b[0] == m.num_bin - 1


def test_zero_as_missing():
    vals = np.concatenate([np.arange(1, 51, dtype=np.float64),
                           np.zeros(30)])
    m = _find(vals, use_missing=True, zero_as_missing=True)
    assert m.missing_type == MissingType.ZERO


def test_bin_to_value_roundtrip_monotone():
    r = np.random.RandomState(3)
    vals = r.randn(1000)
    m = _find(vals, max_bin=64)
    uppers = [m.bin_to_value(i) for i in range(m.num_bin)]
    # upper bounds must be increasing over numerical bins
    nb = m.num_bin - (1 if m.missing_type == MissingType.NAN else 0)
    assert all(uppers[i] <= uppers[i + 1] for i in range(nb - 2))


def test_value_to_bin_respects_boundaries():
    vals = np.array([0.0, 1.0, 2.0, 3.0, 4.0] * 20)
    m = _find(vals)
    for v in [0.0, 1.0, 2.0, 3.0, 4.0]:
        b = int(m.values_to_bins(np.array([v]))[0])
        # upper bound of the assigned bin must be >= the value
        assert m.bin_upper_bound[b] >= v


def test_categorical_binning():
    vals = np.array([0, 1, 2, 1, 0, 2, 5, 5, 5, 1] * 20, np.float64)
    m = _find(vals, bin_type=BinType.CATEGORICAL)
    assert m.bin_type == BinType.CATEGORICAL
    b = m.values_to_bins(np.array([0.0, 1.0, 2.0, 5.0]))
    assert len(set(b.tolist())) == 4
    # unseen category maps to bin 0 (reference: ValueToBin returns 0)
    unseen = m.values_to_bins(np.array([99.0]))
    assert unseen[0] == 0


def test_equal_count_binning_balances_counts():
    r = np.random.RandomState(0)
    vals = r.exponential(size=10000)
    m = _find(vals, max_bin=16)
    bins = m.values_to_bins(vals)
    counts = np.bincount(bins, minlength=m.num_bin)
    nb = m.num_bin
    # greedy equal-count: no bin (except possibly tail) wildly imbalanced
    assert counts.max() < len(vals) / nb * 4


def test_dataset_from_matrix_shapes():
    r = np.random.RandomState(1)
    X = r.randn(500, 8)
    X[:, 3] = 1.0  # trivial column dropped
    cfg = Config({"max_bin": 63, "min_data_in_bin": 1})
    ds = BinnedDataset.from_matrix(X, cfg, label=np.zeros(500))
    assert ds.num_total_features == 8
    assert ds.num_features == 7
    assert ds.X_binned.shape == (500, 7)
    assert ds.X_binned.dtype == np.uint8
    assert ds.max_num_bin() <= 63 + 1  # + NaN bin headroom


def test_dataset_reference_alignment():
    r = np.random.RandomState(2)
    X = r.randn(300, 5)
    cfg = Config({})
    ds = BinnedDataset.from_matrix(X, cfg, label=np.zeros(300))
    X2 = r.randn(100, 5)
    ds2 = BinnedDataset.from_matrix(X2, cfg, label=np.zeros(100), reference=ds)
    assert ds2.bin_mappers is ds.bin_mappers
    assert ds2.X_binned.shape[1] == ds.X_binned.shape[1]


def test_binary_cache_roundtrip(tmp_path):
    r = np.random.RandomState(4)
    X = r.randn(200, 4)
    y = r.rand(200)
    cfg = Config({})
    ds = BinnedDataset.from_matrix(X, cfg, label=y, weight=np.ones(200))
    p = str(tmp_path / "ds.npz")
    ds.save_binary(p)
    ds2 = BinnedDataset.load_binary(p)
    np.testing.assert_array_equal(ds.X_binned, ds2.X_binned)
    np.testing.assert_allclose(ds.metadata.label, ds2.metadata.label)
    assert ds2.num_total_features == 4
