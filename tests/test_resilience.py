"""lightgbm_tpu.resilience — fault injection, supervision, overload guard.

Contracts pinned here (docs/Resilience.md):
- fault plans parse deterministically; unknown kinds fail at config time;
  single-shot faults fire exactly once; with no plan installed inject()
  is inert;
- KvHostComm surfaces timeouts as LightGBMError naming namespace / round
  / rank / key / elapsed ms, retries transient set/get failures with
  backoff, and fails FAST on a dead peer via the heartbeat guard;
- LoopbackComm: a crashing simulated rank breaks the barrier and peers
  get a clean LightGBMError instead of hanging forever;
- MicroBatchQueue: row-bounded admission sheds with OverloadedError,
  queue depth is reported in both requests and rows, submit during drain
  is a clean error (not a hang), drained requests still get answers;
- Watchdog: warmup-aware first deadline (slow-but-alive first compile
  never false-fires), fires once heartbeats stop;
- Supervisor: bounded restarts with exponential backoff, resumes from the
  checkpoint dir, exhaustion raises with the LAST flight-dump path;
- CircuitBreaker: trips after N consecutive failures, admits exactly one
  half-open probe after the cooldown, probe failure re-opens;
- guarded hot-roll: a staged NaN model is refused (rollbacks counter,
  prior generation keeps serving);
- supervised training with an injected crash auto-resumes and the final
  model is byte-identical to the uninterrupted run.
"""
import os
import re
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import engine
from lightgbm_tpu.log import LightGBMError, OverloadedError
from lightgbm_tpu.parallel.network import KvHostComm, LoopbackComm
from lightgbm_tpu.resilience import breaker as breaker_mod
from lightgbm_tpu.resilience import faults
from lightgbm_tpu.resilience.breaker import CircuitBreaker
from lightgbm_tpu.resilience.supervisor import (ATTEMPT_ENV, KvHeartbeat,
                                                ProcessSupervisor, Supervisor,
                                                Watchdog,
                                                heartbeat_file_callback)
from lightgbm_tpu.serving import ServingEngine
from lightgbm_tpu.serving.batching import MicroBatchQueue
from lightgbm_tpu.serving.metrics import ServingMetrics


@pytest.fixture(autouse=True)
def _clean_plan():
    faults.clear_plan()
    yield
    faults.clear_plan()


# ------------------------------------------------------------- fault plans
def test_fault_plan_parses_units_and_args():
    plan = faults.parse_plan(
        "kv_timeout@block:2,kill@iter:7,serve_error@req:50,"
        "serve_delay@request:*:125,hang@iteration:3:10")
    specs = {repr(f) for f in plan.faults}
    assert "kv_timeout@round:2" in specs           # block -> round alias
    assert "kill@iteration:7" in specs             # iter -> iteration
    assert "serve_error@request:50" in specs
    assert "serve_delay@request:*:125" in specs
    d = [f for f in plan.faults if f.kind == "serve_delay"][0]
    assert d.match is None and d.arg_float(0.0) == 125.0
    h = [f for f in plan.faults if f.kind == "hang"][0]
    assert h.arg_float(3600.0) == 10.0


@pytest.mark.parametrize("bad", ["bogus@iter:1", "kill", "kill@iter:x",
                                 "kill@:3"])
def test_fault_plan_rejects_malformed(bad):
    with pytest.raises(LightGBMError):
        faults.parse_plan(bad)


def test_inject_inert_without_plan_and_single_shot():
    # no plan installed: inject is a no-op at any point
    faults.inject("serve_predict")
    faults.inject("train_dispatch", iteration=7)

    faults.install_plan("serve_error@req:2")
    faults.inject("serve_predict")                 # req 1: no fire
    with pytest.raises(LightGBMError, match="injected serving fault"):
        faults.inject("serve_predict")             # req 2: fires
    faults.inject("serve_predict")                 # single shot: spent
    # identical re-install keeps the plan (fire counts survive restarts)
    plan = faults.active_plan()
    assert faults.install_plan("serve_error@req:2") is plan
    faults.inject("serve_predict")


def test_config_validates_fault_plan():
    from lightgbm_tpu.config import Config
    c = Config({"fault_inject": "crash@iter:3", "fault_seed": 5})
    assert c.fault_inject == "crash@iter:3" and c.fault_seed == 5
    with pytest.raises(LightGBMError):
        Config({"fault_inject": "nope@iter:1"})


# ---------------------------------------------------------------- KV comm
class StubKv:
    """Dict-backed coordination-service client double."""

    def __init__(self, fail_sets=0, fail_gets=0):
        self.store = {}
        self.fail_sets = fail_sets
        self.fail_gets = fail_gets
        self.set_calls = 0

    def key_value_set(self, key, value):
        self.set_calls += 1
        if self.fail_sets > 0:
            self.fail_sets -= 1
            raise RuntimeError("UNAVAILABLE: stub transient set failure")
        self.store[key] = value

    def blocking_key_value_get(self, key, timeout_ms):
        if self.fail_gets > 0:
            self.fail_gets -= 1
            raise RuntimeError("UNAVAILABLE: stub transient get failure")
        if key in self.store:
            return self.store[key]
        time.sleep(min(timeout_ms / 1000.0, 0.01))
        raise RuntimeError("DEADLINE_EXCEEDED: stub timeout")

    def key_value_delete(self, key):
        self.store.pop(key, None)


def _comm(stub, rank=0, n=2, timeout_ms=250, **kw):
    return KvHostComm(namespace="t_res", timeout_ms=timeout_ms, client=stub,
                      num_processes=n, rank=rank, retry_backoff_s=0.01, **kw)


def _publish_peer(stub, r, rank, obj):
    import base64
    import pickle
    stub.store["t_res/r%d/p%d" % (r, rank)] = base64.b64encode(
        pickle.dumps(obj)).decode("ascii")


def test_kv_allgather_roundtrip_and_set_retry():
    stub = StubKv(fail_sets=2)
    comm = _comm(stub)
    _publish_peer(stub, 0, 1, {"peer": 1})
    out = comm.allgather({"peer": 0})
    assert out == [{"peer": 0}, {"peer": 1}]
    assert stub.set_calls == 3                      # 2 transient + 1 ok


def test_kv_timeout_surfaces_context():
    stub = StubKv()
    comm = _comm(stub, timeout_ms=150)
    with pytest.raises(LightGBMError) as ei:       # peer 1 never publishes
        comm.allgather("x")
    msg = str(ei.value)
    for needle in ("t_res", "round=0", "rank=0", "peer=1",
                   "t_res/r0/p1", "elapsed"):
        assert needle in msg, msg


def test_kv_set_retry_budget_exhausted():
    stub = StubKv(fail_sets=10)
    comm = _comm(stub, retries=2)
    with pytest.raises(LightGBMError, match="after 3 attempt"):
        comm.allgather("x")


def test_kv_peer_guard_fails_fast():
    stub = StubKv()
    comm = _comm(stub, timeout_ms=60000, peer_guard=lambda: [1])
    t0 = time.monotonic()
    with pytest.raises(LightGBMError, match="peer rank 1 is DEAD"):
        comm.allgather("x")
    assert time.monotonic() - t0 < 10.0            # not the 60s timeout


def test_kv_injected_transient_error_retried():
    faults.install_plan("kv_error@calls:1")
    stub = StubKv()
    comm = _comm(stub)
    _publish_peer(stub, 0, 1, "b")
    assert comm.allgather("a") == ["a", "b"]       # retried through the fault


# ------------------------------------------------------------ LoopbackComm
def test_loopback_crashing_rank_does_not_hang_peers():
    comms = LoopbackComm.group(3, timeout_s=20.0)
    results = {}

    def good(rank):
        try:
            results[rank] = comms[rank].allgather(rank)
        except LightGBMError as e:
            results[rank] = e

    def bad(rank):
        try:
            comms[rank]._shared["slots"][rank] = rank
            raise RuntimeError("simulated rank death")
        except RuntimeError:
            comms[rank].abort()

    threads = [threading.Thread(target=good, args=(r,)) for r in (0, 1)]
    threads.append(threading.Thread(target=bad, args=(2,)))
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "peer thread hung on broken barrier"
    for r in (0, 1):
        assert isinstance(results[r], LightGBMError)
        assert "rank 2 crashed" in str(results[r])


def test_loopback_normal_allgather_still_works():
    comms = LoopbackComm.group(2)
    out = {}
    ts = [threading.Thread(target=lambda r=r: out.setdefault(
        r, comms[r].allgather(r * 10))) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10)
    assert out[0] == [0, 10] and out[1] == [0, 10]


# -------------------------------------------------------------- micro queue
class FakeEngine:
    """Just enough of ServingEngine for queue-only tests."""

    def __init__(self, predict_s=0.0, max_batch=1024):
        self.metrics = ServingMetrics()
        self.max_batch = max_batch
        self.predict_s = predict_s

    def predict(self, model_id, X, raw_score=False, num_iteration=None,
                _record_request=True):
        if self.predict_s:
            time.sleep(self.predict_s)
        return np.zeros((X.shape[0],), np.float64)


def test_queue_reports_rows_and_requests():
    eng = FakeEngine()
    q = MicroBatchQueue(eng, deadline_ms=500.0).start()
    try:
        q.submit("m", np.zeros((3, 2), np.float32))
        q.submit("m", np.zeros((5, 2), np.float32))
        assert eng.metrics.queue_depth == 2        # requests
        assert eng.metrics.queue_rows == 8         # rows
        snap = eng.metrics.snapshot()
        assert snap["queue_depth"] == 2 and snap["queue_rows"] == 8
    finally:
        q.stop(drain=False)


def test_queue_sheds_past_row_bound():
    eng = FakeEngine()
    q = MicroBatchQueue(eng, deadline_ms=500.0, max_queue_rows=4).start()
    try:
        q.submit("m", np.zeros((3, 2), np.float32))
        with pytest.raises(OverloadedError) as ei:
            q.submit("m", np.zeros((3, 2), np.float32))
        assert ei.value.retry_after_s > 0
        assert eng.metrics.shed == 1
        assert eng.metrics.queue_rows <= 4
    finally:
        q.stop(drain=False)


def test_queue_submit_during_drain_clean_error():
    eng = FakeEngine(predict_s=0.3)
    q = MicroBatchQueue(eng, deadline_ms=0.0).start()
    f1 = q.submit("m1", np.zeros((2, 2), np.float32))
    time.sleep(0.1)                                # worker is dispatching f1
    f2 = q.submit("m2", np.zeros((1, 2), np.float32))  # queued behind it
    stopper = threading.Thread(target=q.stop)      # drain=True
    stopper.start()
    time.sleep(0.05)
    with pytest.raises(LightGBMError, match="draining"):
        q.submit("m3", np.zeros((1, 2), np.float32))
    stopper.join(timeout=10)
    assert not stopper.is_alive()
    assert f1.result(timeout=5).shape == (2,)      # drained, not dropped
    assert f2.result(timeout=5).shape == (1,)


def test_queue_request_timeout_expires_stale_requests():
    eng = FakeEngine(predict_s=0.25)
    q = MicroBatchQueue(eng, deadline_ms=0.0,
                        request_timeout_ms=100.0).start()
    try:
        # first request occupies the worker; the second exceeds its
        # deadline while queued and is expired at dispatch
        f1 = q.submit("m", np.zeros((1, 2), np.float32))
        time.sleep(0.05)
        f2 = q.submit("m", np.zeros((1, 2), np.float32))
        assert f1.result(timeout=5).shape == (1,)
        with pytest.raises(OverloadedError, match="expired in queue"):
            f2.result(timeout=5)
        assert eng.metrics.request_timeouts == 1
    finally:
        q.stop(drain=False)


# ---------------------------------------------------------------- watchdog
def test_watchdog_warmup_grace_no_false_fire():
    fired = []
    wd = Watchdog(0.15, warmup_grace_s=1.5, on_fire=fired.append).start()
    try:
        time.sleep(0.5)          # slow-but-alive first compile window
        assert not wd.fired and not fired
        wd.beat()
        time.sleep(0.05)
        assert not wd.fired
    finally:
        wd.stop()
        faults.clear_abort()


def test_watchdog_fires_when_beats_stop():
    fired = []
    wd = Watchdog(0.1, warmup_grace_s=0.0, on_fire=fired.append).start()
    try:
        wd.beat()
        time.sleep(0.5)
        assert wd.fired and len(fired) == 1
        assert faults.abort_event().is_set()
        with pytest.raises(faults.WatchdogAbort):
            faults.inject("train_dispatch", iteration=0)
    finally:
        wd.stop()
        faults.clear_abort()


def test_heartbeat_file_callback_touches(tmp_path):
    path = str(tmp_path / "hb")
    cb = heartbeat_file_callback(path)
    assert cb.before_iteration
    cb(SimpleNamespace(iteration=4))
    assert os.path.exists(path)
    assert open(path).read().startswith("4 ")


# --------------------------------------------------------------- supervisor
def test_supervisor_needs_checkpoint_dir():
    with pytest.raises(LightGBMError, match="checkpoint_dir"):
        Supervisor("")


def test_supervisor_retries_then_succeeds(tmp_path):
    sup = Supervisor(str(tmp_path), max_restarts=3, backoff_s=0.01,
                     backoff_max_s=0.02)
    seen = []

    def attempt(resume, wd):
        seen.append(resume)
        if len(seen) < 3:
            raise RuntimeError("boom %d" % len(seen))
        return "done"

    assert sup.run(attempt) == "done"
    assert sup.restarts == 2
    assert seen[0] is None                       # first try: fresh
    assert seen[1] == str(tmp_path)              # retries resume


def test_supervisor_exhaustion_names_flight_dump(tmp_path):
    sup = Supervisor(str(tmp_path), max_restarts=2, backoff_s=0.01,
                     backoff_max_s=0.02)

    def attempt(resume, wd):
        err = RuntimeError("persistent failure")
        err.flight_dump_path = "/tmp/events.0.crash.jsonl"
        raise err

    with pytest.raises(LightGBMError) as ei:
        sup.run(attempt)
    msg = str(ei.value)
    assert "after 2 restarts" in msg
    assert "/tmp/events.0.crash.jsonl" in msg
    assert sup.restarts == 3                     # initial + 2 restarts


def test_process_supervisor_attempt_env(tmp_path):
    import sys
    prog = ("import os, sys; "
            "sys.exit(0 if os.environ['%s'] == '1' else 7)" % ATTEMPT_ENV)
    sup = ProcessSupervisor([sys.executable, "-c", prog], max_restarts=2,
                            backoff_s=0.01, backoff_max_s=0.02)
    assert sup.run() == 0
    assert sup.restarts == 1 and sup.attempts == [7, 0]


def test_process_supervisor_budget_exhaustion():
    import sys
    sup = ProcessSupervisor([sys.executable, "-c", "import sys; sys.exit(3)"],
                            max_restarts=1, backoff_s=0.01,
                            backoff_max_s=0.02)
    with pytest.raises(LightGBMError, match="after 1 restarts"):
        sup.run()


def test_kv_heartbeat_leases():
    stub = StubKv()
    hb = KvHeartbeat(namespace="hb_t", period_s=0.1, lease_s=0.2,
                     client=stub, rank=0, num_processes=2)
    hb.start()
    try:
        assert "hb_t/p0" in stub.store
        assert hb.dead_peers() == []             # startup grace
        time.sleep(0.35)
        assert hb.dead_peers() == [1]            # never seen past lease
        stub.store["hb_t/p1"] = "%.6f" % time.time()
        assert hb.dead_peers() == []
        stub.store["hb_t/p1"] = "%.6f" % (time.time() - 5.0)
        assert hb.dead_peers() == [1]            # stale lease
    finally:
        hb.stop()
    assert "hb_t/p0" not in stub.store           # lease released on stop


# ----------------------------------------------------------- circuit breaker
def test_breaker_trip_halfopen_probe():
    brk = CircuitBreaker(failure_threshold=2, cooldown_s=0.15)
    assert brk.allow()
    brk.record_failure()
    assert brk.state == breaker_mod.CLOSED and brk.allow()
    brk.record_failure()                          # second consecutive: trip
    assert brk.state == breaker_mod.OPEN
    assert not brk.allow() and brk.retry_after_s() > 0
    time.sleep(0.2)
    assert brk.allow()                            # the half-open probe
    assert brk.state == breaker_mod.HALF_OPEN
    assert not brk.allow()                        # only ONE probe in flight
    brk.record_failure()                          # probe failed: re-open
    assert brk.state == breaker_mod.OPEN and brk.trips == 2
    time.sleep(0.2)
    assert brk.allow()
    brk.record_success()                          # probe ok: close + reset
    assert brk.state == breaker_mod.CLOSED and brk.allow()


def test_breaker_success_resets_consecutive_count():
    brk = CircuitBreaker(failure_threshold=3, cooldown_s=1.0)
    for _ in range(2):
        brk.record_failure()
    brk.record_success()
    for _ in range(2):
        brk.record_failure()
    assert brk.state == breaker_mod.CLOSED       # never 3 consecutive
    assert CircuitBreaker(failure_threshold=0).allow()   # 0 disables


# --------------------------------------------------------- guarded hot-roll
def _tiny_model(tmp_path):
    r = np.random.RandomState(3)
    X = r.randn(160, 4)
    y = X[:, 0] * 2 + np.abs(X[:, 1]) + 0.1 * r.randn(160)
    params = dict(objective="regression", num_leaves=4, min_data_in_leaf=5,
                  verbosity=-1)
    ds = lgb.Dataset(X, label=y, params=dict(params))
    bst = engine.train(dict(params), ds, num_boost_round=3,
                       verbose_eval=False)
    path = str(tmp_path / "good.txt")
    bst.save_model(path)
    return path, X[:4]


def _nan_copy(src, dst):
    text = open(src).read()

    def poison(m):
        n = len(m.group(1).split())
        return "leaf_value=" + " ".join(["nan"] * n)

    open(dst, "w").write(re.sub(r"leaf_value=([^\n]+)", poison, text))


def test_guarded_roll_rejects_nan_model(tmp_path):
    good, Xq = _tiny_model(tmp_path)
    bad = str(tmp_path / "bad.txt")
    _nan_copy(good, bad)
    eng = ServingEngine(max_batch=16, min_bucket=16)
    bundle = eng.stage_and_prewarm("m", good)     # good roll passes guard
    eng.registry.register(bundle, replace=True)
    ref = eng.predict("m", Xq)
    with pytest.raises(LightGBMError, match="canary"):
        eng.stage_and_prewarm("m", bad)
    assert eng.metrics.rollbacks == 1
    out = eng.predict("m", Xq)                    # prior generation lives
    np.testing.assert_array_equal(out, ref)
    assert np.isfinite(out).all()


def test_guarded_roll_watcher_keeps_serving(tmp_path):
    good, Xq = _tiny_model(tmp_path)
    eng = ServingEngine(max_batch=16, min_bucket=16)
    bundle = eng.stage_and_prewarm("m", good)
    eng.registry.register(bundle, replace=True)
    bad = str(tmp_path / "bad.txt")
    _nan_copy(good, bad)
    watcher = eng.registry.watch_dir("m", str(tmp_path), engine=eng)
    watcher._last_id = 0
    # monkeypatch the manifest lookup: snapshot 1 -> the poisoned file
    import lightgbm_tpu.checkpoint.manager as mgr_mod
    orig = mgr_mod.CheckpointManager.latest_model
    mgr_mod.CheckpointManager.latest_model = lambda self: (1, bad)
    try:
        assert watcher.poll() is False            # rejected, not rolled
        assert 1 in watcher._rejected_ids
        assert watcher.poll() is False            # remembered, no rework
        assert eng.metrics.rollbacks == 1         # only validated once
    finally:
        mgr_mod.CheckpointManager.latest_model = orig
    assert np.isfinite(eng.predict("m", Xq)).all()


# ----------------------------------------------- supervised byte-identity
def test_supervised_crash_resume_byte_identical(tmp_path):
    r = np.random.RandomState(9)
    X = r.randn(200, 5)
    y = (X[:, 0] + 2 * X[:, 1] + 0.2 * r.randn(200) > 0).astype(np.float64)
    base = dict(objective="binary", num_leaves=5, min_data_in_leaf=5,
                verbosity=-1, checkpoint_period=1)

    golden_p = dict(base, checkpoint_dir=str(tmp_path / "g"))
    ds = lgb.Dataset(X, label=y, params=dict(golden_p))
    golden = engine.train(dict(golden_p), ds, num_boost_round=6,
                          verbose_eval=False)

    victim_p = dict(base, checkpoint_dir=str(tmp_path / "v"),
                    fault_inject="crash@iter:3", supervise=True,
                    supervise_backoff_s=0.01, supervise_backoff_max_s=0.02)
    ds2 = lgb.Dataset(X, label=y, params=dict(victim_p))
    victim = engine.train(dict(victim_p), ds2, num_boost_round=6,
                          verbose_eval=False)

    # byte-identical trees; the parameters echo differs by construction
    # (checkpoint_dir path, the fault/supervise params themselves)
    def trees_only(s):
        return s.split("\nparameters:", 1)[0]

    assert trees_only(victim.model_to_string()) == \
        trees_only(golden.model_to_string())


def test_supervised_exhaustion_raises(tmp_path):
    r = np.random.RandomState(9)
    X = r.randn(120, 4)
    y = (X[:, 0] > 0).astype(np.float64)
    p = dict(objective="binary", num_leaves=4, min_data_in_leaf=5,
             verbosity=-1, checkpoint_dir=str(tmp_path / "c"),
             checkpoint_period=1, fault_inject="crash@iter:*",
             supervise=True, supervise_max_restarts=1,
             supervise_backoff_s=0.01, supervise_backoff_max_s=0.02)
    ds = lgb.Dataset(X, label=y, params=dict(p))
    with pytest.raises(LightGBMError, match="after 1 restart"):
        engine.train(dict(p), ds, num_boost_round=4, verbose_eval=False)


# -------------------------------------------------------- torn checkpoint
def test_ckpt_torn_fault_breaks_sha(tmp_path):
    from lightgbm_tpu.checkpoint import snapshot as snap_mod
    from lightgbm_tpu.checkpoint.manifest import sha256_file
    faults.install_plan("ckpt_torn@snap:1")
    entry = snap_mod.write_snapshot(
        str(tmp_path), 1, {"iteration": 1, "num_trees": 0,
                           "num_leaves": [], "num_leaves_actual": [],
                           "shrinkage": []},
        {"scores": np.zeros((8, 1), np.float32)}, "model-text")
    state = os.path.join(str(tmp_path), entry["files"]["state"])
    # the recorded sha is the PRE-TEAR one: verification must fail
    assert sha256_file(state) != entry["sha256"][entry["files"]["state"]]
