"""Device-side RenewTreeOutput (core/renew.py): the in-graph segmented
weighted percentile must agree with the host _weighted_percentile on every
leaf, including empty leaves and masked-out rows."""
import pytest
import numpy as np
import jax.numpy as jnp

from lightgbm_tpu.core.renew import renew_leaf_values
from lightgbm_tpu.objectives import _weighted_percentile


@pytest.mark.slow
def test_renew_matches_host_percentile_fuzz():
    r = np.random.RandomState(0)
    for trial in range(30):
        n = r.randint(5, 400)
        num_leaves = r.randint(2, 12)
        alpha = float(r.choice([0.5, 0.1, 0.9, 0.33]))
        resid = r.randn(n).astype(np.float32)
        w = r.rand(n).astype(np.float32) + 0.01
        lid = r.randint(0, num_leaves, n).astype(np.int32)
        mask = r.rand(n) > 0.3
        orig = r.randn(num_leaves).astype(np.float32)
        out = np.asarray(renew_leaf_values(
            jnp.asarray(resid), jnp.asarray(w), jnp.asarray(lid),
            jnp.asarray(mask), num_leaves, alpha, jnp.asarray(orig)))
        for leaf in range(num_leaves):
            sel = (lid == leaf) & mask
            exp = (_weighted_percentile(resid[sel], w[sel], alpha)
                   if sel.any() else orig[leaf])
            assert abs(out[leaf] - exp) < 1e-6, (trial, leaf, out[leaf], exp)


def test_l1_training_renews_in_graph():
    """L1 training must stay on the fused train_many block path (no host
    round-trip per iteration) and land on the label median structure the
    renewal exists for."""
    import lightgbm_tpu as lgb
    r = np.random.RandomState(3)
    X = r.randn(800, 6)
    y = X[:, 0] * 2.0 + np.abs(r.standard_cauchy(800)) * 0.05
    bst = lgb.train({"objective": "regression_l1", "verbosity": -1,
                     "num_leaves": 15, "learning_rate": 0.2},
                    lgb.Dataset(X, y), num_boost_round=30)
    pred = bst.predict(X)
    mae = np.abs(pred - y).mean()
    assert mae < 0.5 * np.abs(y - np.median(y)).mean()
    # the fused-block eligibility is the device-renew contract: a host
    # renewal per iteration would have forced the per-iter path
    b = bst._impl if hasattr(bst, "_impl") else bst
    assert getattr(b, "_use_input_grads", False) is False
