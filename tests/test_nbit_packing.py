"""Joint-coded pair packing of small-bin features (the Dense4bitsBin
analog, dense_nbits_bin.hpp:38-82): two <=16-bin features share one stored
uint8 column; per-feature histograms are marginals of the joint histogram.

Must be a pure storage optimization: identical tree structure, predictions
within float32 accumulation drift of the unpacked run, and B unchanged.
"""
import numpy as np
import pytest
from sklearn.metrics import roc_auc_score

import lightgbm_tpu as lgb


def _mixed_xy(n=4000, seed=0):
    rng = np.random.RandomState(seed)
    X = np.concatenate([
        rng.randn(n, 2),                                     # wide bins
        rng.randint(0, 10, size=(n, 6)).astype(np.float64),  # <=16 bins
    ], axis=1).astype(np.float32)
    y = ((X[:, 0] + (X[:, 2] > 5) + (X[:, 3] < 3) * 0.5
          + 0.3 * X[:, 1]) > 1).astype(np.float32)
    return X, y


@pytest.mark.slow
def test_packing_reduces_columns_and_matches_structure():
    X, y = _mixed_xy()
    params = {"objective": "binary", "verbosity": -1, "num_leaves": 31}
    packed = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=8)
    plain = lgb.train(dict(params, enable_nbit_packing=False),
                      lgb.Dataset(X, label=y), num_boost_round=8)
    ds = packed._impl.train_data
    assert ds.has_packed
    assert ds.num_columns == 5      # 2 wide + 3 packed pairs of 6 small
    assert ds.max_col_bins() == plain._impl.train_data.max_col_bins()
    for tp, tq in zip(packed._impl.models, plain._impl.models):
        np.testing.assert_array_equal(tp.split_feature[:tp.num_nodes],
                                      tq.split_feature[:tq.num_nodes])
        np.testing.assert_allclose(tp.threshold[:tp.num_nodes],
                                   tq.threshold[:tq.num_nodes], rtol=1e-6)
    np.testing.assert_allclose(packed.predict(X, raw_score=True),
                               plain.predict(X, raw_score=True),
                               rtol=1e-4, atol=1e-4)
    assert roc_auc_score(y, packed.predict(X)) > 0.95


def test_packing_skipped_when_it_would_widen_b():
    """All-small datasets keep narrow histograms; packing must not grow B."""
    rng = np.random.RandomState(1)
    X = rng.randint(0, 10, size=(2000, 6)).astype(np.float32)
    y = ((X[:, 0] > 5) | (X[:, 1] < 3)).astype(np.float32)
    bst = lgb.train({"objective": "binary", "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    ds = bst._impl.train_data
    assert not ds.has_packed          # 11*11 > the ~12-bin column width
    assert roc_auc_score(y, bst.predict(X)) > 0.95


@pytest.mark.slow
def test_packing_with_missing_values():
    X, y = _mixed_xy(seed=2)
    X[::7, 3] = np.nan                # NaN in a packed small feature
    packed = lgb.train({"objective": "binary", "verbosity": -1},
                       lgb.Dataset(X, label=y), num_boost_round=6)
    plain = lgb.train({"objective": "binary", "verbosity": -1,
                       "enable_nbit_packing": False},
                      lgb.Dataset(X, label=y), num_boost_round=6)
    assert packed._impl.train_data.has_packed
    np.testing.assert_allclose(packed.predict(X, raw_score=True),
                               plain.predict(X, raw_score=True),
                               rtol=1e-4, atol=1e-4)


def test_packing_binary_cache_roundtrip(tmp_path):
    X, y = _mixed_xy(seed=3)
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    cfg = Config({"objective": "binary", "verbosity": -1})
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    assert ds.has_packed
    path = str(tmp_path / "ds.npz")
    ds.save_binary(path)
    loaded = BinnedDataset.load_binary(path)
    assert loaded.col_packed == ds.col_packed
    np.testing.assert_array_equal(loaded.X_binned, ds.X_binned)
