"""Worker process for the real multi-process test (one of N ranks).

Run by tests/test_multiprocess.py: each rank is a separate OS process
with ONE local CPU device; jax.distributed glues them into a 2-device
global mesh and the data-parallel learner trains across it — the live
analog of the reference's socket-machine walkthrough
(docs/Parallel-Learning-Guide.rst:38-110).
"""
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main() -> None:
    rank = int(os.environ["LIGHTGBM_TPU_RANK"])
    port = os.environ["MP_TEST_PORT"]
    out_path = os.environ["MP_TEST_OUT"]

    from lightgbm_tpu.parallel import network
    # rank 0's entry doubles as the jax.distributed coordinator address
    network.init(machines="127.0.0.1:%s,127.0.0.1:0" % port,
                 num_machines=2, time_out=60)
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 2, jax.devices()

    r = np.random.RandomState(0)
    X = r.randn(4096, 8).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.boosting import create_boosting

    cfg = Config({"objective": "binary", "tree_learner": "data",
                  "num_machines": 2, "num_leaves": 15, "verbosity": -1})
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    b = create_boosting(cfg, ds, create_objective(cfg), [])
    for _ in range(5):
        b.train_one_iter()
    pred = np.asarray(b.predict(X[:256], raw_score=True), np.float64)

    if rank == 0:
        with open(out_path, "w") as f:
            json.dump({"pred": pred.tolist()}, f)
    sys.exit(0)


if __name__ == "__main__":
    main()
