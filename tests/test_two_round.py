"""two_round streaming file loading (two_round / use_two_round_loading).

The reference's DatasetLoader streams >memory text files in two passes
(dataset_loader.cpp:160-219); here round 1 reservoir-samples rows for bin
finding and round 2 bins chunk-by-chunk, so only uint8 columns persist.
"""
import os

import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset


def _write_csv(path, X, y):
    data = np.column_stack([y, X])
    np.savetxt(path, data, delimiter=",", fmt="%.6f")


def test_two_round_matches_one_shot(tmp_path):
    r = np.random.RandomState(0)
    n, f = 5000, 6
    X = r.randn(n, f)
    X[r.rand(n, f) < 0.2] = 0.0
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    path = os.path.join(tmp_path, "train.csv")
    _write_csv(path, X, y)

    cfg = Config({"objective": "binary", "verbosity": -1, "label_column": "0"})
    # small chunks force several round-2 chunks and a chunk-boundary tail
    ds2 = BinnedDataset.from_file_two_round(path, cfg, chunk_rows=700)
    ds1 = lgb.Dataset(path).construct()._binned
    # sample_cnt >= N so the reservoir holds every row: identical mappers,
    # identical binned matrix
    np.testing.assert_array_equal(ds2.X_binned, ds1.X_binned)
    np.testing.assert_allclose(ds2.metadata.label, ds1.metadata.label)
    assert ds2.num_data == n


def test_two_round_through_train(tmp_path):
    r = np.random.RandomState(1)
    n, f = 3000, 5
    X = r.randn(n, f)
    y = (X[:, 0] * X[:, 1] > 0).astype(np.float64)
    path = os.path.join(tmp_path, "t.csv")
    _write_csv(path, X, y)
    params = {"objective": "binary", "metric": "auc", "num_leaves": 15,
              "verbosity": -1, "label_column": "0"}
    b1 = lgb.train(params, lgb.Dataset(path), num_boost_round=5)
    b2 = lgb.train(dict(params, two_round=True), lgb.Dataset(path),
                   num_boost_round=5)
    p1 = b1.predict(X[:500], raw_score=True)
    p2 = b2.predict(X[:500], raw_score=True)
    np.testing.assert_allclose(p1, p2, rtol=0, atol=0)


def test_two_round_valid_set_alignment(tmp_path):
    r = np.random.RandomState(2)
    X = r.randn(2000, 4); y = (X[:, 0] > 0).astype(np.float64)
    Xv = r.randn(500, 4); yv = (Xv[:, 0] > 0).astype(np.float64)
    ptr = os.path.join(tmp_path, "tr.csv"); _write_csv(ptr, X, y)
    pv = os.path.join(tmp_path, "va.csv"); _write_csv(pv, Xv, yv)
    params = {"objective": "binary", "metric": "auc", "two_round": True,
              "verbosity": -1, "label_column": "0"}
    dtr = lgb.Dataset(ptr)
    ev = {}
    lgb.train(params, dtr, num_boost_round=5,
              valid_sets=[lgb.Dataset(pv, reference=dtr)],
              valid_names=["v"], evals_result=ev, verbose_eval=False)
    assert ev["v"]["auc"][-1] > 0.9


def test_two_round_libsvm_falls_back(tmp_path):
    """LibSVM input cannot stream (needs a global feature count); two_round
    silently takes the one-shot parser instead of failing."""
    r = np.random.RandomState(3)
    n = 400
    lines = []
    for i in range(n):
        feats = " ".join("%d:%.4f" % (j, r.randn()) for j in range(4))
        lines.append("%d %s" % (int(r.rand() > 0.5), feats))
    path = os.path.join(tmp_path, "t.libsvm")
    open(path, "w").write("\n".join(lines))
    bst = lgb.train({"objective": "binary", "two_round": True,
                     "verbosity": -1}, lgb.Dataset(path), num_boost_round=3)
    assert np.isfinite(bst.predict(np.zeros((2, 4)))).all()
