"""Native (C++) host runtime: parser parity with the pure-Python path
(the reference validates its C++ loaders end-to-end through the bindings,
SURVEY.md §4; here the two implementations check each other)."""
import os

import numpy as np
import pytest

from lightgbm_tpu import native
from lightgbm_tpu.io import parser


@pytest.fixture(scope="module")
def csv_file(tmp_path_factory):
    d = tmp_path_factory.mktemp("native")
    r = np.random.RandomState(0)
    X = r.randn(2000, 5)
    y = r.randint(0, 2, 2000)
    p = str(d / "data.csv")
    with open(p, "w") as fh:
        fh.write("label,a,b,c,d,e\n")
        for xi, yi in zip(X, y):
            vals = ["%g" % v for v in xi]
            if r.rand() < 0.02:
                vals[1] = ""          # missing -> NaN
            fh.write("%d," % yi + ",".join(vals) + "\n")
    return p, X, y


def test_native_lib_builds():
    assert native.get_lib() is not None, \
        "native library failed to build (g++ is baked into the image)"


def test_native_csv_matches_python(csv_file):
    p, X, y = csv_file
    Xn, yn, names = parser.parse_file(p, has_header=True)
    # max_lines forces the pure-Python path
    Xp, yp, names_p = parser.parse_file(p, has_header=True, max_lines=10**9)
    assert names == names_p == ["a", "b", "c", "d", "e"]
    np.testing.assert_array_equal(yn, yp)
    np.testing.assert_allclose(np.nan_to_num(Xn, nan=-9e9),
                               np.nan_to_num(Xp, nan=-9e9))


def test_native_libsvm(tmp_path):
    r = np.random.RandomState(1)
    X = r.randn(500, 7)
    y = r.randint(0, 2, 500)
    p = str(tmp_path / "d.svm")
    with open(p, "w") as fh:
        for xi, yi in zip(X, y):
            toks = ["%d" % yi] + ["%d:%g" % (j, v)
                                  for j, v in enumerate(xi) if abs(v) > 0.3]
            fh.write(" ".join(toks) + "\n")
    Xn, yn, _ = parser.parse_file(p)
    Xp, yp, _ = parser.parse_file(p, max_lines=10**9)
    assert Xn.shape == Xp.shape
    np.testing.assert_array_equal(yn, yp)
    np.testing.assert_allclose(Xn, Xp)


def test_native_label_by_name(csv_file):
    p, X, y = csv_file
    Xn, yn, names = parser.parse_file(p, has_header=True,
                                      label_column="name:label")
    np.testing.assert_array_equal(yn, y.astype(np.float64))


def test_native_weight_query_sidecars_still_python(tmp_path):
    # sidecar loaders stay in Python; just exercise them
    p = str(tmp_path / "t.csv")
    with open(p, "w") as fh:
        fh.write("1,2,3\n0,4,5\n")
    with open(p + ".weight", "w") as fh:
        fh.write("0.5\n2.0\n")
    w = parser.load_weight_file(p)
    np.testing.assert_allclose(w, [0.5, 2.0])
