"""sklearn estimator surface (LGBMRegressor/Classifier/Ranker) and the
plotting helpers (plot_importance/metric/tree) — reference python-package
sklearn.py / plotting.py parity by function."""
import numpy as np
import pytest
from sklearn.metrics import roc_auc_score

import lightgbm_tpu as lgb


def _xy(n=1500, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(int)
    return X, y


def test_classifier_fit_predict_proba():
    X, y = _xy()
    clf = lgb.LGBMClassifier(n_estimators=10, num_leaves=15)
    clf.fit(X, y)
    proba = clf.predict_proba(X)
    assert proba.shape == (len(y), 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-6)
    assert roc_auc_score(y, proba[:, 1]) > 0.95
    assert set(clf.predict(X)) <= {0, 1}
    assert list(clf.classes_) == [0, 1]


@pytest.mark.slow
def test_regressor_early_stopping_sets_best_iteration():
    X, y = _xy()
    yr = X[:, 0] * 2 + 0.05 * np.random.RandomState(1).randn(len(y))
    reg = lgb.LGBMRegressor(n_estimators=200, learning_rate=0.3)
    reg.fit(X[:1000], yr[:1000], eval_set=[(X[1000:], yr[1000:])],
            eval_metric="l2", early_stopping_rounds=5, verbose=False)
    assert reg.best_iteration_ is not None
    assert reg.best_iteration_ < 200


def test_ranker_fit_with_groups():
    rng = np.random.RandomState(3)
    groups = [20] * 40
    n = sum(groups)
    X = rng.randn(n, 8)
    rel = X[:, 0] + 0.5 * X[:, 1]
    y = np.clip(np.digitize(rel, [-0.5, 0.5, 1.2]), 0, 3)
    rk = lgb.LGBMRanker(n_estimators=10, num_leaves=15)
    rk.fit(X, y, group=groups, eval_set=[(X, y)], eval_group=[groups],
           eval_at=[3], verbose=False)
    scores = rk.predict(X)
    assert scores.shape == (n,)
    # scores must rank high-relevance rows above low within queries
    top = scores[y == 3].mean()
    bot = scores[y == 0].mean()
    assert top > bot


def test_plot_importance_and_metric():
    import matplotlib
    matplotlib.use("Agg")
    X, y = _xy()
    ev = {}
    train = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "metric": "auc",
                     "verbosity": -1}, train, num_boost_round=8,
                    valid_sets=[train], valid_names=["training"],
                    evals_result=ev, verbose_eval=False)
    ax = lgb.plot_importance(bst)
    assert ax is not None
    ax2 = lgb.plot_metric(ev, metric="auc")
    assert ax2 is not None


@pytest.mark.skipif(__import__("shutil").which("dot") is None,
                    reason="graphviz 'dot' executable not installed")
def test_plot_tree_renders():
    import matplotlib
    matplotlib.use("Agg")
    X, y = _xy()
    bst = lgb.train({"objective": "binary", "verbosity": -1, "num_leaves": 7},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    ax = lgb.plot_tree(bst, tree_index=1)
    assert ax is not None
