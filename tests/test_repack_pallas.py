"""In-tile partition kernel (core/repack_pallas.py) — the proven phase-1
primitive of the partition-step mega-kernel plan (docs/Performance.md
north-star section). Byte payloads must come back EXACT (every output
element is a single one-hot product), with correct per-tile left counts,
under skewed and degenerate left/right mixes."""
import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.core.repack_pallas import partition_tiles


@pytest.mark.parametrize("p_left", [0.0, 0.3, 1.0])
def test_partition_tiles_exact(p_left):
    r = np.random.RandomState(5)
    n, c, tile = 2048, 128, 256
    rows = r.randint(0, 256, (n, c)).astype(np.uint8)
    gl = (r.rand(n) < p_left)
    out, cnt = partition_tiles(jnp.asarray(rows), jnp.asarray(gl),
                               row_tile=tile, interpret=True)
    out, cnt = np.asarray(out), np.asarray(cnt)
    assert cnt.shape == (n // tile,)
    for t in range(n // tile):
        sl = slice(t * tile, (t + 1) * tile)
        g = gl[sl]
        ref = np.concatenate([rows[sl][g], rows[sl][~g]])
        np.testing.assert_array_equal(out[sl], ref)
        assert cnt[t] == int(g.sum())
