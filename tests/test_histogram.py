"""Histogram kernel properties (reference: dense_bin.hpp ConstructHistogram,
dataset.h FixHistogram; SURVEY.md §4 property tests)."""
import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.core.histogram import (build_histogram, fix_histogram,
                                         subtract_histogram)


def _ref_hist(xb, g, h, mask, b):
    n, f = xb.shape
    out = np.zeros((f, b, 3), np.float64)
    for i in range(n):
        if mask[i] == 0:
            continue
        for j in range(f):
            out[j, xb[i, j], 0] += g[i]
            out[j, xb[i, j], 1] += h[i]
            out[j, xb[i, j], 2] += 1
    return out


@pytest.mark.parametrize("impl", ["matmul", "scatter"])
def test_histogram_matches_reference_loop(impl):
    r = np.random.RandomState(0)
    n, f, b = 500, 6, 16
    xb = r.randint(0, b, (n, f)).astype(np.uint8)
    g = r.randn(n).astype(np.float32)
    h = r.rand(n).astype(np.float32)
    mask = (r.rand(n) < 0.7).astype(np.float32)
    hist = np.asarray(build_histogram(jnp.asarray(xb), jnp.asarray(g),
                                      jnp.asarray(h), jnp.asarray(mask),
                                      num_bins=b, impl=impl))
    ref = _ref_hist(xb, g, h, mask, b)
    np.testing.assert_allclose(hist, ref, rtol=1e-4, atol=1e-4)


def test_histogram_chunked_equals_unchunked():
    r = np.random.RandomState(1)
    n, f, b = 70000, 4, 32
    xb = r.randint(0, b, (n, f)).astype(np.uint8)
    g = r.randn(n).astype(np.float32)
    h = r.rand(n).astype(np.float32)
    mask = np.ones(n, np.float32)
    h1 = np.asarray(build_histogram(jnp.asarray(xb), jnp.asarray(g),
                                    jnp.asarray(h), jnp.asarray(mask),
                                    num_bins=b, row_chunk=16384))
    h2 = np.asarray(build_histogram(jnp.asarray(xb), jnp.asarray(g),
                                    jnp.asarray(h), jnp.asarray(mask),
                                    num_bins=b, row_chunk=200000))
    np.testing.assert_allclose(h1, h2, rtol=1e-3, atol=1e-2)


def test_subtraction_consistency():
    """SURVEY §4: child = parent - sibling must hold exactly in f32."""
    r = np.random.RandomState(2)
    n, f, b = 2000, 5, 16
    xb = r.randint(0, b, (n, f)).astype(np.uint8)
    g = r.randn(n).astype(np.float32)
    h = r.rand(n).astype(np.float32)
    left = (r.rand(n) < 0.5).astype(np.float32)
    parent = np.asarray(build_histogram(jnp.asarray(xb), jnp.asarray(g),
                                        jnp.asarray(h),
                                        jnp.ones(n, np.float32), num_bins=b))
    hl = np.asarray(build_histogram(jnp.asarray(xb), jnp.asarray(g),
                                    jnp.asarray(h), jnp.asarray(left),
                                    num_bins=b))
    hr = np.asarray(build_histogram(jnp.asarray(xb), jnp.asarray(g),
                                    jnp.asarray(h), jnp.asarray(1 - left),
                                    num_bins=b))
    np.testing.assert_allclose(
        np.asarray(subtract_histogram(jnp.asarray(parent), jnp.asarray(hl))),
        hr, rtol=1e-3, atol=1e-2)


def test_fix_histogram_restores_totals():
    r = np.random.RandomState(3)
    f, b = 4, 16
    hist = r.rand(f, b, 3).astype(np.float32)
    default_bins = np.array([0, 3, 5, 15], np.int32)
    sg, sh, cnt = 100.0, 50.0, 1000.0
    fixed = np.asarray(fix_histogram(jnp.asarray(hist),
                                     jnp.asarray(default_bins),
                                     jnp.float32(sg), jnp.float32(sh),
                                     jnp.float32(cnt)))
    np.testing.assert_allclose(fixed[:, :, 0].sum(1), sg, rtol=1e-5)
    np.testing.assert_allclose(fixed[:, :, 1].sum(1), sh, rtol=1e-5)
    np.testing.assert_allclose(fixed[:, :, 2].sum(1), cnt, rtol=1e-5)


def test_pallas_kernel_matches_scatter():
    """The Pallas TPU histogram kernel (core/histogram_pallas.py), in
    interpreter mode on CPU, must match the scatter reference within the
    kernel's two-term bf16 contraction budget (~1e-5 relative) — the
    GPU_DEBUG_COMPARE discipline (gpu_tree_learner.cpp:992-1010) as a
    test."""
    import jax.numpy as jnp
    from lightgbm_tpu.core.histogram import build_histogram
    r = np.random.RandomState(3)
    for (n, f, b) in [(700, 5, 16), (1500, 13, 256), (513, 8, 64)]:
        xb = r.randint(0, b, (n, f)).astype(np.uint8)
        g = r.randn(n).astype(np.float32)
        h = np.abs(r.randn(n)).astype(np.float32)
        m = (r.rand(n) > 0.4).astype(np.float32)
        ref = np.asarray(build_histogram(
            jnp.asarray(xb), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m),
            num_bins=b, impl="scatter"))
        pal = np.asarray(build_histogram(
            jnp.asarray(xb), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m),
            num_bins=b, impl="pallas_interpret"))
        np.testing.assert_allclose(pal, ref, rtol=1e-4, atol=1e-3)


def test_pallas_kernel_six_channel_matches_scatter():
    """The K=6 fused two-child channel layout (partition_and_hist) must
    come back in the right channel order from the digit-factorized kernel."""
    import jax.numpy as jnp
    from lightgbm_tpu.core.histogram import hist_tile_vals
    r = np.random.RandomState(7)
    n, f, b = 900, 9, 256
    xb = r.randint(0, b, (n, f)).astype(np.uint8)
    vals6 = r.randn(n, 6).astype(np.float32)
    ref = np.asarray(hist_tile_vals(jnp.asarray(xb), jnp.asarray(vals6),
                                    b, "scatter"))
    pal = np.asarray(hist_tile_vals(jnp.asarray(xb), jnp.asarray(vals6),
                                    b, "pallas_interpret"))
    assert pal.shape == (f, b, 6)
    np.testing.assert_allclose(pal, ref, rtol=1e-4, atol=1e-3)


def test_pallas_highest_precision_matches_scatter_tighter():
    """The full-f32 Precision.HIGHEST kernel variant (gpu_use_dp analog,
    tpu_hist_impl=pallas_highest) must match the scatter reference at least
    as tightly as the default two-term bf16 kernel — its whole point is
    users who pay 2x MXU cost for the tightest parity."""
    import jax.numpy as jnp
    from lightgbm_tpu.core.histogram import build_histogram, hist_tile_vals
    r = np.random.RandomState(11)
    n, f, b = 1200, 7, 256
    xb = r.randint(0, b, (n, f)).astype(np.uint8)
    g = r.randn(n).astype(np.float32)
    h = np.abs(r.randn(n)).astype(np.float32)
    m = (r.rand(n) > 0.3).astype(np.float32)
    ref = np.asarray(build_histogram(
        jnp.asarray(xb), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m),
        num_bins=b, impl="scatter"))
    hi = np.asarray(build_histogram(
        jnp.asarray(xb), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m),
        num_bins=b, impl="pallas_highest_interpret"))
    lo = np.asarray(build_histogram(
        jnp.asarray(xb), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m),
        num_bins=b, impl="pallas_interpret"))
    np.testing.assert_allclose(hi, ref, rtol=1e-5, atol=1e-5)
    assert np.abs(hi - ref).max() <= np.abs(lo - ref).max() + 1e-7
    # 6-channel (fused two-child) layout too
    vals6 = r.randn(n, 6).astype(np.float32)
    ref6 = np.asarray(hist_tile_vals(jnp.asarray(xb), jnp.asarray(vals6),
                                     b, "scatter"))
    hi6 = np.asarray(hist_tile_vals(jnp.asarray(xb), jnp.asarray(vals6),
                                    b, "pallas_highest_interpret"))
    np.testing.assert_allclose(hi6, ref6, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("fake_backend,plain_expected", [
    ("cpu", False), ("gpu", False), ("METAL", False), ("neuron", False),
    ("tpu", False), ("axon", False)])
def test_sort_placement_gate_is_allow_list(monkeypatch, fake_backend,
                                           plain_expected):
    """Round-4 on-chip re-measurement: the scatter loop beats the sort
    placement at the auto row_chunk even on TPU (2.31 vs 1.97 iters/s),
    so the default is off EVERYWHERE; the env var overrides both ways
    and interpret spellings opt in for CPU test coverage."""
    import jax
    from lightgbm_tpu.core import partition
    monkeypatch.delenv("LIGHTGBM_TPU_SORT_PLACEMENT", raising=False)
    monkeypatch.setattr(jax, "default_backend", lambda: fake_backend)
    sort_placement_profitable = partition.sort_placement_profitable
    assert not sort_placement_profitable("pallas", vmapped=True)
    assert sort_placement_profitable("pallas", vmapped=False) \
        == plain_expected
    assert sort_placement_profitable("matmul", vmapped=False) \
        == plain_expected
    # interpret spellings opt in so CPU tests cover the sort branch
    assert sort_placement_profitable("pallas_interpret", vmapped=False)
    assert sort_placement_profitable("pallas_highest_interpret",
                                     vmapped=False)
    monkeypatch.setenv("LIGHTGBM_TPU_SORT_PLACEMENT", "1")
    assert sort_placement_profitable("pallas", vmapped=False)
    assert not sort_placement_profitable("pallas", vmapped=True)
    monkeypatch.setenv("LIGHTGBM_TPU_SORT_PLACEMENT", "off")
    assert not sort_placement_profitable("pallas_interpret", vmapped=False)
    monkeypatch.setenv("LIGHTGBM_TPU_SORT_PLACEMENT", "bogus")
    # unrecognized spelling: warn, fall back to the backend gate
    assert sort_placement_profitable("pallas", vmapped=False) \
        == plain_expected
    assert sort_placement_profitable("pallas_interpret", vmapped=False)


def test_slot_kernel_matches_per_slot_scatter():
    """The slot-extended digit kernel (batched-frontier growth) must equal
    building each slot's histogram separately with the scatter reference."""
    import jax.numpy as jnp
    from lightgbm_tpu.core.histogram import build_histogram
    from lightgbm_tpu.core.histogram_pallas import build_histogram_slots
    r = np.random.RandomState(21)
    n, f, b, s = 1100, 6, 256, 8
    xb = r.randint(0, b, (n, f)).astype(np.uint8)
    g = r.randn(n).astype(np.float32)
    h = np.abs(r.randn(n)).astype(np.float32)
    m = (r.rand(n) > 0.2).astype(np.float32)
    slot = r.randint(0, s, n).astype(np.int32)
    vals = jnp.stack([jnp.asarray(g * m), jnp.asarray(h * m),
                      jnp.asarray(m)], axis=0)
    for highest in (False, True):
        out = np.asarray(build_histogram_slots(
            jnp.asarray(xb), jnp.asarray(slot), vals, num_bins=b, n_slots=s,
            interpret=True, highest=highest))
        assert out.shape == (s, f, b, 3)
        for si in range(s):
            msk = m * (slot == si)
            ref = np.asarray(build_histogram(
                jnp.asarray(xb), jnp.asarray(g), jnp.asarray(h),
                jnp.asarray(msk), num_bins=b, impl="scatter"))
            np.testing.assert_allclose(out[si], ref, rtol=1e-4, atol=1e-3)


def test_slot_kernel_sentinel_rows_skip_and_match():
    """slot = -1 rows contribute nothing (match no one-hot), and a row
    tile that is ALL -1 skips its compute body (pl.when) — results must
    equal the reference computed over the active prefix only."""
    import jax.numpy as jnp
    from lightgbm_tpu.core.histogram import build_histogram
    from lightgbm_tpu.core.histogram_pallas import build_histogram_slots
    r = np.random.RandomState(33)
    n, f, b, s = 6000, 4, 64, 4
    xb = r.randint(0, b, (n, f)).astype(np.uint8)
    g = r.randn(n).astype(np.float32)
    h = np.abs(r.randn(n)).astype(np.float32)
    # actives packed to the front (what tpu_batched_pack produces); the
    # tail spans multiple whole row tiles of -1
    n_active = 1500
    slot = np.full(n, -1, np.int32)
    slot[:n_active] = r.randint(0, s, n_active)
    m = np.zeros(n, np.float32)
    m[:n_active] = 1.0
    vals = jnp.stack([jnp.asarray(g * m), jnp.asarray(h * m),
                      jnp.asarray(m)], axis=0)
    out = np.asarray(build_histogram_slots(
        jnp.asarray(xb), jnp.asarray(slot), vals, num_bins=b, n_slots=s,
        interpret=True))
    for si in range(s):
        msk = (slot == si).astype(np.float32)
        ref = np.asarray(build_histogram(
            jnp.asarray(xb), jnp.asarray(g), jnp.asarray(h),
            jnp.asarray(msk), num_bins=b, impl="scatter"))
        np.testing.assert_allclose(out[si], ref, rtol=1e-4, atol=1e-3)


def _frontier_ref(xb, slot, g, h, mask, b, k):
    """Per-slot numpy reference for the frontier builder."""
    n, f = xb.shape
    out = np.zeros((k, f, b, 3), np.float64)
    for i in range(n):
        s = slot[i]
        if s < 0 or mask[i] == 0:
            continue
        for j in range(f):
            out[s, j, xb[i, j], 0] += g[i]
            out[s, j, xb[i, j], 1] += h[i]
            out[s, j, xb[i, j], 2] += mask[i]
    return out


def _frontier_data(seed=41, n=4000, f=6, b=64, k=5):
    """Random binned data with bundled/default-bin-shaped columns: column
    0 is ~90% one default bin (the EFB bundle shape — most rows carry no
    value), column 1 is a narrow 2-bin indicator."""
    r = np.random.RandomState(seed)
    xb = r.randint(0, b, (n, f)).astype(np.uint8)
    default_rows = r.rand(n) < 0.9
    xb[default_rows, 0] = 7                     # the bundle's default bin
    xb[:, 1] = r.randint(0, 2, n)               # near-empty value range
    g = r.randn(n).astype(np.float32)
    h = np.abs(r.randn(n)).astype(np.float32)
    mask = (r.rand(n) < 0.8).astype(np.float32)
    slot = r.randint(-1, k, n).astype(np.int32)  # -1 = inactive rows
    return xb, slot, g, h, mask


FRONTIER_IMPLS = ["matmul", "scatter", "pallas_interpret"]


@pytest.mark.parametrize("impl", FRONTIER_IMPLS)
def test_frontier_builder_matches_reference(impl):
    """Cross-impl equivalence property (ISSUE 2 satellite): every
    spelling of the frontier builder agrees with a per-slot reference
    loop to fp32 tolerance, including bundled/default-bin columns and
    slot = -1 (inactive) rows."""
    from lightgbm_tpu.core.histogram import build_histogram_frontier
    b, k = 64, 5
    xb, slot, g, h, mask = _frontier_data(b=b, k=k)
    out = np.asarray(build_histogram_frontier(
        jnp.asarray(xb), jnp.asarray(slot), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(mask), num_bins=b, num_slots=k, impl=impl))
    assert out.shape == (k, xb.shape[1], b, 3)
    ref = _frontier_ref(xb, slot, g, h, mask, b, k)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-3)


def test_frontier_builder_cross_impl_agreement():
    """matmul vs scatter vs pallas(.interpret) agree with each other (and
    with per-slot build_histogram masks) to fp32 tolerance."""
    from lightgbm_tpu.core.histogram import build_histogram_frontier
    b, k = 64, 5
    xb, slot, g, h, mask = _frontier_data(seed=42, b=b, k=k)
    outs = {impl: np.asarray(build_histogram_frontier(
        jnp.asarray(xb), jnp.asarray(slot), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(mask), num_bins=b, num_slots=k, impl=impl))
        for impl in FRONTIER_IMPLS}
    for impl in FRONTIER_IMPLS[1:]:
        np.testing.assert_allclose(outs[impl], outs["matmul"],
                                   rtol=1e-4, atol=1e-3)
    # and against the single-leaf builder, one mask per slot
    for si in range(k):
        msk = mask * (slot == si)
        ref = np.asarray(build_histogram(
            jnp.asarray(xb), jnp.asarray(g), jnp.asarray(h),
            jnp.asarray(msk.astype(np.float32)), num_bins=b,
            impl="scatter"))
        np.testing.assert_allclose(outs["scatter"][si], ref,
                                   rtol=1e-4, atol=1e-3)


def test_frontier_builder_chunked_equals_unchunked():
    """The lax.scan row-chunked matmul path must equal the one-shot
    path (same slots, same totals)."""
    from lightgbm_tpu.core.histogram import build_histogram_frontier
    b, k = 32, 4
    xb, slot, g, h, mask = _frontier_data(seed=43, n=5000, b=b, k=k)
    a1 = np.asarray(build_histogram_frontier(
        jnp.asarray(xb), jnp.asarray(slot), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(mask), num_bins=b, num_slots=k, row_chunk=1024,
        impl="matmul"))
    a2 = np.asarray(build_histogram_frontier(
        jnp.asarray(xb), jnp.asarray(slot), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(mask), num_bins=b, num_slots=k, row_chunk=100000,
        impl="matmul"))
    np.testing.assert_allclose(a1, a2, rtol=1e-3, atol=1e-2)
