"""obs.slo — multi-window burn-rate judgment over registry metrics.

Contracts pinned here (all under an injected clock — no sleeps):
- burn math: ``burn = bad_fraction / (1 - objective)`` from histogram
  bucket counts (latency), counter sums (availability), and counter
  rates vs a floor (throughput);
- the latency threshold is conservative: when it falls strictly inside
  a bucket the WHOLE bucket counts as bad (``le`` is inclusive, we
  cannot see inside);
- multi-window: burning requires BOTH the fast and slow windows over
  ``burn_warn`` — a brief blip trips the fast window only and stays
  quiet; early in life both windows clamp to available history so a
  sustained breach still flips within one fast window;
- flips are edge-triggered: ``note_slo_burn`` / ``on_burn`` fire once
  per quiet→burning transition, never per tick;
- a throughput floor holds its verdict at 0 until the source counter
  first moves (compile warmup must not page anyone);
- results export as ``lgbm_slo_*`` gauges on the same registry.
"""
import math

from lightgbm_tpu.obs.registry import MetricsRegistry
from lightgbm_tpu.obs.slo import SloEngine, _histogram_totals


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


class StubMonitor:
    def __init__(self):
        self.calls = []

    def note_slo_burn(self, slo, **kw):
        self.calls.append((slo, kw))


def _engine(reg, clock, fast=10.0, slow=60.0, warn=2.0, monitor=None,
            on_burn=None):
    return SloEngine(registry=reg, fast_window_s=fast, slow_window_s=slow,
                     burn_warn=warn, monitor=monitor, on_burn=on_burn,
                     time_fn=clock)


# ------------------------------------------------------------ latency SLO
def test_latency_burn_and_edge_triggered_flip():
    reg = MetricsRegistry()
    hist = reg.histogram("lat_ms", "latency", buckets=[50.0, 500.0])
    clock = FakeClock()
    mon = StubMonitor()
    eng = _engine(reg, clock, monitor=mon)
    eng.add_latency_slo("p99", "lat_ms", threshold_ms=50.0, objective=0.99)

    # healthy phase: 100 requests under threshold over 60s
    for _ in range(10):
        for _ in range(10):
            hist.observe(10.0)
        eng.tick()
        clock.advance(6.0)
    st = eng.evaluate()
    assert st["slos"]["p99"]["fast_burn"] == 0.0
    assert not st["slos"]["p99"]["burning"] and mon.calls == []

    # sustained breach: 50% of traffic over threshold → bad_frac 0.5,
    # budget 0.01 → burn 50x on both (clamped) windows
    for _ in range(12):
        hist.observe(10.0)
        hist.observe(200.0)
        eng.tick()
        clock.advance(6.0)
    st = eng.evaluate()
    doc = st["slos"]["p99"]
    assert doc["burning"]
    assert doc["fast_burn"] >= 40.0 and doc["slow_burn"] >= 2.0
    assert len(mon.calls) == 1 and mon.calls[0][0] == "p99"
    assert mon.calls[0][1]["kind"] == "latency"

    # still burning on later ticks: no re-fire (edge-triggered)
    hist.observe(200.0)
    eng.tick()
    eng.evaluate()
    assert len(mon.calls) == 1
    assert eng.burning("p99")


def test_latency_threshold_inside_bucket_is_conservative():
    reg = MetricsRegistry()
    hist = reg.histogram("lat_ms", "latency", buckets=[10.0, 100.0])
    hist.observe(20.0)     # lands in the le=100 bucket
    total, over = _histogram_totals(reg, "lat_ms", 50.0)
    assert (total, over) == (1.0, 1.0)    # whole bucket counts as bad
    # on the exact bucket bound le is inclusive: 20ms <= le=100 is good
    total, over = _histogram_totals(reg, "lat_ms", 100.0)
    assert (total, over) == (1.0, 0.0)


def test_latency_aggregates_across_label_sets():
    reg = MetricsRegistry()
    reg.histogram("lat_ms", "l", labels={"sink": "a"},
                  buckets=[50.0]).observe(10.0)
    reg.histogram("lat_ms", "l", labels={"sink": "b"},
                  buckets=[50.0]).observe(999.0)
    total, over = _histogram_totals(reg, "lat_ms", 50.0)
    assert (total, over) == (2.0, 1.0)


# ------------------------------------------------------- availability SLO
def test_availability_burn_from_counters():
    reg = MetricsRegistry()
    req = reg.counter("req_total", "r")
    err = reg.counter("err_total", "e")
    shed = reg.counter("shed_total", "s")
    clock = FakeClock()
    eng = _engine(reg, clock, warn=2.0)
    eng.add_availability_slo("avail", "req_total",
                             bad=["err_total", "shed_total"],
                             objective=0.999)
    eng.tick()
    clock.advance(5.0)
    req.inc(990)
    err.inc(6)
    shed.inc(4)
    eng.tick()
    st = eng.evaluate()
    doc = st["slos"]["avail"]
    # bad_frac = 10/1000 = 0.01, budget 0.001 → burn 10x
    assert math.isclose(doc["fast_burn"], 10.0, rel_tol=1e-6)
    assert math.isclose(doc["observed"], 0.01, rel_tol=1e-6)
    assert doc["burning"]


# -------------------------------------------------------- throughput floor
def test_throughput_floor_holds_verdict_until_rows_flow():
    reg = MetricsRegistry()
    rows = reg.counter("rows_total", "rows")
    clock = FakeClock()
    mon = StubMonitor()
    eng = _engine(reg, clock, monitor=mon)
    eng.add_throughput_slo("tput", "rows_total", floor_per_s=1000.0)

    # compile warmup: ticks pass, counter never moves → burn pinned at 0
    for _ in range(5):
        eng.tick()
        clock.advance(5.0)
    st = eng.evaluate()
    assert st["slos"]["tput"]["fast_burn"] == 0.0
    assert not st["slos"]["tput"]["burning"] and mon.calls == []

    # trainer starts, but slow: 100 rows/s vs 1000 floor → burn 10x
    rows.inc(500)
    eng.tick()
    clock.advance(5.0)
    rows.inc(500)
    eng.tick()
    st = eng.evaluate()
    doc = st["slos"]["tput"]
    assert doc["fast_burn"] >= 2.0 and doc["burning"]
    assert len(mon.calls) == 1 and mon.calls[0][1]["kind"] == "throughput"

    # healthy rate clears the burn
    clock.advance(5.0)
    rows.inc(50000)
    eng.tick()
    st = eng.evaluate()
    assert st["slos"]["tput"]["fast_burn"] < 2.0


# ----------------------------------------------------------- multi-window
def test_brief_blip_trips_fast_window_only():
    reg = MetricsRegistry()
    hist = reg.histogram("lat_ms", "latency", buckets=[50.0, 500.0])
    clock = FakeClock()
    eng = _engine(reg, clock, fast=5.0, slow=120.0)
    eng.add_latency_slo("p99", "lat_ms", threshold_ms=50.0, objective=0.99)
    # two minutes of healthy history
    for _ in range(40):
        for _ in range(20):
            hist.observe(10.0)
        eng.tick()
        clock.advance(3.0)
    # a 3s blip of pure badness
    for _ in range(5):
        hist.observe(200.0)
    eng.tick()
    st = eng.evaluate()
    doc = st["slos"]["p99"]
    assert doc["fast_burn"] >= 2.0          # fast window sees the blip
    assert doc["slow_burn"] < 2.0           # diluted over 2 minutes
    assert not doc["burning"]               # and so: no page


def test_early_life_windows_clamp_to_history():
    reg = MetricsRegistry()
    hist = reg.histogram("lat_ms", "latency", buckets=[50.0, 500.0])
    clock = FakeClock()
    eng = _engine(reg, clock, fast=300.0, slow=3600.0)
    eng.add_latency_slo("p99", "lat_ms", threshold_ms=50.0, objective=0.99)
    eng.tick()
    clock.advance(2.0)
    hist.observe(200.0)
    eng.tick()
    st = eng.evaluate()
    doc = st["slos"]["p99"]
    # 2 seconds into the process's life, both windows judge the same 2s
    assert doc["fast_span_s"] == doc["slow_span_s"]
    assert doc["burning"]                   # sustained-from-birth breach


def test_history_ring_trims_past_slow_window():
    reg = MetricsRegistry()
    reg.counter("rows_total", "rows")
    clock = FakeClock()
    eng = _engine(reg, clock, fast=5.0, slow=30.0)
    eng.add_throughput_slo("tput", "rows_total", floor_per_s=1.0)
    for _ in range(500):
        eng.tick()
        clock.advance(1.0)
    assert len(eng._history) < 40           # ring, not unbounded growth


# ---------------------------------------------------------------- exports
def test_gauges_and_status_shape():
    reg = MetricsRegistry()
    reg.counter("req_total", "r").inc(10)
    reg.counter("err_total", "e")
    clock = FakeClock()
    fired = []
    eng = _engine(reg, clock,
                  on_burn=lambda name, **kw: fired.append((name, kw)))
    eng.add_availability_slo("avail", "req_total", bad=["err_total"],
                             objective=0.99, description="serve avail")
    st = eng.status()                       # tick + evaluate in one
    assert set(st) == {"slos", "burn_warn", "fast_window_s",
                       "slow_window_s"}
    doc = st["slos"]["avail"]
    for key in ("kind", "objective", "fast_burn", "slow_burn", "observed",
                "fast_span_s", "slow_span_s", "burning", "description"):
        assert key in doc
    text = reg.prometheus_text()
    assert 'lgbm_slo_burn_rate{slo="avail",window="fast"}' in text
    assert 'lgbm_slo_burning{slo="avail"} 0' in text
    assert 'lgbm_slo_value{slo="avail"}' in text
    assert fired == []                      # healthy → callback untouched

    # flip it and check the on_burn callback fires with the numbers
    reg.counter("err_total", "e").inc(10)
    eng.tick()
    eng.evaluate()
    assert len(fired) == 1 and fired[0][0] == "avail"
    assert fired[0][1]["fast_burn"] >= 2.0
    assert 'lgbm_slo_burning{slo="avail"} 1' in reg.prometheus_text()


def test_evaluate_with_no_history_is_safe():
    eng = _engine(MetricsRegistry(), FakeClock())
    eng.add_latency_slo("p99", "lat_ms", threshold_ms=50.0)
    assert eng.evaluate()["slos"] == {}
    assert not eng.burning("p99")


def test_broken_monitor_never_breaks_judging():
    class ExplodingMonitor:
        def note_slo_burn(self, *a, **k):
            raise RuntimeError("pager is down")

    reg = MetricsRegistry()
    req = reg.counter("req_total", "r")
    err = reg.counter("err_total", "e")
    clock = FakeClock()
    eng = _engine(reg, clock, monitor=ExplodingMonitor())
    eng.add_availability_slo("avail", "req_total", bad=["err_total"],
                             objective=0.99)
    eng.tick()
    clock.advance(5.0)
    req.inc(1)
    err.inc(1)
    eng.tick()
    st = eng.evaluate()                     # must not raise
    assert st["slos"]["avail"]["burning"]
