"""Chunks x chips: out-of-core streaming composed with the mesh learners
(stream/grow_stream.py mesh mode, stream/pipeline.py ShardedChunkPipeline,
gbdt._setup_stream_mesh).

Contracts pinned here (the 2-process leg lives in
tools/dist_train_smoke.py --only stream, this file runs on the 8
virtual-device single-process mesh + LoopbackComm thread ranks):

- sharded streamed training is STRUCTURE-IDENTICAL to serial streamed
  training for both learner schedules (data reduce-scatter, voting),
  including ragged last chunks, label-sorted (distribution-skewed)
  shards, column counts that need padding for the reduce-scatter tile,
  and multiclass;
- voting with top_k >= F degenerates to the exact data-parallel search;
- every unsupported-combo gate refuses BY NAME (config spelling gates +
  the gbdt topology gates) instead of the old blanket refusal;
- sharded ingest reproduces the in-memory loader's drift profile
  bit-identically (per-shard bin-occupancy counts summed over the comm);
- the checkpoint fingerprint folds rank-ordered shard digests: identical
  layout reproduces it, a reshuffled shard assignment refuses resume;
- kill-and-resume under the mesh is byte-identical;
- the compiled-program count is invariant in chunk count under the mesh.
"""
import hashlib
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.log import LightGBMError

from test_stream import _BASE, _data, _struct



def _train(params, X, y, rounds=4, **dskw):
    return lgb.train(dict(params), lgb.Dataset(X, label=y, **dskw),
                     num_boost_round=rounds)


def _mesh_params(extra=None, chunk_rows=160, mesh=2, learner="data"):
    p = dict(_BASE, data_stream_chunk_rows=chunk_rows,
             tree_learner=learner, mesh_shape=[mesh],
             num_machines=mesh)
    p.update(extra or {})
    return p


# ---------------------------------------------- structure identity
@pytest.mark.slow
def test_data_mesh_structure_identical_to_serial_streamed():
    X, y = _data(n=700, f=12)
    serial = _struct(_train(dict(_BASE, data_stream_chunk_rows=160),
                            X, y).model_to_string())
    meshed = _struct(_train(_mesh_params(), X, y).model_to_string())
    assert serial == meshed


def test_voting_mesh_structure_identical_small_topk():
    X, y = _data(n=700, f=12)
    serial = _struct(_train(dict(_BASE, data_stream_chunk_rows=160),
                            X, y).model_to_string())
    meshed = _struct(_train(_mesh_params({"top_k": 4}, learner="voting"),
                            X, y).model_to_string())
    assert serial == meshed


def test_voting_topk_ge_features_degenerates_to_data_parallel():
    """top_k >= F elects every feature: the vote is a no-op and the
    committed trees match the exact data-parallel (== serial) search."""
    X, y = _data(n=700, f=13)
    serial = _struct(_train(dict(_BASE, data_stream_chunk_rows=96),
                            X, y).model_to_string())
    meshed = _struct(_train(
        _mesh_params({"top_k": 13}, chunk_rows=96, learner="voting"),
        X, y).model_to_string())
    assert serial == meshed


def test_mesh4_ragged_chunks_and_column_padding():
    """mesh=4 with 13 stored columns forces the reduce-scatter column
    pad (13 % 4 != 0) AND a ragged last chunk per shard (701 rows)."""
    X, y = _data(n=701, f=13)
    serial = _struct(_train(dict(_BASE, data_stream_chunk_rows=96),
                            X, y).model_to_string())
    meshed = _struct(_train(_mesh_params(chunk_rows=96, mesh=4),
                            X, y).model_to_string())
    assert serial == meshed


@pytest.mark.slow
def test_label_sorted_rows_skewed_shards_identical():
    """Label-sorted rows deal each shard a maximally skewed class
    distribution (shard 0 almost all negatives); histograms are summed
    across the mesh before any decision, so structure must not move."""
    X, y = _data(n=900, f=10, seed=3)
    order = np.argsort(y, kind="stable")
    X, y = X[order], y[order]
    serial = _struct(_train(dict(_BASE, data_stream_chunk_rows=128),
                            X, y).model_to_string())
    for learner, extra in (("data", None), ("voting", {"top_k": 4})):
        meshed = _struct(_train(
            _mesh_params(extra, chunk_rows=128, learner=learner),
            X, y).model_to_string())
        assert serial == meshed, learner


def test_multiclass_mesh_identical():
    # 2 rounds x 3 classes: structure identity holds at these seeds;
    # deeper runs can legitimately diverge on f32 gain near-ties (the
    # documented chunked-accumulation boundary, docs/OutOfCore.md)
    r = np.random.RandomState(3)
    n, f = 701, 13
    X = r.randn(n, f)
    y3 = r.randint(0, 3, n).astype(np.float64)
    p = dict(_BASE, objective="multiclass", num_class=3,
             data_stream_chunk_rows=96)
    serial = _struct(_train(p, X, y3, rounds=2).model_to_string())
    meshed = _struct(_train(dict(p, tree_learner="data", mesh_shape=[2],
                                 num_machines=2), X, y3,
                            rounds=2).model_to_string())
    assert serial == meshed


def test_chunk_count_invariance_of_structure_under_mesh():
    """Same rows at 2 vs 4 chunks per shard commit identical structure
    (histograms are additive over chunks; the collective fires once per
    wave either way)."""
    X, y = _data(n=640, f=8)
    a = _struct(_train(_mesh_params(chunk_rows=160), X, y)
                .model_to_string())
    b = _struct(_train(_mesh_params(chunk_rows=80), X, y)
                .model_to_string())
    assert a == b


def test_compiled_program_count_invariant_in_chunk_count():
    """Fresh boosters at 2 vs 4 chunks/shard compile the same NUMBER of
    programs (fixed-shape per-chunk kernels; chunk count only changes
    how often each one runs)."""
    from lightgbm_tpu.profiling import (backend_compile_count,
                                        install_compile_hook)
    install_compile_hook()
    X, y = _data(n=640, f=8)
    _train(_mesh_params(chunk_rows=320), X, y, rounds=2)  # warm helpers
    c0 = backend_compile_count()
    _train(_mesh_params(chunk_rows=160), X, y, rounds=2)
    c2 = backend_compile_count() - c0
    c0 = backend_compile_count()
    _train(_mesh_params(chunk_rows=80), X, y, rounds=2)
    c4 = backend_compile_count() - c0
    assert c4 - c2 == 0, (c2, c4)


# ---------------------------------------------- gates, each by name
def test_gate_streamed_feature_learner():
    with pytest.raises(LightGBMError, match="streamed\\+feature-learner"):
        Config(dict(_BASE, data_stream_chunk_rows=100,
                    tree_learner="feature", mesh_shape=[2]))


def test_gate_streamed_mesh_f64():
    with pytest.raises(LightGBMError, match="streamed-mesh\\+f64"):
        Config(dict(_BASE, data_stream_chunk_rows=100, gpu_use_dp=True,
                    tree_learner="data", mesh_shape=[2]))


def test_gate_streamed_f64_without_mesh():
    with pytest.raises(LightGBMError, match="gpu_use_dp"):
        Config(dict(_BASE, data_stream_chunk_rows=100, gpu_use_dp=True))


def test_gate_streamed_feature_axis_mesh():
    X, y = _data(n=400, f=8)
    with pytest.raises(LightGBMError, match="feature axis"):
        _train(dict(_BASE, data_stream_chunk_rows=100,
                    tree_learner="data", mesh_shape=[2, 2]), X, y,
               rounds=1)


def test_gate_sharded_dataset_without_mesh():
    sds = _sharded_ingest_pair(dict(_BASE, data_stream_chunk_rows=100))
    with pytest.raises(LightGBMError, match="no mesh is configured"):
        _train_binned(sds[0], dict(_BASE, data_stream_chunk_rows=100))


def test_gate_shard_world_mesh_size_mismatch():
    p = dict(_BASE, data_stream_chunk_rows=100, tree_learner="data",
             mesh_shape=[4], num_machines=4)
    sds = _sharded_ingest_pair(p)
    with pytest.raises(LightGBMError,
                       match="must equal the data-axis size"):
        _train_binned(sds[0], p)


def test_gate_sharded_single_process_shard_mismatch():
    """A 2-way-sharded dataset on a single-process mesh of 2: the one
    process addresses BOTH mesh positions but holds only shard 0's
    chunks — the pipeline refuses the topology."""
    p = dict(_BASE, data_stream_chunk_rows=100, tree_learner="data",
             mesh_shape=[2], num_machines=2)
    sds = _sharded_ingest_pair(p)
    with pytest.raises(LightGBMError):
        _train_binned(sds[0], p)


# ---------------------------------------------- sharded-ingest helpers
def _sharded_ingest_pair(params, X=None, y=None, offsets=None):
    """Ingest the same data as 2 LoopbackComm thread ranks; returns the
    per-rank StreamedDatasets (collective-capable: their shard_comm is
    the live loopback group, so later collective calls must run in BOTH
    threads — see _collective_pair)."""
    from lightgbm_tpu.parallel.network import LoopbackComm
    from lightgbm_tpu.stream.sampler import ingest
    from lightgbm_tpu.stream.source import ArraySource, ShardedSource
    if X is None:
        X, y = _data(n=600, f=6)
    cfg = Config(dict(params))
    comms = LoopbackComm.group(2, timeout_s=30)
    out = [None, None]
    err = []

    def run(rank):
        try:
            src = ShardedSource(
                ArraySource(X, label=y, chunk_rows=90), rank, 2,
                offsets=offsets)
            out[rank] = ingest(src, cfg, comm=comms[rank])
        except BaseException as e:  # noqa: BLE001 - surfaced below
            comms[rank].abort()
            err.append((rank, e))

    ts = [threading.Thread(target=run, args=(r,)) for r in (0, 1)]
    [t.start() for t in ts]
    [t.join(60) for t in ts]
    assert not err, err
    return out


def _collective_pair(sds, fn):
    """Run ``fn(rank, sd)`` in both thread ranks (lockstep, so comm
    collectives inside fn line up); returns [result0, result1]."""
    out = [None, None]
    err = []

    def run(rank):
        try:
            out[rank] = fn(rank, sds[rank])
        except BaseException as e:  # noqa: BLE001 - surfaced below
            sds[rank].shard_comm.abort()
            err.append((rank, e))

    ts = [threading.Thread(target=run, args=(r,)) for r in (0, 1)]
    [t.start() for t in ts]
    [t.join(60) for t in ts]
    assert not err, err
    return out


def _train_binned(sd, params):
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.objectives import create_objective
    cfg = Config(dict(params))
    b = create_boosting(cfg, sd, create_objective(cfg), [])
    b.train_one_iter()
    return b


# ---------------------------------------------- drift profile parity
def test_sharded_drift_profile_matches_in_memory_loader():
    from lightgbm_tpu.obs.drift import DataProfile
    X, y = _data(n=900, f=6, seed=8)
    p = dict(_BASE, data_stream_chunk_rows=150)
    full = lgb.Dataset(X, label=y, params=dict(_BASE)) \
        .construct()._binned
    want = DataProfile.from_binned_dataset(full)
    sds = _sharded_ingest_pair(p, X=X, y=y)
    profs = _collective_pair(sds, lambda rank, sd: sd.data_profile())
    for prof in profs:
        assert prof.num_data == want.num_data
        assert prof.features == want.features   # bit-identical counts


# ---------------------------------------------- fingerprint semantics
def _fingerprints(sds):
    from lightgbm_tpu.checkpoint.snapshot import dataset_fingerprint
    return _collective_pair(sds,
                            lambda rank, sd: dataset_fingerprint(sd))


def test_sharded_fingerprint_accepts_identical_layout():
    X, y = _data(n=600, f=6, seed=4)
    p = dict(_BASE, data_stream_chunk_rows=100)
    fp_a = _fingerprints(_sharded_ingest_pair(p, X=X, y=y))
    fp_b = _fingerprints(_sharded_ingest_pair(p, X=X, y=y))
    # every rank computes the SAME folded fingerprint, and the identical
    # layout reproduces it exactly across runs
    assert fp_a[0] == fp_a[1] == fp_b[0] == fp_b[1]


def test_sharded_fingerprint_refuses_reshuffled_shards():
    """Same global rows dealt to the ranks at a different boundary: the
    rank-ordered (rank, digest, rows) folding must change, so resume
    refuses the reshuffled assignment."""
    from lightgbm_tpu.checkpoint.snapshot import check_compatibility
    X, y = _data(n=600, f=6, seed=4)
    p = dict(_BASE, data_stream_chunk_rows=100)
    fp_even = _fingerprints(_sharded_ingest_pair(p, X=X, y=y))
    skew = _sharded_ingest_pair(p, X=X, y=y, offsets=[0, 150, 600])
    fp_skew = _fingerprints(skew)
    assert fp_skew[0] == fp_skew[1]
    assert fp_even[0] != fp_skew[0]
    # the fingerprint is cached on the dataset after _fingerprints, so
    # the compatibility check below runs comm-free on one rank
    with pytest.raises(LightGBMError, match="different dataset"):
        check_compatibility({"dataset_fingerprint": fp_even[0]},
                            Config(dict(p)), skew[0])


# ---------------------------------------------- checkpoint resume
@pytest.mark.slow
def test_mesh_streamed_resume_byte_identical(tmp_path):
    from lightgbm_tpu import callback, engine
    X, y = _data(n=700, f=8)
    p = _mesh_params(chunk_rows=128)

    def run(ckpt, rounds, resume=False):
        ds = lgb.Dataset(X, label=y, params=dict(p))
        return engine.train(dict(p), ds, num_boost_round=rounds,
                            callbacks=[callback.checkpoint(ckpt,
                                                           period=1)],
                            resume_from=(ckpt if resume else None),
                            verbose_eval=False)

    golden = run(str(tmp_path / "g"), 5)
    run(str(tmp_path / "i"), 2)
    resumed = run(str(tmp_path / "i"), 5, resume=True)
    assert golden.model_to_string() == resumed.model_to_string()


# ---------------------------------------------- pipeline unit seams
def test_split_chunks_rows_and_padded_layout_roundtrip():
    from lightgbm_tpu.stream.pipeline import (shard_rows_host,
                                              shard_rows_perm,
                                              split_chunks_rows)
    r = np.random.RandomState(0)
    chunks = [r.randint(0, 9, (c, 3)).astype(np.uint8)
              for c in (50, 31, 19)]
    flat = np.concatenate(chunks)
    offsets = [0, 23, 100]                      # skewed 23 / 77 split
    per_shard = split_chunks_rows(chunks, offsets)
    assert [sum(c.shape[0] for c in s) for s in per_shard] == [23, 77]
    np.testing.assert_array_equal(
        np.concatenate([c for s in per_shard for c in s]), flat)

    vals = r.randn(100).astype(np.float32)
    local_padded = 80                           # both shards fit in 80
    padded = shard_rows_host(vals, offsets, local_padded)
    assert padded.shape == (160,)
    perm = shard_rows_perm(offsets, local_padded)
    np.testing.assert_array_equal(padded[perm], vals)
    # rows outside every shard's block are exactly zero
    mask = np.ones(160, bool)
    mask[perm] = False
    assert not np.any(padded[mask])


def test_train_set_metric_eval_under_single_process_mesh():
    """get_eval_at(0) must unpermute the shard-major padded scores back
    to original row order — pinned by matching the serial streamed
    metric exactly."""
    from lightgbm_tpu import engine
    X, y = _data(n=600, f=8)

    def logloss(params):
        ev = {}
        ds = lgb.Dataset(X, label=y, params=dict(params))
        engine.train(dict(params, metric="binary_logloss"), ds,
                     num_boost_round=3, valid_sets=[ds],
                     valid_names=["train"], evals_result=ev,
                     verbose_eval=False)
        return ev["train"]["binary_logloss"]

    serial = logloss(dict(_BASE, data_stream_chunk_rows=128))
    meshed = logloss(_mesh_params(chunk_rows=128))
    np.testing.assert_allclose(serial, meshed, rtol=1e-6)


def test_streamed_wave_collective_schedule_pinned():
    """The chunks-x-chips comm contract, statically: one traced growth
    wave carries exactly ONE extra collective over the in-memory learner
    schedule — the int32 psum'd continue flag — and its f32 payload
    equals the in-memory per-wave payload (streaming adds zero f32
    traffic). Mirrors the stream_dist_* perf-gate pins."""
    import jax

    from lightgbm_tpu.analysis import jaxpr_audit

    expected = {"data": 3, "voting": 4}
    for name, overrides in (("data", {"frontier_rs": True}),
                            ("voting", {"voting_top_k": 2})):
        entry = jaxpr_audit.streamed_sharded_fn(param_overrides=overrides,
                                                num_features=16)
        assert entry is not None          # conftest forces 8 devices
        fn, args, _ = entry
        sched = jaxpr_audit.collective_schedule(jax.make_jaxpr(fn)(*args))
        assert len(sched) == expected[name], (name, sched)
        # exactly one int32 collective: the replicated continue flag
        int_ops = [s for s in sched
                   if all("float32" not in o for o in s["operands"])]
        assert len(int_ops) == (1 if name == "data" else 3), (name, sched)
