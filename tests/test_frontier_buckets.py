"""Wave-width-adaptive frontier histograms + persistent compile cache (PR 4).

Contracts pinned here:
- the shared pow-2 bucketing module (lightgbm_tpu/bucketing.py) is the
  single source of truth for serving row buckets AND frontier wave widths,
  with the frontier cap clamped by max_depth (frontier <= 2^(d-1));
- bucketed frontier growth is STRUCTURE-IDENTICAL to fixed-width growth —
  same splits, same node numbering, same leaf values — on dense, EFB,
  categorical, and sharded skewed inputs (the lax.switch over the width
  ladder only changes padding, never the committed top_k prefix);
- one bucketed frontier pass equals per-leaf build_histogram per slot, at
  every ladder width and on both hist impls;
- phase_probe reports wave occupancy and the compile-cache counters, and
  the occupancy-weighted slot-sweep count stays within 2x of num_leaves;
- training performs zero XLA backend compiles after the warmup ladder;
- checkpoint resume stays byte-identical with tree_growth=frontier.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import callback, engine
from lightgbm_tpu.bucketing import (frontier_max_width, pow2_bucket,
                                    pow2_ladder, wave_width_bucket,
                                    wave_width_ladder)
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.log import LightGBMError
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.boosting import create_boosting

from conftest import make_binary


def _train(X, y, params, rounds=3, **ds_kw):
    cfg = Config(params)
    ds = BinnedDataset.from_matrix(X, cfg, label=y, **ds_kw)
    b = create_boosting(cfg, ds, create_objective(cfg), [])
    for _ in range(rounds):
        if b.train_one_iter():
            break
    return b


def _golden_data():
    """Same tie-free dataset as test_grow_frontier._golden_data."""
    rng = np.random.default_rng(0)
    n = 600
    X = rng.normal(size=(n, 6))
    logit = (1.5 * X[:, 0] + 1.0 * X[:, 1] - 0.8 * X[:, 2]
             + 0.5 * X[:, 3] * X[:, 4])
    y = (logit + 0.3 * rng.normal(size=n) > 0).astype(np.float64)
    return X.astype(np.float32), y


def _assert_same_trees(bb, bf, num=3):
    """Bucketed and fixed-width must agree on NUMBERING, not just the split
    multiset — the stable top_k prefix is width-independent."""
    for tb, tf in zip(bb.models[:num], bf.models[:num]):
        assert tb.num_leaves == tf.num_leaves
        nn = tb.num_leaves - 1
        np.testing.assert_array_equal(np.asarray(tb.split_feature[:nn]),
                                      np.asarray(tf.split_feature[:nn]))
        np.testing.assert_array_equal(np.asarray(tb.threshold_bin[:nn]),
                                      np.asarray(tf.threshold_bin[:nn]))
        np.testing.assert_array_equal(np.asarray(tb.left_child[:nn]),
                                      np.asarray(tf.left_child[:nn]))
        np.testing.assert_array_equal(
            np.asarray(tb.leaf_count[:tb.num_leaves]),
            np.asarray(tf.leaf_count[:tf.num_leaves]))
        np.testing.assert_allclose(
            np.asarray(tb.leaf_value[:tb.num_leaves]),
            np.asarray(tf.leaf_value[:tf.num_leaves]), rtol=1e-6, atol=1e-9)


# --------------------------------------------------------- bucketing unit
def test_pow2_bucket_and_ladder():
    assert [pow2_bucket(n) for n in (1, 2, 3, 5, 8, 9)] == [1, 2, 4, 8, 8, 16]
    assert pow2_bucket(100, cap=30) == 30
    assert pow2_bucket(3, min_bucket=16) == 16
    # the ladder always ends exactly at the (possibly non-pow-2) cap
    assert pow2_ladder(1, 30) == [1, 2, 4, 8, 16, 30]
    assert pow2_ladder(16, 16) == [16]
    # every bucket the bucket function can return is on the ladder
    for n in range(1, 31):
        assert pow2_bucket(n, cap=30) in pow2_ladder(1, 30)


def test_frontier_max_width_clamps_by_depth():
    # the satellite bugfix: a depth-d tree's frontier holds <= 2^(d-1)
    # leaves, so 255 leaves at max_depth=3 never needs more than 4 lanes
    assert frontier_max_width(255, 3) == 4
    assert frontier_max_width(255) == 254
    assert frontier_max_width(255, -1) == 254
    assert frontier_max_width(31, 1) == 1
    assert frontier_max_width(2, 10) == 1
    assert wave_width_ladder(255, 3) == [1, 2, 4]
    assert wave_width_ladder(64, 4) == [1, 2, 4, 8]
    assert wave_width_ladder(31) == [1, 2, 4, 8, 16, 30]
    # occupancy accounting mirrors the switch: live snaps up, never past cap
    assert wave_width_bucket(5, 31) == 8
    assert wave_width_bucket(20, 31) == 30
    assert wave_width_bucket(20, 255, 3) == 4


def test_serving_buckets_ride_shared_module():
    from lightgbm_tpu.serving.predictor import bucket_rows, bucket_sizes
    assert bucket_rows(5) == pow2_bucket(5, 16, 4096)
    assert bucket_sizes(16, 100) == pow2_ladder(16, 100)
    with pytest.raises(LightGBMError):
        bucket_rows(0)


# ------------------------------------------------------------ config knobs
def test_config_compile_cache_and_bucketing_knobs(tmp_path):
    assert Config({}).tpu_frontier_bucketing is True
    assert Config({"frontier_bucketing": False}).tpu_frontier_bucketing \
        is False
    d = str(tmp_path / "cache")
    for alias in ("compile_cache_dir", "compilation_cache_dir",
                  "jax_compilation_cache_dir"):
        assert Config({alias: d}).compile_cache_dir == d
    f = tmp_path / "a_file"
    f.write_text("x")
    with pytest.raises(LightGBMError, match="compile_cache_dir"):
        Config({"compile_cache_dir": str(f)})


# --------------------------------------------------- per-wave hist property
@pytest.mark.parametrize("impl", ["matmul", "scatter"])
def test_bucketed_wave_hist_matches_per_leaf(impl):
    """One frontier pass at ANY ladder width == per-leaf build_histogram
    per slot; the padding lanes stay exactly zero."""
    import jax.numpy as jnp
    from lightgbm_tpu.core.histogram import (build_histogram,
                                             build_histogram_frontier)
    r = np.random.RandomState(1)
    n, f, bins, live = 512, 4, 16, 5
    xb = jnp.asarray(r.randint(0, bins, (n, f)), jnp.uint8)
    slot = jnp.asarray(r.randint(-1, live, n), jnp.int32)  # -1 = inactive
    g = jnp.asarray(r.randn(n), jnp.float32)
    h = jnp.asarray(r.rand(n) + 0.5, jnp.float32)
    mask = jnp.asarray((r.rand(n) < 0.8), jnp.float32)
    for width in wave_width_ladder(live + 1):     # 1, 2, 4, 5
        if width < live:
            continue                               # caller-guaranteed fit
        hist = np.asarray(build_histogram_frontier(
            xb, slot, g, h, mask, bins, num_slots=width, impl=impl))
        assert hist.shape == (width, f, bins, 3)
        for k in range(live):
            ref = np.asarray(build_histogram(
                xb, g, h, mask * (np.asarray(slot) == k), bins, impl=impl))
            np.testing.assert_allclose(hist[k], ref, rtol=1e-5, atol=1e-5)
        assert not hist[live:].any()


# ----------------------------------------------- structure identity golden
def test_bucketed_matches_fixed_width_dense():
    X, y = _golden_data()
    base = {"objective": "binary", "num_leaves": 64, "max_depth": 4,
            "min_data_in_leaf": 40, "verbosity": -1,
            "tree_growth": "frontier"}
    bf = _train(X, y, dict(base, tpu_frontier_bucketing=False))
    bb = _train(X, y, dict(base))                  # bucketing is the default
    _assert_same_trees(bb, bf)
    np.testing.assert_array_equal(bb.predict(X, raw_score=True),
                                  bf.predict(X, raw_score=True))
    # and both still match exact growth (the pre-existing golden contract)
    be = _train(X, y, dict(base, tree_growth="exact"))
    np.testing.assert_allclose(be.predict(X, raw_score=True),
                               bb.predict(X, raw_score=True),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.slow
@pytest.mark.slow
def test_bucketed_matches_fixed_width_efb():
    """Exclusive sparse blocks: EFB bundling rewrites the column layout the
    wave sweeps, so pin identity on the bundled path too."""
    r = np.random.RandomState(3)
    n, groups, per = 1500, 4, 5
    X = np.zeros((n, groups * per))
    for gidx in range(groups):
        which = r.randint(0, per + 1, n)
        vals = r.randint(1, 9, n).astype(np.float64)
        for k in range(per):
            X[which == k, gidx * per + k] = vals[which == k]
    y = ((X[:, 0] + X[:, per] - X[:, 2 * per] + 0.5 * r.randn(n))
         > 1.0).astype(np.float32)
    base = {"objective": "binary", "num_leaves": 31, "min_data_in_leaf": 5,
            "verbosity": -1, "tree_growth": "frontier"}
    bf = _train(X, y, dict(base, tpu_frontier_bucketing=False))
    bb = _train(X, y, dict(base))
    _assert_same_trees(bb, bf)


def test_bucketed_matches_fixed_width_categorical():
    r = np.random.RandomState(5)
    n = 800
    cat = r.randint(0, 12, n)
    x2 = r.randn(n)
    effect = np.where(np.isin(cat, [1, 3, 5, 8]), 2.0, -2.0)
    y = (effect + 0.5 * x2 + 0.3 * r.randn(n) > 0).astype(np.float64)
    X = np.column_stack([cat.astype(np.float64), x2])
    base = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
            "tree_growth": "frontier", "categorical_feature": "0",
            "min_data_per_group": 10}
    bf = _train(X, y, dict(base, tpu_frontier_bucketing=False))
    bb = _train(X, y, dict(base))
    _assert_same_trees(bb, bf)


@pytest.mark.slow
def test_bucketed_matches_fixed_width_sharded_skewed():
    """Row-sorted 8-shard data parallel: most (slot, shard) pairs own zero
    rows, the regime where the switch must still pick ONE width on every
    device (the live count derives from the psum'd gains, so it is
    replicated) and the branch-local psum stays a uniform collective.

    Slow-marked like the other 8-device mesh golden test
    (test_frontier_data_parallel_matches_single_device): three frontier
    trainings under shard_map are compile-heavy on the CPU mesh."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    X, y = _golden_data()
    order = np.argsort(X[:, 0], kind="stable")
    X, y = X[order], y[order]
    base = {"objective": "binary", "num_leaves": 64, "max_depth": 4,
            "min_data_in_leaf": 40, "verbosity": -1,
            "tree_growth": "frontier", "tree_learner": "data",
            "num_machines": 1, "mesh_shape": [8]}
    bf = _train(X, y, dict(base, tpu_frontier_bucketing=False))
    bb = _train(X, y, dict(base))
    _assert_same_trees(bb, bf)
    p1 = _train(X, y, {k: v for k, v in base.items()
                       if k not in ("tree_learner", "num_machines",
                                    "mesh_shape")})
    np.testing.assert_allclose(p1.predict(X[:200], raw_score=True),
                               bb.predict(X[:200], raw_score=True),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
@pytest.mark.slow
def test_max_depth_clamp_end_to_end():
    """Regression for the clamp bugfix: with a binding max_depth the wave
    ladder tops out at 2^(d-1), and the grown trees respect the depth cap
    with structure identical to the unclamped-fixed-width path."""
    X, y = make_binary(n=800)
    base = {"objective": "binary", "num_leaves": 255, "max_depth": 3,
            "min_data_in_leaf": 20, "verbosity": -1,
            "tree_growth": "frontier"}
    bb = _train(X, y, dict(base))
    bf = _train(X, y, dict(base, tpu_frontier_bucketing=False))
    _assert_same_trees(bb, bf)
    for t in bb.models:
        # depth-3 tree holds <= 8 leaves (num_leaves is the capacity)
        assert t.num_leaves_actual <= 2 ** 3
    from lightgbm_tpu.profiling import phase_probe
    phases = phase_probe(bb)
    # the probed widths come from the clamped ladder [1, 2, 4]
    assert "frontier_hist_w4" in phases
    assert not any(k.startswith("frontier_hist_w")
                   and int(k.split("w")[-1]) > 4 for k in phases)


# ------------------------------------------------- probe + compile metrics
@pytest.mark.slow
@pytest.mark.slow
def test_phase_probe_reports_occupancy_and_cache():
    from lightgbm_tpu.profiling import phase_probe
    X, y = make_binary(n=2000)
    b = _train(X, y, {"objective": "binary", "num_leaves": 15,
                      "tree_growth": "frontier", "verbosity": -1}, rounds=2)
    phases = phase_probe(b)
    occ = phases["frontier_wave_occupancy"]
    assert 0.0 < occ <= 1.0
    paid = phases["frontier_slot_sweeps_per_tree"]
    fixed = phases["frontier_slot_sweeps_fixed_width"]
    # the ISSUE 4 acceptance bar: occupancy-weighted slot-sweeps within 2x
    # of num_leaves, strictly below the fixed-width waves * (num_leaves-1)
    assert paid <= 2 * 15
    assert paid < fixed
    assert "compile_cache_hits" in phases
    assert "compile_cache_misses" in phases
    # the ladder endpoints get their own hist probes
    assert phases.get("frontier_hist", 0.0) > 0.0
    assert "frontier_hist_w1" in phases and "frontier_hist_w14" in phases


@pytest.mark.slow
@pytest.mark.slow
def test_zero_recompiles_after_warmup_in_process(tmp_path):
    """The measured invariant the cache work exists for: after one
    train_many block (which pre-warms the wave ladder — the eager ladder
    runs in compile_cache_dir mode), further blocks perform ZERO XLA
    backend compiles — across iterations AND trees."""
    import jax
    from lightgbm_tpu.profiling import backend_compile_count
    X, y = make_binary(n=500)
    cfg = Config({"objective": "binary", "num_leaves": 7, "verbosity": -1,
                  "tree_growth": "frontier",
                  "compile_cache_dir": str(tmp_path / "cache")})
    # enable_compile_cache redirects the process-wide persistent cache;
    # restore conftest's shared cache dir afterwards
    saved_dir = jax.config.jax_compilation_cache_dir
    saved_min = jax.config.jax_persistent_cache_min_compile_time_secs
    saved_sz = jax.config.jax_persistent_cache_min_entry_size_bytes
    try:
        ds = BinnedDataset.from_matrix(X, cfg, label=y)
        b = create_boosting(cfg, ds, create_objective(cfg), [])
        b.train_many(2)
        jax.block_until_ready(b.scores)
        floor = backend_compile_count()
        b.train_many(2)
        jax.block_until_ready(b.scores)
        assert backend_compile_count() - floor == 0
        warm = getattr(b, "_ladder_warmup", None)
        assert warm and list(warm["widths"]) == wave_width_ladder(7)
    finally:
        jax.config.update("jax_compilation_cache_dir", saved_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          saved_min)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          saved_sz)


# ---------------------------------------------------- checkpoint identity
@pytest.mark.slow
@pytest.mark.slow
def test_checkpoint_resume_byte_identical_frontier(tmp_path):
    """Checkpoint/resume must stay byte-identical when the frontier grower
    (bucketed by default) is the training path."""
    r = np.random.RandomState(7)
    X = r.randn(400, 6)
    y = (X[:, 0] + X[:, 1] * 2 + 0.3 * r.randn(400) > 0).astype(np.float64)
    params = dict(objective="binary", num_leaves=7, learning_rate=0.2,
                  min_data_in_leaf=5, verbosity=-1, tree_growth="frontier")

    def run(ckpt_dir, rounds, resume=False):
        ds = lgb.Dataset(X, label=y, params=dict(params))
        return engine.train(dict(params), ds, num_boost_round=rounds,
                            callbacks=[callback.checkpoint(ckpt_dir,
                                                           period=1)],
                            resume_from=(ckpt_dir if resume else None),
                            verbose_eval=False)

    golden = run(str(tmp_path / "g"), 4)
    run(str(tmp_path / "i"), 2)                    # "preempted" at 2
    resumed = run(str(tmp_path / "i"), 4, resume=True)
    assert golden.model_to_string() == resumed.model_to_string()
