"""Golden parity vs the reference for multiclass, lambdarank, and
regression (extends tests/test_parity.py's binary coverage).

Artifacts in tests/golden/ were produced by the reference CLI (v2.2.4,
num_threads=1) on its own example datasets with:
  num_trees=10 learning_rate=0.1 num_leaves=31 max_bin=255
  min_data_in_leaf=20
- *_model_ref.txt : reference-written model files
- *_pred_ref.txt  : reference predictions on the example test sets
- *_traj.txt      : per-iteration train/valid metric log lines
"""
import os
import re

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io.parser import parse_file

GOLDEN = os.path.join(os.path.dirname(__file__), "golden")
EXAMPLES = "/root/reference/examples"

def needs_ref_data(task, fname):
    return pytest.mark.skipif(
        not os.path.exists(os.path.join(EXAMPLES, task, fname)),
        reason="reference %s example data not available" % task)


needs_multiclass = needs_ref_data("multiclass_classification",
                                  "multiclass.train")
needs_rank = needs_ref_data("lambdarank", "rank.train")
needs_regression = needs_ref_data("regression", "regression.train")


def _traj(name):
    """Parse '[LightGBM] [Info] Iteration:N, <set> <metric> : v' lines."""
    out = {}
    pat = re.compile(r"Iteration:(\d+), (\S+) (\S+) : ([-\d.eE]+)")
    for line in open(os.path.join(GOLDEN, name)):
        m = pat.search(line)
        if m:
            it, ds, metric, v = m.groups()
            out.setdefault(ds, {}).setdefault(metric, []).append(float(v))
    return out


def _load(task, name, label_column="0"):
    return parse_file(os.path.join(EXAMPLES, task, name), has_header=False,
                      label_column=label_column)


@needs_multiclass
def test_multiclass_reference_model_predicts_identically():
    bst = lgb.Booster(model_file=os.path.join(GOLDEN,
                                              "multiclass_model_ref.txt"))
    X, _, _ = _load("multiclass_classification", "multiclass.test")
    prob = bst.predict(X)
    golden = np.loadtxt(os.path.join(GOLDEN, "multiclass_pred_ref.txt"))
    assert prob.shape == golden.shape
    assert np.abs(prob - golden).max() < 1e-6


@needs_multiclass
def test_multiclass_trajectory_matches_reference():
    X, y, _ = _load("multiclass_classification", "multiclass.train")
    Xv, yv, _ = _load("multiclass_classification", "multiclass.test")
    dtr = lgb.Dataset(X, y)
    ev = {}
    lgb.train({"objective": "multiclass", "num_class": 5,
               "metric": "multi_logloss", "num_leaves": 31,
               "learning_rate": 0.1, "max_bin": 255,
               "min_data_in_leaf": 20, "verbosity": -1},
              dtr, num_boost_round=10,
              valid_sets=[dtr, lgb.Dataset(Xv, yv, reference=dtr)],
              valid_names=["training", "valid_1"], evals_result=ev,
              verbose_eval=False)
    ref = _traj("multiclass_traj.txt")
    ours = ev["training"]["multi_logloss"]
    theirs = ref["training"]["multi_logloss"]
    assert len(ours) == len(theirs)
    assert np.abs(np.asarray(ours) - np.asarray(theirs)).max() < 2e-3
    ours_v = ev["valid_1"]["multi_logloss"]
    theirs_v = ref["valid_1"]["multi_logloss"]
    assert np.abs(np.asarray(ours_v) - np.asarray(theirs_v)).max() < 3e-3


@needs_rank
def test_lambdarank_reference_model_predicts_identically():
    bst = lgb.Booster(model_file=os.path.join(GOLDEN, "rank_model_ref.txt"))
    X, _, _ = _load("lambdarank", "rank.test")
    raw = bst.predict(X, raw_score=True)
    golden = np.loadtxt(os.path.join(GOLDEN, "rank_pred_ref.txt"))
    assert np.abs(raw - golden).max() < 1e-6


@needs_rank
def test_lambdarank_trajectory_matches_reference():
    """NDCG@{1,3,5} per iteration within tolerance (lambdarank gradients,
    query handling, and the DCG tables all pinned at once)."""
    train_path = os.path.join(EXAMPLES, "lambdarank", "rank.train")
    test_path = os.path.join(EXAMPLES, "lambdarank", "rank.test")
    dtr = lgb.Dataset(train_path)
    ev = {}
    lgb.train({"objective": "lambdarank", "metric": "ndcg",
               "ndcg_eval_at": [1, 3, 5], "num_leaves": 31,
               "learning_rate": 0.1, "max_bin": 255,
               "min_data_in_leaf": 20, "verbosity": -1},
              dtr, num_boost_round=10,
              valid_sets=[dtr, lgb.Dataset(test_path, reference=dtr)],
              valid_names=["training", "valid_1"], evals_result=ev,
              verbose_eval=False)
    ref = _traj("rank_traj.txt")
    for ds in ("training", "valid_1"):
        for k in (1, 3, 5):
            ours = np.asarray(ev[ds]["ndcg@%d" % k])
            theirs = np.asarray(ref[ds]["ndcg@%d" % k])
            assert len(ours) == len(theirs)
            assert np.abs(ours - theirs).max() < 5e-3, (ds, k, ours, theirs)


@needs_regression
def test_regression_reference_model_predicts_identically():
    bst = lgb.Booster(model_file=os.path.join(GOLDEN,
                                              "regression_model_ref.txt"))
    X, _, _ = _load("regression", "regression.test")
    pred = bst.predict(X)
    golden = np.loadtxt(os.path.join(GOLDEN, "regression_pred_ref.txt"))
    assert np.abs(pred - golden).max() < 1e-6


@needs_regression
def test_regression_trajectory_matches_reference():
    dtr = lgb.Dataset(os.path.join(EXAMPLES, "regression",
                                   "regression.train"))
    dv = lgb.Dataset(os.path.join(EXAMPLES, "regression",
                                  "regression.test"), reference=dtr)
    ev = {}
    lgb.train({"objective": "regression", "metric": "l2", "num_leaves": 31,
               "learning_rate": 0.1, "max_bin": 255, "min_data_in_leaf": 20,
               "verbosity": -1},
              dtr, num_boost_round=10, valid_sets=[dtr, dv],
              valid_names=["training", "valid_1"], evals_result=ev,
              verbose_eval=False)
    ref = _traj("regression_traj.txt")
    for ds in ("training", "valid_1"):
        ours = np.asarray(ev[ds]["l2"])
        theirs = np.asarray(ref[ds]["l2"])
        assert len(ours) == len(theirs)
        assert np.abs(ours - theirs).max() / max(theirs.max(), 1.0) < 1e-3, ds
