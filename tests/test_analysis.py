"""The static-analysis subsystem itself (lightgbm_tpu/analysis/,
ISSUE 7): lint rules fire exactly where the golden corpus says, the
suppression channel works, the jaxpr/HLO audit primitives detect what
they claim to detect, seeded invariant violations fail the comparison
naming entry + invariant, and the committed ANALYSIS_BASELINE.json
stays well-formed.
"""
import glob
import json
import os
import re

import numpy as np
import pytest

from lightgbm_tpu.analysis import astlint, auditor, hlo_audit, jaxpr_audit
from lightgbm_tpu.analysis.astlint import lint_paths, lint_source
from lightgbm_tpu.obs.registry import MetricsRegistry

HERE = os.path.dirname(os.path.abspath(__file__))
CORPUS = sorted(glob.glob(os.path.join(HERE, "lint_corpus", "*.py")))


# ------------------------------------------------------------ lint corpus
def _expected_markers(path):
    out = set()
    with open(path, encoding="utf-8") as fh:
        for i, line in enumerate(fh, start=1):
            m = re.search(r"# EXPECT=(LGL\d+)", line)
            if m:
                out.add((m.group(1), i))
    return out


@pytest.mark.parametrize("path", CORPUS,
                         ids=[os.path.basename(p) for p in CORPUS])
def test_corpus_rules_fire_exactly_where_marked(path):
    """Golden corpus: every `# EXPECT=RULE` line produces exactly that
    finding, nothing else fires, and suppressed lines stay silent."""
    assert CORPUS, "lint corpus missing"
    got = {(f.rule, f.line) for f in lint_paths([path])}
    assert got == _expected_markers(path)


def test_corpus_covers_every_rule():
    """One seeded violation per catalog rule — a rule nothing exercises
    is a rule that silently broke."""
    fired = {f.rule for f in lint_paths(CORPUS)}
    assert fired == set(astlint.LINT_RULES)


def test_package_lints_clean():
    """The satellite-1 contract: the repo's own source has no
    unsuppressed findings."""
    findings = astlint.lint_package()
    assert findings == [], "\n".join(f.format() for f in findings)


def test_rule_catalog_wellformed():
    for rule, (sev, summary) in astlint.LINT_RULES.items():
        assert re.fullmatch(r"LGL\d{3}", rule)
        assert sev in ("error", "warning")
        assert summary


# ------------------------------------------------------------ suppression
def test_suppression_parsing():
    src = (
        "import jax\n"
        "def f(x):\n"
        "    jax.block_until_ready(x)  "
        "# lgbm-lint: disable=LGL103,LGL101 reason text here\n"
        "\n"
        "    jax.block_until_ready(x)\n"
    )
    findings = lint_source(src, resolve_params=False)
    # line 3 suppressed (multi-rule list parses); a suppression also
    # covers the line directly below it, so the control call sits on 5
    assert [f.line for f in findings] == [5]
    assert findings[0].rule == "LGL103"


def test_file_level_suppression_window():
    """disable-file only counts in the first ten lines — a buried one
    cannot silently turn a rule off for a long file."""
    head = "# lgbm-lint: disable-file=LGL103\nimport jax\n" \
           "def f(x):\n    jax.block_until_ready(x)\n"
    assert lint_source(head, resolve_params=False) == []
    buried = "import jax\n" + "\n" * 12 + \
        "# lgbm-lint: disable-file=LGL103\n" \
        "def f(x):\n    jax.block_until_ready(x)\n"
    assert len(lint_source(buried, resolve_params=False)) == 1


def test_unknown_config_param_detection():
    src = "def f(cfg):\n    return cfg.not_a_real_param\n"
    findings = lint_source(src, known_params={"learning_rate"})
    assert [f.rule for f in findings] == ["LGL107"]
    ok = "def f(cfg):\n    return cfg.learning_rate\n"
    assert lint_source(ok, known_params={"learning_rate"}) == []


# ------------------------------------------------------------ jaxpr audit
def test_structural_fingerprint_stable_and_discriminating():
    import jax
    import jax.numpy as jnp
    fn = lambda x: jnp.sin(x) + 1.0                       # noqa: E731
    sds = jax.ShapeDtypeStruct((8,), jnp.float32)
    fp1 = jaxpr_audit.structural_fingerprint(jax.make_jaxpr(fn)(sds))
    fp2 = jaxpr_audit.structural_fingerprint(jax.make_jaxpr(fn)(sds))
    assert fp1 == fp2
    other = jaxpr_audit.structural_fingerprint(
        jax.make_jaxpr(lambda x: jnp.cos(x) + 1.0)(sds))
    assert other != fp1
    # shape change is a different program too
    wider = jaxpr_audit.structural_fingerprint(
        jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((16,), jnp.float32)))
    assert wider != fp1


def test_iter_eqns_recurses_into_scan():
    import jax
    import jax.numpy as jnp

    def fn(xs):
        return jax.lax.scan(lambda c, x: (c + jnp.sin(x), c), 0.0, xs)

    jx = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((4,), jnp.float32))
    prims = jaxpr_audit.primitive_sequence(jx)
    assert "scan" in prims
    assert "sin" in prims          # only reachable through the sub-jaxpr


def test_collective_schedule_and_counts():
    import jax
    import jax.numpy as jnp

    def fn(x):
        return jax.lax.psum(x, "i"), jax.lax.all_gather(x, "i")

    jx = jax.make_jaxpr(fn, axis_env=[("i", 2)])(
        jax.ShapeDtypeStruct((4,), jnp.float32))
    sched = jaxpr_audit.collective_schedule(jx)
    assert [s["primitive"] for s in sched] == ["psum", "all_gather"]
    assert sched[0]["operands"] == ["float32[4]"]
    counts = jaxpr_audit.count_collectives(jx)
    assert counts == {"psum": 1, "all_gather": 1}
    audit = jaxpr_audit.audit_jaxpr(jx)
    assert audit["psums"] == 1 and audit["collectives"] == 2
    assert audit["f64_eqns"] == 0 and audit["host_callbacks"] == []


def test_f64_equations_detected():
    import jax
    import jax.numpy as jnp
    try:
        from jax.experimental import enable_x64
    except ImportError:
        pytest.skip("no enable_x64 context in this jax")
    with enable_x64():
        jx = jax.make_jaxpr(lambda x: x.astype(jnp.float64) * 2.0)(
            jax.ShapeDtypeStruct((4,), jnp.float32))
    assert jaxpr_audit.count_f64_eqns(jx) > 0
    clean = jax.make_jaxpr(lambda x: x * 2.0)(
        jax.ShapeDtypeStruct((4,), jnp.float32))
    assert jaxpr_audit.count_f64_eqns(clean) == 0


def test_host_callbacks_detected():
    import jax
    import jax.numpy as jnp

    def fn(x):
        return jax.pure_callback(
            np.sin, jax.ShapeDtypeStruct((4,), np.float32), x)

    jx = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((4,), jnp.float32))
    assert jaxpr_audit.host_callback_primitives(jx)


def test_sharded_frontier_entry_matches_perfgate_counter():
    """The shared entry IS the perf-gate program: same per-wave psum
    normalization as the committed psum_per_wave_branch counter."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    from lightgbm_tpu.obs.perfgate import _psum_per_wave, bucketing_ladder
    fn, args, params = jaxpr_audit.sharded_frontier_fn()
    psums = jaxpr_audit.count_collectives(
        jax.make_jaxpr(fn)(*args)).get("psum", 0)
    ladder = bucketing_ladder(params.num_leaves, params.max_depth)
    assert psums / len(ladder) == _psum_per_wave()


# ------------------------------------------------------------ hlo audit
def test_input_output_alias_parsing():
    text = ("HloModule jit_f, input_output_alias={ {0}: (3, {}, "
            "may-alias), {1}: (10, {}, must-alias) }, "
            "entry_computation_layout={(f32[8])->f32[8]}")
    aliases = hlo_audit.input_output_aliases(text)
    assert aliases == [
        {"output_index": [0], "param_number": 3, "kind": "may-alias"},
        {"output_index": [1], "param_number": 10, "kind": "must-alias"},
    ]
    assert hlo_audit.input_output_aliases("HloModule jit_f") == []


def test_audit_donation_effective_and_dropped():
    import jax
    import jax.numpy as jnp
    sds = jax.ShapeDtypeStruct((64,), jnp.float32)
    # same-shape output: XLA records the alias
    ok = hlo_audit.audit_donation(lambda x: x + 1.0, (sds,), (0,))
    assert ok["ok"] and ok["donated_params"] == [0]
    assert 0 in ok["aliased_params"]
    # scalar output cannot reuse the donated [64] buffer: alias dropped,
    # and the audit must SAY so rather than silently passing
    dropped = hlo_audit.audit_donation(lambda x: x.sum(), (sds,), (0,))
    assert not dropped["ok"] and dropped["missing"] == [0]


def test_flat_param_ranges_spans_pytrees():
    import jax
    import jax.numpy as jnp
    sds = jax.ShapeDtypeStruct((4,), jnp.float32)
    ranges = hlo_audit.flat_param_ranges(((sds, sds), None, sds))
    assert ranges == [(0, 2), (2, 2), (2, 3)]


# ------------------------------------------------------------ comparison
def _fake_measured():
    entry = {"fingerprint": "abc", "num_eqns": 10, "psums": 1,
             "all_gathers": 0, "collectives": 1,
             "collective_schedule": [{"primitive": "psum",
                                      "operands": ["float32[4]"]}],
             "f64_eqns": 0, "host_callbacks": []}
    return {"schema": auditor.SCHEMA, "jax": "x", "backend": "cpu",
            "workload": {}, "entries": {"wave": dict(entry)},
            "donation": {"train_block": {
                "donate_argnums": [3, 8], "donated_params": [5, 10],
                "aliased_params": [5, 10], "missing": [], "ok": True}}}


def test_compare_audit_passes_on_identity():
    m = _fake_measured()
    violations, report = auditor.compare_audit(m, m)
    assert violations == []
    assert "wave" in report


def test_seeded_second_psum_fails_naming_entry_and_invariant():
    """The acceptance demo in unit form: one extra psum in a wave entry
    must fail the gate with a violation naming both."""
    base, meas = _fake_measured(), _fake_measured()
    meas["entries"]["wave"]["psums"] = 2
    meas["entries"]["wave"]["collectives"] = 2
    meas["entries"]["wave"]["collective_schedule"].append(
        {"primitive": "psum", "operands": ["float32[4]"]})
    violations, _ = auditor.compare_audit(base, meas)
    assert {v["invariant"] for v in violations} == {
        "psums", "collectives", "collective_schedule"}
    assert all(v["entry"] == "wave" for v in violations)


def test_seeded_f64_is_a_hard_violation_even_if_baselined():
    base, meas = _fake_measured(), _fake_measured()
    base["entries"]["wave"]["f64_eqns"] = 3   # a poisoned baseline
    meas["entries"]["wave"]["f64_eqns"] = 3
    violations, _ = auditor.compare_audit(base, meas)
    assert any(v["invariant"] == "zero_f64" and v["entry"] == "wave"
               for v in violations)


def test_fingerprint_drift_and_missing_entry_fail():
    base, meas = _fake_measured(), _fake_measured()
    meas["entries"]["wave"]["fingerprint"] = "zzz"
    violations, _ = auditor.compare_audit(base, meas)
    assert any(v["invariant"] == "fingerprint" for v in violations)
    del meas["entries"]["wave"]
    violations, _ = auditor.compare_audit(base, meas)
    assert any(v["invariant"] == "present" for v in violations)


def test_dropped_donation_fails():
    base, meas = _fake_measured(), _fake_measured()
    meas["donation"]["train_block"].update(
        ok=False, missing=[10], aliased_params=[5])
    violations, _ = auditor.compare_audit(base, meas)
    assert any(v["invariant"] == "donation_aliased"
               and v["entry"] == "train_block" for v in violations)


def test_write_baseline_refuses_hard_invariant_breaks(tmp_path):
    bad = _fake_measured()
    bad["entries"]["wave"]["f64_eqns"] = 1
    with pytest.raises(ValueError, match="f64"):
        auditor.write_baseline(bad, str(tmp_path / "b.json"))
    bad2 = _fake_measured()
    bad2["donation"]["train_block"]["ok"] = False
    with pytest.raises(ValueError, match="donation"):
        auditor.write_baseline(bad2, str(tmp_path / "b.json"))
    good = _fake_measured()
    path = auditor.write_baseline(good, str(tmp_path / "b.json"))
    assert auditor.load_baseline(path) == good


def test_publish_gauges():
    m = _fake_measured()
    reg = MetricsRegistry()
    auditor.publish(m, [], registry=reg)
    text = reg.prometheus_text()
    assert "lgbm_analysis_entries 1" in text
    assert "lgbm_analysis_violations 0" in text
    assert "lgbm_analysis_collectives_total 1" in text


# ------------------------------------------------------------ baseline file
def test_committed_baseline_is_wellformed():
    path = os.path.join(os.path.dirname(HERE), "ANALYSIS_BASELINE.json")
    with open(path) as fh:
        base = json.load(fh)
    assert base["schema"] == auditor.SCHEMA
    entries = base["entries"]
    # the entry points the audit exists to protect
    for name in ("train_block", "grower", "grower_sharded",
                 "materialize", "frontier_hist_w1", "predict_b32"):
        assert name in entries, name
    for name, e in entries.items():
        assert e["f64_eqns"] == 0, name
        assert e["host_callbacks"] == [], name
        assert re.fullmatch(r"[0-9a-f]{64}", e["fingerprint"]), name
    # the sharded grower's collective schedule is committed exactly
    sharded = entries["grower_sharded"]
    assert sharded["psums"] > 0
    assert len(sharded["collective_schedule"]) == sharded["collectives"]
    don = base["donation"]["train_block"]
    assert don["ok"] and don["missing"] == []
    assert don["donate_argnums"] == [3, 8]


# ------------------------------------------------- donation regression
@pytest.mark.slow
def test_train_block_donation_actually_aliased():
    """Satellite 2: train_many's donated scores/bag-mask buffers are
    really input-output aliased in the compiled executable — XLA
    silently dropping them would turn every block boundary into a full
    [N, K] copy.  Audited on the exact executing signature."""
    import jax
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    X = rng.randn(256, 4).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 7, "max_depth": 3,
                     "tree_growth": "frontier"},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    b = bst._impl
    b.models
    block = int(b._last_block_len)
    assert block > 0
    args = b.train_block_sds(block)
    result = hlo_audit.audit_donation(
        b._build_run_block(), args, type(b).TRAIN_BLOCK_DONATE)
    assert result["ok"], result
    # the aliased leaves are the right buffers: scores [N, K] f32 and
    # the bagging mask [N] f32
    ranges = hlo_audit.flat_param_ranges(args)
    scores_range = ranges[type(b).TRAIN_BLOCK_DONATE[0]]
    leaves = jax.tree_util.tree_leaves(args[type(b).TRAIN_BLOCK_DONATE[0]])
    assert leaves[0].shape == (256, 1)
    assert scores_range[0] in result["aliased_params"]
