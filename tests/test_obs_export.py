"""Metric exposition hardening: hostile labels + snapshot-vs-record races.

Contracts pinned here:
- Prometheus label VALUES escape backslash, quote and newline per the
  0.0.4 text format — both in the registry's own exposition and in the
  fleet's hand-built per-replica rows (``cluster_prometheus``), where a
  replica named ``a"b`` used to emit an unparseable line;
- HELP text escapes backslash and newline (quote rules do NOT apply);
- every emitted line matches the exposition grammar, and escaped label
  values round-trip back to the original string;
- Summary.quantiles() and ServingMetrics.snapshot() copy under their
  locks and serialize OUTSIDE them: hammering observers while scraping
  never throws, and the final counts come out exact.
"""
import json
import re
import threading
import time

from lightgbm_tpu.fleet.replica import FileKvClient, FleetClusterProvider
from lightgbm_tpu.obs.registry import (MetricsRegistry, escape_label_value,
                                       _escape_help)
from lightgbm_tpu.serving.metrics import ServingMetrics

HOSTILE = 'a"b\\c\nd'   # quote, backslash and newline in one value

# one exposition line: name, optional {labels} with escaped values, value
_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(\\\\|\\"|\\n|[^"\\\n])*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(\\\\|\\"|\\n|[^"\\\n])*")*\})?'
    r' \S+$')


def _assert_parseable(text):
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _LINE.match(line), "unparseable exposition line: %r" % line


def _unescape(v):
    return (v.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


# --------------------------------------------------------------- escaping
def test_escape_label_value_roundtrip():
    escaped = escape_label_value(HOSTILE)
    assert "\n" not in escaped and '"' not in escaped.replace('\\"', "")
    assert _unescape(escaped) == HOSTILE


def test_registry_exposition_with_hostile_labels():
    reg = MetricsRegistry()
    reg.counter("lgbm_x_total", "X.", labels={"model": HOSTILE}).inc(3)
    text = reg.prometheus_text()
    assert text == (
        '# HELP lgbm_x_total X.\n'
        '# TYPE lgbm_x_total counter\n'
        'lgbm_x_total{model="a\\"b\\\\c\\nd"} 3\n')
    _assert_parseable(text)
    val = re.search(r'model="((?:\\.|[^"\\])*)"', text).group(1)
    assert _unescape(val) == HOSTILE


def test_hostile_global_labels_escaped():
    reg = MetricsRegistry()
    reg.set_global_labels({"replica": HOSTILE})
    reg.counter("lgbm_y_total", "Y.").inc()
    _assert_parseable(reg.prometheus_text())


def test_help_text_escaping():
    assert _escape_help("a\\b\nc") == "a\\\\b\\nc"
    reg = MetricsRegistry()
    reg.counter("lgbm_z_total", "line one\nline two \\ slash")
    text = reg.prometheus_text()
    assert "# HELP lgbm_z_total line one\\nline two \\\\ slash\n" in text
    assert len([ln for ln in text.splitlines() if ln]) == 3  # no split line


# -------------------------------------------------- fleet cluster export
def test_cluster_prometheus_hostile_replica_name(tmp_path):
    kv = FileKvClient(str(tmp_path / "kv"))
    for name, snap_id in ((HOSTILE, 3), ("sane", 4)):
        kv.key_value_set("fleet/" + name, json.dumps({
            "replica": name, "time": time.time(), "snap_id": snap_id,
            "metrics": {"requests": 10, "shed": 1,
                        "recompiles_after_warmup": 0}}))
    text = FleetClusterProvider(kv).cluster_prometheus()
    _assert_parseable(text)
    assert 'lgbm_fleet_replica_up{replica="a\\"b\\\\c\\nd"} 1' in text
    assert 'lgbm_fleet_replica_snap_id{replica="sane"} 4' in text
    assert "lgbm_fleet_live_replicas 2" in text
    # the hostile name round-trips out of its label value
    vals = {_unescape(m) for m in
            re.findall(r'lgbm_fleet_replica_up\{replica="((?:\\.|[^"\\])*)"',
                       text)}
    assert vals == {HOSTILE, "sane"}


# ----------------------------------------------- snapshot-vs-record races
def _hammer(record, scrape, n_threads=4, per_thread=2000):
    """Run ``record`` from many threads while ``scrape`` loops; surface
    any scraper exception after the join."""
    errors = []
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            try:
                scrape()
            except Exception as e:          # pragma: no cover - the bug
                errors.append(e)
                return

    scr = threading.Thread(target=scraper)
    scr.start()
    threads = [threading.Thread(
        target=lambda t=t: [record(t, i) for i in range(per_thread)])
        for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    scr.join()
    assert not errors, errors[0]
    return n_threads * per_thread


def test_summary_quantiles_concurrent_with_observe():
    reg = MetricsRegistry()
    s = reg.summary("lgbm_lat", "L.", window=512)
    total = _hammer(lambda t, i: s.observe(t + i * 1e-3),
                    lambda: (s.quantiles(), reg.prometheus_text()))
    assert s.count == total
    q = s.quantiles()
    assert set(q) == {0.5, 0.9, 0.99} and q[0.5] <= q[0.99]


def test_serving_metrics_snapshot_concurrent_with_recording():
    m = ServingMetrics(window=256)

    def record(t, i):
        m.record_request(rows=2, latency_s=0.001 * (i % 7))
        m.record_bucket_latency(16, 0.5 + i % 3)
        if i % 10 == 0:
            m.record_cache(hit=True)

    total = _hammer(record, lambda: (m.snapshot(), m.bucket_latency()))
    snap = m.snapshot()
    assert snap["requests"] == total            # exact under concurrency
    assert snap["rows"] == 2 * total
    assert snap["latency_ms"]["count"] > 0
    assert "16" in m.bucket_latency()
