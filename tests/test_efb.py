"""EFB (exclusive feature bundling) + sparse ingestion tests.

Covers the dataset.cpp:67-177 semantics: mutually-exclusive sparse features
share a stored column, training results match the unbundled dense path, and
sparse input flows in without densifying.
"""
import numpy as np
import pytest

import jax

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.io.bundle import find_bundles, bundle_offsets
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.boosting import create_boosting

sp = pytest.importorskip("scipy.sparse")


def _exclusive_groups(n=3000, groups=6, per_group=5, seed=3):
    """Features in blocks of `per_group`, at most one active per row."""
    r = np.random.RandomState(seed)
    f = groups * per_group
    X = np.zeros((n, f))
    for g in range(groups):
        which = r.randint(0, per_group + 1, n)   # per_group features + none
        vals = r.randint(1, 9, n).astype(np.float64)
        for k in range(per_group):
            X[which == k, g * per_group + k] = vals[which == k]
    y = ((X[:, 0] + X[:, per_group] - X[:, 2 * per_group]
          + 0.5 * r.randn(n)) > 1.0).astype(np.float32)
    return X, y


def test_find_bundles_exclusive_features():
    r = np.random.RandomState(0)
    n = 2000
    nz = []
    for g in range(4):
        # 3 exclusive features out of 8 states -> each ~12% nonzero (sparse)
        which = r.randint(0, 8, n)
        for k in range(3):
            nz.append(np.flatnonzero(which == k).astype(np.int64))
    bundles = find_bundles(nz, n, [10] * 12, max_conflict_rate=0.0)
    multi = [b for b in bundles if len(b) > 1]
    assert multi, "mutually exclusive features must bundle"
    # no bundle may pair features from the same exclusive check twice... every
    # bundle must be conflict-free: verify on the actual patterns
    for b in multi:
        seen = np.zeros(n, dtype=bool)
        for j in b:
            assert not (seen[nz[j]]).any(), "conflicting features bundled"
            seen[nz[j]] = True


def test_find_bundles_respects_bin_capacity():
    n = 1000
    nz = [np.array([i], dtype=np.int64) for i in range(10)]
    bundles = find_bundles(nz, n, [200] * 10, max_conflict_rate=0.0)
    for b in bundles:
        assert sum(200 for _ in b) + 1 <= 256 or len(b) == 1


def test_bundle_offsets_layout():
    offs, total = bundle_offsets([3, 7, 9], {3: 5, 7: 4, 9: 6})
    assert offs == [1, 6, 10]
    assert total == 16
    offs1, total1 = bundle_offsets([4], {4: 17})
    assert offs1 == [0] and total1 == 17


@pytest.mark.slow
def test_sparse_input_bundles_and_matches_dense():
    X, y = _exclusive_groups()
    Xs = sp.csr_matrix(X)
    cfg = Config({"objective": "binary", "num_leaves": 15, "verbosity": -1,
                  "min_data_in_leaf": 5})
    ds_b = BinnedDataset.from_matrix(Xs, cfg, label=y)
    assert ds_b.has_bundles
    assert ds_b.num_columns < ds_b.num_features / 2
    cfg_nb = Config({"objective": "binary", "num_leaves": 15, "verbosity": -1,
                     "min_data_in_leaf": 5, "enable_bundle": False})
    ds_d = BinnedDataset.from_matrix(X, cfg_nb, label=y)
    assert ds_d.num_columns == ds_d.num_features

    b1 = create_boosting(cfg, ds_b, create_objective(cfg), [])
    b2 = create_boosting(cfg_nb, ds_d, create_objective(cfg_nb), [])
    for _ in range(5):
        b1.train_one_iter()
        b2.train_one_iter()
    p1 = b1.predict(X[:200], raw_score=True)
    p2 = b2.predict(X[:200], raw_score=True)
    np.testing.assert_allclose(p1, p2, rtol=1e-4, atol=1e-5)


def test_sparse_dense_same_binning():
    """CSR and dense inputs of the same data produce identical bin matrices
    when bundling is off (the sparse path is not allowed to drift)."""
    X, y = _exclusive_groups(n=800, groups=3)
    cfg = Config({"objective": "binary", "verbosity": -1,
                  "enable_bundle": False})
    ds1 = BinnedDataset.from_matrix(X, cfg, label=y)
    ds2 = BinnedDataset.from_matrix(sp.csr_matrix(X), cfg, label=y)
    np.testing.assert_array_equal(ds1.X_binned, ds2.X_binned)
    for m1, m2 in zip(ds1.bin_mappers, ds2.bin_mappers):
        assert m1.num_bin == m2.num_bin
        np.testing.assert_allclose(m1.bin_upper_bound, m2.bin_upper_bound)


def test_efb_binary_cache_roundtrip(tmp_path):
    X, y = _exclusive_groups(n=600, groups=3)
    cfg = Config({"objective": "binary", "verbosity": -1})
    ds = BinnedDataset.from_matrix(sp.csr_matrix(X), cfg, label=y)
    path = str(tmp_path / "cache.npz")
    ds.save_binary(path)
    ds2 = BinnedDataset.load_binary(path)
    np.testing.assert_array_equal(ds.X_binned, ds2.X_binned)
    assert ds.col_features == ds2.col_features
    assert ds.col_offsets == ds2.col_offsets
    assert ds.col_num_bin == ds2.col_num_bin


def test_efb_with_validation_set():
    """Validation sets built against an EFB reference reuse its layout."""
    X, y = _exclusive_groups()
    Xv, yv = _exclusive_groups(seed=11)
    cfg = Config({"objective": "binary", "num_leaves": 15, "verbosity": -1,
                  "metric": "binary_logloss"})
    ds = BinnedDataset.from_matrix(sp.csr_matrix(X), cfg, label=y)
    dv = BinnedDataset.from_matrix(sp.csr_matrix(Xv), cfg, label=yv,
                                   reference=ds)
    assert dv.col_features == ds.col_features
    assert dv.X_binned.shape[1] == ds.X_binned.shape[1]
    from lightgbm_tpu.metrics import create_metric
    b = create_boosting(cfg, ds, create_objective(cfg),
                        [create_metric("binary_logloss", cfg)])
    b.add_valid_data(dv, [create_metric("binary_logloss", cfg)])
    for _ in range(8):
        b.train_one_iter()
    (_, _, train_ll, _), = b.get_eval_at(0)
    (_, _, valid_ll, _), = b.get_eval_at(1)
    assert train_ll < 0.6
    assert valid_ll < 0.75
