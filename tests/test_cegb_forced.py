"""Forced splits (forcedsplits_filename) and CEGB penalties.

Reference semantics: SerialTreeLearner::ForceSplits
(src/treelearner/serial_tree_learner.cpp:593-751) splits a BFS-predetermined
(feature, threshold) chain before best-first growth takes over; CEGB
(:484-504, :533-539) subtracts feature-acquisition costs from candidate
gains.
"""
import json

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture()
def xy():
    rng = np.random.RandomState(7)
    X = rng.randn(2000, 5).astype(np.float32)
    y = (((X[:, 0] > 0.3) & (X[:, 1] < 0.2)) | (X[:, 2] > 0)).astype(
        np.float32)
    return X, y


def _used_features(bst):
    used = set()
    for t in bst._impl.models:
        for i in range(t.num_nodes):
            if t.split_leaf[i] >= 0:
                used.add(int(t.split_feature[i]))
    return used


def test_forced_splits_structure(tmp_path, xy):
    X, y = xy
    fpath = tmp_path / "forced.json"
    fpath.write_text(json.dumps(
        {"feature": 0, "threshold": 0.3,
         "left": {"feature": 1, "threshold": 0.2},
         "right": {"feature": 3, "threshold": -0.5}}))
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1,
                     "forcedsplits_filename": str(fpath)},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    for t in [bst._impl.models[0], bst._impl.models[1]]:
        # node 0: root forced on feature 0 near 0.3
        assert t.split_leaf[0] == 0
        assert t.split_feature[0] == 0
        assert abs(t.threshold[0] - 0.3) < 0.25
        # node 1: BFS order -> root's LEFT child (leaf 0) on feature 1
        assert t.split_leaf[1] == 0
        assert t.split_feature[1] == 1
        assert abs(t.threshold[1] - 0.2) < 0.25
        # node 2: root's RIGHT child (leaf 1) on feature 3
        assert t.split_leaf[2] == 1
        assert t.split_feature[2] == 3


def test_forced_splits_survive_model_roundtrip(tmp_path, xy):
    X, y = xy
    fpath = tmp_path / "forced.json"
    fpath.write_text(json.dumps({"feature": 4, "threshold": 0.0}))
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
                     "forcedsplits_filename": str(fpath)},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    mpath = tmp_path / "model.txt"
    bst.save_model(str(mpath))
    loaded = lgb.Booster(model_file=str(mpath))
    np.testing.assert_allclose(loaded.predict(X[:100]), bst.predict(X[:100]),
                               rtol=1e-6)
    assert bst._impl.models[0].split_feature[0] == 4


def test_forced_split_categorical_rejected(tmp_path, xy):
    X, y = xy
    X[:, 1] = np.round(np.abs(X[:, 1]) * 3)
    fpath = tmp_path / "forced.json"
    fpath.write_text(json.dumps({"feature": 1, "threshold": 1.0}))
    with pytest.raises(lgb.LightGBMError):
        lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
                   "forcedsplits_filename": str(fpath),
                   "categorical_feature": [1]},
                  lgb.Dataset(X, label=y, categorical_feature=[1]),
                  num_boost_round=1)


def test_cegb_coupled_penalty_gates_features(xy):
    X, y = xy
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
                     "cegb_tradeoff": 1.0,
                     "cegb_penalty_feature_coupled": [1e9, 1e9, 0.0, 1e9,
                                                      1e9]},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    assert _used_features(bst) <= {2}


def test_cegb_split_penalty_prunes(xy):
    X, y = xy
    kw = {"objective": "binary", "num_leaves": 31, "verbosity": -1}
    free = lgb.train(dict(kw), lgb.Dataset(X, label=y), num_boost_round=1)
    pen = lgb.train(dict(kw, cegb_penalty_split=0.05),
                    lgb.Dataset(X, label=y), num_boost_round=1)
    assert pen._impl.models[0].num_leaves_actual \
        < free._impl.models[0].num_leaves_actual


def test_cegb_lazy_prefers_paid_rows(xy):
    X, y = xy
    # with a steep lazy penalty the model should stick to few features:
    # re-splitting a feature whose rows already paid is cheaper
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1,
                     "cegb_penalty_feature_lazy": [0.01] * 5},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    free = lgb.train({"objective": "binary", "num_leaves": 15,
                      "verbosity": -1},
                     lgb.Dataset(X, label=y), num_boost_round=3)
    assert len(_used_features(bst)) <= len(_used_features(free))


@pytest.mark.slow
def test_forced_splits_match_on_data_parallel_mesh(tmp_path, xy):
    """Forced splits now ride the fused sharded partition path (the leaf
    rebuild runs straight-line + psum, grow.py leaf_hist): an 8-shard
    data-parallel run must reproduce the serial forced-split model."""
    X, y = xy
    path = str(tmp_path / "forced.json")
    with open(path, "w") as f:
        json.dump({"feature": 2, "threshold": 0.1,
                   "left": {"feature": 3, "threshold": -0.2}}, f)
    kw = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "min_data_in_leaf": 5, "forcedsplits_filename": path}
    serial = lgb.train(dict(kw), lgb.Dataset(X, label=y), num_boost_round=4)
    dp = lgb.train(dict(kw, tree_learner="data", mesh_shape=[8]),
                   lgb.Dataset(X, label=y), num_boost_round=4)
    assert dp._impl._partition_on_mesh       # not the masked fallback
    ps = serial.predict(X[:300], raw_score=True)
    pd = dp.predict(X[:300], raw_score=True)
    np.testing.assert_allclose(ps, pd, rtol=1e-5, atol=1e-5)
    # the forced structure is present in both
    t0s = serial._impl.models[0]
    t0d = dp._impl.models[0]
    assert t0s.split_feature[0] == t0d.split_feature[0] == 2


def test_cegb_lazy_matches_on_data_parallel_mesh(xy):
    """Lazy CEGB's unpaid-row psum runs straight-line (no cond) on the
    sharded partition path; acquisition state threads through the
    shard_map with row_used sharded. 8-shard result == serial result."""
    X, y = xy
    kw = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "min_data_in_leaf": 5, "cegb_tradeoff": 0.5,
          "cegb_penalty_split": 1e-5,
          "cegb_penalty_feature_lazy": [0.001] * 5}
    serial = lgb.train(dict(kw), lgb.Dataset(X, label=y), num_boost_round=4)
    dp = lgb.train(dict(kw, tree_learner="data", mesh_shape=[8]),
                   lgb.Dataset(X, label=y), num_boost_round=4)
    assert dp._impl._partition_on_mesh
    ps = serial.predict(X[:300], raw_score=True)
    pd = dp.predict(X[:300], raw_score=True)
    np.testing.assert_allclose(ps, pd, rtol=1e-5, atol=1e-5)
