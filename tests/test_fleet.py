"""lightgbm_tpu.fleet — refit, multi-model QoS, replicated rolling deploys.

Contracts pinned here:
- the device refit (fleet/refit.py) matches the host numpy golden path,
  preserves every tree structure bit-for-bit, and is BYTE-stable at
  decay_rate=1.0 (the f64 host blend against the original doubles);
- checkpoint -> refit -> resume: ``save_refit`` snapshots are what
  ``latest_model`` serves (the hot-roll poll target) and what
  ``load_latest`` SKIPS (training resume), and retention never prunes
  the only full training snapshot out from under a run of refits;
- QosPolicy: per-model quotas shed only the offending tenant; the
  weighted-fair pick converges served rows to the weight ratio;
- CascadeAutotuner: one ladder rung per step, fresh-sample gating,
  headroom hysteresis;
- FileKvClient satisfies the KvHostComm client seam, including the
  DEADLINE_EXCEEDED timeout marker;
- ReplicaAnnouncer / RollingDeployCoordinator: lease-based liveness,
  sorted-name turn-taking, and a canary rejection that propagates
  fleet-wide without any successor ever staging the bad snapshot.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.checkpoint.manager import CheckpointManager
from lightgbm_tpu.fleet import (CascadeAutotuner, FileKvClient,
                                FleetClusterProvider, QosPolicy,
                                RollingDeployCoordinator, ReplicaAnnouncer,
                                Refitter, refit_booster)
from lightgbm_tpu.serving import ModelRegistry

from conftest import make_binary, make_multiclass


def _binary_booster(n=500, rounds=8, seed=3):
    X, y = make_binary(n=n, f=6, seed=seed)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 15, "learning_rate": 0.2},
                    lgb.Dataset(X, label=y), num_boost_round=rounds)
    return bst, X, y


def _leaf_tables(booster):
    return [np.asarray(t.leaf_value, np.float64).copy()
            for t in booster._impl.models]


def _structure(booster):
    return [(np.asarray(t.split_feature).tobytes(),
             np.asarray(t.threshold).tobytes(),
             np.asarray(t.left_child).tobytes(),
             np.asarray(t.right_child).tobytes())
            for t in booster._impl.models]


# ------------------------------------------------------------------ refit
def test_refit_decay_one_is_byte_stable():
    """decay_rate=1.0 keeps every stored leaf double bit-for-bit: the
    final blend happens on host in f64 against the original values."""
    bst, X, y = _binary_booster()
    refitted = bst.refit(X, y, decay_rate=1.0)
    for old, new in zip(_leaf_tables(bst), _leaf_tables(refitted)):
        np.testing.assert_array_equal(old, new)


@pytest.mark.slow
def test_refit_device_matches_host_golden_binary():
    bst, X, y = _binary_booster()
    rng = np.random.RandomState(0)
    Xw = X + 0.3 * rng.randn(*X.shape)
    dev = refit_booster(bst, Xw, y, decay_rate=0.4)
    bst.config.refit_device = False       # force the host numpy path
    host = bst.refit(Xw, y, decay_rate=0.4)
    for a, b in zip(_leaf_tables(dev), _leaf_tables(host)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(dev.predict(X[:100]), host.predict(X[:100]),
                               rtol=1e-5, atol=1e-6)


def test_refit_device_matches_host_golden_multiclass():
    """k>1 exercises the [N,k] gradient layout inside the scan body."""
    X, y = make_multiclass(n=400, f=6, k=3, seed=5)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "verbosity": -1, "num_leaves": 7},
                    lgb.Dataset(X, label=y), num_boost_round=4)
    dev = refit_booster(bst, X, y, decay_rate=0.0)
    bst.config.refit_device = False
    host = bst.refit(X, y, decay_rate=0.0)
    for a, b in zip(_leaf_tables(dev), _leaf_tables(host)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_refit_shifted_data_changes_only_leaf_tables():
    bst, X, y = _binary_booster()
    refitted = bst.refit(X + 0.5, 1.0 - y, decay_rate=0.0)
    assert _structure(refitted) == _structure(bst)
    changed = sum(not np.array_equal(a, b) for a, b in
                  zip(_leaf_tables(bst), _leaf_tables(refitted)))
    assert changed == len(bst._impl.models)
    assert not np.allclose(refitted.predict(X[:50]), bst.predict(X[:50]))


def test_refitter_reuse_matches_one_shot():
    """A held Refitter (the fleet worker pattern) gives the same answer
    as a fresh one-shot refit, across cycles with different windows."""
    bst, X, y = _binary_booster()
    r = Refitter(bst)
    for seed in (1, 2):
        rng = np.random.RandomState(seed)
        Xw = X + 0.2 * rng.randn(*X.shape)
        held = r.refit(Xw, y, decay_rate=0.3)
        shot = refit_booster(bst, Xw, y, decay_rate=0.3)
        for a, b in zip(_leaf_tables(held), _leaf_tables(shot)):
            np.testing.assert_array_equal(a, b)


def test_refit_weight_changes_leaf_values():
    bst, X, y = _binary_booster()
    w = np.where(y > 0, 5.0, 1.0)
    plain = bst.refit(X, y, decay_rate=0.0)
    weighted = bst.refit(X, y, decay_rate=0.0, weight=w)
    assert any(not np.array_equal(a, b) for a, b in
               zip(_leaf_tables(plain), _leaf_tables(weighted)))


# ------------------------------------------------------- checkpoint seam
def test_checkpoint_refit_resume_byte_stable(tmp_path):
    """save -> save_refit -> latest_model serves the refit; load_latest
    resumes the FULL snapshot with its model text byte-identical."""
    bst, X, y = _binary_booster()
    mgr = CheckpointManager(str(tmp_path), keep_last_n=5)
    mgr.save(bst)
    full_text = bst.model_to_string()
    full_id, _ = mgr.latest_model()

    refitted = bst.refit(X + 0.5, y, decay_rate=0.0)
    entry = mgr.save_refit(refitted)
    assert entry["refit"] is True
    assert int(entry["id"]) > full_id

    snap_id, model_path = mgr.latest_model()
    assert snap_id == int(entry["id"])     # serving hot-rolls the refit
    served = lgb.Booster(model_file=model_path)
    for a, b in zip(_leaf_tables(served), _leaf_tables(refitted)):
        np.testing.assert_allclose(a, b, rtol=0, atol=1e-12)

    handle = mgr.load_latest()             # training resume skips it
    assert int(handle.entry["id"]) == full_id
    assert not handle.entry.get("refit")
    with open(handle.model_path) as fh:
        assert fh.read() == full_text


def test_refit_retention_keeps_last_full_snapshot(tmp_path):
    """A run of refit snapshots must never prune the only resumable
    training state out of the manifest."""
    bst, X, y = _binary_booster(rounds=4)
    mgr = CheckpointManager(str(tmp_path), keep_last_n=2)
    mgr.save(bst)
    full_id, _ = mgr.latest_model()
    for shift in (0.1, 0.2, 0.3, 0.4):
        mgr.save_refit(bst.refit(X + shift, y, decay_rate=0.0))
    handle = mgr.load_latest()
    assert handle is not None and int(handle.entry["id"]) == full_id
    # and the newest refit still serves
    assert mgr.latest_model()[0] > full_id


def test_refit_only_directory_resumes_fresh(tmp_path):
    bst, X, y = _binary_booster(rounds=3)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save_refit(bst.refit(X, y, decay_rate=0.5))
    assert mgr.load_latest() is None       # nothing resumable: fresh start
    assert mgr.latest_model() is not None  # but the model still serves


# ---------------------------------------------------------------- QoS
def test_qos_from_spec_and_quota_admission():
    qos = QosPolicy.from_spec("gold=4, bronze=1", quota_rows=100)
    assert qos.weight("gold") == 4.0
    assert qos.weight("unknown") == 1.0
    assert qos.quota("gold") == 100
    assert qos.admit("gold", 90, 10)         # exactly at quota: admitted
    assert not qos.admit("gold", 91, 10)     # over: shed, counted
    assert qos.snapshot()["gold"]["shed"] == 1
    with pytest.raises(Exception):
        QosPolicy.from_spec("missing-equals")


def test_qos_quota_sheds_only_offending_model():
    qos = QosPolicy(quota_rows={"noisy": 10})
    assert not qos.admit("noisy", 10, 1)
    assert qos.admit("quiet", 10_000, 64)    # unlisted model: no quota


def test_qos_weighted_fair_pick_converges_to_weights():
    qos = QosPolicy(weights={"gold": 4.0, "bronze": 1.0})
    served = {"gold": 0, "bronze": 0}
    queued = {"gold": 64, "bronze": 64}      # both always have work
    for _ in range(200):
        mid = qos.pick(queued)
        qos.account(mid, 32)
        served[mid] += 32
    ratio = served["gold"] / max(served["bronze"], 1)
    assert 3.0 < ratio < 5.0


def test_qos_new_model_starts_at_floor():
    """A late-arriving model must not get an unbounded catch-up burst."""
    qos = QosPolicy()
    qos.account("old", 10_000)
    qos.pick({"new": 1})                     # seen for the first time
    qos.account("new", 1)
    assert qos._served_rows["new"] >= 10_000


class _FakeTunerEngine:
    def __init__(self, margin=0.8):
        self.cascade_trees = 4
        self.cascade_margin = margin
        self.applied = []
        self.metrics = self
        self.lat = {}

    def bucket_latency(self):
        return self.lat

    def set_cascade_margin(self, m):
        self.applied.append(m)


def test_cascade_autotuner_walks_ladder_one_rung_per_step():
    eng = _FakeTunerEngine(margin=0.8)
    tuner = CascadeAutotuner(eng, budget_ms=10.0, rungs=3, min_samples=5)
    assert tuner.step() is None              # no samples at all
    eng.lat = {16: {"count": 10, "p99_ms": 50.0}}
    assert tuner.step() == pytest.approx(0.4)   # one rung down, not two
    assert tuner.step() is None              # same counts: no FRESH samples
    eng.lat = {16: {"count": 20, "p99_ms": 50.0}}
    assert tuner.step() == pytest.approx(0.2)   # bottom rung
    eng.lat = {16: {"count": 30, "p99_ms": 50.0}}
    assert tuner.step() is None              # already at the bottom
    eng.lat = {16: {"count": 40, "p99_ms": 2.0}}
    assert tuner.step() == pytest.approx(0.4)   # headroom: back up
    eng.lat = {16: {"count": 50, "p99_ms": 8.0}}
    assert tuner.step() is None              # inside hysteresis band
    assert eng.applied == [pytest.approx(0.4), pytest.approx(0.2),
                           pytest.approx(0.4)]
    assert tuner.snapshot()["retunes"] == 3


# ------------------------------------------------------------ FileKvClient
def test_file_kv_client_contract(tmp_path):
    kv = FileKvClient(str(tmp_path))
    kv.key_value_set("fleet/a", "one")       # slash in the key is fine
    assert kv.blocking_key_value_get("fleet/a", 100) == "one"
    kv.key_value_set("fleet/a", "two")       # overwrite
    assert kv.try_get("fleet/a") == "two"
    assert kv.try_get("missing") is None
    kv.key_value_set("fleet/b", "x")
    kv.key_value_set("other", "y")
    assert kv.keys("fleet/") == ["fleet/a", "fleet/b"]
    kv.key_value_delete("fleet/a")
    kv.key_value_delete("fleet/a")           # idempotent
    assert kv.try_get("fleet/a") is None


def test_file_kv_client_timeout_is_deadline_exceeded(tmp_path):
    """The KvHostComm transient-vs-fatal marker: timeouts MUST carry
    DEADLINE_EXCEEDED in the message (parallel/network.py _transient)."""
    kv = FileKvClient(str(tmp_path), poll_interval_s=0.01)
    with pytest.raises(Exception, match="DEADLINE_EXCEEDED"):
        kv.blocking_key_value_get("never", timeout_ms=50)


def test_file_kv_client_blocking_get_sees_concurrent_set(tmp_path):
    kv = FileKvClient(str(tmp_path), poll_interval_s=0.005)
    t = threading.Timer(0.05, kv.key_value_set, args=("late", "value"))
    t.start()
    try:
        assert kv.blocking_key_value_get("late", 2000) == "value"
    finally:
        t.cancel()


# ------------------------------------------------------------ announcer
def test_announcer_roundtrip_lease_and_retract(tmp_path):
    kv = FileKvClient(str(tmp_path))
    ann = ReplicaAnnouncer(kv, "replica-a")
    doc = ann.announce_once()
    assert doc["replica"] == "replica-a" and doc["pid"] == os.getpid()
    # a replica that stopped announcing long ago is leased out
    stale = {"replica": "replica-b", "time": time.time() - 100}
    kv.key_value_set("fleet/replica-b", json.dumps(stale))
    kv.key_value_set("fleet/replica-c", "{not json")   # torn write: skipped
    fleet = ReplicaAnnouncer.read_fleet(kv, lease_s=10.0)
    assert fleet["replica-a"]["live"] is True
    assert fleet["replica-b"]["live"] is False
    assert "replica-c" not in fleet
    ann.retract()
    assert "replica-a" not in ReplicaAnnouncer.read_fleet(kv)


def _fleet_fixture(tmp_path, name):
    """One replica's registry/watcher/announcer over a shared KV dir."""
    kv = FileKvClient(str(tmp_path / "kv"))
    registry = ModelRegistry()
    watcher = registry.watch_dir("default", str(tmp_path / "ckpt"))
    ann = ReplicaAnnouncer(kv, name, watcher=watcher)
    return kv, registry, watcher, ann


def test_rolling_deploy_first_replica_rolls_immediately(tmp_path):
    bst, _, _ = _binary_booster(rounds=3)
    CheckpointManager(str(tmp_path / "ckpt")).save(bst)
    kv, registry, watcher, ann = _fleet_fixture(tmp_path, "a")
    coord = RollingDeployCoordinator(kv, ann, watcher,
                                     predecessor_timeout_s=5.0)
    assert coord.step() is True
    assert "default" in registry.ids()
    assert watcher._last_id >= 0
    # the roll was announced (unblocks successors without waiting a period)
    fleet = ReplicaAnnouncer.read_fleet(kv)
    assert fleet["a"]["snap_id"] == watcher._last_id
    assert coord.step() is False             # nothing new: no-op


def test_rolling_deploy_waits_for_predecessor_then_rolls(tmp_path):
    bst, _, _ = _binary_booster(rounds=3)
    CheckpointManager(str(tmp_path / "ckpt")).save(bst)
    kv, registry, watcher, ann = _fleet_fixture(tmp_path, "b")
    target = CheckpointManager(str(tmp_path / "ckpt")).latest_model()[0]
    # live predecessor "a" still serving an older snapshot: not ready
    kv.key_value_set("fleet/a", json.dumps(
        {"replica": "a", "time": time.time(), "snap_id": target - 1}))
    coord = RollingDeployCoordinator(kv, ann, watcher,
                                     poll_interval_s=0.02,
                                     predecessor_timeout_s=30.0)
    ready, rejected_by = coord._predecessors_ready(target)
    assert not ready and rejected_by is None
    # predecessor announces the target mid-wait -> we roll
    t = threading.Timer(0.05, kv.key_value_set, args=("fleet/a", json.dumps(
        {"replica": "a", "time": time.time(), "snap_id": target})))
    t.start()
    try:
        assert coord.step() is True
    finally:
        t.cancel()
    assert "default" in registry.ids()


def test_rolling_deploy_dead_predecessor_cannot_block(tmp_path):
    bst, _, _ = _binary_booster(rounds=3)
    CheckpointManager(str(tmp_path / "ckpt")).save(bst)
    kv, registry, watcher, ann = _fleet_fixture(tmp_path, "b")
    target = CheckpointManager(str(tmp_path / "ckpt")).latest_model()[0]
    kv.key_value_set("fleet/a", json.dumps(
        {"replica": "a", "time": time.time() - 100, "snap_id": -1}))
    coord = RollingDeployCoordinator(kv, ann, watcher,
                                     predecessor_timeout_s=30.0)
    assert coord._predecessors_ready(target) == (True, None)
    assert coord.step() is True


def test_rolling_deploy_canary_rejection_propagates(tmp_path):
    """A predecessor's announced rejection means this replica NEVER
    stages the snapshot — the fleet-wide canary contract."""
    bst, _, _ = _binary_booster(rounds=3)
    CheckpointManager(str(tmp_path / "ckpt")).save(bst)
    kv, registry, watcher, ann = _fleet_fixture(tmp_path, "c")
    target = CheckpointManager(str(tmp_path / "ckpt")).latest_model()[0]
    kv.key_value_set("fleet/a", json.dumps(
        {"replica": "a", "time": time.time(), "snap_id": -1,
         "rejected": [target]}))
    coord = RollingDeployCoordinator(kv, ann, watcher,
                                     predecessor_timeout_s=30.0)
    assert coord.step() is False
    assert target in watcher._rejected_ids
    assert "default" not in registry.ids()   # never staged, never registered
    # the propagated rejection is itself announced for replicas after "c"
    assert target in ReplicaAnnouncer.read_fleet(kv)["c"]["rejected"]
    assert coord.step() is False             # and it stays skipped


# ------------------------------------------------------- cluster provider
def test_fleet_cluster_provider_stats_and_prometheus(tmp_path):
    kv = FileKvClient(str(tmp_path))
    now = time.time()
    kv.key_value_set("fleet/a", json.dumps(
        {"replica": "a", "time": now, "snap_id": 3,
         "metrics": {"requests": 10, "shed": 1}}))
    kv.key_value_set("fleet/b", json.dumps(
        {"replica": "b", "time": now, "snap_id": 4,
         "metrics": {"requests": 5, "shed": 0}}))
    kv.key_value_set("fleet/c", json.dumps(
        {"replica": "c", "time": now - 100, "snap_id": 2,
         "metrics": {"requests": 99, "shed": 9}}))   # dead: excluded
    prov = FleetClusterProvider(kv, lease_s=10.0)
    stats = prov.cluster_stats()
    assert stats["fleet"]["replicas"] == 3
    assert stats["fleet"]["live"] == 2
    assert stats["fleet"]["requests"] == 15
    assert stats["fleet"]["shed"] == 1
    assert stats["fleet"]["snap_id_min"] == 3
    assert stats["fleet"]["snap_id_max"] == 4
    assert stats["fleet"]["rolling"] is True     # mid-deploy spread
    text = prov.cluster_prometheus()
    assert 'lgbm_fleet_replica_up{replica="a"} 1' in text
    assert 'lgbm_fleet_replica_up{replica="c"} 0' in text
    assert 'lgbm_fleet_replica_snap_id{replica="b"} 4' in text
    assert "lgbm_fleet_live_replicas 2" in text
    assert "lgbm_fleet_rolling 1" in text
