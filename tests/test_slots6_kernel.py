"""Round-4 batched-growth kernel + routing units.

Pins two things the end-to-end batched tests cannot isolate:
- build_histogram_slots6 (parent-slot x 6-channel joint kernel) against
  a per-slot numpy reference, including inactive rows and absent slots;
- the dense one-hot routing (route_split_rows) on an EFB-BUNDLED
  dataset under batched growth — the decode_bundle_value path rides
  sel_k one-hot selects there, which no dense-data test exercises.
"""
import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.boosting import create_boosting

from conftest import make_binary
from test_efb import _exclusive_groups


def test_slots6_matches_per_slot_reference():
    import jax.numpy as jnp
    from lightgbm_tpu.core.histogram_pallas import build_histogram_slots6

    r = np.random.RandomState(11)
    n, f, b, k = 5000, 6, 64, 4
    xb = r.randint(0, b, (n, f)).astype(np.uint8)
    slot = r.randint(-1, k, n).astype(np.int32)   # -1 = inactive
    slot[slot == k - 1] = -1                      # leave slot k-1 ABSENT
    sel = (r.rand(n) > 0.4).astype(np.float32)
    vals = r.randn(3, n).astype(np.float32)
    out = np.asarray(build_histogram_slots6(
        jnp.asarray(xb), jnp.asarray(slot), jnp.asarray(sel),
        jnp.asarray(vals), num_bins=b, n_slots=k, row_tile=512,
        interpret=True))
    assert out.shape == (k, f, b, 6)
    for s in range(k):
        m = slot == s
        ref = np.zeros((f, b, 6), np.float32)
        for ch in range(6):
            w = sel[m] if ch < 3 else 1.0 - sel[m]
            v = vals[ch % 3, m] * w
            for j in range(f):
                np.add.at(ref[j, :, ch], xb[m, j], v)
        np.testing.assert_allclose(out[s], ref, rtol=5e-2, atol=5e-2)


def _train(X, y, params, rounds=4):
    cfg = Config(params)
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    b = cfg, ds
    bst = create_boosting(cfg, ds, create_objective(cfg), [])
    for _ in range(rounds):
        bst.train_one_iter()
    return bst, ds


def test_batched_routing_on_efb_bundles():
    """Batched growth over an EFB-bundled dataset: K=1 must reproduce
    exact growth's split structure (the routing's decode_bundle_value
    path through the one-hot selects), and K=4 must stay accurate."""
    X, y = _exclusive_groups()
    base = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
            "min_data_in_leaf": 5, "tpu_hist_impl": "scatter"}
    be, ds_e = _train(X, y, dict(base, tree_growth="exact"))
    assert ds_e.num_columns < X.shape[1], "test requires real bundling"
    b1, _ = _train(X, y, dict(base, tree_growth="batched",
                              tree_batch_splits=1))
    for t0, t1 in zip(be.models, b1.models):
        np.testing.assert_array_equal(np.asarray(t0.split_feature),
                                      np.asarray(t1.split_feature))
        np.testing.assert_array_equal(np.asarray(t0.threshold_bin),
                                      np.asarray(t1.threshold_bin))
    b4, _ = _train(X, y, dict(base, tree_growth="batched",
                              tree_batch_splits=4))
    p0 = be.predict(X[:400], raw_score=True)
    p4 = b4.predict(X[:400], raw_score=True)
    # different split ORDER is fine; the models must agree in quality
    auc = lambda p: float(
        (np.argsort(np.argsort(p))[y[:400] > 0].sum()
         - (y[:400] > 0).sum() * ((y[:400] > 0).sum() + 1) / 2)
        / max((y[:400] > 0).sum() * (400 - (y[:400] > 0).sum()), 1))
    assert abs(auc(p0) - auc(p4)) < 0.05


def test_batched_part_routing_on_efb_bundles():
    """Same EFB routing contract for the partitioned batched grower
    (shares route_split_rows, but its own layout maintenance)."""
    X, y = _exclusive_groups()
    base = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
            "min_data_in_leaf": 5, "tpu_hist_impl": "scatter",
            "tree_growth": "batched", "tree_batch_splits": 4}
    b0, _ = _train(X, y, dict(base))
    b1, _ = _train(X, y, dict(base, tpu_batched_part="true"))
    for t0, t1 in zip(b0.models, b1.models):
        np.testing.assert_array_equal(np.asarray(t0.split_feature),
                                      np.asarray(t1.split_feature))
        np.testing.assert_array_equal(np.asarray(t0.threshold_bin),
                                      np.asarray(t1.threshold_bin))
