"""Corpus: LGL104 dtype-less jnp construction in jit-traced code."""
import jax
import jax.numpy as jnp


@jax.jit
def bad_arange(n_static):
    idx = jnp.arange(8)  # EXPECT=LGL104
    z = jnp.zeros((8,))  # EXPECT=LGL104
    return idx + z


@jax.jit
def explicit_ok(x):
    idx = jnp.arange(8, dtype=jnp.int32)
    z = jnp.zeros((8,), jnp.float32)
    return x + idx + z


def host_side_ok():
    # not traced: weak dtype here never recompiles a device program
    return jnp.arange(8)
