"""Corpus: LGL101 tracer-unsafe branch.  `# EXPECT=RULE` marks the
exact line each rule must fire on; tests/test_analysis.py parses the
markers and asserts the finding set matches them exactly."""
import jax
import jax.numpy as jnp


@jax.jit
def bad_branch(x):
    y = jnp.abs(x)
    if y > 0:  # EXPECT=LGL101
        return x * 2.0
    return x


@jax.jit
def bad_while(x):
    s = x.sum()
    while s > 1.0:  # EXPECT=LGL101
        s = s / 2.0
    return s


@jax.jit
def suppressed_branch(x):
    y = jnp.abs(x)
    # lgbm-lint: disable=LGL101 demonstrating the suppression channel
    if y > 0:
        return y
    return x


@jax.jit
def static_dispatch_ok(x, impl="scatter", row_chunk=1024):
    # static python params: none of these may fire (the histogram.py
    # false-positive class the array-evidence pass exists for)
    if impl == "scatter":
        x = x * 2.0
    n = x.shape[0]
    pad = row_chunk - n
    if pad:
        x = x + 1.0
    if n <= row_chunk:
        x = x - 1.0
    return x


def host_fn(x):
    # not traced: branching on data here is ordinary python
    if x > 0:
        return 1
    return 0
