"""Corpus: LGL107 config parameter reads config.py does not declare."""


def typo_read(cfg):
    return cfg.learning_rte  # EXPECT=LGL107


def declared_ok(cfg):
    return cfg.learning_rate


def alias_ok(cfg):
    # aliases resolve through the canonical table
    return cfg.num_leaves


def method_ok(config):
    # method access on a config object is not a parameter read
    return config.update()
