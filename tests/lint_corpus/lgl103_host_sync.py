"""Corpus: LGL103 host syncs outside approved, suppressed sites."""
import jax


def hot_loop(fn, xs):
    out = None
    for x in xs:
        out = fn(x)
        jax.block_until_ready(out)  # EXPECT=LGL103
    return out


def fetch(x):
    return jax.device_get(x)  # EXPECT=LGL103


def span_close(x):
    jax.block_until_ready(x)  # lgbm-lint: disable=LGL103 span close site
    return x
