"""Corpus: a file-level suppression silences a rule everywhere."""
# lgbm-lint: disable-file=LGL103 benchmark helper, syncs are the point
import jax


def timed_a(x):
    jax.block_until_ready(x)
    return x


def timed_b(x):
    jax.block_until_ready(x)
    return x
