"""Corpus: LGL102 tracer concretization inside jit-traced code."""
import jax
import jax.numpy as jnp


@jax.jit
def bad_float(x):
    y = x.sum()
    return float(y)  # EXPECT=LGL102


@jax.jit
def bad_item(x):
    y = jnp.max(x)
    return y.item()  # EXPECT=LGL102


def inner_lambda_bad(xs):
    # the lambda is traced by scan; float() inside it concretizes
    return jax.lax.scan(
        lambda c, x: (c + float(x), c),  # EXPECT=LGL102
        0.0, xs)


def host_ok(arr):
    # host-side float() of host data is fine
    return float(arr[0])
