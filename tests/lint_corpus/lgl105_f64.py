"""Corpus: LGL105 f64-producing constructs on the device path."""
import jax
import jax.numpy as jnp
import numpy as np


def bad_cast(x):
    return x.astype(jnp.float64)  # EXPECT=LGL105


def bad_dtype_string(n):
    return jnp.zeros((n,), dtype="float64")  # EXPECT=LGL105


def bad_x64_flip():
    jax.config.update("jax_enable_x64", True)  # EXPECT=LGL105


def gated_fallback(x):
    # lgbm-lint: disable=LGL105 explicit double-precision opt-in
    return x.astype(jnp.float64)


def host_ok(a):
    # host-side numpy f64 never lowers to a device program
    return np.float64(a)
