"""Corpus: LGL106 module-global mutation inside jit-traced code."""
import jax

_CALLS = 0
_CACHE = {}


@jax.jit
def bad_global(x):
    global _CALLS  # EXPECT=LGL106
    _CALLS = _CALLS + 1  # EXPECT=LGL106
    return x


@jax.jit
def bad_container(x):
    _CACHE["last"] = x  # EXPECT=LGL106
    return x


def host_ok(x):
    _CACHE["host"] = x
    return x
