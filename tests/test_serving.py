"""lightgbm_tpu.serving — bucketing, parity, zero-recompile, transports.

Contracts pinned here:
- bucket_rows: power-of-two ladder with a min floor and a max cap, so the
  compiled-shape universe is finite and warmup can enumerate it;
- served predictions match Booster.predict to 1e-6 for EVERY golden model
  (binary / multiclass / lambdarank / regression), exact-bucket and padded
  sizes, single- and multi-device mesh (and bit-exactly in practice: the
  serving forward pass accumulates f32 per class in iteration order, the
  same order GBDT.predict uses);
- after warmup over all buckets, randomized-size traffic causes ZERO new
  predictor-cache misses and ZERO XLA backend compiles (jax.monitoring
  hook) — the acceptance criterion tools/serve_smoke.py asserts at scale;
- the micro-batch queue returns each caller exactly its rows, including
  across coalesced mixed-size submissions and for error requests;
- HTTP and stdin front-ends speak the documented JSON schema.

Golden pred-ref comparisons (served output vs the reference CLI's
predictions) additionally run when /root/reference example data exists.
"""
import io
import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.serving import (MicroBatchQueue, ModelRegistry,
                                  ServingEngine, ServingMetrics, build_app,
                                  bucket_rows, bucket_sizes, make_server,
                                  serve_stdin)
from lightgbm_tpu.log import LightGBMError

from conftest import make_binary

GOLDEN = os.path.join(os.path.dirname(__file__), "golden")
EXAMPLES = "/root/reference/examples"

GOLDEN_MODELS = ["model_ref.txt", "multiclass_model_ref.txt",
                 "rank_model_ref.txt", "regression_model_ref.txt"]


def needs_ref_data(task, fname):
    return pytest.mark.skipif(
        not os.path.exists(os.path.join(EXAMPLES, task, fname)),
        reason="reference %s example data not available" % task)


# --------------------------------------------------------------- bucketing
def test_bucket_rows_ladder():
    assert bucket_rows(1) == 16          # min floor
    assert bucket_rows(16) == 16         # exact power of two
    assert bucket_rows(17) == 32         # next power of two
    assert bucket_rows(100) == 128
    assert bucket_rows(4096) == 4096
    assert bucket_rows(5000) == 4096     # capped (engine chunks the rest)
    assert bucket_rows(3, min_bucket=1) == 4
    with pytest.raises(LightGBMError):
        bucket_rows(0)


def test_bucket_sizes_enumerates_ladder():
    assert bucket_sizes(16, 4096) == [16, 32, 64, 128, 256, 512, 1024,
                                      2048, 4096]
    assert bucket_sizes(64, 64) == [64]
    # engine normalizes non-powers up, so the ladder stays exact
    eng = ServingEngine(max_batch=1000, min_bucket=10)
    assert eng.min_bucket == 16 and eng.max_batch == 1024


# ----------------------------------------------------------- golden parity
def _engine_with(model_file, model_id, **kw):
    eng = ServingEngine(**kw)
    eng.registry.load_file(model_id, os.path.join(GOLDEN, model_file))
    return eng


@pytest.mark.parametrize("model_file", GOLDEN_MODELS)
def test_served_matches_booster_predict(model_file):
    """Every golden model, exact-bucket and padded sizes, raw and
    transformed, vs Booster.predict on the same rows."""
    bst = lgb.Booster(model_file=os.path.join(GOLDEN, model_file))
    nf = bst.num_feature()
    eng = _engine_with(model_file, "m", max_batch=256, min_bucket=16)
    rng = np.random.RandomState(3)
    for n in (1, 15, 16, 17, 100, 256, 300):   # padded, exact, chunked
        X = rng.rand(n, nf).astype(np.float32) * 2
        got = eng.predict("m", X)
        ref = bst.predict(X)
        np.testing.assert_allclose(got, ref, atol=1e-6, rtol=0)
        got_raw = eng.predict("m", X, raw_score=True)
        ref_raw = bst.predict(X, raw_score=True)
        np.testing.assert_allclose(got_raw, ref_raw, atol=1e-6, rtol=0)


@pytest.mark.parametrize("model_file", ["model_ref.txt",
                                        "multiclass_model_ref.txt"])
def test_served_matches_booster_multidevice(model_file):
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    bst = lgb.Booster(model_file=os.path.join(GOLDEN, model_file))
    nf = bst.num_feature()
    eng = _engine_with(model_file, "m", max_batch=128, min_bucket=4,
                       num_devices=0)
    assert eng.mesh is not None and eng.mesh.devices.size == 8
    rng = np.random.RandomState(4)
    # 4 < ndev (replicated entry), 8 == ndev, 100 -> 128 (sharded entry)
    for n in (1, 4, 8, 9, 100, 128, 200):
        X = rng.rand(n, nf).astype(np.float32) * 2
        np.testing.assert_allclose(eng.predict("m", X), bst.predict(X),
                                   atol=1e-6, rtol=0)


def test_served_num_iteration_capping():
    bst = lgb.Booster(model_file=os.path.join(GOLDEN, "model_ref.txt"))
    nf = bst.num_feature()
    eng = _engine_with("model_ref.txt", "m", max_batch=64)
    X = np.random.RandomState(5).rand(20, nf).astype(np.float32)
    for ni in (1, 3, None):
        np.testing.assert_allclose(
            eng.predict("m", X, num_iteration=ni),
            bst.predict(X, num_iteration=ni), atol=1e-6, rtol=0)


@needs_ref_data("binary_classification", "binary.test")
def test_served_matches_reference_pred_file():
    from lightgbm_tpu.io.parser import parse_file
    X, _, _ = parse_file(os.path.join(EXAMPLES, "binary_classification",
                                      "binary.test"), has_header=False)
    eng = _engine_with("model_ref.txt", "m")
    golden = np.loadtxt(os.path.join(GOLDEN, "pred_ref.txt"))
    np.testing.assert_allclose(eng.predict("m", X), golden, atol=1e-6)


@needs_ref_data("lambdarank", "rank.test")
def test_served_matches_reference_rank_pred_file():
    from lightgbm_tpu.io.parser import parse_file
    X, _, _ = parse_file(os.path.join(EXAMPLES, "lambdarank", "rank.test"),
                         has_header=False)
    eng = _engine_with("rank_model_ref.txt", "m")
    golden = np.loadtxt(os.path.join(GOLDEN, "rank_pred_ref.txt"))
    np.testing.assert_allclose(eng.predict("m", X), golden, atol=1e-6)


@needs_ref_data("multiclass_classification", "multiclass.test")
def test_served_matches_reference_multiclass_pred_file():
    from lightgbm_tpu.io.parser import parse_file
    X, _, _ = parse_file(os.path.join(EXAMPLES, "multiclass_classification",
                                      "multiclass.test"), has_header=False)
    eng = _engine_with("multiclass_model_ref.txt", "m")
    golden = np.loadtxt(os.path.join(GOLDEN, "multiclass_pred_ref.txt"))
    np.testing.assert_allclose(eng.predict("m", X), golden, atol=1e-6)


# ---------------------------------------------------------- zero recompile
@pytest.mark.slow
@pytest.mark.slow
def test_zero_recompiles_after_warmup():
    """The tentpole property: warmup enumerates every (bucket, raw) entry,
    then randomized-size traffic never compiles again — asserted on BOTH
    signals (predictor-cache misses and the XLA backend-compile hook)."""
    eng = _engine_with("model_ref.txt", "m", max_batch=512, min_bucket=16)
    nf = eng.registry.get("m").num_features
    rng = np.random.RandomState(6)
    # reference outputs computed BEFORE warmup: Booster.predict compiles
    # per shape and would otherwise pollute the process-wide compile count
    sizes = [int(s) for s in rng.randint(1, 1300, size=40)]
    bst = lgb.Booster(model_file=os.path.join(GOLDEN, "model_ref.txt"))
    queries = [rng.rand(n, nf).astype(np.float32) for n in sizes]
    refs = [bst.predict(X) for X in queries]

    warmed = eng.warmup(raw_scores=(False, True))
    assert warmed == len(bucket_sizes(16, 512)) * 2
    for X, ref in zip(queries, refs):
        np.testing.assert_allclose(eng.predict("m", X), ref, atol=1e-6)
    assert eng.metrics.cache_misses_after_warmup() == 0
    assert eng.metrics.recompiles_after_warmup() == 0
    assert eng.cache_size() == warmed


# ------------------------------------------------------- micro-batch queue
def test_micro_batch_queue_roundtrip():
    eng = _engine_with("model_ref.txt", "m", max_batch=128)
    nf = eng.registry.get("m").num_features
    bst = lgb.Booster(model_file=os.path.join(GOLDEN, "model_ref.txt"))
    q = MicroBatchQueue(eng, deadline_ms=10).start()
    try:
        rng = np.random.RandomState(7)
        queries = [rng.rand(k, nf).astype(np.float32)
                   for k in (1, 2, 5, 1, 30, 3)]
        # mixed keys in flight at once: raw and transformed must not fuse
        futs = [q.submit("m", X) for X in queries]
        futs_raw = [q.submit("m", X, raw_score=True) for X in queries[:2]]
        for X, f in zip(queries, futs):
            np.testing.assert_allclose(f.result(timeout=60), bst.predict(X),
                                       atol=1e-6)
        for X, f in zip(queries, futs_raw):
            np.testing.assert_allclose(f.result(timeout=60),
                                       bst.predict(X, raw_score=True),
                                       atol=1e-6)
        assert eng.metrics.queue_depth == 0
    finally:
        q.stop()


def test_micro_batch_queue_coalesces():
    """With a generous deadline, requests submitted together dispatch as
    fewer engine batches than requests."""
    eng = _engine_with("model_ref.txt", "m", max_batch=64)
    nf = eng.registry.get("m").num_features
    eng.warmup()
    base_batches = eng.metrics.batches
    q = MicroBatchQueue(eng, deadline_ms=250).start()
    try:
        X = np.random.RandomState(8).rand(2, nf).astype(np.float32)
        futs = [q.submit("m", X) for _ in range(8)]
        for f in futs:
            assert f.result(timeout=60).shape == (2,)
    finally:
        q.stop()
    assert eng.metrics.batches - base_batches < 8   # fused
    assert eng.metrics.requests == 8                # per-caller accounting


def test_micro_batch_queue_error_delivery():
    eng = _engine_with("model_ref.txt", "m")
    q = MicroBatchQueue(eng, deadline_ms=1).start()
    try:
        bad = q.submit("m", np.zeros((2, 3), np.float32))   # wrong features
        unknown = q.submit("nope", np.zeros((2, 3), np.float32))
        with pytest.raises(LightGBMError):
            bad.result(timeout=60)
        with pytest.raises(LightGBMError):
            unknown.result(timeout=60)
    finally:
        q.stop()
    assert eng.metrics.errors >= 2


# ----------------------------------------------------------------- metrics
def test_metrics_snapshot_schema_and_jsonl(tmp_path):
    m = ServingMetrics(window=8)
    m.record_request(5, 0.002)
    m.record_request(7, 0.004)
    m.record_batch(16)
    m.record_cache(hit=False)
    m.record_cache(hit=True)
    m.set_queue_depth(3)
    snap = m.snapshot()
    for key in ("ts", "uptime_s", "requests", "rows", "batches",
                "rows_per_batch", "queue_depth", "cache_hits",
                "cache_misses", "errors", "backend_compiles",
                "recompiles_after_warmup", "latency_ms"):
        assert key in snap, key
    assert snap["requests"] == 2 and snap["rows"] == 12
    assert snap["cache_hits"] == 1 and snap["cache_misses"] == 1
    assert snap["queue_depth"] == 3
    lat = snap["latency_ms"]
    assert lat["count"] == 2 and lat["p50_ms"] <= lat["p99_ms"] <= lat["max_ms"]
    path = tmp_path / "metrics.jsonl"
    m.write_jsonl(str(path))
    m.write_jsonl(str(path))
    lines = path.read_text().splitlines()
    assert len(lines) == 2 and json.loads(lines[1])["requests"] == 2


def test_latency_summary_quantiles():
    from lightgbm_tpu.profiling import latency_summary
    s = latency_summary(range(1, 101))
    assert s["count"] == 100 and s["p50_ms"] == pytest.approx(50.5)
    assert s["p99_ms"] == pytest.approx(99.01) and s["max_ms"] == 100
    assert latency_summary([])["count"] == 0


# -------------------------------------------------------------- front-ends
def _golden_config(**extra):
    d = {"task": "serve", "input_model": os.path.join(GOLDEN, "model_ref.txt"),
         "serve_max_batch": 64, "serve_min_bucket": 8, "verbosity": -1}
    d.update(extra)
    return Config(d)


def test_http_server_roundtrip():
    app = build_app(_golden_config())
    try:
        bst = lgb.Booster(model_file=os.path.join(GOLDEN, "model_ref.txt"))
        nf = bst.num_feature()
        srv = make_server(app, "127.0.0.1", 0)       # port 0: OS-assigned
        host, port = srv.server_address
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            X = np.random.RandomState(9).rand(5, nf)
            body = json.dumps({"data": X.tolist()}).encode()
            rep = json.loads(urllib.request.urlopen(urllib.request.Request(
                "http://%s:%d/predict" % (host, port), data=body)).read())
            assert rep["rows"] == 5 and rep["model"] == "default"
            np.testing.assert_allclose(rep["predictions"], bst.predict(X),
                                       atol=1e-6)
            met = json.loads(urllib.request.urlopen(
                "http://%s:%d/metrics" % (host, port)).read())
            assert met["requests"] == 1
            health = json.loads(urllib.request.urlopen(
                "http://%s:%d/healthz" % (host, port)).read())
            assert health["status"] == "ok"
            assert health["models"] == ["default"]
            assert health["breaker"]["state"] == "closed"
            # drift is advisory metadata; a bare model file carries
            # no training profile, so it reports so explicitly
            assert health["drift"] == "no_profile"
            models = json.loads(urllib.request.urlopen(
                "http://%s:%d/models" % (host, port)).read())
            assert models["models"][0]["num_features"] == nf
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(urllib.request.Request(
                    "http://%s:%d/predict" % (host, port), data=b"{}"))
            assert exc.value.code == 400
        finally:
            srv.shutdown()
            srv.server_close()
    finally:
        app.close()


def test_stdin_transport():
    app = build_app(_golden_config())
    try:
        nf = app.engine.registry.get("default").num_features
        X = np.random.RandomState(10).rand(3, nf)
        lines = (json.dumps({"data": X.tolist()}) + "\n"
                 + json.dumps({"data": [[0.0]]}) + "\n")   # second: bad width
        out = io.StringIO()
        served = serve_stdin(app, io.StringIO(lines), out)
        assert served == 2
        ok, bad = [json.loads(s) for s in out.getvalue().splitlines()]
        assert ok["rows"] == 3
        assert "error" in bad and "features" in bad["error"]
    finally:
        app.close()


def test_cli_serve_stdin_subprocess():
    """task=serve end to end through the real CLI in a subprocess."""
    import subprocess
    import sys
    bst = lgb.Booster(model_file=os.path.join(GOLDEN, "model_ref.txt"))
    X = np.random.RandomState(11).rand(2, bst.num_feature())
    req = json.dumps({"data": X.tolist()}) + "\n"
    p = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", "task=serve",
         "input_model=%s" % os.path.join(GOLDEN, "model_ref.txt"),
         "serve_stdin=true", "serve_max_batch=16", "serve_min_bucket=8",
         "serve_warmup=false", "verbosity=-1"],
        input=req, capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert p.returncode == 0, p.stderr[-1500:]
    reply = json.loads([l for l in p.stdout.splitlines()
                        if l.startswith("{")][-1])
    np.testing.assert_allclose(reply["predictions"], bst.predict(X),
                               atol=1e-6)


# ---------------------------------------------------------------- registry
def test_registry_rejects_duplicates_and_unknown():
    reg = ModelRegistry()
    reg.load_file("m", os.path.join(GOLDEN, "model_ref.txt"))
    with pytest.raises(LightGBMError):
        reg.load_file("m", os.path.join(GOLDEN, "model_ref.txt"))
    with pytest.raises(LightGBMError):
        reg.get("other")
    assert reg.ids() == ["m"]


def test_trained_booster_served_in_process():
    """The embedder path: train, as_serving_bundle, serve — no file."""
    X, y = make_binary(n=400, f=6)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "min_data_in_leaf": 5}, lgb.Dataset(X, label=y),
                    num_boost_round=5)
    eng = ServingEngine(max_batch=64, min_bucket=8)
    eng.registry.register(bst.as_serving_bundle("live"))
    np.testing.assert_allclose(eng.predict("live", X[:33]), bst.predict(X[:33]),
                               atol=1e-6)
