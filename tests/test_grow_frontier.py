"""Frontier-wave growth (core/grow_frontier.py, tree_growth=frontier).

Contract being pinned:
- when the num_leaves cap never binds, frontier growth performs exactly
  the split SET of the exact leaf-wise algorithm (each leaf's best split
  depends only on its own rows), so the golden structure matches — node
  NUMBERING differs (wave order vs global best-first order), so the
  comparison is the canonical multiset of splits plus predictions;
- on capped workloads quality stays close to exact (same documented
  approximation stance as tree_growth=batched);
- the data-parallel mesh path (one psum per WAVE) matches single-device;
- order-dependent features (forced splits, CEGB, voting) refuse loudly.
"""
import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.log import LightGBMError
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.metrics import create_metric
from lightgbm_tpu.boosting import create_boosting

from conftest import make_binary


def _train(X, y, params, rounds=10, **ds_kw):
    cfg = Config(params)
    ds = BinnedDataset.from_matrix(X, cfg, label=y, **ds_kw)
    mets = [m for m in (create_metric(n, cfg) for n in (cfg.metric or []))
            if m]
    b = create_boosting(cfg, ds, create_objective(cfg), mets)
    for _ in range(rounds):
        if b.train_one_iter():
            break
    return b


def _canonical_splits(booster, num=3):
    """Order-independent view of each tree: sorted (feature, threshold_bin)
    multiset + sorted (leaf_count, leaf_value) multiset."""
    out = []
    for t in booster.models[:num]:
        nn = t.num_leaves - 1
        splits = sorted(zip(t.split_feature[:nn].tolist(),
                            t.threshold_bin[:nn].tolist()))
        leaves = sorted(zip(t.leaf_count[:t.num_leaves].tolist(),
                            np.round(t.leaf_value[:t.num_leaves],
                                     5).tolist()))
        out.append((splits, leaves))
    return out


def _golden_data():
    """Strong-signal, shallow golden dataset: no near-tie gains at any
    node (verified over seeds), so fp summation-order differences between
    the per-leaf and frontier histogram paths cannot flip an argmax."""
    rng = np.random.default_rng(0)
    n = 600
    X = rng.normal(size=(n, 6))
    logit = (1.5 * X[:, 0] + 1.0 * X[:, 1] - 0.8 * X[:, 2]
             + 0.5 * X[:, 3] * X[:, 4])
    y = (logit + 0.3 * rng.normal(size=n) > 0).astype(np.float64)
    return X.astype(np.float32), y


def test_frontier_golden_structure_matches_exact():
    """Uncapped growth: the frontier split SET is identical to exact
    (ISSUE 2 acceptance: identical split structure on a golden dataset)."""
    X, y = _golden_data()
    base = {"objective": "binary", "num_leaves": 64, "max_depth": 4,
            "min_data_in_leaf": 40, "verbosity": -1}
    be = _train(X, y, dict(base, tree_growth="exact"), rounds=3)
    bf = _train(X, y, dict(base, tree_growth="frontier"), rounds=3)
    assert _canonical_splits(be) == _canonical_splits(bf)
    pe = be.predict(X, raw_score=True)
    pf = bf.predict(X, raw_score=True)
    np.testing.assert_allclose(pe, pf, rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_frontier_quality_close_to_exact_capped():
    """When the leaf cap binds, wave truncation is approximate best-first
    (same stance as batched K>1): quality must stay close."""
    X, y = make_binary(n=4000)
    base = {"objective": "binary", "num_leaves": 63, "metric": "auc",
            "verbosity": -1}
    be = _train(X, y, dict(base, tree_growth="exact"), rounds=15)
    bf = _train(X, y, dict(base, tree_growth="frontier"), rounds=15)
    auc_e = dict((m, v) for _, m, v, _ in be.get_eval_at(0))["auc"]
    auc_f = dict((m, v) for _, m, v, _ in bf.get_eval_at(0))["auc"]
    assert auc_f > 0.95
    assert abs(auc_e - auc_f) < 0.02


def test_frontier_fills_leaf_budget():
    """A learnable problem must still grow to the num_leaves budget —
    the wave's prefix-mask bookkeeping must not strand capacity."""
    X, y = make_binary(n=4000)
    b = _train(X, y, {"objective": "binary", "num_leaves": 33,
                      "tree_growth": "frontier", "min_data_in_leaf": 2,
                      "verbosity": -1}, rounds=2)
    assert b.models[0].num_leaves == 33


@pytest.mark.slow
def test_frontier_sweeps_scale_with_depth():
    """The whole point: dataset sweeps per tree = max leaf depth + 1,
    not num_leaves - 1 (ISSUE 2 acceptance)."""
    from lightgbm_tpu.profiling import phase_probe
    X, y = make_binary(n=2000)
    b = _train(X, y, {"objective": "binary", "num_leaves": 31,
                      "tree_growth": "frontier", "verbosity": -1},
               rounds=2)
    phases = phase_probe(b)
    assert "frontier_hist" in phases and phases["frontier_hist"] > 0
    waves = phases["frontier_waves"]
    # a 31-leaf tree needs at least ceil(log2(31)) = 5 waves and at most
    # 30 (degenerate chain); on this learnable workload it must be far
    # below the per-leaf sweep count
    assert 5 <= waves <= 30
    assert phases["frontier_sweeps_per_tree"] == waves + 1
    assert phases["frontier_sweeps_per_tree"] < b.models[0].num_leaves - 1


@pytest.mark.slow
def test_frontier_predict_matches_train_scores():
    X, y = make_binary(n=1500)
    b = _train(X, y, {"objective": "binary", "tree_growth": "frontier",
                      "verbosity": -1}, rounds=8)
    pred = b.predict(X, raw_score=True)
    np.testing.assert_allclose(pred, np.asarray(b.scores)[:, 0],
                               rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_frontier_data_parallel_matches_single_device():
    """Eight-device data-parallel frontier growth must reproduce the
    single-device model (the collective is one psum per WAVE)."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    # the tie-free golden config: psum reordering across shards perturbs
    # gains in the last ulp, which on a near-tie workload can flip a deep
    # argmax and cascade — the same fp sensitivity every grower has under
    # sharding, not a frontier property
    X, y = _golden_data()
    base = {"objective": "binary", "num_leaves": 64, "max_depth": 4,
            "min_data_in_leaf": 40, "verbosity": -1,
            "tree_growth": "frontier"}
    b1 = _train(X, y, dict(base), rounds=5)
    b8 = _train(X, y, dict(base, tree_learner="data", num_machines=1,
                           mesh_shape=[8]), rounds=5)
    assert _canonical_splits(b1, num=5) == _canonical_splits(b8, num=5)
    p1 = b1.predict(X[:200], raw_score=True)
    p8 = b8.predict(X[:200], raw_score=True)
    np.testing.assert_allclose(p1, p8, rtol=2e-4, atol=2e-4)


def test_frontier_refuses_order_dependent_features():
    X, y = make_binary(n=500)
    with pytest.raises(LightGBMError, match="frontier"):
        _train(X, y, {"objective": "binary", "tree_growth": "frontier",
                      "verbosity": -1,
                      "cegb_penalty_feature_coupled": [0.1] * X.shape[1],
                      "cegb_tradeoff": 1.0}, rounds=1)
    # the explicit feature-parallel learner needs grow_tree's fp context
    with pytest.raises(LightGBMError, match="frontier"):
        _train(X, y, {"objective": "binary", "tree_growth": "frontier",
                      "tree_learner": "feature", "verbosity": -1}, rounds=1)
    # voting rides the frontier waves now (parallel/learners.py) but still
    # refuses batched growth, whose commit loop has no election seam
    with pytest.raises(LightGBMError, match="voting"):
        _train(X, y, {"objective": "binary", "tree_growth": "batched",
                      "tree_learner": "voting", "verbosity": -1}, rounds=1)


@pytest.mark.slow
def test_frontier_slot_kernel_end_to_end():
    """Frontier growth through the Pallas slot kernel (interpret mode)
    must match the scatter frontier build."""
    X, y = make_binary(n=1200, f=6)
    base = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
            "tree_growth": "frontier"}
    bs = _train(X, y, dict(base, tpu_hist_impl="scatter"), rounds=3)
    bp = _train(X, y, dict(base, tpu_hist_impl="pallas_interpret"),
                rounds=3)
    ps = bs.predict(X[:300], raw_score=True)
    pp = bp.predict(X[:300], raw_score=True)
    np.testing.assert_allclose(ps, pp, rtol=2e-4, atol=2e-4)


def test_config_validates_growth_and_hist_impl():
    """ISSUE 2 satellite: unknown tree_growth / tpu_hist_impl values fail
    loudly at config time."""
    with pytest.raises(LightGBMError, match="tree_growth"):
        Config({"tree_growth": "levelwise"})
    with pytest.raises(LightGBMError, match="tpu_hist_impl"):
        Config({"tpu_hist_impl": "palas"})
    # the alias from the issue spelling resolves to the canonical name
    assert Config({"tree_grow_mode": "frontier"}).tree_growth == "frontier"
    assert Config({"tpu_hist_impl": " Scatter "}).tpu_hist_impl == "scatter"
