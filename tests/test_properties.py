"""Reference-scenario and property tests (SURVEY.md §4 implication (c):
the invariants the reference only asserts under #ifdef DEBUG).
"""
import pickle

import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.core.histogram import build_histogram, fix_histogram


def test_booster_pickle_roundtrip():
    rng = np.random.RandomState(0)
    X = rng.randn(600, 4)
    y = (X[:, 0] > 0).astype(float)
    bst = lgb.train({"objective": "binary", "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=4)
    bst.best_iteration = 3
    clone = pickle.loads(pickle.dumps(bst))
    np.testing.assert_array_equal(clone.predict(X), bst.predict(X))
    assert clone.best_iteration == 3
    # and the clone itself re-serializes
    again = pickle.loads(pickle.dumps(clone))
    np.testing.assert_array_equal(again.predict(X), bst.predict(X))


def test_sklearn_estimator_pickle_roundtrip():
    rng = np.random.RandomState(1)
    X = rng.randn(500, 5)
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    clf = lgb.LGBMClassifier(n_estimators=4, num_leaves=15)
    clf.fit(X, y)
    clone = pickle.loads(pickle.dumps(clf))
    np.testing.assert_array_equal(clone.predict_proba(X),
                                  clf.predict_proba(X))


def test_non_contiguous_input():
    """Sliced ndarray views train and predict (test_engine.py:630)."""
    rng = np.random.RandomState(2)
    Xbig = rng.randn(1200, 8)
    y = (Xbig[:, 1] > 0).astype(float)
    Xs = Xbig[::2, 1:6]                      # non-contiguous view
    assert not Xs.flags["C_CONTIGUOUS"]
    bst = lgb.train({"objective": "binary", "verbosity": -1},
                    lgb.Dataset(Xs, label=y[::2]), num_boost_round=3)
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y[::2], bst.predict(Xs)) > 0.95


def test_constant_features_dropped():
    """Constant columns are trivial (test_engine.py:789-819): never split
    on, and a fully-constant dataset still trains a constant model."""
    rng = np.random.RandomState(3)
    X = rng.randn(400, 3)
    X[:, 1] = 7.0
    y = (X[:, 0] > 0).astype(float)
    bst = lgb.train({"objective": "binary", "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    assert bst.feature_importance()[1] == 0


def test_get_split_value_histogram():
    rng = np.random.RandomState(4)
    X = rng.randn(600, 3)
    y = (X[:, 0] > 0).astype(float)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 15}, lgb.Dataset(X, label=y),
                    num_boost_round=5)
    counts, edges = bst.get_split_value_histogram(0)
    assert counts.sum() > 0
    assert len(edges) == len(counts) + 1


def test_histogram_subtraction_consistency():
    """parent == left + right for any partition of the rows (the
    FeatureHistogram::Subtract invariant)."""
    rng = np.random.RandomState(5)
    n, f, b = 5000, 6, 64
    xb = jnp.asarray(rng.randint(0, b, (n, f)).astype(np.uint8))
    g = jnp.asarray(rng.randn(n).astype(np.float32))
    h = jnp.asarray(rng.rand(n).astype(np.float32))
    left = (rng.rand(n) < 0.4).astype(np.float32)
    parent = build_histogram(xb, g, h, jnp.ones(n, jnp.float32), b,
                             impl="scatter")
    hl = build_histogram(xb, g, h, jnp.asarray(left), b, impl="scatter")
    hr = build_histogram(xb, g, h, jnp.asarray(1.0 - left), b,
                         impl="scatter")
    np.testing.assert_allclose(np.asarray(hl + hr), np.asarray(parent),
                               rtol=1e-5, atol=1e-4)


def test_fix_histogram_restores_totals():
    """After fix_histogram the per-feature sums equal the exact leaf
    totals (Dataset::FixHistogram, dataset.h:411-412)."""
    rng = np.random.RandomState(6)
    n, f, b = 2000, 4, 32
    xb = jnp.asarray(rng.randint(0, b, (n, f)).astype(np.uint8))
    g = jnp.asarray(rng.randn(n).astype(np.float32))
    h = jnp.asarray(rng.rand(n).astype(np.float32))
    hist = build_histogram(xb, g, h, jnp.ones(n, jnp.float32), b,
                           impl="scatter")
    # corrupt the default bin, then repair it from totals
    default_bins = jnp.zeros(f, jnp.int32)
    corrupted = hist.at[:, 0, :].add(7.0)
    sum_g, sum_h = jnp.sum(g), jnp.sum(h)
    fixed = fix_histogram(corrupted, default_bins, sum_g, sum_h,
                          jnp.float32(n))
    totals = np.asarray(fixed).sum(axis=1)                   # [F, 3]
    np.testing.assert_allclose(totals[:, 0], float(sum_g), rtol=1e-4)
    np.testing.assert_allclose(totals[:, 1], float(sum_h), rtol=1e-4)
    np.testing.assert_allclose(totals[:, 2], float(n), rtol=1e-5)


def test_partition_counts_match_split_info():
    """Per-leaf row counts derived from the final leaf assignment equal
    the counts the split search recorded (the reference's #ifdef DEBUG
    CHECK, serial_tree_learner.cpp:820-822)."""
    rng = np.random.RandomState(7)
    X = rng.randn(3000, 5).astype(np.float32)
    y = (X[:, 0] + np.sin(X[:, 1] * 2) > 0).astype(np.float32)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 31}, lgb.Dataset(X, label=y),
                    num_boost_round=2)
    leaves = bst.predict(X, pred_leaf=True)   # [N, num_trees]
    for t_idx, ht in enumerate(bst._impl.models):
        got = np.bincount(leaves[:, t_idx],
                          minlength=ht.num_leaves_actual)
        np.testing.assert_array_equal(
            got[:ht.num_leaves_actual],
            ht.leaf_count[:ht.num_leaves_actual])
