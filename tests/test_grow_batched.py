"""Batched-frontier growth (core/grow_batched.py, tree_growth=batched).

Contract being pinned:
- batch size 1 reproduces the exact leaf-wise algorithm (same split
  sequence, same node numbering — the reference's tree.cpp:49-67);
- larger batches trade exact best-first ordering for per-step
  parallelism with near-identical model quality (the GPU learner's
  documented-deviation stance, GPU-Performance.rst:132-139);
- the data-parallel mesh path matches single-device batched growth;
- order-dependent features (forced splits, CEGB) refuse loudly.
"""
import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.log import LightGBMError
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.metrics import create_metric
from lightgbm_tpu.boosting import create_boosting

from conftest import make_binary, make_multiclass


def _train(X, y, params, rounds=20, **ds_kw):
    cfg = Config(params)
    ds = BinnedDataset.from_matrix(X, cfg, label=y, **ds_kw)
    mets = [m for m in (create_metric(n, cfg) for n in (cfg.metric or []))
            if m]
    b = create_boosting(cfg, ds, create_objective(cfg), mets)
    for _ in range(rounds):
        if b.train_one_iter():
            break
    return b


def _tree_structures(booster, num=3):
    """(split_feature, threshold, split_leaf) tuples of the first trees."""
    return [(t.split_feature.copy(), t.threshold_bin.copy(),
             t.split_leaf.copy()) for t in booster.models[:num]]


def test_batch_one_matches_exact_structure():
    """K=1 batched growth is the exact algorithm: identical split
    sequences on tie-free data."""
    X, y = make_binary(n=3000)
    base = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
            "min_data_in_leaf": 5}
    be = _train(X, y, dict(base, tree_growth="exact"), rounds=5)
    bb = _train(X, y, dict(base, tree_growth="batched",
                           tree_batch_splits=1), rounds=5)
    for (f1, t1, l1), (f2, t2, l2) in zip(_tree_structures(be),
                                          _tree_structures(bb)):
        np.testing.assert_array_equal(f1, f2)
        np.testing.assert_array_equal(t1, t2)
        np.testing.assert_array_equal(l1, l2)


@pytest.mark.parametrize("kb", [4, 16])
@pytest.mark.slow
@pytest.mark.slow
def test_batched_quality_close_to_exact(kb):
    X, y = make_binary(n=4000)
    base = {"objective": "binary", "num_leaves": 63, "metric": "auc",
            "verbosity": -1}
    be = _train(X, y, dict(base, tree_growth="exact"), rounds=15)
    bb = _train(X, y, dict(base, tree_growth="batched",
                           tree_batch_splits=kb), rounds=15)
    auc_e = dict((m, v) for _, m, v, _ in be.get_eval_at(0))["auc"]
    auc_b = dict((m, v) for _, m, v, _ in bb.get_eval_at(0))["auc"]
    assert auc_b > 0.95
    assert abs(auc_e - auc_b) < 0.02


def test_batched_fills_leaf_budget():
    """A learnable problem must still grow to the num_leaves budget —
    batching must not strand capacity (the prefix-mask bookkeeping)."""
    X, y = make_binary(n=4000)
    b = _train(X, y, {"objective": "binary", "num_leaves": 33,
                      "tree_growth": "batched", "tree_batch_splits": 8,
                      "min_data_in_leaf": 2, "verbosity": -1}, rounds=2)
    assert b.models[0].num_leaves == 33


def test_batched_predict_matches_train_scores():
    X, y = make_binary(n=1500)
    b = _train(X, y, {"objective": "binary", "tree_growth": "batched",
                      "tree_batch_splits": 8, "verbosity": -1}, rounds=8)
    pred = b.predict(X, raw_score=True)
    np.testing.assert_allclose(pred, np.asarray(b.scores)[:, 0],
                               rtol=1e-4, atol=1e-4)


@pytest.mark.slow
@pytest.mark.slow
def test_batched_multiclass():
    X, y = make_multiclass()
    base = {"objective": "multiclass", "num_class": 4,
            "metric": "multi_logloss", "verbosity": -1}
    be = _train(X, y, dict(base, tree_growth="exact"), rounds=15)
    bb = _train(X, y, dict(base, tree_growth="batched",
                           tree_batch_splits=8), rounds=15)
    ll_e = dict((m, v) for _, m, v, _ in be.get_eval_at(0))["multi_logloss"]
    ll_b = dict((m, v) for _, m, v, _ in bb.get_eval_at(0))["multi_logloss"]
    assert ll_b < ll_e + 0.05


def test_batched_data_parallel_matches_single_device():
    """Eight-device data-parallel batched growth must reproduce the
    single-device model (the collective is one psum per step)."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    X, y = make_binary(n=2048)
    base = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
            "tree_growth": "batched", "tree_batch_splits": 8}
    b1 = _train(X, y, dict(base), rounds=5)
    b8 = _train(X, y, dict(base, tree_learner="data", num_machines=1,
                           mesh_shape=[8]), rounds=5)
    p1 = b1.predict(X[:200], raw_score=True)
    p8 = b8.predict(X[:200], raw_score=True)
    np.testing.assert_allclose(p1, p8, rtol=2e-4, atol=2e-4)


def test_batched_monotone_constraints_hold():
    r = np.random.RandomState(5)
    n = 3000
    X = r.randn(n, 4).astype(np.float32)
    y = (X[:, 0] + 0.3 * r.randn(n)).astype(np.float32)
    b = _train(X, y, {"objective": "regression", "verbosity": -1,
                      "tree_growth": "batched", "tree_batch_splits": 8,
                      "monotone_constraints": [1, 0, 0, 0]}, rounds=20)
    grid = np.zeros((50, 4), np.float32)
    grid[:, 0] = np.linspace(-2.5, 2.5, 50)
    pred = b.predict(grid, raw_score=True)
    assert np.all(np.diff(pred) >= -1e-6)


def test_batched_refuses_order_dependent_features(tmp_path):
    X, y = make_binary(n=500)
    with pytest.raises(LightGBMError, match="batched"):
        _train(X, y, {"objective": "binary", "tree_growth": "batched",
                      "verbosity": -1,
                      "cegb_penalty_feature_coupled": [0.1] * X.shape[1],
                      "cegb_tradeoff": 1.0}, rounds=1)
    with pytest.raises(LightGBMError, match="batched"):
        _train(X, y, {"objective": "binary", "tree_growth": "batched",
                      "tree_learner": "voting", "verbosity": -1}, rounds=1)


def test_batched_slot_kernel_end_to_end():
    """Batched growth through the slot-extended Pallas kernel (interpret
    mode) must match the scatter-based combined-index build."""
    X, y = make_binary(n=1200, f=6)
    base = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
            "tree_growth": "batched", "tree_batch_splits": 4}
    bs = _train(X, y, dict(base, tpu_hist_impl="scatter"), rounds=3)
    bp = _train(X, y, dict(base, tpu_hist_impl="pallas_interpret"), rounds=3)
    ps = bs.predict(X[:300], raw_score=True)
    pp = bp.predict(X[:300], raw_score=True)
    np.testing.assert_allclose(ps, pp, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
@pytest.mark.slow
def test_batched_pack_matches_unpacked():
    """tpu_batched_pack (active rows packed to the front + tile-skip slot
    kernel) reorders rows feeding the histogram sums, so models must
    match to f32 summation-order tolerance. n spans multiple 2048-row
    kernel tiles so rows actually cross tile boundaries and late steps
    leave whole tiles inactive (the pl.when skip path)."""
    X, y = make_binary(n=6000, f=6)
    base = {"objective": "binary", "num_leaves": 63, "verbosity": -1,
            "min_data_in_leaf": 5,
            "tree_growth": "batched", "tree_batch_splits": 4,
            "tpu_hist_impl": "pallas_interpret"}
    b0 = _train(X, y, dict(base), rounds=3)
    b1 = _train(X, y, dict(base, tpu_batched_pack=True), rounds=3)
    assert b1.grow_params.batched_pack
    p0 = b0.predict(X[:300], raw_score=True)
    p1 = b1.predict(X[:300], raw_score=True)
    np.testing.assert_allclose(p0, p1, rtol=1e-5, atol=1e-5)
