"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's test stance (tests are end-to-end through the Python
API, SURVEY.md §4) plus what the reference lacks: multi-device collectives are
exercised on a virtual CPU mesh (xla_force_host_platform_device_count) so the
data/feature/voting-parallel code paths run in CI without a TPU pod.
"""
import os

# XLA_FLAGS is read when the CPU client is created, which is still ahead of
# us even if jax was already imported (e.g. by a pytest plugin).
os.environ["JAX_PLATFORMS"] = "cpu"   # for any subprocesses we spawn
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# jax may have been imported before this conftest (pytest plugins), in which
# case it latched JAX_PLATFORMS from the original environment (e.g. a TPU
# tunnel); config.update still wins as long as no backend exists yet.
jax.config.update("jax_platforms", "cpu")

# persistent compilation cache: the tree-growth graph is expensive to compile
# on the CPU backend; cache hits make repeat test runs fast
jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(__file__), ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy tests (long training runs, multi-device meshes, "
        "fuzz sweeps) excluded from the tier-1 fast suite so it fits the "
        "870s budget; run the full suite with -m '' or just -m slow")


@pytest.fixture
def rng():
    return np.random.RandomState(42)


def make_binary(n=2000, f=10, seed=7):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    logit = X[:, 0] + 2.0 * X[:, 1] * (X[:, 2] > 0) - X[:, 3] ** 2 + \
        0.5 * r.randn(n)
    y = (logit > 0).astype(np.float64)
    return X, y


def make_regression(n=2000, f=10, seed=11):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    y = X[:, 0] * 3 + np.abs(X[:, 1]) + np.sin(X[:, 2] * 2) + 0.1 * r.randn(n)
    return X, y


def make_multiclass(n=2000, f=10, k=4, seed=13):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    centers = r.randn(k, f) * 2
    d = ((X[:, None, :] - centers[None, :, :]) ** 2).sum(-1)
    y = np.argmin(d, axis=1).astype(np.float64)
    return X, y


def make_ranking(num_queries=100, per_query=20, f=8, seed=17):
    r = np.random.RandomState(seed)
    n = num_queries * per_query
    X = r.randn(n, f)
    rel = X[:, 0] + 0.5 * X[:, 1] + 0.3 * r.randn(n)
    y = np.zeros(n)
    for q in range(num_queries):
        s = slice(q * per_query, (q + 1) * per_query)
        ranks = np.argsort(np.argsort(-rel[s]))
        y[s] = np.where(ranks < 2, 3, np.where(ranks < 5, 1, 0))
    group = np.full(num_queries, per_query, dtype=np.int64)
    return X, y, group
