"""R package glue (native/R-package/): no R toolchain ships in this image
(native/BINDINGS.md), so the .Call shims are compile-checked against a
minimal mock of the R API — the glue cannot silently rot, and a host
with R installs the package normally via R CMD INSTALL."""
import os
import subprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_r_glue_compiles_against_mock_api(tmp_path):
    src = os.path.join(REPO, "native", "R-package", "src",
                       "lightgbm_tpu_R.cpp")
    mock = os.path.join(REPO, "tests", "r_mock")
    out = str(tmp_path / "glue.o")
    r = subprocess.run(
        ["g++", "-std=c++17", "-Wall", "-Werror", "-c", src, "-o", out,
         "-I", mock, "-I", os.path.join(REPO, "native", "include")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-3000:]
    assert os.path.getsize(out) > 0


def test_r_package_layout_complete():
    pkg = os.path.join(REPO, "native", "R-package")
    for rel in ("DESCRIPTION", "NAMESPACE", "R/lightgbm_tpu.R",
                "src/lightgbm_tpu_R.cpp", "src/Makevars"):
        assert os.path.exists(os.path.join(pkg, rel)), rel
    # every routine registered in the glue is declared and used in R
    glue = open(os.path.join(pkg, "src", "lightgbm_tpu_R.cpp")).read()
    rcode = open(os.path.join(pkg, "R", "lightgbm_tpu.R")).read()
    import re
    registered = set(re.findall(r'\{"(LGBMTPU_\w+)"', glue))
    called = set(re.findall(r"\.Call\((LGBMTPU_\w+)", rcode))
    assert registered == called, (registered ^ called)
