"""Fused multi-iteration training (GBDT.train_many: lax.scan over the
iteration core — the whole boosting loop as one device program)."""
import pytest
import numpy as np
from sklearn.metrics import roc_auc_score

import lightgbm_tpu as lgb
from lightgbm_tpu.basic import Booster, Dataset


def _xy(n=4000, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)
    return X, y


@pytest.mark.slow
def test_fused_matches_per_iteration_exactly():
    """With no stochastic sampling the fused block must be bit-identical
    to the per-iteration dispatch path."""
    X, y = _xy()
    params = {"objective": "binary", "verbosity": -1, "num_leaves": 31}
    fused = lgb.train(dict(params), lgb.Dataset(X, label=y),
                      num_boost_round=8)  # engine takes the fused path
    # prove the fused path actually engaged (only train_many compiles it) —
    # engine.train's default print_evaluation callback must not block it
    assert fused._impl._compiled_block is not None
    periter = Booster(params=dict(params), train_set=Dataset(X, label=y))
    for _ in range(8):
        periter.update()
    assert periter._impl._compiled_block is None
    np.testing.assert_array_equal(
        fused.predict(X[:400], raw_score=True),
        periter.predict(X[:400], raw_score=True))


@pytest.mark.slow
def test_fused_bagging_and_feature_fraction():
    X, y = _xy()
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 31, "bagging_freq": 2,
                     "bagging_fraction": 0.7, "feature_fraction": 0.8},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    assert roc_auc_score(y, bst.predict(X)) > 0.9


def test_fused_goss():
    X, y = _xy(seed=1)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "boosting": "goss", "top_rate": 0.3,
                     "other_rate": 0.2},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    assert roc_auc_score(y, bst.predict(X)) > 0.9


def test_fused_stop_inside_block():
    """Convergence mid-block: the device stop latch freezes scores and the
    flush truncates the model at the stump."""
    rng = np.random.RandomState(2)
    Xs = rng.randn(60, 3).astype(np.float32)
    ys = (Xs[:, 0] > 0).astype(np.float32)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 63, "min_data_in_leaf": 1,
                     "learning_rate": 0.5},
                    lgb.Dataset(Xs, label=ys), num_boost_round=100)
    assert bst.num_trees() < 100
    raw = bst.predict(Xs, raw_score=True)
    sc = np.asarray(bst._impl.scores)[:, 0]
    assert np.abs(raw - sc).max() < 1e-4


def test_train_many_block_boundaries():
    """num_iters > 64 spans multiple blocks; model length is exact."""
    X, y = _xy(n=800, f=4)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 7},
                    lgb.Dataset(X, label=y), num_boost_round=70)
    assert bst.num_trees() == 70
