"""Row-partition growth (core/partition.py) tests.

The partition path must produce bit-identical trees to the masked full-pass
path — it is a pure cost optimization (O(N x depth) vs O(N x num_leaves)
row visits, the DataPartition data_partition.hpp:20-37 analog).
"""
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.core.grow import GrowParams, grow_tree
from lightgbm_tpu.core.split import FeatureMeta, SplitParams


def _meta(f, b, missing=0):
    return FeatureMeta(
        num_bin=jnp.full((f,), b, jnp.int32),
        missing_type=jnp.full((f,), missing, jnp.int32),
        default_bin=jnp.zeros((f,), jnp.int32),
        is_categorical=jnp.zeros((f,), bool),
        penalty=jnp.ones((f,), jnp.float32),
        monotone=jnp.zeros((f,), jnp.int32),
        col=jnp.arange(f, dtype=jnp.int32),
        offset=jnp.zeros((f,), jnp.int32),
        bundled=jnp.zeros((f,), bool))


def _split_params(**kw):
    base = dict(lambda_l1=0.0, lambda_l2=0.1, max_delta_step=0.0,
                min_data_in_leaf=20, min_sum_hessian_in_leaf=1e-3,
                min_gain_to_split=0.0, max_cat_threshold=32,
                cat_smooth=10.0, cat_l2=10.0, max_cat_to_onehot=4,
                min_data_per_group=100)
    base.update(kw)
    return SplitParams(**base)


@pytest.mark.parametrize("num_leaves,chunk", [(31, 512), (63, 300)])
def test_partition_matches_masked(num_leaves, chunk):
    np.random.seed(1)
    n, f, b = 5000, 6, 33
    xb = np.random.randint(0, b, (n, f)).astype(np.uint8)
    grad = np.random.randn(n).astype(np.float32)
    hess = (np.random.rand(n) + 0.5).astype(np.float32)
    mask = (np.random.rand(n) < 0.8).astype(np.float32)
    meta = _meta(f, b)
    fm = jnp.ones((f,), bool)
    out = {}
    for mode in (False, True):
        p = GrowParams(num_leaves=num_leaves, num_bins=b, max_depth=-1,
                       split=_split_params(), row_chunk=chunk,
                       hist_impl="scatter", use_partition=mode)
        t, li, _ = jax.jit(functools.partial(grow_tree, params=p))(
            jnp.asarray(xb), jnp.asarray(grad), jnp.asarray(hess),
            jnp.asarray(mask), meta, fm)
        out[mode] = (jax.tree.map(np.asarray, t), np.asarray(li))
    t0, l0 = out[False]
    t1, l1 = out[True]
    assert (l0 == l1).all()
    assert int(t0.num_leaves) == int(t1.num_leaves)
    for name in t0._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(t0, name), np.float64),
            np.asarray(getattr(t1, name), np.float64),
            rtol=1e-5, atol=1e-6, err_msg=name)


def test_partition_leaf_counts_consistent():
    """Partition bookkeeping: leaf ranges tile [0, N) and counts match the
    per-row leaf_id assignment."""
    from lightgbm_tpu.core.partition import (init_partition, make_row_gather,
                                             partition_and_hist, stack_vals)

    np.random.seed(4)
    n, chunk = 1000, 128
    f, b = 3, 8
    part = init_partition(n, 8, chunk)
    leaf_id = jnp.zeros((n,), jnp.int32)
    decision_np = np.random.rand(n) < 0.3
    # route the split decision through the gathered feature bytes, the way
    # grow_tree does: column 0 holds the decision bit
    xb = np.random.randint(0, b, (n, f)).astype(np.uint8)
    xb[:, 0] = decision_np.astype(np.uint8)
    vals = stack_vals(jnp.asarray(np.random.randn(n).astype(np.float32)),
                      jnp.ones((n,), jnp.float32), jnp.ones((n,), jnp.float32))
    gr = make_row_gather(jnp.asarray(xb), vals)

    part, leaf_id, hl, hr = jax.jit(
        lambda p, l: partition_and_hist(
            p, l, jnp.int32(0), jnp.int32(1),
            lambda rows: rows[:, 0] == 1,
            jnp.asarray(True), chunk, gr, f, b,
            "scatter", maintain_leaf_id=True))(part, leaf_id)
    # the fused histograms cover exactly each child's rows
    assert int(np.asarray(hl)[0, 1, 2]) == int(decision_np.sum())
    assert int(np.asarray(hr)[0, 0, 2]) == int((~decision_np).sum())
    lid = np.asarray(leaf_id)
    order = np.asarray(part.order)[:n]
    begin = np.asarray(part.leaf_begin)
    count = np.asarray(part.leaf_count)
    assert count[0] + count[1] == n
    assert begin[1] == count[0]
    # every leaf range holds exactly its leaf's rows
    np.testing.assert_array_equal(np.sort(order), np.arange(n))
    assert (lid[order[:count[0]]] == 0).all()
    assert (lid[order[count[0]:n]] == 1).all()
    assert count[0] == int(decision_np.sum())
    # reconstruction from ranges matches the maintained assignment
    from lightgbm_tpu.core.partition import leaf_id_from_partition
    lid2 = np.asarray(jax.jit(
        lambda p: leaf_id_from_partition(p, n, 8))(part))
    np.testing.assert_array_equal(lid, lid2)


def test_partition_sort_placement_matches_scatter_path():
    """The pallas impl's single-trip sort+DUS placement must produce the
    same partition and histograms as the chunked scatter path (interpret
    mode exercises the sort branch on CPU)."""
    np.random.seed(9)
    n, f, b = 3000, 5, 64
    xb = np.random.randint(0, b, (n, f)).astype(np.uint8)
    grad = np.random.randn(n).astype(np.float32)
    hess = (np.random.rand(n) + 0.5).astype(np.float32)
    mask = np.ones(n, np.float32)
    meta = _meta(f, b)
    fm = jnp.ones((f,), bool)
    out = {}
    for impl in ("scatter", "pallas_interpret"):
        p = GrowParams(num_leaves=15, num_bins=b, max_depth=-1,
                       split=_split_params(), row_chunk=1024,
                       hist_impl=impl, use_partition=True)
        t_, li, _ = jax.jit(functools.partial(grow_tree, params=p))(
            jnp.asarray(xb), jnp.asarray(grad), jnp.asarray(hess),
            jnp.asarray(mask), meta, fm)
        out[impl] = (jax.tree.map(np.asarray, t_), np.asarray(li))
    t0, l0 = out["scatter"]
    t1, l1 = out["pallas_interpret"]
    assert (l0 == l1).all()
    np.testing.assert_array_equal(t0.split_feature, t1.split_feature)
    np.testing.assert_allclose(t0.leaf_value, t1.leaf_value,
                               rtol=1e-4, atol=1e-5)


def test_frontier_slots_from_partition():
    """The partition hands the frontier builder LEAF IDS: rows inside
    leaves[i]'s range get slot i, every other row -1 (ISSUE 2 tentpole
    hand-off, used by the frontier phase probe)."""
    from lightgbm_tpu.core.partition import (frontier_slots_from_partition,
                                             init_partition, make_row_gather,
                                             partition_and_hist, stack_vals)

    np.random.seed(9)
    n, chunk = 1000, 128
    f, b = 3, 8
    part = init_partition(n, 8, chunk)
    decision_np = np.random.rand(n) < 0.3
    xb = np.random.randint(0, b, (n, f)).astype(np.uint8)
    xb[:, 0] = decision_np.astype(np.uint8)
    vals = stack_vals(jnp.asarray(np.random.randn(n).astype(np.float32)),
                      jnp.ones((n,), jnp.float32), jnp.ones((n,), jnp.float32))
    gr = make_row_gather(jnp.asarray(xb), vals)
    part = jax.jit(
        lambda p: partition_and_hist(
            p, jnp.zeros((n,), jnp.int32), jnp.int32(0), jnp.int32(1),
            lambda rows: rows[:, 0] == 1,
            jnp.asarray(True), chunk, gr, f, b, "scatter"))(part)[0]

    def slots(leaves):
        return np.asarray(jax.jit(
            lambda p: frontier_slots_from_partition(
                p, jnp.asarray(leaves, jnp.int32), n))(part))

    # both leaves selected: slot == leaf id
    s01 = slots([0, 1])
    np.testing.assert_array_equal(s01, np.where(decision_np, 0, 1))
    # slot index follows position IN THE LEAVES LIST, not the leaf id
    s10 = slots([1, 0])
    np.testing.assert_array_equal(s10, np.where(decision_np, 1, 0))
    # unselected leaves' rows are -1
    s1 = slots([1])
    np.testing.assert_array_equal(s1, np.where(decision_np, -1, 0))
    # empty leaves in the list claim no rows
    s_empty = slots([5, 0])
    np.testing.assert_array_equal(s_empty, np.where(decision_np, 1, -1))
