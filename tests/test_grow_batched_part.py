"""Partitioned batched growth (core/grow_batched_part.py,
tpu_batched_part=true).

Contract being pinned:
- identical SPLIT STRUCTURE to the unpartitioned batched mode (same
  top-K frontier algorithm; only histogram summation order differs);
- the tile-pure Pallas kernel path (interpret mode) matches the
  scatter-based combined-index fallback;
- the shard_map data-parallel path reproduces the single-device model
  (each device partitions its LOCAL row shard; one psum per step);
- auto policy keeps it OFF (measured slower on chip, see
  docs/Performance.md round-4 table) while true forces it on.
"""
import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.boosting import create_boosting

from conftest import make_binary


def _train(X, y, params, rounds=4, **ds_kw):
    cfg = Config(params)
    ds = BinnedDataset.from_matrix(X, cfg, label=y, **ds_kw)
    b = create_boosting(cfg, ds, create_objective(cfg), [])
    for _ in range(rounds):
        if b.train_one_iter():
            break
    return b


BASE = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
        "min_data_in_leaf": 5, "tree_growth": "batched",
        "tree_batch_splits": 4, "tpu_hist_impl": "scatter"}


@pytest.mark.slow
def test_part_matches_plain_batched_structure():
    X, y = make_binary(n=3000)
    b0 = _train(X, y, dict(BASE))
    b1 = _train(X, y, dict(BASE, tpu_batched_part="true"))
    assert b1.grow_params.batched_part
    assert not b0.grow_params.batched_part    # auto stays off
    for t0, t1 in zip(b0.models, b1.models):
        np.testing.assert_array_equal(np.asarray(t0.split_feature),
                                      np.asarray(t1.split_feature))
        np.testing.assert_array_equal(np.asarray(t0.threshold_bin),
                                      np.asarray(t1.threshold_bin))
        np.testing.assert_array_equal(np.asarray(t0.split_leaf),
                                      np.asarray(t1.split_leaf))
    p0 = b0.predict(X[:300], raw_score=True)
    p1 = b1.predict(X[:300], raw_score=True)
    np.testing.assert_allclose(p0, p1, rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_part_kernel_matches_fallback():
    """The tile-pure kernel (interpret) vs the combined-index scatter
    build, end to end. n spans multiple 2048-row tiles so segments
    really cross tile boundaries and late steps leave inactive tiles."""
    X, y = make_binary(n=6000, f=6)
    base = dict(BASE, num_leaves=63, tpu_batched_part="true")
    bs = _train(X, y, dict(base))
    bp = _train(X, y, dict(base, tpu_hist_impl="pallas_interpret"))
    ps = bs.predict(X[:300], raw_score=True)
    pp = bp.predict(X[:300], raw_score=True)
    np.testing.assert_allclose(ps, pp, rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_part_data_parallel_matches_single_device():
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    X, y = make_binary(n=2048)
    base = dict(BASE, tree_batch_splits=8, tpu_batched_part="true")
    b1 = _train(X, y, dict(base))
    b8 = _train(X, y, dict(base, tree_learner="data", num_machines=1,
                           mesh_shape=[8]))
    p1 = b1.predict(X[:200], raw_score=True)
    p8 = b8.predict(X[:200], raw_score=True)
    np.testing.assert_allclose(p1, p8, rtol=2e-4, atol=2e-4)


def test_local_slot_mask_semantics():
    """The pre-psum mask for kernel output blocks: only slots with local
    tiles survive; -1 (no slot) must DROP, never wrap to the last slot."""
    import jax.numpy as jnp
    from lightgbm_tpu.core.grow_batched_part import _local_slot_mask

    m = _local_slot_mask(jnp.asarray([-1, 2, 2, 0, -1], jnp.int32), 4)
    np.testing.assert_array_equal(np.asarray(m), [True, False, True, False])
    # a shard whose every tile is inactive contributes NOTHING — in
    # particular -1 must not light up slot kb-1 via negative wrapping
    m = _local_slot_mask(jnp.full((6,), -1, jnp.int32), 4)
    assert not np.asarray(m).any()
    m = _local_slot_mask(jnp.asarray([3, 3, 3], jnp.int32), 4)
    np.testing.assert_array_equal(np.asarray(m), [False, False, False, True])


@pytest.mark.slow
def test_part_data_parallel_skewed_inactive_slots():
    """Data-parallel parity on a row-SORTED dataset: leaves align with
    contiguous row ranges, so nearly every (leaf, shard) pair has zero
    local rows — the regime where an unmasked kernel block would feed
    garbage into the psum (the mask under test is applied on both kernel
    and fallback paths)."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    X, y = make_binary(n=2048)
    order = np.argsort(X[:, 0], kind="stable")
    X, y = X[order], y[order]
    base = dict(BASE, tree_batch_splits=8, tpu_batched_part="true",
                bagging_fraction=1.0)
    b1 = _train(X, y, dict(base))
    b8 = _train(X, y, dict(base, tree_learner="data", num_machines=1,
                           mesh_shape=[8]))
    for t1, t8 in zip(b1.models, b8.models):
        np.testing.assert_array_equal(np.asarray(t1.split_feature),
                                      np.asarray(t8.split_feature))
    p1 = b1.predict(X[:200], raw_score=True)
    p8 = b8.predict(X[:200], raw_score=True)
    np.testing.assert_allclose(p1, p8, rtol=2e-4, atol=2e-4)


def test_part_bagging_and_goss_ride_along():
    """Masked-out rows still travel through the partition (their leaf
    assignment must stay correct for the score update)."""
    X, y = make_binary(n=4000)
    b = _train(X, y, dict(BASE, tpu_batched_part="true",
                          bagging_fraction=0.6, bagging_freq=1), rounds=6)
    pred = b.predict(X, raw_score=True)
    np.testing.assert_allclose(pred, np.asarray(b.scores)[:, 0],
                               rtol=1e-4, atol=1e-4)
