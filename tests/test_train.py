"""End-to-end training quality tests through the GBDT driver (the reference's
test_engine.py style: train, assert metric threshold)."""
import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.metrics import create_metric
from lightgbm_tpu.boosting import create_boosting

from conftest import make_binary, make_regression, make_multiclass, make_ranking


def _train(X, y, params, rounds=30, group=None, weight=None):
    cfg = Config(params)
    ds = BinnedDataset.from_matrix(X, cfg, label=y, group=group, weight=weight)
    obj = create_objective(cfg)
    metric_names = cfg.metric or []
    mets = [m for m in (create_metric(n, cfg) for n in metric_names) if m]
    booster = create_boosting(cfg, ds, obj, mets)
    for _ in range(rounds):
        if booster.train_one_iter():
            break
    return booster, ds


def test_binary_auc():
    X, y = make_binary()
    b, _ = _train(X, y, {"objective": "binary", "num_leaves": 31,
                         "metric": "auc", "verbosity": -1})
    res = dict((m, v) for _, m, v, _ in b.get_eval_at(0))
    assert res["auc"] > 0.95


@pytest.mark.slow
@pytest.mark.slow
def test_binary_predict_matches_train_scores():
    X, y = make_binary(n=1000)
    b, ds = _train(X, y, {"objective": "binary", "verbosity": -1}, rounds=10)
    pred = b.predict(X, raw_score=True)
    train_scores = np.asarray(b.scores)[:, 0]
    np.testing.assert_allclose(pred, train_scores, rtol=1e-4, atol=1e-4)


@pytest.mark.slow
@pytest.mark.slow
def test_regression_l2():
    X, y = make_regression()
    b, _ = _train(X, y, {"objective": "regression", "metric": "l2",
                         "verbosity": -1}, rounds=50)
    res = dict((m, v) for _, m, v, _ in b.get_eval_at(0))
    assert res["l2"] < 0.5


@pytest.mark.slow
@pytest.mark.slow
def test_regression_l1_renews_leaves():
    X, y = make_regression()
    b, _ = _train(X, y, {"objective": "regression_l1", "metric": "l1",
                         "verbosity": -1}, rounds=50)
    res = dict((m, v) for _, m, v, _ in b.get_eval_at(0))
    assert res["l1"] < 0.6


@pytest.mark.slow
@pytest.mark.slow
def test_multiclass():
    X, y = make_multiclass(k=4)
    b, _ = _train(X, y, {"objective": "multiclass", "num_class": 4,
                         "metric": "multi_logloss", "verbosity": -1},
                  rounds=30)
    res = dict((m, v) for _, m, v, _ in b.get_eval_at(0))
    assert res["multi_logloss"] < 0.4
    pred = b.predict(X)
    assert pred.shape == (len(y), 4)
    np.testing.assert_allclose(pred.sum(1), 1.0, rtol=1e-4)
    acc = (pred.argmax(1) == y).mean()
    assert acc > 0.85


@pytest.mark.slow
@pytest.mark.slow
def test_lambdarank_ndcg_improves():
    X, y, group = make_ranking()
    b, _ = _train(X, y, {"objective": "lambdarank", "metric": "ndcg",
                         "eval_at": [5], "verbosity": -1},
                  rounds=30, group=group)
    res = dict((m, v) for _, m, v, _ in b.get_eval_at(0))
    assert res["ndcg@5"] > 0.80


@pytest.mark.slow
@pytest.mark.slow
def test_weights_affect_training():
    X, y = make_binary(n=1000)
    w = np.where(y > 0, 10.0, 1.0)
    b, _ = _train(X, y, {"objective": "binary", "verbosity": -1},
                  rounds=10, weight=w)
    pred = b.predict(X)
    # heavy positive weight → predicted probabilities skew up
    assert pred.mean() > y.mean()


@pytest.mark.slow
@pytest.mark.slow
def test_bagging_and_feature_fraction():
    X, y = make_binary()
    b, _ = _train(X, y, {"objective": "binary", "metric": "auc",
                         "bagging_fraction": 0.6, "bagging_freq": 1,
                         "feature_fraction": 0.7, "verbosity": -1})
    res = dict((m, v) for _, m, v, _ in b.get_eval_at(0))
    assert res["auc"] > 0.92


def test_min_data_in_leaf_respected():
    X, y = make_binary(n=500)
    b, _ = _train(X, y, {"objective": "binary", "min_data_in_leaf": 50,
                         "verbosity": -1}, rounds=5)
    for t in b.models:
        cnt = t.leaf_count[:t.num_leaves_actual]
        assert (cnt >= 50).all()


def test_max_depth_respected():
    X, y = make_binary()
    b, _ = _train(X, y, {"objective": "binary", "max_depth": 3,
                         "num_leaves": 31, "verbosity": -1}, rounds=5)
    for t in b.models:
        # depth-3 tree has at most 8 leaves
        assert t.num_leaves_actual <= 8


@pytest.mark.slow
@pytest.mark.slow
def test_monotone_constraints():
    r = np.random.RandomState(0)
    n = 2000
    X = r.rand(n, 2)
    y = 2 * X[:, 0] + np.sin(6 * X[:, 1]) + 0.1 * r.randn(n)
    b, _ = _train(X, y, {"objective": "regression",
                         "monotone_constraints": [1, 0],
                         "verbosity": -1}, rounds=40)
    # brute-force monotonicity check (reference test_engine.py:680)
    grid = np.tile(np.linspace(0.01, 0.99, 50)[:, None], (1, 2))
    grid[:, 1] = 0.5
    pred = b.predict(grid)
    assert (np.diff(pred) >= -1e-6).all()


def test_rollback_one_iter():
    X, y = make_binary(n=800)
    b, _ = _train(X, y, {"objective": "binary", "verbosity": -1}, rounds=5)
    scores_before = np.asarray(b.scores).copy()
    b.train_one_iter()
    b.rollback_one_iter()
    np.testing.assert_allclose(np.asarray(b.scores), scores_before,
                               rtol=1e-4, atol=1e-5)


def test_constant_labels_constant_prediction():
    r = np.random.RandomState(0)
    X = r.randn(300, 5)
    y = np.full(300, 3.25)
    b, _ = _train(X, y, {"objective": "regression", "verbosity": -1}, rounds=5)
    pred = b.predict(X)
    np.testing.assert_allclose(pred, 3.25, rtol=1e-3)


@pytest.mark.slow
@pytest.mark.slow
def test_dart_goss_rf_train():
    X, y = make_binary()
    for boost, extra in [("dart", {}), ("goss", {}),
                         ("rf", {"bagging_freq": 1, "bagging_fraction": 0.7})]:
        p = {"objective": "binary", "boosting": boost, "metric": "auc",
             "learning_rate": 0.3, "verbosity": -1}
        p.update(extra)
        b, _ = _train(X, y, p, rounds=15)
        res = dict((m, v) for _, m, v, _ in b.get_eval_at(0))
        assert res["auc"] > 0.85, (boost, res)


def test_rf_valid_scores_track_averaged_prediction():
    """Regression: RF valid cache must equal the averaged model prediction
    (the raw sums live outside the cache between iterations)."""
    from lightgbm_tpu.metrics import create_metric
    X, y = make_binary(n=1000)
    Xv, yv = make_binary(n=300, seed=9)
    cfg = Config({"objective": "binary", "boosting": "rf", "bagging_freq": 1,
                  "bagging_fraction": 0.7, "verbosity": -1})
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    dv = BinnedDataset.from_matrix(Xv, cfg, label=yv, reference=ds)
    b = create_boosting(cfg, ds, create_objective(cfg), [])
    b.add_valid_data(dv, [create_metric("binary_logloss", cfg)])
    for _ in range(4):
        b.train_one_iter()
    cache = np.asarray(b._valid_pred_cache[0]["scores"])[:, 0]
    pred = b.predict(Xv, raw_score=True)
    np.testing.assert_allclose(cache, pred, rtol=1e-4, atol=1e-5)
    train_cache = np.asarray(b.scores)[:, 0]
    np.testing.assert_allclose(train_cache, b.predict(X, raw_score=True),
                               rtol=1e-4, atol=1e-5)


def test_model_text_roundtrip_exact_predictions():
    from lightgbm_tpu.io.model_text import model_to_string, parse_model_string
    from lightgbm_tpu.core import tree as tm
    import jax
    import jax.numpy as jnp
    X, y = make_binary(n=800)
    b, ds = _train(X, y, {"objective": "binary", "verbosity": -1}, rounds=8)
    s = model_to_string(b, ds.feature_names, ds.get_feature_infos())
    parsed = parse_model_string(s)
    assert len(parsed["trees"]) == 8
    assert parsed["objective"].startswith("binary")
    mx = max(t.num_nodes for t in parsed["trees"])
    ml = max(t.num_leaves for t in parsed["trees"])
    stacked = jax.tree.map(
        lambda *xs: jnp.asarray(np.stack(xs)),
        *[t.predict_table(mx, ml) for t in parsed["trees"]])
    pl = np.asarray(tm.predict_forest_raw(stacked,
                                          jnp.asarray(X[:200], jnp.float32)))
    np.testing.assert_allclose(pl, b.predict(X[:200], raw_score=True),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow
@pytest.mark.slow
def test_feature_importance_counts_splits():
    X, y = make_binary()
    b, _ = _train(X, y, {"objective": "binary", "verbosity": -1}, rounds=10)
    imp = b.feature_importance("split")
    total_splits = sum(int((t.split_leaf >= 0).sum()) for t in b.models)
    assert imp.sum() == total_splits
    gain_imp = b.feature_importance("gain")
    assert gain_imp.sum() > 0


@pytest.mark.slow
@pytest.mark.slow
def test_categorical_splits_improve_fit():
    """Categorical split finding (FindBestThresholdCategorical,
    feature_histogram.hpp:110-271): a feature whose categories carry signal
    in a non-ordinal way must be exploited via subset splits. Reference
    test: test_engine.py:218-291."""
    r = np.random.RandomState(5)
    n = 3000
    cat = r.randint(0, 12, n)
    x2 = r.randn(n)
    # non-ordinal category effect: {1,3,5,8} high, rest low
    effect = np.where(np.isin(cat, [1, 3, 5, 8]), 2.0, -2.0)
    y = (effect + 0.5 * x2 + 0.3 * r.randn(n) > 0).astype(np.float64)
    X = np.column_stack([cat.astype(np.float64), x2])

    b_cat, _ = _train(X, y, {"objective": "binary", "verbosity": -1,
                             "categorical_feature": "0",
                             "min_data_per_group": 10}, rounds=15)
    from sklearn.metrics import roc_auc_score
    auc_cat = roc_auc_score(y, b_cat.predict(X))
    assert auc_cat > 0.97

    # one-hot mode (small cardinality): max_cat_to_onehot above num_bin
    b_oh, _ = _train(X, y, {"objective": "binary", "verbosity": -1,
                            "categorical_feature": "0",
                            "max_cat_to_onehot": 32}, rounds=15)
    assert roc_auc_score(y, b_oh.predict(X)) > 0.95

    # save -> load -> predict round-trip with categorical splits
    import lightgbm_tpu as lgb
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "categorical_feature": "0", "min_data_per_group": 10},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    s = bst.model_to_string()
    assert "num_cat=" in s
    re = lgb.Booster(model_str=s)
    np.testing.assert_allclose(re.predict(X[:200]), bst.predict(X[:200]),
                               rtol=1e-6, atol=1e-9)


@pytest.mark.slow
@pytest.mark.slow
def test_categorical_large_values_roundtrip():
    """Category IDs above 255 (store/zip-style) must survive training,
    raw prediction, and save/load — variable-width bitsets
    (Tree cat_threshold_, tree.h:276-291)."""
    import lightgbm_tpu as lgb
    r = np.random.RandomState(9)
    n = 2500
    ids = np.array([7, 300, 999, 1204, 55, 801])
    cat = ids[r.randint(0, len(ids), n)]
    y = (np.isin(cat, [300, 1204]) ^ (r.rand(n) < 0.05)).astype(float)
    X = np.column_stack([cat.astype(np.float64), r.randn(n)])
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "categorical_feature": "0", "min_data_per_group": 10,
                     "max_cat_to_onehot": 16},
                    lgb.Dataset(X, label=y), num_boost_round=8)
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, bst.predict(X)) > 0.97
    re = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(re.predict(X[:300]), bst.predict(X[:300]),
                               rtol=1e-6, atol=1e-9)


# ------------------------------------------------- ranking: group bagging
def test_lambdarank_bagging_samples_whole_query_groups():
    """Under lambdarank, bagging_fraction must sample whole QUERY groups
    (one uniform per query broadcast through the row->group map), never
    split a query across the in/out-of-bag boundary — pairwise gradients
    inside a half-sampled query would compare against missing docs."""
    X, y, group = make_ranking(num_queries=80, per_query=10)
    b, _ = _train(X, y, {"objective": "lambdarank",
                         "bagging_fraction": 0.5, "bagging_freq": 1,
                         "verbosity": -1}, rounds=3, group=group)
    assert b._row_group is not None
    mask = np.asarray(b._bag_mask)
    rg = np.asarray(b._row_group)
    for g in np.unique(rg):
        vals = mask[rg == g]
        assert (vals == vals[0]).all(), "query %d split by bagging" % g
    # roughly bagging_fraction of the GROUPS are in-bag
    picked = np.mean([mask[rg == g][0] for g in np.unique(rg)])
    assert 0.3 < picked < 0.7
    # non-ranking objectives keep the plain per-row path
    Xb, yb = make_binary(n=500)
    bb, _ = _train(Xb, yb, {"objective": "binary", "bagging_fraction": 0.5,
                            "bagging_freq": 1, "verbosity": -1}, rounds=2)
    assert bb._row_group is None


@pytest.mark.slow
@pytest.mark.slow
def test_lambdarank_group_bagging_parity():
    """Group-wise bagging still learns: NDCG with bagging stays close to
    the full-data run (the satellite's parity bar)."""
    X, y, group = make_ranking()
    params = {"objective": "lambdarank", "metric": "ndcg", "eval_at": [5],
              "verbosity": -1}
    full, _ = _train(X, y, dict(params), rounds=30, group=group)
    bagged, _ = _train(X, y, dict(params, bagging_fraction=0.7,
                                  bagging_freq=1), rounds=30, group=group)
    ndcg_full = dict((m, v) for _, m, v, _ in full.get_eval_at(0))["ndcg@5"]
    ndcg_bag = dict((m, v) for _, m, v, _ in bagged.get_eval_at(0))["ndcg@5"]
    assert ndcg_bag > 0.78
    assert ndcg_bag > ndcg_full - 0.08


# ------------------------------------------------- ranking: query weights
def test_metadata_query_weights_are_doc_means():
    """metadata.cpp LoadQueryWeights: a query's weight is the MEAN of its
    documents' weights, lazily derived and reset on weight/query swaps."""
    from lightgbm_tpu.io.dataset import Metadata
    md = Metadata(6)
    md.set_label(np.zeros(6))
    md.set_query(np.array([2, 4]))
    assert md.query_weights is None          # no weights: unweighted
    md.set_weight(np.array([1.0, 3.0, 2.0, 2.0, 2.0, 2.0]))
    np.testing.assert_allclose(md.query_weights, [2.0, 2.0])
    md.set_weight(np.array([4.0, 4.0, 1.0, 1.0, 1.0, 1.0]))
    np.testing.assert_allclose(md.query_weights, [4.0, 1.0])


def test_ranking_metrics_honor_query_weights():
    """rank_metric.hpp query_weights_ accumulation: each query's metric
    contribution is scaled by its weight over the weight sum."""
    from lightgbm_tpu.io.dataset import Metadata
    X, y, group = make_ranking(num_queries=6, per_query=8)
    n = len(y)
    score = np.random.RandomState(0).randn(n)
    cfg = Config({"objective": "lambdarank", "eval_at": [3]})
    for name in ("ndcg", "map"):
        md = Metadata(n)
        md.set_label(y)
        md.set_query(group)
        plain = create_metric(name, cfg)
        plain.init(md, n)
        base = plain.eval(score)
        pq = [np.asarray(plain.per_query(y[lo:lo + 8], score[lo:lo + 8]))
              for lo in range(0, n, 8)]
        # docs of query 0 weigh 3x -> query weights [3, 1, 1, 1, 1, 1]
        w = np.ones(n)
        w[:8] = 3.0
        mdw = Metadata(n)
        mdw.set_label(y)
        mdw.set_query(group)
        mdw.set_weight(w)
        weighted = create_metric(name, cfg)
        weighted.init(mdw, n)
        expected = (3.0 * pq[0] + sum(pq[1:])) / 8.0
        np.testing.assert_allclose(weighted.eval(score), expected,
                                   rtol=1e-12)
        # uniform weights reproduce the unweighted metric exactly
        mdu = Metadata(n)
        mdu.set_label(y)
        mdu.set_query(group)
        mdu.set_weight(np.full(n, 2.0))
        uniform = create_metric(name, cfg)
        uniform.init(mdu, n)
        np.testing.assert_allclose(uniform.eval(score), base, rtol=1e-12)
