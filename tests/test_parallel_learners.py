"""Frontier parallel learners (parallel/learners.py, tree_learner=
serial|data|voting on tree_growth=frontier).

Contract being pinned:
- the data-parallel reduce-scatter schedule (DataRSLearner) and the
  full-psum schedule commit IDENTICAL trees — the packed best-record
  election preserves find_best_split's first-max tie-break because
  feature blocks are contiguous in rank order;
- voting with top_k >= F elects every feature and degenerates to the
  exact data-parallel search (structure-identical to serial);
- voting with a small top_k is a DOCUMENTED approximation: training
  still converges, with train loss monotone and near serial's;
- shard skew (sorted rows, uneven remainders) must not change the
  committed structure — histograms are summed across the mesh before
  any decision;
- unsupported combos refuse loudly or warn once, never silently serial.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.config import Config
from lightgbm_tpu.log import LightGBMError, Log

from conftest import make_binary
from test_grow_frontier import _canonical_splits, _golden_data, _train


def _mesh8(extra=None):
    base = {"objective": "binary", "num_leaves": 64, "max_depth": 4,
            "min_data_in_leaf": 40, "verbosity": -1,
            "tree_growth": "frontier"}
    base.update(extra or {})
    return base


# ------------------------------------------------------------ fast units
def test_best_record_pack_roundtrip():
    """Every BestSplit field survives the f32-lane packing bitwise —
    including negative thresholds, bools, and high-bit bitset words
    (a value-cast would corrupt those)."""
    from lightgbm_tpu.core.split import BestSplit
    from lightgbm_tpu.parallel.learners import (RECORD_LANES,
                                                pack_best_record,
                                                unpack_best_record)
    k = 3
    bs = BestSplit(
        gain=jnp.asarray([1.5, -jnp.inf, 0.0], jnp.float32),
        feature=jnp.asarray([7, 0, 2 ** 30], jnp.int32),
        threshold=jnp.asarray([-1, 255, 3], jnp.int32),
        default_left=jnp.asarray([True, False, True]),
        left_sum_grad=jnp.asarray([0.1, -2.0, 3.0], jnp.float32),
        left_sum_hess=jnp.asarray([1.0, 2.0, 3.0], jnp.float32),
        left_count=jnp.asarray([10.0, 0.0, 5.0], jnp.float32),
        right_sum_grad=jnp.asarray([-0.1, 2.0, -3.0], jnp.float32),
        right_sum_hess=jnp.asarray([9.0, 8.0, 7.0], jnp.float32),
        right_count=jnp.asarray([1.0, 2.0, 3.0], jnp.float32),
        left_output=jnp.asarray([0.5, -0.5, 0.0], jnp.float32),
        right_output=jnp.asarray([-0.5, 0.5, 1.0], jnp.float32),
        is_categorical=jnp.asarray([False, True, False]),
        cat_bitset=jnp.asarray(
            np.array([[0xFFFFFFFF] * 8, [0] * 8, [0x80000001] * 8],
                     np.uint32)))
    rec = pack_best_record(bs)
    assert rec.shape == (k, RECORD_LANES)
    out = unpack_best_record(rec)
    for a, b in zip(bs, out):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_top_k_validates():
    with pytest.raises(LightGBMError, match="top_k"):
        Config({"top_k": 0})
    assert Config({"topk": 5}).top_k == 5


def test_unknown_tree_learner_raises():
    with pytest.raises(LightGBMError, match="tree learner"):
        Config({"tree_learner": "gossip"})


def test_check_model_agreement_loopback():
    """The smoke's cross-rank digest check: identical digests pass in
    rank order, a diverged rank fails EVERY rank loudly (naming ranks) —
    a silent majority-wins would hide real replication bugs."""
    import threading
    from lightgbm_tpu.parallel.network import (LoopbackComm,
                                               check_model_agreement)

    def run(digests):
        comms = LoopbackComm.group(len(digests), timeout_s=10)
        out = [None] * len(digests)

        def worker(r):
            try:
                out[r] = check_model_agreement(digests[r], comm=comms[r])
            except Exception as e:  # noqa: BLE001 - asserted below
                out[r] = e
        ts = [threading.Thread(target=worker, args=(r,))
              for r in range(len(digests))]
        [t.start() for t in ts]
        [t.join() for t in ts]
        return out

    ok = run(["abc123", "abc123"])
    assert ok == [["abc123", "abc123"]] * 2
    bad = run(["abc123", "def456"])
    for e in bad:
        assert isinstance(e, LightGBMError)
        assert "rank 0" in str(e) and "rank 1" in str(e)
    # single process (no comm, no cluster): pass-through
    assert check_model_agreement("solo") == ["solo"]


def test_single_device_fallback_warns_once():
    """A parallel tree_learner that cannot build a mesh must say so —
    the silent-serial fallback cost users real scaling runs."""
    from lightgbm_tpu.parallel import mesh as mesh_mod
    msgs = []
    mesh_mod._warned_fallback = False
    Log.reset_callback(msgs.append)
    try:
        m = mesh_mod.build_mesh(Config({"tree_learner": "voting",
                                        "verbosity": 0}),
                                devices=jax.devices()[:1])
        assert m is None
        m = mesh_mod.build_mesh(Config({"tree_learner": "data",
                                        "verbosity": 0}),
                                devices=jax.devices()[:1])
        assert m is None
    finally:
        Log.reset_callback(None)
        mesh_mod._warned_fallback = False
    warned = [m for m in msgs if "falls back to serial" in m]
    assert len(warned) == 1            # one-time, not once per build
    assert "voting" in warned[0]


# ------------------------------------------------- structure identity (mesh)
@pytest.mark.slow
def test_data_rs_matches_serial_and_psum_path():
    """The reduce-scatter schedule commits the same trees as both the
    single-device grower and the full-psum mesh schedule
    (tpu_frontier_rs=false A/B) on the tie-free golden config."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    X, y = _golden_data()
    b1 = _train(X, y, _mesh8(), rounds=5)
    brs = _train(X, y, _mesh8({"tree_learner": "data", "mesh_shape": [8]}),
                 rounds=5)
    bps = _train(X, y, _mesh8({"tree_learner": "data", "mesh_shape": [8],
                               "tpu_frontier_rs": False}), rounds=5)
    assert _canonical_splits(b1, num=5) == _canonical_splits(brs, num=5)
    assert _canonical_splits(bps, num=5) == _canonical_splits(brs, num=5)
    p1 = b1.predict(X[:200], raw_score=True)
    prs = brs.predict(X[:200], raw_score=True)
    np.testing.assert_allclose(p1, prs, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_voting_topk_full_degenerates_to_data_parallel():
    """top_k >= F elects every feature: the voting learner's candidate
    histogram equals the full global histogram and the committed
    structure matches serial exactly."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    X, y = _golden_data()
    b1 = _train(X, y, _mesh8(), rounds=5)
    bv = _train(X, y, _mesh8({"tree_learner": "voting", "mesh_shape": [8],
                              "top_k": X.shape[1]}), rounds=5)
    assert _canonical_splits(b1, num=5) == _canonical_splits(bv, num=5)
    p1 = b1.predict(X[:200], raw_score=True)
    pv = bv.predict(X[:200], raw_score=True)
    np.testing.assert_allclose(p1, pv, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_data_rs_skewed_shards():
    """Rows sorted by label: every shard sees a wildly different class
    mix (the 600-row golden set also leaves the last shard short after
    padding). Histograms are reduced before any decision, so the
    committed structure must still match single-device."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    X, y = _golden_data()
    order = np.argsort(y, kind="stable")
    X, y = X[order], y[order]
    b1 = _train(X, y, _mesh8(), rounds=5)
    b8 = _train(X, y, _mesh8({"tree_learner": "data", "mesh_shape": [8]}),
                rounds=5)
    assert _canonical_splits(b1, num=5) == _canonical_splits(b8, num=5)


@pytest.mark.slow
def test_voting_small_topk_documented_approximation():
    """PV-Tree with a small top_k is approximate: candidates can miss
    the global best feature. The documented contract (docs/
    Distributed.md): training still converges — train logloss decreases
    monotonically and lands within tolerance of serial's."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    X, y = make_binary(n=2000)
    rounds = 8
    base = {"objective": "binary", "num_leaves": 31,
            "metric": "binary_logloss", "verbosity": -1,
            "tree_growth": "frontier"}

    def losses(params):
        from lightgbm_tpu.io.dataset import BinnedDataset
        from lightgbm_tpu.objectives import create_objective
        from lightgbm_tpu.metrics import create_metric
        from lightgbm_tpu.boosting import create_boosting
        cfg = Config(params)
        ds = BinnedDataset.from_matrix(X, cfg, label=y)
        mets = [m for m in (create_metric(n_, cfg)
                            for n_ in (cfg.metric or [])) if m]
        b = create_boosting(cfg, ds, create_objective(cfg), mets)
        out = []
        for _ in range(rounds):
            b.train_one_iter()
            out.append(dict((m, v) for _, m, v, _ in b.get_eval_at(0))
                       ["binary_logloss"])
        return out

    ls = losses(dict(base))
    lv = losses(dict(base, tree_learner="voting", mesh_shape=[8], top_k=3))
    # monotone convergence (strict early, tiny tolerance for late-round
    # fp wiggle) and parity with the exact search at the end
    assert all(b2 <= b1 + 1e-6 for b1, b2 in zip(lv, lv[1:]))
    assert lv[-1] < lv[0] * 0.8
    assert abs(lv[-1] - ls[-1]) < 0.1 * max(ls[0] - ls[-1], 1e-6)
